"""Structured run telemetry: a process-tagged JSONL event stream.

The reference's observability is a hand-read ``PrintSummary`` block plus
``nvprof`` wrapping (SURVEY §5) — one wall-clock number per run. This
sink is the machine-readable upgrade the TensorFlow-on-TPU CFD framework
(PAPERS: arXiv 2108.11076) treats as table stakes: every rung selection,
halo exchange, sentinel probe, rollback and checkpoint write becomes an
*attributable event* in an append-only JSONL stream.

Event model (one JSON object per line):

* every event carries ``t`` (seconds since the sink opened, from
  ``time.monotonic`` — ordering-safe under wall-clock steps), ``proc``
  (``jax.process_index()`` read at emit time, so events logged before
  ``jax.distributed.initialize`` and after both tag correctly), ``kind``
  and ``name``;
* ``kind="span"`` events come in ``phase="begin"/"end"`` pairs with
  ``id``/``parent``/``depth`` describing the nesting (ends carry
  ``seconds``);
* ``kind="counter"`` events carry the increment and the running total;
* domain events use their own kinds: ``dispatch``, ``ladder``,
  ``physics``, ``resilience``, ``io``, ``halo``, ``dist_init``.

The module-level active sink (:func:`install` / :func:`get_sink`) is
what the instrumented layers write to; when nothing is installed they
hit :data:`NULL_SINK`, whose methods are no-ops — instrumentation costs
one attribute check on a hot host path. Hot *device* loops are jitted,
so host-side emission happens at chunk/dispatch cadence, never per cell.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import itertools
import json
import os
import sys
import threading
import time
from typing import Optional

# Version of the event-stream layout itself (a `meta`/`open` event
# records it so downstream tooling can evolve).
EVENT_SCHEMA = 1


def _process_index() -> int:
    """Process tag, read at emit time (cheap: a runtime global). Falls
    back to 0 when jax is not importable or not yet set up.

    Must NOT force backend initialization: the CLI installs the sink
    BEFORE ``jax.distributed.initialize`` (so the join's retry loop is
    in the stream), and ``jax.process_index()`` on an uninitialized
    process would bring the backend up single-process — making the
    later distributed join fail with "must be called before any JAX
    computations". Until the backend exists the tag is this process's
    declared distributed id (0 when undeclared)."""
    try:
        import jax
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            from jax._src import distributed

            state = getattr(distributed, "global_state", None)
            pid = getattr(state, "process_id", None)
            return int(pid) if pid is not None else 0
        return int(jax.process_index())
    except Exception:
        return 0


class NullSink:
    """No-op sink: the uninstalled default. ``active`` lets hot call
    sites skip building event payloads entirely."""

    active = False

    def event(self, kind: str, name: str, **fields) -> None:
        pass

    def counter(self, name: str, inc, **fields) -> None:
        pass

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        yield None

    def tail(self, n: int = 20):
        return []

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


NULL_SINK = NullSink()


class TelemetrySink:
    """JSONL event sink with span nesting, counters and a tail buffer.

    Thread-safe writes (one lock around serialization + write); the span
    stack is per-thread so concurrent host threads cannot corrupt each
    other's nesting. ``tail(n)`` returns the last events as dicts — the
    bench engagement guard prints these when a row fails, so a degraded
    run is diagnosable from the bench output alone.
    """

    active = True

    def __init__(self, path: str, tail_events: int = 512,
                 max_bytes: int = 0):
        self.path = path
        # size-capped rotation: when the stream file exceeds max_bytes,
        # it is renamed to <path>.1 (replacing any previous rotation)
        # and a fresh file continues at <path> — long supervised runs
        # keep the newest ~2*max_bytes of evidence instead of growing
        # without bound. 0 = unbounded (the default).
        self.max_bytes = int(max_bytes or 0)
        self._f = open(path, "a", buffering=1)  # line-buffered
        self._bytes = os.path.getsize(path)
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._counters: dict = {}
        self._tail = collections.deque(maxlen=tail_events)
        self.event(
            "meta", "open",
            schema=EVENT_SCHEMA,
            wall_time=time.time(),
        )

    # ------------------------------------------------------------------ #
    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def event(self, kind: str, name: str, **fields) -> None:
        ev = {
            "t": round(time.monotonic() - self._t0, 6),
            "proc": _process_index(),
            "kind": kind,
            "name": name,
        }
        ev.update(fields)
        line = json.dumps(ev)
        with self._lock:
            self._tail.append(ev)
            try:
                self._f.write(line + "\n")
                self._bytes += len(line) + 1
                if self.max_bytes and self._bytes >= self.max_bytes:
                    self._rotate_locked()
            except ValueError:
                pass  # closed sink: keep the tail, drop the write

    def _rotate_locked(self) -> None:
        """Rotate the stream file (caller holds the lock): the full
        file becomes ``<path>.1`` (last rotation dropped), the fresh
        tail file opens with a ``sink:rotate`` record that — like
        ``meta:open`` — carries the schema version and a wall-clock
        epoch, so a tail-only file still merges and aligns. The
        monotonic clock is NOT reset: ``t`` stays comparable across
        the rotation boundary."""
        rotated = self._bytes
        self._f.flush()
        self._f.close()
        prev = self.path + ".1"
        os.replace(self.path, prev)
        self._f = open(self.path, "a", buffering=1)
        self._bytes = 0
        ev = {
            "t": round(time.monotonic() - self._t0, 6),
            "proc": _process_index(),
            "kind": "sink",
            "name": "rotate",
            "schema": EVENT_SCHEMA,
            "wall_time": time.time(),
            "previous": prev,
            "rotated_bytes": rotated,
        }
        self._tail.append(ev)
        line = json.dumps(ev)
        self._f.write(line + "\n")
        self._bytes += len(line) + 1

    def counter(self, name: str, inc, **fields) -> None:
        """Accumulate ``inc`` into the named counter and log the event
        with the running total."""
        with self._lock:
            total = self._counters.get(name, 0) + inc
            self._counters[name] = total
        self.event("counter", name, inc=inc, total=total, **fields)

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """Nested begin/end pair; yields the span id."""
        sid = next(self._ids)
        stack = self._stack()
        parent = stack[-1] if stack else None
        self.event("span", name, phase="begin", id=sid, parent=parent,
                   depth=len(stack), **fields)
        stack.append(sid)
        t0 = time.monotonic()
        try:
            yield sid
        finally:
            stack.pop()
            self.event(
                "span", name, phase="end", id=sid, parent=parent,
                depth=len(stack),
                seconds=round(time.monotonic() - t0, 6),
            )

    def tail(self, n: int = 20):
        """The last ``n`` events, oldest first."""
        with self._lock:
            evs = list(self._tail)
        return evs[-n:]

    def flush(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


# --------------------------------------------------------------------- #
# Module-level active sink
# --------------------------------------------------------------------- #
_active: NullSink | TelemetrySink = NULL_SINK


def get_sink():
    """The currently installed sink (:data:`NULL_SINK` when none)."""
    return _active


# ------------------------------------------------------------------ #
# Crash-path flush: the JSONL tail is the post-mortem evidence — it
# must survive a SolverDivergedError unwinding to the interpreter, a
# RankFailureError abort and a preemption SystemExit, in EVERY process,
# not only clean returns. Two hooks, installed once on first install():
#
# * an atexit flush (covers SystemExit — which never reaches
#   sys.excepthook — and ordinary interpreter teardown);
# * a chained sys.excepthook that records the crash itself as a final
#   `crash` event (exception type + message) and flushes before the
#   previous hook prints the traceback.
#
# The watchdog's os._exit path bypasses both by design; it flushes and
# closes the sink explicitly before exiting.
# ------------------------------------------------------------------ #
_crash_hooks_installed = False


def _atexit_flush() -> None:
    try:
        _active.flush()
    except Exception:
        pass


def _install_crash_hooks() -> None:
    global _crash_hooks_installed
    if _crash_hooks_installed:
        return
    _crash_hooks_installed = True
    atexit.register(_atexit_flush)
    prev_hook = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            if _active.active:
                code = exc.code if isinstance(exc, SystemExit) else None
                _active.event(
                    "crash", exc_type.__name__,
                    message=str(exc)[:500], exit_code=code,
                )
                _active.flush()
        except Exception:
            pass  # the crash record must never mask the crash
        prev_hook(exc_type, exc, tb)

    sys.excepthook = hook


def install(path: str, tail_events: int = 512,
            max_bytes: int = 0) -> TelemetrySink:
    """Open a JSONL sink at ``path`` and make it the active sink. An
    already-active sink is closed first (last install wins). The first
    install also arms the crash-path flush hooks (atexit +
    ``sys.excepthook``), so the stream's tail survives uncaught errors
    and preemption exits. ``max_bytes`` > 0 arms size-capped rotation
    (``<path>.1`` keeps the previous segment; a ``sink:rotate`` event
    opens each fresh tail)."""
    global _active
    if _active.active:
        _active.close()
    _install_crash_hooks()
    _active = TelemetrySink(path, tail_events=tail_events,
                            max_bytes=max_bytes)
    return _active


def uninstall(sink: Optional[TelemetrySink] = None) -> None:
    """Close and deactivate the active sink. With ``sink`` given, only
    deactivates if that sink is still the active one (so an owner
    cannot tear down a later installation)."""
    global _active
    if sink is not None and sink is not _active:
        sink.close()
        return
    if _active.active:
        _active.close()
    _active = NULL_SINK


@contextlib.contextmanager
def capture(path: str, tail_events: int = 512, max_bytes: int = 0):
    """``with capture('events.jsonl') as sink: ...`` — scoped install."""
    sink = install(path, tail_events=tail_events, max_bytes=max_bytes)
    try:
        yield sink
    finally:
        uninstall(sink)


# Proxy conveniences: instrumented modules call these without holding a
# sink reference; they hit NULL_SINK when telemetry is off.
def event(kind: str, name: str, **fields) -> None:
    _active.event(kind, name, **fields)


def counter(name: str, inc, **fields) -> None:
    _active.counter(name, inc, **fields)


def span(name: str, **fields):
    return _active.span(name, **fields)
