"""Static per-rung cost model: HBM bytes moved and FLOPs per step.

Turns a measured wall-clock rate into a *roofline efficiency*: how close
the engaged stepper rung ran to the rate its memory traffic (or compute)
allows on the hardware — the bytes/FLOPs accounting HipBone (PAPERS:
arXiv 2202.12477) treats as the baseline for every kernel. The model is
static and documented, not profiled: every count below is derived from
the operator definitions in ``ops/`` and the steppers' data flow, so a
test can hand-compute the same numbers (tests/test_telemetry.py).

FLOP conventions (per cell, per RK stage; adds/subs/muls/divs each = 1):

* O4 Laplacian axis term, factored ``c*(16*(q1+q3) - (q0+q4) - 30*q2)``:
  2 pair-adds + (16*, -) + (30*, -) + c* = 4 add/sub + 3 mul = **7/axis**.
* O2 Laplacian axis term ``c*((q0+q2) - 2*q1)``: 2 add/sub + 2 mul =
  **4/axis**.
* Cross-axis accumulation: **ndim-1** adds.
* SSP-RK3 stage combine ``u = a*u0 + b*(u_s + dt*L)``: 3 mul + 2 add =
  **5**.
* WENO5 axis sweep (ops/weno.py, single-division form), per cell-stage:
  LF split 7; per reconstruction side: betas 33 + eps-shifts 3 +
  unnormalized alphas 9 + normalization (2 add, 1 div, 3 mul) 6 +
  candidate stencils 15 + weighted combine 5 = 71; two sides 142; flux
  divergence 2 → **151/axis**. WENO7 (4 stencils, wider betas) is the
  analogous count, **232/axis** (estimate at the same conventions; no
  test pins it — the reference never benchmarked WENO7 either).

HBM traffic (field passes per *step*; 1 pass = cells * itemsize bytes,
itemsize = the STORAGE dtype, so the f64-storage/f32-compute rung pays
f64 bytes):

* ``fused-whole-run-slab`` / ``fused-step``: read state + write state
  once per step (the one-HBM-round-trip-per-step schedule) = **2**.
* ``fused-whole-run``: state is VMEM-resident for the entire run — HBM
  traffic only at run boundaries, modeled as **0** (the roofline is then
  compute-only).
* ``fused-stage``: SSP-RK3 ping-pong S/T1/T2 — stage 1 reads S writes
  T1 (2), stages 2/3 read the previous stage plus S and write (3 each)
  = **8**.
* ``per-axis-pallas``: per stage, one read+write sweep per axis
  (2*ndim) plus the RK combine (read L, u_s, u0; write u = 4) =
  **3*(2*ndim+4)**.
* ``generic-xla``: per stage, L materialized (read u_s, write L) then
  combined (read L, u_s, u0; write u) = **3*6 = 18** — an idealized
  lower bound; XLA may fuse better or worse.

Peaks default per backend (env-overridable with
``TPUCFD_PEAK_BYTES_PER_S`` / ``TPUCFD_PEAK_FLOPS_PER_S``): the TPU row
is a v5e chip (819 GB/s HBM; 4.92e13 f32 FLOP/s matmul peak — stencil
code is VPU-bound and will not approach the compute roof, so the
meaningful number on TPU is the HBM roofline). The CPU row is a nominal
(50 GB/s, 100 GFLOP/s) placeholder so the plumbing is testable without
hardware; CPU percentages are not performance claims.

A *measured* calibration record (``telemetry/calibration.py`` — the
best achieved rate any run's XLA-reported byte/FLOP counts have
demonstrated on this rig) takes precedence over BOTH the defaults and
the env assumptions (:func:`peak_rates` docstring); roofline
percentages then read against demonstrated capability rather than a
datasheet, and the autotuner prunes with measured peaks.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Optional, Sequence

# per-axis FLOPs of the diffusion RHS by Laplacian order
DIFFUSION_AXIS_FLOPS = {2: 4, 4: 7}
# per-axis FLOPs of the WENO flux-divergence sweep by order
WENO_AXIS_FLOPS = {5: 151, 7: 232}
RK_COMBINE_FLOPS = 5
# ADR family (models/adr.py) conventions:
# * first-order upwind advective term per axis, folded coefficients
#   ``cp*(u - u_lo) + cm*(u_hi - u)``: 2 sub + 2 mul + 1 add = **5**;
#   WENO5 linear advection reuses the Burgers sweep count (151/axis).
# * variable-K coefficient ``K0 (1 + eps prod cos(pi x̂))``: ndim
#   cos + (ndim-1) muls + axpy + the K*lap multiply, counted as
#   **3*ndim + 2** (cos = 1 at these conventions — VPU-transcendental,
#   roofline-irrelevant next to the HBM bound); constant K is the one
#   K*lap multiply = **1**.
# * linear-decay reaction ``- lambda u``: mul + sub = **2**.
ADR_UPWIND_AXIS_FLOPS = 5

# (peak HBM bytes/s, peak FLOP/s) by backend family
PEAKS = {
    "tpu": (819.0e9, 4.92e13),  # v5e: HBM BW; f32 matmul peak
    "gpu": (900.0e9, 1.0e13),   # generic placeholder (not measured here)
    "cpu": (5.0e10, 1.0e11),    # nominal, for plumbing/tests only
}


def hbm_passes_per_step(stepper: str, ndim: int, stages: int = 3) -> float:
    """Field passes (cells * itemsize each) one step moves through HBM
    for the given engaged-stepper label; derivations in the module
    docstring."""
    if stepper in ("fused-whole-run-slab", "fused-step"):
        return 2.0
    if stepper == "fused-whole-run":
        return 0.0
    if stepper == "fused-stage":
        return float(stages - 1) * 3.0 + 2.0  # 8 for SSP-RK3
    if stepper == "per-axis-pallas":
        return float(stages) * (2.0 * ndim + 4.0)
    # generic-xla and anything unrecognized: the materialized-RHS bound
    return float(stages) * 6.0


def rhs_flops_per_cell(
    kind: str,
    ndim: int,
    order: int = 4,
    weno_order: int = 5,
    viscous: bool = False,
    advect: str = "upwind",
    reaction: bool = False,
    variable_k: bool = False,
) -> float:
    """FLOPs of one RHS evaluation per cell (no RK combine)."""
    if kind == "diffusion":
        return DIFFUSION_AXIS_FLOPS[order] * ndim + (ndim - 1)
    if kind == "burgers":
        f = WENO_AXIS_FLOPS[weno_order] * ndim + (ndim - 1)
        if viscous:
            # nu*lap(u) rides the O2 Laplacian plus one axpy per cell
            f += DIFFUSION_AXIS_FLOPS[2] * ndim + (ndim - 1) + 2
        return float(f)
    if kind == "adr":
        # diffusive taps + K multiply (+ the variable-K profile)
        f = DIFFUSION_AXIS_FLOPS[order] * ndim + (ndim - 1)
        f += (3 * ndim + 2) if variable_k else 1
        # advective divergence + cross-axis accumulation + the
        # RHS-level subtraction
        adv = (
            ADR_UPWIND_AXIS_FLOPS
            if advect == "upwind"
            else WENO_AXIS_FLOPS[5]
        )
        f += adv * ndim + (ndim - 1) + 1
        if reaction:
            f += 2  # -lambda u: mul + sub
        return float(f)
    raise ValueError(f"unknown solver kind {kind!r}")


@dataclasses.dataclass(frozen=True)
class StepCost:
    """Modeled cost of ONE time step over the whole (global) grid."""

    hbm_bytes: float
    flops: float
    passes: float
    flops_per_cell_stage: float

    def to_dict(self) -> dict:
        return {
            "hbm_bytes_per_step": self.hbm_bytes,
            "flops_per_step": self.flops,
            "hbm_passes_per_step": self.passes,
            "flops_per_cell_stage": self.flops_per_cell_stage,
        }


def step_cost(
    kind: str,
    shape: Sequence[int],
    itemsize: int,
    stepper: str,
    stages: int = 3,
    order: int = 4,
    weno_order: int = 5,
    viscous: bool = False,
    advect: str = "upwind",
    reaction: bool = False,
    variable_k: bool = False,
) -> StepCost:
    cells = math.prod(shape)
    ndim = len(shape)
    per_cell_stage = (
        rhs_flops_per_cell(kind, ndim, order=order, weno_order=weno_order,
                           viscous=viscous, advect=advect,
                           reaction=reaction, variable_k=variable_k)
        + RK_COMBINE_FLOPS
    )
    passes = hbm_passes_per_step(stepper, ndim, stages)
    return StepCost(
        hbm_bytes=passes * cells * itemsize,
        flops=float(stages) * cells * per_cell_stage,
        passes=passes,
        flops_per_cell_stage=per_cell_stage,
    )


# Per-message fixed cost of one halo exchange (ppermute pair): launch +
# interconnect latency, not bandwidth. Crude by design — it only has to
# rank k-candidates for the tuner's pruning, and the measured pass
# decides. Env-overridable like the peaks.
EXCHANGE_LATENCY_S = 25e-6


def halo_exchange_seconds(
    nbytes: float,
    messages: int = 1,
    backend: Optional[str] = None,
) -> float:
    """Modeled wall time of halo traffic: ``messages`` fixed per-message
    latencies (``TPUCFD_EXCHANGE_LATENCY_S`` overrides the default) plus
    the payload at the backend's peak bandwidth. The communication-
    avoiding tradeoff in one line: a k-step schedule moves the same
    bytes per step but pays the latency term only once per k steps."""
    lat = float(
        os.environ.get("TPUCFD_EXCHANGE_LATENCY_S", EXCHANGE_LATENCY_S)
    )
    peak_b, _ = peak_rates(backend)
    return messages * lat + (nbytes / peak_b if peak_b else 0.0)


def deep_halo_recompute_factor(local_nz: int, G: int, k: int) -> float:
    """Mean redundant-work multiplier of the k-step deep-halo schedule
    on a ``local_nz``-row shard: in-block step ``j`` evolves the core
    plus ``(k-1-j)*G`` ghost rows per side, so the average window is
    ``local_nz + (k-1)*G`` rows — the FLOP (and slab-traffic) price paid
    for exchanging once per k steps."""
    if local_nz <= 0:
        return 1.0
    return 1.0 + (k - 1) * G / float(local_nz)


def _backend_family(backend: Optional[str] = None) -> str:
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
    return backend if backend in PEAKS else (
        "tpu" if backend not in ("cpu", "gpu") else backend
    )


def peak_rates(backend: Optional[str] = None):
    """(bytes/s, FLOP/s) peaks for a backend family.

    Precedence: a *measured* calibration record
    (:mod:`telemetry.calibration` — the max achieved rate any run's XLA
    byte/FLOP counts demonstrated on this rig) beats the env overrides
    (``TPUCFD_PEAK_BYTES_PER_S``/``_FLOPS_PER_S``), which beat the
    static per-backend defaults. Measured > assumed: set
    ``TPUCFD_CALIBRATION_PATH=off`` to fall back to assumptions."""
    info = peak_info(backend)
    return info["bytes_per_s"], info["flops_per_s"]


def peak_info(backend: Optional[str] = None) -> dict:
    """:func:`peak_rates` plus provenance: where each peak came from
    (``calibrated`` / ``env`` / ``default``) — carried in the tuner's
    ``tune:candidates`` event so a pruning decision is auditable."""
    family = _backend_family(backend)
    peak_b, peak_f = PEAKS[family]
    src_b = src_f = "default"
    env_b = os.environ.get("TPUCFD_PEAK_BYTES_PER_S")
    env_f = os.environ.get("TPUCFD_PEAK_FLOPS_PER_S")
    if env_b:
        peak_b, src_b = float(env_b), "env"
    if env_f:
        peak_f, src_f = float(env_f), "env"
    try:
        from multigpu_advectiondiffusion_tpu.telemetry import calibration

        cal = calibration.lookup(family)
    except Exception:
        cal = None
    if cal:
        if cal.get("bytes_per_s"):
            peak_b, src_b = float(cal["bytes_per_s"]), "calibrated"
        if cal.get("flops_per_s"):
            peak_f, src_f = float(cal["flops_per_s"]), "calibrated"
    return {
        "backend": family,
        "bytes_per_s": peak_b,
        "flops_per_s": peak_f,
        "bytes_source": src_b,
        "flops_source": src_f,
    }


def roofline(
    cost: StepCost,
    iters: int,
    seconds: float,
    backend: Optional[str] = None,
    devices: int = 1,
) -> dict:
    """Measured seconds vs the model's minimum time on the peak rates.

    ``roofline_pct = 100 * t_model / t_measured`` where ``t_model`` is
    the binding resource's time ``max(bytes/peak_bw, flops/peak_flops)``
    for the whole run (aggregate peaks scale with ``devices``).
    ``bound`` names the binding resource. VMEM-resident rungs (0 modeled
    bytes) are compute-bound by construction.
    """
    peak_b, peak_f = peak_rates(backend)
    peak_b *= max(1, devices)
    peak_f *= max(1, devices)
    bytes_total = cost.hbm_bytes * iters
    flops_total = cost.flops * iters
    t_mem = bytes_total / peak_b if peak_b else 0.0
    t_cmp = flops_total / peak_f if peak_f else 0.0
    t_model = max(t_mem, t_cmp)
    out = {
        "achieved_gbs": (
            round(bytes_total / seconds / 1e9, 3) if seconds > 0 else None
        ),
        "achieved_gflops": (
            round(flops_total / seconds / 1e9, 3) if seconds > 0 else None
        ),
        "peak_gbs": round(peak_b / 1e9, 3),
        "peak_gflops": round(peak_f / 1e9, 3),
        "bound": "hbm" if t_mem >= t_cmp else "flops",
        "roofline_pct": (
            round(100.0 * t_model / seconds, 2) if seconds > 0 else None
        ),
    }
    return out


# --------------------------------------------------------------------- #
# Solver-facing conveniences
# --------------------------------------------------------------------- #
def solver_kind(cfg) -> Optional[str]:
    """Solver family from its config: the plugin registry first
    (``models/registry.spec_for_config`` — the single source for
    registered families, so a third model never edits this), then the
    legacy duck-typed fallback for ad-hoc config doubles in tests."""
    try:
        from multigpu_advectiondiffusion_tpu.models import registry

        spec = registry.spec_for_config(cfg)
        if spec is not None:
            return spec.family_kind
    except Exception:
        pass
    if hasattr(cfg, "weno_order"):
        return "burgers"
    if hasattr(cfg, "velocity"):
        return "adr"
    if hasattr(cfg, "diffusivity"):
        return "diffusion"
    return None


def solver_cost_kwargs(cfg) -> dict:
    """Per-family ``step_cost`` kwargs, resolved through the registry's
    ``cost_kwargs`` hook (legacy literal fallback for unregistered
    configs)."""
    try:
        from multigpu_advectiondiffusion_tpu.models import registry

        spec = registry.spec_for_config(cfg)
        if spec is not None and spec.cost_kwargs is not None:
            return dict(spec.cost_kwargs(cfg))
    except Exception:
        pass
    kind = solver_kind(cfg)
    if kind == "diffusion":
        return {"order": getattr(cfg, "order", 4)}
    if kind == "burgers":
        return {
            "weno_order": getattr(cfg, "weno_order", 5),
            "viscous": bool(getattr(cfg, "nu", 0.0)),
        }
    if kind == "adr":
        return {
            "order": getattr(cfg, "order", 4),
            "advect": getattr(cfg, "advect", "upwind"),
            "reaction": bool(getattr(cfg, "reaction_rate", 0.0)),
            "variable_k": bool(getattr(cfg, "kappa_variation", 0.0)),
        }
    return {}


def solver_step_cost(solver, stepper: str) -> Optional[StepCost]:
    """The static cost of one of ``solver``'s steps on the engaged
    ``stepper`` rung, or ``None`` for solver families the model does not
    cover (e.g. axisymmetric geometry is priced as cartesian — close
    enough for a roofline)."""
    import numpy as np

    from multigpu_advectiondiffusion_tpu.timestepping.integrators import (
        STAGES,
    )

    cfg = solver.cfg
    kind = solver_kind(cfg)
    if kind is None:
        return None
    kwargs = solver_cost_kwargs(cfg)
    # HBM passes are priced at the STORAGE dtype — what actually sits
    # in (and streams from) HBM: the precision='bf16' rung pays
    # 2 B/cell, not the facing f32's 4
    # (models/base.SolverBase.storage_dtype)
    storage = getattr(solver, "storage_dtype", solver.dtype)
    try:
        return step_cost(
            kind,
            cfg.grid.shape,
            np.dtype(storage).itemsize,
            stepper,
            stages=STAGES[cfg.integrator],
            **kwargs,
        )
    except (KeyError, ValueError):
        # a registered family without a documented FLOP convention:
        # runs fine, just publishes no roofline (the model is static
        # and documented per family — new families opt in by adding
        # their counts here)
        return None


def summarize_run(
    solver,
    stepper: str,
    iters: int,
    seconds: float,
    backend: Optional[str] = None,
) -> Optional[dict]:
    """Cost-model block for a finished run: per-step bytes/FLOPs plus
    the roofline efficiency — what ``RunSummary.cost_model`` and the
    bench rows carry."""
    cost = solver_step_cost(solver, stepper)
    if cost is None or iters <= 0 or seconds <= 0:
        return None
    devices = 1 if solver.mesh is None else solver.mesh.devices.size
    out = cost.to_dict()
    out["stepper"] = stepper
    out.update(roofline(cost, iters, seconds, backend=backend,
                        devices=devices))
    return out


def _dispatch_step_memory(solver, state) -> Optional[dict]:
    """XLA memory accounting of the solver's OWN dispatched step
    executable — captured by the measured-introspection layer
    (``telemetry/xprof.py``) at dispatch, so no second copy of the step
    is lowered or compiled just to inspect it. Runs one step to
    populate the dispatch cache when nothing has executed yet."""
    from multigpu_advectiondiffusion_tpu.telemetry import xprof

    def step_record():
        for r in reversed(xprof.records(solver)):
            if r.key == "step" and (
                r.argument_bytes or r.output_bytes or r.temp_bytes
            ):
                return r
        return None

    rec = step_record()
    if rec is None and xprof.enabled():
        try:
            solver.step(state)
        except Exception:
            return None
        rec = step_record()
    if rec is None:
        return None
    return {
        "argument_size_in_bytes": rec.argument_bytes,
        "output_size_in_bytes": rec.output_bytes,
        "temp_size_in_bytes": rec.temp_bytes,
        "generated_code_size_in_bytes": rec.generated_code_bytes,
    }


def solver_memory_cross_check(solver, state,
                              stepper: Optional[str] = None) -> Optional[dict]:
    """Cross-check the static model against XLA's OWN memory accounting
    for one compiled step of ``solver`` (tests/test_telemetry.py holds
    the two within documented bounds).

    The accounting comes from the dispatch layer's already-compiled
    step executable (:func:`_dispatch_step_memory` — the measured
    introspection captured at ``dispatch:build``); only when that layer
    is disabled does the legacy :func:`xla_memory_analysis` hook
    lower+compile a standalone copy.

    Returns ``None`` where the backend exposes no accounting; otherwise
    a dict with the model's :class:`StepCost`, XLA's byte attributes,
    the single-field byte size, and ``min_traffic_bytes`` — the
    argument+output footprint the compiled step cannot avoid moving,
    which the model must never undercut."""
    cost = solver_step_cost(
        solver, stepper or solver.engaged_path()["stepper"]
    )
    if cost is None:
        return None
    mem = _dispatch_step_memory(solver, state)
    if mem is None:
        mem = xla_memory_analysis(solver.step, state)
    if mem is None:
        return None
    import numpy as np

    field_bytes = math.prod(solver.grid.shape) * np.dtype(
        solver.dtype
    ).itemsize
    return {
        "model": cost.to_dict(),
        "xla": mem,
        "field_bytes": int(field_bytes),
        "min_traffic_bytes": int(
            mem.get("argument_size_in_bytes", 0)
            + mem.get("output_size_in_bytes", 0)
        ),
    }


def xla_memory_analysis(fn, *args) -> Optional[dict]:
    """Generic introspection hook: lower+compile ``fn(*args)`` and read
    XLA's own ``memory_analysis()`` where the backend provides one.
    This compiles a standalone copy of ``fn`` — for a solver's own step
    the dispatch path reuses its already-compiled executable instead
    (:func:`_dispatch_step_memory` via ``telemetry/xprof.py``); this
    hook remains for ad-hoc callables and as the disabled-introspection
    fallback. Returns a plain dict of the byte-sized attributes so
    tests can compare magnitudes against the static model without
    depending on the exact HLO schedule."""
    try:
        import jax

        compiled = jax.jit(fn).lower(*args).compile()
        m = compiled.memory_analysis()
    except Exception:
        return None
    if m is None:
        return None
    out = {}
    for attr in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        v = getattr(m, attr, None)
        if v is not None:
            out[attr] = int(v)
    return out or None
