"""Measured performance introspection: XLA's own numbers, first-class.

The reference archives ``nvprof`` counters next to every ``Run.m``
timing; until this module, our perf-observability stack (roofline %,
the tuner's pruning, the trace report) ran entirely on the *modeled*
cost model (``telemetry/costmodel.py``) with env-assumed peaks, and the
repo had zero memory observability. Three measured layers close that
gap (TPU scientific-computing framework, PAPERS arXiv 2108.11076;
HipBone, arXiv 2202.12477 — compiler/hardware-reported FLOPs, bytes
and footprints as first-class outputs of every run):

* **Executable capture** (:func:`wrap_dispatch`): every program the
  dispatch layer builds (``models/base.SolverBase._compiled``) is
  compiled *ahead-of-time once* — the same single compile the jit
  wrapper would have paid — and the compiled executable is kept both
  for execution and for introspection: XLA's ``cost_analysis()``
  (flops / bytes-accessed / transcendentals), ``memory_analysis()``
  (argument/output/temp bytes, the peak-footprint estimate) and the
  measured compile seconds become an :class:`ExecRecord` on the solver
  and an ``xla:cost`` telemetry event. This is also how
  ``costmodel.solver_memory_cross_check`` now reads XLA's accounting —
  reusing the dispatched executable instead of re-lowering a second
  copy of the step.

  *Semantics*: XLA's HLO cost analysis counts loop bodies ONCE
  (trip-count-independent), so for the dispatch programs — whose body
  is one time step (or one k-step block) — the reported flops/bytes
  are per-step-shaped and read directly against the cost model's
  per-step numbers. Sharded programs report per-device counts; global
  figures multiply by the mesh size. Pallas custom calls are opaque to
  the analysis (their interior flops read as 0) — the generic-XLA
  rungs, which the CPU tier-1 path runs, are fully visible.

* **Device-memory watermarks** (:func:`sample_watermark`): chunk-
  cadence ``mem:watermark`` events from ``device.memory_stats()``
  where the backend provides it (TPU/GPU: true device-reported
  bytes-in-use / peak / limit), falling back to a ``jax.live_arrays()``
  byte census (logical array bytes, host-tracked peak) so the CPU
  tier-1 path exercises the same plumbing. The run-level peak and
  headroom land in ``RunSummary.memory`` — the real-HBM-headroom
  numbers ROADMAP items 1 and 5 need to admit work safely.

* **Measured-vs-modeled** (:func:`measured_summary`): the per-run
  reconciliation — XLA bytes/flops per step against the cost model's
  prediction (ratio flagged outside the documented tolerance band,
  default ``TPUCFD_XPROF_TOLERANCE`` = 3x, reported rather than
  hidden), achieved bandwidth against the assumed peak — emitted as an
  ``xla:measured`` event, carried in ``RunSummary.xla`` and bench rows
  (``xla_flops``/``xla_bytes``/``peak_bytes``), rendered by the
  ``tpucfd-trace`` report, and fed to :mod:`telemetry.calibration` so
  the cost model and the autotuner prune with measured rather than
  assumed peaks.

``TPUCFD_XPROF=0`` disables the capture layer (dispatch falls back to
plain jit); every introspection step is individually fault-tolerant —
a backend that cannot answer an analysis question degrades that field
to ``None``/0, never the solve.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import List, Optional

ENABLE_ENV = "TPUCFD_XPROF"
TOLERANCE_ENV = "TPUCFD_XPROF_TOLERANCE"
# modeled/measured bytes (or flops) ratio outside [1/F, F] is reported
# as a discrepancy: the model is an idealized pass count, XLA's is an
# HLO-schedule count — a 3x band separates "different conventions"
# from "one of them is wrong"
DEFAULT_TOLERANCE = 3.0


def enabled() -> bool:
    return os.environ.get(ENABLE_ENV, "").strip().lower() not in (
        "0", "off", "false", "no"
    )


def tolerance_factor() -> float:
    try:
        return float(os.environ.get(TOLERANCE_ENV, DEFAULT_TOLERANCE))
    except ValueError:
        return DEFAULT_TOLERANCE


# --------------------------------------------------------------------- #
# Executable capture
# --------------------------------------------------------------------- #
@dataclasses.dataclass
class ExecRecord:
    """One compiled executable's XLA-reported cost/memory facts."""

    key: str
    solver: str
    stepper: Optional[str]
    impl: Optional[str]
    backend: str
    devices: int
    # iteration count the program bakes in (None for data-dependent
    # trip counts, e.g. the t_end while_loop)
    steps: Optional[int]
    flops: float
    bytes_accessed: float
    transcendentals: float
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    generated_code_bytes: int
    peak_bytes: int
    compile_seconds: float
    # the static model's per-step prediction for the engaged rung
    # (None where the model has no opinion)
    model_bytes_per_step: Optional[float]
    model_flops_per_step: Optional[float]
    # persistent AOT executable cache (tuning/aot_cache.py): "hit"
    # when this executable was deserialized instead of compiled (then
    # compile_seconds is the load time and compile_seconds_saved the
    # original build's compile cost), "store"/"miss" otherwise; None
    # when the cache is disabled
    aot: Optional[str] = None
    compile_seconds_saved: Optional[float] = None
    # buffer donation (ISSUE 19): True when the program donates its
    # state operand (XLA aliases it into the output — no second
    # state-sized HBM buffer per dispatch)
    donated: bool = False

    def to_fields(self) -> dict:
        return dataclasses.asdict(self)


def _normalize_cost(ca) -> dict:
    """``Compiled.cost_analysis()`` -> flat floats (it returns a list of
    one dict on current jax; keys are XLA's own strings)."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    return {
        "flops": float(ca.get("flops", 0.0) or 0.0),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
        "transcendentals": float(ca.get("transcendentals", 0.0) or 0.0),
    }


def _normalize_memory(ma) -> dict:
    """``Compiled.memory_analysis()`` -> byte-sized ints. ``peak_bytes``
    prefers an explicit backend-reported peak attribute and falls back
    to the argument+output+temp footprint sum (the executable's
    unavoidable live set)."""
    out = {"argument_bytes": 0, "output_bytes": 0, "temp_bytes": 0,
           "generated_code_bytes": 0, "peak_bytes": 0}
    if ma is None:
        return out
    for field, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("generated_code_bytes", "generated_code_size_in_bytes"),
    ):
        v = getattr(ma, attr, None)
        if v is not None:
            out[field] = int(v)
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is None:
        alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
        peak = (out["argument_bytes"] + out["output_bytes"]
                + out["temp_bytes"] + alias)
    out["peak_bytes"] = int(peak)
    return out


def records(solver) -> List[ExecRecord]:
    """The executables captured for one solver's dispatch cache (in
    build order; survives ``_cache.clear()`` — they are history)."""
    return list(getattr(solver, "_xla_records", ()) or ())


def primary_record(recs: List[ExecRecord]) -> Optional[ExecRecord]:
    """The record of the run's main program: the deepest-stepping
    executable built last (warm-up programs bake ``steps=1``; the timed
    chunk program bakes the chunk length)."""
    best = None
    for i, r in enumerate(recs):
        rank = ((r.steps or 1), i)
        if best is None or rank >= best[0]:
            best = (rank, r)
    return best[1] if best else None


class _IntrospectedDispatch:
    """Callable wrapping one dispatch-cache entry.

    First call: AOT lower+compile the jitted program on the concrete
    arguments (the one compile the jit wrapper would have paid at the
    same moment), capture the executable's cost/memory analyses and
    compile seconds, emit ``xla:cost``, then execute the compiled
    object — this call and every later one. Any failure on the
    introspection path falls back permanently to the plain jitted
    callable, so a Mosaic rejection still surfaces where the kernel
    ladder expects it and an aval/sharding change simply retraces.
    """

    def __init__(self, fn, solver, key: str, steps: Optional[int] = None,
                 aot_key: Optional[str] = None, donated: bool = False):
        self._fn = fn
        self._solver = solver
        self._key = key
        self._steps = steps
        self._aot_key = aot_key
        self._donated = bool(donated)
        self._compiled = None
        self._fallback = False
        self.record: Optional[ExecRecord] = None

    def prewarm(self, shaped_args) -> Optional[str]:
        """Speculative AOT resolve (ISSUE 19): look the program up in
        the persistent store against ABSTRACT operands
        (``jax.ShapeDtypeStruct`` leaves carry the same aval
        fingerprint as the concrete arrays) and deserialize on a hit —
        NEVER compiles cold, so a miss costs one file stat. Returns
        ``"hit"`` (executable now resident — the first real call skips
        both compile and load), ``"miss"``, ``"resident"`` (already
        compiled), or ``None`` (cache off / fallback engaged)."""
        from multigpu_advectiondiffusion_tpu.tuning import aot_cache

        if self._fallback:
            return None
        if self._compiled is not None:
            return "resident"
        if not (self._aot_key and aot_cache.enabled()):
            return None
        full_key = (
            f"{self._aot_key}|"
            f"avals={aot_cache.aval_fingerprint(shaped_args)}"
        )
        loaded = aot_cache.load(full_key, shaped_args)
        if loaded is None:
            return "miss"
        compiled, meta = loaded
        self._compiled = compiled
        self.record = _capture(
            compiled, self._solver, self._key, self._steps,
            meta["load_seconds"], aot="hit",
            compile_seconds_saved=meta["compile_seconds_saved"],
            donated=self._donated,
        )
        return "hit"

    def _aot_resolve(self, args):
        """Persistent AOT cache (tuning/aot_cache.py): returns
        ``(compiled, compile_seconds, aot_status, saved)`` — loading
        the stored executable on a hit, compiling (and storing) on a
        miss. ``aot_status`` is None when the cache is off."""
        from multigpu_advectiondiffusion_tpu.tuning import aot_cache

        full_key = None
        if self._aot_key and aot_cache.enabled():
            full_key = (
                f"{self._aot_key}|avals={aot_cache.aval_fingerprint(args)}"
            )
            loaded = aot_cache.load(full_key, args)
            if loaded is not None:
                compiled, meta = loaded
                return (
                    compiled, meta["load_seconds"], "hit",
                    meta["compile_seconds_saved"],
                )
        t0 = time.perf_counter()
        compiled = self._fn.lower(*args).compile()
        compile_s = time.perf_counter() - t0
        if full_key is not None:
            persisted = aot_cache.store(full_key, args, compiled,
                                        compile_s)
            return compiled, compile_s, "store" if persisted else "miss", None
        return compiled, compile_s, None, None

    def __call__(self, *args):
        if self._fallback:
            return self._fn(*args)
        if self._compiled is None:
            try:
                compiled, compile_s, aot, saved = self._aot_resolve(args)
            except Exception:
                # compile failures must propagate from the PLAIN path:
                # the kernel ladder classifies them there
                self._fallback = True
                return self._fn(*args)
            self._compiled = compiled
            self.record = _capture(
                compiled, self._solver, self._key, self._steps, compile_s,
                aot=aot, compile_seconds_saved=saved,
                donated=self._donated,
            )
        try:
            return self._compiled(*args)
        except Exception:
            # aval/sharding drift vs the first call: retrace via jit
            self._fallback = True
            return self._fn(*args)


def _capture(compiled, solver, key: str, steps: Optional[int],
             compile_s: float, aot: Optional[str] = None,
             compile_seconds_saved: Optional[float] = None,
             donated: bool = False,
             ) -> Optional[ExecRecord]:
    """Build (and register + emit) the ExecRecord for one compiled
    executable; every probe is individually fault-tolerant."""
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    try:
        cost = _normalize_cost(compiled.cost_analysis())
    except Exception:
        cost = _normalize_cost(None)
    try:
        mem = _normalize_memory(compiled.memory_analysis())
    except Exception:
        mem = _normalize_memory(None)
    stepper = impl = None
    model_bytes = model_flops = None
    devices = 1
    try:
        devices = (
            1 if solver.mesh is None else int(solver.mesh.devices.size)
        )
        mode = "t_end" if key in ("adv", "fused_adv") else "iters"
        eng = solver.engaged_path(mode=mode)
        stepper, impl = eng.get("stepper"), eng.get("impl")
        from multigpu_advectiondiffusion_tpu.telemetry import costmodel

        model = costmodel.solver_step_cost(solver, stepper)
        if model is not None:
            model_bytes = float(model.hbm_bytes)
            model_flops = float(model.flops)
    except Exception:
        pass
    record = ExecRecord(
        key=key,
        solver=type(solver).__name__,
        stepper=stepper,
        impl=impl,
        backend=backend,
        devices=devices,
        steps=steps,
        compile_seconds=round(compile_s, 6),
        model_bytes_per_step=model_bytes,
        model_flops_per_step=model_flops,
        aot=aot,
        compile_seconds_saved=(
            None if compile_seconds_saved is None
            else round(compile_seconds_saved, 6)
        ),
        donated=bool(donated),
        **cost,
        **mem,
    )
    try:
        recs = getattr(solver, "_xla_records", None)
        if recs is None:
            recs = solver._xla_records = []
        recs.append(record)
    except Exception:
        pass
    from multigpu_advectiondiffusion_tpu import telemetry

    telemetry.event("xla", "cost", **record.to_fields())
    return record


def wrap_dispatch(fn, solver, key: str, steps: Optional[int] = None,
                  aot_key: Optional[str] = None,
                  donated: bool = False):
    """Dispatch-layer hook: wrap a freshly built jitted program for
    measured introspection (no-op passthrough when ``TPUCFD_XPROF=0``
    or the builder returned something un-lowerable). ``aot_key``
    additionally routes the first-call compile through the persistent
    AOT executable cache (tuning/aot_cache.py); ``donated`` marks a
    program that donates its state operand (recorded on the
    ``xla:cost`` event — the bit also rides the AOT key upstream)."""
    if not enabled() or not hasattr(fn, "lower"):
        return fn
    return _IntrospectedDispatch(fn, solver, key, steps=steps,
                                 aot_key=aot_key, donated=donated)


# --------------------------------------------------------------------- #
# Device-memory watermarks
# --------------------------------------------------------------------- #
_watermark = {
    "peak": 0, "last": 0, "limit": None, "source": None, "samples": 0,
}


def device_memory_stats() -> Optional[list]:
    """Per-device ``memory_stats()`` dicts, or ``None`` when the
    backend provides none (CPU)."""
    try:
        import jax

        stats = [d.memory_stats() for d in jax.local_devices()]
    except Exception:
        return None
    stats = [s for s in stats if s]
    return stats or None


def live_array_bytes() -> int:
    """Byte census of every live ``jax.Array`` in the process (logical
    nbytes — the CPU-testable fallback when the backend reports no
    memory stats)."""
    try:
        import jax

        return int(sum(
            int(getattr(a, "nbytes", 0) or 0) for a in jax.live_arrays()
        ))
    except Exception:
        return 0


def sample_watermark(emit: bool = True, **fields) -> dict:
    """One device-memory sample: backend-reported where available,
    live-arrays census otherwise. Updates the process-level running
    peak and (``emit``) streams a ``mem:watermark`` event; extra
    ``fields`` (e.g. ``step``) ride along."""
    stats = device_memory_stats()
    if stats:
        in_use = sum(int(s.get("bytes_in_use", 0) or 0) for s in stats)
        peak = sum(
            int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)) or 0)
            for s in stats
        )
        limit = sum(int(s.get("bytes_limit", 0) or 0) for s in stats) or None
        source = "device_stats"
    else:
        in_use = live_array_bytes()
        peak = in_use
        limit = None
        source = "live_arrays"
    _watermark["samples"] += 1
    _watermark["last"] = int(in_use)
    _watermark["peak"] = max(_watermark["peak"], int(peak), int(in_use))
    _watermark["limit"] = limit if limit is not None else _watermark["limit"]
    _watermark["source"] = source
    sample = {
        "bytes_in_use": int(in_use),
        "peak_bytes": _watermark["peak"],
        "limit_bytes": limit,
        "source": source,
    }
    if emit:
        from multigpu_advectiondiffusion_tpu import telemetry

        telemetry.event("mem", "watermark", **sample, **fields)
    return sample


def reset_watermarks() -> None:
    """Zero the running peak (run boundary)."""
    _watermark.update(
        peak=0, last=0, limit=None, source=None, samples=0
    )


def watermark_summary() -> Optional[dict]:
    """The run-level memory block (``RunSummary.memory``): peak bytes
    in use, last sample, the backend-reported limit and the headroom
    under it (``None`` without a sample or a limit)."""
    if not _watermark["samples"]:
        return None
    limit = _watermark["limit"]
    return {
        "peak_bytes_in_use": _watermark["peak"],
        "bytes_in_use": _watermark["last"],
        "limit_bytes": limit,
        "headroom_bytes": (
            int(limit) - _watermark["peak"] if limit else None
        ),
        "source": _watermark["source"],
        "samples": _watermark["samples"],
    }


# --------------------------------------------------------------------- #
# Measured-vs-modeled reconciliation
# --------------------------------------------------------------------- #
def measured_summary(solver, iters: Optional[int] = None,
                     seconds: Optional[float] = None) -> Optional[dict]:
    """The run's measured-introspection block: the primary executable's
    XLA per-step bytes/flops (global: per-device counts x mesh size)
    next to the cost model's prediction (ratio + tolerance-band flag),
    achieved rates against the configured peak, compile seconds over
    every program built. ``None`` when no executable was captured."""
    recs = records(solver)
    rec = primary_record(recs)
    if rec is None:
        return None
    devices = max(1, rec.devices)
    xla_bytes = rec.bytes_accessed * devices
    xla_flops = rec.flops * devices
    out = {
        "stepper": rec.stepper,
        "executables": len(recs),
        "devices": devices,
        "xla_bytes_per_step": xla_bytes,
        "xla_flops_per_step": xla_flops,
        "transcendentals_per_step": rec.transcendentals * devices,
        "peak_bytes": rec.peak_bytes,
        "compile_seconds": round(
            sum(r.compile_seconds for r in recs), 6
        ),
    }
    tol = tolerance_factor()
    out["tolerance_factor"] = tol
    if rec.model_bytes_per_step and xla_bytes > 0:
        ratio = rec.model_bytes_per_step / xla_bytes
        out["model_bytes_per_step"] = rec.model_bytes_per_step
        out["model_bytes_ratio"] = round(ratio, 4)
        out["bytes_within_tolerance"] = bool(1.0 / tol <= ratio <= tol)
    if rec.model_flops_per_step and xla_flops > 0:
        ratio = rec.model_flops_per_step / xla_flops
        out["model_flops_per_step"] = rec.model_flops_per_step
        out["model_flops_ratio"] = round(ratio, 4)
        out["flops_within_tolerance"] = bool(1.0 / tol <= ratio <= tol)
    if iters and seconds and seconds > 0:
        out["achieved_gbs"] = round(
            xla_bytes * iters / seconds / 1e9, 4
        )
        out["achieved_gflops"] = round(
            xla_flops * iters / seconds / 1e9, 4
        )
        from multigpu_advectiondiffusion_tpu.telemetry import costmodel

        peak_b, peak_f = costmodel.peak_rates(rec.backend)
        out["peak_gbs"] = round(peak_b * devices / 1e9, 3)
        out["peak_gflops"] = round(peak_f * devices / 1e9, 3)
        if peak_b:
            out["measured_bw_pct"] = round(
                100.0 * out["achieved_gbs"] / out["peak_gbs"], 2
            )
    return out
