"""Run telemetry: structured event stream + static per-rung cost model.

The cross-cutting observability layer every other subsystem reports
into (the upgrade of the reference's ``nvprof`` + hand-read
``PrintSummary``, SURVEY §5):

* :mod:`sink` — process-tagged JSONL event stream (spans with nesting,
  counters, domain events), installed via the CLI ``--metrics PATH``
  flag or :func:`capture`;
* :mod:`costmodel` — HBM bytes / FLOPs per step for every stepper rung,
  turning measured seconds into a roofline-efficiency percentage.
"""

from multigpu_advectiondiffusion_tpu.telemetry.sink import (  # noqa: F401
    EVENT_SCHEMA,
    NULL_SINK,
    NullSink,
    TelemetrySink,
    capture,
    counter,
    event,
    get_sink,
    install,
    span,
    uninstall,
)
from multigpu_advectiondiffusion_tpu.telemetry import costmodel  # noqa: F401

__all__ = [
    "EVENT_SCHEMA",
    "NULL_SINK",
    "NullSink",
    "TelemetrySink",
    "capture",
    "costmodel",
    "counter",
    "event",
    "get_sink",
    "install",
    "span",
    "uninstall",
]
