"""Run telemetry: structured event stream + static per-rung cost model.

The cross-cutting observability layer every other subsystem reports
into (the upgrade of the reference's ``nvprof`` + hand-read
``PrintSummary``, SURVEY §5):

* :mod:`sink` — process-tagged JSONL event stream (spans with nesting,
  counters, domain events), installed via the CLI ``--metrics PATH``
  flag or :func:`capture`;
* :mod:`costmodel` — HBM bytes / FLOPs per step for every stepper rung,
  turning measured seconds into a roofline-efficiency percentage;
* :mod:`analyze` / :mod:`export` — the consumable layer: merge
  per-rank streams onto one aligned timeline, phase breakdown,
  critical path, Chrome/Perfetto ``trace_event`` export (CLI:
  ``tpucfd-trace`` / ``python -m ... cli trace``);
* :mod:`live` — chunk-cadence step-time watch (``perf:outlier``
  events) and the ``--progress`` terminal status line;
* :mod:`xprof` — measured introspection: per-executable XLA cost/
  memory capture at dispatch (``xla:cost``), device-memory watermarks
  (``mem:watermark``) and the measured-vs-modeled reconciliation;
* :mod:`calibration` — persisted measured-peak record the cost model
  and autotuner consult ahead of the env-assumed peaks;
* :mod:`schema` — the event-kind registry tier-1 tests hold every
  emission site (and README's event table) against.
"""

from multigpu_advectiondiffusion_tpu.telemetry.sink import (  # noqa: F401
    EVENT_SCHEMA,
    NULL_SINK,
    NullSink,
    TelemetrySink,
    capture,
    counter,
    event,
    get_sink,
    install,
    span,
    uninstall,
)
from multigpu_advectiondiffusion_tpu.telemetry import costmodel  # noqa: F401
from multigpu_advectiondiffusion_tpu.telemetry import schema  # noqa: F401

# analyze/export/live are imported lazily by their consumers (the trace
# CLI, the supervisor); xprof/calibration by the dispatch layer and the
# drivers — keeping the package import light for the hot
# instrumentation path.

__all__ = [
    "schema",
    "EVENT_SCHEMA",
    "NULL_SINK",
    "NullSink",
    "TelemetrySink",
    "capture",
    "costmodel",
    "counter",
    "event",
    "get_sink",
    "install",
    "span",
    "uninstall",
]
