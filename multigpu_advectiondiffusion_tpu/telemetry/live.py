"""Live run monitoring: rolling step-time statistics with outlier
detection, and the ``--progress`` terminal status line.

The resilience stack reacts to failures *after* they surface (a NaN, a
dead rank, an SDC mismatch); this layer watches the one signal that
precedes most of them — wall time per step. A preemption stall, an SDC
re-execution, thermal throttling or a wedged peer all show up first as
a step that took too long. :class:`StepTimeWatch` keeps a rolling
per-step-time window at the supervisor's chunk cadence and emits a
``perf:outlier`` event the moment a chunk's per-step time exceeds a
robust (median + k·MAD) threshold — the observability hook a future
scheduler daemon subscribes to.

:class:`ProgressLine` renders the supervisor's ``progress`` events as a
single updating terminal line (step, rate, MLUPS, ETA, mass drift) —
``--progress`` on the CLI. On a TTY it redraws in place; piped into a
log it prints at a bounded cadence so logs stay readable.
"""

from __future__ import annotations

import collections
import statistics
import sys
import time
from typing import Optional

from multigpu_advectiondiffusion_tpu import telemetry

# Robust-threshold parameters: a chunk is an outlier when its per-step
# time exceeds median + MAD_FACTOR * 1.4826 * MAD AND at least
# REL_FLOOR x the median (the second guard keeps near-zero-MAD runs —
# bit-identical chunk times — from flagging 1-ulp jitter).
MAD_FACTOR = 5.0
REL_FLOOR = 1.5
_MAD_TO_SIGMA = 1.4826


class StepTimeWatch:
    """Rolling per-step wall-time histogram + robust outlier detection,
    fed once per supervisor chunk with (steps, seconds)."""

    def __init__(
        self,
        window: int = 256,
        min_samples: int = 8,
        mad_factor: float = MAD_FACTOR,
        rel_floor: float = REL_FLOOR,
    ):
        self._window = collections.deque(maxlen=window)
        self._all = collections.deque(maxlen=4096)  # histogram evidence
        self.min_samples = int(min_samples)
        self.mad_factor = float(mad_factor)
        self.rel_floor = float(rel_floor)
        self.chunks = 0
        self.outliers = 0

    # ------------------------------------------------------------------ #
    def threshold(self) -> Optional[float]:
        """Current outlier bound (None until enough samples)."""
        if len(self._window) < self.min_samples:
            return None
        med = statistics.median(self._window)
        mad = statistics.median(
            abs(x - med) for x in self._window
        )
        return max(
            med + self.mad_factor * _MAD_TO_SIGMA * mad,
            self.rel_floor * med,
        )

    def median(self) -> Optional[float]:
        if not self._window:
            return None
        return statistics.median(self._window)

    def observe(self, steps: int, seconds: float, step: int = 0) -> bool:
        """Record one chunk (``steps`` advanced in ``seconds`` of wall
        time). Returns True — and emits a ``perf:outlier`` event — when
        the chunk's per-step time breaches the robust threshold.
        Outlier chunks do NOT enter the rolling window (a stall must
        not drag the baseline up and mask the next one)."""
        if steps <= 0 or seconds < 0:
            return False
        per_step = seconds / steps
        bound = self.threshold()
        self.chunks += 1
        if bound is not None and per_step > bound:
            self.outliers += 1
            self._all.append(per_step)
            telemetry.event(
                "perf", "outlier",
                step=int(step),
                step_seconds=round(per_step, 6),
                median=round(self.median() or 0.0, 6),
                threshold=round(bound, 6),
            )
            return True
        self._window.append(per_step)
        self._all.append(per_step)
        return False

    # ------------------------------------------------------------------ #
    def histogram(self) -> dict:
        """Step-time histogram over the retained samples: fixed
        relative-to-median bucket edges, so a bimodal run (healthy
        steps + stall band) is visible at a glance."""
        med = (
            statistics.median(self._all) if self._all else 0.0
        )
        rel_edges = [0.5, 0.8, 0.95, 1.05, 1.25, 1.5, 2.0, 4.0]
        edges = [round(r * med, 6) for r in rel_edges]
        counts = [0] * (len(edges) + 1)
        for x in self._all:
            i = 0
            while i < len(edges) and x > edges[i]:
                i += 1
            counts[i] += 1
        return {"edges": edges, "counts": counts}

    def summary(self) -> dict:
        """Final record (also emitted as a ``perf:histogram`` event by
        the supervisor): chunk count, robust center/scale, outliers,
        histogram."""
        med = self.median()
        out = {
            "chunks": self.chunks,
            "outliers": self.outliers,
            "median_step_s": round(med, 6) if med is not None else None,
        }
        out.update(self.histogram())
        return out


def emit_histogram(watch: StepTimeWatch) -> dict:
    """Emit the final ``perf:histogram`` event for a finished run and
    return the summary dict (lands in ``SupervisorReport.perf``)."""
    summary = watch.summary()
    telemetry.event("perf", "histogram", **summary)
    return summary


def _fmt_eta(seconds: Optional[float]) -> str:
    if seconds is None:
        return "--"
    seconds = max(0.0, seconds)
    if seconds < 90:
        return f"{seconds:.0f}s"
    if seconds < 5400:
        return f"{seconds / 60:.1f}m"
    return f"{seconds / 3600:.1f}h"


class ProgressLine:
    """Terminal renderer for the supervisor's ``progress`` events.

    On a TTY the line redraws in place (carriage return); otherwise
    each update prints as a full line at most every ``log_interval``
    seconds (and always on :meth:`close`), so redirected logs keep a
    readable cadence instead of megabytes of ``\\r`` frames."""

    def __init__(self, label: str = "", out=None,
                 log_interval: float = 2.0):
        self.label = label
        self.out = out if out is not None else sys.stderr
        self.log_interval = float(log_interval)
        self._tty = bool(getattr(self.out, "isatty", lambda: False)())
        self._last_render = 0.0
        self._last_fields: Optional[dict] = None
        self._width = 0

    def _format(self, p: dict) -> str:
        step = p.get("step")
        total = p.get("steps_total")
        bits = [self.label or "run"]
        if total:
            done = p.get("steps_done", 0)
            pct = 100.0 * done / total if total else 0.0
            bits.append(f"step {step} ({pct:.0f}%)")
        else:
            bits.append(f"step {step}")
            if p.get("t") is not None and p.get("t_end") is not None:
                bits.append(f"t={p['t']:.4g}/{p['t_end']:.4g}")
        if p.get("rate_steps_per_s"):
            bits.append(f"{p['rate_steps_per_s']:.1f} steps/s")
        if p.get("mlups"):
            bits.append(f"{p['mlups']:.4g} MLUPS")
        bits.append(f"ETA {_fmt_eta(p.get('eta_seconds'))}")
        if p.get("mass_drift") is not None:
            bits.append(f"drift {p['mass_drift']:+.2e}")
        if p.get("retries"):
            bits.append(f"retries {p['retries']}")
        if p.get("outliers"):
            bits.append(f"outliers {p['outliers']}")
        return " | ".join(bits)

    def update(self, p: dict) -> None:
        self._last_fields = p
        now = time.monotonic()
        if self._tty:
            line = self._format(p)
            pad = max(0, self._width - len(line))
            self.out.write("\r" + line + " " * pad)
            self.out.flush()
            self._width = len(line)
            self._last_render = now
        elif now - self._last_render >= self.log_interval:
            self.out.write(self._format(p) + "\n")
            self.out.flush()
            self._last_render = now

    def close(self) -> None:
        """Final render (the last update always lands) + newline."""
        if self._last_fields is not None:
            line = self._format(self._last_fields)
            if self._tty:
                pad = max(0, self._width - len(line))
                self.out.write("\r" + line + " " * pad + "\n")
            else:
                self.out.write(line + "\n")
            self.out.flush()
        self._last_fields = None
