"""Crash-safe multi-run scheduler (ISSUE 14, ROADMAP item 5): a
journaled queue of CLI run requests multiplexed onto the device budget.
See ``service/daemon.py`` for the architecture and README "Service
mode" for usage."""

from multigpu_advectiondiffusion_tpu.service.admission import (
    AdmissionController,
    WarmLedger,
    latest_watermark,
    warm_key,
)
from multigpu_advectiondiffusion_tpu.service.daemon import (
    EXIT_PREEMPTED,
    EXIT_RANK_FAILURE,
    EXIT_SDC,
    InProcessRunner,
    Scheduler,
    SubprocessRunner,
    classify_failure,
)
from multigpu_advectiondiffusion_tpu.service.journal import (
    Journal,
    verify_records,
)
from multigpu_advectiondiffusion_tpu.service.queue import (
    ALLOWED_TRANSITIONS,
    STATES,
    TERMINAL_STATES,
    JobQueue,
    JobRecord,
    JobSpec,
    ingest_spool,
    new_job_id,
    submit_to_spool,
)

__all__ = [
    "ALLOWED_TRANSITIONS",
    "AdmissionController",
    "EXIT_PREEMPTED",
    "EXIT_RANK_FAILURE",
    "EXIT_SDC",
    "InProcessRunner",
    "Journal",
    "JobQueue",
    "JobRecord",
    "JobSpec",
    "STATES",
    "Scheduler",
    "SubprocessRunner",
    "TERMINAL_STATES",
    "WarmLedger",
    "classify_failure",
    "ingest_spool",
    "latest_watermark",
    "new_job_id",
    "submit_to_spool",
    "verify_records",
    "warm_key",
]
