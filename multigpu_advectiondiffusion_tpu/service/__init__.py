"""Crash-safe multi-run scheduler (ISSUE 14, ROADMAP item 5): a
journaled queue of CLI run requests multiplexed onto the device budget.
See ``service/daemon.py`` for the architecture and README "Service
mode" for usage. Since ISSUE 17 the package also hosts the
continuous-batching request server (``service/server.py``): coalesced
ensemble serving with SLOs, backpressure and zero-lost-request
recovery — README "Request serving"."""

from multigpu_advectiondiffusion_tpu.service.admission import (
    AdmissionController,
    WarmLedger,
    latest_watermark,
    warm_key,
)
from multigpu_advectiondiffusion_tpu.service.daemon import (
    EXIT_PREEMPTED,
    EXIT_RANK_FAILURE,
    EXIT_SDC,
    InProcessRunner,
    Scheduler,
    SubprocessRunner,
    classify_failure,
)
from multigpu_advectiondiffusion_tpu.service.journal import (
    Journal,
    verify_records,
)
from multigpu_advectiondiffusion_tpu.service.queue import (
    ALLOWED_TRANSITIONS,
    STATES,
    TERMINAL_STATES,
    JobQueue,
    JobRecord,
    JobSpec,
    ingest_spool,
    new_job_id,
    submit_to_spool,
)
from multigpu_advectiondiffusion_tpu.service.requests import (
    ALLOWED_REQUEST_TRANSITIONS,
    REQUEST_STATES,
    REQUEST_TERMINAL_STATES,
    RequestQueue,
    RequestRecord,
    RequestSpec,
    coalesce_key,
    ingest_request_spool,
    new_request_id,
    submit_request_to_spool,
)
from multigpu_advectiondiffusion_tpu.service.server import (
    RequestServer,
    submit_request_over_socket,
)

__all__ = [
    "ALLOWED_REQUEST_TRANSITIONS",
    "ALLOWED_TRANSITIONS",
    "AdmissionController",
    "EXIT_PREEMPTED",
    "EXIT_RANK_FAILURE",
    "EXIT_SDC",
    "InProcessRunner",
    "Journal",
    "JobQueue",
    "JobRecord",
    "JobSpec",
    "REQUEST_STATES",
    "REQUEST_TERMINAL_STATES",
    "RequestQueue",
    "RequestRecord",
    "RequestServer",
    "RequestSpec",
    "STATES",
    "Scheduler",
    "SubprocessRunner",
    "TERMINAL_STATES",
    "WarmLedger",
    "classify_failure",
    "coalesce_key",
    "ingest_request_spool",
    "ingest_spool",
    "latest_watermark",
    "new_job_id",
    "new_request_id",
    "submit_request_over_socket",
    "submit_request_to_spool",
    "submit_to_spool",
    "verify_records",
    "warm_key",
]
