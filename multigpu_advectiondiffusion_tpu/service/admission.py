"""Admission control: memory watermarks + AOT-warm admission.

Two inputs, both *measured* rather than modeled:

* **device-memory watermarks** (PR 7): every supervised job streams
  ``mem:watermark`` events into its own telemetry sink; the controller
  tail-reads the running jobs' streams, sums their latest peaks, adds
  the candidate's *expected* peak (from the warm ledger when a prior
  identical job recorded one) and defers admission while the total
  would breach the configured budget. No budget (0) = unmetered — the
  CPU container has no device limit to respect.
* **the AOT executable cache** (PR 9): a job whose exact request
  already ran to completion against the shared per-root cache is
  *warm* — admitting it costs a deserialize, not a compile. The warm
  ledger maps the request fingerprint to the measured facts of the
  completed run (compile seconds the cache now saves, the observed
  memory peak) and is rebuilt from the journal on recovery, so a
  restarted scheduler keeps its warm knowledge.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional


def warm_key(argv, mesh_arg: Optional[str] = None) -> str:
    """Fingerprint of one run request: the spec argv plus the granted
    mesh (a different mesh compiles a different executable, so it is a
    different warmth)."""
    body = json.dumps([list(argv), mesh_arg or ""])
    return hashlib.sha256(body.encode()).hexdigest()[:16]


def latest_watermark(events_path: str,
                     tail_bytes: int = 131072) -> Optional[int]:
    """The newest ``mem:watermark`` peak (bytes) in a job's telemetry
    stream, read from a bounded tail so the admission pass stays O(1)
    per running job. None when the stream (or the event) is absent."""
    try:
        size = os.path.getsize(events_path)
        with open(events_path, "rb") as f:
            f.seek(max(0, size - tail_bytes))
            text = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    peak = None
    for line in text.splitlines():
        if '"mem"' not in line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            continue  # torn tail / partial first line of the window
        if ev.get("kind") == "mem" and ev.get("name") == "watermark":
            got = ev.get("peak_bytes") or ev.get("bytes_in_use")
            if got is not None:
                peak = int(got)
    return peak


class WarmLedger:
    """Request fingerprint -> measured facts of a completed identical
    run. Journal-rebuilt (the scheduler records the ledger entry in the
    job's ``done`` transition payload), so warmth survives the
    scheduler's own death exactly like the queue does."""

    def __init__(self):
        self._entries: Dict[str, dict] = {}

    def observe(self, key: str, compile_seconds: float = 0.0,
                peak_bytes: Optional[int] = None) -> dict:
        entry = {
            "compile_seconds": float(compile_seconds or 0.0),
            "peak_bytes": int(peak_bytes) if peak_bytes else None,
        }
        self._entries[key] = entry
        return entry

    def lookup(self, key: str) -> Optional[dict]:
        return self._entries.get(key)

    def __len__(self) -> int:
        return len(self._entries)


class AdmissionController:
    """Decides admit/defer for the highest-priority runnable job.

    ``decide`` returns ``(verdict, info)`` where verdict is ``"admit"``
    or ``"defer"``; info carries the granted device count, warmth and
    the memory accounting — the fields the ``sched:admit``/
    ``sched:defer`` events publish.
    """

    def __init__(self, device_budget: int = 1,
                 mem_budget_bytes: int = 0,
                 ledger: Optional[WarmLedger] = None):
        self.device_budget = max(1, int(device_budget))
        self.mem_budget_bytes = int(mem_budget_bytes or 0)
        self.ledger = ledger if ledger is not None else WarmLedger()

    # ------------------------------------------------------------------ #
    def grant_devices(self, requested: int, free: int) -> int:
        """The elastic slice rule: the largest divisor of the request
        that fits the free devices (>= 1) — a preempted 4-way job
        resumes 2-way when only 2 devices freed up, never 3-way into a
        grid its request was not shaped for."""
        want = max(1, int(requested or 1))
        free = max(0, int(free))
        if free <= 0:
            return 0
        for d in range(min(want, free), 0, -1):
            if want % d == 0:
                return d
        return 1

    def mesh_arg(self, spec, granted: int) -> Optional[str]:
        if granted <= 1:
            return None
        return spec.mesh_template.format(devices=granted)

    # ------------------------------------------------------------------ #
    def observed_memory(self, running_streams: List[str]) -> int:
        """Sum of the running jobs' latest watermark peaks."""
        total = 0
        for path in running_streams:
            peak = latest_watermark(path)
            if peak:
                total += peak
        return total

    def decide(self, record, free_slots: int, free_devices: int,
               running_streams: List[str]) -> tuple:
        spec = record.spec
        if free_slots <= 0:
            return "defer", {"reason": "slots", "free_slots": 0}
        granted = self.grant_devices(spec.devices, free_devices)
        if granted <= 0:
            return "defer", {
                "reason": "devices",
                "requested": spec.devices,
                "free_devices": free_devices,
            }
        key = warm_key(spec.argv, self.mesh_arg(spec, granted))
        warm = self.ledger.lookup(key)
        info = {
            "granted_devices": granted,
            "warm": warm is not None,
            "warm_key": key,
            "expected_compile_seconds_saved": (
                warm["compile_seconds"] if warm else None
            ),
        }
        if self.mem_budget_bytes:
            in_use = self.observed_memory(running_streams)
            estimate = (warm or {}).get("peak_bytes") or 0
            info.update(mem_in_use=in_use, mem_estimate=estimate,
                        mem_budget=self.mem_budget_bytes)
            if in_use + estimate > self.mem_budget_bytes:
                info["reason"] = "memory"
                return "defer", info
        return "admit", info
