"""Crash-safe continuous-batching request server (ISSUE 17).

PR 14's scheduler multiplexes *jobs* — one subprocess per run, the
reference's ``Run.m`` one-binary-per-configuration shape made durable.
This daemon multiplexes *requests*: scenario solves arriving through
the atomic spool mailbox (or an optional local-socket RPC) are
coalesced by compatibility key (``requests.coalesce_key`` — same
family/grid/dtype/precision/impl/mesh compiles the same executable)
onto the ensemble member axis (PR 9/11) and marched as ONE batched
dispatch through bounded ``advance_to_ensemble(max_steps=)`` slices —
the LLM-continuous-batching shape applied to PDE solves:

* finished members return results at the slice boundary while
  stragglers keep stepping;
* newly arrived compatible requests JOIN at the next slice boundary
  (the batch is parked-and-reformed — PR 9 proved the vmap lanes
  bit-exact regardless of batch composition, and each step is a pure
  function of ``(u, t)``, so re-batching never changes any member's
  trajectory);
* divergence of one member (``EnsembleMemberDivergedError`` names
  indices) fails ONLY that request with forensics; the rest re-batch
  and complete.

Robustness is the headline, and it is the PR 14 discipline end to end:
every request transition is a CRC-sealed record in the write-ahead
journal *before* the in-memory queue mutates, per-member slice
checkpoints land atomically each slice, and result artifacts publish
before the ``done`` record — so a SIGKILL at ANY instant replays to
zero lost (and zero duplicated) requests: in-flight members resume
from their slice checkpoint, unstarted ones re-batch, and either path
is bit-exact against an uninterrupted run. Overload is policy, not a
crash: the bounded queue sheds with a structured retry-after verdict
(``serve:shed``), and the memory-watermark admission estimate caps
batch width before anything allocates.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from multigpu_advectiondiffusion_tpu.service.admission import WarmLedger
from multigpu_advectiondiffusion_tpu.service.journal import Journal
from multigpu_advectiondiffusion_tpu.service.requests import (
    RequestQueue,
    RequestRecord,
    RequestSpec,
    coalesce_key,
    ingest_request_spool,
    request_dir,
    submit_request_to_spool,
)

#: rough live-state multiplier for the admission estimate: solution +
#: integrator stages + halo/stencil temporaries per member
_STATE_BYTES_FACTOR = 8

_ITEMSIZE = {"float32": 4, "float64": 8, "bfloat16": 2}


def _finish_eps(te: float) -> float:
    """The ensemble engine's per-member freeze epsilon
    (models/base.advance_to_ensemble) — the server's finished test MUST
    match it, or a frozen lane would be marched forever."""
    return 1e-12 * max(1.0, abs(float(te)))


def submit_request_over_socket(socket_path: str,
                               spec: RequestSpec) -> None:
    """The optional local RPC: one datagram, one request. The server
    writes it into the same spool mailbox the file path uses, so both
    fronts share the journal-first ingest."""
    import socket as _socket

    spec.validate()
    s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_DGRAM)
    try:
        s.sendto(json.dumps(spec.to_json()).encode(), socket_path)
    finally:
        s.close()


class _Batch:
    """One live coalesced dispatch: the ensemble front end, the batched
    state, and the lane -> request mapping (``None`` lanes are clone
    padding so B tiles a member-sharded mesh; their results are
    discarded)."""

    def __init__(self, batch_id: str, key: str, ens, estate,
                 reqs: List[Optional[RequestRecord]],
                 te: List[float]):
        self.batch_id = batch_id
        self.key = key
        self.ens = ens
        self.estate = estate
        self.reqs = reqs
        self.te = te
        self.started = False
        self.slices = 0
        self.prev_it = np.asarray(estate.it).copy()
        # pipelined serving (ISSUE 19): dispatched-but-unretired slices,
        # oldest first. Each entry keeps the slice's estate (t/it stay
        # readable — only u is donated), its launched health stats, the
        # PREVIOUS slice's it (frozen-lane test), and the dispatch wall.
        self.inflight: List[dict] = []
        # device-busy accounting (mechanics-grade: busy is measured
        # dispatch -> first-blocking-pull, so pipelined overlap shows
        # as contiguous busy intervals)
        self.t_formed = time.monotonic()
        self.busy_s = 0.0
        self.last_ready = self.t_formed

    def active(self) -> List[RequestRecord]:
        return [r for r in self.reqs if r is not None
                and r.state in ("batched", "running")]

    @property
    def priority(self) -> int:
        live = self.active()
        return max((r.spec.priority for r in live), default=-(1 << 30))


class RequestServer:
    """The serving daemon. Layout under ``root``::

        journal.jsonl        the request write-ahead journal
        serve_events.jsonl   the daemon's own telemetry stream
        spool/               atomic submission mailbox
        requests/<id>/       verdict.json / result.json / result.bin /
                             member.ckpt (slice checkpoint) / crash.json
    """

    def __init__(self, root: str, max_batch: int = 8,
                 slice_steps: int = 16, queue_bound: int = 64,
                 retry_after_s: float = 2.0,
                 mesh: Optional[str] = None,
                 mem_budget_bytes: int = 0,
                 checkpoint_every: int = 1,
                 growth: float = 1e3,
                 socket_path: Optional[str] = None,
                 fsync: bool = True,
                 metrics_port: Optional[int] = None,
                 metrics_every_s: float = 2.0,
                 slo_objective: float = 0.99,
                 slo_windows=None,
                 pipeline: bool = False,
                 pipeline_depth: int = 2,
                 donate: Optional[bool] = None,
                 group_commit_s: float = 0.0,
                 prewarm: bool = True,
                 http_port: Optional[int] = None,
                 lease: bool = False,
                 heartbeat_s: float = 2.0,
                 best_effort: bool = False,
                 hang_multiplier: float = 8.0,
                 hang_min_history: int = 5,
                 hang_budget_s: Optional[float] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        # single-writer lease (ISSUE 20): acquire BEFORE any other root
        # artifact is opened — a refused incarnation must exit without
        # writing a byte of the holder's journal
        self.lease = None
        if lease:
            from multigpu_advectiondiffusion_tpu.service.lease import (
                ServiceLease,
            )

            self.lease = ServiceLease(
                self.root, role="serve-requests",
                heartbeat_s=heartbeat_s,
            ).acquire()
        os.makedirs(os.path.join(self.root, "requests"), exist_ok=True)
        from multigpu_advectiondiffusion_tpu.telemetry.metrics import (
            DEFAULT_SLO_WINDOWS,
            MetricsRegistry,
            SloTracker,
        )
        from multigpu_advectiondiffusion_tpu.telemetry.sink import (
            TelemetrySink,
        )

        # a PRIVATE sink (the scheduler-daemon discipline): in-process
        # solver runs install their own module-level sinks and must not
        # tear down the server's stream
        self._sink = TelemetrySink(
            os.path.join(self.root, "serve_events.jsonl")
        )
        if self.lease is not None:
            self._sink.event(
                "lease", "acquire", pid=os.getpid(),
                path=self.lease.path,
                takeover=self.lease.takeover is not None,
            )
        # fleet metrics (ISSUE 18): one snapshot dir PER INCARNATION —
        # a restarted server must not overwrite the dead life's
        # counters, because the merged union across incarnations is
        # what reconciles exactly-once against the replayed journal
        self.metrics = MetricsRegistry(proc=f"server-{os.getpid()}")
        if self.lease is not None and self.lease.takeover:
            self._sink.event(
                "lease", "takeover", pid=os.getpid(),
                prev_pid=self.lease.takeover.get("pid"),
                age_s=self.lease.takeover.get("age_s"),
            )
            self.metrics.counter("serve_lease_takeovers_total").inc()
        self.metrics_dir = os.path.join(
            self.root, "metrics", self.metrics.proc
        )
        self.metrics_every_s = float(metrics_every_s)
        self._last_export = 0.0
        self.slo = SloTracker(
            name="request_deadline", objective=float(slo_objective),
            windows=slo_windows or DEFAULT_SLO_WINDOWS,
            emit=self._emit_slo,
        )
        # zero-copy pipelined serving knobs (ISSUE 19)
        self.pipeline = bool(pipeline)
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.donate = bool(pipeline if donate is None else donate)
        self.prewarm_enabled = bool(prewarm)
        self._prewarmed: set = set()
        self._pending_acks: List[tuple] = []
        # fault injection for out/serving_perf_gate.sh --selftest: ack
        # a request's verdict BEFORE its journal record is durable
        # (and drop the record, simulating the power-loss window group
        # commit must never expose) — the gate's consistency check
        # must trip on this
        self._fault_ack_before_fsync = os.environ.get(
            "TPUCFD_FAULT_ACK_BEFORE_FSYNC", ""
        ) not in ("", "0")
        self.journal = Journal(
            os.path.join(self.root, "journal.jsonl"), fsync=fsync,
            group_commit_s=group_commit_s,
        )
        self.journal.on_commit_seconds = self.metrics.histogram(
            "serve_journal_fsync_seconds"
        ).observe
        self.journal.on_commit_batch = self.metrics.histogram(
            "serve_journal_fsync_batch_records"
        ).observe
        self.queue, self.replay_report = RequestQueue.replay(self.journal)
        self.max_batch = max(1, int(max_batch))
        self.slice_steps = max(1, int(slice_steps))
        self.queue_bound = max(1, int(queue_bound))
        self.retry_after_s = float(retry_after_s)
        self.mesh_spec = mesh or ""
        self.mem_budget_bytes = int(mem_budget_bytes or 0)
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.growth = float(growth)
        self.ledger = self._rebuild_ledger()
        self._batch: Optional[_Batch] = None
        self._templates: Dict[str, dict] = {}
        self._recovered = False
        self._stalled_ticks = 0
        # graceful drain (ISSUE 20): the signal handler only sets the
        # request flag — journal writes from a handler could interleave
        # with an append already on the stack; tick() acts on it
        self.draining = False
        self._drain_requested: Optional[str] = None
        # deadline enforcement: past-deadline members are cancelled at
        # slice boundaries unless the operator opted out
        self.best_effort = bool(best_effort)
        # hung-dispatch watchdog: wall-clock budget from measured slice
        # history (rolling median × multiplier, the bench outlier
        # discipline); an explicit hang_budget_s overrides. Cohort
        # labels drive the poison-member bisection across re-batches.
        self.hang_multiplier = float(hang_multiplier)
        self.hang_min_history = max(1, int(hang_min_history))
        self.hang_budget_s = (
            float(hang_budget_s) if hang_budget_s else None
        )
        self._slice_history: deque = deque(maxlen=64)
        self._hang_cohort: Dict[str, str] = {}
        self._hang_strikes: Dict[str, int] = {}
        self._sock = None
        self.socket_path = socket_path
        if socket_path:
            self._open_socket(socket_path)
        self._http = None
        self.metrics_port: Optional[int] = None
        if metrics_port is not None:
            self._start_metrics_http(int(metrics_port))
        # stdlib HTTP ingestion front (ISSUE 19 satellite): POST maps
        # onto the spool protocol, GET reads verdict/result artifacts
        self._ingest_http = None
        self.http_port: Optional[int] = None
        if http_port is not None:
            from multigpu_advectiondiffusion_tpu.service.http import (
                start_ingest_http,
            )

            self._ingest_http, self.http_port = start_ingest_http(
                self, int(http_port)
            )

    # ------------------------------------------------------------------ #
    # Plumbing
    # ------------------------------------------------------------------ #
    def request_dir(self, request_id: str) -> str:
        return request_dir(self.root, request_id)

    def _ckpt_path(self, request_id: str) -> str:
        return os.path.join(self.request_dir(request_id), "member.ckpt")

    def _rebuild_ledger(self) -> WarmLedger:
        """Warmth survives the server's death exactly like the queue:
        rebuilt from the journal's ``warm`` note records."""
        ledger = WarmLedger()
        records, _ = Journal.replay(self.journal.path)
        for rec in records:
            if rec.get("type") == "note" and rec.get("note") == "warm":
                key = rec.get("key")
                if key:
                    ledger.observe(
                        key,
                        compile_seconds=rec.get("compile_seconds", 0.0),
                        peak_bytes=rec.get("peak_bytes"),
                    )
        return ledger

    def _transition(self, request_id: str, to: str,
                    **info) -> RequestRecord:
        frm = self.queue.requests[request_id].state
        rec = self.queue.transition(request_id, to, **info)
        self._sink.event("req", "state", job=request_id,
                         **{"from": frm, "to": to})
        if to == "requeued":
            self.metrics.counter("serve_requests_requeued_total").inc()
        return rec

    def _write_verdict(self, request_id: str, verdict: dict) -> None:
        from multigpu_advectiondiffusion_tpu.utils.io import (
            atomic_write_text,
        )

        d = self.request_dir(request_id)
        os.makedirs(d, exist_ok=True)
        atomic_write_text(
            os.path.join(d, "verdict.json"),
            json.dumps(verdict, sort_keys=True, indent=1),
        )

    def _ack(self, request_id: str, verdict: dict) -> None:
        """Write the externally visible verdict — the ack. Under group
        commit the write is DEFERRED to the next :meth:`_flush_acks`
        barrier, so no submitter ever observes an ack whose journal
        record is not yet fsync-durable (the ISSUE 19 crash-safety
        contract). With ``group_commit_s=0`` every append fsyncs
        inline, so the ack writes immediately — the pre-group-commit
        behavior, byte for byte."""
        if self.journal.group_commit_s > 0.0:
            self._pending_acks.append((request_id, verdict))
        else:
            self._write_verdict(request_id, verdict)

    def _flush_acks(self) -> None:
        """The group-commit barrier of the serving loop: fsync every
        buffered journal record, then release the verdict writes that
        were waiting on durability. Called once per tick (and at
        close), so ack latency is bounded by the tick cadence plus the
        journal's latency window."""
        if self._pending_acks:
            self.journal.commit()
            for rid, verdict in self._pending_acks:
                self._write_verdict(rid, verdict)
            self._pending_acks.clear()
        else:
            # bound staleness of unacked records (e.g. slice
            # checkpoints) even when nothing is waiting on an ack
            self.journal.maybe_commit()

    def _member_bytes(self, spec: RequestSpec) -> int:
        cells = int(math.prod(int(v) for v in spec.n))
        item = _ITEMSIZE.get(spec.dtype, 4)
        if spec.precision == "bf16":
            item = 4  # f32 compute temporaries dominate the estimate
        return cells * item * _STATE_BYTES_FACTOR

    # ------------------------------------------------------------------ #
    # Fleet metrics + SLO surface (ISSUE 18)
    # ------------------------------------------------------------------ #
    def _emit_slo(self, name: str, payload: dict) -> None:
        """An SLO verdict goes to BOTH surfaces: the event stream (for
        live consumers) and the journal (a note record, so the alert
        survives the process exactly like every request transition)."""
        self._sink.event("slo", name, **payload)
        self.journal.append("note", note=f"slo_{name}", **payload)
        counter = ("serve_slo_alerts_total" if name == "alert"
                   else "serve_slo_resolves_total")
        self.metrics.counter(counter).inc()

    def _observe_deadline(self, rec: RequestRecord,
                          seconds: Optional[float], ok: bool) -> None:
        """Feed one terminal verdict to the deadline SLO (requests
        without a declared deadline carry no SLO contract)."""
        deadline = rec.spec.deadline_s
        if deadline is None:
            return
        met = ok and seconds is not None and (
            float(seconds) <= float(deadline)
        )
        self.metrics.counter(
            "serve_deadline_met_total" if met
            else "serve_deadline_missed_total"
        ).inc()
        self.slo.observe(met)
        self.slo.evaluate()

    def export_metrics(self, force: bool = True) -> Optional[dict]:
        """Publish this incarnation's snapshot (atomic JSON + Prom
        text under ``metrics/<proc>/``). Throttled to
        ``metrics_every_s`` unless forced."""
        now = time.monotonic()
        if not force and now - self._last_export < self.metrics_every_s:
            return None
        self._last_export = now
        self.metrics.gauge("serve_queue_depth").set(
            len(self.queue.open_requests())
        )
        snap = self.metrics.write_snapshot(self.metrics_dir)
        self._sink.event(
            "metrics", "snapshot", dir=self.metrics_dir,
            counters=len(snap["counters"]),
            gauges=len(snap["gauges"]),
            histograms=len(snap["histograms"]),
        )
        return snap

    def _start_metrics_http(self, port: int) -> None:
        """The first brick of the HTTP transport debt: a read-only
        stdlib endpoint on loopback serving ``/metrics`` (Prometheus
        text) and ``/metrics.json`` from the live registry."""
        import http.server
        import threading

        registry = self.metrics

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib contract
                if self.path.split("?")[0] == "/metrics":
                    body = registry.to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.split("?")[0] == "/metrics.json":
                    body = json.dumps(
                        registry.snapshot(), sort_keys=True
                    ).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet by design
                pass

        self._http = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), _Handler
        )
        self.metrics_port = int(self._http.server_address[1])
        thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )
        thread.start()
        self._sink.event("metrics", "serve", port=self.metrics_port)

    # ------------------------------------------------------------------ #
    # Socket RPC (optional)
    # ------------------------------------------------------------------ #
    def _open_socket(self, path: str) -> None:
        import socket as _socket

        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_DGRAM)
        s.bind(path)
        s.setblocking(False)
        self._sock = s

    def _drain_socket(self) -> None:
        if self._sock is None:
            return
        while True:
            try:
                data = self._sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            try:
                payload = json.loads(data.decode())
                if not isinstance(payload, dict):
                    raise ValueError("socket payload is not a dict")
                spec = RequestSpec.from_json(payload)
                submit_request_to_spool(self.root, spec)
            except (ValueError, TypeError, KeyError) as err:
                self._sink.event(
                    "serve", "spool_skip", file="<socket>",
                    error=f"{type(err).__name__}: {err}"[:200],
                )

    # ------------------------------------------------------------------ #
    # Recovery
    # ------------------------------------------------------------------ #
    def recover(self) -> dict:
        """Replay already rebuilt the queue; classify what the dead
        server left in flight. Members with a slice checkpoint resume
        from it, the rest re-run from their ICs — both bit-exact (each
        step is a pure function of the state, so WHERE the march was
        split cannot change it)."""
        if self._recovered:
            return {}
        self._recovered = True
        # a clean handover leaves the shutdown marker as the LAST
        # record: the predecessor drained (parked everything to
        # requeued), so this incarnation starts with zero requeue work
        records, _ = Journal.replay(self.journal.path)
        clean = bool(records) and (
            records[-1].get("type") == "note"
            and records[-1].get("note") == "shutdown"
            and bool(records[-1].get("clean"))
        )
        requeued = failed = 0
        for rec in list(self.queue.in_flight()):
            rid = rec.request_id
            ckpt = self._ckpt_path(rid)
            self._transition(
                rid, "requeued", reason="crash_recovery",
                attempt=rec.attempts + 1,
                checkpoint=ckpt if os.path.exists(ckpt) else None,
            )
            if rec.attempts > rec.spec.max_retries + 1:
                self._fail(rec, reason="retries_exhausted")
                failed += 1
            else:
                requeued += 1
        report = {
            "records": self.replay_report.get("records", 0),
            "torn_lines": self.replay_report.get("torn_lines", 0),
            "requests": len(self.queue.requests),
            "requeued": requeued,
            "failed": failed,
            "clean_shutdown": clean,
        }
        self._sink.event("serve", "recover", **report)
        return report

    # ------------------------------------------------------------------ #
    # Ingest + admission
    # ------------------------------------------------------------------ #
    def _ingest(self) -> None:
        if self.draining:
            # admission is closed: the socket stays unread and the
            # spool — a durable mailbox — is left intact for the
            # successor; HTTP answers with the structured draining
            # verdict. Nothing submitted from here on is lost, it is
            # simply the next incarnation's work.
            return
        self._drain_socket()

        def on_skip(name, reason):
            self._sink.event("serve", "spool_skip",
                             file=name, error=reason)

        for rec in ingest_request_spool(self.root, self.queue,
                                        on_skip=on_skip):
            self._sink.event("req", "submit", job=rec.request_id,
                             priority=rec.spec.priority)
            self.metrics.counter("serve_requests_received_total").inc()
        received = sorted(
            (r for r in self.queue.requests.values()
             if r.state == "received"),
            key=lambda r: r.order,
        )
        for rec in received:
            if len(self.queue.open_requests()) > self.queue_bound:
                self._shed(rec)
            else:
                self._admit(rec)

    def _shed(self, rec: RequestRecord) -> None:
        """Backpressure by policy: the bounded queue sheds the newest
        arrival with a structured retry-after verdict instead of
        growing until something OOMs."""
        rid = rec.request_id
        self._transition(rid, "shed", reason="queue_bound",
                         retry_after_s=self.retry_after_s)
        self._ack(rid, {
            "status": "shed",
            "reason": "queue_bound",
            "retry_after_s": self.retry_after_s,
            "open_requests": len(self.queue.open_requests()),
            "queue_bound": self.queue_bound,
        })
        self._sink.event(
            "serve", "shed", job=rid,
            open=len(self.queue.open_requests()),
            bound=self.queue_bound,
            retry_after_s=self.retry_after_s,
        )
        self.metrics.counter("serve_requests_shed_total").inc()

    def _admit(self, rec: RequestRecord) -> None:
        """Semantic admission: model resolves through the registry,
        operand names are the family's, the mesh constraint matches,
        and the memory estimate fits the budget. A bad request fails
        ALONE (``admitted -> failed``), never the daemon."""
        rid = rec.request_id
        spec = rec.spec
        problem = None
        try:
            tpl = self._template(spec)
            supported = set(tpl["solver"].ensemble_operands())
            unknown = sorted(set(spec.operands) - supported)
            if unknown:
                problem = (
                    f"operand(s) {unknown} are not member-varying "
                    f"scalars of {spec.model!r} ({sorted(supported)})"
                )
        except Exception as err:  # noqa: BLE001 — per-request verdict
            problem = f"{type(err).__name__}: {err}"[:300]
        if problem is None and spec.mesh and spec.mesh != self.mesh_spec:
            problem = (
                f"request wants mesh {spec.mesh!r} but this server "
                f"runs {self.mesh_spec or '<unsharded>'!r}"
            )
        if problem is None and self.mem_budget_bytes:
            need = self._member_bytes(spec)
            if need > self.mem_budget_bytes:
                problem = (
                    f"memory_budget: one member needs ~{need} bytes, "
                    f"budget is {self.mem_budget_bytes}"
                )
        self._transition(rid, "admitted")
        if problem is not None:
            self._fail(rec, reason=problem)
            return
        key = coalesce_key(spec)
        self._sink.event(
            "serve", "admit", job=rid, key=key,
            warm=self.ledger.lookup(key) is not None,
        )
        self.metrics.counter("serve_requests_admitted_total").inc()

    # ------------------------------------------------------------------ #
    # Model templates + member states
    # ------------------------------------------------------------------ #
    def _template(self, spec: RequestSpec) -> dict:
        """Per-coalesce-key solver template: family, config, a probe
        solver (operand-name validation, member configs), and the
        parsed serving mesh. Cached — every request in a batch shares
        it by construction."""
        key = coalesce_key(spec)
        tpl = self._templates.get(key)
        if tpl is not None:
            return tpl
        import dataclasses

        from multigpu_advectiondiffusion_tpu.core.grid import Grid
        from multigpu_advectiondiffusion_tpu.models import registry

        fam = registry.get(spec.model)
        grid = Grid.make(
            *spec.n,
            lengths=[float(v) for v in spec.lengths] or None,
        )
        fields = {f.name for f in dataclasses.fields(fam.config_cls)}
        kwargs = {
            k: v for k, v in dict(
                dtype=spec.dtype, precision=spec.precision,
                impl=spec.impl,
            ).items() if k in fields
        }
        cfg = fam.config_cls(grid=grid, **kwargs)
        solver = fam.solver_cls(cfg)
        mesh = decomp = None
        if self.mesh_spec:
            from multigpu_advectiondiffusion_tpu.cli.drivers import (
                parse_ensemble_mesh,
            )

            mesh, decomp = parse_ensemble_mesh(self.mesh_spec, grid)
        tpl = {"family": fam, "cfg": cfg, "solver": solver,
               "mesh": mesh, "decomp": decomp}
        self._templates[key] = tpl
        return tpl

    @staticmethod
    def _member_overrides(spec: RequestSpec) -> dict:
        ov = dict(spec.operands)
        if spec.ic:
            ov["ic"] = spec.ic
        if spec.ic_params:
            ov["ic_params"] = tuple(sorted(
                (k, float(v)) for k, v in spec.ic_params.items()
            ))
        if spec.t0 is not None:
            ov["t0"] = float(spec.t0)
        return ov

    def _member_state(self, rec: RequestRecord, tpl: dict):
        """The lane's starting state: the slice checkpoint when one
        exists and loads (crash resume), else the initial condition. A
        torn/corrupt checkpoint falls back to the IC — slower, but
        bit-exact by the slicing invariance."""
        import dataclasses

        ckpt = self._ckpt_path(rec.request_id)
        cfg = tpl["cfg"]
        if os.path.exists(ckpt):
            try:
                from multigpu_advectiondiffusion_tpu.utils.io import (
                    load_checkpoint,
                )

                st = load_checkpoint(ckpt)
                if tuple(st.u.shape) == tuple(cfg.grid.shape):
                    return st
            except Exception:  # noqa: BLE001 — IC fallback below
                pass
        fields = {f.name for f in dataclasses.fields(cfg)}
        ov = {
            k: v for k, v in self._member_overrides(rec.spec).items()
            if k in fields
        }
        member_cfg = dataclasses.replace(cfg, **ov) if ov else cfg
        return tpl["family"].solver_cls(member_cfg).initial_state()

    # ------------------------------------------------------------------ #
    # Batch formation
    # ------------------------------------------------------------------ #
    def _batch_cap(self, spec: RequestSpec) -> int:
        """Batch width cap for a coalesce group led by ``spec`` — the
        max-batch knob tightened by the memory-budget admission
        estimate. Shared by formation and the speculative prewarm so
        the prewarmed executable's B matches the batch that forms."""
        cap = self.max_batch
        if self.mem_budget_bytes:
            per = self._member_bytes(spec)
            cap = min(cap, max(
                1, self.mem_budget_bytes // max(1, per)
            ))
        return int(cap)

    def _form_batch(self) -> Optional[_Batch]:
        cands = self.queue.batchable()
        if not cands:
            return None
        lead = cands[0]
        key = coalesce_key(lead.spec)
        # hang-bisection cohorts re-batch separately: a suspect set
        # split by the watchdog must not remix, or repeated hangs
        # could never isolate the poison member
        cohort = self._hang_cohort.get(lead.request_id)
        group = [r for r in cands if coalesce_key(r.spec) == key
                 and self._hang_cohort.get(r.request_id) == cohort]
        cap = self._batch_cap(lead.spec)
        if cap < self.max_batch:
            for rec in group[cap:]:
                self._sink.event("serve", "defer",
                                 job=rec.request_id,
                                 reason="memory")
        group = group[:cap]
        try:
            tpl = self._template(lead.spec)
        except Exception as err:  # noqa: BLE001 — fail the group
            for rec in group:
                self._fail(rec,
                           reason=f"{type(err).__name__}: {err}"[:300])
            return None
        # per-member starting states; a request whose IC/checkpoint
        # cannot build fails alone
        reqs: List[Optional[RequestRecord]] = []
        states, te, overrides = [], [], []
        for rec in group:
            try:
                st = self._member_state(rec, tpl)
            except Exception as err:  # noqa: BLE001
                self._fail(rec,
                           reason=f"state: {type(err).__name__}: "
                                  f"{err}"[:300])
                continue
            reqs.append(rec)
            states.append(st)
            te.append(float(rec.spec.t_end))
            overrides.append(self._member_overrides(rec.spec))
        if not reqs:
            return None
        from multigpu_advectiondiffusion_tpu.parallel.mesh import (
            member_extent,
        )

        mext = member_extent(tpl["mesh"])
        pad = (-len(reqs)) % mext
        for _ in range(pad):
            # clone lanes so B tiles the member-sharded mesh; their
            # results are discarded at the slice boundary
            reqs.append(None)
            states.append(states[0])
            te.append(te[0])
            overrides.append(dict(overrides[0]))
        from multigpu_advectiondiffusion_tpu.models.ensemble import (
            EnsembleSolver,
        )

        try:
            ens = EnsembleSolver(
                tpl["family"].solver_cls, tpl["cfg"], overrides,
                mesh=tpl["mesh"], decomp=tpl["decomp"],
            )
            estate = self._stack(ens, states)
            ens.arm(estate)
        except Exception as err:  # noqa: BLE001 — fail the group
            for rec in reqs:
                if rec is not None:
                    self._fail(rec,
                               reason=f"batch: {type(err).__name__}: "
                                      f"{err}"[:300])
            return None
        batch_id = f"b{uuid.uuid4().hex[:8]}"
        for i, rec in enumerate(reqs):
            if rec is None:
                continue
            self._transition(
                rec.request_id, "batched", batch=batch_id, member=i,
                checkpoint=self._ckpt_path(rec.request_id),
            )
        self._sink.event(
            "serve", "batch", batch=batch_id, key=key,
            members=sum(1 for r in reqs if r is not None),
            lanes=len(reqs),
        )
        self.metrics.counter("serve_batches_formed_total").inc()
        return _Batch(batch_id, key, ens, estate, reqs, te)

    @staticmethod
    def _stack(ens, states):
        """Stack member states and place them on the ensemble sharding
        (the EnsembleSolver.initial_state device_put, applied to OUR
        lane states — resumes and joins carry live states, not ICs)."""
        from multigpu_advectiondiffusion_tpu.models.state import (
            EnsembleState,
        )

        est = EnsembleState.stack(states)
        if ens.mesh is not None:
            import jax
            from jax.sharding import NamedSharding

            uspec, mspec = ens.solver._ensemble_specs()
            est = EnsembleState(
                u=jax.device_put(est.u,
                                 NamedSharding(ens.mesh, uspec)),
                t=jax.device_put(est.t,
                                 NamedSharding(ens.mesh, mspec)),
                it=jax.device_put(est.it,
                                  NamedSharding(ens.mesh, mspec)),
            )
        return est

    # ------------------------------------------------------------------ #
    # Lifecycle endpoints
    # ------------------------------------------------------------------ #
    def _fail(self, rec: RequestRecord, reason: str,
              forensics: Optional[dict] = None) -> None:
        rid = rec.request_id
        if forensics:
            from multigpu_advectiondiffusion_tpu.utils.io import (
                atomic_write_text,
            )

            d = self.request_dir(rid)
            os.makedirs(d, exist_ok=True)
            atomic_write_text(os.path.join(d, "crash.json"),
                              json.dumps(forensics, sort_keys=True))
        verdict = {
            "status": "failed", "reason": reason,
            "attempts": rec.attempts,
            **({"forensics": "crash.json"} if forensics else {}),
        }
        if self.journal.group_commit_s > 0.0:
            # journal first, ack after the commit barrier
            self._transition(rid, "failed", reason=reason,
                             failure={"reason": reason})
            self._ack(rid, verdict)
        else:
            self._write_verdict(rid, verdict)
            self._transition(rid, "failed", reason=reason,
                             failure={"reason": reason})
        extra = ({"deadline_s": rec.spec.deadline_s}
                 if rec.spec.deadline_s is not None else {})
        self._sink.event("req", "failed", job=rid, reason=reason[:200],
                         **extra)
        self.metrics.counter("serve_requests_failed_total").inc()
        self._hang_cohort.pop(rid, None)
        self._observe_deadline(rec, seconds=None, ok=False)

    def _finish(self, rec: RequestRecord, b: _Batch, lane: int,
                u: np.ndarray, t: float, it: int) -> None:
        """Publish the lane's result, then journal ``done`` — in that
        order, so a crash between the two re-runs the member (same
        bits) instead of losing the answer. Under group commit the
        verdict ack additionally waits for the ``done`` record's fsync
        (the :meth:`_flush_acks` barrier). ``u``/``t``/``it`` arrive
        as HOST values — the pipelined path gathers finished lanes
        device-side and awaits the copy before calling this."""
        from multigpu_advectiondiffusion_tpu.utils.io import (
            atomic_write_text,
            save_binary,
        )

        rid = rec.request_id
        d = self.request_dir(rid)
        os.makedirs(d, exist_ok=True)
        save_binary(u, os.path.join(d, "result.bin"))
        seconds = (
            time.time() - rec.admitted_wall
            if rec.admitted_wall else None
        )
        summary = {
            "request_id": rid,
            "t": t,
            "it": it,
            "batch": b.batch_id,
            "member": lane,
            "slices": b.slices,
            "max_abs": float(np.max(np.abs(u))),
            "l2": float(np.sqrt(np.mean(u.astype(np.float64) ** 2))),
            "shape": list(u.shape),
            "seconds": seconds,
        }
        atomic_write_text(os.path.join(d, "result.json"),
                          json.dumps(summary, sort_keys=True, indent=1))
        verdict = {
            "status": "done", "seconds": seconds,
            "result": "result.json",
        }
        if self._fault_ack_before_fsync:
            # injected fault (serving_perf_gate --selftest): the ack
            # escapes while the done record is dropped on the floor —
            # the power-loss window the commit barrier exists to close.
            # Memory advances so the loop completes; replay must show
            # an acked-but-unjournaled request.
            self._write_verdict(rid, verdict)
            self.queue._apply_transition(
                rec, rec.state, "done",
                {"t": t, "it": it, "slices": b.slices},
            )
        elif self.journal.group_commit_s > 0.0:
            self._transition(rid, "done", t=t, it=it, slices=b.slices)
            self._ack(rid, verdict)  # released after the fsync barrier
        else:
            self._write_verdict(rid, verdict)
            self._transition(rid, "done", t=t, it=it, slices=b.slices)
        extra = ({"deadline_s": rec.spec.deadline_s}
                 if rec.spec.deadline_s is not None else {})
        self._sink.event("req", "done", job=rid,
                         seconds=seconds, slices=b.slices, **extra)
        self.metrics.counter("serve_requests_done_total").inc()
        if seconds is not None:
            self.metrics.histogram(
                "serve_request_latency_seconds"
            ).observe(seconds)
        self._hang_cohort.pop(rid, None)
        self._hang_strikes.pop(rid, None)
        self._observe_deadline(rec, seconds=seconds, ok=True)
        try:
            os.remove(self._ckpt_path(rid))
        except OSError:
            pass

    def _save_member_ckpt(self, rec: RequestRecord, st) -> None:
        from multigpu_advectiondiffusion_tpu.utils.io import (
            save_checkpoint,
        )

        d = self.request_dir(rec.request_id)
        os.makedirs(d, exist_ok=True)
        save_checkpoint(self._ckpt_path(rec.request_id), st)

    def _observe_batch_idle(self, b: _Batch) -> None:
        """Per-batch device-idle fraction (mechanics-grade: busy is
        measured dispatch -> first blocking pull, so host work hidden
        behind in-flight slices reads as overlap). Observed once, when
        the batch dissolves."""
        wall = time.monotonic() - b.t_formed
        if wall <= 0.0 or b.slices == 0:
            return
        idle = min(1.0, max(0.0, 1.0 - b.busy_s / wall))
        self.metrics.histogram("serve_device_idle_fraction").observe(
            idle
        )
        self._sink.event(
            "pipeline", "batch_idle", batch=b.batch_id,
            idle_fraction=round(idle, 4),
            busy_seconds=round(b.busy_s, 6),
            wall_seconds=round(wall, 6), slices=b.slices,
        )

    def _park(self, b: _Batch, reason: str, estate=None) -> None:
        """Dissolve the batch at a slice boundary: every unfinished
        member checkpoints and requeues (journaled), so the next
        formation — with joiners, without diverged lanes, or after the
        preempting key — resumes bit-exactly. ``estate`` overrides the
        checkpoint source (the donated/pipelined paths park from the
        newest live state — a later point on the same deterministic
        trajectory, so the resumed march is still bit-exact at te)."""
        est = b.estate if estate is None else estate
        for i, rec in enumerate(b.reqs):
            if rec is None or rec.state not in ("batched", "running"):
                continue
            self._save_member_ckpt(rec, est.member(i))
            self._transition(rec.request_id, "requeued", reason=reason,
                             checkpoint=self._ckpt_path(rec.request_id))
        b.inflight.clear()
        self._observe_batch_idle(b)
        self._batch = None

    # ------------------------------------------------------------------ #
    # Graceful drain (ISSUE 20)
    # ------------------------------------------------------------------ #
    def request_drain(self, reason: str = "signal") -> None:
        """Stop admission and hand over: live transports refuse with a
        structured draining verdict, the spool (a durable mailbox) is
        left untouched for the successor, and the in-flight batch parks
        at its next slice boundary. The loop then journals the
        ``shutdown clean=true`` marker and releases the lease."""
        if self.draining:
            return
        self.draining = True
        self._sink.event("drain", "start", reason=str(reason),
                         open=len(self.queue.open_requests()))
        self.journal.append("note", note="drain", reason=str(reason))
        if self.lease is not None:
            self.lease.heartbeat(draining=True, force=True)

    def _finish_drain(self) -> None:
        """The handover epilogue: every ack flushed behind its fsync,
        the clean-shutdown marker as the journal's LAST record, the
        lease released so the successor's acquire wins immediately."""
        self._flush_acks()
        self.journal.append("note", note="shutdown", clean=True,
                            pid=os.getpid())
        self.journal.commit()
        self._sink.event("drain", "done", clean=True,
                         open=len(self.queue.open_requests()))
        if self.lease is not None:
            self._sink.event("lease", "release", pid=os.getpid())
            self.lease.release()
            self.lease = None

    # ------------------------------------------------------------------ #
    # Hung-dispatch watchdog + deadline enforcement (ISSUE 20)
    # ------------------------------------------------------------------ #
    #: adaptive-budget floor: with millisecond slices, median × the
    #: multiplier is blown by any scheduling hiccup on a loaded host —
    #: a "hang" shorter than this is not worth an evacuation. An
    #: explicit ``hang_budget_s`` is exempt (tests pin tighter ones).
    HANG_BUDGET_FLOOR_S = 1.0

    def _slice_budget(self) -> Optional[float]:
        """Wall-clock budget for one slice: the rolling median of
        measured slice history × ``hang_multiplier`` (the bench outlier
        discipline — a hang is an outlier against what this server
        actually measured, not a hardcoded timeout). ``hang_budget_s``
        overrides; None until enough history exists."""
        if self.hang_budget_s is not None:
            return float(self.hang_budget_s)
        if len(self._slice_history) < self.hang_min_history:
            return None
        med = statistics.median(self._slice_history)
        return max(med * self.hang_multiplier,
                   self.HANG_BUDGET_FLOOR_S)

    def _handle_hung(self, b: _Batch, elapsed: float,
                     budget: float) -> None:
        """Budget blown: journal the hang, evacuate the batch from the
        last per-member slice checkpoints (the hung estate is suspect —
        members without a checkpoint resume from their ICs, bit-exact
        either way by slicing invariance), and bisect: the suspects
        split into two cohorts that re-batch separately, so repeated
        hangs converge on the poison member, which is quarantined with
        forensics once it hangs alone."""
        active = b.active()
        rids = [r.request_id for r in active]
        hung_slice = b.slices + 1
        self.journal.append(
            "note", note="dispatch_hung", batch=b.batch_id,
            slice=hung_slice, elapsed_s=round(elapsed, 6),
            budget_s=round(budget, 6), jobs=rids,
        )
        self._sink.event(
            "dispatch", "hung", batch=b.batch_id, slice=hung_slice,
            elapsed_s=round(elapsed, 6), budget_s=round(budget, 6),
            jobs=rids,
        )
        self.metrics.counter("serve_dispatch_hung_total").inc()
        for rec in active:
            self._hang_strikes[rec.request_id] = (
                self._hang_strikes.get(rec.request_id, 0) + 1
            )
        if len(active) == 1:
            rec = active[0]
            if self._hang_strikes.get(rec.request_id, 0) >= 2:
                # bisection converged (a member that hung in company
                # now hangs alone), or a solo batch hung twice:
                # quarantine with forensics
                self._fail(rec, reason="dispatch_hung", forensics={
                    "type": "DispatchHung",
                    "batch": b.batch_id,
                    "slice": hung_slice,
                    "elapsed_s": round(elapsed, 6),
                    "budget_s": round(budget, 6),
                    "strikes": self._hang_strikes.get(
                        rec.request_id, 1),
                    "quarantined": True,
                })
            else:
                # first strike for a solo batch: a transient stall (a
                # loaded host, a GC pause) gets one retry from its
                # checkpoint; a genuinely wedged member hangs again
                # and is quarantined on the repeat
                ckpt = self._ckpt_path(rec.request_id)
                self._transition(
                    rec.request_id, "requeued",
                    reason="dispatch_hung",
                    checkpoint=ckpt if os.path.exists(ckpt) else None,
                )
        else:
            half = (len(active) + 1) // 2
            for idx, rec in enumerate(active):
                self._hang_cohort[rec.request_id] = (
                    f"{b.batch_id}:{'a' if idx < half else 'b'}"
                )
            for rec in active:
                ckpt = self._ckpt_path(rec.request_id)
                self._transition(
                    rec.request_id, "requeued", reason="dispatch_hung",
                    checkpoint=ckpt if os.path.exists(ckpt) else None,
                )
        b.inflight.clear()
        self._observe_batch_idle(b)
        self._batch = None

    def _enforce_deadlines(self, b: _Batch, t_np, it_np) -> int:
        """Cancel past-deadline running members at the slice boundary:
        the member's lane freezes (te clamps to its current t), the
        request fails with partial-progress forensics, and the rest of
        the batch marches on unperturbed. Runs AFTER the finished scan,
        so a member that both finished and expired prefers done."""
        if self.best_effort:
            return 0
        now = time.time()
        cancelled = 0
        for i, rec in enumerate(b.reqs):
            if rec is None or rec.state != "running":
                continue
            if not rec.expired(now):
                continue
            elapsed = (now - rec.admitted_wall
                       if rec.admitted_wall else None)
            self._sink.event(
                "req", "deadline_cancel", job=rec.request_id,
                deadline_s=rec.spec.deadline_s,
                elapsed_s=(round(elapsed, 6)
                           if elapsed is not None else None),
            )
            self.metrics.counter(
                "serve_deadline_cancelled_total"
            ).inc()
            self._fail(rec, reason="deadline_exceeded", forensics={
                "type": "DeadlineExceeded",
                "deadline_s": rec.spec.deadline_s,
                "elapsed_s": elapsed,
                "admitted_wall": rec.admitted_wall,
                "t": float(t_np[i]),
                "it": int(it_np[i]),
                "slices": b.slices,
                "batch": b.batch_id,
                "member": i,
            })
            # freeze the lane: te <= t stops the engine marching it
            b.te[i] = float(t_np[i])
            cancelled += 1
        return cancelled

    # ------------------------------------------------------------------ #
    # The slice loop
    # ------------------------------------------------------------------ #
    def _fail_diverged(self, b: _Batch, err) -> List[str]:
        jobs = []
        for i in sorted(set(err.members)):
            rec = b.reqs[i] if i < len(b.reqs) else None
            if rec is None or rec.state not in ("batched", "running"):
                continue  # a clone lane diverged with its original
            jobs.append(rec.request_id)
            norm = err.member_norms[err.members.index(i)]
            self._fail(rec, reason=f"diverged: {err.reason}",
                       forensics={
                           "type": type(err).__name__,
                           "member": i,
                           "batch": b.batch_id,
                           "step": err.step,
                           "t": err.t,
                           "norm": norm,
                           "reason": err.reason,
                       })
        return jobs

    def _handle_divergence(self, b: _Batch, err, estate) -> None:
        from multigpu_advectiondiffusion_tpu.resilience.errors import (
            EnsembleMemberDivergedError,
        )

        assert isinstance(err, EnsembleMemberDivergedError)
        jobs = self._fail_diverged(b, err)
        self._sink.event("serve", "divergence", batch=b.batch_id,
                         jobs=jobs)
        # survivors re-batch from their PRE-slice state: the diverged
        # lanes polluted only themselves, but the pre-slice state is
        # the last one every survivor is known-healthy at. With the
        # state operand donated, the pre-slice buffer was consumed by
        # the dispatch — survivors park from the POST-slice state the
        # health check just proved them healthy at.
        self._park(b, reason="divergence_rebatch",
                   estate=estate if self.donate else None)

    def _joiners(self, b: _Batch) -> int:
        lead = next((r for r in b.reqs if r is not None), None)
        cohort = (self._hang_cohort.get(lead.request_id)
                  if lead is not None else None)
        return sum(
            1 for r in self.queue.batchable()
            if coalesce_key(r.spec) == b.key
            and self._hang_cohort.get(r.request_id) == cohort
        )

    def _preempting(self, b: _Batch) -> Optional[RequestRecord]:
        for r in self.queue.batchable():
            if coalesce_key(r.spec) != b.key and (
                r.spec.priority > b.priority
            ):
                return r
        return None

    def _start_batch(self, b: _Batch) -> None:
        if b.started:
            return
        for rec in b.reqs:
            if rec is not None and rec.state == "batched":
                self._transition(
                    rec.request_id, "running",
                    attempt=max(rec.attempts, 1),
                    batch=b.batch_id, slices=b.slices,
                )
        b.started = True

    def _tick_batch(self) -> bool:
        if self._batch is None:
            if self.draining:
                return False  # no new work during a drain
            self._batch = self._form_batch()
            if self._batch is None:
                return False
        if self.pipeline:
            return self._tick_batch_pipelined()
        return self._tick_batch_sync()

    def _tick_batch_sync(self) -> bool:
        b = self._batch
        self._start_batch(b)
        t0 = time.monotonic()
        estate = b.ens.advance_to(b.estate, list(b.te),
                                  max_steps=self.slice_steps,
                                  donate=self.donate)
        try:
            b.ens.check_health(estate, growth=self.growth)
        except Exception as err:  # EnsembleMemberDivergedError
            from multigpu_advectiondiffusion_tpu.resilience.errors import (
                EnsembleMemberDivergedError,
            )

            if isinstance(err, EnsembleMemberDivergedError):
                self._handle_divergence(b, err, estate)
                return True
            raise
        # the health probe synchronized on the slice: device busy ran
        # dispatch -> now (the synchronous path's whole-slice wait)
        ready = time.monotonic()
        b.busy_s += max(0.0, ready - max(t0, b.last_ready))
        b.last_ready = ready
        # hung-dispatch watchdog: the batch's first slice carries the
        # compile and is exempt (and unmeasured) — the PR 6 outlier
        # discipline applied to wall clocks
        elapsed = ready - t0
        if b.slices > 0:
            budget = self._slice_budget()
            if budget is not None and elapsed > budget:
                self._handle_hung(b, elapsed, budget)
                return True
            self._slice_history.append(elapsed)
        prev_it = b.prev_it
        b.estate = estate
        b.slices += 1
        b.prev_it = np.asarray(estate.it).copy()
        t_np = np.asarray(estate.t, dtype=np.float64)
        it_np = b.prev_it
        done = 0
        for i, rec in enumerate(b.reqs):
            if rec is None or rec.state != "running":
                continue
            te = b.te[i]
            finished = (
                t_np[i] >= te - _finish_eps(te)
                or int(it_np[i]) == int(prev_it[i])  # frozen lane
            )
            if finished:
                st = estate.member(i)
                self._finish(rec, b, i, np.asarray(st.u),
                             float(t_np[i]), int(it_np[i]))
                done += 1
            elif b.slices % self.checkpoint_every == 0:
                self._save_member_ckpt(rec, estate.member(i))
        self._enforce_deadlines(b, t_np, it_np)
        active = len(b.active())
        slice_seconds = round(time.monotonic() - t0, 6)
        occupancy = round(active / max(1, len(b.reqs)), 4)
        self._sink.event(
            "serve", "slice", batch=b.batch_id, slice=b.slices,
            active=active, done=done,
            occupancy=occupancy, seconds=slice_seconds,
        )
        self.metrics.counter("serve_slices_total").inc()
        self.metrics.histogram("serve_slice_seconds").observe(
            slice_seconds
        )
        self.metrics.histogram("serve_batch_occupancy").observe(
            occupancy
        )
        if self.ledger.lookup(b.key) is None:
            # first completed slice for this key: the executable exists
            # now — journal the warmth so a restarted server knows
            self.ledger.observe(b.key, compile_seconds=0.0)
            self.journal.append("note", note="warm", key=b.key)
        if active == 0:
            self._observe_batch_idle(b)
            self._batch = None
            return True
        pre = self._preempting(b)
        if pre is not None:
            self._sink.event(
                "serve", "preempt", batch=b.batch_id,
                for_job=pre.request_id, parked=active,
            )
            self._park(b, reason="preempted")
            return True
        joiners = self._joiners(b)
        if joiners and active < self.max_batch:
            self._sink.event("serve", "join", batch=b.batch_id,
                             waiting=joiners)
            self._park(b, reason="rebatch_join")
        return True

    # ------------------------------------------------------------------ #
    # The pipelined slice loop (ISSUE 19)
    # ------------------------------------------------------------------ #
    def _dispatch_slice(self, b: _Batch) -> None:
        """Enqueue one bounded slice — JAX async dispatch returns
        before the device finishes, so the caller's host work overlaps
        the march. With donation on, the previous estate's ``u`` is
        consumed by the dispatch; its (undonated) t/it scalars stay
        readable, which is all retirement needs. The health reduction
        launches here too, before the slice's own output buffer can be
        donated into the next slice."""
        prev = b.estate
        # stamp BEFORE the advance call: trace/compile time spent
        # inside the dispatch counts as busy, matching the synchronous
        # loop's dispatch->ready interval — otherwise a cold compile
        # reads as device idle in one mode and busy in the other
        dispatched = time.monotonic()
        estate = b.ens.advance_to(prev, list(b.te),
                                  max_steps=self.slice_steps,
                                  donate=self.donate)
        stats = b.ens.probe_launch(estate)
        slice_no = b.slices + len(b.inflight) + 1
        b.inflight.append({
            "estate": estate,
            "stats": stats,
            "prev_it": prev.it,
            "dispatched": dispatched,
            "slice_no": slice_no,
        })
        b.estate = estate
        self._sink.event("pipeline", "dispatch", batch=b.batch_id,
                         slice=slice_no, depth=len(b.inflight))
        self.metrics.counter("serve_pipeline_dispatches_total").inc()
        self.metrics.gauge("serve_pipeline_depth").set(
            len(b.inflight)
        )

    def _tick_batch_pipelined(self) -> bool:
        """The overlap-everything hot path: keep up to
        ``pipeline_depth`` slices in flight, then retire the OLDEST
        while the newer ones march on-device. Retirement's blocking
        pulls touch per-member scalars only (t/it/health stats); the
        one full-width transfer is a device-side gather of finished
        lanes whose async host copy is awaited at publish time. A
        finished lane's bits are identical in every later slice (the
        frozen-lane invariance the ensemble engine proves), so
        publishing from the newest estate is exact."""
        from multigpu_advectiondiffusion_tpu.resilience.errors import (
            EnsembleMemberDivergedError,
        )

        b = self._batch
        self._start_batch(b)
        # feed the device before any host work
        while len(b.inflight) < self.pipeline_depth:
            self._dispatch_slice(b)
        entry = b.inflight.pop(0)
        estate = entry["estate"]
        pull0 = time.monotonic()
        t_np = np.asarray(estate.t, dtype=np.float64)
        it_np = np.asarray(estate.it)
        prev_it = np.asarray(entry["prev_it"])
        try:
            b.ens.check_health_launched(
                entry["stats"], step=int(np.max(it_np)),
                t=float(np.max(t_np)), growth=self.growth,
            )
        except EnsembleMemberDivergedError as err:
            self._handle_divergence_pipelined(b, err)
            return True
        ready = time.monotonic()
        stall_s = ready - pull0
        b.busy_s += max(
            0.0, ready - max(entry["dispatched"], b.last_ready)
        )
        b.last_ready = ready
        # hung-dispatch watchdog (pipelined): elapsed is dispatch ->
        # retirement of THIS slice; slice 1 carries the compile and is
        # exempt, like the synchronous path
        elapsed = ready - entry["dispatched"]
        if entry["slice_no"] > 1:
            budget = self._slice_budget()
            if budget is not None and elapsed > budget:
                self._handle_hung(b, elapsed, budget)
                return True
            self._slice_history.append(elapsed)
        b.slices += 1
        b.prev_it = it_np.copy()
        finished = []
        for i, rec in enumerate(b.reqs):
            if rec is None or rec.state != "running":
                continue
            te = b.te[i]
            if (
                t_np[i] >= te - _finish_eps(te)
                or int(it_np[i]) == int(prev_it[i])  # frozen lane
            ):
                finished.append(i)
        host0 = time.monotonic()
        gathered = None
        if finished:
            import jax.numpy as jnp

            # device-side gather of finished members ONLY — the
            # (B,*grid) blocking device_get this path replaces
            gathered = jnp.take(b.estate.u, np.asarray(finished),
                                axis=0)
            start_copy = getattr(gathered, "copy_to_host_async", None)
            if start_copy is not None:
                try:
                    start_copy()
                except Exception:  # noqa: BLE001 — copy still awaited
                    pass
        done = 0
        publish_wait = 0.0
        if gathered is not None:
            w0 = time.monotonic()
            u_host = np.asarray(gathered)  # awaited at publish time
            publish_wait = time.monotonic() - w0
            stall_s += publish_wait
            for j, i in enumerate(finished):
                self._finish(b.reqs[i], b, i, u_host[j],
                             float(t_np[i]), int(it_np[i]))
                done += 1
            self._sink.event(
                "pipeline", "publish", batch=b.batch_id,
                slice=b.slices, lanes=len(finished),
                wait_seconds=round(publish_wait, 6),
            )
        self._enforce_deadlines(b, t_np, it_np)
        active = len(b.active())
        if (
            active > 0
            and b.slices % self.checkpoint_every == 0
        ):
            c0 = time.monotonic()
            for i, rec in enumerate(b.reqs):
                if rec is None or rec.state != "running":
                    continue
                # newest estate: a later point on the same trajectory,
                # bit-exact to resume from (slicing invariance)
                self._save_member_ckpt(rec, b.estate.member(i))
            ckpt_wait = time.monotonic() - c0
            stall_s += ckpt_wait
            self._sink.event("pipeline", "stall", batch=b.batch_id,
                             where="checkpoint",
                             seconds=round(ckpt_wait, 6))
        host_s = max(0.0, time.monotonic() - pull0 - stall_s)
        overlap = (
            host_s / (host_s + stall_s)
            if b.inflight and (host_s + stall_s) > 0 else 0.0
        )
        occupancy = round(active / max(1, len(b.reqs)), 4)
        self._sink.event(
            "serve", "slice", batch=b.batch_id, slice=b.slices,
            active=active, done=done, occupancy=occupancy,
            seconds=round(ready - entry["dispatched"], 6),
            stall_seconds=round(stall_s, 6),
            overlap_fraction=round(overlap, 4),
            depth=len(b.inflight),
        )
        self.metrics.counter("serve_slices_total").inc()
        self.metrics.histogram("serve_slice_seconds").observe(
            round(ready - entry["dispatched"], 6)
        )
        self.metrics.histogram("serve_batch_occupancy").observe(
            occupancy
        )
        self.metrics.histogram("serve_pipeline_stall_seconds").observe(
            stall_s
        )
        self.metrics.histogram(
            "serve_pipeline_overlap_fraction"
        ).observe(overlap)
        if self.ledger.lookup(b.key) is None:
            self.ledger.observe(b.key, compile_seconds=0.0)
            self.journal.append("note", note="warm", key=b.key)
        if active == 0:
            b.inflight.clear()
            self._observe_batch_idle(b)
            self._batch = None
            return True
        pre = self._preempting(b)
        if pre is not None:
            self._sink.event(
                "serve", "preempt", batch=b.batch_id,
                for_job=pre.request_id, parked=active,
            )
            self._park(b, reason="preempted")
            return True
        joiners = self._joiners(b)
        if joiners and active < self.max_batch:
            self._sink.event("serve", "join", batch=b.batch_id,
                             waiting=joiners)
            self._park(b, reason="rebatch_join")
        return True

    def _handle_divergence_pipelined(self, b: _Batch, err) -> None:
        from multigpu_advectiondiffusion_tpu.resilience.errors import (
            EnsembleMemberDivergedError,
        )

        jobs = self._fail_diverged(b, err)
        # the pipeline ran ahead of the verdict: re-judge the NEWEST
        # estate so a survivor that diverged inside the lookahead
        # fails now, instead of poisoning the re-formed batch's arm()
        try:
            b.ens.check_health(b.estate, growth=self.growth)
        except EnsembleMemberDivergedError as err2:
            jobs += self._fail_diverged(b, err2)
        self._sink.event("serve", "divergence", batch=b.batch_id,
                         jobs=jobs)
        # survivors park from the newest estate — the only one whose
        # ``u`` is live under donation, and just proven healthy
        self._park(b, reason="divergence_rebatch")

    def _maybe_prewarm(self) -> None:
        """Speculative AOT prewarm (ISSUE 19 layer 4): while the live
        batch marches on-device, deserialize — never compile — the
        warm-ledger executable for the most likely next coalesce key,
        so a key change at the next formation costs a load instead of
        a compile stall. One attempt per key per incarnation."""
        if not self.prewarm_enabled or self._batch is None:
            return
        from multigpu_advectiondiffusion_tpu.tuning import aot_cache

        if not aot_cache.enabled():
            return
        b = self._batch
        lead = None
        key = None
        for r in self.queue.batchable():
            k = coalesce_key(r.spec)
            if k == b.key or k in self._prewarmed:
                continue
            if self.ledger.lookup(k) is None:
                continue  # cold key: prewarm never compiles
            lead, key = r, k
            break
        if lead is None:
            return
        self._prewarmed.add(key)
        t0 = time.monotonic()
        try:
            tpl = self._template(lead.spec)
            group = [x for x in self.queue.batchable()
                     if coalesce_key(x.spec) == key]
            group = group[:self._batch_cap(lead.spec)]
            overrides = [
                self._member_overrides(x.spec) for x in group
            ]
            from multigpu_advectiondiffusion_tpu.models.ensemble import (
                EnsembleSolver,
            )
            from multigpu_advectiondiffusion_tpu.parallel.mesh import (
                member_extent,
            )

            pad = (-len(overrides)) % member_extent(tpl["mesh"])
            overrides += [dict(overrides[0]) for _ in range(pad)]
            # construction never compiles; prewarm only deserializes
            ens = EnsembleSolver(
                tpl["family"].solver_cls, tpl["cfg"], overrides,
                mesh=tpl["mesh"], decomp=tpl["decomp"],
            )
            status = ens.prewarm(max_steps=self.slice_steps,
                                 donate=self.donate)
        except Exception as err:  # noqa: BLE001 — prewarm never kills
            status = f"error: {type(err).__name__}: {err}"[:200]
        self._sink.event(
            "pipeline", "prewarm", key=key, status=str(status),
            seconds=round(time.monotonic() - t0, 6),
        )
        self.metrics.counter("serve_prewarm_total").inc()
        if status == "hit":
            self.metrics.counter("serve_prewarm_hits_total").inc()

    # ------------------------------------------------------------------ #
    # The loop
    # ------------------------------------------------------------------ #
    def tick(self) -> dict:
        self.recover()
        if self._drain_requested and not self.draining:
            self.request_drain(self._drain_requested)
        self._ingest()
        progressed = self._tick_batch()
        if self.draining and self._batch is not None:
            # park at this slice boundary: members checkpoint and
            # requeue, so the successor resumes them with zero
            # crash-recovery work
            b = self._batch
            parked = len(b.active())
            self._park(b, reason="drain")
            self._sink.event("drain", "parked", batch=b.batch_id,
                             members=parked)
            self.metrics.counter("serve_drain_parked_total").inc()
        # host-side work that overlaps the in-flight slices: prewarm
        # the likely next executable, then the group-commit barrier
        # that releases this tick's acks
        self._maybe_prewarm()
        self._flush_acks()
        if self.lease is not None:
            self.lease.heartbeat(draining=self.draining)
        open_count = len(self.queue.open_requests())
        self.metrics.gauge("serve_queue_depth").set(open_count)
        self.slo.evaluate()  # time alone can clear (or breach) windows
        self.export_metrics(force=False)
        return {
            "progressed": progressed,
            "open": open_count,
        }

    def state_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for rec in self.queue.requests.values():
            counts[rec.state] = counts.get(rec.state, 0) + 1
        return counts

    def serve(self, until_idle: bool = True,
              max_seconds: Optional[float] = None,
              max_ticks: Optional[int] = None,
              poll_seconds: float = 0.05) -> dict:
        """The serving loop. ``until_idle`` returns once every request
        is terminal; otherwise serve runs until a signal kills the
        process — the journal makes that safe at any instant."""
        self.recover()
        self._sink.event(
            "serve", "start", root=self.root,
            max_batch=self.max_batch, slice_steps=self.slice_steps,
            queue_bound=self.queue_bound,
            pipeline=self.pipeline, pipeline_depth=self.pipeline_depth,
            donate=self.donate,
            group_commit_s=self.journal.group_commit_s,
        )
        # SIGTERM/SIGINT ask for a graceful drain; the handler only
        # sets a flag (journal appends from a handler frame could
        # interleave with one already on the stack)
        import signal as _signal

        def _on_signal(signum, frame):  # noqa: ARG001
            self._drain_requested = f"signal {signum}"

        prev_handlers = {}
        try:
            for sig in (_signal.SIGTERM, _signal.SIGINT):
                prev_handlers[sig] = _signal.signal(sig, _on_signal)
        except ValueError:  # not the main thread: signals stay global
            prev_handlers = {}
        t0 = time.monotonic()
        ticks = 0
        reason = "idle"
        try:
            while True:
                out = self.tick()
                ticks += 1
                if not out["progressed"]:
                    self._stalled_ticks += 1
                else:
                    self._stalled_ticks = 0
                if self.draining and self._batch is None:
                    reason = "drained"
                    break
                if max_ticks is not None and ticks >= max_ticks:
                    reason = "ticks"
                    break
                if max_seconds is not None and (
                    time.monotonic() - t0 > max_seconds
                ):
                    reason = "timeout"
                    break
                if until_idle:
                    if out["open"] == 0 and self._batch is None:
                        reason = "idle"
                        break
                    if self._stalled_ticks > 50 and self._batch is None:
                        # open requests nothing can batch (e.g.
                        # everything deferred) — refuse to spin forever
                        reason = "stalled"
                        break
                if not out["progressed"]:
                    time.sleep(poll_seconds)
        finally:
            for sig, h in prev_handlers.items():
                try:
                    _signal.signal(sig, h)
                except (ValueError, TypeError):
                    pass
        if reason == "drained":
            self._finish_drain()
        outcome = {"reason": reason, "states": self.state_counts()}
        self._sink.event("serve", "stop", reason=reason,
                         states=outcome["states"])
        self.export_metrics(force=True)
        return outcome

    def close(self) -> None:
        self._flush_acks()
        self.export_metrics(force=True)
        if self._ingest_http is not None:
            try:
                self._ingest_http.shutdown()
                self._ingest_http.server_close()
            except OSError:
                pass
            self._ingest_http = None
        if self._http is not None:
            try:
                self._http.shutdown()
                self._http.server_close()
            except OSError:
                pass
            self._http = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self.journal.close()
        if self.lease is not None:
            self._sink.event("lease", "release", pid=os.getpid())
            self.lease.release()
            self.lease = None
        close = getattr(self._sink, "close", None)
        if callable(close):
            close()
