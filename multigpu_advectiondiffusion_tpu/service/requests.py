"""Scenario requests: specs, the request-lifecycle state machine, and
the journal-backed request queue the serving daemon recovers from.

PR 14's scheduler multiplexes *jobs* (one subprocess per run); this
layer multiplexes *requests* — many users asking for solves that are
compatible enough to share ONE batched ensemble dispatch
(``service/server.py``). The shapes deliberately mirror
``service/queue.py``:

* a :class:`RequestSpec` JSON round-trips through the same atomic
  spool mailbox (tmp + ``os.replace``; two processes never append to
  one journal);
* every lifecycle transition is a CRC-sealed record in the PR 14
  write-ahead journal *before* the in-memory queue mutates, so a
  SIGKILLed server replays to exactly what it knew;
* ``verify_records`` (``service/journal.py``) checks the request
  journal against THIS module's transition table and terminal states —
  one verifier, two state machines.

The request lifecycle::

    received -> admitted -> batched -> running -> done | failed
        |           |          |          \\
        v           v          v           v
      shed        failed    requeued <-- (preemption / crash recovery)
    (backpressure)             |
                               v
                           batched | failed

``shed`` is the backpressure verdict (bounded queue: overload is a
policy outcome with a retry-after hint, never an OOM); ``requeued``
carries the slice checkpoint a recovering batch resumes from.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
import uuid
from typing import Dict, List, Optional, Tuple

from multigpu_advectiondiffusion_tpu.service.journal import Journal

#: request lifecycle states (ISSUE 17)
REQUEST_STATES = (
    "received", "admitted", "batched", "running", "requeued",
    "done", "failed", "shed",
)
REQUEST_TERMINAL_STATES = frozenset({"done", "failed", "shed"})

#: legal (from, to) pairs — ``verify_records(...,
#: allowed_transitions=ALLOWED_REQUEST_TRANSITIONS,
#: terminal_states=REQUEST_TERMINAL_STATES, initial_state='received')``
#: holds the serving journal to this table
ALLOWED_REQUEST_TRANSITIONS = frozenset({
    ("received", "admitted"),
    ("received", "shed"),            # backpressure: bounded queue
    ("admitted", "batched"),
    ("admitted", "failed"),          # per-request validation failure
    ("batched", "running"),
    ("batched", "requeued"),         # crash recovery: never marched
    ("running", "done"),
    ("running", "failed"),           # divergence forensics / deadline
    ("running", "requeued"),         # preemption / crash recovery
    ("requeued", "batched"),
    ("requeued", "failed"),          # retries exhausted
})

_DTYPES = ("float32", "float64", "bfloat16")
_PRECISIONS = ("native", "bf16")


@dataclasses.dataclass
class RequestSpec:
    """One scenario request: the physics a user wants solved, plus the
    SLO metadata the server schedules it by. JSON round-trips for the
    spool and the journal.

    ``model``/``n``/``lengths``/``dtype``/``precision``/``impl``/
    ``mesh`` form the *coalesce key* (:func:`coalesce_key`): requests
    agreeing on all of them compile to the SAME batched executable and
    may share one ensemble dispatch. ``operands`` (member-varying
    scalars, e.g. diffusivity), ``ic``/``ic_params``/``t0`` and
    ``t_end`` vary freely *within* a batch — they ride the member
    axis."""

    request_id: str
    model: str                       # registry family name
    n: List[int] = dataclasses.field(default_factory=lambda: [32, 32])
    lengths: List[float] = dataclasses.field(default_factory=list)
    t_end: float = 0.2
    dtype: str = "float32"
    precision: str = "native"
    impl: str = "xla"
    #: serving-mesh constraint token ("" = whatever the server runs);
    #: a non-empty value must match the server's --mesh spec verbatim
    mesh: str = ""
    #: member-varying scalar overrides (names from the family's
    #: ``ensemble_operands()``), e.g. ``{"diffusivity": 0.5}``
    operands: Dict[str, float] = dataclasses.field(default_factory=dict)
    ic: Optional[str] = None
    ic_params: Dict[str, float] = dataclasses.field(default_factory=dict)
    t0: Optional[float] = None
    priority: int = 0
    #: SLO: seconds from admission before the deadline triggers the
    #: priority-preemption path (None = best effort)
    deadline_s: Optional[float] = None
    max_retries: int = 1

    def validate(self) -> None:
        """Structural validation only — cheap enough for ingest and
        replay (no model/registry import). Model resolution and operand
        names are checked at batch-formation time, where a bad request
        fails ALONE (``admitted -> failed``), never the daemon."""
        if (not self.request_id or "/" in self.request_id
                or ".." in self.request_id):
            raise ValueError(f"bad request id {self.request_id!r}")
        if not self.model or not isinstance(self.model, str):
            raise ValueError(f"{self.request_id}: empty model name")
        if not (isinstance(self.n, (list, tuple)) and
                1 <= len(self.n) <= 3 and
                all(isinstance(v, int) and v >= 2 for v in self.n)):
            raise ValueError(
                f"{self.request_id}: n must be 1-3 ints >= 2, got "
                f"{self.n!r}"
            )
        if self.lengths and len(self.lengths) != len(self.n):
            raise ValueError(
                f"{self.request_id}: {len(self.lengths)} lengths for "
                f"{len(self.n)} grid axes"
            )
        te = float(self.t_end)
        if not (te == te and abs(te) != float("inf")):
            raise ValueError(f"{self.request_id}: non-finite t_end")
        if self.dtype not in _DTYPES:
            raise ValueError(
                f"{self.request_id}: dtype {self.dtype!r} not in "
                f"{_DTYPES}"
            )
        if self.precision not in _PRECISIONS:
            raise ValueError(
                f"{self.request_id}: precision {self.precision!r} not "
                f"in {_PRECISIONS}"
            )
        if not isinstance(self.operands, dict) or not all(
            isinstance(k, str) for k in self.operands
        ):
            raise ValueError(
                f"{self.request_id}: operands must map names to scalars"
            )
        if self.deadline_s is not None and float(self.deadline_s) <= 0:
            raise ValueError(
                f"{self.request_id}: deadline_s must be positive"
            )
        if int(self.max_retries) < 0:
            raise ValueError(
                f"{self.request_id}: max_retries must be >= 0"
            )

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, data: dict) -> "RequestSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


def new_request_id() -> str:
    return f"req-{int(time.time())}-{uuid.uuid4().hex[:6]}"


def coalesce_key(spec: RequestSpec) -> str:
    """The batching compatibility token: requests with equal keys
    compile to the same batched executable (same family, grid, dtype,
    precision rung, impl rung, mesh) and may fold onto one ensemble
    member axis. Everything member-varying (operands, ICs, horizons)
    is deliberately absent."""
    return "|".join([
        spec.model,
        "x".join(str(int(v)) for v in spec.n),
        ",".join(f"{float(v):g}" for v in (spec.lengths or [])),
        spec.dtype,
        spec.precision,
        spec.impl,
        spec.mesh or "",
    ])


@dataclasses.dataclass
class RequestRecord:
    """In-memory view of one request, rebuilt from the journal."""

    spec: RequestSpec
    state: str = "received"
    order: int = 0            # FIFO tiebreak within a priority band
    attempts: int = 0
    slices: int = 0           # bounded advance slices marched so far
    batch: Optional[str] = None
    member: Optional[int] = None   # member lane in its current batch
    t: Optional[float] = None      # last journaled solve time
    it: int = 0
    checkpoint: Optional[str] = None  # slice checkpoint a resume loads
    admitted_wall: Optional[float] = None
    failures: List[dict] = dataclasses.field(default_factory=list)

    @property
    def request_id(self) -> str:
        return self.spec.request_id

    def sort_key(self) -> tuple:
        """Deadline-aware priority order: higher priority first, then
        the earliest absolute deadline, then FIFO."""
        deadline = float("inf")
        if self.spec.deadline_s is not None:
            base = self.admitted_wall or 0.0
            deadline = base + float(self.spec.deadline_s)
        return (-self.spec.priority, deadline, self.order)

    def deadline_wall(self) -> Optional[float]:
        if self.spec.deadline_s is None or self.admitted_wall is None:
            return None
        return self.admitted_wall + float(self.spec.deadline_s)

    def expired(self, now: Optional[float] = None) -> bool:
        """True when the request's deadline has passed — the server's
        slice-boundary cancellation predicate (ISSUE 20). Requests
        without a deadline never expire."""
        wall = self.deadline_wall()
        if wall is None:
            return False
        return (time.time() if now is None else float(now)) > wall


class RequestQueue:
    """The journal-backed request queue: every mutation journals first
    (``service/journal.py`` record vocabulary — ``submit``/``state``
    with the request id in the ``job`` field, so ``verify_records``
    and ``tpucfd-trace`` read both journals with one parser)."""

    def __init__(self, journal: Journal):
        self.journal = journal
        self.requests: Dict[str, RequestRecord] = {}
        self._order = 0

    # ------------------------------------------------------------------ #
    def submit(self, spec: RequestSpec) -> RequestRecord:
        spec.validate()
        if spec.request_id in self.requests:
            raise ValueError(
                f"request id {spec.request_id!r} already submitted"
            )
        self.journal.append("submit", job=spec.request_id,
                            spec=spec.to_json())
        return self._apply_submit(spec)

    def _apply_submit(self, spec: RequestSpec) -> RequestRecord:
        self._order += 1
        rec = RequestRecord(spec=spec, order=self._order)
        self.requests[spec.request_id] = rec
        return rec

    def transition(self, request_id: str, to: str,
                   **info) -> RequestRecord:
        rec = self.requests[request_id]
        frm = rec.state
        if (frm, to) not in ALLOWED_REQUEST_TRANSITIONS:
            raise ValueError(
                f"illegal request transition {frm!r} -> {to!r} for "
                f"{request_id}"
            )
        self.journal.append("state", job=request_id,
                            **{"from": frm, "to": to}, **info)
        self._apply_transition(rec, frm, to, info)
        return rec

    def _apply_transition(self, rec: RequestRecord, frm: str, to: str,
                          info: dict) -> None:
        rec.state = to
        if "batch" in info:
            rec.batch = info["batch"]
        if "member" in info:
            rec.member = (None if info["member"] is None
                          else int(info["member"]))
        if "t" in info and info["t"] is not None:
            rec.t = float(info["t"])
        if "it" in info and info["it"] is not None:
            rec.it = int(info["it"])
        if "checkpoint" in info:
            rec.checkpoint = info["checkpoint"]
        if "attempt" in info:
            rec.attempts = max(rec.attempts, int(info["attempt"]))
        if "slices" in info and info["slices"] is not None:
            rec.slices = int(info["slices"])
        if "failure" in info and isinstance(info["failure"], dict):
            rec.failures.append(info["failure"])
        if to == "admitted" and rec.admitted_wall is None:
            rec.admitted_wall = float(
                info.get("wall") or time.time()
            )
        if to == "requeued":
            rec.batch = None
            rec.member = None

    # ------------------------------------------------------------------ #
    def batchable(self) -> List[RequestRecord]:
        """Requests waiting for a batch slot (admitted or requeued),
        deadline-aware priority order."""
        return sorted(
            (r for r in self.requests.values()
             if r.state in ("admitted", "requeued")),
            key=RequestRecord.sort_key,
        )

    def in_flight(self) -> List[RequestRecord]:
        return [r for r in self.requests.values()
                if r.state in ("batched", "running")]

    def open_requests(self) -> List[RequestRecord]:
        return [r for r in self.requests.values()
                if r.state not in REQUEST_TERMINAL_STATES]

    # ------------------------------------------------------------------ #
    @classmethod
    def replay(cls, journal: Journal) -> Tuple["RequestQueue", dict]:
        """Rebuild a queue from ``journal.path`` — illegal records are
        skipped (and reported) rather than fatal, the JobQueue.replay
        discipline."""
        records, torn = Journal.replay(journal.path)
        q = cls(journal)
        problems: List[str] = []
        for rec in records:
            rtype, rid = rec.get("type"), rec.get("job")
            if rtype == "submit":
                try:
                    spec = RequestSpec.from_json(rec.get("spec") or {})
                    spec.validate()
                except (TypeError, ValueError) as err:
                    problems.append(
                        f"seq {rec.get('seq')}: bad spec: {err}"
                    )
                    continue
                if spec.request_id in q.requests:
                    problems.append(
                        f"seq {rec.get('seq')}: duplicate submit {rid!r}"
                    )
                    continue
                q._apply_submit(spec)
            elif rtype == "state":
                r = q.requests.get(rid)
                if r is None:
                    problems.append(
                        f"seq {rec.get('seq')}: state for unknown {rid!r}"
                    )
                    continue
                frm, to = rec.get("from"), rec.get("to")
                if (frm != r.state
                        or (frm, to) not in ALLOWED_REQUEST_TRANSITIONS):
                    problems.append(
                        f"seq {rec.get('seq')}: skipping illegal "
                        f"{frm!r}->{to!r} for {rid!r} "
                        f"(state {r.state!r})"
                    )
                    continue
                # the journal envelope's "wall" rides into the info
                # dict, so replay restores the ORIGINAL admission wall
                # clock and deadlines survive a restart
                q._apply_transition(r, frm, to, rec)
        report = {
            "records": len(records),
            "torn_lines": torn,
            "problems": problems,
            "requests": len(q.requests),
        }
        return q, report


# --------------------------------------------------------------------- #
# Spool: the multi-writer-safe submission mailbox (queue.py discipline)
# --------------------------------------------------------------------- #
def request_spool_dir(root: str) -> str:
    return os.path.join(root, "spool")


def request_dir(root: str, request_id: str) -> str:
    """Per-request artifact directory (verdict/result/checkpoint)."""
    return os.path.join(root, "requests", request_id)


def submit_request_to_spool(root: str, spec: RequestSpec) -> str:
    """Atomically park ``spec`` for the serving daemon (tmp + rename).
    Usable while no server runs — requests wait until one ingests
    them."""
    spec.validate()
    d = request_spool_dir(root)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{spec.request_id}.json")
    if os.path.exists(path):
        raise ValueError(
            f"request id {spec.request_id!r} already spooled"
        )
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".req_", suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(spec.to_json(), f, indent=1)
    os.replace(tmp, path)
    return path


def ingest_request_spool(root: str, queue: RequestQueue,
                         on_skip=None) -> List[RequestRecord]:
    """Move every parked request into the journal-backed queue.
    Dedupe-by-id across restarts (a server that died between journaling
    and unlinking drops the spool file on the next pass); torn/corrupt
    spool JSON is quarantined as ``<name>.bad`` with a named journal
    ``note`` record and an optional ``on_skip(name, reason)`` callback
    — the hardened ``service/queue.ingest_spool`` discipline."""
    d = request_spool_dir(root)
    if not os.path.isdir(d):
        return []
    ingested: List[RequestRecord] = []
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(d, name)
        try:
            with open(path) as f:
                payload = json.load(f)
            if not isinstance(payload, dict):
                raise ValueError(
                    f"request payload is {type(payload).__name__}, "
                    "not dict"
                )
            spec = RequestSpec.from_json(payload)
            spec.validate()
        except (ValueError, TypeError, KeyError, OSError) as err:
            reason = f"{type(err).__name__}: {err}"[:200]
            try:
                os.replace(path, path + ".bad")
            except OSError:
                pass
            queue.journal.append("note", note="spool_skip",
                                 file=name, error=reason)
            if on_skip is not None:
                on_skip(name, reason)
            continue
        if spec.request_id not in queue.requests:
            ingested.append(queue.submit(spec))
        os.remove(path)
    return ingested
