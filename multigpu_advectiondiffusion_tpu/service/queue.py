"""Job specs, the per-job state machine, and the journal-backed queue.

States follow the lifecycle the scheduler journals::

    queued -> admitted -> running -> checkpointed -> done | failed
                 |            \\--------/    |
                 \\<--------- (requeue: preempted / retry / recovery)

Every transition is appended to the write-ahead journal *before* the
queue's in-memory state changes (``service/journal.py``), so a replay
reconstructs exactly what the dead scheduler knew. Requeues carry their
reason (preemption, a classified failure with its retry policy, or
crash recovery) in the journal payload — the per-job failure ledger is
rebuilt from those records, not from a second source of truth.

Submission also works while no daemon runs: ``submit_to_spool`` parks
an atomic spec file under ``<root>/spool/`` and the daemon ingests it
into the journal on its next pass (the crash-safe mailbox — two
processes never append to one journal).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
import uuid
from typing import Dict, List, Optional, Tuple

from multigpu_advectiondiffusion_tpu.service.journal import Journal

#: lifecycle states (ISSUE 14); ``preempted`` is transient — the
#: scheduler requeues a preempted job in the same pass
STATES = (
    "queued", "admitted", "running", "checkpointed", "preempted",
    "done", "failed",
)
TERMINAL_STATES = frozenset({"done", "failed"})

#: legal (from, to) pairs — ``verify_records`` holds the journal to
#: this table, so a buggy scheduler write trips the gate
ALLOWED_TRANSITIONS = frozenset({
    ("queued", "admitted"),
    ("admitted", "running"),
    ("admitted", "queued"),          # recovery: admitted but never ran
    ("running", "checkpointed"),
    ("running", "preempted"),
    ("running", "done"),
    ("running", "failed"),
    ("running", "queued"),           # retry / crash recovery
    ("checkpointed", "preempted"),
    ("checkpointed", "done"),
    ("checkpointed", "failed"),
    ("checkpointed", "queued"),      # retry / crash recovery
    ("preempted", "queued"),         # requeue for elastic resume
})

#: flags the scheduler owns — a spec carrying one would fight the
#: per-job namespacing (``--save``), the journal (``--resume``) or the
#: daemon's device accounting (``--mesh``)
_FORBIDDEN_FLAGS = (
    "--save", "--metrics", "--resume", "--coordinator",
    "--num-processes", "--process-id", "--aot-cache", "--mesh",
    "--dt-scale",
)


@dataclasses.dataclass
class JobSpec:
    """One run request: the CLI argv (model + physics/supervision
    flags) plus scheduling metadata. JSON round-trips for the spool
    and the journal."""

    job_id: str
    argv: List[str]
    priority: int = 0
    max_retries: int = 2
    #: device request (0/1 = unsharded); the scheduler grants the
    #: largest divisor of this that fits the free slice — the elastic
    #: "whatever mesh slice frees up" rule
    devices: int = 0
    #: mesh spec template formatted with the *granted* device count
    mesh_template: str = "dz={devices}"
    env: Dict[str, str] = dataclasses.field(default_factory=dict)

    def validate(self) -> None:
        if not self.job_id or "/" in self.job_id or ".." in self.job_id:
            raise ValueError(f"bad job id {self.job_id!r}")
        if not self.argv:
            raise ValueError("empty job argv")
        bad = sorted(
            {f for f in _FORBIDDEN_FLAGS
             for a in self.argv if a == f or a.startswith(f + "=")}
        )
        if bad:
            raise ValueError(
                f"job {self.job_id}: {bad} are scheduler-owned flags — "
                "the daemon assigns per-job directories, telemetry "
                "sinks, resume sources, meshes and inherited dt scales "
                "itself"
            )
        if self.devices and self.devices < 0:
            raise ValueError("devices request must be >= 0")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, data: dict) -> "JobSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


def new_job_id() -> str:
    return f"job-{int(time.time())}-{uuid.uuid4().hex[:6]}"


@dataclasses.dataclass
class JobRecord:
    """In-memory view of one job, rebuilt from the journal on replay."""

    spec: JobSpec
    state: str = "queued"
    order: int = 0            # FIFO tiebreak within a priority band
    attempts: int = 0
    pid: Optional[int] = None
    granted_devices: int = 0
    #: inherited dt backoff across attempts (``--dt-scale``): a
    #: diverged attempt multiplies it by the spec's --dt-backoff
    dt_scale: float = 1.0
    #: failure ledger: one entry per failed attempt — rc, policy,
    #: reason, wall (rebuilt from requeue/failed journal payloads)
    failures: List[dict] = dataclasses.field(default_factory=list)
    preempt_requested: bool = False
    warm: bool = False

    @property
    def job_id(self) -> str:
        return self.spec.job_id

    def sort_key(self) -> tuple:
        return (-self.spec.priority, self.order)


class JobQueue:
    """The journal-backed queue: every mutation journals first."""

    def __init__(self, journal: Journal):
        self.journal = journal
        self.jobs: Dict[str, JobRecord] = {}
        self._order = 0

    # ------------------------------------------------------------------ #
    def submit(self, spec: JobSpec) -> JobRecord:
        spec.validate()
        if spec.job_id in self.jobs:
            raise ValueError(f"job id {spec.job_id!r} already submitted")
        self.journal.append("submit", job=spec.job_id,
                            spec=spec.to_json())
        return self._apply_submit(spec)

    def _apply_submit(self, spec: JobSpec) -> JobRecord:
        self._order += 1
        rec = JobRecord(spec=spec, order=self._order)
        self.jobs[spec.job_id] = rec
        return rec

    def transition(self, job_id: str, to: str, **info) -> JobRecord:
        rec = self.jobs[job_id]
        frm = rec.state
        if (frm, to) not in ALLOWED_TRANSITIONS:
            raise ValueError(
                f"illegal transition {frm!r} -> {to!r} for {job_id}"
            )
        self.journal.append("state", job=job_id,
                            **{"from": frm, "to": to}, **info)
        self._apply_transition(rec, frm, to, info)
        return rec

    def _apply_transition(self, rec: JobRecord, frm: str, to: str,
                          info: dict) -> None:
        rec.state = to
        if "pid" in info:
            rec.pid = info["pid"]
        if "attempt" in info:
            rec.attempts = max(rec.attempts, int(info["attempt"]))
        if "granted_devices" in info:
            rec.granted_devices = int(info["granted_devices"])
        if "dt_scale" in info:
            rec.dt_scale = float(info["dt_scale"])
        if "failure" in info and isinstance(info["failure"], dict):
            rec.failures.append(info["failure"])
        if to == "queued":
            rec.pid = None
            rec.preempt_requested = False
            rec.granted_devices = 0  # the reservation frees with the slot

    # ------------------------------------------------------------------ #
    def runnable(self) -> List[JobRecord]:
        """Queued jobs, highest priority first, FIFO within a band."""
        return sorted(
            (r for r in self.jobs.values() if r.state == "queued"),
            key=JobRecord.sort_key,
        )

    def in_flight(self) -> List[JobRecord]:
        return [r for r in self.jobs.values()
                if r.state in ("admitted", "running", "checkpointed")]

    def open_jobs(self) -> List[JobRecord]:
        return [r for r in self.jobs.values()
                if r.state not in TERMINAL_STATES]

    # ------------------------------------------------------------------ #
    @classmethod
    def replay(cls, journal: Journal) -> Tuple["JobQueue", dict]:
        """Rebuild a queue from ``journal.path``. Illegal records are
        skipped (and reported) rather than fatal — a half-written
        journal must still yield the best-effort queue a recovering
        daemon can act on."""
        records, torn = Journal.replay(journal.path)
        q = cls(journal)
        problems: List[str] = []
        for rec in records:
            rtype, job = rec.get("type"), rec.get("job")
            if rtype == "submit":
                try:
                    spec = JobSpec.from_json(rec.get("spec") or {})
                    spec.validate()
                except (TypeError, ValueError) as err:
                    problems.append(f"seq {rec.get('seq')}: bad spec: {err}")
                    continue
                if spec.job_id in q.jobs:
                    problems.append(
                        f"seq {rec.get('seq')}: duplicate submit {job!r}"
                    )
                    continue
                q._apply_submit(spec)
            elif rtype == "state":
                r = q.jobs.get(job)
                if r is None:
                    problems.append(
                        f"seq {rec.get('seq')}: state for unknown {job!r}"
                    )
                    continue
                frm, to = rec.get("from"), rec.get("to")
                if frm != r.state or (frm, to) not in ALLOWED_TRANSITIONS:
                    problems.append(
                        f"seq {rec.get('seq')}: skipping illegal "
                        f"{frm!r}->{to!r} for {job!r} (state {r.state!r})"
                    )
                    continue
                q._apply_transition(r, frm, to, rec)
        report = {
            "records": len(records),
            "torn_lines": torn,
            "problems": problems,
            "jobs": len(q.jobs),
        }
        return q, report


# --------------------------------------------------------------------- #
# Spool: the multi-writer-safe submission mailbox
# --------------------------------------------------------------------- #
def spool_dir(root: str) -> str:
    return os.path.join(root, "spool")


def submit_to_spool(root: str, spec: JobSpec) -> str:
    """Atomically park ``spec`` for the daemon (tmp + rename in the
    spool directory, the repo's persistent-write discipline). Usable
    while no daemon runs — specs wait until one ingests them."""
    spec.validate()
    d = spool_dir(root)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{spec.job_id}.json")
    if os.path.exists(path):
        raise ValueError(f"job id {spec.job_id!r} already spooled")
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".spec_", suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(spec.to_json(), f, indent=1)
    os.replace(tmp, path)
    return path


def ingest_spool(root: str, queue: JobQueue,
                 on_skip=None) -> List[JobRecord]:
    """Move every parked spec into the journal-backed queue; a spec
    whose id the journal already knows (the daemon died between
    journaling and unlinking) is deduplicated by dropping the spool
    file. A torn or corrupt spec file (truncated JSON, non-dict
    payload, a spec that fails validation) never crashes the daemon:
    it is quarantined as ``<name>.bad`` next to the spool, a named
    ``note`` record lands in the journal, and ``on_skip(name, error)``
    — when given — lets the caller mirror the skip as a telemetry
    event. Returns the newly ingested records."""
    d = spool_dir(root)
    if not os.path.isdir(d):
        return []
    ingested: List[JobRecord] = []
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(d, name)
        try:
            with open(path) as f:
                payload = json.load(f)
            if not isinstance(payload, dict):
                raise ValueError(
                    f"spec payload is {type(payload).__name__}, not dict"
                )
            spec = JobSpec.from_json(payload)
            spec.validate()
        except (ValueError, TypeError, KeyError, OSError) as err:
            # quarantine, report, continue — a poisoned mailbox entry
            # must not take the daemon (or block the entries behind it)
            reason = f"{type(err).__name__}: {err}"[:200]
            try:
                os.replace(path, path + ".bad")
            except OSError:
                pass
            queue.journal.append("note", note="spool_skip",
                                 file=name, error=reason)
            if on_skip is not None:
                on_skip(name, reason)
            continue
        if spec.job_id not in queue.jobs:
            ingested.append(queue.submit(spec))
        os.remove(path)
    return ingested
