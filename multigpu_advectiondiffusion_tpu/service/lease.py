"""Single-writer lease for a service root.

Exactly one server incarnation (``serve-requests`` or the ``serve``
scheduler) may write a root's journal at a time: two writers interleave
appends at stale sequence numbers and double-serve requests — the
failure class the crash-safety layer cannot detect until replay. The
lease makes the exclusion explicit and *operable*:

* the mutex is an ``fcntl.flock`` on ``<root>/lease.lock``, held for
  the owner's lifetime — the kernel releases it when the holder dies,
  so a crashed holder's lease is reclaimed with zero timeout tuning;
* ``<root>/lease.json`` is advisory metadata (pid, role, cmdline,
  acquire/heartbeat walls, drain state) written atomically for
  ``tpucfd-status`` / ``GET /healthz``; stale metadata left by a crash
  is classified with the pid+cmdline guard (the scheduler's adoption
  discipline) before takeover is reported;
* a losing acquire raises :class:`LeaseHeldError` naming the holder —
  the CLI maps it to ``EXIT_LEASE_HELD`` (78) with a structured line,
  never a traceback, and never touches the journal.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Optional

try:  # pragma: no cover - absent only on non-POSIX platforms
    import fcntl
except ImportError:  # pragma: no cover
    fcntl = None  # type: ignore[assignment]

#: sysexits-adjacent, after EXIT_PREEMPTED=75 / EXIT_RANK_FAILURE=76 /
#: EXIT_SDC=77: the root already has a live writer.
EXIT_LEASE_HELD = 78

LEASE_FILE = "lease.json"
LOCK_FILE = "lease.lock"


class LeaseHeldError(RuntimeError):
    """Another live incarnation holds the root's writer lease."""

    def __init__(self, path: str, holder: dict, age_s: float):
        self.path = path
        self.holder = dict(holder or {})
        self.age_s = float(age_s)
        pid = self.holder.get("pid")
        super().__init__(
            f"lease held by pid {pid if pid is not None else '?'}, "
            f"age {self.age_s:.1f}s ({path})"
        )


def _pid_alive(pid) -> bool:
    if not isinstance(pid, int) or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def _pid_cmdline(pid) -> Optional[str]:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return f.read().replace(b"\0", b" ").decode(
                "utf-8", "replace"
            )
    except OSError:
        return None


def _holder_matches(holder: dict, root: str) -> bool:
    """pid+cmdline guard: does the recorded pid still look like the
    process that took the lease?  ``True`` on any doubt (no /proc,
    permission) — adoption errs toward *not* declaring staleness."""
    pid = holder.get("pid")
    if not _pid_alive(pid):
        return False
    cmd = _pid_cmdline(pid)
    if cmd is None:  # can't inspect: treat as live (be conservative)
        return True
    want = holder.get("cmdline")
    if want:
        return want.strip() == cmd.strip()
    return os.path.basename(root) in cmd or root in cmd


def _read_meta(path: str) -> Optional[dict]:
    try:
        with open(path, "r") as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return None
    return meta if isinstance(meta, dict) else None


class ServiceLease:
    """Hold the single-writer lease on ``root`` for this process."""

    def __init__(self, root: str, role: str = "serve",
                 heartbeat_s: float = 2.0):
        self.root = os.path.abspath(root)
        self.path = os.path.join(self.root, LEASE_FILE)
        self.lock_path = os.path.join(self.root, LOCK_FILE)
        self.role = role
        self.heartbeat_s = float(heartbeat_s)
        self.takeover: Optional[dict] = None
        self.acquired_wall: Optional[float] = None
        self._fd: Optional[int] = None
        self._last_beat = 0.0
        self._draining = False

    @property
    def held(self) -> bool:
        return self._fd is not None

    def acquire(self) -> "ServiceLease":
        os.makedirs(self.root, exist_ok=True)
        fd = os.open(self.lock_path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            if fcntl is not None:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            info = inspect_lease(self.root)
            raise LeaseHeldError(
                self.path, info.get("holder") or {},
                info.get("age_s") or 0.0,
            ) from None
        if fcntl is None:
            # no flock on this platform: fall back to the metadata
            # pid guard alone (weaker, but still refuses live holders)
            stale = _read_meta(self.path)
            if stale and stale.get("pid") != os.getpid() and (
                _holder_matches(stale, self.root)
            ):
                os.close(fd)
                now = time.time()
                raise LeaseHeldError(
                    self.path, stale,
                    now - float(stale.get("heartbeat")
                                or stale.get("acquired") or now),
                )
        self._fd = fd
        now = time.time()
        stale = _read_meta(self.path)
        if stale and stale.get("pid") not in (None, os.getpid()):
            # the flock was free, yet metadata survives: the previous
            # holder died without releasing.  Record the takeover.
            self.takeover = {
                "pid": stale.get("pid"),
                "role": stale.get("role"),
                "age_s": round(now - float(
                    stale.get("heartbeat")
                    or stale.get("acquired") or now), 3),
            }
        self.acquired_wall = now
        self._write_meta(now)
        return self

    def _write_meta(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        meta = {
            "pid": os.getpid(),
            "role": self.role,
            "root": self.root,
            "cmdline": _pid_cmdline(os.getpid()),
            "acquired": round(self.acquired_wall or now, 6),
            "heartbeat": round(now, 6),
            "draining": bool(self._draining),
        }
        fd, tmp = tempfile.mkstemp(
            dir=self.root, prefix=".lease_", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self._last_beat = time.monotonic()

    def heartbeat(self, draining: bool = False,
                  force: bool = False) -> bool:
        """Refresh the advisory metadata; throttled to
        ``heartbeat_s`` unless the drain state flips or ``force``."""
        if self._fd is None:
            return False
        flipped = bool(draining) != self._draining
        self._draining = bool(draining)
        if not force and not flipped and (
            time.monotonic() - self._last_beat < self.heartbeat_s
        ):
            return False
        self._write_meta()
        return True

    def release(self) -> None:
        if self._fd is None:
            return
        try:
            os.unlink(self.path)
        except OSError:
            pass
        try:
            if fcntl is not None:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
        except OSError:
            pass
        try:
            os.close(self._fd)
        except OSError:
            pass
        self._fd = None

    def __enter__(self) -> "ServiceLease":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def inspect_lease(root: str) -> dict:
    """Read-only lease view for status/healthz: never takes the lock.

    ``locked`` is authoritative liveness (a non-blocking flock probe);
    ``stale`` flags leftover metadata whose recorded pid no longer
    passes the pid+cmdline guard — the root a crashed holder left
    behind, reclaimable by the next acquire."""
    root = os.path.abspath(root)
    path = os.path.join(root, LEASE_FILE)
    meta = _read_meta(path)
    locked = False
    lock_path = os.path.join(root, LOCK_FILE)
    if fcntl is not None and os.path.exists(lock_path):
        try:
            fd = os.open(lock_path, os.O_RDWR)
        except OSError:
            fd = None
        if fd is not None:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                fcntl.flock(fd, fcntl.LOCK_UN)
            except OSError:
                locked = True
            finally:
                os.close(fd)
    out = {
        "present": meta is not None,
        "locked": locked,
        "holder": meta,
        "age_s": None,
        "heartbeat_age_s": None,
        "alive": False,
        "stale": False,
        "draining": False,
    }
    if meta is None:
        return out
    now = time.time()
    acquired = meta.get("acquired")
    beat = meta.get("heartbeat") or acquired
    if isinstance(acquired, (int, float)):
        out["age_s"] = round(now - float(acquired), 3)
    if isinstance(beat, (int, float)):
        out["heartbeat_age_s"] = round(now - float(beat), 3)
    out["draining"] = bool(meta.get("draining"))
    out["alive"] = locked or _holder_matches(meta, root)
    out["stale"] = not out["alive"]
    return out
