"""Write-ahead journal for the job scheduler: crash-safe by replay.

One JSONL file, append-only. Every record is a *commit record*: the
line carries a CRC32 of its own canonical serialization, and the append
flushes + fsyncs before the caller acts on the transition — the WAL
discipline (journal first, act second), so a scheduler SIGKILLed at any
instant can rebuild its exact queue state by replaying the journal.

Failure containment mirrors the rest of the repo:

* **torn tails** — a crash mid-append leaves a partial last line (or a
  line whose CRC no longer matches). Replay skips and *counts* torn
  lines instead of failing, the ``telemetry/analyze.load_stream``
  discipline for crashed ranks' event streams;
* **ENOSPC** (``resilience/faults.disk_full``) — an append that cannot
  reach the disk retries once, then parks the record in an in-memory
  pending buffer and marks the journal *degraded* instead of killing
  the daemon; the next successful append drains the buffer in order,
  so a freed disk heals the journal without losing sequencing.

**Group commit** (ISSUE 19): ``group_commit_s > 0`` batches records
per fsync under a bounded-latency window. Every append still writes
and flushes its line immediately (the torn-tail/CRC discipline is
unchanged — the bytes reach the OS before ``append`` returns), but the
fsync is deferred until the window since the first unsynced record
elapses, or until the caller demands a barrier with :meth:`commit`.
The crash-safety contract is the caller's to keep and the API makes it
cheap: a record's ``durable`` key is True only once ITS fsync ran, and
the server acks/publishes nothing until ``commit()`` returns — one
fsync then covers every record of the boundary instead of one fsync
per transition. ``group_commit_s=0`` (the default) is byte- and
syscall-identical to the pre-group-commit journal.

Records are dicts with an envelope of ``seq`` (strictly increasing),
``wall`` (epoch seconds), ``type`` (``submit``/``state``/``note``) and
the caller's fields; the ``crc`` field commits the rest.
"""

from __future__ import annotations

import binascii
import json
import os
import time
from typing import List, Optional, Tuple

JOURNAL_SCHEMA = 1


def _crc(body: str) -> str:
    return f"{binascii.crc32(body.encode()) & 0xFFFFFFFF:08x}"


def _seal(rec: dict) -> str:
    """Serialize ``rec`` with its commit CRC appended."""
    body = json.dumps(rec, sort_keys=True)
    return json.dumps({**rec, "crc": _crc(body)}, sort_keys=True)


def _check(rec: dict) -> bool:
    """True when ``rec``'s CRC commits its own content."""
    got = rec.get("crc")
    if not isinstance(got, str):
        return False
    body = {k: v for k, v in rec.items() if k != "crc"}
    return _crc(json.dumps(body, sort_keys=True)) == got


class Journal:
    """Append-side handle. Replay is a classmethod so readers never
    need (or take) the writer's file handle."""

    def __init__(self, path: str, fsync: bool = True,
                 group_commit_s: float = 0.0):
        self.path = path
        self._fsync = bool(fsync)
        # the group-commit window only means anything when fsync is on
        # (fsync=False already defers durability to the OS entirely)
        self.group_commit_s = (
            max(0.0, float(group_commit_s or 0.0)) if self._fsync
            else 0.0
        )
        self._f = None
        self.degraded = False
        self._pending: List[str] = []
        # group-commit accounting: records written+flushed but not yet
        # fsynced, and the wall the oldest of them was written at (the
        # bounded-latency deadline reads against it)
        self._unsynced = 0
        self._first_unsynced: Optional[float] = None
        # durable-commit latency observer: the metrics layer sets this
        # to Histogram.observe so every fsync'd commit lands in
        # serve_journal_fsync_seconds without the journal importing
        # telemetry
        self.on_commit_seconds = None
        self.last_commit_seconds = None
        # group-commit batch-size observer (records per fsync) — the
        # fsync amortization the dashboard/bench rows report
        self.on_commit_batch = None
        self.last_commit_batch = 0
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # continue the sequence a previous incarnation committed — the
        # replay cost is paid once, at open
        records, _ = self.replay(path)
        self._seq = max((r.get("seq", 0) for r in records), default=0)

    # ------------------------------------------------------------------ #
    def append(self, rtype: str, **fields) -> dict:
        """Journal one commit record; returns the record. ``durable``
        is False while the journal is degraded (the record sits in the
        pending buffer) or — under group commit — until the record's
        fsync ran (:meth:`commit` is the barrier that makes it True)."""
        self._seq += 1
        rec = {
            "seq": self._seq,
            "wall": round(time.time(), 6),
            "type": str(rtype),
            **fields,
        }
        line = _seal(rec)
        durable = self._commit(line)
        rec["durable"] = durable
        return rec

    def _commit(self, line: str) -> bool:
        """Drain any pending records, then write ``line``; one retry on
        an OSError (ENOSPC and friends), then degrade instead of raise.
        Under group commit the write flushes but the fsync is deferred:
        returns True only when the record is fsynced-durable NOW."""
        backlog = self._pending + [line]
        for attempt in (0, 1):
            try:
                t0 = time.monotonic()
                self._write("\n".join(backlog) + "\n")
                self._pending = []
                self.degraded = False
                if self.group_commit_s > 0.0:
                    self._unsynced += len(backlog)
                    now = time.monotonic()
                    if self._first_unsynced is None:
                        self._first_unsynced = now
                    if now - self._first_unsynced >= self.group_commit_s:
                        return self.commit() > 0
                    return False  # flushed; fsync pending in-window
                self.last_commit_seconds = time.monotonic() - t0
                if self.on_commit_seconds is not None:
                    self.on_commit_seconds(self.last_commit_seconds)
                return True
            except OSError:
                # a failed write leaves the handle in an unknown state;
                # reopen before the retry
                self._close_handle()
                if attempt == 0:
                    continue
                self._pending = backlog
                self.degraded = True
                return False
        return False  # unreachable

    # ------------------------------------------------------------------ #
    # Group commit
    # ------------------------------------------------------------------ #
    @property
    def unsynced(self) -> int:
        """Records written+flushed whose fsync has not yet run."""
        return self._unsynced

    def commit_due(self) -> bool:
        """True when the bounded-latency window has elapsed for the
        oldest unsynced record (the loop's cue to call commit)."""
        return (
            self._unsynced > 0
            and self._first_unsynced is not None
            and time.monotonic() - self._first_unsynced
            >= self.group_commit_s
        )

    def commit(self) -> int:
        """The group-commit barrier: fsync every record written since
        the last fsync. Returns the batch size (0 = nothing pending).
        The caller acks/publishes only after this returns — that is the
        whole crash-safety contract under group commit."""
        if self._unsynced <= 0:
            return 0
        if self._f is None or self._f.closed:
            # the records were flushed through a handle that is gone
            # (ENOSPC reopen path); nothing to fsync against
            self._unsynced = 0
            self._first_unsynced = None
            return 0
        t0 = time.monotonic()
        try:
            os.fsync(self._f.fileno())
        except OSError:
            self._close_handle()
            self.degraded = True
            return 0
        self.last_commit_seconds = time.monotonic() - t0
        n, self._unsynced = self._unsynced, 0
        self._first_unsynced = None
        self.last_commit_batch = n
        if self.on_commit_seconds is not None:
            self.on_commit_seconds(self.last_commit_seconds)
        if self.on_commit_batch is not None:
            self.on_commit_batch(n)
        return n

    def maybe_commit(self) -> int:
        """Fsync only when the latency window has elapsed — the serving
        loop's per-tick call, bounding how stale an unsynced record can
        get even when no ack forces a barrier."""
        return self.commit() if self.commit_due() else 0

    def _write(self, text: str) -> None:
        """The raw durable write (patched by ``faults.disk_full``).
        Under group commit the fsync is deferred to :meth:`commit`."""
        if self._f is None or self._f.closed:
            self._f = open(self.path, "a")
        self._f.write(text)
        self._f.flush()
        if self._fsync and self.group_commit_s <= 0.0:
            os.fsync(self._f.fileno())

    def _close_handle(self) -> None:
        try:
            if self._f is not None and not self._f.closed:
                self._f.close()
        except OSError:
            pass
        self._f = None

    def close(self) -> None:
        if self._pending:
            # last chance for parked records (disk may have freed up)
            self._commit_pending_best_effort()
        self.commit()  # group commit: no unsynced tail left behind
        self._close_handle()

    def _commit_pending_best_effort(self) -> None:
        backlog, self._pending = self._pending, []
        try:
            self._write("\n".join(backlog) + "\n")
            self.degraded = False
        except OSError:
            self._pending = backlog

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------------ #
    @staticmethod
    def replay(path: str) -> Tuple[List[dict], int]:
        """Read every committed record, tolerating torn lines. Returns
        ``(records, torn_count)`` — torn means unparseable JSON, a
        non-dict line, or a CRC that no longer commits its content
        (a mid-write crash or bit rot)."""
        if not os.path.exists(path):
            return [], 0
        records: List[dict] = []
        torn = 0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    torn += 1
                    continue
                if not isinstance(rec, dict) or not _check(rec):
                    torn += 1
                    continue
                records.append(rec)
        return records, torn


def verify_records(records: List[dict],
                   torn: int = 0,
                   allowed_transitions=None,
                   require_complete: bool = False,
                   terminal_states=None,
                   initial_state: str = "queued") -> List[str]:
    """Structural linearization check over replayed records: sequence
    numbers strictly increase, every transition names a submitted job,
    every (from, to) pair is legal, and — with ``require_complete`` —
    every submitted job reached a terminal state. Returns a list of
    problem strings (empty = the journal linearizes).

    The defaults check the job scheduler's table; the request server
    passes its own ``allowed_transitions``/``terminal_states``/
    ``initial_state`` (``service/requests.py``) — one verifier, two
    state machines."""
    from multigpu_advectiondiffusion_tpu.service.queue import (
        ALLOWED_TRANSITIONS,
        TERMINAL_STATES,
    )

    allowed = allowed_transitions or ALLOWED_TRANSITIONS
    terminal = (TERMINAL_STATES if terminal_states is None
                else frozenset(terminal_states))
    problems: List[str] = []
    last_seq: Optional[int] = None
    state: dict = {}
    for rec in records:
        seq = rec.get("seq")
        if not isinstance(seq, int):
            problems.append(f"record without integer seq: {rec}")
            continue
        if last_seq is not None and seq <= last_seq:
            problems.append(
                f"seq {seq} does not advance past {last_seq}"
            )
        last_seq = seq
        rtype = rec.get("type")
        job = rec.get("job")
        if rtype == "submit":
            if job in state:
                problems.append(f"seq {seq}: duplicate submit of {job!r}")
            state[job] = initial_state
        elif rtype == "state":
            if job not in state:
                problems.append(
                    f"seq {seq}: transition for unsubmitted job {job!r}"
                )
                continue
            frm, to = rec.get("from"), rec.get("to")
            if frm != state[job]:
                problems.append(
                    f"seq {seq}: {job!r} transition from {frm!r} but "
                    f"journal has it in {state[job]!r}"
                )
            if (frm, to) not in allowed:
                problems.append(
                    f"seq {seq}: illegal transition {frm!r} -> {to!r} "
                    f"for {job!r}"
                )
            state[job] = to
        elif rtype != "note":
            problems.append(f"seq {seq}: unknown record type {rtype!r}")
    if require_complete:
        if torn:
            problems.append(f"{torn} torn journal line(s)")
        for job, st in sorted(state.items()):
            if st not in terminal:
                problems.append(
                    f"job {job!r} never reached a terminal state "
                    f"(journal leaves it {st!r})"
                )
    return problems
