"""Write-ahead journal for the job scheduler: crash-safe by replay.

One JSONL file, append-only. Every record is a *commit record*: the
line carries a CRC32 of its own canonical serialization, and the append
flushes + fsyncs before the caller acts on the transition — the WAL
discipline (journal first, act second), so a scheduler SIGKILLed at any
instant can rebuild its exact queue state by replaying the journal.

Failure containment mirrors the rest of the repo:

* **torn tails** — a crash mid-append leaves a partial last line (or a
  line whose CRC no longer matches). Replay skips and *counts* torn
  lines instead of failing, the ``telemetry/analyze.load_stream``
  discipline for crashed ranks' event streams;
* **ENOSPC** (``resilience/faults.disk_full``) — an append that cannot
  reach the disk retries once, then parks the record in an in-memory
  pending buffer and marks the journal *degraded* instead of killing
  the daemon; the next successful append drains the buffer in order,
  so a freed disk heals the journal without losing sequencing.

**Group commit** (ISSUE 19): ``group_commit_s > 0`` batches records
per fsync under a bounded-latency window. Every append still writes
and flushes its line immediately (the torn-tail/CRC discipline is
unchanged — the bytes reach the OS before ``append`` returns), but the
fsync is deferred until the window since the first unsynced record
elapses, or until the caller demands a barrier with :meth:`commit`.
The crash-safety contract is the caller's to keep and the API makes it
cheap: a record's ``durable`` key is True only once ITS fsync ran, and
the server acks/publishes nothing until ``commit()`` returns — one
fsync then covers every record of the boundary instead of one fsync
per transition. ``group_commit_s=0`` (the default) is byte- and
syscall-identical to the pre-group-commit journal.

Records are dicts with an envelope of ``seq`` (strictly increasing),
``wall`` (epoch seconds), ``type`` (``submit``/``state``/``note``) and
the caller's fields; the ``crc`` field commits the rest.

**Schema versioning** (ISSUE 20): a fresh journal's first line is a
sealed header record ``{"seq": 0, "type": "note", "note": "schema",
"schema": N}`` written outside the user sequence (seq 0, no group-
commit accounting), so a format change can never silently mis-replay an
old root. :meth:`Journal.replay` strips headers from the returned
records (consumers see only state-machine records) and raises
:class:`JournalSchemaError` on a version newer than this code —
refusal, not corruption. Pre-versioning roots are *v0* (headerless):
they keep replaying as before, and :func:`migrate_journal` upgrades
them in place atomically (header prepended, every existing line
byte-verbatim, so the replayed state machine is identical).
"""

from __future__ import annotations

import binascii
import json
import os
import tempfile
import time
from typing import List, Optional, Tuple

JOURNAL_SCHEMA = 1


class JournalSchemaError(RuntimeError):
    """The journal was written by a NEWER schema than this code reads.

    Raised loudly instead of mis-replaying: a future format may encode
    state this reader would silently drop."""

    def __init__(self, path: str, found: int, supported: int):
        self.path = path
        self.found = found
        self.supported = supported
        super().__init__(
            f"journal {path} has schema {found}, newer than the "
            f"supported {supported} — refusing to replay (upgrade the "
            f"code, or serve this root with the version that wrote it)"
        )


def _is_schema_header(rec: dict) -> bool:
    return rec.get("type") == "note" and rec.get("note") == "schema"


def _crc(body: str) -> str:
    return f"{binascii.crc32(body.encode()) & 0xFFFFFFFF:08x}"


def _seal(rec: dict) -> str:
    """Serialize ``rec`` with its commit CRC appended."""
    body = json.dumps(rec, sort_keys=True)
    return json.dumps({**rec, "crc": _crc(body)}, sort_keys=True)


def _check(rec: dict) -> bool:
    """True when ``rec``'s CRC commits its own content."""
    got = rec.get("crc")
    if not isinstance(got, str):
        return False
    body = {k: v for k, v in rec.items() if k != "crc"}
    return _crc(json.dumps(body, sort_keys=True)) == got


class Journal:
    """Append-side handle. Replay is a classmethod so readers never
    need (or take) the writer's file handle."""

    def __init__(self, path: str, fsync: bool = True,
                 group_commit_s: float = 0.0):
        self.path = path
        self._fsync = bool(fsync)
        # the group-commit window only means anything when fsync is on
        # (fsync=False already defers durability to the OS entirely)
        self.group_commit_s = (
            max(0.0, float(group_commit_s or 0.0)) if self._fsync
            else 0.0
        )
        self._f = None
        self.degraded = False
        self._pending: List[str] = []
        # group-commit accounting: records written+flushed but not yet
        # fsynced, and the wall the oldest of them was written at (the
        # bounded-latency deadline reads against it)
        self._unsynced = 0
        self._first_unsynced: Optional[float] = None
        # durable-commit latency observer: the metrics layer sets this
        # to Histogram.observe so every fsync'd commit lands in
        # serve_journal_fsync_seconds without the journal importing
        # telemetry
        self.on_commit_seconds = None
        self.last_commit_seconds = None
        # group-commit batch-size observer (records per fsync) — the
        # fsync amortization the dashboard/bench rows report
        self.on_commit_batch = None
        self.last_commit_batch = 0
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # continue the sequence a previous incarnation committed — the
        # replay cost is paid once, at open (raises JournalSchemaError
        # on a future-version file: refuse before writing a byte)
        existing = os.path.exists(path) and os.path.getsize(path) > 0
        records, _ = self.replay(path)
        self._seq = max((r.get("seq", 0) for r in records), default=0)
        if existing:
            self.schema = journal_schema(path) or 0
        else:
            self.schema = JOURNAL_SCHEMA
            self._stamp_header()

    def _stamp_header(self) -> None:
        """Write the seq-0 schema header. Outside the user sequence and
        the group-commit accounting: readers strip it, acks never wait
        on it, and the seq counter stays a pure record count."""
        rec = {
            "seq": 0,
            "wall": round(time.time(), 6),
            "type": "note",
            "note": "schema",
            "schema": JOURNAL_SCHEMA,
        }
        line = _seal(rec)
        try:
            self._write(line + "\n")
            if self._fsync and self.group_commit_s > 0.0:
                os.fsync(self._f.fileno())
        except OSError:
            # same containment as any append: park it, heal later
            self._close_handle()
            self._pending.append(line)
            self.degraded = True

    # ------------------------------------------------------------------ #
    def append(self, rtype: str, **fields) -> dict:
        """Journal one commit record; returns the record. ``durable``
        is False while the journal is degraded (the record sits in the
        pending buffer) or — under group commit — until the record's
        fsync ran (:meth:`commit` is the barrier that makes it True)."""
        self._seq += 1
        rec = {
            "seq": self._seq,
            "wall": round(time.time(), 6),
            "type": str(rtype),
            **fields,
        }
        line = _seal(rec)
        durable = self._commit(line)
        rec["durable"] = durable
        return rec

    def _commit(self, line: str) -> bool:
        """Drain any pending records, then write ``line``; one retry on
        an OSError (ENOSPC and friends), then degrade instead of raise.
        Under group commit the write flushes but the fsync is deferred:
        returns True only when the record is fsynced-durable NOW."""
        backlog = self._pending + [line]
        for attempt in (0, 1):
            try:
                t0 = time.monotonic()
                self._write("\n".join(backlog) + "\n")
                self._pending = []
                self.degraded = False
                if self.group_commit_s > 0.0:
                    self._unsynced += len(backlog)
                    now = time.monotonic()
                    if self._first_unsynced is None:
                        self._first_unsynced = now
                    if now - self._first_unsynced >= self.group_commit_s:
                        return self.commit() > 0
                    return False  # flushed; fsync pending in-window
                self.last_commit_seconds = time.monotonic() - t0
                if self.on_commit_seconds is not None:
                    self.on_commit_seconds(self.last_commit_seconds)
                return True
            except OSError:
                # a failed write leaves the handle in an unknown state;
                # reopen before the retry
                self._close_handle()
                if attempt == 0:
                    continue
                self._pending = backlog
                self.degraded = True
                return False
        return False  # unreachable

    # ------------------------------------------------------------------ #
    # Group commit
    # ------------------------------------------------------------------ #
    @property
    def unsynced(self) -> int:
        """Records written+flushed whose fsync has not yet run."""
        return self._unsynced

    def commit_due(self) -> bool:
        """True when the bounded-latency window has elapsed for the
        oldest unsynced record (the loop's cue to call commit)."""
        return (
            self._unsynced > 0
            and self._first_unsynced is not None
            and time.monotonic() - self._first_unsynced
            >= self.group_commit_s
        )

    def commit(self) -> int:
        """The group-commit barrier: fsync every record written since
        the last fsync. Returns the batch size (0 = nothing pending).
        The caller acks/publishes only after this returns — that is the
        whole crash-safety contract under group commit."""
        if self._unsynced <= 0:
            return 0
        if self._f is None or self._f.closed:
            # the records were flushed through a handle that is gone
            # (ENOSPC reopen path); nothing to fsync against
            self._unsynced = 0
            self._first_unsynced = None
            return 0
        t0 = time.monotonic()
        try:
            os.fsync(self._f.fileno())
        except OSError:
            self._close_handle()
            self.degraded = True
            return 0
        self.last_commit_seconds = time.monotonic() - t0
        n, self._unsynced = self._unsynced, 0
        self._first_unsynced = None
        self.last_commit_batch = n
        if self.on_commit_seconds is not None:
            self.on_commit_seconds(self.last_commit_seconds)
        if self.on_commit_batch is not None:
            self.on_commit_batch(n)
        return n

    def maybe_commit(self) -> int:
        """Fsync only when the latency window has elapsed — the serving
        loop's per-tick call, bounding how stale an unsynced record can
        get even when no ack forces a barrier."""
        return self.commit() if self.commit_due() else 0

    def _write(self, text: str) -> None:
        """The raw durable write (patched by ``faults.disk_full``).
        Under group commit the fsync is deferred to :meth:`commit`."""
        if self._f is None or self._f.closed:
            self._f = open(self.path, "a")
        self._f.write(text)
        self._f.flush()
        if self._fsync and self.group_commit_s <= 0.0:
            os.fsync(self._f.fileno())

    def _close_handle(self) -> None:
        try:
            if self._f is not None and not self._f.closed:
                self._f.close()
        except OSError:
            pass
        self._f = None

    def close(self) -> None:
        if self._pending:
            # last chance for parked records (disk may have freed up)
            self._commit_pending_best_effort()
        self.commit()  # group commit: no unsynced tail left behind
        self._close_handle()

    def _commit_pending_best_effort(self) -> None:
        backlog, self._pending = self._pending, []
        try:
            self._write("\n".join(backlog) + "\n")
            self.degraded = False
        except OSError:
            self._pending = backlog

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------------ #
    @staticmethod
    def replay(path: str,
               include_schema: bool = False) -> Tuple[List[dict], int]:
        """Read every committed record, tolerating torn lines. Returns
        ``(records, torn_count)`` — torn means unparseable JSON, a
        non-dict line, or a CRC that no longer commits its content
        (a mid-write crash or bit rot).

        Schema headers are validated (a version newer than
        ``JOURNAL_SCHEMA`` raises :class:`JournalSchemaError` — loud
        refusal, never a silent mis-replay) and stripped from the
        returned records unless ``include_schema`` — they are format
        metadata, not state-machine history."""
        if not os.path.exists(path):
            return [], 0
        records: List[dict] = []
        torn = 0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    torn += 1
                    continue
                if not isinstance(rec, dict) or not _check(rec):
                    torn += 1
                    continue
                if _is_schema_header(rec):
                    found = rec.get("schema")
                    if isinstance(found, int) and found > JOURNAL_SCHEMA:
                        raise JournalSchemaError(
                            path, found, JOURNAL_SCHEMA
                        )
                    if not include_schema:
                        continue
                records.append(rec)
        return records, torn


def journal_schema(path: str) -> Optional[int]:
    """The schema version a journal file was written under: the first
    committed record's header value, ``0`` for a headerless (v0) file
    with content, ``None`` for a missing/empty/all-torn file. Never
    raises — the refusal decision belongs to :meth:`Journal.replay`."""
    if not os.path.exists(path):
        return None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or not _check(rec):
                continue
            if _is_schema_header(rec):
                found = rec.get("schema")
                return found if isinstance(found, int) else 0
            return 0
    return None


def schema_stamps(path: str) -> List[int]:
    """Every schema-header value in file order (a migrated-then-
    appended history can carry several) — feed to
    :func:`verify_records` for the monotonicity check."""
    stamps: List[int] = []
    if not os.path.exists(path):
        return stamps
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or not _check(rec):
                continue
            if _is_schema_header(rec):
                found = rec.get("schema")
                stamps.append(found if isinstance(found, int) else -1)
    return stamps


def migrate_journal(path: str) -> dict:
    """Upgrade a v0 (headerless) journal to the current schema in
    place, atomically: the header line is prepended and every existing
    line rides byte-verbatim (CRCs untouched), so replay produces the
    identical state machine. Idempotent — an already-current journal is
    left alone. Raises :class:`JournalSchemaError` on a future version
    and ``FileNotFoundError`` on a missing file."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    found = journal_schema(path)
    if found is not None and found > JOURNAL_SCHEMA:
        raise JournalSchemaError(path, found, JOURNAL_SCHEMA)
    records, torn = Journal.replay(path)
    if found == JOURNAL_SCHEMA:
        return {"migrated": False, "from_schema": found,
                "schema": JOURNAL_SCHEMA, "records": len(records),
                "torn": torn}
    header = _seal({
        "seq": 0,
        "wall": round(time.time(), 6),
        "type": "note",
        "note": "schema",
        "schema": JOURNAL_SCHEMA,
    })
    with open(path) as f:
        body = f.read()
    parent = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(
        dir=parent, prefix=".journal_migrate_", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(header + "\n" + body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return {"migrated": True, "from_schema": found or 0,
            "schema": JOURNAL_SCHEMA, "records": len(records),
            "torn": torn}


def verify_records(records: List[dict],
                   torn: int = 0,
                   allowed_transitions=None,
                   require_complete: bool = False,
                   terminal_states=None,
                   initial_state: str = "queued",
                   schema_versions=None) -> List[str]:
    """Structural linearization check over replayed records: sequence
    numbers strictly increase, every transition names a submitted job,
    every (from, to) pair is legal, and — with ``require_complete`` —
    every submitted job reached a terminal state. Returns a list of
    problem strings (empty = the journal linearizes).

    The defaults check the job scheduler's table; the request server
    passes its own ``allowed_transitions``/``terminal_states``/
    ``initial_state`` (``service/requests.py``) — one verifier, two
    state machines. ``schema_versions`` (from :func:`schema_stamps`)
    adds the version check: stamps must be known (≤ JOURNAL_SCHEMA)
    and non-decreasing in file order — a regressed stamp means an
    older writer appended to a migrated root."""
    from multigpu_advectiondiffusion_tpu.service.queue import (
        ALLOWED_TRANSITIONS,
        TERMINAL_STATES,
    )

    allowed = allowed_transitions or ALLOWED_TRANSITIONS
    terminal = (TERMINAL_STATES if terminal_states is None
                else frozenset(terminal_states))
    problems: List[str] = []
    if schema_versions:
        last_v: Optional[int] = None
        for v in schema_versions:
            if not isinstance(v, int) or v < 0:
                problems.append(f"malformed schema stamp {v!r}")
                continue
            if v > JOURNAL_SCHEMA:
                problems.append(
                    f"schema stamp {v} is newer than the supported "
                    f"{JOURNAL_SCHEMA}"
                )
            if last_v is not None and v < last_v:
                problems.append(
                    f"schema stamp regressed {last_v} -> {v} (an "
                    f"older writer appended to a migrated journal)"
                )
            last_v = v
    last_seq: Optional[int] = None
    state: dict = {}
    for rec in records:
        seq = rec.get("seq")
        if not isinstance(seq, int):
            problems.append(f"record without integer seq: {rec}")
            continue
        if last_seq is not None and seq <= last_seq:
            problems.append(
                f"seq {seq} does not advance past {last_seq}"
            )
        last_seq = seq
        rtype = rec.get("type")
        job = rec.get("job")
        if rtype == "submit":
            if job in state:
                problems.append(f"seq {seq}: duplicate submit of {job!r}")
            state[job] = initial_state
        elif rtype == "state":
            if job not in state:
                problems.append(
                    f"seq {seq}: transition for unsubmitted job {job!r}"
                )
                continue
            frm, to = rec.get("from"), rec.get("to")
            if frm != state[job]:
                problems.append(
                    f"seq {seq}: {job!r} transition from {frm!r} but "
                    f"journal has it in {state[job]!r}"
                )
            if (frm, to) not in allowed:
                problems.append(
                    f"seq {seq}: illegal transition {frm!r} -> {to!r} "
                    f"for {job!r}"
                )
            state[job] = to
        elif rtype != "note":
            problems.append(f"seq {seq}: unknown record type {rtype!r}")
    if require_complete:
        if torn:
            problems.append(f"{torn} torn journal line(s)")
        for job, st in sorted(state.items()):
            if st not in terminal:
                problems.append(
                    f"job {job!r} never reached a terminal state "
                    f"(journal leaves it {st!r})"
                )
    return problems
