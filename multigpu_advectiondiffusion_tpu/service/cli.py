"""``serve``/``submit`` CLI verbs for the crash-safe scheduler.

::

    # submit three requests (works with or without a live daemon)
    python -m multigpu_advectiondiffusion_tpu.cli submit --root runs/ \
        --job-id j1 -- diffusion3d --n 64 64 64 --iters 2000 \
        --checkpoint-every 100 --sentinel-every 100
    # start the daemon; --until-idle returns once the queue drains
    python -m multigpu_advectiondiffusion_tpu.cli serve --root runs/ \
        --max-concurrent 2 --devices 8 --until-idle
    # offline: replay + linearization-check the journal
    python -m multigpu_advectiondiffusion_tpu.cli serve --root runs/ \
        --verify --require-complete
"""

from __future__ import annotations

import argparse
import os
import sys


def configure_serve(p: argparse.ArgumentParser) -> None:
    p.add_argument("--root", required=True, metavar="DIR",
                   help="scheduler root: journal.jsonl, spool/, "
                        "jobs/<id>/ namespaces, the shared AOT cache "
                        "and the daemon's sched_events.jsonl live here")
    p.add_argument("--max-concurrent", type=int, default=1, metavar="N",
                   help="run slots: jobs admitted at once (default 1)")
    p.add_argument("--devices", type=int, default=1, metavar="P",
                   help="device budget the admission controller "
                        "carves mesh slices from (default 1)")
    p.add_argument("--mem-budget-mb", type=int, default=0, metavar="MB",
                   help="defer admission while the running jobs' "
                        "measured mem:watermark peaks plus the "
                        "candidate's expected peak exceed this "
                        "(0 = unmetered)")
    p.add_argument("--poll", type=float, default=0.2, metavar="S",
                   help="scheduler loop cadence in seconds")
    p.add_argument("--until-idle", action="store_true",
                   help="exit once every job is terminal (the gate/CI "
                        "mode); default: serve until SIGTERM/SIGINT, "
                        "which drains running jobs through their "
                        "checkpoint-and-exit-75 preemption path first")
    p.add_argument("--no-aot-cache", action="store_true",
                   help="disable the shared per-root AOT executable "
                        "cache (warm admission loses its "
                        "deserialize-instead-of-compile path)")
    p.add_argument("--verify", action="store_true",
                   help="no daemon: replay the journal, print the "
                        "queue state table, and exit nonzero when the "
                        "journal does not linearize (illegal or "
                        "out-of-order transitions)")
    p.add_argument("--require-complete", action="store_true",
                   help="with --verify: also fail when any submitted "
                        "job never reached done/failed, or the journal "
                        "has torn lines — the sched_gate.sh assertion")
    p.set_defaults(fn=run_serve)


def configure_submit(p: argparse.ArgumentParser) -> None:
    p.add_argument("--root", required=True, metavar="DIR")
    p.add_argument("--job-id", default=None,
                   help="stable id (default: generated); also the "
                        "job's directory name under <root>/jobs/")
    p.add_argument("--priority", type=int, default=0,
                   help="higher runs first; a strictly higher arrival "
                        "preempts a running lower-priority job through "
                        "the checkpoint-and-exit-75 path")
    p.add_argument("--max-retries", type=int, default=2,
                   help="bounded retry budget per failure policy")
    p.add_argument("--devices", type=int, default=0,
                   help="device request; the scheduler grants the "
                        "largest divisor that fits the free slice "
                        "(elastic resume may re-admit on a smaller "
                        "slice than the first attempt ran on)")
    p.add_argument("--mesh-template", default="dz={devices}",
                   help="mesh spec formatted with the granted device "
                        "count when > 1 (default 'dz={devices}')")
    p.add_argument("--env", action="append", default=[],
                   metavar="KEY=VAL",
                   help="environment override for the job's worker "
                        "process; repeatable")
    p.add_argument("argv", nargs=argparse.REMAINDER,
                   help="the job's CLI request after '--': model + "
                        "flags (the scheduler owns --save/--metrics/"
                        "--resume/--mesh/--aot-cache)")
    p.set_defaults(fn=run_submit)


def run_serve(args) -> None:
    from multigpu_advectiondiffusion_tpu.service.daemon import Scheduler
    from multigpu_advectiondiffusion_tpu.service.journal import (
        Journal,
        verify_records,
    )
    from multigpu_advectiondiffusion_tpu.service.queue import JobQueue

    if args.verify:
        journal_path = os.path.join(args.root, "journal.jsonl")
        records, torn = Journal.replay(journal_path)
        problems = verify_records(
            records, torn=torn,
            require_complete=args.require_complete,
        )
        # the state table, rebuilt exactly the way recovery would
        q, report = JobQueue.replay(Journal(journal_path, fsync=False))
        print(f"-- journal {journal_path}: {len(records)} record(s), "
              f"{torn} torn line(s), {len(q.jobs)} job(s)")
        for rec in sorted(q.jobs.values(), key=lambda r: r.order):
            print(f"   {rec.job_id:<24} {rec.state:<13} "
                  f"attempts={rec.attempts} "
                  f"failures={len(rec.failures)} "
                  f"dt_scale={rec.dt_scale:g}")
        for msg in report.get("problems", []):
            problems.append(f"replay: {msg}")
        for msg in problems:
            print(f"   PROBLEM: {msg}", file=sys.stderr)
        if problems:
            raise SystemExit(1)
        print("-- journal linearizes")
        return None

    sched = Scheduler(
        args.root,
        max_concurrent=args.max_concurrent,
        device_budget=args.devices,
        mem_budget_bytes=args.mem_budget_mb * (1 << 20),
        poll_seconds=args.poll,
        aot_cache=not args.no_aot_cache,
    )
    try:
        outcome = sched.serve(until_idle=args.until_idle)
    finally:
        sched.close()
    states = outcome.get("states", {})
    print(f"-- serve: {outcome.get('reason')}; "
          + ", ".join(f"{k}={v}" for k, v in sorted(states.items())))
    if outcome.get("reason") == "stalled":
        raise SystemExit(2)
    return None


def run_submit(args) -> None:
    from multigpu_advectiondiffusion_tpu.service.queue import (
        JobSpec,
        new_job_id,
        submit_to_spool,
    )

    argv = list(args.argv)
    if argv and argv[0] == "--":
        argv = argv[1:]
    env = {}
    for item in args.env:
        key, _, val = item.partition("=")
        env[key] = val
    spec = JobSpec(
        job_id=args.job_id or new_job_id(),
        argv=argv,
        priority=args.priority,
        max_retries=args.max_retries,
        devices=args.devices,
        mesh_template=args.mesh_template,
        env=env,
    )
    path = submit_to_spool(args.root, spec)
    print(f"-- submitted {spec.job_id} (priority {spec.priority}) "
          f"-> {path}")
    return None
