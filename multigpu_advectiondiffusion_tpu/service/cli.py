"""``serve``/``submit`` CLI verbs for the crash-safe scheduler.

::

    # submit three requests (works with or without a live daemon)
    python -m multigpu_advectiondiffusion_tpu.cli submit --root runs/ \
        --job-id j1 -- diffusion3d --n 64 64 64 --iters 2000 \
        --checkpoint-every 100 --sentinel-every 100
    # start the daemon; --until-idle returns once the queue drains
    python -m multigpu_advectiondiffusion_tpu.cli serve --root runs/ \
        --max-concurrent 2 --devices 8 --until-idle
    # offline: replay + linearization-check the journal
    python -m multigpu_advectiondiffusion_tpu.cli serve --root runs/ \
        --verify --require-complete
"""

from __future__ import annotations

import argparse
import os
import sys


def configure_serve(p: argparse.ArgumentParser) -> None:
    p.add_argument("--root", required=True, metavar="DIR",
                   help="scheduler root: journal.jsonl, spool/, "
                        "jobs/<id>/ namespaces, the shared AOT cache "
                        "and the daemon's sched_events.jsonl live here")
    p.add_argument("--max-concurrent", type=int, default=1, metavar="N",
                   help="run slots: jobs admitted at once (default 1)")
    p.add_argument("--devices", type=int, default=1, metavar="P",
                   help="device budget the admission controller "
                        "carves mesh slices from (default 1)")
    p.add_argument("--mem-budget-mb", type=int, default=0, metavar="MB",
                   help="defer admission while the running jobs' "
                        "measured mem:watermark peaks plus the "
                        "candidate's expected peak exceed this "
                        "(0 = unmetered)")
    p.add_argument("--poll", type=float, default=0.2, metavar="S",
                   help="scheduler loop cadence in seconds")
    p.add_argument("--until-idle", action="store_true",
                   help="exit once every job is terminal (the gate/CI "
                        "mode); default: serve until SIGTERM/SIGINT, "
                        "which drains running jobs through their "
                        "checkpoint-and-exit-75 preemption path first")
    p.add_argument("--no-aot-cache", action="store_true",
                   help="disable the shared per-root AOT executable "
                        "cache (warm admission loses its "
                        "deserialize-instead-of-compile path)")
    p.add_argument("--verify", action="store_true",
                   help="no daemon: replay the journal, print the "
                        "queue state table, and exit nonzero when the "
                        "journal does not linearize (illegal or "
                        "out-of-order transitions)")
    p.add_argument("--require-complete", action="store_true",
                   help="with --verify: also fail when any submitted "
                        "job never reached done/failed, or the journal "
                        "has torn lines — the sched_gate.sh assertion")
    p.add_argument("--no-lease", action="store_true",
                   help="skip the single-writer lease (testing only: "
                        "two daemons on one root WILL interleave "
                        "journal appends)")
    p.set_defaults(fn=run_serve)


def configure_submit(p: argparse.ArgumentParser) -> None:
    p.add_argument("--root", required=True, metavar="DIR")
    p.add_argument("--job-id", default=None,
                   help="stable id (default: generated); also the "
                        "job's directory name under <root>/jobs/")
    p.add_argument("--priority", type=int, default=0,
                   help="higher runs first; a strictly higher arrival "
                        "preempts a running lower-priority job through "
                        "the checkpoint-and-exit-75 path")
    p.add_argument("--max-retries", type=int, default=2,
                   help="bounded retry budget per failure policy")
    p.add_argument("--devices", type=int, default=0,
                   help="device request; the scheduler grants the "
                        "largest divisor that fits the free slice "
                        "(elastic resume may re-admit on a smaller "
                        "slice than the first attempt ran on)")
    p.add_argument("--mesh-template", default="dz={devices}",
                   help="mesh spec formatted with the granted device "
                        "count when > 1 (default 'dz={devices}')")
    p.add_argument("--env", action="append", default=[],
                   metavar="KEY=VAL",
                   help="environment override for the job's worker "
                        "process; repeatable")
    p.add_argument("argv", nargs=argparse.REMAINDER,
                   help="the job's CLI request after '--': model + "
                        "flags (the scheduler owns --save/--metrics/"
                        "--resume/--mesh/--aot-cache)")
    p.set_defaults(fn=run_submit)


def run_serve(args) -> None:
    from multigpu_advectiondiffusion_tpu.service.daemon import Scheduler
    from multigpu_advectiondiffusion_tpu.service.journal import (
        Journal,
        JournalSchemaError,
        schema_stamps,
        verify_records,
    )
    from multigpu_advectiondiffusion_tpu.service.lease import (
        EXIT_LEASE_HELD,
        LeaseHeldError,
    )
    from multigpu_advectiondiffusion_tpu.service.queue import JobQueue

    if args.verify:
        journal_path = os.path.join(args.root, "journal.jsonl")
        try:
            records, torn = Journal.replay(journal_path)
        except JournalSchemaError as err:
            print(f"   PROBLEM: {err}", file=sys.stderr)
            raise SystemExit(1)
        problems = verify_records(
            records, torn=torn,
            require_complete=args.require_complete,
            schema_versions=schema_stamps(journal_path),
        )
        # the state table, rebuilt exactly the way recovery would
        with Journal(journal_path, fsync=False) as j:
            q, report = JobQueue.replay(j)
        print(f"-- journal {journal_path}: {len(records)} record(s), "
              f"{torn} torn line(s), {len(q.jobs)} job(s)")
        for rec in sorted(q.jobs.values(), key=lambda r: r.order):
            print(f"   {rec.job_id:<24} {rec.state:<13} "
                  f"attempts={rec.attempts} "
                  f"failures={len(rec.failures)} "
                  f"dt_scale={rec.dt_scale:g}")
        for msg in report.get("problems", []):
            problems.append(f"replay: {msg}")
        for msg in problems:
            print(f"   PROBLEM: {msg}", file=sys.stderr)
        if problems:
            raise SystemExit(1)
        print("-- journal linearizes")
        return None

    try:
        sched = Scheduler(
            args.root,
            max_concurrent=args.max_concurrent,
            device_budget=args.devices,
            mem_budget_bytes=args.mem_budget_mb * (1 << 20),
            poll_seconds=args.poll,
            aot_cache=not args.no_aot_cache,
            lease=not args.no_lease,
        )
    except LeaseHeldError as err:
        print(f"-- serve: {err}", file=sys.stderr)
        raise SystemExit(EXIT_LEASE_HELD)
    try:
        outcome = sched.serve(until_idle=args.until_idle)
    finally:
        sched.close()
    states = outcome.get("states", {})
    print(f"-- serve: {outcome.get('reason')}; "
          + ", ".join(f"{k}={v}" for k, v in sorted(states.items())))
    if outcome.get("reason") == "stalled":
        raise SystemExit(2)
    return None


def configure_serve_requests(p: argparse.ArgumentParser) -> None:
    p.add_argument("--root", required=True, metavar="DIR",
                   help="serving root: journal.jsonl, spool/, "
                        "requests/<id>/ artifacts and the server's "
                        "serve_events.jsonl live here")
    p.add_argument("--max-batch", type=int, default=8, metavar="B",
                   help="coalescing width: compatible requests folded "
                        "onto one ensemble member axis (default 8)")
    p.add_argument("--slice-steps", type=int, default=16, metavar="N",
                   help="bounded advance slice: finished members "
                        "return and joiners enter at every N-step "
                        "boundary (default 16)")
    p.add_argument("--queue-bound", type=int, default=64, metavar="N",
                   help="backpressure: open requests beyond this shed "
                        "with a retry-after verdict (default 64)")
    p.add_argument("--retry-after", type=float, default=2.0,
                   metavar="S",
                   help="retry-after hint in shed verdicts (default 2)")
    p.add_argument("--mesh", default=None, metavar="SPEC",
                   help="serving mesh, e.g. 'members=2' or "
                        "'members=2,dz=2' — batches shard their member "
                        "axis over it (clone-padded so B tiles)")
    p.add_argument("--mem-budget-mb", type=int, default=0, metavar="MB",
                   help="cap the batch width so the estimated live "
                        "state fits (0 = unmetered)")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   metavar="K",
                   help="slice checkpoints every K slices (default 1 "
                        "— every slice boundary is crash-resumable)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="also accept requests over an AF_UNIX "
                        "datagram socket at PATH (off by default)")
    p.add_argument("--poll", type=float, default=0.05, metavar="S",
                   help="idle loop cadence in seconds")
    p.add_argument("--until-idle", action="store_true",
                   help="exit once every request is terminal (the "
                        "gate/CI mode); default: serve until killed — "
                        "the journal makes that safe at any instant")
    p.add_argument("--max-seconds", type=float, default=None,
                   metavar="S",
                   help="stop serving after S wall seconds")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve a read-only /metrics (Prometheus text) "
                        "+ /metrics.json endpoint on loopback at PORT "
                        "(0 = ephemeral; off by default)")
    p.add_argument("--metrics-every", type=float, default=2.0,
                   metavar="S",
                   help="atomic metrics-snapshot cadence under "
                        "<root>/metrics/<proc>/ (default 2)")
    p.add_argument("--slo-objective", type=float, default=0.99,
                   metavar="F",
                   help="deadline-SLO good-fraction target driving "
                        "the burn-rate alerts (default 0.99)")
    p.add_argument("--pipeline", action="store_true",
                   help="pipelined slice loop (ISSUE 19): keep "
                        "--pipeline-depth slices in flight, donate the "
                        "state buffer into each dispatch, gather only "
                        "finished lanes, and overlap all host/IO work "
                        "with device compute")
    p.add_argument("--pipeline-depth", type=int, default=2,
                   metavar="D",
                   help="in-flight slice bound for --pipeline "
                        "(default 2)")
    p.add_argument("--donate", dest="donate", default=None,
                   action="store_true",
                   help="donate the ensemble state operand (in-place "
                        "HBM update, no second (B,*grid) buffer); "
                        "default: on with --pipeline, off without")
    p.add_argument("--no-donate", dest="donate", action="store_false",
                   help="force the undonated dispatch (the "
                        "bit-exactness reference)")
    p.add_argument("--group-commit-ms", type=float, default=0.0,
                   metavar="MS",
                   help="journal group commit: batch records per fsync "
                        "under this latency window; acks wait for the "
                        "commit barrier (0 = fsync per record, the "
                        "default)")
    p.add_argument("--no-prewarm", dest="prewarm",
                   action="store_false", default=True,
                   help="disable the speculative AOT prewarm of the "
                        "likely next coalesce key")
    p.add_argument("--http-port", type=int, default=None,
                   metavar="PORT",
                   help="HTTP ingestion adapter on loopback: POST "
                        "/requests submits via the spool protocol, GET "
                        "/requests/<id>[/result[.bin]] reads status/"
                        "results (0 = ephemeral; off by default)")
    p.add_argument("--verify", action="store_true",
                   help="no daemon: replay the request journal, print "
                        "the state table, and exit nonzero when it "
                        "does not linearize against the request "
                        "transition table")
    p.add_argument("--require-complete", action="store_true",
                   help="with --verify: also fail when any submitted "
                        "request never reached done/failed/shed, or "
                        "the journal has torn lines — the "
                        "serve_gate.sh assertion")
    p.add_argument("--no-lease", action="store_true",
                   help="skip the single-writer lease (testing only: "
                        "two servers on one root WILL double-serve "
                        "requests and interleave journal appends)")
    p.add_argument("--drain", action="store_true",
                   help="no daemon: signal the live lease holder on "
                        "--root to drain (stop admission, park the "
                        "in-flight batch at the next slice boundary, "
                        "journal a clean shutdown, release the lease) "
                        "and return; exit 1 when no live holder")
    p.add_argument("--best-effort", action="store_true",
                   help="do not cancel past-deadline requests at "
                        "slice boundaries; deadlines stay advisory "
                        "(ordering + SLO accounting only)")
    p.add_argument("--hang-budget", type=float, default=None,
                   metavar="S",
                   help="fixed wall-clock budget per non-first slice; "
                        "beyond it the dispatch is declared hung and "
                        "the batch evacuated (default: adaptive, "
                        "rolling-median x --hang-multiplier)")
    p.add_argument("--hang-multiplier", type=float, default=8.0,
                   metavar="X",
                   help="adaptive hung-dispatch budget: rolling median "
                        "slice wall time times X (default 8)")
    p.set_defaults(fn=run_serve_requests)


def configure_request(p: argparse.ArgumentParser) -> None:
    p.add_argument("--root", required=True, metavar="DIR")
    p.add_argument("--request-id", default=None,
                   help="stable id (default: generated); also the "
                        "request's directory under <root>/requests/")
    p.add_argument("--model", required=True,
                   help="registry family name (diffusion/burgers/adr)")
    p.add_argument("--n", type=int, nargs="+", default=[32, 32],
                   metavar="N", help="grid sizes, physical order")
    p.add_argument("--lengths", type=float, nargs="+", default=[],
                   metavar="L", help="domain extents, physical order")
    p.add_argument("--t-end", type=float, default=0.2,
                   help="simulated-time horizon (default 0.2)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64", "bfloat16"])
    p.add_argument("--precision", default="native",
                   choices=["native", "bf16"])
    p.add_argument("--impl", default="xla",
                   help="kernel rung (xla/pallas/.../auto; part of "
                        "the coalesce key)")
    p.add_argument("--req-mesh", default="", metavar="SPEC",
                   help="require the server to run this mesh spec "
                        "(default: accept whatever it runs)")
    p.add_argument("--operand", action="append", default=[],
                   metavar="NAME=VALUE",
                   help="member-varying scalar override (e.g. "
                        "diffusivity=0.5); repeatable")
    p.add_argument("--ic", default=None,
                   help="initial-condition name override")
    p.add_argument("--ic-param", action="append", default=[],
                   metavar="NAME=VALUE",
                   help="IC parameter override; repeatable")
    p.add_argument("--t0", type=float, default=None,
                   help="initial simulated time override")
    p.add_argument("--priority", type=int, default=0,
                   help="higher coalesces/marches first; a strictly "
                        "higher arrival preempts a running batch at "
                        "the next slice boundary")
    p.add_argument("--deadline", type=float, default=None, metavar="S",
                   help="SLO: seconds from admission; drives the "
                        "deadline-aware batch ordering")
    p.add_argument("--max-retries", type=int, default=1,
                   help="crash-resume budget (default 1)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="submit over the server's AF_UNIX socket "
                        "instead of the spool file")
    p.add_argument("--wait", type=float, default=None, metavar="S",
                   help="poll the request's verdict.json until it is "
                        "terminal (or S seconds pass; exit 3 on "
                        "timeout, 1 on failed, 75 on shed)")
    p.set_defaults(fn=run_request)


def _kv_floats(items, flag: str) -> dict:
    out = {}
    for item in items:
        key, sep, val = item.partition("=")
        if not sep or not key:
            raise SystemExit(f"{flag} wants NAME=VALUE, got {item!r}")
        out[key] = float(val)
    return out


def run_serve_requests(args) -> None:
    from multigpu_advectiondiffusion_tpu.service.journal import (
        Journal,
        JournalSchemaError,
        schema_stamps,
        verify_records,
    )
    from multigpu_advectiondiffusion_tpu.service.lease import (
        EXIT_LEASE_HELD,
        LeaseHeldError,
        inspect_lease,
    )
    from multigpu_advectiondiffusion_tpu.service.requests import (
        ALLOWED_REQUEST_TRANSITIONS,
        REQUEST_TERMINAL_STATES,
        RequestQueue,
    )

    if args.drain:
        import signal

        info = inspect_lease(args.root)
        if not info.get("present") or not info.get("alive"):
            print(f"-- drain: no live lease holder on {args.root}"
                  + (" (stale lease on disk)" if info.get("stale")
                     else ""),
                  file=sys.stderr)
            raise SystemExit(1)
        pid = int(info["holder"]["pid"])
        os.kill(pid, signal.SIGTERM)
        print(f"-- drain: SIGTERM sent to lease holder pid {pid} "
              f"(age {info.get('age_s', 0.0):.1f}s); it will stop "
              f"admission, park in-flight work at the next slice "
              f"boundary, journal a clean shutdown and release the "
              f"lease")
        return None

    if args.verify:
        journal_path = os.path.join(args.root, "journal.jsonl")
        try:
            records, torn = Journal.replay(journal_path)
        except JournalSchemaError as err:
            print(f"   PROBLEM: {err}", file=sys.stderr)
            raise SystemExit(1)
        problems = verify_records(
            records, torn=torn,
            allowed_transitions=ALLOWED_REQUEST_TRANSITIONS,
            terminal_states=REQUEST_TERMINAL_STATES,
            initial_state="received",
            require_complete=args.require_complete,
            schema_versions=schema_stamps(journal_path),
        )
        with Journal(journal_path, fsync=False) as j:
            q, report = RequestQueue.replay(j)
        print(f"-- journal {journal_path}: {len(records)} record(s), "
              f"{torn} torn line(s), {len(q.requests)} request(s)")
        for rec in sorted(q.requests.values(), key=lambda r: r.order):
            print(f"   {rec.request_id:<24} {rec.state:<10} "
                  f"attempts={rec.attempts} slices={rec.slices} "
                  f"failures={len(rec.failures)}")
        for msg in report.get("problems", []):
            problems.append(f"replay: {msg}")
        for msg in problems:
            print(f"   PROBLEM: {msg}", file=sys.stderr)
        if problems:
            raise SystemExit(1)
        print("-- request journal linearizes")
        return None

    from multigpu_advectiondiffusion_tpu.service.server import (
        RequestServer,
    )

    try:
        server = RequestServer(
            args.root,
            max_batch=args.max_batch,
            slice_steps=args.slice_steps,
            queue_bound=args.queue_bound,
            retry_after_s=args.retry_after,
            mesh=args.mesh,
            mem_budget_bytes=args.mem_budget_mb * (1 << 20),
            checkpoint_every=args.checkpoint_every,
            socket_path=args.socket,
            metrics_port=args.metrics_port,
            metrics_every_s=args.metrics_every,
            slo_objective=args.slo_objective,
            pipeline=args.pipeline,
            pipeline_depth=args.pipeline_depth,
            donate=args.donate,
            group_commit_s=args.group_commit_ms / 1000.0,
            prewarm=args.prewarm,
            http_port=args.http_port,
            lease=not args.no_lease,
            best_effort=args.best_effort,
            hang_budget_s=args.hang_budget,
            hang_multiplier=args.hang_multiplier,
        )
    except LeaseHeldError as err:
        print(f"-- serve-requests: {err}", file=sys.stderr)
        raise SystemExit(EXIT_LEASE_HELD)
    if server.metrics_port is not None:
        print(f"-- metrics endpoint: "
              f"http://127.0.0.1:{server.metrics_port}/metrics")
    if server.http_port is not None:
        print(f"-- request endpoint: "
              f"http://127.0.0.1:{server.http_port}/requests")
    try:
        outcome = server.serve(
            until_idle=args.until_idle,
            max_seconds=args.max_seconds,
            poll_seconds=args.poll,
        )
    finally:
        server.close()
    states = outcome.get("states", {})
    print(f"-- serve-requests: {outcome.get('reason')}; "
          + ", ".join(f"{k}={v}" for k, v in sorted(states.items())))
    if outcome.get("reason") == "stalled":
        raise SystemExit(2)
    return None


def run_request(args) -> None:
    import json
    import time

    from multigpu_advectiondiffusion_tpu.service.requests import (
        RequestSpec,
        new_request_id,
        request_dir,
        submit_request_to_spool,
    )

    spec = RequestSpec(
        request_id=args.request_id or new_request_id(),
        model=args.model,
        n=list(args.n),
        lengths=list(args.lengths),
        t_end=args.t_end,
        dtype=args.dtype,
        precision=args.precision,
        impl=args.impl,
        mesh=args.req_mesh,
        operands=_kv_floats(args.operand, "--operand"),
        ic=args.ic,
        ic_params=_kv_floats(args.ic_param, "--ic-param"),
        t0=args.t0,
        priority=args.priority,
        deadline_s=args.deadline,
        max_retries=args.max_retries,
    )
    if args.socket:
        from multigpu_advectiondiffusion_tpu.service.server import (
            submit_request_over_socket,
        )

        submit_request_over_socket(args.socket, spec)
        print(f"-- submitted {spec.request_id} over {args.socket}")
    else:
        path = submit_request_to_spool(args.root, spec)
        print(f"-- submitted {spec.request_id} "
              f"(priority {spec.priority}) -> {path}")
    if args.wait is None:
        return None
    verdict_path = os.path.join(
        request_dir(args.root, spec.request_id), "verdict.json"
    )
    deadline = time.monotonic() + args.wait
    while time.monotonic() < deadline:
        try:
            with open(verdict_path) as f:
                verdict = json.load(f)
        except (OSError, ValueError):
            time.sleep(0.05)
            continue
        print(json.dumps(verdict, sort_keys=True))
        status = verdict.get("status")
        if status == "failed":
            raise SystemExit(1)
        if status == "shed":
            raise SystemExit(75)
        return None
    print(f"-- no verdict for {spec.request_id} within {args.wait}s",
          file=sys.stderr)
    raise SystemExit(3)


def run_submit(args) -> None:
    from multigpu_advectiondiffusion_tpu.service.queue import (
        JobSpec,
        new_job_id,
        submit_to_spool,
    )

    argv = list(args.argv)
    if argv and argv[0] == "--":
        argv = argv[1:]
    env = {}
    for item in args.env:
        key, _, val = item.partition("=")
        env[key] = val
    spec = JobSpec(
        job_id=args.job_id or new_job_id(),
        argv=argv,
        priority=args.priority,
        max_retries=args.max_retries,
        devices=args.devices,
        mesh_template=args.mesh_template,
        env=env,
    )
    path = submit_to_spool(args.root, spec)
    print(f"-- submitted {spec.job_id} (priority {spec.priority}) "
          f"-> {path}")
    return None


def configure_migrate(p: argparse.ArgumentParser) -> None:
    p.add_argument("--root", required=True, metavar="DIR",
                   help="service root whose journal.jsonl to upgrade "
                        "in place (atomic: tempfile + rename) to the "
                        "current schema version")
    p.set_defaults(fn=run_migrate)


def run_migrate(args) -> None:
    from multigpu_advectiondiffusion_tpu import telemetry
    from multigpu_advectiondiffusion_tpu.service.journal import (
        JournalSchemaError,
        migrate_journal,
    )

    path = os.path.join(args.root, "journal.jsonl")
    try:
        report = migrate_journal(path)
    except FileNotFoundError:
        print(f"-- migrate: no journal at {path}", file=sys.stderr)
        raise SystemExit(1)
    except JournalSchemaError as err:
        print(f"-- migrate: {err}", file=sys.stderr)
        raise SystemExit(1)
    telemetry.event(
        "journal", "migrate",
        path=path,
        migrated=report["migrated"],
        from_schema=report["from_schema"],
        schema=report["schema"],
        records=report["records"],
    )
    if report["migrated"]:
        print(f"-- journal {path}: schema {report['from_schema']} -> "
              f"{report['schema']} ({report['records']} record(s), "
              f"{report['torn']} torn line(s) preserved)")
    else:
        print(f"-- journal {path}: already schema "
              f"{report['schema']}, nothing to do")
    return None
