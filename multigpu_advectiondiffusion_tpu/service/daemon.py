"""The crash-safe multi-run scheduler daemon (ISSUE 14, ROADMAP item 5).

One long-lived process multiplexes a journaled queue of run requests
onto the device budget, reusing the one-shot CLI as its worker binary —
every resilience property the batch machinery already proves (atomic
CRC checkpoints, ``--resume auto``, preemption-safe exit 75, elastic
resharded resume, the rank watchdog, the AOT executable cache) becomes
a scheduling primitive:

* **crash safety** — every state transition is a write-ahead journal
  commit (``service/journal.py``); SIGKILL the daemon at any instant,
  restart it, and :meth:`Scheduler.recover` replays the journal,
  re-adopts still-alive job processes (or classifies dead ones by
  their artifacts) and requeues in-flight work for ``--resume auto``
  recovery — the queue completes bit-exact vs an uninterrupted run;
* **per-job namespacing** — each job owns ``<root>/jobs/<id>/``
  (checkpoints, telemetry, snapshots, heartbeats all keyed by job id),
  so concurrent or serial jobs can never adopt each other's
  checkpoints;
* **admission control** — measured memory watermarks + AOT-warm
  admission (``service/admission.py``);
* **priority preemption** — a higher-priority arrival SIGTERMs the
  lowest-priority running job; the existing preemption path checkpoints
  it and exits 75, the scheduler requeues it, and it resumes
  elastically on whatever device slice is free at re-admission;
* **bounded retries** — failed attempts are classified
  (divergence / SDC / rank failure / disk-full / generic) into
  distinct policies; divergence inherits the dt backoff across
  attempts (``--dt-scale``), disk-full retries exactly once, and every
  attempt lands in the job's journaled failure ledger.

Jobs run as child processes with ``PR_SET_PDEATHSIG`` (Linux): the
daemon's death kills its workers, so recovery never races a live
orphan writing the job directory; where pdeathsig is unavailable the
recovery path re-adopts live orphans by pid + cmdline instead.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from multigpu_advectiondiffusion_tpu.service.admission import (
    AdmissionController,
    WarmLedger,
    warm_key,
)
from multigpu_advectiondiffusion_tpu.service.journal import Journal
from multigpu_advectiondiffusion_tpu.service.queue import (
    JobQueue,
    JobRecord,
    JobSpec,
    ingest_spool,
)

#: exit-code vocabulary the workers already document (README table)
EXIT_PREEMPTED = 75
EXIT_RANK_FAILURE = 76
EXIT_SDC = 77

#: structured-error type names classified as divergence (the family
#: rooted at SolverDivergedError whose retry wants a smaller dt)
_DIVERGED_TYPES = frozenset({
    "SolverDivergedError", "PhysicsViolationError", "SanitizerError",
    "EnsembleMemberDivergedError",
})

#: retry policies per failure class: ``budget`` None = the spec's
#: max_retries; ``dt_backoff`` multiplies the inherited --dt-scale
RETRY_POLICIES = {
    "diverged": {"dt_backoff": True, "budget": None},
    "sdc": {"dt_backoff": False, "budget": None},
    "rank_failure": {"dt_backoff": False, "budget": None},
    "disk_full": {"dt_backoff": False, "budget": 1},
    "error": {"dt_backoff": False, "budget": None},
}


# --------------------------------------------------------------------- #
# argv helpers
# --------------------------------------------------------------------- #
def _flag_value(argv: List[str], flag: str) -> Optional[str]:
    for i, a in enumerate(argv):
        if a == flag and i + 1 < len(argv):
            return argv[i + 1]
    return None


def _set_flag(argv: List[str], flag: str, value: str) -> List[str]:
    out = list(argv)
    for i, a in enumerate(out):
        if a == flag and i + 1 < len(out):
            out[i + 1] = value
            return out
    return out + [flag, value]


def _ckpt_iteration(path: str) -> Optional[int]:
    stem = os.path.basename(path)
    if not stem.startswith("checkpoint_"):
        return None
    stem = stem[len("checkpoint_"):].rsplit(".", 1)[0]
    return int(stem) if stem.isdigit() else None


# --------------------------------------------------------------------- #
# Worker runners
# --------------------------------------------------------------------- #
def _load_libc():
    """Resolve libc BEFORE any fork: the preexec hook runs between
    fork and exec inside a threaded (JAX) parent, where an import or
    dlopen could deadlock on an inherited lock — so it must only call
    an already-bound symbol."""
    try:
        import ctypes

        return ctypes.CDLL(None, use_errno=True)
    except Exception:  # noqa: BLE001 — best-effort; adoption covers it
        return None


_LIBC = _load_libc()


def _pdeathsig_preexec():  # pragma: no cover — runs in the child
    """Ask Linux to SIGKILL this worker when the daemon dies, closing
    the adopt-a-live-orphan race for crash recovery."""
    if _LIBC is not None:
        _LIBC.prctl(1, signal.SIGKILL)  # PR_SET_PDEATHSIG


class SubprocessHandle:
    def __init__(self, proc: subprocess.Popen, log_fh):
        self._proc = proc
        self._log_fh = log_fh
        self.pid = proc.pid

    def poll(self) -> Optional[int]:
        return self._proc.poll()

    def terminate(self) -> None:
        if self._proc.poll() is None:
            self._proc.terminate()

    def kill(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()

    def close(self) -> None:
        try:
            self._log_fh.close()
        except OSError:
            pass


class SubprocessRunner:
    """Default runner: one CLI process per job attempt (the reference's
    one-binary-per-run shape, now multiplexed by the daemon)."""

    def __init__(self, python: Optional[str] = None,
                 pdeathsig: bool = True):
        self.python = python or sys.executable
        self.pdeathsig = pdeathsig and sys.platform.startswith("linux")

    def start(self, argv: List[str], env: Dict[str, str],
              log_path: str) -> SubprocessHandle:
        pkg_dir = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        repo = os.path.dirname(pkg_dir)
        merged = dict(os.environ)
        merged.update(env)
        merged["PYTHONPATH"] = os.pathsep.join(
            [repo] + ([merged["PYTHONPATH"]]
                      if merged.get("PYTHONPATH") else [])
        )
        log_fh = open(log_path, "a")
        proc = subprocess.Popen(
            [self.python, "-m", "multigpu_advectiondiffusion_tpu.cli",
             *argv],
            stdout=log_fh, stderr=subprocess.STDOUT, env=merged,
            preexec_fn=_pdeathsig_preexec if self.pdeathsig else None,
        )
        return SubprocessHandle(proc, log_fh)


class FinishedHandle:
    """A handle whose work already ran (in-process runner) or whose
    outcome is already known (artifact classification)."""

    def __init__(self, rc: int, pid: Optional[int] = None):
        self._rc = int(rc)
        self.pid = pid

    def poll(self) -> int:
        return self._rc

    def terminate(self) -> None:
        pass

    def kill(self) -> None:
        pass

    def close(self) -> None:
        pass


class InProcessRunner:
    """Test-grade runner: executes the CLI in this process (no
    subprocess cost, no preemption concurrency). Structured failures
    land in ``<job>/crash.json`` for the classifier, mirroring the
    crash event the subprocess excepthook would have streamed."""

    def start(self, argv: List[str], env: Dict[str, str],
              log_path: str) -> FinishedHandle:
        del env  # in-process: the test harness owns the environment
        from multigpu_advectiondiffusion_tpu.cli.__main__ import main

        job_dir = _flag_value(argv, "--save") or "."
        try:
            rv = main(list(argv))
            rc = 0 if rv is not False else 1
        except SystemExit as exc:
            rc = int(exc.code or 0) if not isinstance(exc.code, str) else 1
        except KeyboardInterrupt:
            raise
        except BaseException as exc:  # noqa: BLE001 — classified below
            memo = {
                "type": type(exc).__name__,
                "message": str(exc)[:500],
                "errno": getattr(exc, "errno", None),
            }
            from multigpu_advectiondiffusion_tpu.utils.io import (
                atomic_write_text,
            )

            atomic_write_text(
                os.path.join(job_dir, "crash.json"), json.dumps(memo)
            )
            rc = 1
        return FinishedHandle(rc)


class AdoptedHandle:
    """A still-alive worker from a previous daemon incarnation: poll
    watches the pid; once it dies the outcome is classified from the
    job directory's artifacts (a non-child cannot be waited on)."""

    def __init__(self, pid: int, job_dir: str):
        self.pid = int(pid)
        self.job_dir = job_dir

    def poll(self) -> Optional[int]:
        if _pid_alive(self.pid):
            return None
        return _artifact_rc(self.job_dir)

    def terminate(self) -> None:
        try:
            os.kill(self.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass

    def kill(self) -> None:
        try:
            os.kill(self.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass

    def close(self) -> None:
        pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _pid_runs_job(pid: int, job_dir: str) -> bool:
    """Guard against pid reuse before adopting: the live process's
    cmdline must mention this job's directory. Falls back to pid
    liveness where /proc is unavailable."""
    if not _pid_alive(pid):
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().decode("utf-8", errors="replace")
    except OSError:
        return True
    return job_dir in cmdline


def _artifact_rc(job_dir: str) -> int:
    """Outcome of an attempt whose exit code was unobservable (adopted
    orphan): a published summary means success, a preemption manifest
    means exit 75, anything else is a retryable failure — ``--resume
    auto`` picks up from the checkpoints either way."""
    if os.path.exists(os.path.join(job_dir, "summary.json")):
        return 0
    if os.path.exists(os.path.join(job_dir, "preempt.json")):
        return EXIT_PREEMPTED
    return 1


def _crash_evidence(job_dir: str, tail_bytes: int = 131072) -> dict:
    """Structured failure evidence: the in-process crash memo, else the
    last ``crash`` event in the job's telemetry stream tail."""
    memo_path = os.path.join(job_dir, "crash.json")
    if os.path.exists(memo_path):
        try:
            with open(memo_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            pass
    events = os.path.join(job_dir, "events.jsonl")
    last = {}
    try:
        size = os.path.getsize(events)
        with open(events, "rb") as f:
            f.seek(max(0, size - tail_bytes))
            text = f.read().decode("utf-8", errors="replace")
    except OSError:
        return last
    for line in text.splitlines():
        if '"crash"' not in line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            continue
        if ev.get("kind") == "crash":
            last = {"type": ev.get("name"),
                    "message": ev.get("message", "")}
    return last


def classify_failure(rc: int, job_dir: str) -> tuple:
    """Map a failed attempt to its retry policy: ``(policy, reason)``."""
    if rc == EXIT_RANK_FAILURE:
        return "rank_failure", "peer rank died or stalled (exit 76)"
    if rc == EXIT_SDC:
        return "sdc", "silent-data-corruption budget exhausted (exit 77)"
    ev = _crash_evidence(job_dir)
    etype = ev.get("type") or ""
    message = ev.get("message") or ""
    if etype in _DIVERGED_TYPES:
        return "diverged", f"{etype}: {message}"[:300]
    if etype == "SDCDetectedError":
        return "sdc", f"{etype}: {message}"[:300]
    if etype in ("OSError", "IOError") and (
        ev.get("errno") == 28 or "No space left" in message
    ):
        return "disk_full", f"{etype}: {message}"[:300]
    return "error", (f"{etype}: {message}"[:300] if etype
                     else f"exit code {rc}")


# --------------------------------------------------------------------- #
# The scheduler
# --------------------------------------------------------------------- #
class Scheduler:
    """Journal-backed multi-run scheduler; see the module docstring.

    Layout under ``root``::

        journal.jsonl        write-ahead queue journal (commit records)
        sched_events.jsonl   the daemon's own sched:*/job:* telemetry
        spool/               atomic submission mailbox
        aot/                 shared AOT executable cache (warm admission)
        jobs/<id>/           per-job namespace: checkpoints, events.jsonl,
                             job.log, snapshots, .heartbeats, results
    """

    def __init__(self, root: str, max_concurrent: int = 1,
                 device_budget: int = 1, mem_budget_bytes: int = 0,
                 poll_seconds: float = 0.2, runner=None,
                 aot_cache: bool = True, fsync: bool = True,
                 lease: bool = False, heartbeat_s: float = 2.0):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        # single-writer lease (ISSUE 20): same mechanism as the request
        # server — acquire before any root artifact is opened, so a
        # second daemon on this root exits naming the holder instead of
        # interleaving journal appends
        self.lease = None
        if lease:
            from multigpu_advectiondiffusion_tpu.service.lease import (
                ServiceLease,
            )

            self.lease = ServiceLease(
                self.root, role="serve", heartbeat_s=heartbeat_s,
            ).acquire()
        self.jobs_root = os.path.join(self.root, "jobs")
        os.makedirs(self.jobs_root, exist_ok=True)
        self.aot_dir = (
            os.path.join(self.root, "aot") if aot_cache else None
        )
        if self.aot_dir:
            os.makedirs(self.aot_dir, exist_ok=True)
        self.max_concurrent = max(1, int(max_concurrent))
        self.poll_seconds = float(poll_seconds)
        self.runner = runner if runner is not None else SubprocessRunner()
        from multigpu_advectiondiffusion_tpu.telemetry.sink import (
            TelemetrySink,
        )

        # a PRIVATE sink (never the module-level slot): in-process
        # workers install/uninstall their own --metrics sinks and must
        # not tear down the daemon's stream
        self._sink = TelemetrySink(
            os.path.join(self.root, "sched_events.jsonl")
        )
        if self.lease is not None:
            self._sink.event(
                "lease", "acquire", pid=os.getpid(),
                path=self.lease.path,
                takeover=self.lease.takeover is not None,
            )
            if self.lease.takeover:
                self._sink.event(
                    "lease", "takeover", pid=os.getpid(),
                    prev_pid=self.lease.takeover.get("pid"),
                    age_s=self.lease.takeover.get("age_s"),
                )
        self.journal = Journal(
            os.path.join(self.root, "journal.jsonl"), fsync=fsync
        )
        self.queue, self.replay_report = JobQueue.replay(self.journal)
        self.admission = AdmissionController(
            device_budget=device_budget,
            mem_budget_bytes=mem_budget_bytes,
            ledger=self._rebuild_ledger(),
        )
        #: job_id -> live attempt {handle, started, mesh_arg, base_it}
        self._handles: Dict[str, dict] = {}
        self._deferred: Dict[str, str] = {}
        self._recovered = False
        # fleet metrics (ISSUE 18): the scheduler's own snapshot dir,
        # per incarnation, unioned with the server's by
        # telemetry.metrics.merge_snapshot_dirs
        from multigpu_advectiondiffusion_tpu.telemetry.metrics import (
            MetricsRegistry,
        )

        self.metrics = MetricsRegistry(proc=f"daemon-{os.getpid()}")
        self.metrics_dir = os.path.join(
            self.root, "metrics", self.metrics.proc
        )
        self.metrics_every_s = 2.0
        self._last_export = 0.0
        self.journal.on_commit_seconds = self.metrics.histogram(
            "sched_journal_fsync_seconds"
        ).observe

    # ------------------------------------------------------------------ #
    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.jobs_root, job_id)

    def events_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir(job_id), "events.jsonl")

    def _rebuild_ledger(self) -> WarmLedger:
        """Warm knowledge survives the scheduler's death: every done
        transition journals its ledger entry, replayed here."""
        ledger = WarmLedger()
        records, _ = Journal.replay(self.journal.path)
        for rec in records:
            entry = rec.get("warm_entry")
            if (rec.get("type") == "state" and rec.get("to") == "done"
                    and isinstance(entry, dict) and entry.get("key")):
                ledger.observe(entry["key"],
                               entry.get("compile_seconds", 0.0),
                               entry.get("peak_bytes"))
        return ledger

    def _transition(self, job_id: str, to: str, **info) -> JobRecord:
        frm = self.queue.jobs[job_id].state
        rec = self.queue.transition(job_id, to, **info)
        self._sink.event(
            "job", "state", job=job_id,
            **{"from": frm, "to": to},
            reason=info.get("reason"),
        )
        return rec

    # ------------------------------------------------------------------ #
    # Recovery: replay + re-adopt / requeue in-flight work
    # ------------------------------------------------------------------ #
    def recover(self) -> dict:
        if self._recovered:
            return {}
        self._recovered = True
        adopted = requeued = completed = 0
        for rec in list(self.queue.in_flight()):
            job_id = rec.job_id
            jd = self.job_dir(job_id)
            if rec.state == "admitted":
                # admitted but the running record never landed: any
                # spawned worker died with the daemon (pdeathsig)
                self._transition(job_id, "queued",
                                 reason="recovered-unstarted")
                requeued += 1
                continue
            if rec.pid and _pid_runs_job(rec.pid, jd):
                self._handles[job_id] = {
                    "handle": AdoptedHandle(rec.pid, jd),
                    "started": time.monotonic(),
                    "mesh_arg": None,
                    "adopted": True,
                }
                self._sink.event("sched", "adopt", job=job_id,
                                 pid=rec.pid)
                adopted += 1
                continue
            rc = _artifact_rc(jd)
            if rc == 0:
                self._finalize_done(rec, rc, mesh_arg=None,
                                    recovered=True)
                completed += 1
            elif rc == EXIT_PREEMPTED:
                self._transition(job_id, "preempted", rc=rc,
                                 reason="recovered-preempted")
                self._transition(job_id, "queued",
                                 reason="requeue-after-preemption")
                requeued += 1
            else:
                self._transition(job_id, "queued",
                                 reason="recovered-dead",
                                 dt_scale=rec.dt_scale)
                requeued += 1
        report = {
            "records": self.replay_report.get("records", 0),
            "torn_lines": self.replay_report.get("torn_lines", 0),
            "problems": len(self.replay_report.get("problems", [])),
            "jobs": len(self.queue.jobs),
            "adopted": adopted,
            "requeued": requeued,
            "completed": completed,
        }
        self._sink.event("sched", "recover", **report)
        return report

    # ------------------------------------------------------------------ #
    # Submission
    # ------------------------------------------------------------------ #
    def submit(self, spec: JobSpec) -> JobRecord:
        rec = self.queue.submit(spec)
        self._sink.event(
            "job", "submit", job=spec.job_id,
            priority=spec.priority, devices=spec.devices,
            max_retries=spec.max_retries,
        )
        self.metrics.counter("sched_jobs_submitted_total").inc()
        if self.journal.degraded:
            self._sink.event("sched", "journal_degraded",
                             pending=len(self.journal._pending))
        return rec

    def _ingest_spool(self) -> None:
        def on_skip(name, reason):
            self._sink.event("sched", "spool_skip",
                             file=name, error=reason)

        for rec in ingest_spool(self.root, self.queue, on_skip=on_skip):
            self._sink.event(
                "job", "submit", job=rec.job_id,
                priority=rec.spec.priority, devices=rec.spec.devices,
                max_retries=rec.spec.max_retries,
            )
            self.metrics.counter("sched_jobs_submitted_total").inc()

    # ------------------------------------------------------------------ #
    # Attempt lifecycle
    # ------------------------------------------------------------------ #
    def _reserved_devices(self) -> int:
        return sum(r.granted_devices for r in self.queue.in_flight())

    def _build_argv(self, rec: JobRecord,
                    mesh_arg: Optional[str]) -> List[str]:
        from multigpu_advectiondiffusion_tpu.resilience.recovery import (
            find_latest_checkpoint,
        )

        spec = rec.spec
        jd = self.job_dir(rec.job_id)
        argv = list(spec.argv)
        total = _flag_value(argv, "--iters")
        latest = find_latest_checkpoint(jd, report=lambda m: None)
        if latest is not None and total is not None:
            done_it = _ckpt_iteration(latest)
            if done_it is not None:
                remaining = max(0, int(total) - done_it)
                argv = _set_flag(argv, "--iters", str(remaining))
        argv += ["--resume", "auto", "--save", jd,
                 "--metrics", self.events_path(rec.job_id)]
        if self.aot_dir:
            argv += ["--aot-cache", self.aot_dir]
        if rec.dt_scale != 1.0:
            argv += ["--dt-scale", f"{rec.dt_scale:.12g}"]
        if mesh_arg:
            argv += ["--mesh", mesh_arg]
        return argv

    def _start(self, rec: JobRecord, info: dict) -> None:
        job_id = rec.job_id
        jd = self.job_dir(job_id)
        os.makedirs(jd, exist_ok=True)
        # stale terminal markers from the previous attempt would
        # misclassify this one (adoption reads artifacts)
        for name in ("summary.json", "preempt.json", "result.bin",
                     "crash.json"):
            try:
                os.remove(os.path.join(jd, name))
            except FileNotFoundError:
                pass
        mesh_arg = self.admission.mesh_arg(
            rec.spec, info.get("granted_devices", 1)
        )
        argv = self._build_argv(rec, mesh_arg)
        attempt = rec.attempts + 1
        handle = self.runner.start(
            argv, rec.spec.env, os.path.join(jd, "job.log")
        )
        self._transition(
            job_id, "running", pid=getattr(handle, "pid", None),
            attempt=attempt, dt_scale=rec.dt_scale,
        )
        self._handles[job_id] = {
            "handle": handle,
            "started": time.monotonic(),
            "mesh_arg": mesh_arg,
            "adopted": False,
        }
        self._sink.event(
            "job", "start", job=job_id,
            pid=getattr(handle, "pid", None), attempt=attempt,
            mesh=mesh_arg, dt_scale=rec.dt_scale,
            warm=bool(info.get("warm")),
        )

    def _admit(self) -> int:
        admitted = 0
        for rec in self.queue.runnable():
            free_slots = self.max_concurrent - len(self._handles)
            free_devices = (
                self.admission.device_budget - self._reserved_devices()
            )
            streams = [self.events_path(j) for j in self._handles]
            verdict, info = self.admission.decide(
                rec, free_slots, free_devices, streams
            )
            if verdict != "admit":
                reason = info.get("reason", "?")
                if self._deferred.get(rec.job_id) != reason:
                    self._deferred[rec.job_id] = reason
                    self._sink.event("sched", "defer", job=rec.job_id,
                                     reason=reason, **{
                                         k: v for k, v in info.items()
                                         if k != "reason"
                                     })
                # strict priority: never backfill past a deferred
                # higher-priority job
                break
            self._deferred.pop(rec.job_id, None)
            self._transition(
                rec.job_id, "admitted",
                granted_devices=info["granted_devices"],
                warm=info["warm"], warm_key=info["warm_key"],
            )
            self._sink.event(
                "sched", "admit", job=rec.job_id,
                granted_devices=info["granted_devices"],
                warm=info["warm"],
                expected_compile_seconds_saved=info.get(
                    "expected_compile_seconds_saved"),
                mem_in_use=info.get("mem_in_use"),
                free_devices=free_devices,
            )
            self.metrics.counter("sched_jobs_admitted_total").inc()
            self._start(rec, info)
            admitted += 1
        return admitted

    def _observe_checkpoints(self) -> None:
        from multigpu_advectiondiffusion_tpu.resilience.recovery import (
            scan_checkpoints,
        )

        for job_id in list(self._handles):
            rec = self.queue.jobs[job_id]
            if rec.state != "running":
                continue
            names = scan_checkpoints(self.job_dir(job_id))
            if names:
                self._transition(job_id, "checkpointed",
                                 checkpoint=names[0])

    def _finalize_done(self, rec: JobRecord, rc: int,
                       mesh_arg: Optional[str],
                       recovered: bool = False) -> None:
        jd = self.job_dir(rec.job_id)
        compile_s, peak = 0.0, None
        try:
            with open(os.path.join(jd, "summary.json")) as f:
                summary = json.load(f)
            compile_s = float(summary.get("compile_seconds") or 0.0)
            peak = (summary.get("memory") or {}).get("peak_bytes")
        except (OSError, ValueError, TypeError):
            summary = None
        key = warm_key(rec.spec.argv, mesh_arg)
        entry = self.admission.ledger.observe(key, compile_s, peak)
        self._transition(
            rec.job_id, "done", rc=rc, recovered=recovered,
            warm_entry={"key": key, **entry},
        )

    def _finalize_failure(self, rec: JobRecord, rc: int) -> None:
        jd = self.job_dir(rec.job_id)
        policy, reason = classify_failure(rc, jd)
        entry = {
            "attempt": rec.attempts, "rc": rc, "policy": policy,
            "reason": reason, "wall": round(time.time(), 3),
        }
        prior = sum(1 for f in rec.failures
                    if f.get("policy") == policy)
        budget = RETRY_POLICIES[policy]["budget"]
        if budget is None:
            budget = rec.spec.max_retries
        if prior < budget:
            dt_scale = rec.dt_scale
            if RETRY_POLICIES[policy]["dt_backoff"]:
                backoff = _flag_value(rec.spec.argv, "--dt-backoff")
                dt_scale *= float(backoff) if backoff else 0.5
            self._transition(
                rec.job_id, "queued", failure=entry,
                dt_scale=dt_scale, reason=f"retry-{policy}",
            )
            self._sink.event(
                "sched", "retry", job=rec.job_id,
                attempt=rec.attempts, policy=policy,
                dt_scale=dt_scale, reason=reason,
            )
            self.metrics.counter("sched_retries_total").inc()
            return
        # retries exhausted for this policy: terminal, with forensics
        self._transition(rec.job_id, "failed", failure=entry,
                         reason=policy)
        from multigpu_advectiondiffusion_tpu.utils.io import (
            atomic_write_text,
        )

        log_tail = ""
        try:
            with open(os.path.join(jd, "job.log")) as f:
                log_tail = f.read()[-2000:]
        except OSError:
            pass
        atomic_write_text(
            os.path.join(jd, "failure.json"),
            json.dumps({
                "job": rec.job_id,
                "attempts": rec.attempts,
                "last_rc": rc,
                "policy": policy,
                "reason": reason,
                "ledger": rec.failures,
                "log_tail": log_tail,
            }, indent=1),
        )

    def _reap(self) -> int:
        reaped = 0
        for job_id in list(self._handles):
            h = self._handles[job_id]
            rc = h["handle"].poll()
            if rc is None:
                continue
            h["handle"].close()
            del self._handles[job_id]
            reaped += 1
            rec = self.queue.jobs[job_id]
            seconds = round(time.monotonic() - h["started"], 3)
            self._sink.event("job", "exit", job=job_id, rc=rc,
                             seconds=seconds,
                             adopted=bool(h.get("adopted")))
            self.metrics.counter("sched_job_exits_total").inc()
            self.metrics.histogram("sched_job_seconds").observe(seconds)
            if rc == 0:
                self._finalize_done(rec, rc, mesh_arg=h["mesh_arg"])
            elif rc == EXIT_PREEMPTED:
                self._transition(job_id, "preempted", rc=rc)
                self._transition(job_id, "queued",
                                 reason="requeue-after-preemption",
                                 dt_scale=rec.dt_scale)
            else:
                self._finalize_failure(rec, rc)
        return reaped

    def _maybe_preempt(self) -> None:
        runnable = self.queue.runnable()
        if not runnable:
            return
        top = runnable[0]
        blocked = None
        if len(self._handles) >= self.max_concurrent:
            blocked = "slots"
        elif (self.admission.device_budget
              - self._reserved_devices()) < 1:
            blocked = "devices"
        if blocked is None:
            return
        victims = sorted(
            (r for r in self.queue.in_flight()
             if r.state in ("running", "checkpointed")
             and r.spec.priority < top.spec.priority
             and not r.preempt_requested
             and r.job_id in self._handles),
            key=lambda r: (r.spec.priority, -r.order),
        )
        if not victims:
            return
        victim = victims[0]
        victim.preempt_requested = True
        self._handles[victim.job_id]["handle"].terminate()
        self._sink.event(
            "sched", "preempt", victim=victim.job_id,
            for_job=top.job_id, blocked=blocked,
            victim_priority=victim.spec.priority,
            priority=top.spec.priority,
        )
        self.metrics.counter("sched_preemptions_total").inc()

    # ------------------------------------------------------------------ #
    # The loop
    # ------------------------------------------------------------------ #
    def tick(self) -> dict:
        """One scheduler pass: ingest, observe, reap, preempt, admit."""
        self.recover()
        self._ingest_spool()
        self._observe_checkpoints()
        reaped = self._reap()
        self._maybe_preempt()
        admitted = self._admit()
        if self.journal.degraded:
            self._sink.event("sched", "journal_degraded",
                             pending=len(self.journal._pending))
        if self.lease is not None:
            self.lease.heartbeat()
        self.metrics.gauge("sched_jobs_running").set(len(self._handles))
        self.metrics.gauge("sched_jobs_open").set(
            len(self.queue.open_jobs())
        )
        self.export_metrics(force=False)
        return {
            "running": len(self._handles),
            "open": len(self.queue.open_jobs()),
            "reaped": reaped,
            "admitted": admitted,
        }

    def export_metrics(self, force: bool = True) -> Optional[dict]:
        """Publish this incarnation's atomic metrics snapshot under
        ``metrics/<proc>/`` (throttled unless forced)."""
        now = time.monotonic()
        if not force and now - self._last_export < self.metrics_every_s:
            return None
        self._last_export = now
        snap = self.metrics.write_snapshot(self.metrics_dir)
        self._sink.event(
            "metrics", "snapshot", dir=self.metrics_dir,
            counters=len(snap["counters"]),
            gauges=len(snap["gauges"]),
            histograms=len(snap["histograms"]),
        )
        return snap

    def serve(self, until_idle: bool = False,
              max_seconds: Optional[float] = None) -> dict:
        """The daemon loop. ``until_idle`` returns once every job is
        terminal (or nothing further can be admitted); otherwise serve
        runs until SIGTERM/SIGINT — which also politely drains running
        jobs through their preemption path before returning."""
        from multigpu_advectiondiffusion_tpu.resilience.preemption import (
            PreemptionGuard,
        )

        self.recover()
        self._sink.event(
            "sched", "start", root=self.root,
            max_concurrent=self.max_concurrent,
            device_budget=self.admission.device_budget,
            until_idle=bool(until_idle),
        )
        t0 = time.monotonic()
        stop_reason = "idle"
        with PreemptionGuard() as guard:
            while True:
                status = self.tick()
                if guard.should_stop:
                    stop_reason = f"signal {guard.signum}"
                    self._drain()
                    # the workers parked through their preemption path:
                    # a successor starts with zero surprise recovery
                    self.journal.append("note", note="shutdown",
                                        clean=True, pid=os.getpid(),
                                        reason=stop_reason)
                    break
                if max_seconds and time.monotonic() - t0 > max_seconds:
                    stop_reason = "max_seconds"
                    break
                if until_idle and not self._handles:
                    if not status["open"]:
                        break
                    if not status["admitted"] and not status["reaped"]:
                        stop_reason = "stalled"
                        break
                if not self._handles and not until_idle:
                    time.sleep(self.poll_seconds)
                elif self._handles:
                    time.sleep(self.poll_seconds)
        states = {}
        for r in self.queue.jobs.values():
            states[r.state] = states.get(r.state, 0) + 1
        self._sink.event("sched", "stop", reason=stop_reason,
                         states=states)
        return {"reason": stop_reason, "states": states}

    def _drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: SIGTERM every worker (they checkpoint and
        exit 75 -> requeued), reap what lands before the timeout."""
        for h in self._handles.values():
            h["handle"].terminate()
        deadline = time.monotonic() + timeout
        while self._handles and time.monotonic() < deadline:
            self._reap()
            if self._handles:
                time.sleep(0.1)

    def close(self) -> None:
        self.export_metrics(force=True)
        self.journal.close()
        if self.lease is not None:
            self._sink.event("lease", "release", pid=os.getpid())
            self.lease.release()
            self.lease = None
        self._sink.close()
