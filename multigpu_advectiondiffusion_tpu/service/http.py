"""Minimal stdlib HTTP ingestion adapter for the request server.

Closes ROADMAP item 1's open transport debt without a new dependency:
the daemon already speaks two fronts (the atomic spool mailbox and the
optional unix-datagram RPC), and both funnel through the journal-first
spool ingest. This adapter is the third front, and deliberately the
thinnest possible one — every POST is written into the SAME spool
mailbox (``submit_request_to_spool``), so HTTP submissions inherit the
whole crash-safety story (journal-first, CRC-sealed records, SIGKILL
replay) with zero new code paths; GETs only ever READ the published
artifacts (verdict/result JSON written atomically by the server), so a
reader can never observe a torn result.

Verbs::

    POST /requests            body = RequestSpec JSON -> 202 {request_id}
    GET  /requests/<id>       verdict.json if published, else the live
                              queue state ({"status": "pending", ...})
    GET  /requests/<id>/result        result.json (summary)
    GET  /requests/<id>/result.bin    raw field bytes (octet-stream)
    GET  /healthz             liveness + queue depth

The server binds loopback only — this is an ingestion adapter for
co-located producers, not an internet-facing API.
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional, Tuple

from multigpu_advectiondiffusion_tpu.service.requests import (
    RequestSpec,
    submit_request_to_spool,
)

_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,128}$")

# a RequestSpec is a few hundred bytes of JSON; anything near this is
# hostile or corrupt, and an unbounded read lets one POST exhaust RAM
MAX_BODY_BYTES = 1 << 20


def _request_paths(root: str, request_id: str) -> Optional[str]:
    """The request's artifact directory, or None for an id that could
    escape ``root`` (path traversal is a 400, never a read)."""
    if not _ID_RE.match(request_id):
        return None
    return os.path.join(root, "requests", request_id)


def start_ingest_http(server, port: int) -> Tuple[object, int]:
    """Start the ingestion endpoint on ``127.0.0.1:port`` (0 picks a
    free port) in a daemon thread; returns ``(httpd, bound_port)``.
    ``server`` is the live :class:`RequestServer` — used for the root
    path, the telemetry sink, and the live queue state on status GETs.
    """
    import http.server
    import threading

    root = server.root
    sink = server._sink
    queue = server.queue

    class _Handler(http.server.BaseHTTPRequestHandler):
        def _send(self, code: int, body: bytes,
                  ctype: str = "application/json") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, payload: dict) -> None:
            self._send(code, json.dumps(payload, sort_keys=True).encode())

        def _send_file(self, path: str, ctype: str) -> None:
            try:
                with open(path, "rb") as f:
                    body = f.read()
            except FileNotFoundError:
                self._send_json(404, {"error": "not found"})
                return
            self._send(200, body, ctype)

        def do_POST(self):  # noqa: N802 — stdlib contract
            try:
                self._post()
            except Exception as err:  # noqa: BLE001 — transport wall:
                # a handler bug must answer structured JSON, never leak
                # a traceback to the peer or kill the listener thread
                try:
                    self._send_json(500, {
                        "error": f"{type(err).__name__}"[:300],
                    })
                except OSError:
                    pass

        def _post(self):
            if self.path.split("?")[0] not in ("/requests", "/submit"):
                self._send_json(404, {"error": "POST /requests"})
                return
            if server.draining:
                self._send_json(503, {
                    "status": "draining",
                    "error": "server is draining; resubmit to the "
                             "successor",
                    "retry_after_s": server.retry_after_s,
                })
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
            except (ValueError, TypeError):
                self._send_json(400, {
                    "error": "bad Content-Length header",
                })
                return
            if length < 0 or length > MAX_BODY_BYTES:
                self._send_json(413, {
                    "error": f"body exceeds {MAX_BODY_BYTES} bytes",
                    "max_body_bytes": MAX_BODY_BYTES,
                })
                return
            try:
                payload = json.loads(self.rfile.read(length).decode())
                if not isinstance(payload, dict):
                    raise ValueError("request body is not a JSON object")
                spec = RequestSpec.from_json(payload)
                # the spool write IS the submission: the daemon's next
                # ingest journals it first, exactly like file/socket
                submit_request_to_spool(root, spec)
            except (ValueError, TypeError, KeyError) as err:
                # UnicodeDecodeError is a ValueError subclass: malformed
                # UTF-8 lands here too, as a 400 not a traceback
                sink.event(
                    "serve", "spool_skip", file="<http>",
                    error=f"{type(err).__name__}: {err}"[:200],
                )
                self._send_json(400, {
                    "error": f"{type(err).__name__}: {err}"[:300],
                })
                return
            self._send_json(202, {
                "request_id": spec.request_id,
                "status": "spooled",
            })

        def do_PUT(self):  # noqa: N802 — stdlib contract
            self._send_json(405, {"error": "method not allowed"})

        def do_DELETE(self):  # noqa: N802 — stdlib contract
            self._send_json(405, {"error": "method not allowed"})

        def do_GET(self):  # noqa: N802 — stdlib contract
            try:
                self._get()
            except Exception as err:  # noqa: BLE001 — transport wall
                try:
                    self._send_json(500, {
                        "error": f"{type(err).__name__}"[:300],
                    })
                except OSError:
                    pass

        def _get(self):
            path = self.path.split("?")[0]
            if path == "/healthz":
                lease = None
                if server.lease is not None:
                    lease = {
                        "pid": os.getpid(),
                        "held": bool(server.lease.held),
                    }
                self._send_json(200, {
                    "status": ("draining" if server.draining
                               else "ok"),
                    "draining": bool(server.draining),
                    "lease": lease,
                    "open_requests": len(queue.open_requests()),
                })
                return
            m = re.match(
                r"^/requests/([^/]+)(?:/(result|result\.bin))?$", path
            )
            if not m:
                self._send_json(404, {"error": "not found"})
                return
            rid, sub = m.group(1), m.group(2)
            d = _request_paths(root, rid)
            if d is None:
                self._send_json(400, {"error": "bad request id"})
                return
            if sub == "result":
                self._send_file(os.path.join(d, "result.json"),
                                "application/json")
                return
            if sub == "result.bin":
                self._send_file(os.path.join(d, "result.bin"),
                                "application/octet-stream")
                return
            verdict = os.path.join(d, "verdict.json")
            if os.path.exists(verdict):
                self._send_file(verdict, "application/json")
                return
            rec = queue.requests.get(rid)
            if rec is None:
                self._send_json(404, {"error": "unknown request"})
                return
            self._send_json(200, {
                "status": "pending",
                "state": rec.state,
                "attempts": rec.attempts,
            })

        def log_message(self, *args):  # quiet by design
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                            _Handler)
    bound = int(httpd.server_address[1])
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    sink.event("serve", "http", port=bound)
    return httpd, bound
