"""Shared slicing helpers for stencil operators.

All operators work on *padded* arrays: the caller supplies a ``padder``
callable ``padder(u, axis, halo) -> padded`` which is either plain BC
padding (single device, :func:`core.bc.pad_axis`) or a ``ppermute`` halo
exchange (sharded, :mod:`parallel.halo`). This is the TPU-native analog of
the reference's ghost-cell machinery
(``MultiGPU/Diffusion3d_Baseline/Kernels.cu:32-99`` pack/unpack kernels).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

# padder(u, axis, halo) -> u padded with `halo` ghost cells on both ends.
Padder = Callable[[jnp.ndarray, int, int], jnp.ndarray]


def slice_axis(a: jnp.ndarray, axis: int, start: int, stop: int) -> jnp.ndarray:
    """Static slice ``a[..., start:stop, ...]`` along one axis."""
    return jax.lax.slice_in_dim(a, start, stop, axis=axis)


def shifted(a_padded: jnp.ndarray, axis: int, offset: int, length: int) -> jnp.ndarray:
    """View of length ``length`` at ``offset`` into the padded axis."""
    return jax.lax.slice_in_dim(a_padded, offset, offset + length, axis=axis)


def boundary_band_mask(
    shape: Sequence[int],
    band: int,
    global_shape: Sequence[int] | None = None,
    offsets: Sequence[jnp.ndarray | int] | None = None,
    axes: Sequence[int] | None = None,
) -> jnp.ndarray:
    """Boolean mask, True on cells >= ``band`` away from every global face.

    Mirrors the reference Laplacian's interior guard
    (``Matlab_Prototipes/DiffusionNd/Laplace3d.m:21``: cells within ``band=2``
    of a wall get ``Lu = 0``). ``offsets``/``global_shape`` let a shard build
    the mask in its local window (offset = shard_index * local_n). ``axes``
    restricts the guard to walled axes (periodic axes have no walls).
    """
    ndim = len(shape)
    if global_shape is None:
        global_shape = shape
    if offsets is None:
        offsets = [0] * ndim
    if axes is None:
        axes = range(ndim)
    mask = jnp.ones(tuple(shape), dtype=bool)
    for axis in axes:
        idx = jax.lax.broadcasted_iota(jnp.int32, tuple(shape), axis) + offsets[axis]
        mask = mask & (idx >= band) & (idx < global_shape[axis] - band)
    return mask


def face_mask(
    shape: Sequence[int],
    axes: Sequence[int],
    global_shape: Sequence[int] | None = None,
    offsets: Sequence[jnp.ndarray | int] | None = None,
) -> jnp.ndarray:
    """True on cells lying on a global face of any of the given axes.

    Mirrors the MATLAB Dirichlet clamp (``heat3d.m:65-67``).
    """
    ndim = len(shape)
    if global_shape is None:
        global_shape = shape
    if offsets is None:
        offsets = [0] * ndim
    mask = jnp.zeros(tuple(shape), dtype=bool)
    for axis in axes:
        idx = jax.lax.broadcasted_iota(jnp.int32, tuple(shape), axis) + offsets[axis]
        mask = mask | (idx == 0) | (idx == global_shape[axis] - 1)
    return mask


# ghost_fn(u, axis, halo) -> (lo, hi) ghost slabs for sharded axes, or
# None where the axis is local (plain BC padding applies).
GhostFn = Callable[[jnp.ndarray, int, int], "tuple | None"]


def split_axis_apply(
    fn: Callable[[jnp.ndarray], jnp.ndarray],
    u: jnp.ndarray,
    axis: int,
    r: int,
    lo: jnp.ndarray,
    hi: jnp.ndarray,
) -> jnp.ndarray:
    """Overlapped interior/boundary schedule for a 1-axis stencil op.

    ``fn`` maps an array padded by ``r`` along ``axis`` to the stencil
    result (``2r`` shorter). The interior cells ``[r, n-r)`` are computed
    from purely local data — independent of the in-flight ghost
    collectives, so XLA overlaps them — and the two ``r``-wide boundary
    bands are computed from ``ghost + 2r`` edge cells once the ghosts
    arrive. This is the reference's boundary-first compute ordering
    (``MultiGPU/Diffusion3d_Baseline/main.c:203-260``: boundary kernels on
    send streams, interior kernel concurrent on the compute stream)
    expressed as dataflow instead of stream choreography.

    The arithmetic per cell is identical to the padded path (same stencil
    over the same values), so results equal ``fn(concat([lo, u, hi]))``
    up to compiler FMA-fusion differences (ulp level).
    """
    n = u.shape[axis]
    if n < 2 * r:
        # bands would overlap; tiny shards take the unsplit path
        return fn(jnp.concatenate([lo, u, hi], axis=axis))
    interior = fn(u)  # cells [r, n-r): u itself is their padded input
    lo_in = jnp.concatenate([lo, slice_axis(u, axis, 0, 2 * r)], axis=axis)
    hi_in = jnp.concatenate([slice_axis(u, axis, n - 2 * r, n), hi], axis=axis)
    return jnp.concatenate(
        [fn(lo_in), interior, fn(hi_in)], axis=axis
    )
