"""Axisymmetric (cylindrical r-y) diffusion operator.

Re-design of ``Matlab_Prototipes/DiffusionNd/Laplace2d_axisymmetric.m``:

    Lu = D * ( u_rr + (1/r) u_r + u_yy )

with 4th-order central stencils for both derivatives and ``1/r`` zeroed at
the axis singularity (``heat2d_axisymmetric.m:26``). The standalone
``RadCorr2d.m`` correction carries a sign/scale defect (noted in SURVEY §7);
the formula used here matches the driver-tested
``Laplace2d_axisymmetric.m:10-12``.

Array layout: ``u`` has shape ``(ny, nr)`` — r innermost, matching the
framework's x-innermost convention.
"""

from __future__ import annotations

import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu.core.bc import Boundary, pad_axis
from multigpu_advectiondiffusion_tpu.ops.laplacian import d2_from_padded
from multigpu_advectiondiffusion_tpu.ops.stencils import Padder, shifted

# 4th-order first derivative: (q[i-2] - 8 q[i-1] + 8 q[i+1] - q[i+2]) / (12 dx)
_D1_COEFS = (1.0, -8.0, 0.0, 8.0, -1.0)


def d1_from_padded(up: jnp.ndarray, axis: int, dx: float) -> jnp.ndarray:
    """4th-order central first derivative of an array padded by 2."""
    n = up.shape[axis] - 4
    scale = 1.0 / (12.0 * dx)
    acc = None
    for j, c in enumerate(_D1_COEFS):
        if c == 0.0:
            continue
        term = shifted(up, axis, j, n) * (c * scale)
        acc = term if acc is None else acc + term
    return acc


def inverse_radius(r: jnp.ndarray) -> jnp.ndarray:
    """``1/r`` with the axis point forced to zero (heat2d_axisymmetric.m:26)."""
    return jnp.where(r == 0.0, 0.0, 1.0 / jnp.where(r == 0.0, 1.0, r))


def axis_mask(r: jnp.ndarray) -> jnp.ndarray:
    """True exactly on the coordinate singularity r = 0."""
    return r == 0.0


def axisymmetric_laplacian(
    u: jnp.ndarray,
    spacing,
    inv_r: jnp.ndarray,
    diffusivity: float = 1.0,
    padder: Padder | None = None,
    bcs=None,
    on_axis: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """``D (u_rr + u_r/r + u_yy)`` on an ``(ny, nr)`` field.

    ``inv_r`` is the precomputed ``1/r`` row vector of length ``nr``.

    Deviation from the reference (intentional upgrade): the reference
    simply zeroes ``1/r`` at the axis (``heat2d_axisymmetric.m:26``),
    dropping the ``u_r/r`` term there — an O(1) consistency error that
    caps the whole solve at 1st-order convergence. Here, where
    ``on_axis`` marks r = 0, the term takes its analytic limit
    ``u_r/r -> u_rr`` (smooth axisymmetric fields have ``u_r(0) = 0``).
    """
    if (padder is None) == (bcs is None):
        raise ValueError("provide exactly one of padder/bcs")
    if padder is None:
        padder = lambda x, axis, halo: pad_axis(x, axis, halo, bcs[axis])  # noqa: E731
    dy, dr = spacing
    up_r = padder(u, 1, 2)
    up_y = padder(u, 0, 2)
    u_rr = d2_from_padded(up_r, 1, dr, order=4)
    u_yy = d2_from_padded(up_y, 0, dy, order=4)
    u_r = d1_from_padded(up_r, 1, dr)
    radial = inv_r[None, :] * u_r
    if on_axis is not None:
        radial = jnp.where(on_axis[None, :], u_rr, radial)
    return diffusivity * (u_rr + radial + u_yy)


__all__ = [
    "axisymmetric_laplacian",
    "d1_from_padded",
    "inverse_radius",
    "Boundary",
]
