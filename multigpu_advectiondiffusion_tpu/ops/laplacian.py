"""Central-difference Laplacians (2nd and 4th order).

TPU-native re-design of the reference's Laplacian kernels:

* 4th-order 13-point 3-D stencil — ``LaplaceO4_async``
  (``MultiGPU/Diffusion3d_Baseline/Kernels.cu:207-261``) and the MATLAB
  ground truth ``Matlab_Prototipes/DiffusionNd/Laplace3d.m:22-25``:
  ``D/(12 dx^2) * (-u[i+2] + 16 u[i+1] - 30 u[i] + 16 u[i-1] - u[i-2])``
  summed per axis.
* 2nd-order variants (``LaplaceO2_async``, ``Kernels.cu:152-201``).

Where the CUDA kernels hand-pipeline registers over the z axis, here each
axis term is a sum of shifted slices of a padded array; XLA fuses the whole
stencil into one bandwidth-bound loop over HBM tiles (the Pallas variant in
``ops/pallas`` tiles it explicitly).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu.core.bc import Boundary, pad_axis
from multigpu_advectiondiffusion_tpu.ops.stencils import (
    GhostFn,
    Padder,
    shifted,
    split_axis_apply,
)

# order -> (coefficients, halo radius, denominator)
D2_STENCILS = {
    2: ((1.0, -2.0, 1.0), 1, 1.0),
    4: ((-1.0, 16.0, -30.0, 16.0, -1.0), 2, 12.0),
}


def d2_from_padded(
    up: jnp.ndarray, axis: int, dx: float, order: int = 4
) -> jnp.ndarray:
    """Second derivative along ``axis`` of an array padded by the stencil radius."""
    coefs, r, denom = D2_STENCILS[order]
    n = up.shape[axis] - 2 * r
    scale = 1.0 / (denom * dx * dx)
    acc = None
    for j, c in enumerate(coefs):
        term = shifted(up, axis, j, n) * (c * scale)
        acc = term if acc is None else acc + term
    return acc


def second_derivative(
    u: jnp.ndarray,
    axis: int,
    dx: float,
    bc: Boundary,
    order: int = 4,
) -> jnp.ndarray:
    _, r, _ = D2_STENCILS[order]
    return d2_from_padded(pad_axis(u, axis, r, bc), axis, dx, order)


def laplacian(
    u: jnp.ndarray,
    spacing: Sequence[float],
    diffusivity: float | Sequence[float] = 1.0,
    order: int = 4,
    padder: Padder | None = None,
    bcs: Sequence[Boundary] | None = None,
    impl: str = "xla",
    ghost_fn: GhostFn | None = None,
) -> jnp.ndarray:
    """``sum_axis K_axis * d2u/dx_axis^2`` over all array axes.

    Exactly one of ``padder`` (sharded/explicit halo source) or ``bcs``
    (single-device BC padding) must be provided. ``impl`` selects the
    kernel strategy: ``"xla"`` (fused shifted slices) or ``"pallas"``
    (VMEM slab-pipelined TPU kernel; falls back to XLA where unsupported).
    ``ghost_fn`` (sharded axes only) switches those axes to the
    overlapped interior/boundary schedule (:func:`split_axis_apply`);
    ignored on the Pallas path, which consumes one padded array.
    """
    if (padder is None) == (bcs is None):
        raise ValueError("provide exactly one of padder/bcs")
    if padder is None:
        padder = lambda x, axis, halo: pad_axis(x, axis, halo, bcs[axis])  # noqa: E731
    if isinstance(diffusivity, (int, float)):
        diffusivity = [float(diffusivity)] * u.ndim
    _, r, _ = D2_STENCILS[order]

    if impl == "pallas":
        from multigpu_advectiondiffusion_tpu.ops.pallas import (
            laplacian as pallas_lap,
        )

        if pallas_lap.supported(u.shape, order, u.dtype.itemsize):
            up = u
            for axis in range(u.ndim):
                up = padder(up, axis, r)
            fn = (
                pallas_lap.laplacian_o4_3d
                if u.ndim == 3
                else pallas_lap.laplacian_o4_2d
            )
            return fn(up, spacing, diffusivity)
    elif impl != "xla":
        raise ValueError(f"unknown laplacian impl {impl!r}; use 'xla'/'pallas'")

    acc = None
    for axis in range(u.ndim):
        ghosts = ghost_fn(u, axis, r) if ghost_fn is not None else None
        if ghosts is not None:
            term = diffusivity[axis] * split_axis_apply(
                lambda up, a=axis: d2_from_padded(up, a, spacing[a], order),
                u, axis, r, *ghosts,
            )
        else:
            term = diffusivity[axis] * d2_from_padded(
                padder(u, axis, r), axis, spacing[axis], order
            )
        acc = term if acc is None else acc + term
    return acc
