"""Scalar flux functions for the hyperbolic solvers.

Mirrors the selectable flux menu of the MATLAB drivers
(``Matlab_Prototipes/InviscidBurgersNd/LFWENO5FDM3d.m:30-40``):
linear advection, Burgers ``u^2/2`` (the CUDA kernels' ``Flux``:
``MultiGPU/Burgers3d_Baseline/Kernels.cu:32-35``), and Buckley–Leverett.
Each entry provides ``f(u)`` and its wave speed ``f'(u)``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Flux:
    name: str
    f: Callable[[jnp.ndarray], jnp.ndarray]
    df: Callable[[jnp.ndarray], jnp.ndarray]
    cfl_max: float  # author-recommended CFL ceiling (LFWENO5FDM3d.m:31-39)


def linear(c: float = -1.0) -> Flux:
    return Flux(
        name="linear",
        f=lambda w: c * w,
        df=lambda w: jnp.full_like(w, c),
        cfl_max=0.65,
    )


def burgers() -> Flux:
    return Flux(
        name="burgers",
        f=lambda w: 0.5 * w * w,
        df=lambda w: w,
        cfl_max=0.40,
    )


def buckley_leverett() -> Flux:
    def f(w):
        return 4.0 * w * w / (4.0 * w * w + (1.0 - w) ** 2)

    def df(w):
        return 8.0 * w * (1.0 - w) / (5.0 * w * w - 2.0 * w + 1.0) ** 2

    return Flux(name="buckley", f=f, df=df, cfl_max=0.20)


def get(name: str, **kwargs) -> Flux:
    registry = {
        "linear": linear,
        "burgers": burgers,
        "buckley": buckley_leverett,
        "buckley_leverett": buckley_leverett,
    }
    if name not in registry:
        raise ValueError(f"unknown flux {name!r}; use {sorted(registry)}")
    return registry[name](**kwargs)
