"""Fully-fused SSP-RK3 Burgers/WENO5 stepping on a persistent padded state.

The reference's hot loop launches, per RK stage, three direction-sweep
kernels (``Compute_dF/dG/dH``), an optional Laplacian, and an RK-update
kernel, each streaming the full state through device memory
(``SingleGPU/Burgers3d_WENO5/main.cpp:143-149``,
``MultiGPU/Burgers3d_Baseline/main.c:201-301``). The generic JAX path here
mirrors that structure (pad → per-axis WENO divergence → sum → axpy), and
measures ~1 TFLOP/s effective on v5e — far under the VPU roof — because
XLA materializes the split fluxes and interface fluxes between fusions.

This module collapses each RK stage to ONE Pallas kernel: a z-slab of the
state is DMA'd into VMEM once and all three WENO5 flux divergences, the
viscous Laplacian (when ``nu > 0``), and the RK stage combination are
evaluated in-register before the slab's core rows are written back.

Layout and ghost discipline (mirrors ``fused_diffusion``):

* The state lives in a *padded, tile-aligned* layout
  ``(nz+6, round8(ny+6), round128(nx+6))`` for the whole run. All
  non-interior cells hold edge-replicated values (the reference's
  non-periodic ghost rule, ``WENO5resAdv_X.m:53``).
* Each stage kernel re-synthesizes the ghost cells of its output rows
  from the freshly computed interior (x/y via broadcast selects, the z
  ghost rows via two small extra DMAs on the first/last grid block), so
  the padded invariant holds at every stage boundary — equivalent to the
  generic path's re-padding of ``u`` every stage.
* y/x stencil reads use full-width circular shifts (``pltpu.roll``);
  wrapped lanes land only in ghost/slack outputs, which the edge
  synthesis overwrites. z reads are in-slab row slices (the slab carries
  a 3-row halo).
* Buffer choreography per step (three live padded buffers, zero allocs):
  ``T1 = stage1(S)``, ``T2 = stage2(T1, S)``, ``S' = stage3(T2, S) → S``
  with the final stage writing in place over ``S`` (each grid block reads
  its ``u`` rows strictly before writing them; the TPU grid is a
  sequential loop, so no other block races the ghost-row writes).

Single-chip, fixed-dt only: the sharded world and the adaptive-dt mode
(which needs a global ``max|f'(u)|`` reduction before stage 1) keep the
generic ``shard_map``/XLA path.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.flux import Flux
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
    _STAGES,
    _shift,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    SUBLANE,
    compiler_params,
    interpret_mode,
    round_up,
)
from multigpu_advectiondiffusion_tpu.ops.weno import (
    _weno5_minus,
    _weno5_plus,
)

R = 3  # WENO5 stencil radius == persistent ghost width

# Conservative VMEM budget for the per-block working set. The physical
# VMEM is 128 MiB; the Mosaic scoped ceiling we request is 100 MiB
# (laplacian.VMEM_LIMIT); leave headroom for double-buffered DMAs.
_VMEM_BUDGET = 80 * 1024 * 1024

# Live row-sized buffers per block, by slab height h = bz + 2R and face
# height f = bz + 1: slab + vp + vm (3h) + one axis' WENO working set
# (~13f: 5+5 shifted operands, betas, weights, interface flux) + rhs
# accumulator, RK result, u rows (~4 bz). Mosaic's true liveness grows
# faster with bz than this model (a bz=8 variant at 256^3 exceeded the
# 128 MiB physical VMEM while the model said 77 MiB), and measured
# throughput is flat from bz=1 to bz=2 — the kernel is VPU-bound, so the
# z-halo re-read that a larger bz would amortize is already hidden.
# Hence the hard bz <= 2 cap.
_MAX_BZ = 2


def _live_bytes(bz: int, row_bytes: int) -> int:
    return (3 * (bz + 2 * R) + 13 * (bz + 1) + 4 * bz) * row_bytes


def _pick_bz(nz: int, row_bytes: int) -> int | None:
    for bz in range(min(_MAX_BZ, nz), 0, -1):
        if nz % bz == 0 and _live_bytes(bz, row_bytes) <= _VMEM_BUDGET:
            return bz
    return None


def _split(flux: Flux, v):
    """Local Lax–Friedrichs splitting ``f± = (f(v) ± |f'(v)| v)/2``
    (``WENO5resAdv_X.m:58-60``)."""
    a = jnp.abs(flux.df(v))
    fu = flux.f(v)
    return 0.5 * (fu + a * v), 0.5 * (fu - a * v)


def _div_roll(vp, vm, axis, inv_dx, variant):
    """Flux divergence along a y/x axis of core rows via circular shifts.

    ``hface[i]`` (interface right of cell i) = WENO5⁻(vp[i-2..i+2]) +
    WENO5⁺(vm[i-1..i+3]); divergence = (hface[i] - hface[i-1]) / dx.
    Wrapped lanes touch only ghost/slack outputs (masked by the caller's
    edge synthesis).
    """
    qp = [_shift(vp, off, axis) for off in range(-2, 3)]
    qm = [_shift(vm, off, axis) for off in range(-1, 4)]
    h = _weno5_minus(*qp, variant) + _weno5_plus(*qm, variant)
    return (h - _shift(h, -1, axis)) * inv_dx


def _div_z(vp, vm, bz, inv_dx, variant):
    """Flux divergence along z of the ``bz`` core rows via slab slices.

    Face row ``s`` of the ``bz+1`` interface rows sits right of slab row
    ``R-1+s``; its minus stencil reads vp rows ``s..s+4``, its plus
    stencil vm rows ``s+1..s+5`` — exactly the 2R+bz rows of the slab.
    """
    qp = [vp[j : j + bz + 1] for j in range(5)]
    qm = [vm[j + 1 : j + 2 + bz] for j in range(5)]
    h = _weno5_minus(*qp, variant) + _weno5_plus(*qm, variant)
    return (h[1:] - h[:-1]) * inv_dx


def _laplacian(v, vc, bz, scales):
    """O4 Laplacian of the core rows (radius 2 < R, fits the same halo)."""
    acc = None
    for axis in range(3):
        for j, c in enumerate(O4_COEFFS):
            coef = jnp.asarray(c * scales[axis], v.dtype)
            term = (
                v[j + 1 : j + 1 + bz] if axis == 0
                else _shift(vc, j - 2, axis)
            ) * coef
            acc = term if acc is None else acc + term
    return acc


def _edge_fill(rk, ny, nx):
    """Overwrite every non-interior y/x cell with the edge-replicated
    interior value (``WENO5resAdv_X.m:53``); corners/slack included."""
    gy = lax.broadcasted_iota(jnp.int32, rk.shape, 1) - R
    gx = lax.broadcasted_iota(jnp.int32, rk.shape, 2) - R
    t = jnp.where(gx < 0, rk[:, :, R : R + 1], rk)
    t = jnp.where(gx >= nx, t[:, :, R + nx - 1 : R + nx], t)
    t = jnp.where(gy < 0, t[:, R : R + 1, :], t)
    return jnp.where(gy >= ny, t[:, R + ny - 1 : R + ny, :], t)


def _stage_kernel(
    v_hbm,
    u_hbm,
    out_hbm,
    vs,
    us,
    res,
    gres,
    sem_v,
    sem_u,
    sem_w,
    sem_g,
    *,
    bz: int,
    n_blocks: int,
    interior_shape: Sequence[int],
    inv_dx: Sequence[float],
    nu_scales: Sequence[float] | None,
    flux: Flux,
    variant: str,
    a: float,
    b: float,
    dt: float,
):
    nz, ny, nx = interior_shape
    k = pl.program_id(0)

    cp_v = pltpu.make_async_copy(v_hbm.at[pl.ds(k * bz, bz + 2 * R)], vs, sem_v)
    cp_v.start()
    if us is not None:
        src = u_hbm if u_hbm is not None else out_hbm
        cp_u = pltpu.make_async_copy(src.at[pl.ds(R + k * bz, bz)], us, sem_u)
        cp_u.start()
        cp_u.wait()
    cp_v.wait()

    v = vs[:]
    vc = v[R : R + bz]
    dtype = v.dtype

    # Split fluxes over the whole slab (z needs the halo rows); the y/x
    # sweeps use only the core-row slice of the same arrays.
    vp, vm = _split(flux, v)
    rhs = -(
        _div_z(vp, vm, bz, inv_dx[0], variant)
        + _div_roll(vp[R : R + bz], vm[R : R + bz], 1, inv_dx[1], variant)
        + _div_roll(vp[R : R + bz], vm[R : R + bz], 2, inv_dx[2], variant)
    )
    if nu_scales is not None:
        rhs = rhs + _laplacian(v, vc, bz, nu_scales)

    rk = b * (vc + dt * rhs) if a == 0.0 else a * us[:] + b * (vc + dt * rhs)
    res[:] = _edge_fill(rk.astype(dtype), ny, nx)

    cp_w = pltpu.make_async_copy(res, out_hbm.at[pl.ds(R + k * bz, bz)], sem_w)
    cp_w.start()
    cp_w.wait()

    # z ghost rows: replicate the new boundary interior row (edge BC).
    @pl.when(k == 0)
    def _():
        gres[:] = jnp.broadcast_to(res[0:1], gres.shape)
        cp = pltpu.make_async_copy(gres, out_hbm.at[pl.ds(0, R)], sem_g)
        cp.start()
        cp.wait()

    @pl.when(k == n_blocks - 1)
    def _():
        gres[:] = jnp.broadcast_to(res[bz - 1 : bz], gres.shape)
        cp = pltpu.make_async_copy(gres, out_hbm.at[pl.ds(R + nz, R)], sem_g)
        cp.start()
        cp.wait()


def _make_stage(padded_shape, interior_shape, dtype, *, bz, inv_dx, nu_scales,
                flux, variant, a, b, dt, u_source):
    """One fused RK-stage call; output aliased onto the last operand.

    ``u_source`` as in ``fused_diffusion._make_stage``: ``"none"`` /
    ``"operand"`` / ``"target"`` (in-place final stage).
    """
    nz = interior_shape[0]
    trailing = padded_shape[1:]
    use_u = u_source != "none"
    n_blocks = nz // bz

    kern = functools.partial(
        _stage_kernel,
        bz=bz,
        n_blocks=n_blocks,
        interior_shape=tuple(interior_shape),
        inv_dx=tuple(inv_dx),
        nu_scales=None if nu_scales is None else tuple(nu_scales),
        flux=flux,
        variant=variant,
        a=a,
        b=b,
        dt=dt,
    )

    def kernel(*refs):
        if u_source == "operand":
            (v_hbm, u_hbm, _tgt, out_hbm, vs, us, res, gres,
             sem_v, sem_u, sem_w, sem_g) = refs
        elif u_source == "target":
            (v_hbm, _tgt, out_hbm, vs, us, res, gres,
             sem_v, sem_u, sem_w, sem_g) = refs
            u_hbm = None  # read from out_hbm (in place)
        else:
            v_hbm, _tgt, out_hbm, vs, res, gres, sem_v, sem_w, sem_g = refs
            u_hbm, us, sem_u = None, None, None
        kern(v_hbm, u_hbm, out_hbm, vs, us, res, gres,
             sem_v, sem_u, sem_w, sem_g)

    n_in = 3 if u_source == "operand" else 2
    scratch = [pltpu.VMEM((bz + 2 * R,) + trailing, dtype)]
    if use_u:
        scratch.append(pltpu.VMEM((bz,) + trailing, dtype))
    scratch.append(pltpu.VMEM((bz,) + trailing, dtype))
    scratch.append(pltpu.VMEM((R,) + trailing, dtype))
    scratch.append(pltpu.SemaphoreType.DMA)
    if use_u:
        scratch.append(pltpu.SemaphoreType.DMA)
    scratch.append(pltpu.SemaphoreType.DMA)
    scratch.append(pltpu.SemaphoreType.DMA)

    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_in,
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(tuple(padded_shape), dtype),
        scratch_shapes=scratch,
        input_output_aliases={n_in - 1: 0},  # last operand -> out
        compiler_params=None if interpret_mode() else compiler_params(),
        interpret=interpret_mode(),
    )


class FusedBurgersStepper:
    """Jit-cached fused runner for one (grid, flux, dtype, dt) config.

    Returns ``None``-equivalent via :func:`supported` when the working
    set cannot fit VMEM even at ``bz = 1``.
    """

    def __init__(self, interior_shape, dtype, spacing, flux: Flux,
                 variant: str, nu: float, dt: float, block_z=None):
        nz, ny, nx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.padded_shape = (
            nz + 2 * R,
            round_up(ny + 2 * R, SUBLANE),
            round_up(nx + 2 * R, LANE),
        )
        self.dtype = jnp.dtype(dtype)
        row_bytes = (
            self.padded_shape[1] * self.padded_shape[2] * self.dtype.itemsize
        )
        bz = block_z if block_z is not None else _pick_bz(nz, row_bytes)
        if bz is None or nz % bz != 0:
            raise ValueError(
                f"no viable z-block for nz={nz} at row size {row_bytes} B"
            )
        inv_dx = [1.0 / spacing[i] for i in range(3)]
        nu_scales = None
        if nu:
            nu_scales = [
                float(nu) / (12.0 * spacing[i] * spacing[i]) for i in range(3)
            ]
        sources = ("none", "operand", "target")
        s1, s2, s3 = (
            _make_stage(
                self.padded_shape, self.interior_shape, self.dtype,
                bz=bz, inv_dx=inv_dx, nu_scales=nu_scales, flux=flux,
                variant=variant, a=a, b=b, dt=float(dt), u_source=src,
            )
            for (a, b), src in zip(_STAGES, sources)
        )
        self.dt = float(dt)
        self.block_z = bz

        def step(S, T1, T2):
            T1 = s1(S, T1)       # u1 = u - dt div f(u) [+ nu lap]
            T2 = s2(T1, S, T2)   # u2 = 3/4 u + 1/4 (u1 + dt rhs(u1))
            S = s3(T2, S)        # u  = 1/3 u + 2/3 (u2 + dt rhs(u2))
            return S, T1, T2

        self._step = step

    @staticmethod
    def supported(interior_shape, dtype) -> bool:
        nz, ny, nx = interior_shape
        row_bytes = (
            round_up(ny + 2 * R, SUBLANE)
            * round_up(nx + 2 * R, LANE)
            * jnp.dtype(dtype).itemsize
        )
        return _pick_bz(nz, row_bytes) is not None

    def embed(self, u):
        nz, ny, nx = self.interior_shape
        pz, py, px = self.padded_shape
        return jnp.pad(
            u.astype(self.dtype),
            ((R, pz - nz - R), (R, py - ny - R), (R, px - nx - R)),
            mode="edge",
        )

    def extract(self, S):
        nz, ny, nx = self.interior_shape
        return lax.slice(S, (R, R, R), (R + nz, R + ny, R + nx))

    def run(self, u, t, num_iters: int):
        """``num_iters`` fused SSP-RK3 steps; returns ``(u, t)``."""
        S = self.embed(u)
        T1 = S
        T2 = S

        def body(i, carry):
            S, T1, T2, t = carry
            S, T1, T2 = self._step(S, T1, T2)
            return S, T1, T2, t + self.dt

        S, T1, T2, t = lax.fori_loop(0, num_iters, body, (S, T1, T2, t))
        return self.extract(S), t
