"""Fully-fused SSP-RK3 Burgers/WENO stepping on a persistent padded state.

Serves WENO5-JS/Z (halo 3) and WENO7-JS (halo 4, forward-difference
betas ``ops.weno._weno7_side_nd_e``; reference ground truth
``Matlab_Prototipes/InviscidBurgersNd/WENO7resAdv_X.m:60-148``) with one
kernel family — the stencil radius ``r`` parameterizes the layout and
DMA discipline, the sweep helpers dispatch on ``order``.

The reference's hot loop launches, per RK stage, three direction-sweep
kernels (``Compute_dF/dG/dH``), an optional Laplacian, and an RK-update
kernel, each streaming the full state through device memory
(``SingleGPU/Burgers3d_WENO5/main.cpp:143-149``,
``MultiGPU/Burgers3d_Baseline/main.c:201-301``). The generic JAX path here
mirrors that structure (pad → per-axis WENO divergence → sum → axpy) and
is far below the VPU roof because XLA materializes the split fluxes and
interface fluxes between fusions.

This module collapses each RK stage to ONE Pallas kernel over a 2-D
``(z, y)`` block grid: a ``(bz+2r, by+16, X)`` box of the state is DMA'd
into VMEM and all three WENO flux divergences, the viscous Laplacian
(when ``nu > 0``), and the RK stage combination are evaluated in VMEM
before the block's core cells are written back. The kernel is VPU-bound,
so the design minimizes *arithmetic*, not just traffic:

* z- and y-direction sweeps are value *slices* of the VMEM box (both
  carry their halo in the box), so only the x sweep pays for circular
  shifts (``pltpu.roll`` on the lane axis).
* WENO reconstruction uses the forward-difference form
  (``ops.weno._weno5_side_nd``): shared first- and second-difference
  arrays replace 5-point stencil combinations, the nonlinear weights
  use the single-division formulation
  (``_weno5_alphas_unnormalized``), and the one division per
  reconstruction is a Newton-refined reciprocal (``_recip``).
* Small z-blocks made the old 1-D-grid kernel recompute the z-direction
  interface fluxes ~2x and the split fluxes ~7x; the (bz, by) blocking
  brings both overheads to ~1.1-2x.

Layout and ghost discipline:

* The state lives in a *padded, tile-aligned* layout for the whole run:
  ``(nz+2r, 8+ny+8, round128(nx))`` — z carries exactly the r-row halo
  (the leading axis is untiled, any slice is legal), y carries an
  8-column margin on each side (ghosts in its inner r columns) because
  Mosaic requires sublane-axis DMA offsets to be 8-aligned, and x is
  **lane-aligned at 0 with NO stored ghosts**: x ghost columns are
  synthesized in VMEM at block-load time (edge replicas,
  ``WENO5resAdv_X.m:53``) into the buffer's slack lanes — or into a
  128-lane working tail when the interior fills its lane tiles — so
  every non-x operation and every HBM transfer runs at
  ``round128(nx)`` lanes instead of ``round128(nx+6)`` (at 512^3 that
  one tile is 20% of all traffic and VPU work). The x sweep's circular
  rolls read the ghosts at the wrap positions (last ``r`` lanes of the
  working width = left ghosts), exactly like the old inline layout.
  Consequence: the x axis must not be sharded in this layout (there
  are no stored x ghosts for a ppermute refresh to rewrite).
  **x-sharded meshes** instead construct the stepper with
  ``x_sharded=True``, which switches to a stored-x-ghost layout —
  interior at lane offset ``r``, ``round128(nx_local + 2r)`` stored
  lanes, ghost lanes maintained on the write side (edge replicas,
  correct at global walls) and rewritten by the between-stage ppermute
  refresh at shard edges. That accepts the extra lane tile the default
  layout avoids; the measured price and the comparison against the
  generic path's loss are in PARITY.md.
* Block (kz, ky) reads box ``[kz*bz, kz*bz+bz+2r) x [ky*by, ky*by+by+16)``
  (both starts/extents 8-aligned in y) and writes only its disjoint core
  box; edge blocks additionally write the adjacent ghost boxes with
  edge-replicated values. Disjoint writes keep the 2-slot DMA pipeline
  race-free. The (z-ghost x y-margin) corner boxes are never rewritten
  after the initial embed; no core output ever reads them. Lanes beyond
  ``nx`` hold garbage between stages (patched on every load).
* dt enters as a runtime SMEM scalar, so the same compiled stages serve
  fixed *and* adaptive dt — restoring the physically-correct CFL the
  reference hard-coded away (``MultiGPU/Burgers3d_Baseline/main.c:193``).
  The adaptive mode's ``max|f'(u)|`` is *emitted by the final stage
  kernel(s)* (folded across blocks in SMEM, x-slack lanes masked) and
  carried between steps — no HBM re-read; a ``lax.pmax`` on the emitted
  scalar serves sharded runs, and the split-overlap schedule's three
  final-stage calls each fold their own blocks (combined by two scalar
  maxes).
* Sharded mode (``global_shape`` != ``interior_shape``): the stages run
  shard-local inside ``shard_map`` with an SMEM global-offset operand
  (edge synthesis keyed on *global* coordinates), and the caller
  refreshes sharded-axis ghosts between stages
  (``parallel.halo.make_ghost_refresh`` with this stepper's
  ``core_offsets``) — the tuned kernel under the mesh, as the reference
  runs its tuned kernels under MPI (``main.c:189-303``).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.flux import Flux
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
    _STAGES,
    _shift,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    SUBLANE,
    compiler_params,
    interpret_mode,
    round_up,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.stepper_base import (
    FusedStepperBase,
)
from multigpu_advectiondiffusion_tpu.ops.weno import (
    HALO,
    _curv,
    _weno5_side_nd,
    _weno5_side_nd_e,
    _weno7_side_nd_e,
)

R = 3  # WENO5 stencil radius; WENO7 instances run with r = HALO[7] = 4
MARGIN = 8  # y-side margin: >= max stencil radius, multiple of the
#             (8) sublane tile — covers both orders


def _recip(x):
    """Newton-refined reciprocal: one hardware estimate plus one NR step
    (``r (2 - x r)``, ~3 VPU ops) instead of Mosaic's exact-divide
    chain. The NR step squares the estimate's relative error, landing
    within ~1 ulp of the exact quotient (measured against the XLA
    divide in the fused-vs-XLA parity tests); the kernels spend 6 of
    these per cell-stage, the single largest non-FMA item in the WENO
    op mix."""
    if interpret_mode():
        return 1.0 / x
    r = pl.reciprocal(x, approx=True)
    return r * (2.0 - x * r)


# Conservative VMEM budget for the per-block working set (physical VMEM
# is 128 MiB; the Mosaic scoped ceiling requested is 100 MiB).
_VMEM_BUDGET = 72 * 1024 * 1024


def _x_widths(lx: int, r: int = R, x_ghosts: bool = False):
    """``(px, W)``: stored lane width (interior only, lane-aligned at 0)
    and the x-sweep working width. The working buffer needs the ``r``
    right-ghost lanes after ``lx`` and ``r`` left-ghost lanes at its very
    end (read via circular wrap), disjoint — when the stored slack can't
    hold both, the sweep works on a 128-lane-extended value instead.

    ``x_ghosts`` selects the stored-x-ghost layout for x-sharded meshes:
    the interior sits at lane offset ``r`` with real ghost lanes on both
    sides (``round128(lx + 2r)`` stored lanes, no working tail — the
    sweeps read inline ghosts, nothing wraps). This buys the ppermute
    refresh an x slab to rewrite at the price of the extra lane tile the
    lane-aligned layout exists to avoid (measured in PARITY.md)."""
    if x_ghosts:
        px = round_up(lx + 2 * r, LANE)
        return px, px
    px = round_up(lx, LANE)
    return px, (px if px - lx >= 2 * r else px + LANE)


def _live_bytes(bz: int, by: int, lx: int, itemsize: int,
                r: int = R, order: int = 5,
                x_ghosts: bool = False) -> int:
    px, w = _x_widths(lx, r, x_ghosts)
    core = bz * by * px * itemsize
    slab = (bz + 2 * r) * (by + 2 * MARGIN) * w * itemsize  # one box @W
    # v double-buffered (2 slabs @W) + ghost-patched w + vp + vm (3
    # slabs @W) + u/res double-buffered (4 cores) + live core-sized
    # sweep intermediates (~14 for the 5-point sweeps; order 7 keeps 6
    # e-windows per side plus the beta partial products in flight)
    return 5 * slab + (18 if order == 5 else 24) * core


def _pick_blocks(nz, ny, lx, itemsize, r: int = R, order: int = 5,
                 x_ghosts: bool = False):
    """First viable block in measured-preference order.

    v5e, 512^3 (lane-aligned layout, roll-based y sweep), order 5:
    (8,64) 9491 MLUPS > (16,32) 9378 > (8,16)/(16,16) ~8877 > (16,64)
    8289 — beyond (8,64) the larger working set costs more in Mosaic
    scheduling than the halo amortization returns. Order 7 (halo 4, 6
    e-windows per sweep side live) peaks one size smaller — (8,32) 5247
    > (4,64) 5206 > (8,16) 5047 > (16,64) 5044 > (8,128) 4988 > (8,64)
    4553 (out/weno7_block_exp.py sweeps) — so its y preference leads
    with 32.
    """
    by_pref = (64, 128, 32, 16, 8) if order == 5 else (32, 64, 16, 128, 8)
    for by in by_pref:
        if ny % by:
            continue
        for bz in (8, 7, 6, 5, 4, 3, 2, 1):
            if nz % bz:
                continue
            if _live_bytes(bz, by, lx, itemsize, r, order,
                           x_ghosts) <= _VMEM_BUDGET:
                return (bz, by)
    return None


def _split(flux: Flux, v):
    """Local Lax–Friedrichs splitting ``f± = (f(v) ± |f'(v)| v)/2``
    (``WENO5resAdv_X.m:58-60``). For the Burgers flux the identity
    ``f± = t (t ± |v|)`` with ``t = v/2`` saves two full-box ops."""
    if flux.name == "burgers":
        t = 0.5 * v
        a = jnp.abs(v)
        return t * (t + a), t * (t - a)
    a = jnp.abs(flux.df(v))
    fu = flux.f(v)
    return 0.5 * (fu + a * v), 0.5 * (fu - a * v)


def _div_z(vp, vm, bz, by, inv_dx, variant, order=5, r=R, y0=MARGIN):
    """Flux divergence along z of the core box via slab row slices.

    Interface row ``s`` (0..bz) sits right of slab row ``r-1+s``; the
    minus window is vp rows ``s..s+2r-2`` (center ``s+r-1``), the plus
    window vm rows ``s+1..s+2r-1`` (center ``s+r``). For order 5 the
    betas' curvature terms are windows of one shared array per side
    (``_curv``); order 7 uses the e-form per window (its betas are
    quadratic forms of the same shared first-difference arrays). Row
    slices of the leading axis are free.

    ``y0``/``by`` select the output's y window (default: this module's
    margin-carrying core); the slab whole-run stepper
    (:mod:`fused_slab_run`) passes ``y0=0`` with the full padded width.
    """
    yc = slice(y0, y0 + by)
    p = vp[:, yc]
    m = vm[:, yc]
    ep = p[1:] - p[:-1]
    em = m[1:] - m[:-1]
    if order == 7:
        nm, dm = _weno7_side_nd_e(
            *(ep[j : j + bz + 1] for j in range(6)), "minus"
        )
        np_, dp = _weno7_side_nd_e(
            *(em[j + 1 : j + 2 + bz] for j in range(6)), "plus"
        )
    else:
        cp = _curv(ep[1:] - ep[:-1])
        cm = _curv(em[1:] - em[:-1])
        nm, dm = _weno5_side_nd(
            *(ep[j : j + bz + 1] for j in range(4)),
            *(cp[j : j + bz + 1] for j in range(3)),
            variant, "minus",
        )
        np_, dp = _weno5_side_nd(
            *(em[j + 1 : j + 2 + bz] for j in range(4)),
            *(cm[j + 1 : j + 2 + bz] for j in range(3)),
            variant, "plus",
        )
    h = (p[r - 1 : r + bz] + m[r : r + 1 + bz]) + (
        nm * _recip(dm) + np_ * _recip(dp)
    )
    return (h[1:] - h[:-1]) * inv_dx


def _div_y(vp, vm, bz, by, inv_dx, variant, order=5, r=R):
    """Flux divergence along y of the core box via sublane *rolls* over
    the full margin-carrying width.

    Measured on v5e (512^3): whole-array sublane rolls beat
    sublane-offset window slices by ~25% of the sweep — every slice at a
    non-tile offset lowers to a per-operand realignment through the same
    shift unit a roll uses once, and the extra margin-width ALU is free
    (the kernel is shift-bound, not FLOP-bound). Wrapped rows land only
    in margin columns, which the core output slice discards.
    """
    h = _div_roll(vp[r : r + bz], vm[r : r + bz], 1, inv_dx, variant,
                  order)
    return h[:, MARGIN : MARGIN + by]


def _div_roll(vp, vm, axis, inv_dx, variant, order=5):
    """Flux divergence along ``axis`` via circular shifts (e-form);
    wrapped positions land only in ghost/slack outputs, which the edge
    synthesis overwrites. Used for the lane (x) axis here and for both
    axes of the 2-D whole-run stepper (:mod:`fused_burgers2d`)."""
    ep = _shift(vp, 1, axis) - vp
    em = _shift(vm, 1, axis) - vm
    if order == 7:
        # 6 e-windows per side (shifts -3..+2 minus / -2..+3 plus); the
        # betas are ALU-only quadratic forms of the rolled windows
        nm, dm = _weno7_side_nd_e(
            *(_shift(ep, j - 3, axis) for j in range(6)), "minus"
        )
        np_, dp = _weno7_side_nd_e(
            *(_shift(em, j - 2, axis) for j in range(6)), "plus"
        )
    else:
        # curvature per-window (_weno5_side_nd_e): a shared cd array
        # would cost 4 extra rolls — the binding resource — while
        # recomputing from the already-rolled windows is ALU-only
        nm, dm = _weno5_side_nd_e(
            *(_shift(ep, j - 2, axis) for j in range(4)),
            variant, "minus",
        )
        np_, dp = _weno5_side_nd_e(
            *(_shift(em, j - 1, axis) for j in range(4)),
            variant, "plus",
        )
    h = (vp + _shift(vm, 1, axis)) + (nm * _recip(dm) + np_ * _recip(dp))
    return (h - _shift(h, -1, axis)) * inv_dx


def _div_x(vp, vm, inv_dx, variant, order=5):
    """Flux divergence along x (lanes) of the core box.

    Lane rolls, deliberately: routing this sweep through an in-VMEM
    transpose so the reconstruction runs on (cheaper) sublane rolls was
    built and measured at 512^3 — both as 3 transposes (vp/vm in,
    divergence out) and as 2 (v once, fluxes re-split in transposed
    space) — and ties the lane-roll rate to within 0.3% at the best
    block for each strategy: the transposes ride the same VPU permute
    unit and cost exactly the lane-vs-sublane premium they remove.
    Measured rejection table in PARITY.md."""
    return _div_roll(vp, vm, 2, inv_dx, variant, order)


def _laplacian(v, vc_w, bz, by, px, scales, r=R):
    """O4 Laplacian of the core box (radius 2 < r, fits the same halo).

    ``v`` is the px-wide box (z/y terms need no x ghosts); ``vc_w`` the
    W-wide core whose circular x shifts read the synthesized ghost lanes
    at the wrap positions, sliced back to ``px``. y terms roll the full
    margin-carrying rows and slice the (tile-aligned, free) core columns
    — same rolls-beat-realignments measurement as :func:`_div_y`."""
    yc = slice(MARGIN, MARGIN + by)
    vrows = v[r : r + bz]
    acc = None
    for axis in range(3):
        for j, c in enumerate(O4_COEFFS):
            coef = jnp.asarray(c * scales[axis], v.dtype)
            if axis == 0:
                term = v[j + r - 2 : j + r - 2 + bz, yc] * coef
            elif axis == 1:
                term = _shift(vrows, j - 2, 1)[:, yc] * coef
            else:
                term = _shift(vc_w, j - 2, 2)[:, :, :px] * coef
            acc = term if acc is None else acc + term
    return acc


def _stage_kernel(
    dt_ref,
    v_hbm,
    u_hbm,
    g_hbm,
    out_hbm,
    mx_ref,
    vs,
    us,
    res,
    gyres,
    gzres,
    macc,
    sem_v,
    sem_u,
    sem_w,
    sem_g,
    sem_gv,
    *,
    bz: int,
    by: int,
    n_bz: int,
    n_by: int,
    local_shape: Sequence[int],
    ly_eff: int,
    inv_dx: Sequence[float],
    nu_scales: Sequence[float] | None,
    flux: Flux,
    variant: str,
    a: float,
    b: float,
    order: int = 5,
    r: int = R,
    kz_base: int = 0,
    n_bz_grid: int | None = None,
    ghost_src: str | None = None,
    z_edge_writes: bool = True,
    x0: int = 0,
    x_ghosts: bool = False,
):
    """One (z, y) block of one RK stage, 2-slot double-buffered.

    The TPU grid is a sequential loop, so block ``k`` prefetches block
    ``k+1``'s box while it computes, and defers the wait on its core
    write until the slot is reused at ``k+2``. All core write boxes are
    disjoint (and disjoint from the edge-ghost boxes), so in-flight
    writes never alias prefetched reads; the in-place final stage reads
    its ``u`` box strictly before the overwriting DMA of the same block.

    Roles (the overlapped z-slab schedule splits one stage into three
    calls so XLA can run interior compute concurrently with the halo
    ppermute): ``kz_base`` offsets this call's z-blocks inside the slab,
    ``n_bz_grid`` is this call's z-grid extent (default: all blocks),
    ``ghost_src`` = ``"lo"``/``"hi"`` DMAs the ``r`` z-ghost rows of the box
    from the separate exchanged-slab operand ``g_hbm`` instead of the
    padded buffer (whose z-ghost rows are stale in split mode), and
    ``z_edge_writes=False`` skips the z edge-replica maintenance (split
    mode never reads buffer z-ghosts).

    ``mx_ref``/``macc`` (non-None only on the emitting final stage of
    adaptive runs): the kernel folds ``max|f'(rk)|`` over every block's
    interior lanes into an SMEM accumulator (the TPU grid is
    sequential) and emits it as a scalar output — the next step's CFL
    reduction without re-reading the state from HBM. Dead y-rounding
    columns are edge *replicas* of interior values, so including them
    cannot raise the max; x lanes beyond ``lx`` hold garbage and are
    masked out.
    """
    lz, ly, lx = local_shape
    px, w = _x_widths(lx, r, x_ghosts)
    if n_bz_grid is None:
        n_bz_grid = n_bz
    kz = pl.program_id(0) + kz_base  # absolute z-block index
    ky = pl.program_id(1)
    k = pl.program_id(0) * n_by + ky  # this call's linear block index
    n_blocks = n_bz_grid * n_by
    slot = lax.rem(k, jnp.asarray(2, k.dtype))
    nslot = lax.rem(k + 1, jnp.asarray(2, k.dtype))

    def boxes(j):
        nb = jnp.asarray(n_by, jnp.int32)
        j = jnp.asarray(j, jnp.int32)
        return (kz_base + lax.div(j, nb)) * bz, lax.rem(j, nb) * by

    def _xsl(dst):
        # the VMEM slot carries a working tail beyond the stored px
        # lanes when the interior fills its tiles (ghost synthesis
        # space) — DMAs fill only the stored lanes
        return dst if w == px else dst.at[:, :, pl.ds(0, px)]

    def copy_v(j, s):
        z0, y0 = boxes(j)
        ysl = pl.ds(pl.multiple_of(y0, SUBLANE), by + 2 * MARGIN)
        if ghost_src is None:
            return [
                pltpu.make_async_copy(
                    v_hbm.at[pl.ds(z0, bz + 2 * r), ysl],
                    _xsl(vs.at[s]),
                    sem_v.at[s],
                )
            ]
        if ghost_src == "lo":
            # bottom shard edge: z-ghost rows from the exchanged slab
            return [
                pltpu.make_async_copy(
                    g_hbm.at[:, ysl],
                    _xsl(vs.at[s, pl.ds(0, r)]),
                    sem_gv.at[s],
                ),
                pltpu.make_async_copy(
                    v_hbm.at[pl.ds(z0 + r, bz + r), ysl],
                    _xsl(vs.at[s, pl.ds(r, bz + r)]),
                    sem_v.at[s],
                ),
            ]
        # top shard edge
        return [
            pltpu.make_async_copy(
                v_hbm.at[pl.ds(z0, bz + r), ysl],
                _xsl(vs.at[s, pl.ds(0, bz + r)]),
                sem_v.at[s],
            ),
            pltpu.make_async_copy(
                g_hbm.at[:, ysl],
                _xsl(vs.at[s, pl.ds(bz + r, r)]),
                sem_gv.at[s],
            ),
        ]

    def copy_u(j, s):
        z0, y0 = boxes(j)
        src = u_hbm if u_hbm is not None else out_hbm
        return pltpu.make_async_copy(
            src.at[
                pl.ds(r + z0, bz),
                pl.ds(pl.multiple_of(MARGIN + y0, SUBLANE), by),
            ],
            us.at[s],
            sem_u.at[s],
        )

    def copy_w(j, s):
        z0, y0 = boxes(j)
        return pltpu.make_async_copy(
            res.at[s],
            out_hbm.at[
                pl.ds(r + z0, bz),
                pl.ds(pl.multiple_of(MARGIN + y0, SUBLANE), by),
            ],
            sem_w.at[s],
        )

    @pl.when(k == 0)
    def _():
        for cp in copy_v(0, 0):
            cp.start()
        if us is not None:
            copy_u(0, 0).start()

    @pl.when(k + 1 < n_blocks)
    def _():
        for cp in copy_v(k + 1, nslot):
            cp.start()
        if us is not None:
            copy_u(k + 1, nslot).start()

    if us is not None:
        copy_u(k, slot).wait()
    for cp in copy_v(k, slot):
        cp.wait()

    # x ghost synthesis on the freshly-loaded box: the lane-aligned
    # stored layout carries no x ghosts, so patch the slack/tail lanes
    # with edge replicas (WENO5resAdv_X.m:53) — right ghosts right after
    # the interior at lanes lx..lx+r-1, left ghosts at the wrap positions
    # W-r..W-1 the circular x sweep reads. Replaces the old layout's
    # per-stage x edge rewrite on the store side; x is not sharded in
    # this layout, so local replication is correct in every world. The
    # stored-x-ghost layout (``x_ghosts``) needs no load-side patch: its
    # ghost lanes hold real values (write-side maintenance at global
    # walls, ppermute refresh at shard edges) and nothing wraps.
    v = vs[slot]
    if not x_ghosts:
        gxw = lax.broadcasted_iota(jnp.int32, v.shape, 2)
        v = jnp.where(gxw >= lx, v[:, :, lx - 1 : lx], v)
        v = jnp.where(gxw >= w - r, v[:, :, 0:1], v)

    vc = v[r : r + bz, MARGIN : MARGIN + by, :px]
    dtype = v.dtype
    dt = dt_ref[0].astype(dtype)

    # Split fluxes once over the whole box; each sweep slices what it
    # needs (z: rows, y: columns, x: lane shifts of the W-wide core —
    # only the x sweep sees the ghost tail, everything else runs at the
    # stored px lanes).
    vp, vm = _split(flux, v)
    rhs = -(
        _div_z(vp[:, :, :px], vm[:, :, :px], bz, by, inv_dx[0], variant,
               order, r)
        + _div_y(vp[:, :, :px], vm[:, :, :px], bz, by, inv_dx[1], variant,
                 order, r)
        + _div_x(
            vp[r : r + bz, MARGIN : MARGIN + by],
            vm[r : r + bz, MARGIN : MARGIN + by],
            inv_dx[2],
            variant,
            order,
        )[:, :, :px]
    )
    if nu_scales is not None:
        rhs = rhs + _laplacian(
            v[:, :, :px], v[r : r + bz, MARGIN : MARGIN + by], bz, by, px,
            nu_scales, r,
        )

    rk = b * (vc + dt * rhs) if a == 0.0 else a * us[slot] + b * (vc + dt * rhs)
    rk = rk.astype(dtype)

    if ly_eff != ly:
        # y-rounding margin: core columns >= ly are dead — refill them
        # with the edge replica of the last interior column (they serve
        # as that column's y-sweep ghosts next stage). Dead columns live
        # only in the last y-block, where column ly-1 sits at this static
        # local index; other blocks' masks are all-false.
        gy = lax.broadcasted_iota(jnp.int32, rk.shape, 1) + ky * by
        edge = (ly - 1) - (n_by - 1) * by
        rk = jnp.where(gy >= ly, rk[:, edge : edge + 1], rk)

    if x_ghosts:
        # stored-x-ghost maintenance: ghost and slack lanes get the edge
        # replica of the boundary interior lane — correct at global x
        # walls (edge BC, WENO5resAdv_X.m:53); at interior shard edges
        # the ppermute refresh overwrites the inner r ghost lanes before
        # the next stage reads them. The x analog of the y-margin
        # rewrite above, done in-register instead of by edge-block DMAs
        # because every block owns its full lane extent.
        gx = lax.broadcasted_iota(jnp.int32, rk.shape, 2)
        rk = jnp.where(gx < x0, rk[:, :, x0 : x0 + 1], rk)
        rk = jnp.where(gx >= x0 + lx, rk[:, :, x0 + lx - 1 : x0 + lx], rk)

    if mx_ref is not None:
        gxc = lax.broadcasted_iota(jnp.int32, rk.shape, 2)
        m = jnp.max(
            jnp.where(
                (gxc >= x0) & (gxc < x0 + lx),
                jnp.abs(flux.df(rk)),
                jnp.zeros_like(rk),
            )
        ).astype(jnp.float32)

        @pl.when(k == 0)
        def _():
            macc[0] = m

        @pl.when(k > 0)
        def _():
            macc[0] = jnp.maximum(macc[0], m)

        @pl.when(k == n_blocks - 1)
        def _():
            mx_ref[0] = macc[0]

    @pl.when(k >= 2)
    def _():
        copy_w(k - 2, slot).wait()

    res[slot] = rk
    copy_w(k, slot).start()

    z0, y0 = boxes(k)

    # y ghost+margin boxes: written by the shard-edge y-blocks with the
    # edge-replicated core column (meaningful only at *global* edges —
    # elsewhere the refresh overwrites the inner ``r`` ghost columns).
    @pl.when(ky == 0)
    def _():
        gyres[:] = jnp.broadcast_to(res[slot][:, 0:1], gyres.shape)
        cp = pltpu.make_async_copy(
            gyres, out_hbm.at[pl.ds(r + z0, bz), pl.ds(0, MARGIN)], sem_g
        )
        cp.start()
        cp.wait()

    @pl.when(ky == n_by - 1)
    def _():
        gyres[:] = jnp.broadcast_to(res[slot][:, by - 1 : by], gyres.shape)
        cp = pltpu.make_async_copy(
            gyres,
            out_hbm.at[
                pl.ds(r + z0, bz),
                pl.ds(pl.multiple_of(MARGIN + ly_eff, SUBLANE), MARGIN),
            ],
            sem_g,
        )
        cp.start()
        cp.wait()

    # z ghost rows: replicate the new boundary interior row (edge BC).
    # Skipped in the split-overlap schedule, which never reads buffer
    # z-ghosts (they ride the exchanged-slab operands instead).
    if z_edge_writes:
        @pl.when(kz == 0)
        def _():
            gzres[:] = jnp.broadcast_to(res[slot][0:1], gzres.shape)
            cp = pltpu.make_async_copy(
                gzres,
                out_hbm.at[
                    pl.ds(0, r),
                    pl.ds(pl.multiple_of(MARGIN + y0, SUBLANE), by),
                ],
                sem_g,
            )
            cp.start()
            cp.wait()

        @pl.when(kz == n_bz - 1)
        def _():
            gzres[:] = jnp.broadcast_to(res[slot][bz - 1 : bz], gzres.shape)
            cp = pltpu.make_async_copy(
                gzres,
                out_hbm.at[
                    pl.ds(r + lz, r),
                    pl.ds(pl.multiple_of(MARGIN + y0, SUBLANE), by),
                ],
                sem_g,
            )
            cp.start()
            cp.wait()

    @pl.when(k == n_blocks - 1)
    def _():
        copy_w(k, slot).wait()
        if n_blocks >= 2:
            copy_w(k - 1, nslot).wait()


def _make_stage(padded_shape, local_shape, dtype, *, bz, by, inv_dx,
                nu_scales, flux, variant, a, b, u_source, role=None,
                emit_max=False, order=5, r=R, x0=0, x_ghosts=False):
    """One fused RK-stage call; output aliased onto the last operand.

    ``u_source``: ``"none"`` / ``"operand"`` / ``"target"`` (in-place
    final stage), as in ``fused_diffusion._make_stage``. Operands:
    ``dt (SMEM (1,))`` [+ ``u``] [+ exchanged ghost slab for
    ``bottom``/``top`` roles] + target. The default ``"full"`` role
    serves sharded mode with the serialized between-stage refresh;
    ``"interior"``/``"bottom"``/``"top"`` are the three calls of the
    overlapped z-slab schedule (see :func:`_stage_kernel`).

    ``emit_max`` (final stage of adaptive runs, "full" role only): the
    call additionally returns the SMEM scalar ``max|f'(u_next)|`` folded
    across all blocks — the next step's CFL input without an HBM
    re-read.
    """
    lz = local_shape[0]
    ly_eff = padded_shape[1] - 2 * MARGIN  # ly rounded up to by multiple
    trailing = padded_shape[2:]
    px, w = _x_widths(local_shape[2], r, x_ghosts)
    assert trailing == (px,), (trailing, px)
    use_u = u_source != "none"
    n_bz, n_by = lz // bz, ly_eff // by

    role = role or "full"
    if role == "full":
        kz_base, n_bz_grid, ghost_src, z_edge = 0, n_bz, None, True
    elif role == "interior":
        kz_base, n_bz_grid, ghost_src, z_edge = 1, n_bz - 2, None, False
    elif role == "bottom":
        kz_base, n_bz_grid, ghost_src, z_edge = 0, 1, "lo", False
    elif role == "top":
        kz_base, n_bz_grid, ghost_src, z_edge = n_bz - 1, 1, "hi", False
    else:
        raise ValueError(f"unknown stage role {role!r}")
    use_g = ghost_src is not None

    kern = functools.partial(
        _stage_kernel,
        bz=bz,
        by=by,
        n_bz=n_bz,
        n_by=n_by,
        local_shape=tuple(local_shape),
        ly_eff=ly_eff,
        inv_dx=tuple(inv_dx),
        nu_scales=None if nu_scales is None else tuple(nu_scales),
        flux=flux,
        variant=variant,
        a=a,
        b=b,
        order=order,
        r=r,
        kz_base=kz_base,
        n_bz_grid=n_bz_grid,
        ghost_src=ghost_src,
        z_edge_writes=z_edge,
        x0=x0,
        x_ghosts=x_ghosts,
    )

    def kernel(*refs):
        dt_ref, *refs = refs
        g_hbm, sem_gv = None, None
        if u_source == "operand":
            v_hbm, u_hbm, *refs = refs
        else:
            v_hbm, *refs = refs
            u_hbm = None  # "target": read from out_hbm (in place)
        if use_g:
            g_hbm, *refs = refs
        _tgt, out_hbm, *refs = refs
        if emit_max:
            mx_ref, *refs = refs
        else:
            mx_ref = None
        vs, *refs = refs
        if use_u:
            us, *refs = refs
        else:
            us = None
        res, gyres, gzres, *refs = refs
        if emit_max:
            macc, *refs = refs
        else:
            macc = None
        sem_v, *refs = refs
        if use_u:
            sem_u, *refs = refs
        else:
            sem_u = None
        sem_w, sem_g, *refs = refs
        if use_g:
            (sem_gv,) = refs
        kern(dt_ref, v_hbm, u_hbm, g_hbm, out_hbm, mx_ref, vs, us, res,
             gyres, gzres, macc, sem_v, sem_u, sem_w, sem_g, sem_gv)

    n_in = 1 + (2 if u_source == "operand" else 1) + (1 if use_g else 0) + 1
    yb = by + 2 * MARGIN
    # the v slot is W-wide (ghost-synthesis tail); cores/ghost boxes px
    scratch = [pltpu.VMEM((2, bz + 2 * r, yb, w), dtype)]
    if use_u:
        scratch.append(pltpu.VMEM((2, bz, by) + trailing, dtype))
    scratch.append(pltpu.VMEM((2, bz, by) + trailing, dtype))
    scratch.append(pltpu.VMEM((bz, MARGIN) + trailing, dtype))
    scratch.append(pltpu.VMEM((r, by) + trailing, dtype))
    if emit_max:
        scratch.append(pltpu.SMEM((1,), jnp.float32))
    scratch.append(pltpu.SemaphoreType.DMA((2,)))
    if use_u:
        scratch.append(pltpu.SemaphoreType.DMA((2,)))
    scratch.append(pltpu.SemaphoreType.DMA((2,)))
    scratch.append(pltpu.SemaphoreType.DMA)
    if use_g:
        scratch.append(pltpu.SemaphoreType.DMA((2,)))

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * (n_in - 1)

    out_specs = pl.BlockSpec(memory_space=pl.ANY)
    out_shape = jax.ShapeDtypeStruct(tuple(padded_shape), dtype)
    if emit_max:
        out_specs = (out_specs, pl.BlockSpec(memory_space=pltpu.SMEM))
        out_shape = (out_shape, jax.ShapeDtypeStruct((1,), jnp.float32))

    return pl.pallas_call(
        kernel,
        grid=(n_bz_grid, n_by),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        input_output_aliases={n_in - 1: 0},  # last operand -> out
        compiler_params=None if interpret_mode() else compiler_params(),
        interpret=interpret_mode(),
    )


class FusedBurgersStepper(FusedStepperBase):
    """Jit-cached fused runner for one (grid, flux, dtype) config.

    ``dt`` fixes the step (CUDA-parity mode); ``dt_fn`` (a callable
    ``core_interior -> scalar``) enables adaptive CFL stepping — it runs
    between fused steps on a no-copy interior view of the padded state.
    Exactly one must be provided. ``global_shape`` switches to
    shard-local mode (see module docstring).
    """

    halo = R  # class default; instances set halo = HALO[order]
    # interior origin in the padded layout; x is lane-aligned at 0 (no
    # stored x ghosts) unless the instance runs the x-sharded layout,
    # which stores ghosts at lane offset r (instances overwrite this)
    core_offsets = (R, MARGIN, 0)

    def __init__(self, interior_shape, dtype, spacing, flux: Flux,
                 variant: str, nu: float, dt: float | None = None,
                 dt_fn=None, block=None, global_shape=None,
                 y_sharded: bool = False, overlap_split: bool = False,
                 dt_from_max=None, wave_fn=None, order: int = 5,
                 x_sharded: bool = False):
        if (dt is None) == (dt_fn is None):
            raise ValueError("provide exactly one of dt/dt_fn")
        if order not in HALO:
            raise ValueError(f"unsupported WENO order {order}")
        if order == 7 and variant != "js":
            raise ValueError("WENO7 supports only the 'js' variant")
        r = HALO[order]
        self.order = order
        self.halo = r
        self.stencil_radius = r  # WENO reach; ghosts refresh per stage
        # x-sharded meshes switch to the stored-x-ghost layout: interior
        # at lane offset r with real ghost lanes for the ppermute
        # refresh to rewrite (_x_widths docstring; priced in PARITY.md)
        self.x_sharded = bool(x_sharded)
        x0 = r if self.x_sharded else 0
        self.x0 = x0
        self.core_offsets = (r, MARGIN, x0)
        lz, ly, lx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.global_shape = tuple(global_shape or interior_shape)
        self.sharded = self.global_shape != self.interior_shape
        if y_sharded and ly % SUBLANE:
            # dead y-rounding columns inside a y-exchanged core would be
            # sent to neighbors as ghosts; a y-sharded axis keeps exact
            # tiling (z/x-only decompositions may still round y — their
            # exchanges never ship y columns as ghosts)
            raise ValueError(
                f"y-sharded fused Burgers needs ly % {SUBLANE} == 0, got {ly}"
            )
        ly_eff = round_up(ly, SUBLANE)
        self.padded_shape = (
            lz + 2 * r,
            ly_eff + 2 * MARGIN,
            _x_widths(lx, r, self.x_sharded)[0],
        )
        self.dtype = jnp.dtype(dtype)
        blk = block if block is not None else _pick_blocks(
            lz, ly_eff, lx, self.dtype.itemsize, r, order, self.x_sharded
        )
        if blk is None or lz % blk[0] or ly_eff % blk[1] or blk[1] % 8:
            raise ValueError(
                f"no viable (bz, by) block for interior {interior_shape}"
            )
        bz, by = blk
        inv_dx = [1.0 / spacing[i] for i in range(3)]
        nu_scales = None
        if nu:
            nu_scales = [
                float(nu) / (12.0 * spacing[i] * spacing[i]) for i in range(3)
            ]
        sources = ("none", "operand", "target")
        # The split-overlap z-slab schedule needs a strict interior band
        # (n_bz >= 3) AND bz >= r: with a thinner block, the first
        # interior-role block's box (padded rows [bz, ...)) would reach
        # into the z-ghost rows [0, r) that split mode never refreshes.
        # Otherwise fall back to the serialized refresh.
        self.overlap_split = bool(
            overlap_split and self.sharded and lz // bz >= 3 and bz >= r
        )
        # Adaptive mode emits max|f'(u_next)| from the final stage
        # kernel(s), replacing the between-step full-array reduction
        # (one whole HBM read per step). The split schedule's three
        # stage-3 calls each fold their own blocks; the step combines
        # the partials with two scalar maxes.
        self._emit_max = bool(
            dt_fn is not None
            and dt_from_max is not None
            and wave_fn is not None
        )
        self._dt_from_max = dt_from_max
        self._wave_fn = wave_fn

        def mk(role):
            return tuple(
                _make_stage(
                    self.padded_shape, self.interior_shape, self.dtype,
                    bz=bz, by=by, inv_dx=inv_dx, nu_scales=nu_scales,
                    flux=flux, variant=variant, a=a, b=b, u_source=src,
                    role=role, order=order, r=r, x0=x0,
                    x_ghosts=self.x_sharded,
                    # the final stage emits in every role: the split
                    # schedule's three calls each fold their own blocks
                    emit_max=(self._emit_max and src == "target"),
                )
                for (a, b), src in zip(_STAGES, sources)
            )

        self.dt = None if dt is None else float(dt)
        self._dt_fn = dt_fn
        self.block = (bz, by)

        if self.overlap_split:
            (s1i, s2i, s3i) = mk("interior")
            (s1b, s2b, s3b) = mk("bottom")
            (s1t, s2t, s3t) = mk("top")
            emitting = self._emit_max

            def step(S, T1, T2, dt_arr, offsets=None, refresh=None,
                     exch=None):
                # Each stage: start the z-halo ppermute of its input,
                # run the ghost-independent interior blocks concurrently
                # (XLA schedules them between collective-permute-start/
                # -done — only the two edge calls consume the exchanged
                # slabs), then finish the shard-edge blocks. The
                # reference overlaps its tuned kernel with MPI halo
                # traffic the same way, by z-partitioned streams
                # (MultiGPU/Diffusion3d_Baseline/main.c:203-260). On
                # pencil meshes ``refresh`` serializes the y ghosts on
                # each stage's composed output.
                del offsets  # no global wall masks here
                fix = refresh if refresh is not None else (lambda P: P)
                lo, hi = exch(S)
                T1 = fix(
                    s1t(dt_arr, S, hi, s1b(dt_arr, S, lo, s1i(dt_arr, S, T1)))
                )
                lo, hi = exch(T1)
                T2 = fix(s2t(dt_arr, T1, S, hi,
                             s2b(dt_arr, T1, S, lo, s2i(dt_arr, T1, S, T2))))
                lo, hi = exch(T2)
                if emitting:
                    Si, mi = s3i(dt_arr, T2, S)
                    Sb, mb = s3b(dt_arr, T2, lo, Si)
                    S, mt = s3t(dt_arr, T2, hi, Sb)
                    m = jnp.maximum(jnp.maximum(mi[0], mb[0]), mt[0])
                    return fix(S), T1, T2, m
                S = fix(
                    s3t(dt_arr, T2, hi, s3b(dt_arr, T2, lo, s3i(dt_arr, T2, S)))
                )
                return S, T1, T2

        else:
            s1, s2, s3 = mk("full")

            if self._emit_max:

                def step(S, T1, T2, dt_arr, offsets=None, refresh=None,
                         exch=None):
                    del offsets, exch  # no global wall masks here
                    fix = refresh if refresh is not None else (lambda P: P)
                    T1 = fix(s1(dt_arr, S, T1))
                    T2 = fix(s2(dt_arr, T1, S, T2))
                    S, mx = s3(dt_arr, T2, S)
                    return fix(S), T1, T2, mx[0]

            else:

                def step(S, T1, T2, dt_arr, offsets=None, refresh=None,
                         exch=None):
                    del offsets, exch  # no global wall masks here
                    fix = refresh if refresh is not None else (lambda P: P)
                    T1 = fix(s1(dt_arr, S, T1))
                    T2 = fix(s2(dt_arr, T1, S, T2))
                    S = fix(s3(dt_arr, T2, S))
                    return S, T1, T2

        self._step = step

    @staticmethod
    def supported(interior_shape, dtype, y_sharded: bool = False,
                  order: int = 5, x_sharded: bool = False) -> bool:
        lz, ly, lx = interior_shape
        if y_sharded and ly % SUBLANE:
            return False
        ly_eff = round_up(ly, SUBLANE)
        return (
            _pick_blocks(lz, ly_eff, lx, jnp.dtype(dtype).itemsize,
                         HALO[order], order, x_sharded)
            is not None
        )

    def embed(self, u):
        r = self.halo
        lz, ly, lx = self.interior_shape
        pz, py, px = self.padded_shape
        return jnp.pad(
            u.astype(self.dtype),
            ((r, pz - lz - r), (MARGIN, py - ly - MARGIN),
             (self.x0, px - lx - self.x0)),
            mode="edge",
        )

    def extract(self, S):
        r = self.halo
        lz, ly, lx = self.interior_shape
        return lax.slice(
            S, (r, MARGIN, self.x0), (r + lz, MARGIN + ly, self.x0 + lx)
        )

    def _dt_value(self, S):
        if self.dt is not None:
            return jnp.asarray(self.dt, jnp.float32)
        # no-copy interior view: XLA fuses the slice into the reduction
        return self._dt_fn(self.extract(S)).astype(jnp.float32)

    # run()/run_to() come from FusedStepperBase (the reference Burgers
    # drivers' native mode is run_to's `while (t < tEnd)`,
    # MultiGPU/Burgers3d_Baseline/main.c:190-317). ``offsets`` is
    # accepted there for interface parity and ignored by _step — edge
    # synthesis here needs no global coordinates.
