"""Fully-fused SSP-RK3 Burgers/WENO5 stepping on a persistent padded state.

The reference's hot loop launches, per RK stage, three direction-sweep
kernels (``Compute_dF/dG/dH``), an optional Laplacian, and an RK-update
kernel, each streaming the full state through device memory
(``SingleGPU/Burgers3d_WENO5/main.cpp:143-149``,
``MultiGPU/Burgers3d_Baseline/main.c:201-301``). The generic JAX path here
mirrors that structure (pad → per-axis WENO divergence → sum → axpy) and
is far below the VPU roof because XLA materializes the split fluxes and
interface fluxes between fusions.

This module collapses each RK stage to ONE Pallas kernel over a 2-D
``(z, y)`` block grid: a ``(bz+6, by+16, X)`` box of the state is DMA'd
into VMEM and all three WENO5 flux divergences, the viscous Laplacian
(when ``nu > 0``), and the RK stage combination are evaluated in VMEM
before the block's core cells are written back. The kernel is VPU-bound,
so the design minimizes *arithmetic*, not just traffic:

* z- and y-direction sweeps are value *slices* of the VMEM box (both
  carry their halo in the box), so only the x sweep pays for circular
  shifts (``pltpu.roll`` on the lane axis).
* WENO reconstruction uses the forward-difference form
  (``ops.weno._weno5_side_nd``): shared first- and second-difference
  arrays replace 5-point stencil combinations, the nonlinear weights
  use the single-division formulation
  (``_weno5_alphas_unnormalized``), and the one division per
  reconstruction is a Newton-refined reciprocal (``_recip``).
* Small z-blocks made the old 1-D-grid kernel recompute the z-direction
  interface fluxes ~2x and the split fluxes ~7x; the (bz, by) blocking
  brings both overheads to ~1.1-2x.

Layout and ghost discipline:

* The state lives in a *padded, tile-aligned* layout for the whole run:
  ``(nz+6, 8+ny+8, round128(nx+6))`` — z carries exactly the 3-row halo
  (the leading axis is untiled, any slice is legal), y carries an
  8-column margin on each side (ghosts in its inner 3 columns) because
  Mosaic requires sublane-axis DMA offsets to be 8-aligned, and x is
  lane-padded. All non-interior cells hold edge-replicated values (the
  reference's non-periodic ghost rule, ``WENO5resAdv_X.m:53``).
* Block (kz, ky) reads box ``[kz*bz, kz*bz+bz+6) x [ky*by, ky*by+by+16)``
  (both starts/extents 8-aligned in y) and writes only its disjoint core
  box; edge blocks additionally write the adjacent ghost boxes with
  edge-replicated values. Disjoint writes keep the 2-slot DMA pipeline
  race-free. The (z-ghost x y-margin) corner boxes are never rewritten
  after the initial embed; no core output ever reads them.
* dt enters as a runtime SMEM scalar, so the same compiled stages serve
  fixed *and* adaptive dt — the adaptive mode computes the global
  ``max|f'(u)|`` reduction (``lax.pmax`` across a mesh) between steps,
  restoring the physically-correct CFL the reference hard-coded away
  (``MultiGPU/Burgers3d_Baseline/main.c:193``).
* Sharded mode (``global_shape`` != ``interior_shape``): the stages run
  shard-local inside ``shard_map`` with an SMEM global-offset operand
  (edge synthesis keyed on *global* coordinates), and the caller
  refreshes sharded-axis ghosts between stages
  (``parallel.halo.make_ghost_refresh`` with this stepper's
  ``core_offsets``) — the tuned kernel under the mesh, as the reference
  runs its tuned kernels under MPI (``main.c:189-303``).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.flux import Flux
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
    _STAGES,
    _shift,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    SUBLANE,
    compiler_params,
    interpret_mode,
    round_up,
)
from multigpu_advectiondiffusion_tpu.ops.weno import _curv, _weno5_side_nd

R = 3  # WENO5 stencil radius == persistent ghost width
MARGIN = 8  # y-side margin: >= R, multiple of the (8) sublane tile


def _recip(x):
    """Newton-refined reciprocal: one hardware estimate plus one NR step
    (``r (2 - x r)``, ~3 VPU ops) instead of Mosaic's exact-divide
    chain. The NR step squares the estimate's relative error, landing
    within ~1 ulp of the exact quotient (measured against the XLA
    divide in the fused-vs-XLA parity tests); the kernels spend 6 of
    these per cell-stage, the single largest non-FMA item in the WENO
    op mix."""
    if interpret_mode():
        return 1.0 / x
    r = pl.reciprocal(x, approx=True)
    return r * (2.0 - x * r)


# Conservative VMEM budget for the per-block working set (physical VMEM
# is 128 MiB; the Mosaic scoped ceiling requested is 100 MiB).
_VMEM_BUDGET = 72 * 1024 * 1024


def _live_bytes(bz: int, by: int, x_pad: int, itemsize: int) -> int:
    col = x_pad * itemsize
    slab = (bz + 2 * R) * (by + 2 * MARGIN) * col  # one (z,y) box
    core = bz * by * col
    # v double-buffered (2) + vp + vm (2 slabs) + u/res double-buffered
    # (4 cores) + ~14 live core-sized sweep intermediates
    return 4 * slab + 18 * core


def _pick_blocks(nz, ny, x_pad, itemsize):
    """First viable block in measured-preference order.

    v5e, 512^3: (8,64) 6045 MLUPS > (4,64) 5903 > (8,128) 5580 >
    (16,64) 5292 — beyond (8,64) the larger working set costs more in
    Mosaic scheduling than the halo amortization returns.
    """
    for by in (64, 128, 32, 16, 8):
        if ny % by:
            continue
        for bz in (8, 7, 6, 5, 4, 3, 2, 1):
            if nz % bz:
                continue
            if _live_bytes(bz, by, x_pad, itemsize) <= _VMEM_BUDGET:
                return (bz, by)
    return None


def _split(flux: Flux, v):
    """Local Lax–Friedrichs splitting ``f± = (f(v) ± |f'(v)| v)/2``
    (``WENO5resAdv_X.m:58-60``)."""
    a = jnp.abs(flux.df(v))
    fu = flux.f(v)
    return 0.5 * (fu + a * v), 0.5 * (fu - a * v)


def _div_z(vp, vm, bz, by, inv_dx, variant):
    """Flux divergence along z of the core box via slab row slices.

    Interface row ``s`` (0..bz) sits right of slab row ``R-1+s``; the
    minus window is vp rows ``s..s+4`` (center ``s+2``), the plus window
    vm rows ``s+1..s+5`` (center ``s+3``). The betas' curvature terms
    are windows of one shared array per side (``_curv``); row slices of
    the leading axis are free.
    """
    yc = slice(MARGIN, MARGIN + by)
    p = vp[:, yc]
    m = vm[:, yc]
    ep = p[1:] - p[:-1]
    em = m[1:] - m[:-1]
    cp = _curv(ep[1:] - ep[:-1])
    cm = _curv(em[1:] - em[:-1])
    nm, dm = _weno5_side_nd(
        p[2 : 3 + bz],
        *(ep[j : j + bz + 1] for j in range(4)),
        *(cp[j : j + bz + 1] for j in range(3)),
        variant, "minus",
    )
    np_, dp = _weno5_side_nd(
        m[3 : 4 + bz],
        *(em[j + 1 : j + 2 + bz] for j in range(4)),
        *(cm[j + 1 : j + 2 + bz] for j in range(3)),
        variant, "plus",
    )
    h = nm * _recip(dm) + np_ * _recip(dp)
    return (h[1:] - h[:-1]) * inv_dx


def _div_y(vp, vm, bz, by, inv_dx, variant):
    """Flux divergence along y of the core box via sublane slices.

    Interface ``i`` (0..by) sits right of core column ``i-1`` (slab
    column ``MARGIN+i-1``); minus window columns ``MARGIN+i-3 ..
    MARGIN+i+1`` (center ``MARGIN+i-1``), plus window shifted by one.
    """
    p = vp[R : R + bz]
    m = vm[R : R + bz]
    ep = p[:, 1:] - p[:, :-1]
    em = m[:, 1:] - m[:, :-1]
    cp = _curv(ep[:, 1:] - ep[:, :-1])
    cm = _curv(em[:, 1:] - em[:, :-1])
    n = by + 1
    nm, dm = _weno5_side_nd(
        p[:, MARGIN - 1 : MARGIN + by],
        *(ep[:, MARGIN - 3 + j : MARGIN - 3 + j + n] for j in range(4)),
        *(cp[:, MARGIN - 3 + j : MARGIN - 3 + j + n] for j in range(3)),
        variant, "minus",
    )
    np_, dp = _weno5_side_nd(
        m[:, MARGIN : MARGIN + by + 1],
        *(em[:, MARGIN - 2 + j : MARGIN - 2 + j + n] for j in range(4)),
        *(cm[:, MARGIN - 2 + j : MARGIN - 2 + j + n] for j in range(3)),
        variant, "plus",
    )
    h = nm * _recip(dm) + np_ * _recip(dp)
    return (h[:, 1:] - h[:, :-1]) * inv_dx


def _div_roll(vp, vm, axis, inv_dx, variant):
    """Flux divergence along ``axis`` via circular shifts (e-form);
    wrapped positions land only in ghost/slack outputs, which the edge
    synthesis overwrites. Used for the lane (x) axis here and for both
    axes of the 2-D whole-run stepper (:mod:`fused_burgers2d`)."""
    ep = _shift(vp, 1, axis) - vp
    em = _shift(vm, 1, axis) - vm
    cp = _curv(_shift(ep, 1, axis) - ep)
    cm = _curv(_shift(em, 1, axis) - em)
    nm, dm = _weno5_side_nd(
        vp,
        *(_shift(ep, j - 2, axis) for j in range(4)),
        *(_shift(cp, j - 2, axis) for j in range(3)),
        variant, "minus",
    )
    np_, dp = _weno5_side_nd(
        _shift(vm, 1, axis),
        *(_shift(em, j - 1, axis) for j in range(4)),
        *(_shift(cm, j - 1, axis) for j in range(3)),
        variant, "plus",
    )
    h = nm * _recip(dm) + np_ * _recip(dp)
    return (h - _shift(h, -1, axis)) * inv_dx


def _div_x(vp, vm, inv_dx, variant):
    """Flux divergence along x (lanes) of the core box."""
    return _div_roll(vp, vm, 2, inv_dx, variant)


def _laplacian(v, vc, bz, by, scales):
    """O4 Laplacian of the core box (radius 2 < R, fits the same halo)."""
    yc = slice(MARGIN, MARGIN + by)
    acc = None
    for axis in range(3):
        for j, c in enumerate(O4_COEFFS):
            coef = jnp.asarray(c * scales[axis], v.dtype)
            if axis == 0:
                term = v[j + 1 : j + 1 + bz, yc] * coef
            elif axis == 1:
                term = v[R : R + bz, MARGIN - 2 + j : MARGIN - 2 + j + by] * coef
            else:
                term = _shift(vc, j - 2, 2) * coef
            acc = term if acc is None else acc + term
    return acc


def _stage_kernel(
    dt_ref,
    v_hbm,
    u_hbm,
    out_hbm,
    vs,
    us,
    res,
    gyres,
    gzres,
    sem_v,
    sem_u,
    sem_w,
    sem_g,
    *,
    bz: int,
    by: int,
    n_bz: int,
    n_by: int,
    local_shape: Sequence[int],
    ly_eff: int,
    inv_dx: Sequence[float],
    nu_scales: Sequence[float] | None,
    flux: Flux,
    variant: str,
    a: float,
    b: float,
):
    """One (z, y) block of one RK stage, 2-slot double-buffered.

    The TPU grid is a sequential loop, so block ``k`` prefetches block
    ``k+1``'s box while it computes, and defers the wait on its core
    write until the slot is reused at ``k+2``. All core write boxes are
    disjoint (and disjoint from the edge-ghost boxes), so in-flight
    writes never alias prefetched reads; the in-place final stage reads
    its ``u`` box strictly before the overwriting DMA of the same block.
    """
    lz, ly, lx = local_shape
    kz = pl.program_id(0)
    ky = pl.program_id(1)
    k = kz * n_by + ky
    slot = lax.rem(k, jnp.asarray(2, k.dtype))
    nslot = lax.rem(k + 1, jnp.asarray(2, k.dtype))

    def boxes(j):
        nb = jnp.asarray(n_by, jnp.int32)
        j = jnp.asarray(j, jnp.int32)
        return lax.div(j, nb) * bz, lax.rem(j, nb) * by

    def copy_v(j, s):
        z0, y0 = boxes(j)
        return pltpu.make_async_copy(
            v_hbm.at[
                pl.ds(z0, bz + 2 * R),
                pl.ds(pl.multiple_of(y0, SUBLANE), by + 2 * MARGIN),
            ],
            vs.at[s],
            sem_v.at[s],
        )

    def copy_u(j, s):
        z0, y0 = boxes(j)
        src = u_hbm if u_hbm is not None else out_hbm
        return pltpu.make_async_copy(
            src.at[
                pl.ds(R + z0, bz),
                pl.ds(pl.multiple_of(MARGIN + y0, SUBLANE), by),
            ],
            us.at[s],
            sem_u.at[s],
        )

    def copy_w(j, s):
        z0, y0 = boxes(j)
        return pltpu.make_async_copy(
            res.at[s],
            out_hbm.at[
                pl.ds(R + z0, bz),
                pl.ds(pl.multiple_of(MARGIN + y0, SUBLANE), by),
            ],
            sem_w.at[s],
        )

    @pl.when(k == 0)
    def _():
        copy_v(0, 0).start()
        if us is not None:
            copy_u(0, 0).start()

    @pl.when(k + 1 < n_bz * n_by)
    def _():
        copy_v(k + 1, nslot).start()
        if us is not None:
            copy_u(k + 1, nslot).start()

    if us is not None:
        copy_u(k, slot).wait()
    copy_v(k, slot).wait()

    v = vs[slot]
    vc = v[R : R + bz, MARGIN : MARGIN + by]
    dtype = v.dtype
    dt = dt_ref[0].astype(dtype)

    # Split fluxes once over the whole box; each sweep slices what it
    # needs (z: rows, y: columns, x: lane shifts of the core).
    vp, vm = _split(flux, v)
    rhs = -(
        _div_z(vp, vm, bz, by, inv_dx[0], variant)
        + _div_y(vp, vm, bz, by, inv_dx[1], variant)
        + _div_x(
            vp[R : R + bz, MARGIN : MARGIN + by],
            vm[R : R + bz, MARGIN : MARGIN + by],
            inv_dx[2],
            variant,
        )
    )
    if nu_scales is not None:
        rhs = rhs + _laplacian(v, vc, bz, by, nu_scales)

    rk = b * (vc + dt * rhs) if a == 0.0 else a * us[slot] + b * (vc + dt * rhs)
    rk = rk.astype(dtype)

    # x edge synthesis on every block (all blocks span the full lane
    # width): replicate the local edge interior column into ghost and
    # slack lanes (WENO5resAdv_X.m:53). At global edges the local edge
    # IS the global edge; at internal shard edges the between-stage
    # ghost refresh overwrites these lanes, so the fill value there is
    # irrelevant — local replication is correct in every world.
    gx = lax.broadcasted_iota(jnp.int32, rk.shape, 2) - R
    rk = jnp.where(gx < 0, rk[:, :, R : R + 1], rk)
    rk = jnp.where(gx >= lx, rk[:, :, R + lx - 1 : R + lx], rk)

    if ly_eff != ly:
        # y-rounding margin: core columns >= ly are dead — refill them
        # with the edge replica of the last interior column (they serve
        # as that column's y-sweep ghosts next stage). Dead columns live
        # only in the last y-block, where column ly-1 sits at this static
        # local index; other blocks' masks are all-false.
        gy = lax.broadcasted_iota(jnp.int32, rk.shape, 1) + ky * by
        edge = (ly - 1) - (n_by - 1) * by
        rk = jnp.where(gy >= ly, rk[:, edge : edge + 1], rk)

    @pl.when(k >= 2)
    def _():
        copy_w(k - 2, slot).wait()

    res[slot] = rk
    copy_w(k, slot).start()

    z0, y0 = boxes(k)

    # y ghost+margin boxes: written by the shard-edge y-blocks with the
    # edge-replicated core column (meaningful only at *global* edges —
    # elsewhere the refresh overwrites the inner R ghost columns).
    @pl.when(ky == 0)
    def _():
        gyres[:] = jnp.broadcast_to(res[slot][:, 0:1], gyres.shape)
        cp = pltpu.make_async_copy(
            gyres, out_hbm.at[pl.ds(R + z0, bz), pl.ds(0, MARGIN)], sem_g
        )
        cp.start()
        cp.wait()

    @pl.when(ky == n_by - 1)
    def _():
        gyres[:] = jnp.broadcast_to(res[slot][:, by - 1 : by], gyres.shape)
        cp = pltpu.make_async_copy(
            gyres,
            out_hbm.at[
                pl.ds(R + z0, bz),
                pl.ds(pl.multiple_of(MARGIN + ly_eff, SUBLANE), MARGIN),
            ],
            sem_g,
        )
        cp.start()
        cp.wait()

    # z ghost rows: replicate the new boundary interior row (edge BC).
    @pl.when(kz == 0)
    def _():
        gzres[:] = jnp.broadcast_to(res[slot][0:1], gzres.shape)
        cp = pltpu.make_async_copy(
            gzres,
            out_hbm.at[
                pl.ds(0, R),
                pl.ds(pl.multiple_of(MARGIN + y0, SUBLANE), by),
            ],
            sem_g,
        )
        cp.start()
        cp.wait()

    @pl.when(kz == n_bz - 1)
    def _():
        gzres[:] = jnp.broadcast_to(res[slot][bz - 1 : bz], gzres.shape)
        cp = pltpu.make_async_copy(
            gzres,
            out_hbm.at[
                pl.ds(R + lz, R),
                pl.ds(pl.multiple_of(MARGIN + y0, SUBLANE), by),
            ],
            sem_g,
        )
        cp.start()
        cp.wait()

    @pl.when(k == n_bz * n_by - 1)
    def _():
        copy_w(k, slot).wait()
        if n_bz * n_by >= 2:
            copy_w(k - 1, nslot).wait()


def _make_stage(padded_shape, local_shape, dtype, *, bz, by, inv_dx,
                nu_scales, flux, variant, a, b, u_source):
    """One fused RK-stage call; output aliased onto the last operand.

    ``u_source``: ``"none"`` / ``"operand"`` / ``"target"`` (in-place
    final stage), as in ``fused_diffusion._make_stage``. Operands:
    ``dt (SMEM (1,))`` + arrays. The same stage serves sharded mode
    unchanged — edge synthesis is local replication, and the caller's
    between-stage refresh fixes non-global shard edges.
    """
    lz = local_shape[0]
    ly_eff = padded_shape[1] - 2 * MARGIN  # ly rounded up to by multiple
    trailing = padded_shape[2:]
    use_u = u_source != "none"
    n_bz, n_by = lz // bz, ly_eff // by

    kern = functools.partial(
        _stage_kernel,
        bz=bz,
        by=by,
        n_bz=n_bz,
        n_by=n_by,
        local_shape=tuple(local_shape),
        ly_eff=ly_eff,
        inv_dx=tuple(inv_dx),
        nu_scales=None if nu_scales is None else tuple(nu_scales),
        flux=flux,
        variant=variant,
        a=a,
        b=b,
    )

    def kernel(*refs):
        dt_ref, *refs = refs
        if u_source == "operand":
            (v_hbm, u_hbm, _tgt, out_hbm, vs, us, res, gyres, gzres,
             sem_v, sem_u, sem_w, sem_g) = refs
        elif u_source == "target":
            (v_hbm, _tgt, out_hbm, vs, us, res, gyres, gzres,
             sem_v, sem_u, sem_w, sem_g) = refs
            u_hbm = None  # read from out_hbm (in place)
        else:
            (v_hbm, _tgt, out_hbm, vs, res, gyres, gzres,
             sem_v, sem_w, sem_g) = refs
            u_hbm, us, sem_u = None, None, None
        kern(dt_ref, v_hbm, u_hbm, out_hbm, vs, us, res,
             gyres, gzres, sem_v, sem_u, sem_w, sem_g)

    n_in = (3 if u_source == "operand" else 2) + 1
    yb = by + 2 * MARGIN
    scratch = [pltpu.VMEM((2, bz + 2 * R, yb) + trailing, dtype)]
    if use_u:
        scratch.append(pltpu.VMEM((2, bz, by) + trailing, dtype))
    scratch.append(pltpu.VMEM((2, bz, by) + trailing, dtype))
    scratch.append(pltpu.VMEM((bz, MARGIN) + trailing, dtype))
    scratch.append(pltpu.VMEM((R, by) + trailing, dtype))
    scratch.append(pltpu.SemaphoreType.DMA((2,)))
    if use_u:
        scratch.append(pltpu.SemaphoreType.DMA((2,)))
    scratch.append(pltpu.SemaphoreType.DMA((2,)))
    scratch.append(pltpu.SemaphoreType.DMA)

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * (n_in - 1)

    return pl.pallas_call(
        kernel,
        grid=(n_bz, n_by),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(tuple(padded_shape), dtype),
        scratch_shapes=scratch,
        input_output_aliases={n_in - 1: 0},  # last operand -> out
        compiler_params=None if interpret_mode() else compiler_params(),
        interpret=interpret_mode(),
    )


class FusedBurgersStepper:
    """Jit-cached fused runner for one (grid, flux, dtype) config.

    ``dt`` fixes the step (CUDA-parity mode); ``dt_fn`` (a callable
    ``core_interior -> scalar``) enables adaptive CFL stepping — it runs
    between fused steps on a no-copy interior view of the padded state.
    Exactly one must be provided. ``global_shape`` switches to
    shard-local mode (see module docstring).
    """

    halo = R
    core_offsets = (R, MARGIN, R)  # interior origin in the padded layout

    def __init__(self, interior_shape, dtype, spacing, flux: Flux,
                 variant: str, nu: float, dt: float | None = None,
                 dt_fn=None, block=None, global_shape=None,
                 y_sharded: bool = False):
        if (dt is None) == (dt_fn is None):
            raise ValueError("provide exactly one of dt/dt_fn")
        lz, ly, lx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.global_shape = tuple(global_shape or interior_shape)
        self.sharded = self.global_shape != self.interior_shape
        if y_sharded and ly % SUBLANE:
            # dead y-rounding columns inside a y-exchanged core would be
            # sent to neighbors as ghosts; a y-sharded axis keeps exact
            # tiling (z/x-only decompositions may still round y — their
            # exchanges never ship y columns as ghosts)
            raise ValueError(
                f"y-sharded fused Burgers needs ly % {SUBLANE} == 0, got {ly}"
            )
        ly_eff = round_up(ly, SUBLANE)
        self.padded_shape = (
            lz + 2 * R,
            ly_eff + 2 * MARGIN,
            round_up(lx + 2 * R, LANE),
        )
        self.dtype = jnp.dtype(dtype)
        blk = block if block is not None else _pick_blocks(
            lz, ly_eff, self.padded_shape[2], self.dtype.itemsize
        )
        if blk is None or lz % blk[0] or ly_eff % blk[1] or blk[1] % 8:
            raise ValueError(
                f"no viable (bz, by) block for interior {interior_shape}"
            )
        bz, by = blk
        inv_dx = [1.0 / spacing[i] for i in range(3)]
        nu_scales = None
        if nu:
            nu_scales = [
                float(nu) / (12.0 * spacing[i] * spacing[i]) for i in range(3)
            ]
        sources = ("none", "operand", "target")
        s1, s2, s3 = (
            _make_stage(
                self.padded_shape, self.interior_shape, self.dtype,
                bz=bz, by=by, inv_dx=inv_dx, nu_scales=nu_scales,
                flux=flux, variant=variant, a=a, b=b, u_source=src,
            )
            for (a, b), src in zip(_STAGES, sources)
        )
        self.dt = None if dt is None else float(dt)
        self._dt_fn = dt_fn
        self.block = (bz, by)

        def step(S, T1, T2, dt_arr, refresh=None):
            fix = refresh if refresh is not None else (lambda P: P)
            T1 = fix(s1(dt_arr, S, T1))
            T2 = fix(s2(dt_arr, T1, S, T2))
            S = fix(s3(dt_arr, T2, S))
            return S, T1, T2

        self._step = step

    @staticmethod
    def supported(interior_shape, dtype, y_sharded: bool = False) -> bool:
        lz, ly, lx = interior_shape
        if y_sharded and ly % SUBLANE:
            return False
        ly_eff = round_up(ly, SUBLANE)
        x_pad = round_up(lx + 2 * R, LANE)
        return (
            _pick_blocks(lz, ly_eff, x_pad, jnp.dtype(dtype).itemsize)
            is not None
        )

    def embed(self, u):
        lz, ly, lx = self.interior_shape
        pz, py, px = self.padded_shape
        return jnp.pad(
            u.astype(self.dtype),
            ((R, pz - lz - R), (MARGIN, py - ly - MARGIN), (R, px - lx - R)),
            mode="edge",
        )

    def extract(self, S):
        lz, ly, lx = self.interior_shape
        return lax.slice(
            S, (R, MARGIN, R), (R + lz, MARGIN + ly, R + lx)
        )

    def _dt_value(self, S):
        if self.dt is not None:
            return jnp.asarray(self.dt, jnp.float32)
        # no-copy interior view: XLA fuses the slice into the reduction
        return self._dt_fn(self.extract(S)).astype(jnp.float32)

    def run(self, u, t, num_iters: int, refresh=None, offsets=None):
        """``num_iters`` fused SSP-RK3 steps; returns ``(u, t)``.

        Sharded mode (must run inside ``shard_map``): ``refresh`` rewrites
        the padded buffers' sharded-axis ghosts after every stage.
        ``offsets`` is accepted for interface parity with the diffusion
        stepper and unused — edge synthesis here needs no global
        coordinates (local replication + refresh cover every world).
        """
        del offsets
        if self.sharded and refresh is None:
            raise ValueError("sharded fused stepper needs a ghost refresh")
        S = self.embed(u)
        if refresh is not None:
            S = refresh(S)
        T1 = S
        T2 = S

        def body(i, carry):
            S, T1, T2, t = carry
            dt = self._dt_value(S)
            S, T1, T2 = self._step(S, T1, T2, dt.reshape(1), refresh=refresh)
            return S, T1, T2, t + dt.astype(t.dtype)

        S, T1, T2, t = lax.fori_loop(0, num_iters, body, (S, T1, T2, t))
        return self.extract(S), t

    def run_to(self, u, t, t_end, refresh=None, offsets=None):
        """March fused steps until ``t_end``; returns ``(u, t, steps)``.

        The reference Burgers drivers' *native* execution mode — ``while
        (t < tEnd)`` over the tuned kernels with the final step trimmed
        (``MultiGPU/Burgers3d_Baseline/main.c:190-317``,
        ``SingleGPU/Burgers3d_WENO5/main.cpp:127-150``) — at the fused
        stepper's speed: dt is already a runtime SMEM scalar, so the same
        compiled stages serve the trimmed last step. Termination and
        trimming mirror :meth:`SolverBase.advance_to` exactly (same eps
        guard), so step counts and trajectories match the generic path.
        """
        del offsets
        if self.sharded and refresh is None:
            raise ValueError("sharded fused stepper needs a ghost refresh")
        S = self.embed(u)
        if refresh is not None:
            S = refresh(S)
        te = jnp.asarray(t_end, t.dtype)
        eps = 1e-12 * jnp.maximum(1.0, jnp.abs(te))

        def cond(carry):
            return carry[3] < te - eps

        def body(carry):
            S, T1, T2, t, it = carry
            dt = jnp.minimum(
                self._dt_value(S), (te - t).astype(jnp.float32)
            )
            S, T1, T2 = self._step(S, T1, T2, dt.reshape(1), refresh=refresh)
            return S, T1, T2, t + dt.astype(t.dtype), it + 1

        S, T1, T2, t, steps = lax.while_loop(
            cond, body, (S, S, S, t, jnp.zeros((), jnp.int32))
        )
        return self.extract(S), t, steps
