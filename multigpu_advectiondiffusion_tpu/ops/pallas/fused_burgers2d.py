"""Whole-run VMEM-resident SSP-RK3 stepping for 2-D Burgers/WENO.

Serves WENO5-JS/Z (halo 3) and WENO7-JS (halo 4) with the same in-core
sweeps — the order parameterizes the ghost width and the e-window count
(``fused_burgers._div_roll``), mirroring the 3-D family
(``LFWENO7FDM2d.m`` is the reference ground truth for order 7).

Same design as :mod:`fused_diffusion2d`: a reference-scale 2-D grid
(400×406, ``MultiGPU/Burgers2d_Baseline/Run.m``) is under 1 MB in f32,
so the padded state is loaded into VMEM once, every WENO sweep of every
RK stage of every iteration runs in-core, and the result is written back
once. The reference launches 2 sweep kernels + an RK kernel per stage
per iteration, each streaming the state through device memory
(``Burgers2d_Baseline/Kernels.cu``); here a 200-iteration run does two
HBM transfers total.

Ghost discipline follows :mod:`fused_burgers`: all non-interior cells
hold edge-replicated values (``WENO5resAdv_X.m:53``), re-synthesized
from the freshly computed interior after every stage; stencil reads are
masked circular shifts.

dt modes: fixed (CUDA-parity, ``main.c:193``) or adaptive — the global
``max|f'(u)|`` reduction runs *in-core* before every step
(``whole_run_adaptive``): because every ghost/slack cell is an edge
replica of an interior value, the reduction over the full padded array
equals the interior reduction, so no masking is needed
(``LFWENO5FDM2d.m:71``).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

from multigpu_advectiondiffusion_tpu.ops.flux import Flux
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
    _div_roll,
    _split,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import _shift
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    SUBLANE,
    round_up,
)

R = 3  # WENO5 stencil radius == ghost width; order 7 runs with halo 4

# WENO keeps many more live full-array temporaries than the Laplacian
# (vp/vm, 10 shifted operands, betas, weights, interface fluxes);
# order 7 holds 6 e-windows per side plus the quadratic-form partials.
_VMEM_BUDGET = 64 * 1024 * 1024
_LIVE_BUFFERS = 24
_LIVE_BUFFERS_W7 = 30


def _edge_fill_2d(rk, ny, nx, r=R):
    """Edge-replicate every non-interior cell (corners/slack included)."""
    gy = lax.broadcasted_iota(jnp.int32, rk.shape, 0) - r
    gx = lax.broadcasted_iota(jnp.int32, rk.shape, 1) - r
    t = jnp.where(gx < 0, rk[:, r : r + 1], rk)
    t = jnp.where(gx >= nx, t[:, r + nx - 1 : r + nx], t)
    t = jnp.where(gy < 0, t[r : r + 1, :], t)
    return jnp.where(gy >= ny, t[r + ny - 1 : r + ny, :], t)


def _laplacian_2d(v, scales):
    acc = None
    for axis in range(2):
        for j, c in enumerate(O4_COEFFS):
            term = _shift(v, j - 2, axis) * jnp.asarray(c * scales[axis], v.dtype)
            acc = term if acc is None else acc + term
    return acc


def _stage(u, v, *, interior_shape, inv_dx, nu_scales, flux, variant, a, b,
           dt, order=5, r=R):
    """One RK stage over the full padded array, ghosts re-synthesized.
    ``dt`` is a trace-time float (fixed mode) or a traced in-core scalar
    (adaptive mode, bound per-iteration by ``whole_run_adaptive``)."""
    ny, nx = interior_shape
    vp, vm = _split(flux, v)
    rhs = -(
        _div_roll(vp, vm, 0, inv_dx[0], variant, order)
        + _div_roll(vp, vm, 1, inv_dx[1], variant, order)
    )
    if nu_scales is not None:
        rhs = rhs + _laplacian_2d(v, nu_scales)
    dt = jnp.asarray(dt, v.dtype)
    rk = b * (v + dt * rhs) if a == 0.0 else a * u + b * (v + dt * rhs)
    return _edge_fill_2d(rk.astype(v.dtype), ny, nx, r)


class FusedBurgers2DStepper:
    """Jit-cached whole-run VMEM stepper for one (grid, flux) config.

    Exactly one of ``dt`` (fixed, CUDA-parity) / ``dt_fn`` (adaptive —
    called on the padded in-core state before every step) must be given,
    mirroring :class:`fused_burgers.FusedBurgersStepper`."""

    engaged_label = "fused-whole-run"

    def stencil_spec(self) -> dict:
        """Stencil metadata (analysis/halo_verify.py): whole-run VMEM
        residency with an ``r``-deep edge-resynthesized pad — no
        exchange, single-chip only."""
        return {
            "kernel": self.engaged_label,
            "stage_radius": int(self.halo),
            "fused_stages": 1,
            "ghost_depth": int(self.halo),
            "exchange_depth": None,
            "steps_per_exchange": 1,
            "storage_dtype": str(jnp.dtype(self.dtype)),
            "bytes_per_cell": int(jnp.dtype(self.dtype).itemsize),
        }

    def __init__(self, interior_shape, dtype, spacing, flux: Flux,
                 variant: str, nu: float, dt: float | None = None,
                 dt_fn=None, order: int = 5):
        from multigpu_advectiondiffusion_tpu.ops.weno import HALO

        if (dt is None) == (dt_fn is None):
            raise ValueError("provide exactly one of dt/dt_fn")
        if order == 7 and variant != "js":
            raise ValueError("WENO7 supports only the 'js' variant")
        r = HALO[order]
        self.order = order
        self.halo = r
        ny, nx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.padded_shape = (
            round_up(ny + 2 * r, SUBLANE),
            round_up(nx + 2 * r, LANE),
        )
        self.dtype = jnp.dtype(dtype)
        nu_scales = None
        if nu:
            nu_scales = tuple(
                float(nu) / (12.0 * spacing[i] * spacing[i]) for i in range(2)
            )
        self._stage = functools.partial(
            _stage,
            interior_shape=self.interior_shape,
            inv_dx=tuple(1.0 / spacing[i] for i in range(2)),
            nu_scales=nu_scales,
            flux=flux,
            variant=variant,
            order=order,
            r=r,
        )
        self.dt = None if dt is None else float(dt)
        self._dt_fn = dt_fn

    @staticmethod
    def supported(interior_shape, dtype, order: int = 5) -> bool:
        from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
            fits_vmem,
        )
        from multigpu_advectiondiffusion_tpu.ops.weno import HALO

        return fits_vmem(
            interior_shape, HALO[order],
            _LIVE_BUFFERS if order == 5 else _LIVE_BUFFERS_W7,
            jnp.dtype(dtype).itemsize, budget=_VMEM_BUDGET,
        )

    def embed(self, u):
        r = self.halo
        ny, nx = self.interior_shape
        py, px = self.padded_shape
        return jnp.pad(
            u.astype(self.dtype),
            ((r, py - ny - r), (r, px - nx - r)),
            mode="edge",
        )

    def extract(self, S):
        r = self.halo
        ny, nx = self.interior_shape
        return lax.slice(S, (r, r), (r + ny, r + nx))

    def run(self, u, t, num_iters: int):
        from multigpu_advectiondiffusion_tpu.ops.pallas.whole_run import (
            accumulate_t,
            whole_run,
            whole_run_adaptive,
        )

        if num_iters == 0:
            return u, t
        if self.dt is not None:
            out = whole_run(
                functools.partial(self._stage, dt=self.dt),
                self.embed(u), num_iters,
            )
            return self.extract(out), accumulate_t(t, self.dt, num_iters)
        out, t_sum = whole_run_adaptive(
            self._stage, self.embed(u), num_iters, self._dt_fn
        )
        return self.extract(out), t + t_sum.astype(t.dtype)
