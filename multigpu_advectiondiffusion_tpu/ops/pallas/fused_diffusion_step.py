"""Whole-step (3-stage) fused SSP-RK3 diffusion kernel.

One Pallas pass per z-slab per *full time step*: the slab is read once
with a 6-row z-halo (2 rows per RK stage), all three stage combinations
are evaluated in-register on progressively narrowing row windows
(``bz+8`` → ``bz+4`` → ``bz``), and only the final rows are written.
This is temporal blocking over the RK stages — the redundant band
compute (12 extra rows per block) buys a drop in HBM traffic from ~8.6
array passes per step (3 stage reads + 3 writes + 2 ``u`` reads of the
per-stage pipeline in :mod:`fused_diffusion`) to ~(1 + (bz+12)/bz): the
``a*u`` terms of stages 2/3 come from the same slab, free.

Ghost discipline matches :mod:`fused_diffusion` (frozen Dirichlet
boundary band, ``reference_parity``), except the z ghosts are 8 rows
deep so the widest stage window of the first/last block stays in frozen
territory instead of needing clamped reads. Within a step, intermediate
stage values in the y/x ghost columns are re-frozen by the same
interior/face masks the per-stage kernel applies, at the stage's own
z-offset.

Buffers ping-pong at the step level: blocks write rows other blocks
still read, so the step cannot run in place; two padded buffers
alternate across ``lax.fori_loop`` iterations.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
    _STAGES,
    _shift,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    R,
    SUBLANE,
    VMEM_LIMIT,
    compiler_params,
    interpret_mode,
    pick_block,
    round_up,
)

ZGHOST = R + 3 * R  # 8: stage-3-deep window of the edge blocks


def _stage_rows(v, u, *, gz0, interior_shape, scales, a, b, dt, band,
                bc_value):
    """One RK combination over ``v``'s full y/x width; rows are a z-slab
    whose first row has global z index ``gz0``. Returns ``v.shape[0]-2R``
    rows. ``u`` supplies the ``a*u`` term on the output rows."""
    nz, ny, nx = interior_shape
    dtype = v.dtype
    out_rows = v.shape[0] - 2 * R
    vc = v[R : R + out_rows]

    acc = None
    for axis in range(3):
        for j, c in enumerate(O4_COEFFS):
            coef = jnp.asarray(c * scales[axis], dtype)
            term = (
                v[j : j + out_rows] if axis == 0 else _shift(vc, j - R, axis)
            ) * coef
            acc = term if acc is None else acc + term

    rk = b * (vc + dt * acc) if a == 0.0 else a * u + b * (vc + dt * acc)

    shp = vc.shape
    gz = lax.broadcasted_iota(jnp.int32, shp, 0) + gz0
    gy = lax.broadcasted_iota(jnp.int32, shp, 1) - R
    gx = lax.broadcasted_iota(jnp.int32, shp, 2) - R

    def between(g, n):
        return (g >= band) & (g < n - band)

    interior = between(gz, nz) & between(gy, ny) & between(gx, nx)
    face = (
        (gz == 0) | (gz == nz - 1)
        | (gy == 0) | (gy == ny - 1)
        | (gx == 0) | (gx == nx - 1)
    )
    frozen = jnp.where(face, jnp.asarray(bc_value, dtype), vc)
    return jnp.where(interior, rk, frozen)


def _step_kernel(v_hbm, _tgt, out_hbm, vs, res, sem_v, sem_w, *, bz: int,
                 n_blocks: int, interior_shape, scales, dt, band, bc_value):
    """One z-block of one FULL step, 2-slot double-buffered like
    ``fused_diffusion._stage_kernel`` (sequential grid; prefetch next
    slab, defer the write drain until the slot recycles)."""
    k = pl.program_id(0)
    slot = lax.rem(k, jnp.asarray(2, k.dtype))
    nslot = lax.rem(k + 1, jnp.asarray(2, k.dtype))
    halo = 3 * R  # 6 z-rows each side of the block's core rows

    def copy_v(j, s):
        # slab = padded rows [ZGHOST - halo + j*bz, +bz + 2*halo)
        return pltpu.make_async_copy(
            v_hbm.at[pl.ds((ZGHOST - halo) + j * bz, bz + 2 * halo)],
            vs.at[s], sem_v.at[s],
        )

    def copy_w(j, s):
        return pltpu.make_async_copy(
            res.at[s], out_hbm.at[pl.ds(ZGHOST + j * bz, bz)], sem_w.at[s]
        )

    @pl.when(k == 0)
    def _():
        copy_v(0, 0).start()

    @pl.when(k + 1 < n_blocks)
    def _():
        copy_v(k + 1, nslot).start()

    copy_v(k, slot).wait()
    v = vs[slot]

    stage = functools.partial(
        _stage_rows, interior_shape=tuple(interior_shape),
        scales=tuple(scales), dt=dt, band=band, bc_value=bc_value,
    )
    (a1, b1), (a2, b2), (a3, b3) = _STAGES
    base = k * bz - halo  # global z of slab row 0
    # stage windows narrow by 2R rows each: bz+8 -> bz+4 -> bz
    t1 = stage(v, None, gz0=base + R, a=a1, b=b1)
    t2 = stage(t1, v[2 * R : 2 * R + bz + 4], gz0=base + 2 * R, a=a2, b=b2)
    t3 = stage(t2, v[3 * R : 3 * R + bz], gz0=base + 3 * R, a=a3, b=b3)

    @pl.when(k >= 2)
    def _():
        copy_w(k - 2, slot).wait()

    res[slot] = t3
    copy_w(k, slot).start()

    @pl.when(k == n_blocks - 1)
    def _():
        copy_w(k, slot).wait()
        if n_blocks >= 2:
            copy_w(k - 1, nslot).wait()


class StepFusedDiffusionStepper:
    """Three RK stages per HBM pass; interface mirrors
    ``FusedDiffusionStepper`` (``embed``/``extract``/``run``)."""

    engaged_label = "fused-step"
    stencil_radius = R  # O4 Laplacian reach per stage
    fused_stages = 3  # whole-step temporal blocking: 3 stages per pass

    def stencil_spec(self) -> dict:
        """Stencil metadata (analysis/halo_verify.py): the z pad is
        ``ZGHOST = 4R`` (the 3-stage trapezoid's ``3R`` plus one extra
        ``R`` for the edge blocks' stage-3-deep windows); single-chip
        only, so there is no exchange depth to verify."""
        return {
            "kernel": self.engaged_label,
            "stage_radius": R,
            "fused_stages": 3,
            "ghost_depth": ZGHOST,
            "exchange_depth": None,
            "steps_per_exchange": 1,
            "storage_dtype": str(jnp.dtype(self.dtype)),
            "bytes_per_cell": int(jnp.dtype(self.dtype).itemsize),
        }

    def __init__(self, interior_shape, dtype, spacing, diffusivity, dt,
                 band, bc_value, block_z=None):
        nz, ny, nx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.padded_shape = (
            nz + 2 * ZGHOST,
            round_up(ny + 2 * R, SUBLANE),
            round_up(nx + 2 * R, LANE),
        )
        self.dtype = jnp.dtype(dtype)
        self.bc_value = float(bc_value)
        row_bytes = (
            self.padded_shape[1] * self.padded_shape[2] * self.dtype.itemsize
        )
        if block_z is None:
            # ~8 live row-sized buffers per block row + ~110 fixed rows
            # (double-buffered slab incl. 12-row halos, t1/t2 windows,
            # stencil temporaries); calibrate conservatively against the
            # shared scoped-VMEM ceiling.
            budget_rows = (VMEM_LIMIT // row_bytes - 110) // 8
            block_z = pick_block(nz, max(1, min(20, int(budget_rows))))
        if nz % block_z != 0:
            raise ValueError(f"block_z={block_z} must divide nz={nz}")
        self.block_z = block_z
        scales = [
            float(diffusivity[i]) / (12.0 * spacing[i] * spacing[i])
            for i in range(3)
        ]
        bz = block_z
        n_blocks = nz // bz

        kern = functools.partial(
            _step_kernel, bz=bz, n_blocks=n_blocks,
            interior_shape=self.interior_shape, scales=tuple(scales),
            dt=float(dt), band=band, bc_value=float(bc_value),
        )

        halo = 3 * R
        self._step_call = pl.pallas_call(
            kern,
            grid=(n_blocks,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            out_shape=jax.ShapeDtypeStruct(self.padded_shape, self.dtype),
            scratch_shapes=[
                pltpu.VMEM((2, bz + 2 * halo) + self.padded_shape[1:],
                           self.dtype),
                pltpu.VMEM((2, bz) + self.padded_shape[1:], self.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            input_output_aliases={1: 0},  # ping-pong target -> out
            compiler_params=None if interpret_mode() else compiler_params(),
            interpret=interpret_mode(),
        )
        self.dt = float(dt)

    def embed(self, u):
        full = jnp.full(self.padded_shape, self.bc_value, self.dtype)
        return lax.dynamic_update_slice(
            full, u.astype(self.dtype), (ZGHOST, R, R)
        )

    def extract(self, S):
        nz, ny, nx = self.interior_shape
        return lax.slice(
            S, (ZGHOST, R, R), (ZGHOST + nz, R + ny, R + nx)
        )

    def run(self, u, t, num_iters: int):
        S = self.embed(u)
        T = S

        def body(i, carry):
            S, T, t = carry
            T = self._step_call(S, T)
            return T, S, t + self.dt

        S, T, t = lax.fori_loop(0, num_iters, body, (S, T, t))
        return self.extract(S), t
