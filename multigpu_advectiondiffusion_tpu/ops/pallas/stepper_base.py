"""Shared execution driver for the fused per-stage steppers.

Both 3-D fused steppers (:mod:`fused_diffusion`, :mod:`fused_burgers`)
expose the same two execution modes over their per-stage kernels:

* :meth:`run` — fixed-count `lax.fori_loop` (the CUDA drivers'
  ``max_iters`` mode, ``MultiGPU/Diffusion3d_Baseline/main.c:189``);
* :meth:`run_to` — ``while t < t_end`` with the last step trimmed (the
  Burgers drivers' and MATLAB heat drivers' *native* mode,
  ``MultiGPU/Burgers3d_Baseline/main.c:190-317``, ``heat3d.m:48-77``),
  at full fused speed because dt enters the stage kernels as a runtime
  SMEM scalar.

Termination and trimming mirror ``SolverBase.advance_to`` exactly (same
eps guard) — defined ONCE here so step counts and trajectories cannot
desynchronize between the generic and fused paths or between solvers.

Subclasses provide ``embed``/``extract``, ``_step(S, T1, T2, dt_arr,
offsets=, refresh=, exch=)``, ``_dt_value(S)`` (a traced f32 scalar —
constant for diffusion, the CFL reduction for adaptive Burgers), and the
``sharded``/``overlap_split`` flags; ``needs_offsets`` marks steppers
whose kernels take a global-offset SMEM operand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


class FusedStepperBase:
    needs_offsets = False
    engaged_label = "fused-stage"  # what engaged_path()/PrintSummary report

    def _dt_value(self, S):
        raise NotImplementedError

    def _check_sharded_args(self, refresh, offsets, exch):
        if not self.sharded:
            return
        if self.needs_offsets and offsets is None:
            raise ValueError("sharded fused stepper needs offsets")
        if self.overlap_split and exch is None:
            raise ValueError("split-overlap fused stepper needs exch")
        if not self.overlap_split and refresh is None:
            raise ValueError("sharded fused stepper needs a ghost refresh")
        if (
            self.overlap_split
            and refresh is None
            and any(
                g != l
                for g, l in zip(
                    self.global_shape[1:], self.interior_shape[1:]
                )
            )
        ):
            # pencil meshes: only the leading axis rides the exchanged
            # slabs — the other sharded axes' ghosts need the serialized
            # refresh, or they silently stay frozen at embed time
            raise ValueError(
                "pencil split-overlap stepper needs a ghost refresh for "
                "its non-leading sharded axes"
            )

    def run(self, u, t, num_iters: int, refresh=None, offsets=None,
            exch=None):
        """``num_iters`` fused SSP-RK3 steps; returns ``(u, t)``.

        Sharded mode (must run inside ``shard_map``): ``refresh``
        rewrites the padded buffers' sharded-axis ghosts after every RK
        stage — or, in split-overlap mode, ``exch`` produces the
        ``(lo, hi)`` exchanged z-slabs the stages consume as separate
        operands. ``offsets`` is this shard's int32 global-offset vector
        (consumed only by steppers with global wall masks).

        Steppers with ``_emit_max`` (adaptive Burgers) carry the
        stage-emitted ``max|f'(u)|`` scalar between steps instead of
        re-reading the state for the CFL reduction — ``_dt_from_max``
        must reproduce ``_dt_value`` exactly given the same max, so the
        two modes are trajectory-identical.
        """
        self._check_sharded_args(refresh, offsets, exch)
        S = self.embed(u)
        if refresh is not None:
            # non-split: full sharded-axis refresh of the fresh embed;
            # pencil split mode: the serialized (non-z) axes' refresh —
            # the z ghosts ride the exchanged-slab operands instead
            S = refresh(S)
        dt_of, step_of, m0 = self._loop_pieces(u, refresh, offsets, exch)

        def body(i, carry):
            S, T1, T2, t, m = carry
            # named_scope: the fused step body shows as one labeled
            # region per rung in --trace captures
            with jax.named_scope(f"tpucfd.{self.engaged_label}"):
                dt = dt_of(S, m)
                S, T1, T2, m = step_of(S, T1, T2, dt, m)
            return S, T1, T2, t + dt.astype(t.dtype), m

        S, T1, T2, t, _ = lax.fori_loop(0, num_iters, body, (S, S, S, t, m0))
        return self.extract(S), t

    def run_to(self, u, t, t_end, refresh=None, offsets=None, exch=None):
        """March fused steps until ``t_end``; returns ``(u, t, steps)``.

        The reference drivers' native ``while (t < tEnd)`` mode at the
        fused stepper's speed, with the final step trimmed through the
        runtime SMEM dt scalar.
        """
        self._check_sharded_args(refresh, offsets, exch)
        S = self.embed(u)
        if refresh is not None:
            # non-split: full sharded-axis refresh of the fresh embed;
            # pencil split mode: the serialized (non-z) axes' refresh —
            # the z ghosts ride the exchanged-slab operands instead
            S = refresh(S)
        te = jnp.asarray(t_end, t.dtype)
        eps = 1e-12 * jnp.maximum(1.0, jnp.abs(te))
        dt_of, step_of, m0 = self._loop_pieces(u, refresh, offsets, exch)

        def cond(carry):
            return carry[3] < te - eps

        def body(carry):
            S, T1, T2, t, it, m = carry
            with jax.named_scope(f"tpucfd.{self.engaged_label}"):
                dt = jnp.minimum(dt_of(S, m), (te - t).astype(jnp.float32))
                S, T1, T2, m = step_of(S, T1, T2, dt, m)
            return S, T1, T2, t + dt.astype(t.dtype), it + 1, m

        S, T1, T2, t, steps, _ = lax.while_loop(
            cond, body, (S, S, S, t, jnp.zeros((), jnp.int32), m0)
        )
        return self.extract(S), t, steps

    def _loop_pieces(self, u, refresh, offsets, exch):
        """``(dt_of(S, m), step_of(S, T1, T2, dt, m), m0)`` — the single
        place the dt source is chosen, so run()/run_to() each have ONE
        loop body and the trim/termination semantics cannot fork between
        the read-back and emit-max modes. Non-emitting steppers carry a
        dummy scalar ``m``."""
        emit = getattr(self, "_emit_max", False)
        m0 = (
            self._wave_fn(u).astype(jnp.float32)
            if emit
            else jnp.zeros((), jnp.float32)
        )

        def dt_of(S, m):
            return (
                self._dt_from_max(m).astype(jnp.float32)
                if emit
                else self._dt_value(S)
            )

        def step_of(S, T1, T2, dt, m):
            out = self._step(S, T1, T2, dt.reshape(1), offsets=offsets,
                             refresh=refresh, exch=exch)
            if emit:
                return out
            S, T1, T2 = out
            return S, T1, T2, m

        return dt_of, step_of, m0
