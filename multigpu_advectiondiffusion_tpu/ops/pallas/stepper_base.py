"""Shared execution driver for the fused per-stage steppers.

Both 3-D fused steppers (:mod:`fused_diffusion`, :mod:`fused_burgers`)
expose the same two execution modes over their per-stage kernels:

* :meth:`run` — fixed-count `lax.fori_loop` (the CUDA drivers'
  ``max_iters`` mode, ``MultiGPU/Diffusion3d_Baseline/main.c:189``);
* :meth:`run_to` — ``while t < t_end`` with the last step trimmed (the
  Burgers drivers' and MATLAB heat drivers' *native* mode,
  ``MultiGPU/Burgers3d_Baseline/main.c:190-317``, ``heat3d.m:48-77``),
  at full fused speed because dt enters the stage kernels as a runtime
  SMEM scalar.

Termination and trimming mirror ``SolverBase.advance_to`` exactly (same
eps guard) — defined ONCE here so step counts and trajectories cannot
desynchronize between the generic and fused paths or between solvers.

Subclasses provide ``embed``/``extract``, ``_step(S, T1, T2, dt_arr,
offsets=, refresh=, exch=)``, ``_dt_value(S)`` (a traced f32 scalar —
constant for diffusion, the CFL reduction for adaptive Burgers), and the
``sharded``/``overlap_split`` flags; ``needs_offsets`` marks steppers
whose kernels take a global-offset SMEM operand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def chunk_counts(num_iters: int, steps_per_exchange: int):
    """``(full_blocks, remainder)`` of the communication-avoiding k-step
    chunk schedule: ``full_blocks`` whole blocks of ``steps_per_exchange``
    steps (one deep halo exchange each) plus one partial block of
    ``remainder`` steps (which still pays a full-depth exchange — the
    per-run cost of a non-multiple iteration count, not a correctness
    issue: every block starts from a fully refreshed buffer). The ONE
    definition both the slab stepper's run loop and the telemetry
    byte-accounting use, so "exchanges per run" cannot fork between the
    executed schedule and the reported one."""
    if steps_per_exchange < 1:
        raise ValueError(
            f"steps_per_exchange must be >= 1, got {steps_per_exchange}"
        )
    return num_iters // steps_per_exchange, num_iters % steps_per_exchange


def _with_repeats(fn, repeats: int):
    """Bind the static telemetry ``repeats`` hint into a refresh/exch
    closure headed into a ``fori_loop`` body (trace-once, execute-N)."""
    if fn is None:
        return None
    return lambda P: fn(P, repeats=repeats)


class FusedStepperBase:
    needs_offsets = False
    engaged_label = "fused-stage"  # what engaged_path()/PrintSummary report
    #: per-stage stencil radius h, queryable metadata for the static
    #: halo verifier (analysis/halo_verify.py). None -> equals the
    #: per-refresh ghost depth ``halo`` (true for the per-stage family:
    #: ghosts refresh every RK stage at exactly the stencil radius)
    stencil_radius = None
    #: RK stages recomputed per ghost refresh (the trapezoid factor):
    #: 1 for the per-stage family; the whole-step/slab rungs override
    fused_stages = 1
    # communication-avoiding chunk length: the per-stage kernels bake
    # one stencil-halo refresh per RK stage into their dataflow, so the
    # per-stage family serves k=1 only — the k-step deep-halo schedule
    # lives on the slab whole-run rung (ops/pallas/fused_slab_run.py),
    # which overrides this per instance. Dispatch validates the knob
    # against the engaged rung (models/base.py) and fails loudly rather
    # than silently running the per-step cadence.
    steps_per_exchange = 1

    #: in-kernel remote-DMA exchange contract (ROADMAP item 2), or
    #: ``None`` (every shipped rung today: the exchange is an XLA
    #: ppermute between compiled calls). A rung that moves its ghost
    #: rows inside the Pallas program via ``pltpu.make_async_remote_
    #: copy`` declares ``{"axis": 0, "window_rows": k*G,
    #: "buffers": >=2}`` and the static halo verifier proves the
    #: declaration against the exchange arithmetic BEFORE any hardware
    #: run — where a schedule mismatch stops being a hang and becomes
    #: silent corruption (a neighbor push landing over rows the
    #: consumer already read).
    remote_dma = None

    def stencil_spec(self) -> dict:
        """Queryable stencil/halo metadata — the ``R = 3``-style radius
        constants promoted to a contract the static verifier
        (``analysis/halo_verify.py``) can prove consistent with the
        ghost/exchange/BlockSpec arithmetic. Keys: ``stage_radius`` (h,
        one stage's stencil reach), ``fused_stages`` (stages recomputed
        per ghost refresh), ``ghost_depth`` (rows refreshed per
        exchange site, ``>= fused_stages * h``), ``exchange_depth``
        (rows ppermuted per exchange, ``k * ghost_depth``; None for
        single-chip-only steppers), ``steps_per_exchange`` (k),
        ``remote_dma`` (the declared in-kernel exchange window, None
        while the exchange rides XLA collectives — see the class
        attribute), and the storage declaration (ISSUE 16):
        ``storage_dtype`` is the HBM-resident buffer dtype — the dtype
        every halo/DMA wire byte carries — and ``bytes_per_cell`` its
        itemsize, from which the verifier derives every declared byte
        count (f64-facing states run f32 buffers; ``precision='bf16'``
        runs bf16 buffers at 2 B/cell)."""
        h = int(self.stencil_radius or self.halo)
        buf = jnp.dtype(self.dtype)
        return {
            "kernel": self.engaged_label,
            "stage_radius": h,
            "fused_stages": int(self.fused_stages),
            "ghost_depth": int(self.halo),
            "exchange_depth": int(
                getattr(self, "exchange_depth", self.halo)
            ),
            "steps_per_exchange": int(
                getattr(self, "steps_per_exchange", 1) or 1
            ),
            "remote_dma": getattr(self, "remote_dma", None),
            "storage_dtype": str(buf),
            "bytes_per_cell": int(buf.itemsize),
        }

    def _dt_value(self, S):
        raise NotImplementedError

    def _check_sharded_args(self, refresh, offsets, exch):
        if not self.sharded:
            return
        if self.needs_offsets and offsets is None:
            raise ValueError("sharded fused stepper needs offsets")
        if self.overlap_split and exch is None:
            raise ValueError("split-overlap fused stepper needs exch")
        if not self.overlap_split and refresh is None:
            raise ValueError("sharded fused stepper needs a ghost refresh")
        if (
            self.overlap_split
            and refresh is None
            and any(
                g != l
                for g, l in zip(
                    self.global_shape[1:], self.interior_shape[1:]
                )
            )
        ):
            # pencil meshes: only the leading axis rides the exchanged
            # slabs — the other sharded axes' ghosts need the serialized
            # refresh, or they silently stay frozen at embed time
            raise ValueError(
                "pencil split-overlap stepper needs a ghost refresh for "
                "its non-leading sharded axes"
            )

    def run(self, u, t, num_iters: int, refresh=None, offsets=None,
            exch=None):
        """``num_iters`` fused SSP-RK3 steps; returns ``(u, t)``.

        Sharded mode (must run inside ``shard_map``): ``refresh``
        rewrites the padded buffers' sharded-axis ghosts after every RK
        stage — or, in split-overlap mode, ``exch`` produces the
        ``(lo, hi)`` exchanged z-slabs the stages consume as separate
        operands. ``offsets`` is this shard's int32 global-offset vector
        (consumed only by steppers with global wall masks).

        Steppers with ``_emit_max`` (adaptive Burgers) carry the
        stage-emitted ``max|f'(u)|`` scalar between steps instead of
        re-reading the state for the CFL reduction — ``_dt_from_max``
        must reproduce ``_dt_value`` exactly given the same max, so the
        two modes are trajectory-identical.
        """
        self._check_sharded_args(refresh, offsets, exch)
        S = self.embed(u)
        if refresh is not None:
            # non-split: full sharded-axis refresh of the fresh embed;
            # pencil split mode: the serialized (non-z) axes' refresh —
            # the z ghosts ride the exchanged-slab operands instead
            S = refresh(S)
        # exchanges inside the fori body trace ONCE but execute
        # num_iters times: bind the static count so the telemetry byte
        # counters report true bytes per compiled execution
        dt_of, step_of, m0 = self._loop_pieces(
            u, _with_repeats(refresh, num_iters), offsets,
            _with_repeats(exch, num_iters),
        )

        def body(i, carry):
            S, T1, T2, t, m = carry
            # named_scope: the fused step body shows as one labeled
            # region per rung in --trace captures
            with jax.named_scope(f"tpucfd.{self.engaged_label}"):
                dt = dt_of(S, m)
                S, T1, T2, m = step_of(S, T1, T2, dt, m)
            return S, T1, T2, t + dt.astype(t.dtype), m

        S, T1, T2, t, _ = lax.fori_loop(0, num_iters, body, (S, S, S, t, m0))
        return self.extract(S), t

    def run_to(self, u, t, t_end, refresh=None, offsets=None, exch=None):
        """March fused steps until ``t_end``; returns ``(u, t, steps)``.

        The reference drivers' native ``while (t < tEnd)`` mode at the
        fused stepper's speed, with the final step trimmed through the
        runtime SMEM dt scalar. (The halo telemetry counters record
        this mode's loop-resident exchange sites at ``repeats=1`` — a
        ``while_loop`` trip count is dynamic, so per-execution bytes
        are not statically knowable here; scale by the summary's step
        count instead.)
        """
        self._check_sharded_args(refresh, offsets, exch)
        S = self.embed(u)
        if refresh is not None:
            # non-split: full sharded-axis refresh of the fresh embed;
            # pencil split mode: the serialized (non-z) axes' refresh —
            # the z ghosts ride the exchanged-slab operands instead
            S = refresh(S)
        te = jnp.asarray(t_end, t.dtype)
        eps = 1e-12 * jnp.maximum(1.0, jnp.abs(te))
        dt_of, step_of, m0 = self._loop_pieces(u, refresh, offsets, exch)

        def cond(carry):
            return carry[3] < te - eps

        def body(carry):
            S, T1, T2, t, it, m = carry
            with jax.named_scope(f"tpucfd.{self.engaged_label}"):
                dt = jnp.minimum(dt_of(S, m), (te - t).astype(jnp.float32))
                S, T1, T2, m = step_of(S, T1, T2, dt, m)
            return S, T1, T2, t + dt.astype(t.dtype), it + 1, m

        S, T1, T2, t, steps, _ = lax.while_loop(
            cond, body, (S, S, S, t, jnp.zeros((), jnp.int32), m0)
        )
        return self.extract(S), t, steps

    def _loop_pieces(self, u, refresh, offsets, exch):
        """``(dt_of(S, m), step_of(S, T1, T2, dt, m), m0)`` — the single
        place the dt source is chosen, so run()/run_to() each have ONE
        loop body and the trim/termination semantics cannot fork between
        the read-back and emit-max modes. Non-emitting steppers carry a
        dummy scalar ``m``."""
        emit = getattr(self, "_emit_max", False)
        m0 = (
            self._wave_fn(u).astype(jnp.float32)
            if emit
            else jnp.zeros((), jnp.float32)
        )

        def dt_of(S, m):
            return (
                self._dt_from_max(m).astype(jnp.float32)
                if emit
                else self._dt_value(S)
            )

        def step_of(S, T1, T2, dt, m):
            out = self._step(S, T1, T2, dt.reshape(1), offsets=offsets,
                             refresh=refresh, exch=exch)
            if emit:
                return out
            S, T1, T2 = out
            return S, T1, T2, m

        return dt_of, step_of, m0
