"""Fully-fused SSP-RK3 diffusion stepping on a persistent padded state.

The reference's hot loop runs, per RK stage, a Laplacian kernel and an
RK-update kernel over HBM-resident arrays plus ghost-cell maintenance
(``MultiGPU/Diffusion3d_Baseline/main.c:189-303``). The generic JAX path
here mirrors that structure (pad → stencil → axpy → clamp as separate
XLA fusions), which costs several full-array HBM passes per stage.

This module collapses each RK stage to ONE Pallas kernel at minimum HBM
traffic (read stage input + read step input + write output, ~12 B/cell):

* The state lives in a *padded, tile-aligned* layout ``(nz+4, Y8, X128)``
  for the whole run; ghost cells are materialized once and then never
  rewritten — with ``reference_parity`` Dirichlet walls the RHS is zeroed
  on the 2-cell boundary band (``Laplace3d.m:21``), so boundary cells and
  ghosts are constant through every stage.
* Each stage kernel DMAs a z-slab (+2 halo rows), evaluates the 13-point
  Laplacian with in-slab value slices (z) and circular shifts (y/x —
  wraparound touches only masked ghost columns), applies the RK stage
  combination ``a*u + b*(v + dt*L(v))``, re-imposes the Dirichlet faces
  (``heat3d.m:65-67``), and writes only the core z-rows back — the
  output buffer is aliased to a dead input buffer whose ghost cells are
  already valid.
* Buffer choreography per step (three live padded buffers, zero allocs):
  ``T1 = stage1(S)``, ``T2 = stage2(T1, S)``, ``S' = stage3(T2, S) → S``.
  Stage 3 writes in place over ``S`` while reading it: each grid block
  reads its ``u`` rows strictly before writing them, and other blocks'
  reads are row-disjoint from its writes.

Sharded mode (``global_shape`` != ``interior_shape``): the same stage
kernels run shard-local inside ``shard_map`` — wall masks take this
shard's global offsets from an SMEM operand, and a per-stage ghost
refresh (``parallel.halo.make_ghost_refresh``) rewrites the sharded-axis
ghost slabs by ``ppermute`` between stages.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    R,
    SUBLANE,
    VMEM_LIMIT,
    _aligned_row_bytes_3d,
    compiler_params,
    interpret_mode,
    pick_block,
    round_up,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.stepper_base import (
    FusedStepperBase,
)

# SSP-RK3 stage combinations u_next = a*u + b*(v + dt*L(v))
# (Compute_RK, MultiGPU/Diffusion3d_Baseline/Kernels.cu:266-300)
_STAGES = ((0.0, 1.0), (0.75, 0.25), (1.0 / 3.0, 2.0 / 3.0))


def _shift(x, off: int, axis: int):
    """Full-width circular shift: result[i] = x[i + off] along ``axis``.

    Wraparound rows/columns land only in ghost/slack positions, whose
    outputs are masked back to the stage input. A zero shift returns
    ``x`` unchanged — Mosaic's roll lowering builds a zero-width slice
    for amount 0, which some toolchain versions reject.
    """
    n = x.shape[axis]
    if off % n == 0:
        return x
    if interpret_mode():
        return jnp.roll(x, -off, axis)
    return pltpu.roll(x, (-off) % n, axis)


def _stage_kernel(
    dt_ref,
    v_hbm,
    u_hbm,
    g_hbm,
    out_hbm,
    vs,
    us,
    res,
    sem_v,
    sem_u,
    sem_w,
    sem_gv,
    *,
    bz: int,
    n_blocks: int,
    global_shape: Sequence[int],
    offs_ref=None,
    scales: Sequence[float],
    a: float,
    b: float,
    band: int,
    bc_value: float,
    kz_base: int = 0,
    n_blocks_grid: int | None = None,
    ghost_src: str | None = None,
    compute_dtype=None,
):
    """One z-block of one RK stage, 2-slot double-buffered.

    The TPU grid is a sequential loop, so block ``k`` prefetches block
    ``k+1``'s slab (and ``u`` rows) while it computes, and defers the
    wait on its output DMA until the same slot is reused at ``k+2`` —
    reads, compute, and writes of consecutive blocks overlap. All row
    ranges of distinct blocks are disjoint, so the in-flight writes
    never alias the prefetched reads (the in-place final stage reads its
    ``u`` rows strictly before the overwriting DMA of the same block).

    ``dt`` is a runtime SMEM scalar, so the same compiled stages serve
    fixed-count runs AND the trimmed last step of ``run_to``. Roles for
    the overlapped z-slab schedule (as in :mod:`fused_burgers`):
    ``kz_base`` offsets this call's blocks, ``n_blocks_grid`` is this
    call's grid extent, and ``ghost_src`` = ``"lo"``/``"hi"`` DMAs the R
    z-ghost rows from the separately exchanged slab operand ``g_hbm``
    instead of the padded buffer (whose z-ghost rows are stale in split
    mode — frozen Dirichlet values are only correct at global edges).
    """
    nz, ny, nx = global_shape
    if n_blocks_grid is None:
        n_blocks_grid = n_blocks
    k = pl.program_id(0)  # this call's linear block index
    kz = k + kz_base  # absolute z-block index
    slot = lax.rem(k, jnp.asarray(2, k.dtype))
    nslot = lax.rem(k + 1, jnp.asarray(2, k.dtype))

    def copy_v(j, s):
        z0 = (j + kz_base) * bz
        if ghost_src is None:
            return [
                pltpu.make_async_copy(
                    v_hbm.at[pl.ds(z0, bz + 2 * R)], vs.at[s], sem_v.at[s]
                )
            ]
        if ghost_src == "lo":
            return [
                pltpu.make_async_copy(
                    g_hbm, vs.at[s, pl.ds(0, R)], sem_gv.at[s]
                ),
                pltpu.make_async_copy(
                    v_hbm.at[pl.ds(z0 + R, bz + R)],
                    vs.at[s, pl.ds(R, bz + R)],
                    sem_v.at[s],
                ),
            ]
        return [
            pltpu.make_async_copy(
                v_hbm.at[pl.ds(z0, bz + R)],
                vs.at[s, pl.ds(0, bz + R)],
                sem_v.at[s],
            ),
            pltpu.make_async_copy(
                g_hbm, vs.at[s, pl.ds(bz + R, R)], sem_gv.at[s]
            ),
        ]

    def copy_u(j, s):
        # u rows come from u_hbm — which for the in-place final stage is
        # the output buffer itself (read strictly before the overwrite;
        # other blocks' reads are row-disjoint from any in-flight write).
        src = u_hbm if u_hbm is not None else out_hbm
        return pltpu.make_async_copy(
            src.at[pl.ds(R + (j + kz_base) * bz, bz)], us.at[s], sem_u.at[s]
        )

    def copy_w(j, s):
        return pltpu.make_async_copy(
            res.at[s],
            out_hbm.at[pl.ds(R + (j + kz_base) * bz, bz)],
            sem_w.at[s],
        )

    @pl.when(k == 0)
    def _():
        for cp in copy_v(0, 0):
            cp.start()
        if us is not None:
            copy_u(0, 0).start()

    @pl.when(k + 1 < n_blocks_grid)
    def _():
        for cp in copy_v(k + 1, nslot):
            cp.start()
        if us is not None:
            copy_u(k + 1, nslot).start()

    if us is not None:
        copy_u(k, slot).wait()
    for cp in copy_v(k, slot):
        cp.wait()

    # bf16-storage rung: the state lives (and moves through HBM) at half
    # the bytes; arithmetic runs in ``compute_dtype`` (f32) so the RK
    # accumulation doesn't lose the stencil's cancellation digits
    stored = vs[slot]
    v = (
        stored
        if compute_dtype is None
        else stored.astype(jnp.dtype(compute_dtype))
    )
    vc = v[R : R + bz]  # stage input, core z-rows, full y/x width
    dtype = v.dtype
    dt = dt_ref[0].astype(dtype)

    # 13-point O4 Laplacian (z-term via slab rows, y/x via masked
    # circular shifts). Diffusivity is folded into each term's
    # coefficient, so the rounding differs from the XLA path's
    # per-axis-then-scale association by ~1 ulp per term.
    acc = None
    for axis in range(3):
        for j, c in enumerate(O4_COEFFS):
            coef = jnp.asarray(c * scales[axis], dtype)
            term = (v[j : j + bz] if axis == 0 else _shift(vc, j - R, axis)) * coef
            acc = term if acc is None else acc + term

    u_in = None if us is None else us[slot].astype(dtype)
    rk = (
        b * (vc + dt * acc)
        if a == 0.0
        else a * u_in + b * (vc + dt * acc)
    )

    # Global interior-cell indices of this block (ghost offset already
    # removed for z: the written rows are exactly the core rows). When
    # sharded, ``offs_ref`` holds this shard's global offsets so the
    # band/face tests keep using *global* coordinates (reference-parity
    # walls are global, Laplace3d.m:21 / heat3d.m:65-67).
    shp = vc.shape
    oz, oy, ox = (
        (offs_ref[0], offs_ref[1], offs_ref[2])
        if offs_ref is not None
        else (0, 0, 0)
    )
    gz = lax.broadcasted_iota(jnp.int32, shp, 0) + kz * bz + oz
    gy = lax.broadcasted_iota(jnp.int32, shp, 1) - R + oy
    gx = lax.broadcasted_iota(jnp.int32, shp, 2) - R + ox

    def between(g, n):
        return (g >= band) & (g < n - band)

    interior = between(gz, nz) & between(gy, ny) & between(gx, nx)
    face = (
        (gz == 0) | (gz == nz - 1)
        | (gy == 0) | (gy == ny - 1)
        | (gx == 0) | (gx == nx - 1)
    )
    frozen = jnp.where(face, jnp.asarray(bc_value, dtype), vc)

    # the res slot is recycled every other block: drain its previous
    # write before overwriting, then issue this block's write and leave
    # it in flight (drained at k+2, or below on the last blocks)
    @pl.when(k >= 2)
    def _():
        copy_w(k - 2, slot).wait()

    res[slot] = jnp.where(interior, rk, frozen).astype(stored.dtype)
    copy_w(k, slot).start()

    @pl.when(k == n_blocks_grid - 1)
    def _():
        copy_w(k, slot).wait()
        if n_blocks_grid >= 2:
            copy_w(k - 1, nslot).wait()


def _make_stage(padded_shape, interior_shape, dtype, *, bz, scales, a, b,
                band, bc_value, u_source, global_shape=None, sharded=False,
                role=None, compute_dtype=None):
    """Build one fused RK-stage call; output aliased onto the last operand.

    ``u_source``: where the step-input ``u`` (the ``a*u`` term) is read
    from — ``"none"`` (stage 1, a == 0), ``"operand"`` (separate input
    buffer), or ``"target"`` (the aliased output buffer itself, for the
    in-place final stage — avoids passing one buffer as two operands,
    which would force XLA to insert a defensive copy).

    ``role``: ``"full"`` (default) or the overlapped z-slab schedule's
    ``"interior"``/``"bottom"``/``"top"`` (see :func:`_stage_kernel`).

    ``sharded``: prepend an int32 ``(3,)`` SMEM operand carrying this
    shard's global offsets (the stage then runs shard-local inside
    ``shard_map``; ``global_shape`` is the global interior for the
    band/face tests).
    """
    trailing = padded_shape[1:]
    use_u = u_source != "none"
    # blocks cover the padded buffer's (possibly block-rounded) z extent;
    # dead tail rows beyond the real interior stay frozen via the masks
    n_blocks = (padded_shape[0] - 2 * R) // bz

    role = role or "full"
    if role == "full":
        kz_base, n_grid, ghost_src = 0, n_blocks, None
    elif role == "interior":
        kz_base, n_grid, ghost_src = 1, n_blocks - 2, None
    elif role == "bottom":
        kz_base, n_grid, ghost_src = 0, 1, "lo"
    elif role == "top":
        kz_base, n_grid, ghost_src = n_blocks - 1, 1, "hi"
    else:
        raise ValueError(f"unknown stage role {role!r}")
    use_g = ghost_src is not None

    kern = functools.partial(
        _stage_kernel,
        bz=bz,
        n_blocks=n_blocks,
        global_shape=tuple(global_shape or interior_shape),
        scales=tuple(scales),
        a=a,
        b=b,
        band=band,
        bc_value=bc_value,
        kz_base=kz_base,
        n_blocks_grid=n_grid,
        ghost_src=ghost_src,
        compute_dtype=compute_dtype,
    )

    def kernel(*refs):
        dt_ref, *refs = refs
        offs_ref, g_hbm, sem_gv = None, None, None
        if sharded:
            offs_ref, *refs = refs
        if u_source == "operand":
            v_hbm, u_hbm, *refs = refs
        else:
            v_hbm, *refs = refs
            u_hbm = None  # "target": read from out_hbm
        if use_g:
            g_hbm, *refs = refs
        _tgt, out_hbm, vs, *refs = refs
        if use_u:
            us, *refs = refs
        else:
            us = None
        res, sem_v, *refs = refs
        if use_u:
            sem_u, *refs = refs
        else:
            sem_u = None
        sem_w, *refs = refs
        if use_g:
            (sem_gv,) = refs
        kern(dt_ref, v_hbm, u_hbm, g_hbm, out_hbm, vs, us, res,
             sem_v, sem_u, sem_w, sem_gv, offs_ref=offs_ref)

    n_in = (
        1  # dt
        + (1 if sharded else 0)
        + (2 if u_source == "operand" else 1)
        + (1 if use_g else 0)
        + 1  # aliased target
    )
    scratch = [pltpu.VMEM((2, bz + 2 * R) + trailing, dtype)]
    if use_u:
        scratch.append(pltpu.VMEM((2, bz) + trailing, dtype))
    scratch.append(pltpu.VMEM((2, bz) + trailing, dtype))
    scratch.append(pltpu.SemaphoreType.DMA((2,)))
    if use_u:
        scratch.append(pltpu.SemaphoreType.DMA((2,)))
    scratch.append(pltpu.SemaphoreType.DMA((2,)))
    if use_g:
        scratch.append(pltpu.SemaphoreType.DMA((2,)))

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]  # dt
    in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * (n_in - 1)
    if sharded:
        in_specs[1] = pl.BlockSpec(memory_space=pltpu.SMEM)

    return pl.pallas_call(
        kernel,
        grid=(n_grid,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(tuple(padded_shape), dtype),
        scratch_shapes=scratch,
        input_output_aliases={n_in - 1: 0},  # last operand -> out
        compiler_params=None if interpret_mode() else compiler_params(),
        interpret=interpret_mode(),
    )


class FusedDiffusionStepper(FusedStepperBase):
    """Jit-cached fused runner for one (grid, dtype, dt) configuration.

    ``global_shape`` (when it differs from ``interior_shape``) switches
    the stages to shard-local mode: ``interior_shape`` is this shard's
    block, mask tests use global coordinates from a runtime offsets
    operand, and :meth:`run` accepts a per-stage ghost-``refresh``
    callback (``parallel.halo.make_ghost_refresh``). This is the tuned
    kernel running under the mesh — the reference's MultiGPU tier runs
    the same ``LaplaceO4_async`` kernel its single-GPU ladder tuned
    (``MultiGPU/Diffusion3d_Baseline/main.c:189-303``,
    ``Kernels.cu:207-261``).
    """

    halo = R
    stencil_radius = R  # O4 Laplacian reach; ghosts refresh per stage
    needs_offsets = True  # global wall masks take an offsets operand

    def __init__(self, interior_shape, dtype, spacing, diffusivity, dt,
                 band, bc_value, block_z=None, global_shape=None,
                 overlap_split: bool = False, storage_dtype=None):
        nz, ny, nx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.global_shape = tuple(global_shape or interior_shape)
        self.sharded = self.global_shape != self.interior_shape
        self.dtype = jnp.dtype(dtype)
        # f64-storage/f32-compute rung: the *state* stays f64 between
        # runs, the kernels (and every HBM-resident padded buffer) run
        # ``dtype`` — embed downcasts, extract restores (Mosaic has no
        # f64 vector path; accuracy is f32, priced in PARITY.md)
        self._storage = jnp.dtype(storage_dtype or dtype)
        # bf16-storage rung: state/DMA at 2 B/cell (the ref-grid row is
        # measured at 85-92% of HBM pin bandwidth — bytes are the only
        # remaining lever, PARITY.md), arithmetic in f32
        compute_dtype = (
            jnp.float32 if self.dtype == jnp.bfloat16 else None
        )
        self.bc_value = float(bc_value)
        row_bytes = _aligned_row_bytes_3d((nz, ny, nx), self.dtype.itemsize)
        # VMEM model calibrated on v5e at the bench grid (row =
        # 208*512*4 B): ~9 live row-sized buffers per block row plus ~56
        # rows of fixed overhead; bz=20 measured fastest, bz=32 exceeds
        # VMEM. Capped at the largest measured-safe block.
        budget_rows = max(1, min(20, int((VMEM_LIMIT // row_bytes - 56) // 9)))
        if block_z is None:
            if self.sharded:
                # sharded shards exchange their core rows — dead padding
                # rows inside the domain would corrupt neighbor ghosts,
                # so the block must divide the local extent exactly
                block_z = pick_block(nz, budget_rows)
            else:
                # unsharded: pad z up to a block multiple instead of
                # shrinking the block to a divisor (nz=206 would force
                # bz=2). Dead tail rows hold bc_value from embed() and
                # stay frozen (they are neither interior nor face in the
                # global-index masks), so interior cell nz-1 reads them
                # as the Dirichlet ghosts it needs. Score balances z-halo
                # amortization bz/(bz+2R) against wasted dead rows.
                def score(b):
                    blocks = -(-nz // b)
                    return (b / (b + 2 * R)) * (nz / (blocks * b))

                block_z = max(range(1, budget_rows + 1), key=score)
        elif self.sharded and nz % block_z != 0:
            raise ValueError(
                f"block_z={block_z} must divide local nz={nz} when "
                "sharded; a non-divisor would leave dead rows inside "
                "the exchanged core"
            )
        bz = block_z
        # nz rounded up to a block multiple (== nz when sharded: both
        # branches above guarantee an exact divisor there)
        nz_eff = -(-nz // bz) * bz
        # narrow dtypes pack more rows per native (sublane, 128) tile —
        # bf16's tile is (16, 128) — so the y padding rounds accordingly
        sub = SUBLANE * max(1, 4 // self.dtype.itemsize)
        self.padded_shape = (
            nz_eff + 2 * R,
            round_up(ny + 2 * R, sub),
            round_up(nx + 2 * R, LANE),
        )
        scales = [
            float(diffusivity[i]) / (12.0 * spacing[i] * spacing[i])
            for i in range(3)
        ]
        # split-overlap needs a strict interior band (>= 3 blocks) and
        # bz >= R so interior boxes never reach the stale ghost rows
        self.overlap_split = bool(
            overlap_split and self.sharded and nz // bz >= 3 and bz >= R
        )
        sources = ("none", "operand", "target")

        def mk(role):
            return tuple(
                _make_stage(
                    self.padded_shape, self.interior_shape, self.dtype,
                    bz=bz, scales=scales, a=a, b=b,
                    band=band, bc_value=float(bc_value), u_source=src,
                    global_shape=self.global_shape, sharded=self.sharded,
                    role=role, compute_dtype=compute_dtype,
                )
                for (a, b), src in zip(_STAGES, sources)
            )

        self.dt = float(dt)

        if self.overlap_split:
            (s1i, s2i, s3i) = mk("interior")
            (s1b, s2b, s3b) = mk("bottom")
            (s1t, s2t, s3t) = mk("top")

            def step(S, T1, T2, dt_arr, offsets=None, refresh=None,
                     exch=None):
                # Interior blocks run concurrently with the z-halo
                # ppermute; only the two edge calls consume the
                # exchanged slabs — the reference's five-stream
                # boundary/interior split (main.c:203-260) as dataflow.
                # On pencil meshes ``refresh`` serializes the non-z
                # sharded axes' ghosts on each stage's composed output
                # (the next stage reads them from the buffer).
                fix = refresh if refresh is not None else (lambda P: P)
                pre = (dt_arr, offsets)
                lo, hi = exch(S)
                T1 = fix(s1t(*pre, S, hi, s1b(*pre, S, lo, s1i(*pre, S, T1))))
                lo, hi = exch(T1)
                T2 = fix(s2t(*pre, T1, S, hi,
                             s2b(*pre, T1, S, lo, s2i(*pre, T1, S, T2))))
                lo, hi = exch(T2)
                S = fix(s3t(*pre, T2, hi, s3b(*pre, T2, lo, s3i(*pre, T2, S))))
                return S, T1, T2

        else:
            s1, s2, s3 = mk("full")

            def step(S, T1, T2, dt_arr, offsets=None, refresh=None,
                     exch=None):
                del exch
                pre = (
                    (dt_arr,) if offsets is None else (dt_arr, offsets)
                )
                fix = refresh if refresh is not None else (lambda P: P)
                T1 = fix(s1(*pre, S, T1))     # u1 = u + dt L(u)
                T2 = fix(s2(*pre, T1, S, T2))  # 3/4 u + 1/4 (u1 + dt L(u1))
                S = fix(s3(*pre, T2, S))      # 1/3 u + 2/3 (u2 + dt L(u2))
                return S, T1, T2              # in place

        self._step = step

    def embed(self, u):
        full = jnp.full(self.padded_shape, self.bc_value, self.dtype)
        return lax.dynamic_update_slice(full, u.astype(self.dtype), (R, R, R))

    def extract(self, S):
        nz, ny, nx = self.interior_shape
        out = lax.slice(S, (R, R, R), (R + nz, R + ny, R + nx))
        return out.astype(self._storage)

    def _dt_value(self, S):
        return jnp.asarray(self.dt, jnp.float32)

    # run()/run_to() come from FusedStepperBase (the MATLAB heat
    # drivers' native mode is run_to's `while t < t_end`,
    # heat3d.m:48-77); the kernels' global wall masks make ``offsets``
    # mandatory when sharded (needs_offsets).
