"""Pallas TPU kernel for the WENO5/WENO7 flux divergence.

TPU re-design of the reference's tiled face-flux kernels
(``SingleGPU/Burgers3d_WENO5_SharedMem/kernels.cu:212-400``): each tile
loads its stencil halo once, reconstructs every interface flux exactly
once, and differences adjacent faces. Here the "shared-memory tile" is a
VMEM z-slab DMA'd from HBM, and the per-thread serial sweeps of the
baseline kernels (``MultiGPU/Burgers3d_Baseline/Kernels.cu:225-452``)
become full-slab vector slices.

The kernel consumes an array *pre-padded by 3 along the sweep axis* (BC
ghosts or ppermute halo attached by the caller), so one kernel serves
single-device and sharded execution. The WENO5 stencil algebra is the
fused kernels' difference form (``ops.weno._weno5_side_nd[_e]``, the
same functions the fused steppers trace — equivalent to the XLA path's
``_weno5_minus/_weno5_plus`` up to the documented few-ulp FMA bound);
WENO7 keeps the XLA path's full-range q-form (``_weno7_minus/_plus``)
— see :func:`_face_flux` for the range argument.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.flux import Flux
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    align_trailing,
    compiler_params,
    round_up,
)

R = 3  # WENO5 stencil radius (WENO7: 4 — see _halo)

# Mosaic keeps ~16 live row-sized buffers per (block-row + 1) during the
# dual WENO5 reconstruction (measured: 205 MiB at block=8 on a 512^2
# trailing extent), so the z-block must be sized against VMEM, not a
# fixed 8. WENO7 carries ~1.5x the live set (7+7 shifted operands, 4
# betas/weights per side).
_VMEM_BUDGET = 80 * 1024 * 1024
_LIVE_ROWS = {5: 16, 7: 24}


def _halo(order: int) -> int:
    from multigpu_advectiondiffusion_tpu.ops.weno import HALO

    return HALO[order]


def _live_bytes(b: int, halo_lead: int, row_bytes: int, order: int) -> int:
    return (_LIVE_ROWS[order] * (b + 1) + b + halo_lead) * row_bytes


def _pick_vmem_block(
    nb: int, halo_lead: int, row_bytes: int, order: int = 5
) -> int | None:
    for b in range(min(8, nb), 0, -1):
        if (
            nb % b == 0
            and _live_bytes(b, halo_lead, row_bytes, order) <= _VMEM_BUDGET
        ):
            return b
    return None


def _row_bytes(shape, dtype) -> int:
    """Bytes of one tile-aligned leading-axis row of a padded 3-D array."""
    return (
        round_up(shape[1], 8) * round_up(shape[2], 128)
        * jnp.dtype(dtype).itemsize
    )


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _face_flux(window, axis, n_faces, flux, variant, order):
    """All ``n_faces`` interface fluxes along ``axis`` of a padded slab.

    Used only for the *leading* (untiled) axis, whose slices are free
    row selections; tiled-axis sweeps go through :func:`_div_windowed`
    instead.

    WENO5 reconstruction runs in the fused kernels' forward-difference
    form (``fused_burgers._div_z`` generalized to any free axis):
    shared first-difference/curvature windows, single-division weights,
    Newton reciprocals (range bound ~3e4 split-flux jumps — harmless).
    WENO7 deliberately keeps the classical q-form: the single-division
    order-7 weights raise betas to the 6th power, which bounds valid
    split-flux jumps to ~3.6 (``ops.weno._weno7_side_nd_e``) — fine
    inside the fused steppers, whose bounded solver states they serve,
    but this per-axis op is a general-purpose primitive that must
    accept arbitrary data (the suite feeds it random-normal fields)."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
        _recip,
        _split,
    )
    from multigpu_advectiondiffusion_tpu.ops.weno import (
        _curv,
        _weno5_side_nd,
        _weno7_minus,
        _weno7_plus,
    )

    vp, vm = _split(flux, window)
    r = _halo(order)

    def sl(arr, lo, ln=n_faces):
        idx = [slice(None)] * arr.ndim
        idx[axis] = slice(lo, lo + ln)
        return arr[tuple(idx)]

    if order == 7:
        return _weno7_minus([sl(vp, j) for j in range(7)]) + _weno7_plus(
            [sl(vm, j + 1) for j in range(7)]
        )
    ne = window.shape[axis] - 1
    ep = sl(vp, 1, ne) - sl(vp, 0, ne)
    em = sl(vm, 1, ne) - sl(vm, 0, ne)
    cp = _curv(sl(ep, 1, ne - 1) - sl(ep, 0, ne - 1))
    cm = _curv(sl(em, 1, ne - 1) - sl(em, 0, ne - 1))
    nm, dm = _weno5_side_nd(
        *(sl(ep, j) for j in range(4)),
        *(sl(cp, j) for j in range(3)),
        variant, "minus",
    )
    np_, dp = _weno5_side_nd(
        *(sl(em, j + 1) for j in range(4)),
        *(sl(cm, j + 1) for j in range(3)),
        variant, "plus",
    )
    return (sl(vp, r - 1) + sl(vm, r)) + (
        nm * _recip(dm) + np_ * _recip(dp)
    )


def _div_windowed(window, axis, n, flux, variant, inv_dx, order):
    """Divergence over a slab padded by the order's halo on a *tiled*
    sweep axis, via whole-array circular rolls
    (:func:`fused_burgers._div_roll` for WENO5; the same construction
    with the full-range q-form reconstructions for WENO7 — see
    :func:`_face_flux` for why order 7 must not use the range-bounded
    e-form here).

    On the VPU a tiled-axis window slice lowers to a per-operand
    realignment through the same shift unit a roll uses once — the
    rolls-beat-slices measurement behind the fused kernels' y sweep.
    Wrapped positions land only in the halo-deep pad band, outside the
    ``[r, r+n)`` output slice."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
        _div_roll,
        _split,
    )

    r = _halo(order)
    vp, vm = _split(flux, window)
    if order == 7:
        from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (  # noqa: E501
            _shift,
        )
        from multigpu_advectiondiffusion_tpu.ops.weno import (
            _weno7_minus,
            _weno7_plus,
        )

        # interface right of cell k: minus side cells k-3..k+3, plus
        # side cells k-2..k+4 (the roll analog of the padded offsets
        # 0..6 / 1..7 in interface_flux_from_padded)
        v = [_shift(vp, j, axis) for j in range(-3, 4)]
        u = [_shift(vm, j, axis) for j in range(-2, 5)]
        h = _weno7_minus(v) + _weno7_plus(u)
        div = (h - _shift(h, -1, axis)) * inv_dx
    else:
        div = _div_roll(vp, vm, axis, inv_dx, variant)
    idx = [slice(None)] * window.ndim
    idx[axis] = slice(r, r + n)
    return div[tuple(idx)]


def flux_divergence_pallas(
    up: jnp.ndarray,
    axis: int,
    dx: float,
    flux: Flux,
    variant: str = "js",
    block: int | None = None,
    order: int = 5,
) -> jnp.ndarray:
    """``d f(u)/dx`` along ``axis`` of an array padded by the order's
    halo (3 for WENO5, 4 for WENO7) on that axis.

    3-D arrays are processed in z-slabs; the sweep axis may be any axis,
    including the blocked one (the slab then carries the halo in-block).
    Slab DMAs slice only the leading (untiled) axis, with the trailing
    axes tile-aligned by ``align_trailing``; 2-D grids at reference scale
    fit VMEM whole, so they use a single-block kernel.
    """
    r = _halo(order)
    if up.ndim == 2:
        # whole-array kernel: `block` has no meaning (supported() gates size)
        return _flux_divergence_2d(up, axis, dx, flux, variant, order)

    ndim = up.ndim
    shape = list(up.shape)
    shape[axis] -= 2 * r
    n = shape[axis]  # output length along the sweep axis
    lead_axis = 0  # block over the leading axis
    nb = shape[0]
    halo_lead = 2 * r if axis == lead_axis else 0
    b = block or _pick_vmem_block(
        nb, halo_lead, _row_bytes(up.shape, up.dtype), order
    )
    if b is None:
        raise ValueError("no VMEM-viable block; gate with supported() first")
    up = align_trailing(up)

    def kernel(up_hbm, out_ref, slab, sem):
        k = pl.program_id(0)
        cp = pltpu.make_async_copy(
            up_hbm.at[pl.ds(k * b, b + halo_lead)], slab, sem
        )
        cp.start()
        cp.wait()
        window = slab[:]
        if axis != lead_axis:
            div = _div_windowed(window, axis, n, flux, variant, 1.0 / dx,
                                order)
            # crop the align_trailing tile padding (div is already
            # sweep-sliced to n on `axis`)
            idx = [slice(0, e) for e in (b,) + tuple(shape[1:])]
            out_ref[:] = div[tuple(idx)]
            return
        h = _face_flux(window, axis, b + 1, flux, variant, order)
        idx_lo = [slice(0, e) for e in (b,) + tuple(shape[1:])]
        idx_hi = list(idx_lo)
        idx_lo[axis] = slice(0, b)
        idx_hi[axis] = slice(1, b + 1)
        out_ref[:] = (h[tuple(idx_hi)] - h[tuple(idx_lo)]) * (1.0 / dx)

    slab_shape = (b + halo_lead,) + up.shape[1:]
    out_block = list(shape)
    out_block[0] = b

    return pl.pallas_call(
        kernel,
        grid=(nb // b,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            tuple(out_block),
            lambda k: (k,) + (0,) * (ndim - 1),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(tuple(shape), up.dtype),
        scratch_shapes=[
            pltpu.VMEM(slab_shape, up.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=_interpret(),
        compiler_params=None if _interpret() else compiler_params(),
    )(up)


def _flux_divergence_2d(
    up: jnp.ndarray, axis: int, dx: float, flux: Flux, variant: str,
    order: int = 5,
) -> jnp.ndarray:
    """Whole-array VMEM kernel for 2-D sweeps (size-gated by ``supported``)."""
    shape = list(up.shape)
    shape[axis] -= 2 * _halo(order)
    n = shape[axis]

    def kernel(up_ref, out_ref):
        window = up_ref[:]
        # both 2-D axes are tiled (sublane/lane) -> roll-based sweep
        div = _div_windowed(window, axis, n, flux, variant, 1.0 / dx, order)
        idx = [slice(0, e) for e in shape]
        idx[axis] = slice(None)
        out_ref[:] = div[tuple(idx)]

    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(tuple(shape), up.dtype),
        interpret=_interpret(),
        compiler_params=None if _interpret() else compiler_params(),
    )(up)


def supported(ndim: int, order: int, variant: str, shape=None,
              dtype=jnp.float32) -> bool:
    if order == 5:
        if variant not in ("js", "z"):
            return False
    elif order == 7:
        # WENO7 is JS-only, like the XLA path (the reference's WENO7 is
        # MATLAB-only with no Z variant, WENO7resAdv_X.m)
        if variant != "js":
            return False
    else:
        return False
    r = _halo(order)
    if ndim == 3:
        if shape is None:
            return True
        # every sweep axis must admit a VMEM-viable z-block (the z sweep
        # carries the 2r-row lead halo — the binding constraint)
        padded = (shape[0] + 2 * r, shape[1] + 2 * r, shape[2] + 2 * r)
        return (
            _pick_vmem_block(shape[0], 2 * r, _row_bytes(padded, dtype),
                             order)
            is not None
        )
    if ndim == 2:
        from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
            fits_vmem,
        )

        # shape is required to size-gate the whole-array 2-D kernel
        # (live full-size intermediates: vp/vm shifts, betas, weights —
        # ~10 for WENO5, ~18 for WENO7). WENO5 keeps the conservative
        # default budget it shipped with; WENO7's larger live set is
        # gated against this module's measured scope instead, or the
        # reference 2-D grid (400x406) would be spuriously rejected.
        if shape is None:
            return False
        if order == 7:
            return fits_vmem(
                shape, r, _LIVE_ROWS[order] - 6,
                jnp.dtype(dtype).itemsize, budget=_VMEM_BUDGET,
            )
        return fits_vmem(shape, r, 10, jnp.dtype(dtype).itemsize)
    return False
