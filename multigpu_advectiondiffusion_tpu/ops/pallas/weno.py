"""Pallas TPU kernel for the WENO5 flux divergence.

TPU re-design of the reference's tiled face-flux kernels
(``SingleGPU/Burgers3d_WENO5_SharedMem/kernels.cu:212-400``): each tile
loads its stencil halo once, reconstructs every interface flux exactly
once, and differences adjacent faces. Here the "shared-memory tile" is a
VMEM z-slab DMA'd from HBM, and the per-thread serial sweeps of the
baseline kernels (``MultiGPU/Burgers3d_Baseline/Kernels.cu:225-452``)
become full-slab vector slices.

The kernel consumes an array *pre-padded by 3 along the sweep axis* (BC
ghosts or ppermute halo attached by the caller), so one kernel serves
single-device and sharded execution. The WENO math itself is shared with
the XLA path (``ops.weno._weno5_minus/_weno5_plus``) — one source of
truth for the stencil algebra.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.flux import Flux
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    align_trailing,
    compiler_params,
    round_up,
)

R = 3  # WENO5 stencil radius

# Mosaic keeps ~16 live row-sized buffers per (block-row + 1) during the
# dual reconstruction (measured: 205 MiB at block=8 on a 512^2 trailing
# extent), so the z-block must be sized against VMEM, not a fixed 8.
_VMEM_BUDGET = 80 * 1024 * 1024


def _live_bytes(b: int, halo_lead: int, row_bytes: int) -> int:
    return (16 * (b + 1) + b + halo_lead) * row_bytes


def _pick_vmem_block(nb: int, halo_lead: int, row_bytes: int) -> int | None:
    for b in range(min(8, nb), 0, -1):
        if nb % b == 0 and _live_bytes(b, halo_lead, row_bytes) <= _VMEM_BUDGET:
            return b
    return None


def _row_bytes(shape, dtype) -> int:
    """Bytes of one tile-aligned leading-axis row of a padded 3-D array."""
    return (
        round_up(shape[1], 8) * round_up(shape[2], 128)
        * jnp.dtype(dtype).itemsize
    )


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _face_flux(window, axis, n_faces, flux, variant):
    """All ``n_faces`` interface fluxes along ``axis`` of a padded slab.

    Used only for the *leading* (untiled) axis, whose slices are free
    row selections; tiled-axis sweeps go through :func:`_div_windowed`
    instead."""
    from multigpu_advectiondiffusion_tpu.ops.weno import (
        _weno5_minus,
        _weno5_plus,
    )

    a = jnp.abs(flux.df(window))
    fu = flux.f(window)
    vp = 0.5 * (fu + a * window)
    vm = 0.5 * (fu - a * window)

    def shifts(arr, lo):
        out = []
        for j in range(5):
            idx = [slice(None)] * arr.ndim
            idx[axis] = slice(lo + j, lo + j + n_faces)
            out.append(arr[tuple(idx)])
        return out

    return _weno5_minus(*shifts(vp, 0), variant) + _weno5_plus(
        *shifts(vm, 1), variant
    )


def _div_windowed(window, axis, n, flux, variant, inv_dx):
    """Divergence over a slab padded by ``R`` on a *tiled* sweep axis,
    via whole-array circular rolls (:func:`fused_burgers._div_roll`).

    On the VPU a tiled-axis window slice lowers to a per-operand
    realignment through the same shift unit a roll uses once — the
    rolls-beat-slices measurement behind the fused kernels' y sweep.
    Wrapped positions land only in the R-deep pad band, outside the
    ``[R, R+n)`` output slice."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
        _div_roll,
        _split,
    )

    vp, vm = _split(flux, window)
    div = _div_roll(vp, vm, axis, inv_dx, variant)
    idx = [slice(None)] * window.ndim
    idx[axis] = slice(R, R + n)
    return div[tuple(idx)]


def flux_divergence_pallas(
    up: jnp.ndarray,
    axis: int,
    dx: float,
    flux: Flux,
    variant: str = "js",
    block: int | None = None,
) -> jnp.ndarray:
    """``d f(u)/dx`` along ``axis`` of an array padded by 3 on that axis.

    3-D arrays are processed in z-slabs; the sweep axis may be any axis,
    including the blocked one (the slab then carries the halo in-block).
    Slab DMAs slice only the leading (untiled) axis, with the trailing
    axes tile-aligned by ``align_trailing``; 2-D grids at reference scale
    fit VMEM whole, so they use a single-block kernel.
    """
    if up.ndim == 2:
        # whole-array kernel: `block` has no meaning (supported() gates size)
        return _flux_divergence_2d(up, axis, dx, flux, variant)

    ndim = up.ndim
    shape = list(up.shape)
    shape[axis] -= 2 * R
    n = shape[axis]  # output length along the sweep axis
    lead_axis = 0  # block over the leading axis
    nb = shape[0]
    halo_lead = 2 * R if axis == lead_axis else 0
    b = block or _pick_vmem_block(nb, halo_lead, _row_bytes(up.shape, up.dtype))
    if b is None:
        raise ValueError("no VMEM-viable block; gate with supported() first")
    up = align_trailing(up)

    def kernel(up_hbm, out_ref, slab, sem):
        k = pl.program_id(0)
        cp = pltpu.make_async_copy(
            up_hbm.at[pl.ds(k * b, b + halo_lead)], slab, sem
        )
        cp.start()
        cp.wait()
        window = slab[:]
        if axis != lead_axis:
            div = _div_windowed(window, axis, n, flux, variant, 1.0 / dx)
            # crop the align_trailing tile padding (div is already
            # sweep-sliced to n on `axis`)
            idx = [slice(0, e) for e in (b,) + tuple(shape[1:])]
            out_ref[:] = div[tuple(idx)]
            return
        h = _face_flux(window, axis, b + 1, flux, variant)
        idx_lo = [slice(0, e) for e in (b,) + tuple(shape[1:])]
        idx_hi = list(idx_lo)
        idx_lo[axis] = slice(0, b)
        idx_hi[axis] = slice(1, b + 1)
        out_ref[:] = (h[tuple(idx_hi)] - h[tuple(idx_lo)]) * (1.0 / dx)

    slab_shape = (b + halo_lead,) + up.shape[1:]
    out_block = list(shape)
    out_block[0] = b

    return pl.pallas_call(
        kernel,
        grid=(nb // b,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            tuple(out_block),
            lambda k: (k,) + (0,) * (ndim - 1),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(tuple(shape), up.dtype),
        scratch_shapes=[
            pltpu.VMEM(slab_shape, up.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=_interpret(),
        compiler_params=None if _interpret() else compiler_params(),
    )(up)


def _flux_divergence_2d(
    up: jnp.ndarray, axis: int, dx: float, flux: Flux, variant: str
) -> jnp.ndarray:
    """Whole-array VMEM kernel for 2-D sweeps (size-gated by ``supported``)."""
    shape = list(up.shape)
    shape[axis] -= 2 * R
    n = shape[axis]

    def kernel(up_ref, out_ref):
        window = up_ref[:]
        # both 2-D axes are tiled (sublane/lane) -> roll-based sweep
        div = _div_windowed(window, axis, n, flux, variant, 1.0 / dx)
        idx = [slice(0, e) for e in shape]
        idx[axis] = slice(None)
        out_ref[:] = div[tuple(idx)]

    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(tuple(shape), up.dtype),
        interpret=_interpret(),
        compiler_params=None if _interpret() else compiler_params(),
    )(up)


def supported(ndim: int, order: int, variant: str, shape=None,
              dtype=jnp.float32) -> bool:
    if order != 5 or variant not in ("js", "z"):
        return False
    if ndim == 3:
        if shape is None:
            return True
        # every sweep axis must admit a VMEM-viable z-block (the z sweep
        # carries the 2R-row lead halo — the binding constraint)
        padded = (shape[0] + 2 * R, shape[1] + 2 * R, shape[2] + 2 * R)
        return (
            _pick_vmem_block(shape[0], 2 * R, _row_bytes(padded, dtype))
            is not None
        )
    if ndim == 2:
        from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
            fits_vmem,
        )

        # shape is required to size-gate the whole-array 2-D kernel
        # (~10 live full-size intermediates: vp/vm shifts, betas, weights).
        return shape is not None and fits_vmem(
            shape, R, 10, jnp.dtype(dtype).itemsize
        )
    return False
