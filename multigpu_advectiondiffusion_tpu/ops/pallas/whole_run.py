"""Shared whole-run VMEM-resident SSP-RK3 driver.

One Pallas program whose grid is the *iteration counter*: the padded
state is DMA'd into VMEM scratch at the first grid step, all three RK
stages of every iteration run in-core (the TPU grid is a sequential
loop, so scratch persists across steps), and the result is written back
at the last step. Used by :mod:`fused_diffusion2d` and
:mod:`fused_burgers2d`, which differ only in the stage function.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import _STAGES
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    compiler_params,
    interpret_mode,
)


def _kernel(s_hbm, out_hbm, S, T1, T2, sem, *, n_iters, stage):
    k = pl.program_id(0)

    @pl.when(k == 0)
    def _():
        cp = pltpu.make_async_copy(s_hbm, S, sem)
        cp.start()
        cp.wait()

    u = S[:]
    (a1, b1), (a2, b2), (a3, b3) = _STAGES
    T1[:] = stage(u, u, a=a1, b=b1)
    T2[:] = stage(u, T1[:], a=a2, b=b2)
    S[:] = stage(u, T2[:], a=a3, b=b3)

    @pl.when(k == n_iters - 1)
    def _():
        cp = pltpu.make_async_copy(S, out_hbm, sem)
        cp.start()
        cp.wait()


def whole_run(stage, S0: jnp.ndarray, num_iters: int) -> jnp.ndarray:
    """``num_iters`` fused SSP-RK3 steps of ``stage`` on padded state
    ``S0``, entirely VMEM-resident; returns the final padded state.

    ``stage(u, v, *, a, b)`` is one RK combination over the full padded
    array (ghost discipline included).
    """
    kern = functools.partial(_kernel, n_iters=num_iters, stage=stage)
    return pl.pallas_call(
        kern,
        grid=(num_iters,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(S0.shape, S0.dtype),
        scratch_shapes=[
            pltpu.VMEM(S0.shape, S0.dtype),
            pltpu.VMEM(S0.shape, S0.dtype),
            pltpu.VMEM(S0.shape, S0.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=None if interpret_mode() else compiler_params(),
        interpret=interpret_mode(),
    )(S0)


def _kernel_adaptive(s_hbm, out_hbm, t_out, S, T1, T2, tacc, sem, *,
                     n_iters, stage, dt_fn):
    """Like :func:`_kernel` but dt is recomputed from the in-VMEM state
    before every step (``dt_fn`` — a whole-array reduction; the padded
    state's ghost/slack cells are edge replicas of interior values, so
    the reduction over the full array equals the interior reduction) and
    the accumulated time advance is emitted as an SMEM scalar output."""
    k = pl.program_id(0)

    @pl.when(k == 0)
    def _():
        cp = pltpu.make_async_copy(s_hbm, S, sem)
        cp.start()
        cp.wait()
        tacc[0] = jnp.float32(0.0)

    u = S[:]
    dt = dt_fn(u)
    (a1, b1), (a2, b2), (a3, b3) = _STAGES
    T1[:] = stage(u, u, a=a1, b=b1, dt=dt)
    T2[:] = stage(u, T1[:], a=a2, b=b2, dt=dt)
    S[:] = stage(u, T2[:], a=a3, b=b3, dt=dt)
    tacc[0] = tacc[0] + dt.astype(jnp.float32)

    @pl.when(k == n_iters - 1)
    def _():
        t_out[0] = tacc[0]
        cp = pltpu.make_async_copy(S, out_hbm, sem)
        cp.start()
        cp.wait()


def whole_run_adaptive(stage, S0: jnp.ndarray, num_iters: int, dt_fn):
    """Adaptive-dt variant of :func:`whole_run`: returns ``(final padded
    state, accumulated time advance)``. ``stage`` additionally takes the
    per-iteration ``dt``; ``dt_fn(padded_state) -> scalar`` runs in-core
    between steps (the restored CFL rule the CUDA drivers hard-coded
    away, ``LFWENO5FDM2d.m:71`` vs ``main.c:193``)."""
    kern = functools.partial(
        _kernel_adaptive, n_iters=num_iters, stage=stage, dt_fn=dt_fn
    )
    S, t_sum = pl.pallas_call(
        kern,
        grid=(num_iters,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(S0.shape, S0.dtype),
            jax.ShapeDtypeStruct((1,), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM(S0.shape, S0.dtype),
            pltpu.VMEM(S0.shape, S0.dtype),
            pltpu.VMEM(S0.shape, S0.dtype),
            pltpu.SMEM((1,), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=None if interpret_mode() else compiler_params(),
        interpret=interpret_mode(),
    )(S0)
    return S, t_sum[0]


def accumulate_t(t, dt: float, num_iters: int):
    """Iterative t accumulation, matching the generic loop's rounding."""
    return lax.fori_loop(0, num_iters, lambda i, tt: tt + dt, t)
