"""Whole-run VMEM-resident SSP-RK3 stepping for 2-D diffusion.

The reference's 2-D solvers stream the full state through device memory
twice per kernel, 4 kernels per step (`SingleGPU/Diffusion2d*`,
``MultiGPU/Diffusion2d_Baseline``). On TPU a reference-scale 2-D grid
(1001², ``Diffusion2d/Run.m``) is ~4 MB in f32 — smaller than VMEM — so
the TPU-native design is: load the padded state into VMEM **once**, run
*every* RK stage of *every* iteration in-core, and write the result back
**once**. HBM traffic for a 1000-iteration run drops from ~8 GB to
~8 MB; the run is purely VPU-bound. No CUDA-era structure corresponds to
this — it is what the memory hierarchy invites when the whole domain
fits on-chip.

Layout mirrors ``fused_diffusion``: padded, tile-aligned state
``(round8(ny+2R), round128(nx+2R))`` whose ghost/slack cells hold the
frozen Dirichlet value (``reference_parity`` walls: RHS zeroed on the
boundary band, faces re-clamped each step — ``Laplace3d.m:21``,
``heat3d.m:65-67``); stencils are masked circular shifts; the Pallas
grid is the *iteration counter*, with state living in scratch across
grid steps (the TPU grid is a sequential loop).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax

from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import _shift
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    R,
    SUBLANE,
    round_up,
)

# The working set is ~6 padded-array-sized buffers (S, T1, T2 + stage
# temporaries); gate well under the Mosaic scoped ceiling.
_VMEM_BUDGET = 64 * 1024 * 1024
_LIVE_BUFFERS = 8


def _stage(u, v, *, interior_shape, band, scales, a, b, dt, bc_value):
    """One RK stage ``a*u + b*(v + dt*L(v))`` over the full padded array.

    Wraparound lanes from the circular shifts land only outside the
    interior mask and are replaced by the frozen boundary values. The
    masks are iota-derived inside the kernel (values may not be captured
    from outside a Pallas body).
    """
    dtype = v.dtype
    interior, face = _masks(v.shape, interior_shape, band)
    acc = None
    for axis in range(2):
        for j, c in enumerate(O4_COEFFS):
            term = _shift(v, j - R, axis) * jnp.asarray(c * scales[axis], dtype)
            acc = term if acc is None else acc + term
    rk = b * (v + dt * acc) if a == 0.0 else a * u + b * (v + dt * acc)
    frozen = jnp.where(face, jnp.asarray(bc_value, dtype), v)
    return jnp.where(interior, rk, frozen)


def _masks(padded_shape, interior_shape, band):
    ny, nx = interior_shape
    gy = lax.broadcasted_iota(jnp.int32, padded_shape, 0) - R
    gx = lax.broadcasted_iota(jnp.int32, padded_shape, 1) - R

    def between(g, n):
        return (g >= band) & (g < n - band)

    interior = between(gy, ny) & between(gx, nx)
    face = (gy == 0) | (gy == ny - 1) | (gx == 0) | (gx == nx - 1)
    return interior, face


class FusedDiffusion2DStepper:
    """Jit-cached whole-run VMEM stepper for one (grid, dtype, dt)."""

    engaged_label = "fused-whole-run"
    stencil_radius = R  # O4 Laplacian reach; in-core frozen ghosts

    def stencil_spec(self) -> dict:
        """Stencil metadata (analysis/halo_verify.py): whole-run VMEM
        residency with an ``R``-deep frozen Dirichlet pad — no
        exchange, single-chip only."""
        return {
            "kernel": self.engaged_label,
            "stage_radius": R,
            "fused_stages": 1,
            "ghost_depth": R,
            "exchange_depth": None,
            "steps_per_exchange": 1,
            "storage_dtype": str(jnp.dtype(self.dtype)),
            "bytes_per_cell": int(jnp.dtype(self.dtype).itemsize),
        }

    def __init__(self, interior_shape, dtype, spacing, diffusivity, dt,
                 band, bc_value):
        ny, nx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.padded_shape = (
            round_up(ny + 2 * R, SUBLANE),
            round_up(nx + 2 * R, LANE),
        )
        self.dtype = jnp.dtype(dtype)
        self.bc_value = float(bc_value)
        self._scales = tuple(
            float(diffusivity[i]) / (12.0 * spacing[i] * spacing[i])
            for i in range(2)
        )
        self.dt = float(dt)
        self._band = band

    @staticmethod
    def supported(interior_shape, dtype) -> bool:
        from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
            fits_vmem,
        )

        return fits_vmem(
            interior_shape, R, _LIVE_BUFFERS,
            jnp.dtype(dtype).itemsize, budget=_VMEM_BUDGET,
        )

    def embed(self, u):
        full = jnp.full(self.padded_shape, self.bc_value, self.dtype)
        return lax.dynamic_update_slice(full, u.astype(self.dtype), (R, R))

    def extract(self, S):
        ny, nx = self.interior_shape
        return lax.slice(S, (R, R), (R + ny, R + nx))

    def run(self, u, t, num_iters: int):
        from multigpu_advectiondiffusion_tpu.ops.pallas.whole_run import (
            accumulate_t,
            whole_run,
        )

        if num_iters == 0:
            return u, t
        stage = functools.partial(
            _stage, interior_shape=self.interior_shape, band=self._band,
            scales=self._scales, dt=self.dt, bc_value=self.bc_value,
        )
        out = whole_run(stage, self.embed(u), num_iters)
        return self.extract(out), accumulate_t(t, self.dt, num_iters)
