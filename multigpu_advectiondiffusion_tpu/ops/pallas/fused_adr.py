"""Fully-fused SSP-RK3 advection–diffusion–reaction stepping (3-D).

The per-stage rung of the title family (``models/adr.py``): each RK
stage is ONE Pallas kernel over the persistent padded state, the same
minimum-HBM-traffic choreography as :mod:`fused_diffusion` (slab DMA +
2-slot double buffering; ``T1 = stage1(S)``, ``T2 = stage2(T1, S)``,
``S' = stage3(T2, S) -> S`` in place), with the ADR right-hand side
evaluated in VMEM per slab:

* 13-point O4 Laplacian taps (z via slab rows, y/x via masked circular
  shifts) — the *un-scaled* tap sum, so the spatially varying
  coefficient can multiply it;
* **K(x)** computed IN-KERNEL from global cell indices:
  ``K(x) = K0 * (1 + eps * cos(pi ẑ) cos(pi ŷ) cos(pi x̂))`` with
  ``x̂ = g/(n-1) - 1/2`` — no second HBM operand, and under a mesh the
  same global-offsets SMEM operand that feeds the wall masks feeds the
  coefficient, so a shard computes exactly its window of the global
  field (``models/adr.py kappa_profile`` is the ONE other definition of
  this formula; tests hold the two together);
* first-order **upwind** advective divergence at constant velocity
  (radius 1, inside the existing R=2 ghost ring):
  ``a⁺(u_i - u_{i-1})/dx + a⁻(u_{i+1} - u_i)/dx`` per axis — the
  monotone flux the generic rung's ``advect="upwind"`` mode matches
  term-for-term (WENO5 advection rides the generic rung);
* linear-decay reaction ``-lambda * u`` folded into the stage.

Reference-parity walls are the diffusion kernel's discipline verbatim:
RHS zeroed on the global boundary band, Dirichlet faces re-imposed,
masks in *global* indices so a sharded run reproduces the single-device
solution. Sharded mode runs the stages shard-local under ``shard_map``
with the per-stage ``ppermute`` ghost refresh
(``parallel.halo.make_ghost_refresh``) — the ADR family inherits the
mesh skeleton, it does not reimplement it.
"""

from __future__ import annotations

import functools
import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
    _STAGES,
    _shift,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    R,
    SUBLANE,
    VMEM_LIMIT,
    _aligned_row_bytes_3d,
    compiler_params,
    interpret_mode,
    pick_block,
    round_up,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.stepper_base import (
    FusedStepperBase,
)


def _stage_kernel(
    dt_ref,
    v_hbm,
    u_hbm,
    out_hbm,
    vs,
    us,
    res,
    sem_v,
    sem_u,
    sem_w,
    *,
    bz: int,
    n_blocks: int,
    global_shape: Sequence[int],
    offs_ref=None,
    lap_scales: Sequence[float],
    adv_p: Sequence[float],
    adv_m: Sequence[float],
    lam: float,
    k0: float,
    k_eps: float,
    a: float,
    b: float,
    band: int,
    bc_value: float,
    compute_dtype=None,
):
    """One z-block of one ADR RK stage, 2-slot double-buffered (the
    :mod:`fused_diffusion` prefetch/defer choreography: block ``k``
    prefetches ``k+1`` while computing, drains its output DMA at
    ``k+2``)."""
    nz, ny, nx = global_shape
    k = pl.program_id(0)
    slot = lax.rem(k, jnp.asarray(2, k.dtype))
    nslot = lax.rem(k + 1, jnp.asarray(2, k.dtype))

    def copy_v(j, s):
        return pltpu.make_async_copy(
            v_hbm.at[pl.ds(j * bz, bz + 2 * R)], vs.at[s], sem_v.at[s]
        )

    def copy_u(j, s):
        # the in-place final stage reads its u rows from the aliased
        # output buffer, strictly before the overwriting DMA
        src = u_hbm if u_hbm is not None else out_hbm
        return pltpu.make_async_copy(
            src.at[pl.ds(R + j * bz, bz)], us.at[s], sem_u.at[s]
        )

    def copy_w(j, s):
        return pltpu.make_async_copy(
            res.at[s], out_hbm.at[pl.ds(R + j * bz, bz)], sem_w.at[s]
        )

    @pl.when(k == 0)
    def _():
        copy_v(0, 0).start()
        if us is not None:
            copy_u(0, 0).start()

    @pl.when(k + 1 < n_blocks)
    def _():
        copy_v(k + 1, nslot).start()
        if us is not None:
            copy_u(k + 1, nslot).start()

    if us is not None:
        copy_u(k, slot).wait()
    copy_v(k, slot).wait()

    # bf16-storage rung (the fused_diffusion convention): the state
    # lives and moves through HBM at half the bytes; all ADR arithmetic
    # runs in ``compute_dtype`` (f32) so the stencil taps, upwind
    # differences and RK accumulation keep their cancellation digits
    stored = vs[slot]
    v = (
        stored
        if compute_dtype is None
        else stored.astype(jnp.dtype(compute_dtype))
    )
    vc = v[R : R + bz]  # stage input, core z-rows, full y/x width
    dtype = v.dtype
    dt = dt_ref[0].astype(dtype)

    # un-scaled O4 Laplacian tap sum per axis (1/(12 dx^2) folded into
    # the tap coefficient; K(x) multiplies the summed result below)
    lap = None
    for axis in range(3):
        for j, c in enumerate(O4_COEFFS):
            coef = jnp.asarray(c * lap_scales[axis], dtype)
            term = (
                v[j : j + bz] if axis == 0 else _shift(vc, j - R, axis)
            ) * coef
            lap = term if lap is None else lap + term

    # first-order upwind advective divergence (radius 1 < R: the ±1
    # neighbors are always inside the refreshed ghost ring; y/x
    # wraparound lands in masked ghost columns like the Laplacian's)
    adv = None
    for axis in range(3):
        cp, cm = adv_p[axis], adv_m[axis]
        if cp == 0.0 and cm == 0.0:
            continue
        lo = v[R - 1 : R - 1 + bz] if axis == 0 else _shift(vc, -1, axis)
        hi = v[R + 1 : R + 1 + bz] if axis == 0 else _shift(vc, 1, axis)
        term = jnp.asarray(cp, dtype) * (vc - lo) + jnp.asarray(
            cm, dtype
        ) * (hi - vc)
        adv = term if adv is None else adv + term

    # global interior-cell indices (sharded: offsets from SMEM — the
    # same operand serves the wall masks AND the K(x) coefficient)
    shp = vc.shape
    oz, oy, ox = (
        (offs_ref[0], offs_ref[1], offs_ref[2])
        if offs_ref is not None
        else (0, 0, 0)
    )
    gz = lax.broadcasted_iota(jnp.int32, shp, 0) + k * bz + oz
    gy = lax.broadcasted_iota(jnp.int32, shp, 1) - R + oy
    gx = lax.broadcasted_iota(jnp.int32, shp, 2) - R + ox

    if k_eps:
        pi = jnp.asarray(math.pi, dtype)

        def chat(g, n):
            return jnp.cos(pi * (g.astype(dtype) / (n - 1) - 0.5))

        kf = jnp.asarray(k0, dtype) * (
            1.0
            + jnp.asarray(k_eps, dtype)
            * chat(gz, nz) * chat(gy, ny) * chat(gx, nx)
        )
        rhs = kf * lap
    else:
        rhs = jnp.asarray(k0, dtype) * lap
    if adv is not None:
        rhs = rhs - adv
    if lam:
        rhs = rhs - jnp.asarray(lam, dtype) * vc

    u_in = None if us is None else us[slot].astype(dtype)
    rk = (
        b * (vc + dt * rhs)
        if a == 0.0
        else a * u_in + b * (vc + dt * rhs)
    )

    def between(g, n):
        return (g >= band) & (g < n - band)

    interior = between(gz, nz) & between(gy, ny) & between(gx, nx)
    face = (
        (gz == 0) | (gz == nz - 1)
        | (gy == 0) | (gy == ny - 1)
        | (gx == 0) | (gx == nx - 1)
    )
    frozen = jnp.where(face, jnp.asarray(bc_value, dtype), vc)

    @pl.when(k >= 2)
    def _():
        copy_w(k - 2, slot).wait()

    res[slot] = jnp.where(interior, rk, frozen).astype(stored.dtype)
    copy_w(k, slot).start()

    @pl.when(k == n_blocks - 1)
    def _():
        copy_w(k, slot).wait()
        if n_blocks >= 2:
            copy_w(k - 1, nslot).wait()


def _make_stage(padded_shape, interior_shape, dtype, *, bz, a, b,
                u_source, sharded=False, global_shape=None,
                compute_dtype=None, **phys):
    """Build one fused ADR RK-stage call; output aliased onto the last
    operand (``u_source`` as in :mod:`fused_diffusion`: "none" /
    "operand" / "target")."""
    trailing = padded_shape[1:]
    use_u = u_source != "none"
    n_blocks = (padded_shape[0] - 2 * R) // bz

    kern = functools.partial(
        _stage_kernel,
        bz=bz,
        n_blocks=n_blocks,
        global_shape=tuple(global_shape or interior_shape),
        a=a,
        b=b,
        compute_dtype=compute_dtype,
        **phys,
    )

    def kernel(*refs):
        dt_ref, *refs = refs
        offs_ref = None
        if sharded:
            offs_ref, *refs = refs
        if u_source == "operand":
            v_hbm, u_hbm, *refs = refs
        else:
            v_hbm, *refs = refs
            u_hbm = None  # "target": read from out_hbm
        _tgt, out_hbm, vs, *refs = refs
        if use_u:
            us, *refs = refs
        else:
            us = None
        res, sem_v, *refs = refs
        if use_u:
            sem_u, *refs = refs
        else:
            sem_u = None
        (sem_w,) = refs
        kern(dt_ref, v_hbm, u_hbm, out_hbm, vs, us, res,
             sem_v, sem_u, sem_w, offs_ref=offs_ref)

    n_in = (
        1  # dt
        + (1 if sharded else 0)
        + (2 if u_source == "operand" else 1)
        + 1  # aliased target
    )
    scratch = [pltpu.VMEM((2, bz + 2 * R) + trailing, dtype)]
    if use_u:
        scratch.append(pltpu.VMEM((2, bz) + trailing, dtype))
    scratch.append(pltpu.VMEM((2, bz) + trailing, dtype))
    scratch.append(pltpu.SemaphoreType.DMA((2,)))
    if use_u:
        scratch.append(pltpu.SemaphoreType.DMA((2,)))
    scratch.append(pltpu.SemaphoreType.DMA((2,)))

    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]  # dt
    in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * (n_in - 1)
    if sharded:
        in_specs[1] = pl.BlockSpec(memory_space=pltpu.SMEM)

    return pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(tuple(padded_shape), dtype),
        scratch_shapes=scratch,
        input_output_aliases={n_in - 1: 0},  # last operand -> out
        compiler_params=None if interpret_mode() else compiler_params(),
        interpret=interpret_mode(),
    )


class FusedADRStepper(FusedStepperBase):
    """Jit-cached fused per-stage runner for one ADR configuration.

    ``global_shape`` != ``interior_shape`` switches to shard-local mode
    (global wall masks and the in-kernel K(x) coefficient take this
    shard's offsets from a runtime SMEM operand; :meth:`run` accepts
    the per-stage ghost ``refresh``) — the tuned kernel under the mesh,
    exactly the :class:`~.fused_diffusion.FusedDiffusionStepper`
    contract, so the ADR family rides the existing sharded dispatch
    unmodified. No split-overlap / whole-step / slab variants: ADR
    ships the per-stage rung only (``models/adr.py`` declines the
    others loudly)."""

    halo = R
    stencil_radius = R  # max(advective upwind 1, diffusive O4 2)
    needs_offsets = True
    overlap_split = False

    def __init__(self, interior_shape, dtype, spacing, diffusivity,
                 velocity, reaction, dt, band, bc_value,
                 kappa_variation: float = 0.0, block_z=None,
                 global_shape=None, storage_dtype=None):
        nz, ny, nx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.global_shape = tuple(global_shape or interior_shape)
        self.sharded = self.global_shape != self.interior_shape
        self.dtype = jnp.dtype(dtype)
        # split-dtype storage, both directions (the fused_diffusion
        # convention): ``storage_dtype`` is the FACING dtype (embed
        # downcasts, extract restores); ``dtype`` is the kernel/HBM
        # buffer dtype. bf16 kernels upcast to f32 for the arithmetic.
        self._storage = jnp.dtype(storage_dtype or dtype)
        compute_dtype = (
            jnp.float32 if self.dtype == jnp.bfloat16 else None
        )
        self.bc_value = float(bc_value)
        if len(tuple(velocity)) != 3:
            raise ValueError(
                f"fused ADR wants a 3-vector velocity, got {velocity!r}"
            )
        row_bytes = _aligned_row_bytes_3d((nz, ny, nx),
                                          self.dtype.itemsize)
        # same VMEM budget model as the fused diffusion stepper (the
        # slab buffers are identical; the extra ADR arithmetic is
        # register-resident)
        budget_rows = max(
            1, min(20, int((VMEM_LIMIT // row_bytes - 56) // 9))
        )
        if block_z is None:
            if self.sharded:
                block_z = pick_block(nz, budget_rows)
            else:
                def score(bz):
                    blocks = -(-nz // bz)
                    return (bz / (bz + 2 * R)) * (nz / (blocks * bz))

                block_z = max(range(1, budget_rows + 1), key=score)
        elif self.sharded and nz % block_z != 0:
            raise ValueError(
                f"block_z={block_z} must divide local nz={nz} when "
                "sharded (dead rows inside the exchanged core would "
                "corrupt neighbor ghosts)"
            )
        bz = block_z
        nz_eff = -(-nz // bz) * bz
        sub = SUBLANE * max(1, 4 // self.dtype.itemsize)
        self.padded_shape = (
            nz_eff + 2 * R,
            round_up(ny + 2 * R, sub),
            round_up(nx + 2 * R, LANE),
        )
        self.core_offsets = (R, R, R)
        self.dt = float(dt)

        phys = {
            "lap_scales": tuple(
                1.0 / (12.0 * dx * dx) for dx in spacing
            ),
            "adv_p": tuple(
                max(float(v), 0.0) / dx
                for v, dx in zip(velocity, spacing)
            ),
            "adv_m": tuple(
                min(float(v), 0.0) / dx
                for v, dx in zip(velocity, spacing)
            ),
            "lam": float(reaction),
            "k0": float(diffusivity),
            "k_eps": float(kappa_variation),
            "band": int(band),
            "bc_value": float(bc_value),
        }
        sources = ("none", "operand", "target")
        s1, s2, s3 = (
            _make_stage(
                self.padded_shape, self.interior_shape, self.dtype,
                bz=bz, a=a, b=b, u_source=src,
                sharded=self.sharded, global_shape=self.global_shape,
                compute_dtype=compute_dtype,
                **phys,
            )
            for (a, b), src in zip(_STAGES, sources)
        )

        def step(S, T1, T2, dt_arr, offsets=None, refresh=None,
                 exch=None):
            del exch  # no split-overlap schedule on this rung
            pre = (dt_arr,) if offsets is None else (dt_arr, offsets)
            fix = refresh if refresh is not None else (lambda P: P)
            T1 = fix(s1(*pre, S, T1))      # u1 = u + dt RHS(u)
            T2 = fix(s2(*pre, T1, S, T2))  # 3/4 u + 1/4 (u1 + dt RHS)
            S = fix(s3(*pre, T2, S))       # 1/3 u + 2/3 (u2 + dt RHS)
            return S, T1, T2               # in place

        self._step = step

    def embed(self, u):
        full = jnp.full(self.padded_shape, self.bc_value, self.dtype)
        return lax.dynamic_update_slice(
            full, u.astype(self.dtype), (R, R, R)
        )

    def extract(self, S):
        nz, ny, nx = self.interior_shape
        out = lax.slice(S, (R, R, R), (R + nz, R + ny, R + nx))
        return out.astype(self._storage)

    def _dt_value(self, S):
        return jnp.asarray(self.dt, jnp.float32)
