"""Pallas TPU kernel for the 4th-order Laplacian.

TPU re-design of the reference's most-optimized diffusion kernel — the
z-register-pipelined ``Compute_Laplace3d_Async``
(``SingleGPU/Diffusion3d_Blocking/kernels.cu:37-88``) and
``LaplaceO4_async`` (``MultiGPU/Diffusion3d_Baseline/Kernels.cu:207-261``).
Where each CUDA thread marches k keeping a 5-deep register window, here
each Pallas program DMAs a z-slab (plus 2-cell halo) from HBM into VMEM
and evaluates all three axis stencils as vector slices over the slab —
the VPU's (8, 128) lanes play the role of the thread block, the slab the
role of the register pipeline.

The kernel consumes a *pre-padded* array: BC ghost cells or ``ppermute``
halo cells are attached by the caller (``ops.laplacian.laplacian``), so
one kernel serves both execution worlds. Corner ghost regions are never
read (13-point cross stencil).

Mosaic tiling note: HBM→VMEM slab DMAs slice only the leading (untiled)
axis; the trailing two axes are copied whole, so their extents must be
multiples of the f32 (8, 128) tile — the caller-side ``align_trailing``
pad guarantees that. Value slices *inside* the kernel carry no such
restriction.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

R = 2  # stencil radius of the O4 second derivative
_C = (-1.0, 16.0, -30.0, 16.0, -1.0)  # /12 dx^2 (Laplace3d.m:22-25)

# f32 VMEM tile: (sublane, lane) = (8, 128)
SUBLANE, LANE = 8, 128

# Conservative per-kernel VMEM budget (bytes) for whole-array 2-D kernels.
VMEM_BUDGET = 12 * 1024 * 1024

# Scoped-VMEM ceiling passed to Mosaic (the 16 MiB default is far below
# the chip's physical VMEM and rejects reference-scale slabs).
VMEM_LIMIT = 100 * 1024 * 1024

# Model budget for the 3-D slab kernels' block picker. The model counts
# the kernel's raw materializations per block row — 15 axis-term
# buffers (5 coefficients x 3 axes) plus the VMEM output block — plus
# the slab's 2R halo rows, i.e. ((15 + 1) b + 2R) aligned rows. This
# OVERestimates what Mosaic actually allocates (it fuses the adds), so
# the budget is deliberately above the 100 MiB scoped ceiling.
# Calibrated on two v5e anchors with _aligned_row_bytes_3d rows:
# 6.6 MB rows (990x1605 trailing) compile at bz=1 (model 132 MB) and
# fail at bz=2 (model 238 MB); 1.33 MB rows (512^2 trailing, aligned
# 520x640) fail at bz=8 (model 176 MB, actual 105.1 MB vs the 100 MiB
# scope) and compile at bz=4 (model 90 MB).
VMEM_BLOCK_BUDGET_3D = 134 * 1024 * 1024


def compiler_params():
    return pltpu.CompilerParams(vmem_limit_bytes=VMEM_LIMIT)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


# Public names for the pieces other Pallas modules build on
# (fused_diffusion, weno): the O4 stencil, interpret-mode switch, and
# tile rounding are this module's shared vocabulary, not file-locals.
O4_COEFFS = _C
interpret_mode = _interpret
round_up = _round_up


def align_trailing(up: jnp.ndarray) -> jnp.ndarray:
    """Zero-pad the trailing two axes to (8, 128)-tile multiples so slab
    DMAs are expressible; the pad region feeds no interior output."""
    sl = _round_up(up.shape[-2], SUBLANE)
    ln = _round_up(up.shape[-1], LANE)
    if (sl, ln) == up.shape[-2:]:
        return up
    pw = [(0, 0)] * (up.ndim - 2) + [(0, sl - up.shape[-2]), (0, ln - up.shape[-1])]
    return jnp.pad(up, pw)


def pick_block(n: int, target: int = 8) -> int:
    """Largest divisor of ``n`` that is <= target (>=1)."""
    for b in range(min(target, n), 0, -1):
        if n % b == 0:
            return b
    return 1


def _axis_term(u, axis, scale, lead, shape):
    """Sum of shifted slices along ``axis`` of the slab ``u``.

    ``lead`` is the slice start per axis for the core region; ``shape`` is
    the output block shape.
    """
    acc = None
    for j, c in enumerate(_C):
        starts = list(lead)
        starts[axis] = j
        idx = tuple(
            slice(s, s + n) for s, n in zip(starts, shape)
        )
        term = u[idx] * (c * scale)
        acc = term if acc is None else acc + term
    return acc


def _aligned_row_bytes_3d(interior_shape, itemsize: int) -> int:
    """Tile-aligned bytes of one padded leading-axis row."""
    return (
        _round_up(interior_shape[1] + 2 * R, SUBLANE)
        * _round_up(interior_shape[2] + 2 * R, LANE)
        * itemsize
    )


def pick_vmem_block_3d(nz: int, row_bytes: int, target: int = 8):
    """Largest divisor of ``nz`` (<= target) whose modeled working set
    fits ``VMEM_BLOCK_BUDGET_3D``, or ``None``. Model: 16 row-sized
    buffers per block row (15 axis terms + the output block) plus the
    slab's 2R halo rows (see the budget constant for the measured
    calibration anchors)."""
    for b in range(min(target, nz), 0, -1):
        need = (16 * b + 2 * R) * row_bytes
        if nz % b == 0 and need <= VMEM_BLOCK_BUDGET_3D:
            return b
    return None


def laplacian_o4_3d(
    up: jnp.ndarray,
    spacing: Sequence[float],
    diffusivity: Sequence[float],
    block_z: int | None = None,
) -> jnp.ndarray:
    """``sum_a K_a d2/da^2`` of a 3-D array padded by 2 on every axis.

    ``up`` has shape ``(nz+4, ny+4, nx+4)``; returns ``(nz, ny, nx)``.
    """
    nzp, nyp, nxp = up.shape
    nz, ny, nx = nzp - 2 * R, nyp - 2 * R, nxp - 2 * R
    bz = block_z or pick_vmem_block_3d(
        nz, _aligned_row_bytes_3d((nz, ny, nx), up.dtype.itemsize)
    )
    if bz is None:
        raise ValueError("no VMEM-viable z-block; gate with supported()")
    up = align_trailing(up)
    # identical association order to the XLA path (ops.laplacian.laplacian):
    # per-axis stencil scaled by 1/(12 dx^2), then multiplied by K_axis.
    scales = [1.0 / (12.0 * spacing[a] * spacing[a]) for a in range(3)]

    def kernel(up_hbm, out_ref, slab, sem):
        k = pl.program_id(0)
        cp = pltpu.make_async_copy(
            up_hbm.at[pl.ds(k * bz, bz + 2 * R)], slab, sem
        )
        cp.start()
        cp.wait()
        u = slab[:]
        shape = (bz, ny, nx)
        lead = (R, R, R)
        acc = diffusivity[0] * _axis_term(u, 0, scales[0], lead, shape)
        acc += diffusivity[1] * _axis_term(u, 1, scales[1], lead, shape)
        acc += diffusivity[2] * _axis_term(u, 2, scales[2], lead, shape)
        out_ref[:] = acc

    return pl.pallas_call(
        kernel,
        grid=(nz // bz,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (bz, ny, nx), lambda k: (k, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nz, ny, nx), up.dtype),
        scratch_shapes=[
            pltpu.VMEM((bz + 2 * R,) + up.shape[1:], up.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=_interpret(),
        compiler_params=None if _interpret() else compiler_params(),
    )(up)


def laplacian_o4_2d(
    up: jnp.ndarray,
    spacing: Sequence[float],
    diffusivity: Sequence[float],
) -> jnp.ndarray:
    """2-D variant: ``up`` is ``(ny+4, nx+4)``, whole array VMEM-resident.

    2-D grids at reference scale (1001², ``SingleGPU/Diffusion2d/Run.m``)
    fit VMEM outright, so no slab pipeline is needed; ``supported`` gates
    larger grids back to the XLA path.
    """
    nyp, nxp = up.shape
    ny, nx = nyp - 2 * R, nxp - 2 * R
    scales = [1.0 / (12.0 * spacing[a] * spacing[a]) for a in range(2)]

    def kernel(up_ref, out_ref):
        u = up_ref[:]
        shape = (ny, nx)
        lead = (R, R)
        acc = diffusivity[0] * _axis_term(u, 0, scales[0], lead, shape)
        acc += diffusivity[1] * _axis_term(u, 1, scales[1], lead, shape)
        out_ref[:] = acc

    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ny, nx), up.dtype),
        interpret=_interpret(),
        compiler_params=None if _interpret() else compiler_params(),
    )(up)


def fits_vmem(shape: Sequence[int], halo: int, n_live: int,
              itemsize: int = 4, budget: int = VMEM_BUDGET) -> bool:
    """Whether a whole-array 2-D kernel with ``n_live`` full-size live
    intermediates fits the VMEM ``budget`` after tile rounding."""
    rows = _round_up(shape[0] + 2 * halo, SUBLANE)
    cols = _round_up(shape[1] + 2 * halo, LANE)
    return n_live * rows * cols * itemsize <= budget


def supported(shape: Sequence[int], order: int, itemsize: int = 4) -> bool:
    """Whether the Pallas path covers this problem (else XLA fallback)."""
    if order != 4:
        return False
    if len(shape) == 3:
        # very wide trailing extents (e.g. the reference's 1601x986 slab
        # planes) can exceed VMEM even at a 1-row block
        return (
            pick_vmem_block_3d(shape[0], _aligned_row_bytes_3d(shape, itemsize))
            is not None
        )
    if len(shape) == 2:
        return fits_vmem(shape, R, 3, itemsize)
    return False
