"""Per-stage fused SSP-RK3 kernels for *sharded* 2-D grids.

The reference runs its (only) tuned 2-D kernels under MPI — the 2-D
MultiGPU baselines are half of its capability-target projects
(``MultiGPU/Diffusion2d_Baseline/main.c:64,189-280``,
``MultiGPU/Burgers2d_Baseline/main.c:186+``). The single-chip TPU design
for these grids is the whole-run VMEM stepper
(:mod:`fused_diffusion2d`, :mod:`fused_burgers2d`), but its temporal
blocking crosses the points where sharded-axis ghosts must refresh, so
it cannot run under a mesh.

This module is the sharded counterpart, on the 3-D per-stage pattern
(:mod:`fused_diffusion`, :mod:`fused_burgers`): the state lives in a
persistent padded tile-aligned layout, each RK stage is ONE Pallas
kernel over the whole local shard (a 2-D shard is far under VMEM), and
the caller refreshes sharded-axis ghosts by ``ppermute`` between stages
(``parallel.halo.make_ghost_refresh``). Global wall/edge decisions use
*global* coordinates from an SMEM offsets operand, exactly like the 3-D
stage kernels.

Because a 2-D shard fits VMEM whole, there is no block grid and no
manual DMA pipeline: operands use whole-array VMEM block specs, stages
are pure calls with the output aliased onto the retiring buffer of the
three-buffer RK choreography (``T1 = s1(S)``, ``T2 = s2(T1, S)``,
``S' = s3(T2, S) -> S``).

``overlap="split"`` on a y-slab mesh swaps the serialized refresh for a
three-band schedule per stage: the ghost-independent interior band runs
concurrently with the in-flight slab ``ppermute`` (AOT-verified: the
compiled v5e schedule places the band's ``tpu_custom_call`` inside a
collective-permute window), and two halo-row edge bands consume the
exchanged slabs as separate operands — the reference's five-stream
boundary/interior choreography as dataflow, in 2-D.

Ghost discipline:

* Burgers: every non-interior cell at a *global* domain edge is an edge
  replica of the nearest interior cell (``WENO5resAdv_X.m:53``),
  re-synthesized after every stage; sharded-axis ghost cells hold
  neighbor data and are rewritten by the between-stage refresh. Dead
  rounding slack is never read by interior outputs (stencil reads reach
  exactly the ``R``-deep ghosts).
* Diffusion: reference-parity walls — the RHS mask freezes the global
  boundary band, global faces re-clamp to the Dirichlet value
  (``Laplace3d.m:21``, ``heat3d.m:65-67``); non-interior cells pass the
  stage input through, so buffer ghosts stay whatever the refresh wrote.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.flux import Flux
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
    _div_roll,
    _split,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers2d import (
    _LIVE_BUFFERS as _BURGERS_LIVE,
    _VMEM_BUDGET as _BURGERS_BUDGET,
    _laplacian_2d,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers2d import (
    R as R_WENO,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
    _STAGES,
    _shift,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion2d import (
    _LIVE_BUFFERS as _DIFF_LIVE,
    _VMEM_BUDGET as _DIFF_BUDGET,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    SUBLANE,
    compiler_params,
    fits_vmem,
    interpret_mode,
    round_up,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import R as R_LAP
from multigpu_advectiondiffusion_tpu.ops.pallas.stepper_base import (
    FusedStepperBase,
)


def _global_coords(shape, offs_ref, halo):
    """Global interior indices of every padded cell of this shard."""
    gy = lax.broadcasted_iota(jnp.int32, shape, 0) - halo + offs_ref[0]
    gx = lax.broadcasted_iota(jnp.int32, shape, 1) - halo + offs_ref[1]
    return gy, gx


def _edge_fill_global(rk, offs_ref, local_shape, global_shape, halo):
    """Edge-replicate cells outside the *global* domain.

    The replica source sits at a static local index (first/last interior
    row/column): the mask can only be true on the shard that owns the
    corresponding global edge, where that index holds the right value —
    on every other shard the mask is all-false and the source value is
    discarded. Sharded-axis ghosts with valid global coordinates keep
    their computed values; the between-stage ppermute refresh overwrites
    them."""
    ly, lx = local_shape
    NY, NX = global_shape
    gy, gx = _global_coords(rk.shape, offs_ref, halo)
    t = jnp.where(gx < 0, rk[:, halo : halo + 1], rk)
    t = jnp.where(gx >= NX, t[:, halo + lx - 1 : halo + lx], t)
    t = jnp.where(gy < 0, t[halo : halo + 1, :], t)
    return jnp.where(gy >= NY, t[halo + ly - 1 : halo + ly, :], t)


def _burgers_stage(v, u, dt, offs_ref, *, a, b, local_shape, global_shape,
                   inv_dx, nu_scales, flux, variant, order=5, halo=R_WENO):
    """One RK stage of 2-D Burgers/WENO over the whole padded shard
    (order 5 halo 3, order 7 halo 4).

    Same op sequence as the single-chip whole-run stage
    (``fused_burgers2d._stage``) so the sharded run reproduces it
    per-cell; only the ghost synthesis is keyed on global coordinates."""
    vp, vm = _split(flux, v)
    rhs = -(
        _div_roll(vp, vm, 0, inv_dx[0], variant, order)
        + _div_roll(vp, vm, 1, inv_dx[1], variant, order)
    )
    if nu_scales is not None:
        rhs = rhs + _laplacian_2d(v, nu_scales)
    dt = dt.astype(v.dtype)
    rk = b * (v + dt * rhs) if a == 0.0 else a * u + b * (v + dt * rhs)
    return _edge_fill_global(
        rk.astype(v.dtype), offs_ref, local_shape, global_shape, halo
    )


def _diffusion_stage(v, u, dt, offs_ref, *, a, b, global_shape, scales,
                     band, bc_value):
    """One RK stage of 2-D O4 diffusion over the whole padded shard,
    reference-parity walls in global coordinates (``Laplace3d.m:21``,
    ``heat3d.m:65-67``)."""
    dtype = v.dtype
    acc = None
    for axis in range(2):
        for j, c in enumerate(O4_COEFFS):
            term = _shift(v, j - R_LAP, axis) * jnp.asarray(
                c * scales[axis], dtype
            )
            acc = term if acc is None else acc + term
    dt = dt.astype(dtype)
    rk = b * (v + dt * acc) if a == 0.0 else a * u + b * (v + dt * acc)
    NY, NX = global_shape
    gy, gx = _global_coords(v.shape, offs_ref, R_LAP)

    def between(g, n):
        return (g >= band) & (g < n - band)

    interior = between(gy, NY) & between(gx, NX)
    face = (gy == 0) | (gy == NY - 1) | (gx == 0) | (gx == NX - 1)
    frozen = jnp.where(face, jnp.asarray(bc_value, dtype), v)
    return jnp.where(interior, rk, frozen)


def _make_stage(padded_shape, dtype, stage_fn, *, a, b, u_source):
    """One whole-shard RK-stage ``pallas_call``.

    ``u_source``: ``"none"`` (stage 1, ``a == 0`` — the trailing operand
    is only the donation target), ``"operand"`` (separate ``u`` input
    plus a donation target), or ``"alias_u"`` (the in-place final stage:
    ``u`` is the last operand and the output is aliased onto it).
    Operand order: ``dt (SMEM (1,))``, ``offsets (SMEM (2,))``, ``v``,
    then per ``u_source``; the output is always aliased onto the last
    operand.
    """
    use_u = u_source != "none"
    has_tgt = u_source != "alias_u"

    def kernel(*refs):
        dt_ref, offs_ref, v_ref, *rest = refs
        out_ref = rest[-1]
        u = rest[0][...] if use_u else None
        out_ref[...] = stage_fn(
            v_ref[...], u, dt_ref[0], offs_ref, a=a, b=b
        )

    n_in = 3 + (1 if use_u else 0) + (1 if has_tgt else 0)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
    in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)] * (n_in - 2)
    return pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(tuple(padded_shape), dtype),
        input_output_aliases={n_in - 1: 0},
        compiler_params=None if interpret_mode() else compiler_params(),
        interpret=interpret_mode(),
    )


def _make_band_stage(in_rows, out_rows, out_row0, trailing, dtype,
                     stage_fn, *, a, b, use_u):
    """One band call of the split-overlap schedule: input is a JAX-level
    row slice of the padded buffer (ghost rows pre-concatenated from the
    exchanged slabs for the edge bands), the stage evaluates over it,
    and only the ``out_rows`` rows starting at ``out_row0`` are emitted.
    Operands: ``dt``, ``offsets`` (pre-adjusted so the stage's global-y
    formula ``iota - halo + offs[0]`` is exact for this band), ``v``
    [, ``u`` — same row range as ``v``, stale rows discarded]."""

    def kernel(*refs):
        dt_ref, offs_ref, v_ref, *rest = refs
        # checked band contract: the rows the caller assembled must be
        # exactly what this stage was built for — a mismatch would
        # silently shift the emitted window
        assert v_ref.shape[0] == in_rows, (v_ref.shape, in_rows)
        out_ref = rest[-1]
        u = rest[0][...] if use_u else None
        full = stage_fn(v_ref[...], u, dt_ref[0], offs_ref, a=a, b=b)
        out_ref[...] = lax.slice_in_dim(full, out_row0, out_row0 + out_rows,
                                        axis=0)

    n_in = 3 + (1 if use_u else 0)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
    in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)] * (n_in - 2)
    return pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((out_rows,) + tuple(trailing), dtype),
        compiler_params=None if interpret_mode() else compiler_params(),
        interpret=interpret_mode(),
    )


class _Sharded2DStepperBase(FusedStepperBase):
    """Shared plumbing: three-buffer step choreography with per-stage
    ghost refresh (or the split-overlap band schedule),
    run()/run_to() from :class:`FusedStepperBase`."""

    needs_offsets = True  # global edge/wall decisions
    overlap_split = False

    def _build_step(self, stage_fn_for, dtype):
        """``stage_fn_for(band_shape | None)`` returns the stage
        callable — ``None`` means the full local interior (the
        serialized whole-shard calls); a band shape parametrizes the
        split-overlap band calls (Burgers' edge-fill source indices
        must stay inside the band array)."""
        sources = ("none", "operand", "alias_u")
        if not self.overlap_split:
            s1, s2, s3 = (
                _make_stage(
                    self.padded_shape, dtype, stage_fn_for(None),
                    a=a, b=b, u_source=src,
                )
                for (a, b), src in zip(_STAGES, sources)
            )

            def step(S, T1, T2, dt_arr, offsets=None, refresh=None,
                     exch=None):
                del exch
                # an all-extent-1 mesh builds this stepper unsharded: no
                # refresh/offsets arrive, and this shard IS the global
                # block
                offs = (
                    offsets
                    if offsets is not None
                    else jnp.zeros((len(self.interior_shape),), jnp.int32)
                )
                fix = refresh if refresh is not None else (lambda P: P)
                T1 = fix(s1(dt_arr, offs, S, T1))
                T2 = fix(s2(dt_arr, offs, T1, S, T2))
                S = fix(s3(dt_arr, offs, T2, S))
                return S, T1, T2

            self._step = step
            return

        # Split-overlap band schedule on the axis-0 slab: per stage, the
        # interior band (rows that depend on no ghost row) runs
        # concurrently with the in-flight ppermute of the exchanged
        # slabs — only the two h-row edge-band calls consume them. The
        # reference's five-stream boundary/interior choreography as
        # dataflow (MultiGPU/Diffusion2d_Baseline/main.c:189-280).
        h = self.halo
        ly, lx = self.interior_shape
        trailing = self.padded_shape[1:]
        mid = ly - 2 * h

        def band_calls(a, b, use_u):
            edge_fn = stage_fn_for((h, lx))
            mid_fn = stage_fn_for((mid, lx))
            return (
                _make_band_stage(3 * h, h, h, trailing, dtype, edge_fn,
                                 a=a, b=b, use_u=use_u),
                _make_band_stage(ly, mid, h, trailing, dtype, mid_fn,
                                 a=a, b=b, use_u=use_u),
                _make_band_stage(3 * h, h, h, trailing, dtype, edge_fn,
                                 a=a, b=b, use_u=use_u),
            )

        calls = [
            band_calls(a, b, src != "none")
            for (a, b), src in zip(_STAGES, sources)
        ]

        def step(S, T1, T2, dt_arr, offsets=None, refresh=None, exch=None):
            del refresh
            offs = (
                offsets
                if offsets is not None
                else jnp.zeros((2,), jnp.int32)
            )
            # the band stages' global-y formula is `iota - h + offs[0]`;
            # each band's first input row sits at a different interior
            # row, so offs[0] is pre-shifted per band (bottom: -h, i.e.
            # unshifted; interior: 0; top: ly-2h)
            o_b = offs
            o_i = offs + jnp.asarray([h, 0], jnp.int32)
            o_t = offs + jnp.asarray([ly - h, 0], jnp.int32)

            def run_stage(cb, ci, ct, v, u):
                lo, hi = exch(v)
                sl = lambda a0, r0, r1: lax.slice_in_dim(a0, r0, r1, axis=0)  # noqa: E731,E501
                args = lambda o, vin, u_rng: (  # noqa: E731
                    (dt_arr, o, vin)
                    + (() if u is None else (sl(u, *u_rng),))
                )
                # the interior call consumes no exchanged slab — XLA
                # schedules it inside the collective-permute window
                m = ci(*args(o_i, sl(v, h, h + ly), (h, h + ly)))
                bb = cb(*args(
                    o_b,
                    jnp.concatenate([lo, sl(v, h, 3 * h)], axis=0),
                    (0, 3 * h),
                ))
                tt = ct(*args(
                    o_t,
                    jnp.concatenate([sl(v, h + ly - 2 * h, h + ly), hi],
                                    axis=0),
                    (h + ly - 2 * h, h + ly + h),
                ))
                # stale ghost/slack rows ride along unread (split mode
                # never reads buffer ghosts — they live in the operands)
                return jnp.concatenate(
                    [sl(v, 0, h), bb, m, tt, sl(v, h + ly, v.shape[0])],
                    axis=0,
                )

            T1 = run_stage(*calls[0], S, None)
            T2 = run_stage(*calls[1], T1, S)
            S = run_stage(*calls[2], T2, S)
            return S, T1, T2

        self._step = step

    def extract(self, S):
        h = self.halo
        ly, lx = self.interior_shape
        return lax.slice(S, (h, h), (h + ly, h + lx))


class ShardedFusedBurgers2DStepper(_Sharded2DStepperBase):
    """Per-stage fused 2-D Burgers/WENO5 for shard-local execution inside
    ``shard_map`` — the tuned 2-D kernel under the mesh, matching the
    reference's MPI deployment of its 2-D kernels
    (``MultiGPU/Burgers2d_Baseline/main.c:186+``). Serves both dt modes:
    fixed (CUDA parity) and adaptive (``max|f'(u)|`` + ``lax.pmax``
    between steps through the runtime SMEM dt scalar)."""

    halo = R_WENO  # class default; instances set halo = HALO[order]
    core_offsets = (R_WENO, R_WENO)

    def __init__(self, interior_shape, dtype, spacing, flux: Flux,
                 variant: str, nu: float, dt: float | None = None,
                 dt_fn=None, global_shape=None,
                 overlap_split: bool = False, order: int = 5):
        from multigpu_advectiondiffusion_tpu.ops.weno import HALO

        if (dt is None) == (dt_fn is None):
            raise ValueError("provide exactly one of dt/dt_fn")
        if order == 7 and variant != "js":
            raise ValueError("WENO7 supports only the 'js' variant")
        r = HALO[order]
        self.order = order
        self.halo = r
        self.stencil_radius = r  # per-stage refresh at the WENO reach
        self.core_offsets = (r, r)
        ly, lx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.global_shape = tuple(global_shape or interior_shape)
        self.sharded = self.global_shape != self.interior_shape
        # split needs a non-degenerate interior band (>= h rows)
        self.overlap_split = bool(
            overlap_split and self.sharded and ly >= 3 * r
        )
        self.padded_shape = (
            round_up(ly + 2 * r, SUBLANE),
            round_up(lx + 2 * r, LANE),
        )
        self.dtype = jnp.dtype(dtype)
        nu_scales = None
        if nu:
            nu_scales = tuple(
                float(nu) / (12.0 * spacing[i] * spacing[i]) for i in range(2)
            )

        def stage_fn_for(band_shape):
            return functools.partial(
                _burgers_stage,
                local_shape=band_shape or self.interior_shape,
                global_shape=self.global_shape,
                inv_dx=tuple(1.0 / spacing[i] for i in range(2)),
                nu_scales=nu_scales,
                flux=flux,
                variant=variant,
                order=order,
                halo=r,
            )

        self._build_step(stage_fn_for, self.dtype)
        self.dt = None if dt is None else float(dt)
        self._dt_fn = dt_fn

    @staticmethod
    def supported(interior_shape, dtype, order: int = 5) -> bool:
        from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers2d import (  # noqa: E501
            _LIVE_BUFFERS_W7,
        )
        from multigpu_advectiondiffusion_tpu.ops.weno import HALO

        return fits_vmem(
            interior_shape, HALO[order],
            _BURGERS_LIVE if order == 5 else _LIVE_BUFFERS_W7,
            jnp.dtype(dtype).itemsize, budget=_BURGERS_BUDGET,
        )

    def embed(self, u):
        r = self.halo
        ly, lx = self.interior_shape
        py, px = self.padded_shape
        return jnp.pad(
            u.astype(self.dtype),
            ((r, py - ly - r), (r, px - lx - r)),
            mode="edge",
        )

    def _dt_value(self, S):
        if self.dt is not None:
            return jnp.asarray(self.dt, jnp.float32)
        # interior view; the solver's dt_fn carries the lax.pmax
        return self._dt_fn(self.extract(S)).astype(jnp.float32)


class ShardedFusedDiffusion2DStepper(_Sharded2DStepperBase):
    """Per-stage fused 2-D O4 diffusion for shard-local execution inside
    ``shard_map`` — the tuned 2-D kernel under the mesh
    (``MultiGPU/Diffusion2d_Baseline/main.c:189-280``), reference-parity
    global walls via the offsets operand."""

    halo = R_LAP
    stencil_radius = R_LAP  # per-stage refresh at the O4 reach
    core_offsets = (R_LAP, R_LAP)

    def __init__(self, interior_shape, dtype, spacing, diffusivity, dt,
                 band, bc_value, global_shape=None,
                 overlap_split: bool = False):
        ly, lx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.global_shape = tuple(global_shape or interior_shape)
        self.sharded = self.global_shape != self.interior_shape
        self.overlap_split = bool(
            overlap_split and self.sharded and ly >= 3 * R_LAP
        )
        self.padded_shape = (
            round_up(ly + 2 * R_LAP, SUBLANE),
            round_up(lx + 2 * R_LAP, LANE),
        )
        self.dtype = jnp.dtype(dtype)
        self.bc_value = float(bc_value)
        stage_fn = functools.partial(
            _diffusion_stage,
            global_shape=self.global_shape,
            scales=tuple(
                float(diffusivity[i]) / (12.0 * spacing[i] * spacing[i])
                for i in range(2)
            ),
            band=band,
            bc_value=self.bc_value,
        )
        self._build_step(lambda band_shape: stage_fn, self.dtype)
        self.dt = float(dt)

    @staticmethod
    def supported(interior_shape, dtype) -> bool:
        return fits_vmem(
            interior_shape, R_LAP, _DIFF_LIVE,
            jnp.dtype(dtype).itemsize, budget=_DIFF_BUDGET,
        )

    def embed(self, u):
        full = jnp.full(self.padded_shape, self.bc_value, self.dtype)
        return lax.dynamic_update_slice(
            full, u.astype(self.dtype), (R_LAP, R_LAP)
        )

    def _dt_value(self, S):
        return jnp.asarray(self.dt, jnp.float32)
