"""Slab-pipelined whole-run SSP-RK3 stepping for 3-D (diffusion + Burgers).

The 2-D solvers reach their 400-813x rates through the whole-run VMEM
stepper (:mod:`whole_run`): state on-chip for the entire run, zero HBM
traffic per step. A 3-D reference grid does not fit VMEM, so the 3-D
fused path has been the per-stage stepper — three Pallas calls per step,
each a full HBM round trip of the state (~9 array passes per step
counting stage inputs, ``u`` reads and writes).

This module is the 3-D rung between the two: ONE Pallas program whose
grid is ``(timestep, z-slab)``. The TPU grid is a sequential loop, so the
program streams z-slabs HBM->VMEM with double-buffered async copies,
fuses all three RK stages of the step in VMEM while the next slab's DMA
is in flight, and writes each slab's core back once — one HBM round trip
per step (``1 + (bz + 2G)/bz`` array passes) instead of three.

Slab independence comes from **redundant ghost-region recompute** (the
reference's revolving-buffer idea, and the standard trapezoid rule of
temporal blocking): each slab loads ``G = 3h`` extra rows per side
(``h`` = per-stage stencil radius: 2 for the O4 Laplacian, 3/4 for
WENO5/7), recomputes stage 1 on a ``bz + 4h``-row window and stage 2 on
``bz + 2h``, so the stage-3 core needs nothing from neighboring slabs
within the step. No slab ever reads another slab's output of the same
step — which is what lets the whole step run inside one sequential grid
with plain double-buffered DMA and no inter-slab synchronization.

Step-level state ping-pong rides a single stacked ``(2,) + padded``
buffer: step ``k`` reads ``buf[k % 2]`` and writes ``buf[1 - k % 2]``
(slab ``j+1`` of step ``k`` still reads rows that slab ``j`` would
overwrite in place). The buffer parity of the final state is
``num_iters % 2``, known statically. Across the step boundary the
prefetch of the next step's first slab reads rows this step already
wrote; it is issued only when the write-drain schedule proves those
writes have landed (``cross_ok``), else the first slab of each step
loads synchronously.

Redundant recompute is paid in VPU work: ``2h/bz`` extra rows per
stage. The dispatch (``models/*._fused_stepper``) therefore engages
this stepper only where the traffic saving can win — large-``bz`` slabs
(HBM-bound diffusion) or grids whose z extent fits one or two slabs —
and falls back to the per-stage ``fused-stage`` path otherwise;
``impl='pallas_slab'`` pins it for measurement.

Sharded mode (z-slab decomposition only, pinned): the whole-run grid
cannot cross ghost refreshes, so each step runs as one slab-pipelined
Pallas call per step under ``shard_map``, with a single ``G``-deep
z-halo exchange per STEP (same bytes as the per-stage path's three
``h``-deep exchanges, a third of the messages, and one kernel launch
per step instead of three). With ``overlap='split'`` the step runs the
familiar three-call schedule (interior slabs concurrent with the
in-flight ``ppermute``; only the two edge slabs consume the exchanged
``G``-deep slabs), mirroring :mod:`fused_diffusion`'s per-stage split.

**Communication-avoiding k-step schedule** (``steps_per_exchange=k``):
the within-step G=3h trick generalized ACROSS steps. The padded buffer
carries ``k*G`` ghost rows per side; ONE ``k*G``-deep exchange per
k-step block, and in-block step ``j`` evolves the core extended by
``(k-1-j)*G`` rows per side — the standard trapezoid of temporal
blocking, here spanning both the RK stages *and* k whole steps. Step 0
consumes the exchanged ghosts; every later step reads exactly the
previous step's output window, so the block needs no communication at
all. Bytes per step are unchanged (``2*k*G`` rows every k steps);
messages and collective latencies drop by 1/k, paid for with the
redundant window growth ``~(k-1)*G/lz`` in VPU work and slab traffic.
Split-overlap composes: the block-start exchange overlaps the interior
call (output window exactly the locally valid core), with single-slab
edge calls consuming the ``k*G``-deep operands. Exchange cadence is
selected per measured tuning decision (``impl='auto'``,
:mod:`multigpu_advectiondiffusion_tpu.tuning`) or pinned via the
``steps_per_exchange`` config knob.

**In-kernel remote-DMA exchange** (``exchange='dma'``, ROADMAP item 2):
the sharded composition above still breaks out of the Pallas program
every step (or every k-step block) to run the ``ppermute`` between
compiled calls. The dma mode instead runs the ENTIRE sharded run as one
whole-run Pallas program per shard — grid ``(timestep, z-slab)`` like
the unsharded rung — and moves the ``k*G`` ghost rows over ICI from
*inside* the kernel via ``pltpu.make_async_remote_copy``: at each
block's last step the freshly written core edge windows are pushed to
the ±z neighbors' dedicated 2-slot landing buffer (cyclic ring pushes,
every shard in lockstep — the wall shards' wrapped slabs land in rows
the receiver never reads, the ``ppermute`` discipline in-kernel), and
the next block's first iteration waits the paired send/recv semaphores
and splices the landed slabs into the read parity's ghost rows with a
local DMA (wall sides keep their frozen embed BC ghosts). Pushes land
in the landing buffer only — never over state rows — so a fast
neighbor can never overwrite rows the local step is still computing;
the static halo verifier (``analysis/halo_verify``) proves the
declared send/recv windows (``stencil_spec()['remote_dma']``) against
exactly that invariant before any hardware run. The in-block step
windows shrink by the usual ``(k-1-j)*G`` trapezoid, realized on a
uniform z-block (``bz | lz``, and ``bz | 2G`` when k > 1) with the
out-of-window grid iterations predicated off.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from multigpu_advectiondiffusion_tpu.ops.flux import Flux
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
    _div_roll,
    _div_z,
    _split,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
    _STAGES,
    _shift,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion_step import (
    _stage_rows,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.laplacian import (
    LANE,
    O4_COEFFS,
    R,
    SUBLANE,
    VMEM_LIMIT,
    compiler_params,
    interpret_mode,
    round_up,
)
from multigpu_advectiondiffusion_tpu.ops.pallas.whole_run import accumulate_t
from multigpu_advectiondiffusion_tpu.ops.weno import HALO

# Conservative budget for the slab working set (the Mosaic scoped
# ceiling is VMEM_LIMIT = 100 MiB; leave headroom for Mosaic's own
# scheduling slack, as fused_burgers does).
_VMEM_BUDGET = 72 * 1024 * 1024


def _dma_compiler_params():
    """Mosaic params for the in-kernel remote-DMA program: the scoped
    VMEM ceiling of every slab kernel, plus the collective id (and,
    where this jax exposes it, the side-effect pin) the cross-chip
    DMAs require. Only built on the TPU lowering path — interpret mode
    passes None like every other slab call — so resolve the params
    class per jax version (``CompilerParams`` today,
    ``TPUCompilerParams`` on older releases)."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    kwargs = {"vmem_limit_bytes": VMEM_LIMIT, "collective_id": 0}
    if "has_side_effects" in getattr(cls, "__dataclass_fields__", {}):
        kwargs["has_side_effects"] = True
    return cls(**kwargs)


def _check_steps_per_exchange(k, sharded: bool, nz: int, G: int) -> int:
    """Validate the communication-avoiding chunk length for a stepper
    instance: sharded-only (an unsharded run exchanges nothing, so k is
    meaningless there) and the shard must be thick enough to *serve* the
    ``k*G``-deep exchange from its core."""
    k = int(k)
    if k < 1:
        raise ValueError(f"steps_per_exchange must be >= 1, got {k}")
    if k == 1:
        return 1
    if not sharded:
        raise ValueError(
            "the k-step communication-avoiding schedule applies to "
            "sharded (z-slab) runs only"
        )
    if nz < k * G:
        raise ValueError(
            f"local z extent {nz} cannot serve the k-step schedule's "
            f"{k * G}-deep exchange (steps_per_exchange={k}, G={G})"
        )
    return k


def _cross_ok(bz: int, G: int, n_slabs: int) -> bool:
    """Whether the next step's first-slab prefetch may be issued at the
    current step's last slab. The prefetch reads dst rows ``[0, bz+2G)``:
    ghost rows (never written) plus the cores of slabs ``0..M``. At
    prefetch time the drain schedule has waited writes through ``i-3``
    (slab ``n_slabs-4``), so all read rows have landed iff
    ``M <= n_slabs - 4`` — which also keeps the two still-in-flight
    writes (slabs ``n_slabs-3``/``-2``) disjoint from the read."""
    M = 1 + (G - 1) // bz
    return M <= n_slabs - 4


def _whole_run_kernel(s_in, ss, vs, res, sem_v, sem_w, *, step_fn, bz: int,
                      G: int, n_slabs: int, n_iters: int, cross: bool,
                      batched: bool = False):
    """(timestep, z-slab) grid body; ``ss`` is the stacked (2, pz, Y, X)
    state (output aliased onto the input — all access goes through the
    out ref). ``step_fn(v, j) -> (bz, Y, X)`` fuses the three RK stages
    of slab ``j`` on the ``(bz + 2G)``-row VMEM box ``v``.

    ``batched``: the B-folded ensemble variant — the grid gains a
    LEADING member axis (``(B, timestep, z-slab)``), ``ss`` a leading
    member dimension (``(B, 2, pz, Y, X)``), and every DMA indexes the
    current member's stack. The sequential TPU grid finishes member
    ``m`` (including the end-of-member write drain at ``i == total-1``)
    before ``m+1`` starts, and no copy ever addresses another member's
    rows — the member axis is halo-free by construction (statically
    proven by ``analysis/halo_verify``)."""
    del s_in  # aliased with ss
    # canonical i32 indices: interpret mode under x64 hands the two grid
    # dimensions different integer widths
    if batched:
        m = jnp.asarray(pl.program_id(0), jnp.int32)
        k = jnp.asarray(pl.program_id(1), jnp.int32)
        j = jnp.asarray(pl.program_id(2), jnp.int32)
    else:
        m = None
        k = jnp.asarray(pl.program_id(0), jnp.int32)
        j = jnp.asarray(pl.program_id(1), jnp.int32)
    n = jnp.asarray(n_slabs, jnp.int32)
    two = jnp.asarray(2, jnp.int32)
    i = k * n + j
    total = n_iters * n_slabs
    slot = lax.rem(i, two)
    nslot = lax.rem(i + 1, two)

    def _stack(parity):
        # the (2, pz, Y, X) ping-pong stack of the current member
        return ss.at[m, parity] if batched else ss.at[parity]

    def copy_in(kk, jj, s):
        kk = jnp.asarray(kk, jnp.int32)  # literal 0s stay i32 under x64
        jj = jnp.asarray(jj, jnp.int32)
        return pltpu.make_async_copy(
            _stack(lax.rem(kk, two)).at[pl.ds(jj * bz, bz + 2 * G)],
            vs.at[s],
            sem_v.at[s],
        )

    def copy_out(ii, s):
        ii = jnp.asarray(ii, jnp.int32)
        kk = lax.div(ii, n)
        jj = lax.rem(ii, n)
        return pltpu.make_async_copy(
            res.at[s],
            _stack(1 - lax.rem(kk, two)).at[pl.ds(G + jj * bz, bz)],
            sem_w.at[s],
        )

    # ---- load schedule ----
    if cross:
        # steady 2-deep pipeline across step boundaries (see _cross_ok)
        @pl.when(i == 0)
        def _():
            copy_in(0, 0, slot).start()

        @pl.when(i + 1 < total)
        def _():
            wrap = j + 1 == n
            kk = jnp.where(wrap, k + 1, k)
            jj = jnp.where(wrap, jnp.asarray(0, jnp.int32), j + 1)
            copy_in(kk, jj, nslot).start()

    else:
        # the next step's slab-0 read races this step's tail writes on
        # thin slab counts: drain the outstanding writes of the previous
        # step, then load slab 0 synchronously. With a single slab per
        # step only one write is ever outstanding (the previous
        # iteration drained i-2 as *its* i-1) — waiting it twice would
        # hang the semaphore.
        if n_slabs >= 2:
            @pl.when((j == 0) & (i >= 2))
            def _():
                copy_out(i - 2, slot).wait()

        @pl.when((j == 0) & (i >= 1))
        def _():
            copy_out(i - 1, nslot).wait()

        @pl.when(j == 0)
        def _():
            copy_in(k, 0, slot).start()

        @pl.when((i + 1 < total) & (j + 1 < n))
        def _():
            copy_in(k, j + 1, nslot).start()

    copy_in(k, j, slot).wait()
    out = step_fn(vs[slot], j)

    # ---- write-drain schedule (invariant: writes <= i-3 have landed at
    # iteration start; at j == 0 both outstanding writes are drained,
    # at j >= 2 the slot's previous write) ----
    if cross:
        @pl.when((j == 0) & (i >= 2))
        def _():
            copy_out(i - 2, slot).wait()

        @pl.when((j == 0) & (i >= 1))
        def _():
            copy_out(i - 1, nslot).wait()

    @pl.when(j >= 2)
    def _():
        copy_out(i - 2, slot).wait()

    res[slot] = out
    copy_out(i, slot).start()

    @pl.when(i == total - 1)
    def _():
        copy_out(i, slot).wait()
        if n_slabs > 1:  # at the last iteration j >= 1, so i-1 is live
            copy_out(i - 1, nslot).wait()


def _pick_dma_block(lz: int, G: int, k: int, viable) -> int | None:
    """Largest z-block serving the uniform-bz in-kernel dma grid: it
    must tile the final core window exactly (``bz | lz``) and — deep
    schedules only — every in-block window extent ``lz + 2*(k-1-j)*G``
    too (``bz | 2G`` suffices, the extents differing by 2G per step)."""
    for b in range(lz, 0, -1):
        if lz % b:
            continue
        if k > 1 and (2 * G) % b:
            continue
        if viable(b):
            return b
    return None


def _whole_run_dma_kernel(offs, s_in, land_in, ss, land, vs, res, sem_v,
                          sem_w, sem_land, send_sem, recv_sem, *, step_fn,
                          bz: int, G: int, k: int, lz: int, n0: int,
                          n_iters: int, mesh_axis: str, num_shards: int):
    """Sharded whole-run grid with in-kernel neighbor halo exchange.

    Grid ``(timestep, z-slab)`` per shard; ``ss`` is the stacked
    ``(2, pz, Y, X)`` ping-pong state (aliased out), ``land`` the
    dedicated ``(2 slots, 2 sides, k*G, Y, X)`` remote-DMA landing
    buffer (aliased out; written ONLY by the neighbors' pushes). The
    schedule, with ``depth = k*G`` and in-block step ``j = s % k``:

    * step ``j`` computes the core extended by ``(k-1-j)*G`` rows per
      side (the deep-halo trapezoid) on a uniform ``bz`` z-block —
      grid iterations beyond the step's window are predicated off;
      slab loads double-buffer within the step, writes drain fully at
      each step's tail (the next step's reads are exactly this step's
      output window);
    * at each block's last step, after the write drain, every shard
      pushes its freshly written core edge windows (rows
      ``[depth, 2*depth)`` and ``[pz-2*depth, pz-depth)`` of the
      parity the next block reads) to the ±z neighbors' landing slot
      ``(b+1) % 2`` via ``make_async_remote_copy`` — cyclic ring
      pushes issued by EVERY shard in lockstep (rank-uniform sites;
      the wall shards' wrapped slabs land in rows the receiver never
      consumes, mirroring the XLA path's cyclic ``ppermute``);
    * at each block's first iteration the paired send/recv semaphores
      are waited (send: my source rows are reusable; recv: the
      neighbors' rows landed) and the landed slabs are spliced into
      the read parity's ghost rows with a local DMA — predicated per
      side on the shard's rank, so the wall sides keep their frozen
      embed BC ghosts (Dirichlet values / edge replicas, maintained
      across steps by the step windows' out-of-domain pass-through);
    * block 0 has no prior block to push for it: its exchange is the
      same pair of pushes issued at the first iteration from the
      embedded initial state (the XLA path's block-start refresh of
      the fresh embed, in-kernel).

    Pushes never address state rows — the landing buffer is the only
    remote-DMA destination — so the send/recv windows are disjoint
    from every locally computed row by construction (the invariant
    ``analysis/halo_verify`` proves from the declared
    ``remote_dma`` windows), and the 2-slot landing ping-pong plus
    the block-dependency chain (a neighbor cannot start block ``b+1``
    before receiving my block-``b`` push) bound the skew: a push for
    block ``b+2`` cannot arrive before my block-``b`` reads of that
    slot are done."""
    del s_in, land_in  # aliased with ss / land
    depth = k * G
    pz = lz + 2 * depth
    box = bz + 2 * G
    s = jnp.asarray(pl.program_id(0), jnp.int32)
    jj = jnp.asarray(pl.program_id(1), jnp.int32)
    two = jnp.asarray(2, jnp.int32)
    kk = jnp.asarray(k, jnp.int32)
    j = lax.rem(s, kk)
    b = lax.div(s, kk)
    read_par = lax.rem(s, two)
    write_par = 1 - read_par
    total = jnp.asarray(n_iters, jnp.int32)
    if k == 1:
        n_act = jnp.asarray(n0, jnp.int32)
    else:
        # bz | lz and bz | 2G make every in-block extent tile exactly
        n_act = jnp.asarray(lz // bz, jnp.int32) + (
            (kk - 1 - j) * jnp.asarray((2 * G) // bz, jnp.int32)
        )
    active = jj < n_act
    oz = offs[0]
    me = jnp.asarray(lax.axis_index(mesh_axis), jnp.int32)
    P = jnp.asarray(num_shards, jnp.int32)

    def remote_pair(slot, par):
        # my top core window -> +z neighbor's LO landing slab; my
        # bottom -> -z neighbor's HI. Sources sit inside the core
        # (rows this shard itself computed), destinations inside the
        # landing buffer only.
        up = pltpu.make_async_remote_copy(
            ss.at[par, pl.ds(pz - 2 * depth, depth)],
            land.at[slot, 0],
            send_sem.at[slot, 0],
            recv_sem.at[slot, 0],
            device_id=lax.rem(me + 1, P),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        down = pltpu.make_async_remote_copy(
            ss.at[par, pl.ds(depth, depth)],
            land.at[slot, 1],
            send_sem.at[slot, 1],
            recv_sem.at[slot, 1],
            device_id=lax.rem(me + P - 1, P),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        return up, down

    def land_copy(side: int, par):
        dst0 = 0 if side == 0 else pz - depth
        return pltpu.make_async_copy(
            land.at[lax.rem(b, two), side],
            ss.at[par, pl.ds(dst0, depth)],
            sem_land.at[side],
        )

    # ---- block start: wait the pushes, splice into the read parity's
    # ghost rows (wall sides keep the frozen embed BC ghosts) ----
    @pl.when((j == 0) & (jj == 0))
    def _():
        @pl.when(b == 0)
        def _():
            up, down = remote_pair(jnp.asarray(0, jnp.int32), read_par)
            up.start()
            down.start()

        up, down = remote_pair(lax.rem(b, two), read_par)
        up.wait()
        down.wait()

        @pl.when(me > 0)
        def _():
            land_copy(0, read_par).start()
            land_copy(0, read_par).wait()

        @pl.when(me < P - 1)
        def _():
            land_copy(1, read_par).start()
            land_copy(1, read_par).wait()

    def copy_in(slab, slot):
        return pltpu.make_async_copy(
            ss.at[read_par, pl.ds(j * G + slab * bz, box)],
            vs.at[slot],
            sem_v.at[slot],
        )

    def copy_out(slab, slot):
        return pltpu.make_async_copy(
            res.at[slot],
            ss.at[write_par, pl.ds((j + 1) * G + slab * bz, bz)],
            sem_w.at[slot],
        )

    @pl.when(jj == 0)
    def _():
        copy_in(jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32)).start()

    @pl.when(active & (jj + 1 < n_act))
    def _():
        copy_in(jj + 1, lax.rem(jj + 1, two)).start()

    @pl.when(active)
    def _():
        slot = lax.rem(jj, two)
        copy_in(jj, slot).wait()
        out = step_fn(vs[slot], j, jj, oz)

        @pl.when(jj >= 2)
        def _():
            copy_out(jj - 2, slot).wait()

        res[slot] = out
        copy_out(jj, slot).start()

    # ---- step tail (the step's last grid iteration, active or not):
    # drain the step's outstanding writes, then — at block ends — push
    # the fresh core edges for the neighbors' next block
    @pl.when(jj == n0 - 1)
    def _():
        @pl.when(n_act >= 2)
        def _():
            copy_out(n_act - 2, lax.rem(n_act - 2, two)).wait()

        copy_out(n_act - 1, lax.rem(n_act - 1, two)).wait()

        @pl.when((j == kk - 1) & (s + 1 < total))
        def _():
            up, down = remote_pair(lax.rem(b + 1, two),
                                   lax.rem(s + 1, two))
            up.start()
            down.start()


def _step_call_kernel(*refs, step_fn, bz: int, G: int, z_out0: int,
                      n_grid: int, ghost_src, op_rows: int, g_start: int,
                      sharded: bool):
    """One sharded per-step call (grid = this call's slab range): reads
    the padded state ``s_in``, writes the step result into a separate
    ping-pong target (aliased out).

    The call is parameterized on its *output window*: ``n_grid`` slabs
    of ``bz`` rows starting at padded row ``z_out0``, each computed from
    a ``bz + 2G``-row input box starting ``G`` rows above. The per-step
    schedule uses one full-core window; the communication-avoiding deep
    schedule builds one call per in-block step, the windows shrinking by
    ``G`` per step (the cross-step trapezoid of redundant ghost
    recompute). Roles mirror the per-stage split schedule: ``ghost_src``
    = "lo"/"hi" DMAs ``op_rows`` rows of the box (at its start/end) from
    the separately exchanged slab operand — ``g_hbm[g_start:]`` — instead
    of the buffer (whose exchanged-depth z ghosts are stale in split
    mode). Ghost-consuming calls are always single-slab (``n_grid == 1``)
    so the operand/buffer split is static."""
    offs = None
    if sharded:
        offs, *refs = refs
    s_in, *refs = refs
    g_hbm = None
    if ghost_src is not None:
        g_hbm, *refs = refs
    _tgt, out, vs, res, sem_v, sem_w, *refs = refs
    sem_g = refs[0] if refs else None

    k = jnp.asarray(pl.program_id(0), jnp.int32)
    slot = lax.rem(k, jnp.asarray(2, jnp.int32))
    nslot = lax.rem(k + 1, jnp.asarray(2, jnp.int32))
    box = bz + 2 * G

    def copy_in(kk, s):
        z0 = (z_out0 - G) + kk * bz  # padded row of the box's first row
        if ghost_src is None:
            return [
                pltpu.make_async_copy(
                    s_in.at[pl.ds(z0, box)], vs.at[s], sem_v.at[s]
                )
            ]
        # single-slab ghost calls: z0 == z_out0 - G, all splits static
        cps = []
        if ghost_src == "lo":
            cps.append(
                pltpu.make_async_copy(
                    g_hbm.at[pl.ds(g_start, op_rows)],
                    vs.at[s, pl.ds(0, op_rows)],
                    sem_g.at[s],
                )
            )
            if op_rows < box:
                cps.append(
                    pltpu.make_async_copy(
                        s_in.at[pl.ds(z0 + op_rows, box - op_rows)],
                        vs.at[s, pl.ds(op_rows, box - op_rows)],
                        sem_v.at[s],
                    )
                )
            return cps
        head = box - op_rows
        if head:
            cps.append(
                pltpu.make_async_copy(
                    s_in.at[pl.ds(z0, head)],
                    vs.at[s, pl.ds(0, head)],
                    sem_v.at[s],
                )
            )
        cps.append(
            pltpu.make_async_copy(
                g_hbm.at[pl.ds(g_start, op_rows)],
                vs.at[s, pl.ds(head, op_rows)],
                sem_g.at[s],
            )
        )
        return cps

    def copy_out(kk, s):
        return pltpu.make_async_copy(
            res.at[s],
            out.at[pl.ds(z_out0 + kk * bz, bz)],
            sem_w.at[s],
        )

    @pl.when(k == 0)
    def _():
        for cp in copy_in(0, 0):
            cp.start()

    @pl.when(k + 1 < n_grid)
    def _():
        for cp in copy_in(k + 1, nslot):
            cp.start()

    for cp in copy_in(k, slot):
        cp.wait()

    oz = offs[0] if offs is not None else 0
    out_rows = step_fn(vs[slot], k, oz)

    @pl.when(k >= 2)
    def _():
        copy_out(k - 2, slot).wait()

    res[slot] = out_rows
    copy_out(k, slot).start()

    @pl.when(k == n_grid - 1)
    def _():
        copy_out(k, slot).wait()
        if n_grid >= 2:
            copy_out(k - 1, nslot).wait()


class _SlabRunStepper:
    """Shared driver for the two slab whole-run steppers.

    Subclasses provide the layout (``padded_shape``, ``core_offsets``),
    ``embed``/``extract``, and ``_step_fn(v, base_z) -> (bz, Y, X)``
    (``base_z``: traced global z index of the box's first row). ``halo``
    is the fused-step halo ``G`` — under a mesh the base class's ghost
    machinery then exchanges G-deep slabs once per step."""

    engaged_label = "fused-whole-run-slab"
    needs_offsets = True  # global-coordinate masks / edge synthesis
    overlap_split = False  # sharded split instances set True in __init__
    # interface parity with the per-stage steppers (probed by callers):
    # slab mode is fixed-dt only (no stage-emitted wave speed) and never
    # runs the stored-x-ghost layout (z-slab decompositions only)
    _emit_max = False
    x_sharded = False
    # communication-avoiding chunk length k and the per-exchange ghost
    # depth k*G; sharded instances with steps_per_exchange > 1 override
    # in __init__ (models/base._fused_sharded_ctx exchanges
    # ``exchange_depth`` rows instead of the per-step stencil halo)
    k = steps_per_exchange = 1
    #: queryable stencil metadata (analysis/halo_verify.py): all three
    #: RK stages recompute per ghost refresh, so G = halo = 3 * h
    fused_stages = 3
    stencil_radius = None  # subclasses declare h (R / HALO[order])
    #: B-folded member grid axis (run_batched): declared member count of
    #: a batched instance (1 = unbatched). The member axis carries NO
    #: stencil reach — each member owns its own (2, pz, Y, X) stack and
    #: no DMA crosses members — so its halo is 0 by construction; the
    #: static verifier proves the declaration and that a batched
    #: instance never composes with spatial sharding in one program.
    members = 1
    member_halo = 0
    #: halo-exchange transport of a sharded instance: "collective"
    #: (XLA ppermute between the per-step slab calls — every schedule
    #: above) or "dma" (ONE whole-run Pallas program per shard with
    #: in-kernel `make_async_remote_copy` neighbor pushes; declared to
    #: the static verifier via ``remote_dma``); ``_init_exchange``
    #: sets the instance state
    exchange = "collective"
    remote_dma = None
    mesh_axis = None
    num_shards = None

    def stencil_spec(self) -> dict:
        """Stencil/halo contract of the slab rung (see
        ``stepper_base.FusedStepperBase.stencil_spec``): ``halo`` is
        the fused-step ghost depth ``G = 3h``, the exchange moves
        ``k * G`` rows, and the deep schedule's in-block windows shrink
        by ``G`` per step — all statically provable from these fields
        plus ``interior_shape``/``padded_shape``/``core_offsets``.
        ``members``/``member_halo`` declare the B-folded leading member
        grid axis (halo-free; ``run_batched``)."""
        return {
            "kernel": self.engaged_label,
            "stage_radius": int(self.stencil_radius),
            "fused_stages": int(self.fused_stages),
            "ghost_depth": int(self.halo),
            "exchange_depth": int(self.exchange_depth),
            "steps_per_exchange": int(self.steps_per_exchange),
            "members": int(self.members),
            "member_halo": int(self.member_halo),
            # halo-exchange transport actually engaged on this instance
            "exchange": self.exchange,
            # declared in-kernel remote-DMA window (ROADMAP item 2) —
            # None while the exchange rides XLA ppermute between slab
            # calls; exchange='dma' instances declare it and
            # halo_verify proves window/disjointness/semaphore pairing
            # against the exchange arithmetic BEFORE any hardware run
            "remote_dma": getattr(self, "remote_dma", None),
            # HBM/wire storage declaration (halo_verify derives every
            # declared byte count from it; bf16 rungs carry 2 B/cell)
            "storage_dtype": str(jnp.dtype(self.dtype)),
            "bytes_per_cell": int(jnp.dtype(self.dtype).itemsize),
        }

    def _dma_block_viable(self, b: int) -> bool:  # pragma: no cover
        raise NotImplementedError

    def _init_exchange(self, exchange, mesh_axis, num_shards) -> None:
        """Validate + arm the halo-exchange transport. ``'dma'``
        requires a sharded z-slab instance with a concrete (string)
        mesh axis — a compound multihost axis spans DCN, which remote
        DMA cannot cross — picks the uniform dma z-block, and declares
        the ``remote_dma`` contract the static verifier proves."""
        exchange = str(exchange)
        if exchange not in ("collective", "dma"):
            raise ValueError(
                f"unknown exchange mode {exchange!r}; "
                "'collective' (XLA ppermute) or 'dma' (in-kernel)"
            )
        self.exchange = exchange
        if exchange != "dma":
            return
        if not self.sharded:
            raise ValueError(
                "exchange='dma' serves sharded (z-slab) slab instances "
                "only — an unsharded run has no neighbor to push to"
            )
        if self.overlap_split:
            raise ValueError(
                "exchange='dma' replaces the XLA exchange entirely; "
                "the split-overlap schedule does not compose with it"
            )
        if not isinstance(mesh_axis, str) or num_shards is None:
            raise ValueError(
                "exchange='dma' needs the z mesh axis name and shard "
                "count (a compound/multihost mesh axis cannot host the "
                "ICI remote-DMA ring)"
            )
        self.mesh_axis = mesh_axis
        self.num_shards = int(num_shards)
        depth = self.exchange_depth
        lz = self.interior_shape[0]
        if lz < depth:
            raise ValueError(
                f"local z extent {lz} cannot serve the {depth}-deep "
                "in-kernel exchange (the pushed core edge windows "
                "would leave the shard's own rows)"
            )
        bz = _pick_dma_block(lz, self.halo, self.k,
                             self._dma_block_viable)
        if bz is None:
            raise ValueError(
                "no viable uniform z-block for the in-kernel dma grid "
                f"(lz={lz}, G={self.halo}, k={self.k})"
            )
        self._dma_bz = bz
        self._dma_n0 = (lz + 2 * (self.k - 1) * self.halo) // bz
        pz = self.padded_shape[0]
        self.remote_dma = {
            "axis": 0,
            "window_rows": depth,
            "buffers": 2,
            # pushed rows: my freshly computed core edge windows...
            "send_windows": ((depth, 2 * depth),
                             (pz - 2 * depth, pz - depth)),
            # ...landing OUTSIDE the neighbor's core — first in the
            # dedicated landing buffer, spliced into these ghost rows
            "recv_windows": ((0, depth), (pz - depth, pz)),
            "semaphores": ("send", "recv"),
            "landing": "dedicated",
        }

    def _run_dma(self, u, t, num_iters: int, offsets):
        """The whole sharded run as ONE Pallas program per shard (must
        run inside ``shard_map``): ghost rows move over ICI from inside
        the kernel — the program never returns to XLA between steps."""
        from multigpu_advectiondiffusion_tpu.ops.pallas.stepper_base import (
            chunk_counts,
        )
        from multigpu_advectiondiffusion_tpu.parallel.halo import (
            record_remote_dma,
        )

        G, k = self.halo, self.k
        depth = self.exchange_depth
        bz, n0 = self._dma_bz, self._dma_n0
        lz = self.interior_shape[0]
        full, rem_steps = chunk_counts(num_iters, k)
        blocks = full + (1 if rem_steps else 0)
        trailing = self.padded_shape[1:]
        record_remote_dma(
            kernel=self.engaged_label,
            plane_shape=trailing,
            itemsize=self.dtype.itemsize,
            window_rows=depth,
            blocks=blocks,
            mesh_axis=self.mesh_axis,
        )
        kern = functools.partial(
            _whole_run_dma_kernel,
            step_fn=lambda v, j, jj, oz: self._step_fn(
                v, j * G + jj * bz - depth + oz
            ),
            bz=bz, G=G, k=k, lz=lz, n0=n0, n_iters=num_iters,
            mesh_axis=self.mesh_axis, num_shards=self.num_shards,
        )
        S = self.embed(u)
        SS = jnp.stack([S, S])
        land = jnp.zeros((2, 2, depth) + tuple(trailing), self.dtype)
        scratch = [
            pltpu.VMEM((2, bz + 2 * G) + tuple(trailing), self.dtype),
            pltpu.VMEM((2, bz) + tuple(trailing), self.dtype),
            pltpu.SemaphoreType.DMA((2,)),   # slab loads
            pltpu.SemaphoreType.DMA((2,)),   # slab writes
            pltpu.SemaphoreType.DMA((2,)),   # landing -> state splices
            pltpu.SemaphoreType.DMA((2, 2)),  # send [slot, side]
            pltpu.SemaphoreType.DMA((2, 2)),  # recv [slot, side]
        ]
        with jax.named_scope(f"tpucfd.{self.engaged_label}[dma]"):
            out, _ = pl.pallas_call(
                kern,
                grid=(num_iters, n0),
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.SMEM),
                    pl.BlockSpec(memory_space=pl.ANY),
                    pl.BlockSpec(memory_space=pl.ANY),
                ],
                out_specs=(
                    pl.BlockSpec(memory_space=pl.ANY),
                    pl.BlockSpec(memory_space=pl.ANY),
                ),
                out_shape=(
                    jax.ShapeDtypeStruct(SS.shape, SS.dtype),
                    jax.ShapeDtypeStruct(land.shape, land.dtype),
                ),
                input_output_aliases={1: 0, 2: 1},
                scratch_shapes=scratch,
                compiler_params=(
                    None if interpret_mode() else _dma_compiler_params()
                ),
                interpret=interpret_mode(),
            )(offsets, SS, land)
        return (
            self.extract(out[num_iters % 2]),
            accumulate_t(t, self.dt, num_iters),
        )

    def _check_members(self, members: int) -> int:
        """Validate a declared member fold: the batched grid serves
        unsharded (single-chip or member-sharded) instances only — a
        spatially sharded instance runs per-step calls whose ghost
        refresh the member fold cannot cross."""
        members = int(members)
        if members < 1:
            raise ValueError(f"members must be >= 1, got {members}")
        if members > 1 and self.sharded:
            raise ValueError(
                "the B-folded slab grid composes with member sharding "
                "only; a spatially sharded slab instance cannot fold a "
                "member axis (its per-step ghost refresh would have to "
                "cross the fold)"
            )
        return members

    # populated by subclass __init__:
    #   interior_shape, global_shape, sharded, overlap_split, halo (=G),
    #   exchange_depth (=k*G), core_offsets, padded_shape, dtype
    #   (kernel), _storage, dt, bz, n_slabs, _step_fn
    #: window ledger of every sharded call built (_make_call), in
    #: construction order — the static halo verifier
    #: (analysis/halo_verify.py) proves these against the trapezoid
    #: arithmetic it re-derives from stencil_spec()
    _call_windows = ()

    def _scratch(self):
        trailing = self.padded_shape[1:]
        return [
            pltpu.VMEM((2, self.bz + 2 * self.halo) + trailing, self.dtype),
            pltpu.VMEM((2, self.bz) + trailing, self.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]

    def _whole_run(self, P, num_iters: int):
        G, bz, n_slabs = self.halo, self.bz, self.n_slabs
        kern = functools.partial(
            _whole_run_kernel,
            step_fn=lambda v, j: self._step_fn(v, j * bz - G),
            bz=bz, G=G, n_slabs=n_slabs, n_iters=num_iters,
            cross=_cross_ok(bz, G, n_slabs),
        )
        SS = jnp.stack([P, P])
        out = pl.pallas_call(
            kern,
            grid=(num_iters, n_slabs),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            out_shape=jax.ShapeDtypeStruct(SS.shape, SS.dtype),
            scratch_shapes=self._scratch(),
            input_output_aliases={0: 0},
            compiler_params=None if interpret_mode() else compiler_params(),
            interpret=interpret_mode(),
        )(SS)
        return out[num_iters % 2]

    def run_batched(self, us, ts, num_iters: int):
        """Advance B independent members ``num_iters`` fused steps in
        ONE Pallas program: the ``(timestep, z-slab)`` grid gains a
        LEADING member axis — grid ``(B, num_iters, n_slabs)``, stacked
        state ``(B, 2, pz, Y, X)``. The sequential TPU grid streams one
        member's whole run, drains its writes, then starts the next;
        scratch (the double-buffered slab/result slots) is shared
        because members never overlap in time. The member axis carries
        no stencil reach — uniform-physics ensembles ride the fastest
        rung instead of being declined (ROADMAP item 1). Unsharded
        instances only (``_check_members``); under a member-sharded
        mesh each device runs this program over its own members."""
        if self.sharded:
            raise ValueError(
                "run_batched serves unsharded slab instances only "
                "(member-sharded meshes run one fold per device; "
                "spatial sharding declines the member fold)"
            )
        B = int(us.shape[0])
        if num_iters == 0:
            return us, ts
        G, bz, n_slabs = self.halo, self.bz, self.n_slabs
        kern = functools.partial(
            _whole_run_kernel,
            step_fn=lambda v, j: self._step_fn(v, j * bz - G),
            bz=bz, G=G, n_slabs=n_slabs, n_iters=num_iters,
            cross=_cross_ok(bz, G, n_slabs), batched=True,
        )
        with jax.named_scope(f"tpucfd.{self.engaged_label}[members]"):
            S = jax.vmap(self.embed)(us)      # (B, pz, Y, X)
            SS = jnp.stack([S, S], axis=1)    # (B, 2, pz, Y, X)
            out = pl.pallas_call(
                kern,
                grid=(B, num_iters, n_slabs),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pl.ANY),
                out_shape=jax.ShapeDtypeStruct(SS.shape, SS.dtype),
                scratch_shapes=self._scratch(),
                input_output_aliases={0: 0},
                compiler_params=(
                    None if interpret_mode() else compiler_params()
                ),
                interpret=interpret_mode(),
            )(SS)
            final = jax.vmap(self.extract)(out[:, num_iters % 2])
        return final, accumulate_t(ts, self.dt, num_iters)

    def _make_call(self, z_out0: int, bz: int, n_grid: int, ghost_src=None):
        """One sharded step call writing ``n_grid`` slabs of ``bz`` rows
        at padded row ``z_out0`` (input boxes reach ``G`` rows beyond on
        both sides). ``ghost_src`` = "lo"/"hi" sources the box rows that
        fall inside the exchanged-depth ghost region from the separately
        exchanged slab operand (single-slab calls only: the split is
        computed statically here)."""
        G = self.halo
        depth = self.exchange_depth  # k*G rows per exchanged operand
        pz = self.padded_shape[0]
        box = bz + 2 * G
        op_rows = g_start = 0
        if ghost_src is not None:
            if n_grid != 1:  # pragma: no cover - internal invariant
                raise ValueError("ghost-consuming calls are single-slab")
            b0 = z_out0 - G
            if ghost_src == "lo":
                # operand covers padded rows [0, depth)
                op_rows = min(depth - b0, box)
                g_start = b0
            else:
                # operand covers padded rows [pz - depth, pz)
                op_rows = min(b0 + box - (pz - depth), box)
                g_start = b0 + (box - op_rows) - (pz - depth)
            if op_rows <= 0:  # pragma: no cover - internal invariant
                raise ValueError("ghost call consumes no operand rows")
        # global z of a box's first row: padded row minus the core
        # offset (exchange depth) plus this shard's global offset (oz,
        # traced — applied in-kernel)
        gz_base = z_out0 - G - self.core_offsets[0]
        self._call_windows.append({
            "z_out0": int(z_out0), "bz": int(bz), "n_grid": int(n_grid),
            "ghost_src": ghost_src, "op_rows": int(op_rows),
            "g_start": int(g_start),
        })

        kern = functools.partial(
            _step_call_kernel,
            step_fn=lambda v, kk, oz: self._step_fn(
                v, gz_base + kk * bz + oz
            ),
            bz=bz, G=G, z_out0=z_out0, n_grid=n_grid,
            ghost_src=ghost_src, op_rows=op_rows, g_start=g_start,
            sharded=True,
        )
        use_g = ghost_src is not None
        n_in = 1 + 1 + (1 if use_g else 0) + 1  # offs, s_in, [g], tgt
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
        in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * (n_in - 1)
        trailing = self.padded_shape[1:]
        scratch = [
            pltpu.VMEM((2, box) + trailing, self.dtype),
            pltpu.VMEM((2, bz) + trailing, self.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
        if use_g:
            scratch.append(pltpu.SemaphoreType.DMA((2,)))
        return pl.pallas_call(
            kern,
            grid=(n_grid,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            out_shape=jax.ShapeDtypeStruct(self.padded_shape, self.dtype),
            scratch_shapes=scratch,
            input_output_aliases={n_in - 1: 0},  # ping-pong target -> out
            compiler_params=None if interpret_mode() else compiler_params(),
            interpret=interpret_mode(),
        )

    def _pick_call_bz(self, extent: int) -> int:
        """Largest viable z-block tiling ``extent`` exactly (the deep
        schedule's windows all differ, so each call picks its own)."""
        raise NotImplementedError

    def _build_sharded_calls(self):
        self._call_windows = []
        G, bz, n_slabs = self.halo, self.bz, self.n_slabs
        if self.k > 1:
            self._build_deep_calls()
            return
        if self.overlap_split:
            self._calls = (
                self._make_call(G + bz, bz, n_slabs - 2),        # interior
                self._make_call(G, bz, 1, ghost_src="lo"),       # bottom
                self._make_call(G + (n_slabs - 1) * bz, bz, 1,
                                ghost_src="hi"),                  # top
            )
        else:
            self._calls = (self._make_call(G, bz, n_slabs),)

    def _build_deep_calls(self):
        """The communication-avoiding k-step block: one call per in-block
        step ``j``, its output window the core extended by
        ``(k-1-j) * G`` rows per side — the cross-step trapezoid. Step 0
        consumes the freshly exchanged ``k*G``-deep ghosts (from the
        buffer after a deep refresh, or — split mode — from the
        exchanged slab operands via single-slab edge calls that overlap
        the interior call with the in-flight ppermute); each later step
        reads exactly the previous step's output window, so nothing else
        in the block depends on communication."""
        G, k, lz = self.halo, self.k, self.interior_shape[0]
        depth = self.exchange_depth  # k*G
        pz = self.padded_shape[0]
        calls = []
        for j in range(k):
            ext = lz + 2 * (k - 1 - j) * G
            bz_j = self._pick_call_bz(ext)
            calls.append(self._make_call((j + 1) * G, bz_j, ext // bz_j))
        self._deep_calls = tuple(calls)
        if not self.overlap_split:
            return
        # split step 0: the interior call covers the window computable
        # from the locally valid core alone (box exactly [depth,
        # pz-depth)); the ghost-region output rows come from unrolled
        # single-slab edge calls consuming the exchanged operands
        ext_i = lz - 2 * G
        bz_i = self._pick_call_bz(ext_i)
        self._deep_interior = self._make_call(G + depth, bz_i,
                                              ext_i // bz_i)
        bz_e = self._pick_call_bz(depth)
        self._deep_bottom = tuple(
            self._make_call(G + i * bz_e, bz_e, 1, ghost_src="lo")
            for i in range(depth // bz_e)
        )
        self._deep_top = tuple(
            self._make_call(pz - G - depth + i * bz_e, bz_e, 1,
                            ghost_src="hi")
            for i in range(depth // bz_e)
        )

    def run(self, u, t, num_iters: int, refresh=None, offsets=None,
            exch=None):
        """``num_iters`` fused steps; returns ``(u, t)``. Unsharded: one
        whole-run Pallas program. Sharded (inside ``shard_map``): one
        slab-pipelined call per step with a G-deep z-ghost ``refresh``
        per step — or, in split mode, ``exch``'s exchanged G-slabs
        consumed by the two edge calls while the interior call overlaps
        the ppermute. With ``steps_per_exchange = k > 1`` the
        communication-avoiding schedule runs instead: ONE ``k*G``-deep
        exchange per k-step block, the in-between steps recomputing the
        ghost zone redundantly on shrinking windows (split mode overlaps
        each block's exchange with the block-start interior call)."""
        if num_iters == 0:
            return u, t
        if not self.sharded:
            with jax.named_scope(f"tpucfd.{self.engaged_label}"):
                S = self._whole_run(self.embed(u), num_iters)
            return self.extract(S), accumulate_t(t, self.dt, num_iters)

        if offsets is None:
            raise ValueError("sharded slab stepper needs offsets")
        if self.exchange == "dma":
            # in-kernel remote-DMA exchange: no refresh/exch closures —
            # the whole run is one Pallas program per shard
            return self._run_dma(u, t, num_iters, offsets)
        if self.overlap_split:
            if exch is None:
                raise ValueError("split-overlap slab stepper needs exch")
        elif refresh is None:
            raise ValueError("sharded slab stepper needs a ghost refresh")

        from multigpu_advectiondiffusion_tpu.ops.pallas.stepper_base import (
            _with_repeats,
            chunk_counts,
        )

        S = self.embed(u)
        T = S
        if self.k > 1:
            full_blocks, rem = chunk_counts(num_iters, self.k)

            def block(S, T, nsteps, refresh_b, exch_b):
                if self.overlap_split:
                    with jax.named_scope("tpucfd.slab_deep_exchange"):
                        lo, hi = exch_b(S)
                    with jax.named_scope(
                        f"tpucfd.{self.engaged_label}[deep-split]"
                    ):
                        T = self._deep_interior(offsets, S, T)
                        for c in self._deep_bottom:
                            T = c(offsets, S, lo, T)
                        for c in self._deep_top:
                            T = c(offsets, S, hi, T)
                else:
                    with jax.named_scope("tpucfd.slab_deep_refresh"):
                        S = refresh_b(S)
                    with jax.named_scope(
                        f"tpucfd.{self.engaged_label}[deep]"
                    ):
                        T = self._deep_calls[0](offsets, S, T)
                S, T = T, S
                with jax.named_scope(f"tpucfd.{self.engaged_label}[deep]"):
                    for j in range(1, nsteps):
                        T = self._deep_calls[j](offsets, S, T)
                        S, T = T, S
                return S, T

            if full_blocks:
                S, T = lax.fori_loop(
                    0, full_blocks,
                    lambda i, c: block(
                        c[0], c[1], self.k,
                        _with_repeats(refresh, full_blocks),
                        _with_repeats(exch, full_blocks),
                    ),
                    (S, T),
                )
            if rem:
                # partial tail block: a full-depth exchange still buys
                # only ``rem`` steps (priced in PARITY.md); the core is
                # valid after any prefix of a block's steps
                S, T = block(S, T, rem, refresh, exch)
            return self.extract(S), accumulate_t(t, self.dt, num_iters)

        if self.overlap_split:
            interior, bottom, top = self._calls
            exch_loop = _with_repeats(exch, num_iters)

            def body(it, carry):
                # named_scope: the split-overlap schedule's pieces are
                # separately labeled in --trace captures — the exchanged
                # G-slabs next to the interior call they overlap with
                S, T = carry
                with jax.named_scope("tpucfd.slab_split_exchange"):
                    lo, hi = exch_loop(S)
                with jax.named_scope(
                    f"tpucfd.{self.engaged_label}[split]"
                ):
                    T = top(offsets, S, hi,
                            bottom(offsets, S, lo, interior(offsets, S, T)))
                return T, S

        else:
            (full,) = self._calls
            refresh_loop = _with_repeats(refresh, num_iters)

            def body(it, carry):
                S, T = carry
                with jax.named_scope("tpucfd.slab_ghost_refresh"):
                    S = refresh_loop(S)
                with jax.named_scope(f"tpucfd.{self.engaged_label}"):
                    T = full(offsets, S, T)
                return T, S

        S, T = lax.fori_loop(0, num_iters, body, (S, T))
        return self.extract(S), accumulate_t(t, self.dt, num_iters)


# --------------------------------------------------------------------- #
# Diffusion
# --------------------------------------------------------------------- #

_G_DIFF = 3 * R  # 6: three O4 stages of redundant recompute


def _diff_row_bytes(interior_shape, itemsize: int) -> int:
    ny, nx = interior_shape[1], interior_shape[2]
    return (
        round_up(ny + 2 * R, SUBLANE) * round_up(nx + 2 * R, LANE) * itemsize
    )


def _diff_budget_rows(row_bytes: int) -> int:
    # the same calibrated shape as the whole-step stepper's picker (~8
    # live row-sized buffers per block row + fixed overhead incl. the
    # doubled slab/result slots), against the Mosaic scoped ceiling
    return max(1, min(20, int((VMEM_LIMIT // row_bytes - 130) // 8)))


def _split_block(nz: int, cap: int, G: int, viable) -> int | None:
    """Largest viable divisor of ``nz`` that can host the three-call
    split-overlap schedule: an interior band of >= 1 slab (n_slabs >= 3)
    whose boxes never reach the stale ghost rows (bz >= G)."""
    for b in range(min(cap, nz // 3), G - 1, -1):
        if nz % b == 0 and viable(b):
            return b
    return None


def _pick_bz_diffusion(nz: int, row_bytes: int, sharded: bool,
                       G: int = _G_DIFF, want_split: bool = False):
    cap = _diff_budget_rows(row_bytes)
    if sharded:
        if want_split:
            b = _split_block(nz, cap, G, lambda b: True)
            if b is not None:
                return b
        # exchanged cores forbid dead rows: largest divisor <= cap
        for b in range(min(cap, nz), 0, -1):
            if nz % b == 0:
                return b
        return 1
    # unsharded: dead tail rows are legal — score the halo amortization
    # bz/(bz+2G) against the wasted dead rows (as FusedDiffusionStepper)
    def score(b):
        blocks = -(-nz // b)
        return (b / (b + 2 * G)) * (nz / (blocks * b))

    return max(range(1, cap + 1), key=score)


class SlabRunDiffusionStepper(_SlabRunStepper):
    """Whole-run slab-pipelined diffusion stepper.

    Constructor signature mirrors :class:`FusedDiffusionStepper` so the
    two are interchangeable at the dispatch site. ``storage_dtype``
    (e.g. f64) keeps the *state* at that precision while the kernels run
    ``dtype`` (f32) — the f64-storage/f32-compute rung: Mosaic has no
    f64 vector path, so TPU f64 configs ride the f32 kernels and pay
    only the cast at the run boundary (accuracy priced in PARITY.md).
    """

    halo = _G_DIFF
    stencil_radius = R  # O4 Laplacian reach; G = 3 * R

    def __init__(self, interior_shape, dtype, spacing, diffusivity, dt,
                 band, bc_value, block_z=None, global_shape=None,
                 overlap_split: bool = False, storage_dtype=None,
                 steps_per_exchange: int = 1, members: int = 1,
                 exchange: str = "collective", mesh_axis=None,
                 num_shards=None):
        nz, ny, nx = interior_shape
        G = _G_DIFF
        self.interior_shape = tuple(interior_shape)
        self.global_shape = tuple(global_shape or interior_shape)
        self.sharded = self.global_shape != self.interior_shape
        self.dtype = jnp.dtype(dtype)
        self._storage = jnp.dtype(storage_dtype or dtype)
        self.bc_value = float(bc_value)
        self.members = self._check_members(members)
        k = _check_steps_per_exchange(steps_per_exchange, self.sharded,
                                      nz, G)
        self.k = self.steps_per_exchange = k
        self.exchange_depth = k * G
        row_bytes = _diff_row_bytes(interior_shape, self.dtype.itemsize)
        if block_z is None:
            block_z = _pick_bz_diffusion(
                nz, row_bytes, self.sharded,
                want_split=bool(overlap_split and self.sharded),
            )
        elif self.sharded and nz % block_z != 0:
            raise ValueError(
                f"block_z={block_z} must divide local nz={nz} when sharded"
            )
        bz = self.bz = block_z
        nz_eff = nz if self.sharded else -(-nz // bz) * bz
        self.n_slabs = nz_eff // bz
        # bf16 buffers need the doubled sublane tile (min tile (16, 128))
        sub = SUBLANE * max(1, 4 // self.dtype.itemsize)
        self.padded_shape = (
            nz_eff + 2 * self.exchange_depth,
            round_up(ny + 2 * R, sub),
            round_up(nx + 2 * R, LANE),
        )
        self.core_offsets = (self.exchange_depth, R, R)
        scales = tuple(
            float(diffusivity[i]) / (12.0 * spacing[i] * spacing[i])
            for i in range(3)
        )
        self.dt = float(dt)
        # split-overlap needs interior work that never touches the stale
        # z-ghost rows: per-step (k=1) that is >= 3 slabs with bz >= G;
        # the deep schedule's block-start interior call just needs a
        # non-empty window strictly inside the exchanged core (nz > 2G)
        if k > 1:
            self.overlap_split = bool(
                overlap_split and self.sharded and nz > 2 * G
            )
        else:
            self.overlap_split = bool(
                overlap_split and self.sharded
                and self.n_slabs >= 3 and bz >= G
            )

        stage = functools.partial(
            _stage_rows, interior_shape=self.global_shape, scales=scales,
            dt=self.dt, band=band, bc_value=float(bc_value),
        )
        (a1, b1), (a2, b2), (a3, b3) = _STAGES

        def step_fn(v, base_z):
            # the whole-step chain (fused_diffusion_step) on one slab:
            # windows narrow by 2R per stage, masks at global z indices.
            # Window extents derive from the box (not self.bz) so the
            # deep schedule's per-call block sizes all serve; rows
            # outside the global domain pass through _stage_rows
            # untouched (neither interior nor face), keeping the
            # exchanged Dirichlet ghosts frozen across a k-step block.
            w = v.shape[0]
            t1 = stage(v, None, gz0=base_z + R, a=a1, b=b1)
            t2 = stage(t1, v[2 * R: w - 2 * R],
                       gz0=base_z + 2 * R, a=a2, b=b2)
            return stage(t2, v[3 * R: w - 3 * R],
                         gz0=base_z + 3 * R, a=a3, b=b3)

        if self.dtype == jnp.bfloat16:
            # bf16-storage/f32-compute (ISSUE 16): the slab buffers (and
            # every wire byte) stay bf16; each slab upcasts once, runs
            # the three RK stages in f32, and downcasts the core rows
            inner = step_fn

            def step_fn(v, base_z):
                return inner(
                    v.astype(jnp.float32), base_z
                ).astype(jnp.bfloat16)

        self._step_fn = step_fn
        self._init_exchange(exchange, mesh_axis, num_shards)
        if self.sharded and self.exchange != "dma":
            self._build_sharded_calls()

    def _dma_block_viable(self, b: int) -> bool:
        row = _diff_row_bytes(self.interior_shape, self.dtype.itemsize)
        return b <= _diff_budget_rows(row)

    @staticmethod
    def supported(interior_shape, dtype, sharded: bool = False) -> bool:
        row = _diff_row_bytes(interior_shape, jnp.dtype(dtype).itemsize)
        if _diff_budget_rows(row) < 1:
            return False
        if sharded:
            return interior_shape[0] >= 1
        return True

    @staticmethod
    def profitable(interior_shape, dtype, sharded: bool = False) -> bool:
        """Where the slab schedule is modeled to beat the per-stage
        path. Deliberately conservative: the whole-step rung — the same
        fused-3-stages-with-redundant-recompute structure, minus the
        multi-step grid — *measured slower* than per-stage on v5e
        ("compute growth outweighs the HBM saving", PARITY.md), so deep
        multi-slab grids keep the measured per-stage default until a
        TPU session measures the whole-run variant
        (``impl='pallas_slab'`` pins it for that). The structural wins
        engage automatically: z extents served by one or two slabs
        (near-whole-state-in-VMEM per step, minimal redundant rows),
        and hypothetically slabs thick enough that the recompute tax is
        noise (bz >= 4G — above today's VMEM-budget cap at bench-scale
        rows, so effectively future-proofing)."""
        nz = interior_shape[0]
        row = _diff_row_bytes(interior_shape, jnp.dtype(dtype).itemsize)
        bz = _pick_bz_diffusion(nz, row, sharded)
        n_slabs = -(-nz // bz)
        return bz >= 4 * _G_DIFF or n_slabs <= 2

    def _pick_call_bz(self, extent: int) -> int:
        row = _diff_row_bytes(self.interior_shape, self.dtype.itemsize)
        return _pick_bz_diffusion(extent, row, True, G=self.halo)

    def embed(self, u):
        full = jnp.full(self.padded_shape, self.bc_value, self.dtype)
        return lax.dynamic_update_slice(
            full, u.astype(self.dtype), self.core_offsets
        )

    def extract(self, S):
        nz, ny, nx = self.interior_shape
        d = self.exchange_depth
        out = lax.slice(S, (d, R, R), (d + nz, R + ny, R + nx))
        return out.astype(self._storage)


# --------------------------------------------------------------------- #
# Burgers / WENO
# --------------------------------------------------------------------- #


def _burg_row_bytes(interior_shape, itemsize: int, r: int) -> int:
    ny, nx = interior_shape[1], interior_shape[2]
    return (
        round_up(ny + 2 * r, SUBLANE) * round_up(nx + 2 * r, LANE) * itemsize
    )


def _burg_live_rows(bz: int, r: int, order: int) -> int:
    """Model of the live full-width row count: pipeline slots + stage
    windows + the widest stage's sweep intermediates (as fused_burgers's
    ``_live_bytes``, but on full-width rows)."""
    G = 3 * r
    k = 14 if order == 5 else 20
    return 2 * (bz + 2 * G) + 2 * bz + (bz + 4 * r) + (bz + 2 * r) + k * (
        bz + 4 * r
    )


def _pick_bz_burgers(nz: int, row_bytes: int, r: int, order: int,
                     want_split: bool = False):
    """Largest divisor of nz whose modeled working set fits the budget
    (no dead z rows: edge replication indexes the last interior row at a
    static slab-local position only when blocks tile nz exactly).
    ``want_split``: prefer a block the split-overlap schedule can use
    (n_slabs >= 3, bz >= G) when one fits."""
    def fits(b):
        return _burg_live_rows(b, r, order) * row_bytes <= _VMEM_BUDGET

    if want_split:
        b = _split_block(nz, nz, 3 * r, fits)
        if b is not None:
            return b
    for b in range(nz, 0, -1):
        if nz % b == 0 and fits(b):
            return b
    return None


class SlabRunBurgersStepper(_SlabRunStepper):
    """Whole-run slab-pipelined Burgers/WENO stepper (fixed dt).

    Layout is the 2-D whole-run stepper's, extruded: trailing dims
    ``(round8(ny+2r), round128(nx+2r))`` with inline edge-replicated
    ghosts re-synthesized in VMEM after every stage (x/y always; z at
    the global walls, keyed on global coordinates so sharded shards
    leave their neighbor-filled ghost rows alone). Adaptive dt needs a
    global reduction between steps, which the whole-run grid cannot
    host — adaptive configs keep the per-stage stepper.
    """

    def __init__(self, interior_shape, dtype, spacing, flux: Flux,
                 variant: str, nu: float, dt: float, block_z=None,
                 global_shape=None, overlap_split: bool = False,
                 order: int = 5, steps_per_exchange: int = 1,
                 members: int = 1, exchange: str = "collective",
                 mesh_axis=None, num_shards=None, storage_dtype=None):
        if order not in HALO:
            raise ValueError(f"unsupported WENO order {order}")
        if order == 7 and variant != "js":
            raise ValueError("WENO7 supports only the 'js' variant")
        r = HALO[order]
        G = 3 * r
        self.order = order
        self.halo = G
        self.stencil_radius = r  # WENO reach; G = 3 * r
        nz, ny, nx = interior_shape
        self.interior_shape = tuple(interior_shape)
        self.global_shape = tuple(global_shape or interior_shape)
        self.sharded = self.global_shape != self.interior_shape
        self.dtype = jnp.dtype(dtype)
        # storage_dtype is the FACING dtype (the fused-stepper
        # convention): extract restores it; bf16 kernel buffers under
        # precision='bf16' face an f32 state
        self._storage = jnp.dtype(storage_dtype or dtype)
        self.members = self._check_members(members)
        k = _check_steps_per_exchange(steps_per_exchange, self.sharded,
                                      nz, G)
        self.k = self.steps_per_exchange = k
        self.exchange_depth = k * G
        row_bytes = _burg_row_bytes(interior_shape, self.dtype.itemsize, r)
        if block_z is None:
            block_z = _pick_bz_burgers(
                nz, row_bytes, r, order,
                want_split=bool(overlap_split and self.sharded),
            )
            if block_z is None:
                raise ValueError(
                    f"no viable slab block for interior {interior_shape}"
                )
        elif nz % block_z != 0:
            raise ValueError(f"block_z={block_z} must divide nz={nz}")
        bz = self.bz = block_z
        self.n_slabs = nz // bz
        # bf16 buffers need the doubled sublane tile (min tile (16, 128))
        sub = SUBLANE * max(1, 4 // self.dtype.itemsize)
        self.padded_shape = (
            nz + 2 * self.exchange_depth,
            round_up(ny + 2 * r, sub),
            round_up(nx + 2 * r, LANE),
        )
        self.r = r
        self.core_offsets = (self.exchange_depth, r, r)
        self.dt = float(dt)
        if k > 1:
            self.overlap_split = bool(
                overlap_split and self.sharded and nz > 2 * G
            )
        else:
            self.overlap_split = bool(
                overlap_split and self.sharded
                and self.n_slabs >= 3 and bz >= G
            )
        inv_dx = tuple(1.0 / spacing[i] for i in range(3))
        nu_scales = None
        if nu:
            nu_scales = tuple(
                float(nu) / (12.0 * spacing[i] * spacing[i])
                for i in range(3)
            )
        NZ, NY, NX = self.global_shape

        deep = k > 1

        def fill(t, base, zsrc):
            """Edge-replicate ghost/slack cells (WENO5resAdv_X.m:53):
            x/y from the static boundary columns; z keyed on *global*
            row indices, so the masks are nonempty only on the slabs
            (and shards) that actually touch a wall. ``zsrc``: ``None``
            skips the z fill (the window has no out-of-domain rows), a
            static ``(lo_src, hi_src)`` pair names the replica source
            rows at fixed slab-local positions (per-step schedule), and
            ``"dyn"`` indexes them dynamically from the traced window
            origin — the deep schedule's windows shift per in-block
            step, so the wall row has no fixed slab-local position
            (clipped: when the wall is outside this box the mask is
            empty and the clipped read is harmless)."""
            gx = lax.broadcasted_iota(jnp.int32, t.shape, 2) - r
            t = jnp.where(gx < 0, t[:, :, r: r + 1], t)
            t = jnp.where(gx >= NX, t[:, :, r + NX - 1: r + NX], t)
            gy = lax.broadcasted_iota(jnp.int32, t.shape, 1) - r
            t = jnp.where(gy < 0, t[:, r: r + 1], t)
            t = jnp.where(gy >= NY, t[:, r + NY - 1: r + NY], t)
            if zsrc is None:
                return t
            gz = lax.broadcasted_iota(jnp.int32, t.shape, 0) + base
            if zsrc == "dyn":
                n = t.shape[0]
                zero = jnp.asarray(0, jnp.int32)
                top = jnp.asarray(n - 1, jnp.int32)
                lo = lax.dynamic_slice_in_dim(
                    t, jnp.clip(-base, zero, top), 1, axis=0
                )
                hi = lax.dynamic_slice_in_dim(
                    t, jnp.clip(NZ - 1 - base, zero, top), 1, axis=0
                )
            else:
                lo_src, hi_src = zsrc
                lo = t[lo_src: lo_src + 1]
                hi = t[hi_src: hi_src + 1]
            t = jnp.where(gz < 0, lo, t)
            t = jnp.where(gz >= NZ, hi, t)
            return t

        def stage(u, vwin, a, b, w_out, base, zsrc, dtv):
            vc = vwin[r: r + w_out]
            vp, vm = _split(flux, vwin)
            Y = vwin.shape[1]
            rhs = -(
                _div_z(vp, vm, w_out, Y, inv_dx[0], variant, order, r, y0=0)
                + _div_roll(vp[r: r + w_out], vm[r: r + w_out], 1,
                            inv_dx[1], variant, order)
                + _div_roll(vp[r: r + w_out], vm[r: r + w_out], 2,
                            inv_dx[2], variant, order)
            )
            if nu_scales is not None:
                acc = None
                for axis in range(3):
                    for jj, c in enumerate(O4_COEFFS):
                        coef = jnp.asarray(c * nu_scales[axis], vwin.dtype)
                        if axis == 0:
                            term = vwin[r - 2 + jj: r - 2 + jj + w_out] * coef
                        else:
                            term = _shift(vc, jj - 2, axis) * coef
                        acc = term if acc is None else acc + term
                rhs = rhs + acc
            rk = b * (vc + dtv * rhs) if a == 0.0 else (
                a * u + b * (vc + dtv * rhs)
            )
            return fill(rk.astype(vwin.dtype), base, zsrc)

        (a1, b1), (a2, b2), (a3, b3) = _STAGES
        dt_f = self.dt  # python float: materialized in-kernel, not captured

        def step_fn(v, base_z):
            d = jnp.asarray(dt_f, v.dtype)
            # windows derive from the box (not self.bz): the deep
            # schedule's per-call block sizes all route through here.
            # Step-input z ghosts are stale in HBM (never rewritten):
            # re-synthesize at the global walls; shard-interior ghosts
            # hold fresh neighbor rows (refresh/exch) and pass through
            w = v.shape[0]
            bw = w - 2 * G
            v = fill(v, base_z, "dyn" if deep else (G, bw + G - 1))
            t1 = stage(None, v, a1, b1, w - 2 * r, base_z + r,
                       "dyn" if deep else (G - r, bw + 2 * r - 1), d)
            t2 = stage(v[2 * r: w - 2 * r], t1, a2, b2, w - 4 * r,
                       base_z + 2 * r,
                       "dyn" if deep else (G - 2 * r, bw + r - 1), d)
            # k=1: stage-3 output is exactly the core — no z-ghost rows
            # left; deep windows still carry ghost-region rows, which on
            # wall shards may sit outside the domain and need the
            # replica fill like every other stage
            return stage(v[G: w - G], t2, a3, b3, bw,
                         base_z + G, "dyn" if deep else None, d)

        if self.dtype == jnp.bfloat16:
            # bf16-storage/f32-compute (ISSUE 16): slab buffers and
            # wire bytes stay bf16; the WENO reconstruction and RK
            # stages run in f32 per slab
            inner = step_fn

            def step_fn(v, base_z):
                return inner(
                    v.astype(jnp.float32), base_z
                ).astype(jnp.bfloat16)

        self._step_fn = step_fn
        self._init_exchange(exchange, mesh_axis, num_shards)
        if self.sharded and self.exchange != "dma":
            self._build_sharded_calls()

    def _dma_block_viable(self, b: int) -> bool:
        row = _burg_row_bytes(
            self.interior_shape, self.dtype.itemsize, self.r
        )
        return _burg_live_rows(b, self.r, self.order) * row <= _VMEM_BUDGET

    @staticmethod
    def supported(interior_shape, dtype, order: int = 5) -> bool:
        r = HALO[order]
        row = _burg_row_bytes(interior_shape, jnp.dtype(dtype).itemsize, r)
        return _pick_bz_burgers(interior_shape[0], row, r, order) is not None

    @staticmethod
    def profitable(interior_shape, dtype, order: int = 5) -> bool:
        """The WENO stages are VPU-bound, so the 2r/bz redundant-compute
        tax must stay small for the traffic cut to matter: engage only
        with thick slabs or a one/two-slab z extent (where the per-call
        overhead saving dominates anyway). ``impl='pallas_slab'``
        overrides for measurement."""
        r = HALO[order]
        nz = interior_shape[0]
        row = _burg_row_bytes(interior_shape, jnp.dtype(dtype).itemsize, r)
        bz = _pick_bz_burgers(nz, row, r, order)
        if bz is None:
            return False
        return bz >= 6 * r or nz // bz <= 2

    def _pick_call_bz(self, extent: int) -> int:
        row = _burg_row_bytes(
            self.interior_shape, self.dtype.itemsize, self.r
        )
        b = _pick_bz_burgers(extent, row, self.r, self.order)
        if b is None:  # pragma: no cover - _VMEM_BUDGET admits bz=1
            raise ValueError(
                f"no viable slab block for a {extent}-row deep window"
            )
        return b

    def embed(self, u):
        d, r = self.exchange_depth, self.r
        nz, ny, nx = self.interior_shape
        pz, py, px = self.padded_shape
        return jnp.pad(
            u.astype(self.dtype),
            ((d, d), (r, py - ny - r), (r, px - nx - r)),
            mode="edge",
        )

    def extract(self, S):
        d, r = self.exchange_depth, self.r
        nz, ny, nx = self.interior_shape
        out = lax.slice(S, (d, r, r), (d + nz, r + ny, r + nx))
        return out.astype(self._storage)
