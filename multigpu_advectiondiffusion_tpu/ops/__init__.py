from multigpu_advectiondiffusion_tpu.ops import flux, laplacian, weno, stencils, axisym

__all__ = ["flux", "laplacian", "weno", "stencils", "axisym"]


def is_pallas_impl(impl: str) -> bool:
    """Whether a solver ``impl`` string selects a Pallas kernel flavor
    ("pallas", "pallas_step", ...) — the single definition both solvers'
    eligibility checks use."""
    return impl.startswith("pallas")


def op_impl(impl: str) -> str:
    """Normalize a solver ``impl`` flavor to what the per-op dispatchers
    accept: every Pallas flavor maps to "pallas"."""
    return "pallas" if is_pallas_impl(impl) else impl
