from multigpu_advectiondiffusion_tpu.ops import flux, laplacian, weno, stencils, axisym

__all__ = ["flux", "laplacian", "weno", "stencils", "axisym"]

# Every kernel-strategy rung a config may request ("pallas" = best
# available, suffixed flavors pin one rung, "auto" = measured: the
# tuning subsystem resolves it to a concrete rung + steps_per_exchange
# from its persisted decision cache at solver construction). The
# configs validate against this so a typo'd impl fails at construction
# instead of silently benchmarking the generic path — and the
# resilience ladder's degradation targets are guaranteed members.
IMPLS = (
    "xla", "pallas", "pallas_axis", "pallas_step", "pallas_slab",
    "pallas_stage", "auto",
)


def is_pallas_impl(impl: str) -> bool:
    """Whether a solver ``impl`` string selects a Pallas kernel flavor
    ("pallas", "pallas_axis", "pallas_step", "pallas_slab",
    "pallas_stage", ...) — the single definition both solvers'
    eligibility checks use. "pallas" promises best-available; the
    suffixed flavors pin one rung of the stepper ladder (slab whole-run
    / per-stage / whole-step / per-axis)."""
    return impl.startswith("pallas")


def is_fused_impl(impl: str) -> bool:
    """Whether the flavor may engage a fused whole-stage/whole-run
    stepper. "pallas_axis" explicitly opts out — it pins the per-axis
    slab kernels, an explicit rung of the kernel-strategy ladder (the
    analog of benchmarking the reference's non-fused variants)."""
    return is_pallas_impl(impl) and impl != "pallas_axis"


def op_impl(impl: str) -> str:
    """Normalize a solver ``impl`` flavor to what the per-op dispatchers
    accept: every Pallas flavor maps to "pallas"."""
    return "pallas" if is_pallas_impl(impl) else impl
