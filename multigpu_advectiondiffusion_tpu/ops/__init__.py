from multigpu_advectiondiffusion_tpu.ops import flux, laplacian, weno, stencils, axisym

__all__ = ["flux", "laplacian", "weno", "stencils", "axisym"]
