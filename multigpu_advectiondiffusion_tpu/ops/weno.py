"""WENO5-JS / WENO5-Z / WENO7-JS flux-divergence operators.

TPU-native re-design of the reference's flux reconstruction:

* WENO5-JS dual reconstruction — ``Reconstruct1d``
  (``MultiGPU/Burgers3d_Baseline/Kernels.cu:112-220``) and the MATLAB ground
  truth ``Matlab_Prototipes/InviscidBurgersNd/WENO5resAdv_X.m:57-125``.
* WENO5-Z weights — ``WENO5Zreconstruction``
  (``SingleGPU/Burgers3d_WENO5_SharedMem/kernels.cu:153-207``):
  ``alpha_k = d_k * (1 + tau5/(beta_k + eps))`` with ``tau5 = |B0 - B2|``.
* WENO7-JS — ``Matlab_Prototipes/InviscidBurgersNd/WENO7resAdv_X.m``.

Splitting is component-wise (local) Lax–Friedrichs, exactly as in the
reference: ``f^{+-} = (f(u) +- |f'(u)| u)/2`` per point
(``WENO5resAdv_X.m:58-60``; the CUDA kernels inline ``|u|*u`` for Burgers,
``Burgers3d_Baseline/Kernels.cu:256-264``).

Structure: each interface flux is computed exactly once and adjacent
interfaces are differenced — the "compute each face once" idea of the
shared-memory variant (``_SharedMem/kernels.cu:212-272``) — expressed as
shifted slices of one padded array so XLA fuses the entire sweep.

Deviation from the reference (intentional): the MATLAB residual leaves the
first interface flux of the sweep zero-filled (``WENO5resAdv_X.m:54,125``
reads ``hn(:,I-1,:)`` at positions it never wrote), corrupting the first
cell's residual. Here every one of the ``N+1`` interfaces is reconstructed
from properly padded data.
"""

from __future__ import annotations

import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu.core.bc import Boundary, pad_axis
from multigpu_advectiondiffusion_tpu.ops.flux import Flux
from multigpu_advectiondiffusion_tpu.ops.stencils import (
    GhostFn,
    Padder,
    shifted,
    split_axis_apply,
)

HALO = {5: 3, 7: 4}
EPSILON = 1e-6  # WENO5resAdv_X.m:75

# Optimal linear weights, upwind-biased ("minus") side.
_D5 = (0.1, 0.6, 0.3)  # WENO5resAdv_X.m:75
_D7 = (1.0 / 35.0, 12.0 / 35.0, 18.0 / 35.0, 4.0 / 35.0)  # WENO7resAdv_X.m:85


def _weno5_betas(q0, q1, q2, q3, q4):
    b0 = 13.0 / 12.0 * (q0 - 2 * q1 + q2) ** 2 + 0.25 * (q0 - 4 * q1 + 3 * q2) ** 2
    b1 = 13.0 / 12.0 * (q1 - 2 * q2 + q3) ** 2 + 0.25 * (q1 - q3) ** 2
    b2 = 13.0 / 12.0 * (q2 - 2 * q3 + q4) ** 2 + 0.25 * (3 * q2 - 4 * q3 + q4) ** 2
    return b0, b1, b2


def _weno5_alphas_unnormalized(betas, d, variant):
    """Unnormalized nonlinear weights, single-division form.

    The textbook JS weights ``alpha_k = d_k/(eps+beta_k)^2`` cost one
    division per stencil plus one for the normalization — 4 per
    reconstruction, and divisions dominate the WENO op mix on the TPU VPU
    (no native divide; each lowers to a Newton-iterated reciprocal).
    Multiplying every alpha by ``prod_j (eps+beta_j)^2`` — which cancels
    in the normalized weights exactly — gives the division-free form
    ``alpha_k' = d_k * (prod_{j != k} (eps+beta_j))^2``; the caller then
    spends the reconstruction's single division on the normalization.
    Same algebra for the Z weights ``d_k (1 + tau5/(beta_k+eps))``:
    ``alpha_k' = d_k (beta_k+eps+tau5) * prod_{j != k} (beta_j+eps)``.

    Range note (f32): alphas' scale as ``beta^4`` (JS), overflowing only
    when ``beta > ~4e9``, i.e. cell-to-cell jumps beyond ~3e4 — far
    outside any physical use of these solvers; f64 is available for more.
    """
    s0, s1, s2 = (b + EPSILON for b in betas)
    if variant == "js":
        return (
            d[0] * (s1 * s2) ** 2,
            d[1] * (s0 * s2) ** 2,
            d[2] * (s0 * s1) ** 2,
        )
    if variant == "z":
        tau5 = jnp.abs(betas[0] - betas[2])
        return (
            d[0] * (s0 + tau5) * (s1 * s2),
            d[1] * (s1 + tau5) * (s0 * s2),
            d[2] * (s2 + tau5) * (s0 * s1),
        )
    raise ValueError(f"unknown WENO5 variant {variant!r}; use 'js' or 'z'")


def _weno5_minus(q0, q1, q2, q3, q4, variant):
    """Reconstruct u^- at the interface right of center cell q2."""
    a0, a1, a2 = _weno5_alphas_unnormalized(
        _weno5_betas(q0, q1, q2, q3, q4), _D5, variant
    )
    num = (
        a0 * (2 * q0 - 7 * q1 + 11 * q2)
        + a1 * (-q1 + 5 * q2 + 2 * q3)
        + a2 * (2 * q2 + 5 * q3 - q4)
    )
    return num / (6.0 * (a0 + a1 + a2))


def _weno5_plus(q0, q1, q2, q3, q4, variant):
    """Reconstruct u^+ at the interface left of center cell q2."""
    d = tuple(reversed(_D5))
    a0, a1, a2 = _weno5_alphas_unnormalized(
        _weno5_betas(q0, q1, q2, q3, q4), d, variant
    )
    num = (
        a0 * (-q0 + 5 * q1 + 2 * q2)
        + a1 * (2 * q1 + 5 * q2 - q3)
        + a2 * (11 * q2 - 7 * q3 + 2 * q4)
    )
    return num / (6.0 * (a0 + a1 + a2))


_C13 = 13.0 / 12.0  # curvature coefficient of the smoothness indicators


def _curv(dd):
    """Curvature term ``13/12 dd^2`` of a second difference
    ``dd_j = e_{j+1} - e_j``. In slice-cheap sweeps (the fused z sweep)
    the caller computes one shared array and passes windows; in
    shift-bound sweeps :func:`_weno5_side_nd_e` recomputes it per
    window. One definition keeps the ``(c * dd) * dd`` association
    uniform across sweeps (the sharded-vs-unsharded fused equality
    tests hold to a documented few-ulp bound, not bitwise — XLA's
    interpret-mode contraction freedom already rules that out)."""
    return _C13 * dd * dd


def _weno5_side_nd_e(e0, e1, e2, e3, variant, side):
    """:func:`_weno5_side_nd` with the curvature terms recomputed from
    the extracted windows instead of sliced from a shared array. For
    sweeps whose window extraction pays a real shift per array (lane
    rolls, sublane realignments) this trades 3 shift ops for ~9 cheap
    FMAs — on the TPU VPU the shift/permute unit, not the ALU, is the
    binding resource of the fused WENO kernels (measured: removing ~8%
    of the ALU ops moved the 512^3 rate by 0%, removing one lane tile
    moved it 14%)."""
    return _weno5_side_nd(
        e0, e1, e2, e3,
        _curv(e1 - e0), _curv(e2 - e1), _curv(e3 - e2),
        variant, side,
    )


def _weno5_side_nd(e0, e1, e2, e3, cd0, cd1, cd2, variant, side):
    """One WENO5 reconstruction in forward-difference form, returned as
    unnormalized ``(numerator, denominator)`` of the *deviation from the
    center cell*: the reconstructed value is ``q2 + num/den``.

    ``e_j = q_{j+1} - q_j`` over the 5-cell window ``q0..q4``, and
    ``cd_k`` are the betas' *curvature* terms ``13/12 (e_{k+1}-e_k)^2``
    — windows of ONE shared second-difference array: the three betas of
    one reconstruction and the betas of *neighboring* interfaces all
    draw on the same array, so sweep kernels compute it once and pass
    shifted windows. ``side`` is ``"minus"`` (reconstruct u^- at the
    interface right of the center) or ``"plus"`` (u^+ at the interface
    left of it).

    Three classic identities trim the op mix to near-minimal:
    the ``6 q2`` term of every candidate polynomial cancels against the
    normalization (so ``q2`` never enters the weighted sum — the caller
    adds it once, after the division), the ``1/6`` of the candidates is
    folded into their e-coefficients, and the betas' ``0.25 l^2`` is
    ``(l/2)^2`` with ``l/2`` formed directly by one FMA.

    Returning num/den separately leaves the division strategy to the
    caller — the fused TPU kernels spend a Newton-refined reciprocal
    estimate on it rather than Mosaic's exact-divide chain.
    """
    l0 = 1.5 * e1 - 0.5 * e0
    l1 = 0.5 * e1 + 0.5 * e2  # -(q1 - q3)/2; sign irrelevant, squared
    l2 = 0.5 * e3 - 1.5 * e2
    betas = (
        cd0 + l0 * l0,
        cd1 + l1 * l1,
        cd2 + l2 * l2,
    )
    d = _D5 if side == "minus" else tuple(reversed(_D5))
    a0, a1, a2 = _weno5_alphas_unnormalized(betas, d, variant)
    s = 1.0 / 6.0
    if side == "minus":
        num = (
            a0 * (5.0 * s * e1 - 2.0 * s * e0)
            + a1 * (s * e1 + 2.0 * s * e2)
            + a2 * (4.0 * s * e2 - s * e3)
        )
    else:
        num = (
            a0 * (s * e0 - 4.0 * s * e1)
            + a1 * (-2.0 * s * e1 - s * e2)
            + a2 * (2.0 * s * e3 - 5.0 * s * e2)
        )
    return num, a0 + a1 + a2




# WENO7 smoothness indicators as quadratic forms in the three first
# differences of each 4-cell stencil. The q-form betas (``_weno7_betas``
# below, ``WENO7resAdv_X.m:60-83``) are shift-invariant, so the rewrite
# ``beta_k = A ea^2 + B eb^2 + C ec^2 + D ea eb + E eb ec + F ea ec``
# with ``(ea, eb, ec) = (e_k, e_{k+1}, e_{k+2})`` is exact; coefficients
# derived symbolically in ``out/weno7_diffform.py``. Note the mirror
# symmetry (beta3/beta0, beta2/beta1 swap A<->C, D<->E) — the same
# left/right symmetry the q-form hides.
_B7 = (
    (6649.0, 45076.0, 25729.0, -33916.0, -63436.0, 22778.0),
    (3169.0, 17236.0, 6649.0, -13036.0, -17116.0, 5978.0),
    (6649.0, 17236.0, 3169.0, -17116.0, -13036.0, 5978.0),
    (25729.0, 45076.0, 6649.0, -63436.0, -33916.0, 22778.0),
)

# Candidate-polynomial deviations from the center cell (x12), in the
# same per-stencil difference windows: stencil k's candidate is
# ``c + (ca e_k + cb e_{k+1} + cc e_{k+2})/12``. Derived alongside _B7;
# the plus side is the minus side under ``e_j -> -e_{5-j}``.
_C7 = {
    "minus": ((3.0, -10.0, 13.0), (-1.0, 4.0, 3.0),
              (1.0, 6.0, -1.0), (9.0, -4.0, 1.0)),
    "plus": ((-1.0, 4.0, -9.0), (1.0, -6.0, -1.0),
             (-3.0, -4.0, 1.0), (-13.0, 10.0, -3.0)),
}


def _weno7_side_nd_e(e0, e1, e2, e3, e4, e5, side):
    """One WENO7-JS reconstruction in forward-difference form, returned
    as unnormalized ``(numerator, denominator)`` of the deviation from
    the center cell: the reconstructed value is ``q3 + num/den``.

    ``e_j = q_{j+1} - q_j`` over the 7-cell window ``q0..q6`` (center
    ``q3``). ``side`` as in :func:`_weno5_side_nd`. The betas are the
    :data:`_B7` quadratic forms; the nonlinear weights use the
    division-free formulation (multiply every textbook alpha
    ``d_k/(eps+beta_k)^2`` by ``(prod_j (eps+beta_j))^2``):
    ``alpha_k' = d_k (prod_{j != k} s_j)^2`` with ``s_j = beta_j + eps``,
    associated as ``(s s s)^2`` so every intermediate stays normal.

    Range note (f32): alphas' scale as ``beta^6`` — the smooth-field
    floor is ``d_min eps^6 ~ 2.9e-38`` (just above f32 min normal, no
    flush) and the top overflows when ``beta > ~2.6e6``, i.e.
    cell-to-cell jumps in the split flux beyond ~3.6. The solvers'
    bounded states (|u| ~ 1) keep split-flux jumps under ~3, inside the
    window; larger-amplitude data belongs on the f64 XLA path.
    """
    e = (e0, e1, e2, e3, e4, e5)
    d = _D7 if side == "minus" else tuple(reversed(_D7))
    cs = _C7[side]
    s = []
    for k in range(4):
        A, B, C, D, E, F = _B7[k]
        ea, eb, ec = e[k], e[k + 1], e[k + 2]
        beta = (A * ea + D * eb + F * ec) * ea + (B * eb + E * ec) * eb \
            + C * (ec * ec)
        s.append(beta + EPSILON)
    # shared partial products: each alpha' is d_k * (product of the
    # OTHER three s_j) squared
    p01 = s[0] * s[1]
    p23 = s[2] * s[3]
    m = (s[1] * p23, s[0] * p23, p01 * s[3], p01 * s[2])
    t = 1.0 / 12.0
    num = None
    den = None
    for k in range(4):
        a = d[k] * (m[k] * m[k])
        ca, cb, cc = cs[k]
        dev = (ca * t) * e[k] + (cb * t) * e[k + 1] + (cc * t) * e[k + 2]
        num = a * dev if num is None else num + a * dev
        den = a if den is None else den + a
    return num, den


def _weno7_betas(q):
    m3, m2, m1, c, p1, p2, p3 = q
    b0 = (
        m1 * (134241 * m1 - 114894 * c)
        + m3 * (56694 * m1 - 47214 * m2 + 6649 * m3 - 22778 * c)
        + 25729 * c * c
        + m2 * (-210282 * m1 + 85641 * m2 + 86214 * c)
    )
    b1 = (
        c * (41001 * c - 30414 * p1)
        + m2 * (-19374 * m1 + 3169 * m2 + 19014 * c - 5978 * p1)
        + 6649 * p1 * p1
        + m1 * (33441 * m1 - 70602 * c + 23094 * p1)
    )
    b2 = (
        p1 * (33441 * p1 - 19374 * p2)
        + m1 * (6649 * m1 - 30414 * c + 23094 * p1 - 5978 * p2)
        + 3169 * p2 * p2
        + c * (41001 * c - 70602 * p1 + 19014 * p2)
    )
    b3 = (
        p2 * (85641 * p2 - 47214 * p3)
        + c * (25729 * c - 114894 * p1 + 86214 * p2 - 22778 * p3)
        + 6649 * p3 * p3
        + p1 * (134241 * p1 - 210282 * p2 + 56694 * p3)
    )
    return b0, b1, b2, b3


def _weno7_weights(betas, d):
    alphas = [dk / (EPSILON + b) ** 2 for dk, b in zip(d, betas)]
    inv = 1.0 / sum(alphas[1:], alphas[0])
    return [a * inv for a in alphas]


def _weno7_minus(q):
    m3, m2, m1, c, p1, p2, p3 = q
    w0, w1, w2, w3 = _weno7_weights(_weno7_betas(q), _D7)
    return (
        w0 * (-3 * m3 + 13 * m2 - 23 * m1 + 25 * c)
        + w1 * (m2 - 5 * m1 + 13 * c + 3 * p1)
        + w2 * (-m1 + 7 * c + 7 * p1 - p2)
        + w3 * (3 * c + 13 * p1 - 5 * p2 + p3)
    ) / 12.0


def _weno7_plus(q):
    m3, m2, m1, c, p1, p2, p3 = q
    d = tuple(reversed(_D7))
    w0, w1, w2, w3 = _weno7_weights(_weno7_betas(q), d)
    return (
        w0 * (m3 - 5 * m2 + 13 * m1 + 3 * c)
        + w1 * (-m2 + 7 * m1 + 7 * c - p1)
        + w2 * (3 * m1 + 13 * c - 5 * p1 + p2)
        + w3 * (25 * c - 23 * p1 + 13 * p2 - 3 * p3)
    ) / 12.0


def interface_flux_from_padded(
    up: jnp.ndarray,
    axis: int,
    flux: Flux,
    order: int = 5,
    variant: str = "js",
) -> jnp.ndarray:
    """Numerical flux at all ``N+1`` interfaces along ``axis``.

    ``up`` must be padded with ``HALO[order]`` ghost cells on both ends of
    ``axis``. Interface ``i`` sits between cells ``i-1`` and ``i``.
    """
    r = HALO[order]
    n_if = up.shape[axis] - 2 * r + 1  # N + 1 interfaces

    a = jnp.abs(flux.df(up))
    fu = flux.f(up)
    vp_ = 0.5 * (fu + a * up)  # upwind-from-left state f^+
    vm_ = 0.5 * (fu - a * up)  # upwind-from-right state f^-

    if order == 5:
        # minus side: cells i-3..i+1 -> padded offsets 0..4
        v = [shifted(vp_, axis, j, n_if) for j in range(5)]
        # plus side: cells i-2..i+2 -> padded offsets 1..5
        u = [shifted(vm_, axis, j + 1, n_if) for j in range(5)]
        return _weno5_minus(*v, variant) + _weno5_plus(*u, variant)
    if order == 7:
        if variant != "js":
            raise ValueError("WENO7 supports only the 'js' variant")
        v = [shifted(vp_, axis, j, n_if) for j in range(7)]
        u = [shifted(vm_, axis, j + 1, n_if) for j in range(7)]
        return _weno7_minus(v) + _weno7_plus(u)
    raise ValueError(f"unsupported WENO order {order}; use 5 or 7")


def flux_divergence(
    u: jnp.ndarray,
    axis: int,
    dx: float,
    flux: Flux,
    order: int = 5,
    variant: str = "js",
    padder: Padder | None = None,
    bc: Boundary | None = None,
    impl: str = "xla",
    ghost_fn: GhostFn | None = None,
) -> jnp.ndarray:
    """Conservative residual ``d f(u) / dx`` along one axis.

    Equivalent role to ``Compute_dF/dG/dH``
    (``MultiGPU/Burgers3d_Baseline/Kernels.cu:225-452``) and
    ``WENO5resAdv_{X,Y,Z}.m``. Exactly one of ``padder``/``bc`` selects the
    ghost-cell source. ``impl``: ``"xla"`` or ``"pallas"`` (VMEM
    slab-pipelined kernel; falls back to XLA where unsupported).
    ``ghost_fn`` switches sharded axes to the overlapped
    interior/boundary schedule (:func:`split_axis_apply`).
    """
    if (padder is None) == (bc is None):
        raise ValueError("provide exactly one of padder/bc")
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown WENO impl {impl!r}; use 'xla'/'pallas'")
    r = HALO[order]

    def div_from_padded(up):
        h = interface_flux_from_padded(up, axis, flux, order, variant)
        m = up.shape[axis] - 2 * r
        return (shifted(h, axis, 1, m) - shifted(h, axis, 0, m)) / dx

    # Only build ghosts when the split schedule will consume them — a
    # pallas impl pads via padder() below, and issuing the ppermute pair
    # here would rely on XLA DCE to avoid doubled halo traffic (mirrors
    # the ordering in ops/laplacian.py).
    if ghost_fn is not None and impl != "pallas":
        ghosts = ghost_fn(u, axis, r)
        if ghosts is not None:
            return split_axis_apply(div_from_padded, u, axis, r, *ghosts)

    up = padder(u, axis, r) if padder is not None else pad_axis(u, axis, r, bc)

    if impl == "pallas":
        from multigpu_advectiondiffusion_tpu.ops.pallas import (
            weno as pallas_weno,
        )

        if pallas_weno.supported(u.ndim, order, variant, shape=u.shape,
                                 dtype=u.dtype):
            return pallas_weno.flux_divergence_pallas(
                up, axis, dx, flux, variant, order=order
            )

    return div_from_padded(up)
