"""``tpucfd-status``: the live serving dashboard (ISSUE 18).

One screen answering "is the fleet healthy right now": request/job
state counts replayed from the CRC journal, the merged cross-process
metrics snapshot (latency quantiles through the one shared histogram
codepath, queue depth + its watermark, shed/fail counters), and the
deadline-SLO verdict (journaled ``slo_alert``/``slo_resolve`` notes —
an alert the dead server raised is still an alert).

Three consumers, three modes:

* a person at a tty — live redraw (the multi-line sibling of
  ``ProgressLine``'s carriage-return discipline: repaint in place,
  never scroll);
* a script — ``--once`` renders a single frame and exits;
* a machine — ``--json`` emits the status dict verbatim.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional


def configure_parser(ap: argparse.ArgumentParser) -> None:
    """Arguments shared by the standalone prog and the CLI subcommand."""
    ap.add_argument("--root", required=True, metavar="DIR",
                    help="service root (request server or scheduler): "
                         "journal.jsonl, metrics/, and the event "
                         "streams live here")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (the script mode; "
                         "default: live tty redraw)")
    ap.add_argument("--json", action="store_true",
                    help="emit the status dict as JSON (implies "
                         "--once unless --interval polling is wanted)")
    ap.add_argument("--interval", type=float, default=1.0, metavar="S",
                    help="live-mode refresh cadence (default 1)")
    ap.add_argument("--max-seconds", type=float, default=None,
                    metavar="S",
                    help="live mode: stop after S wall seconds "
                         "(default: until Ctrl-C)")
    ap.add_argument("--metrics", default=None, metavar="PATH",
                    help="also stream this verb's own status:render "
                         "events to a JSONL sink at PATH")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="tpucfd-status",
        description="fleet status: journal-replayed request/job "
                    "states + merged metrics snapshots + SLO verdict, "
                    "as a live tty dashboard, one-shot text frame, "
                    "or JSON",
    )
    configure_parser(ap)
    return ap


# --------------------------------------------------------------------- #
# Collection
# --------------------------------------------------------------------- #
def _state_counts(root: str) -> dict:
    """Replay the root's journal into request/job state counts. The
    journal is the durable truth (the metrics snapshot is a cadence
    behind by design), so the dashboard's state table reads it."""
    from multigpu_advectiondiffusion_tpu.service.journal import (
        Journal,
        JournalSchemaError,
    )

    out = {"requests": {}, "jobs": {}, "journal_records": 0,
           "torn_lines": 0, "clean_shutdown": False, "draining": False,
           "schema_error": None,
           "slo": {"alerts": 0, "resolves": 0,
                   "firing": False, "last_alert": None}}
    path = os.path.join(root, "journal.jsonl")
    if not os.path.exists(path):
        return out
    try:
        records, torn = Journal.replay(path)
    except JournalSchemaError as err:
        # a future-schema journal is a dashboard FACT, not a crash
        out["schema_error"] = str(err)
        return out
    out["journal_records"] = len(records)
    out["torn_lines"] = int(torn)
    if records:
        last = records[-1]
        out["clean_shutdown"] = bool(
            last.get("type") == "note"
            and last.get("note") == "shutdown"
            and last.get("clean")
        )
    out["draining"] = any(
        rec.get("type") == "note" and rec.get("note") == "drain"
        for rec in records
    ) and not out["clean_shutdown"]
    is_serving = os.path.isdir(os.path.join(root, "requests"))
    key = "requests" if is_serving else "jobs"
    states = {}
    for rec in records:
        rtype = rec.get("type")
        if rtype == "submit" and rec.get("job"):
            states[rec["job"]] = "received" if is_serving else "queued"
        elif rtype == "state" and rec.get("job"):
            states[rec["job"]] = rec.get("to")
        elif rtype == "note":
            note = rec.get("note")
            if note == "slo_alert":
                out["slo"]["alerts"] += 1
                out["slo"]["firing"] = True
                out["slo"]["last_alert"] = {
                    k: rec.get(k)
                    for k in ("slo", "window_s", "burn_rate",
                              "threshold", "wall")
                    if rec.get(k) is not None
                }
            elif note == "slo_resolve":
                out["slo"]["resolves"] += 1
                out["slo"]["firing"] = False
    for state in states.values():
        out[key][state] = out[key].get(state, 0) + 1
    return out


def collect_status(root: str) -> dict:
    """One status frame: journal truth + merged metrics + quantiles."""
    from multigpu_advectiondiffusion_tpu.service.lease import (
        inspect_lease,
    )
    from multigpu_advectiondiffusion_tpu.telemetry.metrics import (
        merge_snapshot_dirs,
        snapshot_histogram,
    )

    root = os.path.abspath(root)
    status = {"root": root, "wall_time": round(time.time(), 3)}
    status.update(_state_counts(root))
    status["lease"] = inspect_lease(root)
    if status["lease"].get("alive"):
        # the live holder's own flag beats the journal-derived guess
        status["draining"] = bool(status["lease"].get("draining"))
    merged = merge_snapshot_dirs(os.path.join(root, "metrics"))
    status["metrics"] = {
        "snapshots": merged.get("snapshots", 0),
        "skipped": merged.get("skipped", []),
        "procs": merged.get("merged_procs", []),
        "wall_time": merged.get("wall_time"),
        "counters": merged.get("counters", {}),
        "gauges": merged.get("gauges", {}),
    }
    quantiles = {}
    for name in ("serve_request_latency_seconds", "serve_slice_seconds",
                 "serve_batch_occupancy", "serve_journal_fsync_seconds",
                 "serve_journal_fsync_batch_records",
                 "serve_pipeline_stall_seconds",
                 "serve_pipeline_overlap_fraction",
                 "serve_device_idle_fraction",
                 "sched_job_seconds"):
        hist = snapshot_histogram(merged, name)
        if hist is None or hist.count == 0:
            continue
        quantiles[name] = {
            "count": hist.count,
            "mean": round(hist.mean(), 6),
            "p50": round(hist.quantile(0.50), 6),
            "p95": round(hist.quantile(0.95), 6),
            "p99": round(hist.quantile(0.99), 6),
            "max": hist.max,
        }
    status["quantiles"] = quantiles
    return status


# --------------------------------------------------------------------- #
# Rendering
# --------------------------------------------------------------------- #
def _fmt_states(states: dict) -> str:
    return (", ".join(f"{k}={v}" for k, v in sorted(states.items()))
            or "none")


def render_text(status: dict) -> List[str]:
    """The dashboard frame as lines (the live mode repaints them)."""
    met = status["metrics"]
    counters = met["counters"]
    gauges = met["gauges"]
    lines = [
        f"tpucfd-status  {status['root']}",
        f"  journal   {status['journal_records']} record(s), "
        f"{status['torn_lines']} torn line(s)"
        + (", clean shutdown" if status.get("clean_shutdown") else ""),
    ]
    if status.get("schema_error"):
        lines.append(f"  journal   SCHEMA ERROR: "
                     f"{status['schema_error']}")
    lease = status.get("lease") or {}
    if lease.get("present"):
        holder = lease.get("holder") or {}
        hb = lease.get("heartbeat_age_s")
        line = (f"  lease     pid={holder.get('pid')} "
                f"role={holder.get('role')} "
                f"age={lease.get('age_s', 0.0):.1f}s")
        if hb is not None:
            line += f" heartbeat={hb:.1f}s ago"
        if lease.get("stale"):
            line += "  STALE (holder dead; next start takes over)"
        elif lease.get("draining"):
            line += "  draining"
        lines.append(line)
    elif status.get("draining"):
        lines.append("  lease     none  (journal shows a drain in "
                     "progress)")
    if status["requests"]:
        lines.append(f"  requests  {_fmt_states(status['requests'])}")
    if status["jobs"]:
        lines.append(f"  jobs      {_fmt_states(status['jobs'])}")
    depth = gauges.get("serve_queue_depth") or {}
    if depth:
        lines.append(
            f"  queue     depth={depth.get('value')} "
            f"max={depth.get('max')}"
        )
    flow = []
    for label, key in (("recv", "serve_requests_received_total"),
                       ("done", "serve_requests_done_total"),
                       ("failed", "serve_requests_failed_total"),
                       ("shed", "serve_requests_shed_total"),
                       ("requeued", "serve_requests_requeued_total"),
                       ("slices", "serve_slices_total")):
        if key in counters:
            flow.append(f"{label}={counters[key]}")
    if flow:
        lines.append("  serving   " + " ".join(flow))
    lat = status["quantiles"].get("serve_request_latency_seconds")
    if lat:
        lines.append(
            f"  latency   p50={lat['p50'] * 1e3:.1f}ms "
            f"p95={lat['p95'] * 1e3:.1f}ms "
            f"p99={lat['p99'] * 1e3:.1f}ms "
            f"(n={lat['count']})"
        )
    sl = status["quantiles"].get("serve_slice_seconds")
    if sl:
        lines.append(
            f"  slices    p50={sl['p50'] * 1e3:.1f}ms "
            f"p99={sl['p99'] * 1e3:.1f}ms (n={sl['count']})"
        )
    # zero-copy pipelined serving (ISSUE 19): the overlap line only
    # appears once the pipelined loop has retired a slice
    depth_g = gauges.get("serve_pipeline_depth") or {}
    overlap = status["quantiles"].get("serve_pipeline_overlap_fraction")
    idle = status["quantiles"].get("serve_device_idle_fraction")
    if depth_g or overlap or idle:
        parts = []
        if depth_g:
            parts.append(f"depth={depth_g.get('value')}"
                         f"/max={depth_g.get('max')}")
        if overlap:
            parts.append(f"overlap p50={overlap['p50']:.2f} "
                         f"mean={overlap['mean']:.2f}")
        if idle:
            parts.append(f"idle mean={idle['mean']:.2f}")
        lines.append("  pipeline  " + " ".join(parts))
    fsync = status["quantiles"].get("serve_journal_fsync_batch_records")
    if fsync:
        lines.append(
            f"  fsync     batch p50={fsync['p50']:.1f} "
            f"mean={fsync['mean']:.1f} max={fsync['max']:.0f} "
            f"record(s)/fsync (n={fsync['count']})"
        )
    slo = status["slo"]
    verdict = "FIRING" if slo["firing"] else "ok"
    detail = ""
    if slo["last_alert"]:
        la = slo["last_alert"]
        detail = (f"  last: {la.get('slo')} burn={la.get('burn_rate')}"
                  f" window={la.get('window_s')}s")
    lines.append(
        f"  slo       {verdict}  alerts={slo['alerts']} "
        f"resolves={slo['resolves']}{detail}"
    )
    lines.append(
        f"  snapshots {met['snapshots']} proc(s)"
        + (f", {len(met['skipped'])} skipped" if met["skipped"] else "")
    )
    return lines


class _Redraw:
    """Multi-line in-place repaint: ANSI cursor-up + clear-line per
    frame on a tty (the ProgressLine discipline lifted to a block);
    plain sequential frames when piped."""

    def __init__(self, out=None):
        self.out = out if out is not None else sys.stdout
        self.is_tty = hasattr(self.out, "isatty") and self.out.isatty()
        self._painted = 0

    def frame(self, lines: List[str]) -> None:
        if self.is_tty and self._painted:
            self.out.write(f"\x1b[{self._painted}A")
        for line in lines:
            if self.is_tty:
                self.out.write("\x1b[2K")
            self.out.write(line + "\n")
        self._painted = len(lines)
        self.out.flush()


# --------------------------------------------------------------------- #
# Driver
# --------------------------------------------------------------------- #
def run(args) -> None:
    from multigpu_advectiondiffusion_tpu import telemetry

    once = args.once or (args.json and args.max_seconds is None)
    redraw = _Redraw()
    t0 = time.monotonic()
    while True:
        status = collect_status(args.root)
        telemetry.event(
            "status", "render", root=status["root"],
            requests=sum(status["requests"].values()),
            jobs=sum(status["jobs"].values()),
        )
        if args.json:
            print(json.dumps(status, sort_keys=True))
        else:
            redraw.frame(render_text(status))
        if once:
            return
        if args.max_seconds is not None and (
            time.monotonic() - t0 >= args.max_seconds
        ):
            return
        try:
            time.sleep(max(0.05, args.interval))
        except KeyboardInterrupt:
            return


def main(argv: Optional[list] = None) -> None:
    args = build_parser().parse_args(argv)
    owned = None
    if args.metrics:
        from multigpu_advectiondiffusion_tpu import telemetry

        owned = telemetry.install(args.metrics)
    try:
        run(args)
    finally:
        if owned is not None:
            from multigpu_advectiondiffusion_tpu import telemetry

            telemetry.uninstall(owned)


if __name__ == "__main__":
    main()
