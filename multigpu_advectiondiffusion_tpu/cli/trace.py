"""``tpucfd-trace``: offline analysis of ``--metrics`` JSONL streams.

The consumable layer over the telemetry subsystem — where the reference
opened one ``nvprof`` file per rank in the Visual Profiler by hand
(``profile.sh``), this merges every rank's stream onto one aligned
timeline and answers the questions a person (or the future scheduler
daemon) actually asks of a run:

* where did the wall clock go? (compile vs step vs checkpoint I/O vs
  rollback re-execution vs modeled halo time, per rank);
* how close did each run land to its cost-model roofline?
* which rank (and which span chain) bounded the run — the cross-rank
  critical path and end skew;
* which steps stalled (``perf:outlier`` record)?
* did the cost model's bytes/FLOPs match what XLA actually compiled?
  (the measured-vs-modeled section over ``xla:cost``/``xla:measured``
  events — per-rung ratios flagged outside the tolerance band — plus
  each rank's ``mem:watermark`` device-memory peak)

Usage (also a ``trace`` subcommand of the main CLI)::

    python -m multigpu_advectiondiffusion_tpu.cli.trace \
        out/run/events_p0.jsonl out/run/events_p1.jsonl \
        --export out/run/trace.json         # open at ui.perfetto.dev

    python -m multigpu_advectiondiffusion_tpu.cli trace out/run/ --json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional


def configure_parser(ap: argparse.ArgumentParser) -> None:
    """Arguments shared by the standalone prog and the CLI subcommand."""
    ap.add_argument("streams", nargs="+", metavar="STREAM",
                    help="one or more --metrics JSONL files (rotated "
                         ".1 segments ride along automatically), or a "
                         "service root directory — its top-level "
                         "streams (rank sinks, sched_events.jsonl, "
                         "serve_events.jsonl) AND the per-job streams "
                         "under <root>/jobs/<id>/ are auto-discovered")
    ap.add_argument("--export", default=None, metavar="PATH",
                    help="write the merged, clock-aligned trace as "
                         "Chrome trace_event JSON — opens directly at "
                         "ui.perfetto.dev / chrome://tracing")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report (JSON) "
                         "instead of the text block")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="also write the JSON report to PATH")
    ap.set_defaults(fn=run)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="tpucfd-trace",
        description="merge + analyze per-process telemetry streams "
                    "(clock-aligned cross-rank trace, phase breakdown, "
                    "measured-vs-roofline, critical path, Perfetto "
                    "export)",
    )
    configure_parser(ap)
    return ap


def run(args) -> None:
    """Execute an analysis request (the argparse-facing driver)."""
    from multigpu_advectiondiffusion_tpu.telemetry.analyze import (
        analyze,
        align_clocks,
        load_streams,
    )

    try:
        streams = load_streams(args.streams)
    except FileNotFoundError as err:
        raise SystemExit(str(err))
    align_clocks(streams)

    if args.export:
        from multigpu_advectiondiffusion_tpu.telemetry.export import (
            write_chrome_trace,
        )

        obj = write_chrome_trace(args.export, streams)
        print(
            f"wrote {len(obj['traceEvents'])} trace events to "
            f"{args.export} (open at https://ui.perfetto.dev)",
            file=sys.stderr,
        )

    report = analyze(args.streams)
    if args.out:
        from multigpu_advectiondiffusion_tpu.utils.io import (
            atomic_write_text,
        )

        atomic_write_text(args.out, json.dumps(report.to_dict(), indent=2))
    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(report.format_text())


def main(argv: Optional[list] = None) -> None:
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
