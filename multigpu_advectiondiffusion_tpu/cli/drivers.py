"""Shared CLI run driver — the re-design of the reference's per-project
``main.c``/``main.cpp`` drivers and ``Run.m`` harnesses (SURVEY §3.1, §3.5):
build solver → save ``initial.bin`` → timed hot loop → save ``result.bin``
→ PrintSummary block (+ JSON + optional PNG render, replacing MATLAB).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Optional

import numpy as np


from multigpu_advectiondiffusion_tpu.bench.timing import sync
from multigpu_advectiondiffusion_tpu.models.base import SolverBase
from multigpu_advectiondiffusion_tpu.parallel.mesh import Decomposition, make_mesh
from multigpu_advectiondiffusion_tpu.timestepping.integrators import STAGES
from multigpu_advectiondiffusion_tpu.utils import io as io_utils
from multigpu_advectiondiffusion_tpu.utils.summary import RunSummary


def parse_mesh_spec(spec: Optional[str]):
    """``'dz=4,dy=2'`` -> (mesh, Decomposition) or (None, None).

    Mesh axis names map to grid axes by suffix: dz/dy/dx/dr -> z/y/x/r.
    A ``_suffix`` after the letter declares members of a *compound* axis
    splitting one grid axis over several mesh axes, outermost first in
    spec order — the multi-host layout: ``'dz_dcn=2,dz_ici=4'`` puts z
    over ``('dz_dcn', 'dz_ici')`` with the DCN hop between process
    granules (``parallel/mesh.py`` Decomposition docstring).
    """
    if not spec:
        return None, None
    sizes = {}
    for part in spec.split(","):
        name, _, num = part.partition("=")
        sizes[name.strip()] = int(num)
    mesh = make_mesh(sizes)
    return mesh, sizes


def decomposition_for(grid, mesh_sizes) -> Optional[Decomposition]:
    if not mesh_sizes:
        return None
    suffix_to_axis = {}
    names = grid.axis_names  # e.g. ('z','y','x'); axisym grids use ('y','x')
    for ax, n in enumerate(names):
        suffix_to_axis[n] = ax
    # r is the innermost axis of axisymmetric grids
    suffix_to_axis.setdefault("r", grid.ndim - 1)
    groups = {}  # grid axis -> mesh axis names, spec order (dcn first)
    for mesh_name in mesh_sizes:
        suffix = mesh_name.lstrip("d").split("_", 1)[0]
        if suffix not in suffix_to_axis:
            raise ValueError(
                f"mesh axis {mesh_name!r} has no grid axis (grid axes: {names})"
            )
        groups.setdefault(suffix_to_axis[suffix], []).append(mesh_name)
    mapping = {
        ax: (ns[0] if len(ns) == 1 else tuple(ns))
        for ax, ns in groups.items()
    }
    return Decomposition.of(mapping)


def physics_meta(solver: SolverBase) -> dict:
    """JSON-safe snapshot of the config fields that define the physics a
    checkpoint will continue under (diffusivity/nu/bc/weno/cfl/...).
    Excludes the grid (validated separately), the IC (irrelevant once a
    state exists), and kernel-strategy knobs that cannot change results."""
    import dataclasses

    # steps_per_exchange/exchange are kernel-strategy knobs like
    # impl/overlap: they change the exchange cadence/transport, not the
    # physics a checkpoint continues under
    skip = {"grid", "ic", "ic_params", "impl", "overlap",
            "steps_per_exchange", "exchange"}
    out = {}
    for f in dataclasses.fields(solver.cfg):
        if f.name in skip:
            continue
        v = getattr(solver.cfg, f.name)
        if isinstance(v, tuple):
            v = list(v)
        try:
            json.dumps(v)
        except TypeError:
            # non-serializable fields (e.g. a source-term callable) have
            # no stable representation across processes — recording repr()
            # would spuriously reject legitimate resumes
            continue
        out[f.name] = v
    return out


def build_ensemble_members(sweeps, members: int, aliases=None):
    """CLI ``--sweep`` specs -> per-member override dicts.

    ``NAME=a:b`` sweeps linearly, ``NAME=v1,...`` lists one value per
    member. ``aliases`` maps CLI names to config fields (``K`` ->
    ``diffusivity``); an ``ic.PARAM`` name lands in the member's
    ``ic_params`` (Riemann-state sweeps: ``ic.left=2:1``)."""
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        parse_sweep_spec,
    )

    aliases = aliases or {}
    out = [dict() for _ in range(members)]
    ic_params = [dict() for _ in range(members)]
    for spec in sweeps or []:
        name, values = parse_sweep_spec(spec, members)
        if name.startswith("ic."):
            key = name[3:]
            for i, v in enumerate(values):
                ic_params[i][key] = v
            continue
        name = aliases.get(name, name)
        for i, v in enumerate(values):
            out[i][name] = v
    for i, p in enumerate(ic_params):
        if p:
            out[i]["ic_params"] = tuple(sorted(p.items()))
    return out


def parse_ensemble_mesh(mesh_spec, grid):
    """``--mesh members=8`` / ``members=4,dz=2`` -> ``(mesh,
    spatial_decomp)`` for the batched ensemble engine. The member axis
    shards the batched state's leading axis (halo-free); remaining
    axes map to grid axes like any spatial mesh. A spec WITHOUT a
    members axis declines loudly — a purely spatial mesh shards one
    member's grid."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import MEMBER_AXIS

    if not mesh_spec:
        return None, None
    mesh, sizes = parse_mesh_spec(mesh_spec)
    if MEMBER_AXIS not in sizes:
        raise ValueError(
            "--ensemble composes with --mesh through a 'members' axis "
            "(e.g. --mesh members=8 or --mesh members=4,dz=2); a "
            "purely spatial mesh shards one member's grid — drop "
            "--mesh or add the members axis"
        )
    spatial = {k: v for k, v in sizes.items() if k != MEMBER_AXIS}
    decomp = decomposition_for(grid, spatial) if spatial else None
    return mesh, decomp


def run_ensemble_solver(solver_cls, cfg, name: str, args, aliases=None):
    """The batched-ensemble CLI driver (``--ensemble B [--sweep ...]``):
    ONE batched dispatch advances all B members; per-member summaries
    (max|u|, mass drift) and member-attributed divergence come out of
    the batch (models/ensemble.py). ``--mesh members=P[,dz=Q]``
    composes: the member axis shards over the device mesh (optionally
    x a z-slab spatial subgroup), so one dispatch serves B x P users.
    Supervision machinery that rolls state back (checkpoints, SDC
    guard, diagnostics cadence) stays single-run; ``--sentinel-every``
    is served as a chunked per-member health probe."""
    import time as _time

    import jax

    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )
    from multigpu_advectiondiffusion_tpu.utils.metrics import mlups

    B = int(args.ensemble)
    unsupported = {
        "--coordinator": getattr(args, "coordinator", None),
        "--resume": getattr(args, "resume", None),
        "--checkpoint-every": getattr(args, "checkpoint_every", 0),
        "--snapshot-every": getattr(args, "snapshot_every", 0),
        "--snapshots": getattr(args, "snapshots", 0),
        "--sdc-every": getattr(args, "sdc_every", 0),
        "--diag-every": getattr(args, "diag_every", 0),
        "--progress": getattr(args, "progress", False),
        "--watchdog-timeout": getattr(args, "watchdog_timeout", 0.0),
        "--dt-scale": (getattr(args, "dt_scale", 1.0) or 1.0) != 1.0,
    }
    offending = [k for k, v in unsupported.items() if v]
    if offending:
        raise ValueError(
            f"--ensemble does not compose with {offending} (single-run "
            "supervision machinery); drop them or run members "
            "individually"
        )
    members = build_ensemble_members(args.sweep, B, aliases=aliases)
    mesh, spatial_decomp = parse_ensemble_mesh(
        getattr(args, "mesh", None), cfg.grid
    )
    es = EnsembleSolver(solver_cls, cfg, members, mesh=mesh,
                        decomp=spatial_decomp)
    estate = es.initial_state()
    iters = args.iters
    if iters is None and args.t_end is None:
        iters = 100

    from multigpu_advectiondiffusion_tpu import telemetry

    scope = telemetry.get_sink()
    span = (
        scope.span("run_solver", run=name, ensemble=B)
        if scope.active
        else contextlib.nullcontext()
    )
    with span:
        # untimed warm-up/compile of the batched program (the
        # reference's untimed warm phase), then the timed dispatch
        t0 = _time.perf_counter()
        warm = es.run(estate, 1) if iters is not None else es.advance_to(
            estate, float(estate.t.max())
        )
        sync(warm.u)
        compile_s = _time.perf_counter() - t0

        sentinel = int(getattr(args, "sentinel_every", 0) or 0)
        t0 = _time.perf_counter()
        if iters is not None:
            if sentinel:
                out, done = estate, 0
                while done < iters:
                    n = min(sentinel, iters - done)
                    out = es.run(out, n)
                    done += n
                    # member-attributed divergence: one blown-up member
                    # names its index, the batch result stays valid
                    es.check_health(
                        out, growth=getattr(args, "sentinel_growth", 1e3)
                    )
            else:
                out = es.run(estate, iters)
        else:
            out = es.advance_to(estate, args.t_end)
        sync(out.u)
        seconds = _time.perf_counter() - t0

        work = iters if iters is not None else int(
            np.asarray(out.it).max()
        )
        rate = mlups(
            cfg.grid.num_cells * B, max(1, work),
            STAGES[cfg.integrator], seconds,
        )
        summaries = es.member_summaries(out)
        if sentinel == 0:
            es.check_health(
                out, growth=getattr(args, "sentinel_growth", 1e3)
            )
        engaged = es.engaged_path()
        result = {
            "name": name,
            "ensemble": B,
            "grid_xyz": list(cfg.grid.shape_xyz),
            "iters": work,
            "seconds": round(seconds, 6),
            "compile_seconds": round(compile_s, 4),
            "mlups_members": round(rate, 2),
            "devices": engaged.get("devices", 1),
            "member_sharding": engaged.get("member_sharding", 1),
            "mesh": engaged.get("mesh"),
            "engaged": engaged,
            "members": summaries,
        }
        if scope.active:
            scope.event(
                "summary", name, seconds=round(seconds, 6),
                mlups=round(rate, 3), ensemble=B,
                stepper=engaged["stepper"],
            )

    # Safe rank divergence: single-process engine (the gate above
    # rejects --coordinator), so the coordinator gate is vestigial
    # uniprocess hygiene — there is no peer to desynchronize from and
    # no collective below this point.
    # tpucfd-check: allow[rank-divergent-effect]
    if jax.process_index() == 0:
        placement = ""
        if engaged.get("devices", 1) > 1:
            placement = (
                f", {engaged['member_sharding']}-way member sharding "
                f"over {engaged['devices']} devices"
            )
        print(f"-- {name} ensemble: B={B} members, {work} iters, "
              f"{seconds:.4f}s, {rate:,.1f} MLUPS*members "
              f"({engaged['stepper']}{placement})")
        for row in summaries:
            drift = row.get("mass_drift")
            print(
                f"   member {row['member']:3d}: t={row['t']:.5g} "
                f"max|u|={row['max_abs']:.5g}"
                + (f" mass_drift={drift:+.3e}" if drift is not None
                   else "")
                + (f" {row['overrides']}" if row.get("overrides") else "")
            )
        if args.save:
            os.makedirs(args.save, exist_ok=True)
            io_utils.save_binary(
                np.asarray(out.u),
                os.path.join(args.save, "ensemble_result.bin"),
            )
            tmp = os.path.join(args.save, "ensemble_summary.json.tmp")
            with open(tmp, "w") as f:
                json.dump(result, f, indent=1)
            os.replace(
                tmp, os.path.join(args.save, "ensemble_summary.json")
            )
    return result


def run_solver(
    solver: SolverBase,
    name: str,
    *args,
    metrics_path: Optional[str] = None,
    metrics_max_bytes: int = 0,
    watchdog_timeout: float = 0.0,
    **kwargs,
) -> RunSummary:
    """Public run driver; see :func:`_run_solver` for the full contract.

    ``metrics_path`` opens a structured-telemetry JSONL sink for the
    run's duration (the CLI's ``--metrics``); when a sink is already
    installed (e.g. by ``cli.main`` before the multihost join) it is
    reused and left alone. The whole run executes under a top-level
    ``run_solver`` span so every dispatch/physics/resilience/io event is
    attributable to this run.

    ``watchdog_timeout`` > 0 arms the rank-liveness watchdog for
    multi-process runs (heartbeat records under ``save_dir``): a peer
    dead or silent past the timeout aborts this process with the
    documented rank-failure exit code instead of hanging in a
    collective; any exception raised while a peer is down is classified
    as the structured ``RankFailureError`` it really is."""
    import jax

    from multigpu_advectiondiffusion_tpu import telemetry
    from multigpu_advectiondiffusion_tpu.parallel import multihost

    watchdog = None
    if watchdog_timeout and watchdog_timeout > 0 and jax.process_count() > 1:
        save_dir = kwargs.get("save_dir")
        if not save_dir:
            raise ValueError(
                "--watchdog-timeout needs --save DIR (the heartbeat "
                "records live under it)"
            )
        os.makedirs(save_dir, exist_ok=True)
        watchdog = multihost.RankWatchdog(
            os.path.join(save_dir, ".heartbeats"),
            timeout_seconds=watchdog_timeout,
            report_dir=save_dir,
        )

    with contextlib.ExitStack() as scope:
        if metrics_path and not telemetry.get_sink().active:
            sink = telemetry.install(metrics_path,
                                     max_bytes=metrics_max_bytes)
            scope.callback(telemetry.uninstall, sink)
        if watchdog is not None:
            # after the sink install, so direct run_solver(metrics_path=
            # ...) callers get the armed record in their stream too
            telemetry.event(
                "rank", "watchdog_armed",
                timeout=float(watchdog_timeout),
                interval=watchdog.interval,
                processes=jax.process_count(),
            )
        t_sink = telemetry.get_sink()
        if t_sink.active:
            scope.enter_context(t_sink.span("run_solver", run=name))
        # the scope covers warm-up, the timed solve AND the gathered
        # file output — every cross-process collective of the run
        scope.enter_context(multihost.watchdog_scope(watchdog))
        return _run_solver(solver, name, *args, **kwargs)


def _run_solver(
    solver: SolverBase,
    name: str,
    iters: Optional[int] = None,
    t_end: Optional[float] = None,
    save_dir: Optional[str] = None,
    plot: bool = False,
    check_error: bool = False,
    repeats: int = 1,
    snapshot_every: int = 0,
    checkpoint_every: int = 0,
    checkpoint_keep: int = 0,
    checkpoint_sharded: bool = False,
    resume: Optional[str] = None,
    profile_dir: Optional[str] = None,
    sentinel_every: int = 0,
    sentinel_growth: float = 1e3,
    max_retries: int = 3,
    dt_backoff: float = 0.5,
    sdc_every: int = 0,
    progress: bool = False,
    diag_every: int = 0,
    diag_strict: bool = False,
    snapshots: int = 0,
    snapshot_stride: int = 1,
    snapshot_max_bytes: int = 0,
    dt_scale: float = 1.0,
) -> RunSummary:
    """Execute the timed solve exactly the way the reference drivers do:
    untimed warm-up/compile, barrier-sandwiched hot loop
    (``MultiGPU/Diffusion3d_Baseline/main.c:184-307``), then I/O.

    ``snapshot_every``/``checkpoint_every`` (iters mode only) emit
    float32 ``snap_*.bin`` via the async writer / restartable,
    CRC-verified ``.ckpt`` checkpoints every N iterations — the restart
    capability the reference lacks (SURVEY §5). ``checkpoint_keep``
    bounds disk use by deleting all but the newest N checkpoints.

    Resilience (README/PARITY "Failure modes & resilience"):
    ``sentinel_every`` > 0 supervises the run — a mesh-aware health
    probe every N steps, rollback to the last good checkpoint and a
    ``dt_backoff`` retry schedule on divergence (at most
    ``max_retries``). ``resume='auto'`` scans ``save_dir`` for the
    newest CRC-valid checkpoint, skipping corrupt ones. SIGTERM/SIGINT
    end the run at the next chunk boundary with a final atomic
    checkpoint + ``preempt.json`` manifest and exit code 75
    (``resilience.EXIT_PREEMPTED``).
    """
    if (iters is None) == (t_end is None):
        raise ValueError("provide exactly one of iters/t_end")
    import jax

    from multigpu_advectiondiffusion_tpu.resilience.preemption import (
        PreemptionExit,
        PreemptionGuard,
    )
    from multigpu_advectiondiffusion_tpu.resilience.recovery import (
        find_latest_checkpoint,
    )
    from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
        supervise_run,
    )

    # Multi-process runs (the mpirun analog, --coordinator): file output
    # happens once, on the coordinator; shards living on other processes
    # are allgathered first. _fetch is a COLLECTIVE when sharded across
    # processes — every process must call it, only the write is gated.
    is_coord = jax.process_index() == 0

    def _fetch(u):
        if getattr(u, "is_fully_addressable", True):
            return u
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(u, tiled=True)

    if resume == "auto":
        # newest CRC-valid checkpoint in the run directory; corrupt/
        # truncated candidates are reported and skipped (selection rules
        # in resilience/recovery.py). Nothing valid -> fresh start.
        if not save_dir:
            raise ValueError("--resume auto needs --save DIR to scan")
        resume = find_latest_checkpoint(save_dir)
        if resume is None and is_coord:
            print(
                f"--resume auto: no valid checkpoint under {save_dir}; "
                "starting from the initial condition"
            )

    if resume:
        import jax.numpy as jnp

        # sharded checkpoint directories reassemble straight onto this
        # run's mesh (which may differ from the saving run's) — each
        # process reads only the regions its shards need
        state = io_utils.load_checkpoint(
            resume,
            sharding=None if solver.mesh is None else solver.sharding(),
        )
        if tuple(state.u.shape) != tuple(solver.grid.shape):
            raise ValueError(
                f"checkpoint grid {tuple(state.u.shape)} != configured "
                f"grid {tuple(solver.grid.shape)}"
            )
        u = jnp.asarray(state.u, solver.dtype)
        if solver.mesh is not None:
            u = jax.device_put(u, solver.sharding())
        state = type(state)(u=u, t=state.t, it=state.it)
        # recorded physical bounds (.npz meta field / .ckpt sidecar) — a
        # matching node count on a different domain is silently wrong
        # physics
        meta = io_utils.read_checkpoint_meta(resume)
        # elastic reshard: a .ckptd written on mesh A restoring onto a
        # different process/device topology (the restart-after-losing-a-
        # host path) is legitimate and worth recording — each process
        # read only the shard regions overlapping its NEW placement
        saved_procs = (meta or {}).get("num_processes")
        if saved_procs is not None and int(saved_procs) != jax.process_count():
            from multigpu_advectiondiffusion_tpu import telemetry

            telemetry.event(
                "resilience", "elastic_resume",
                checkpoint=resume,
                saved_processes=int(saved_procs),
                processes=jax.process_count(),
            )
            if is_coord:
                print(
                    f"elastic resume: checkpoint {resume} was written "
                    f"by {int(saved_procs)} process(es); restoring onto "
                    f"{jax.process_count()}"
                )
        got = (meta or {}).get("bounds")
        if got is not None:
            want = [list(b) for b in solver.grid.bounds]
            if not np.allclose(got, want):
                raise ValueError(
                    f"checkpoint domain bounds {got} != configured "
                    f"bounds {want}"
                )
        # matching grid + bounds but different physics (e.g. another --K
        # or WENO variant) would silently continue the wrong equation
        # under the same artifact numbering
        recorded = (meta or {}).get("physics")
        if recorded is not None:
            current = physics_meta(solver)
            diffs = {
                k: (recorded[k], current[k])
                for k in recorded
                if k in current and recorded[k] != current[k]
            }
            if diffs:
                detail = ", ".join(
                    f"{k}: checkpoint={a!r} configured={b!r}"
                    for k, (a, b) in sorted(diffs.items())
                )
                raise ValueError(
                    f"checkpoint physics parameters differ: {detail}"
                )
    else:
        state = solver.initial_state()
    start_it = int(state.it)

    if dt_scale and float(dt_scale) != 1.0:
        # dt-backoff inheritance (--dt-scale, the scheduler's retry
        # knob): start at the reduced step a failed attempt backed off
        # to. Applied AFTER resume validation — the checkpoint's
        # recorded physics are compared against the unscaled config —
        # and through the same scale_dt path the supervisor's in-run
        # backoff uses, so the two schedules compose.
        from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
            scale_dt,
        )

        what = scale_dt(solver, float(dt_scale))
        from multigpu_advectiondiffusion_tpu import telemetry

        telemetry.event(
            "resilience", "dt_inherit",
            factor=float(dt_scale), action=what,
        )
        if is_coord:
            print(f"dt-scale {float(dt_scale):g}: {what} "
                  "(inherited backoff)")

    # measured introspection: run-scoped device-memory watermarks
    # (supervised chunks sample at cadence; every run samples at the
    # warm-up and final boundaries, so RunSummary.memory always lands)
    from multigpu_advectiondiffusion_tpu.telemetry import xprof

    xprof.reset_watermarks()

    if save_dir:
        os.makedirs(save_dir, exist_ok=True)
        u_host = _fetch(state.u)
        # Safe rank divergence: every rank joined the _fetch allgather
        # above; only the write is gated (one writer per artifact),
        # and no peer reads initial.bin during the run.
        # tpucfd-check: allow[rank-divergent-effect]
        if is_coord:
            io_utils.save_binary(u_host, os.path.join(save_dir, "initial.bin"))

    # compile (untimed, like the reference's untimed warm phase)
    t0 = time.perf_counter()
    if iters is not None:
        out = solver.run(state, 1)
    else:
        out = solver.step(state)
    sync(out.u)
    compile_s = time.perf_counter() - t0
    xprof.sample_watermark(step=int(out.it))

    supervised = sentinel_every > 0
    periodic = (
        (snapshot_every or checkpoint_every)
        and iters is not None
        and not supervised
    )
    if supervised and snapshot_every:
        raise ValueError(
            "--sentinel-every supervises checkpoint-grain chunks; "
            "combine it with --checkpoint-every, not --snapshot-every"
        )
    if sdc_every and not supervised:
        raise ValueError(
            "--sdc-every rides the sentinel cadence; it needs "
            "--sentinel-every > 0"
        )
    if progress and not supervised:
        raise ValueError(
            "--progress renders the supervisor's chunk-cadence events; "
            "it needs --sentinel-every > 0"
        )
    if diag_every and not supervised:
        raise ValueError(
            "--diag-every rides the sentinel's jitted probe cadence; "
            "it needs --sentinel-every > 0"
        )
    if diag_strict and not diag_every:
        raise ValueError(
            "--diag-strict escalates diagnostic violations; it needs "
            "--diag-every > 0"
        )
    if snapshots and not supervised:
        raise ValueError(
            "--snapshots streams at the supervised chunk cadence; it "
            "needs --sentinel-every > 0 (unsupervised periodic output: "
            "--snapshot-every)"
        )
    if (
        periodic or (supervised and (checkpoint_every or snapshots))
    ) and not save_dir:
        raise ValueError("snapshot/checkpoint output needs save_dir")

    def _write_checkpoint(st):
        """One restartable checkpoint named by global iteration (atomic,
        CRC-verified; sharded -> per-shard .ckptd directory). Collective
        when sharded across processes."""
        glob_it = int(st.it)
        if checkpoint_sharded:
            path = os.path.join(save_dir, f"checkpoint_{glob_it:06d}.ckptd")
            io_utils.save_checkpoint_sharded(
                path, st, grid=solver.grid, physics=physics_meta(solver)
            )
        else:
            path = os.path.join(save_dir, f"checkpoint_{glob_it:06d}.ckpt")
            u_host = _fetch(st.u)
            # Safe rank divergence: the single-file checkpoint has one
            # writer by design; every rank already joined the _fetch
            # allgather, and the .ckpt publish is atomic + CRC-gated
            # so a resuming reader sees complete-or-absent.
            # tpucfd-check: allow[rank-divergent-effect]
            if is_coord:
                io_utils.save_checkpoint(
                    path,
                    type(st)(u=u_host, t=st.t, it=st.it),
                    grid=solver.grid,
                    physics=physics_meta(solver),
                )
        io_utils.rotate_checkpoints(save_dir, checkpoint_keep)
        return path

    best = float("inf")
    io_s = None
    sup_report = None
    # trace() itself is exception-safe and idempotent (utils/profiling):
    # it closes on every exit path and a leaked predecessor can no
    # longer poison start_trace — no extra guard logic needed here
    profiled = contextlib.nullcontext()
    if profile_dir:
        from multigpu_advectiondiffusion_tpu.utils.profiling import trace

        # Multi-process launches write one trace dir per process —
        # the %q{OMPI_COMM_WORLD_RANK} per-rank naming of the
        # reference's profile.sh (MultiGPU/Diffusion3d_Baseline/
        # profile.sh:2), keyed on jax.process_index().
        if jax.process_count() > 1:
            profile_dir = os.path.join(
                profile_dir, f"rank{jax.process_index()}"
            )
        profiled = trace(profile_dir)
    guard = PreemptionGuard()
    with profiled, guard:
        if supervised:
            # supervised chunked loop: sentinel probes at cadence,
            # rollback + dt-backoff retries on divergence; the disk
            # checkpoints (when requested) are the rollback grain
            io_acc = [0.0]

            def save_ckpt(st):
                sync(st.u)  # don't book device compute as I/O
                io_t0 = time.perf_counter()
                _write_checkpoint(st)
                io_acc[0] += time.perf_counter() - io_t0

            # --snapshots: downsampled field-snapshot streaming through
            # the double-buffered background writer (atomic publishes,
            # rotation-capped by --snapshot-max-bytes). _fetch is a
            # collective when sharded — every process calls, only the
            # coordinator writes.
            snap_streamer = None
            save_snap = None
            if snapshots:
                if is_coord:
                    snap_streamer = io_utils.SnapshotStreamer(
                        save_dir, stride=snapshot_stride,
                        max_bytes=snapshot_max_bytes,
                    )

                def save_snap(st):
                    sync(st.u)
                    io_t0 = time.perf_counter()
                    u_host = _fetch(st.u)
                    if snap_streamer is not None:
                        snap_streamer.write(u_host, int(st.it))
                    io_acc[0] += time.perf_counter() - io_t0

            # --progress: the coordinator renders the supervisor's
            # chunk-cadence progress events as one status line (other
            # ranks still emit the events into their own streams)
            progress_line = None
            if progress and is_coord:
                from multigpu_advectiondiffusion_tpu.telemetry.live import (
                    ProgressLine,
                )

                progress_line = ProgressLine(label=name)
            t0 = time.perf_counter()
            try:
                out, sup_report = supervise_run(
                    solver,
                    state,
                    iters=iters,
                    t_end=t_end,
                    sentinel_every=sentinel_every,
                    growth=sentinel_growth,
                    max_retries=max_retries,
                    dt_backoff=dt_backoff,
                    checkpoint_every=checkpoint_every,
                    save_checkpoint=save_ckpt if checkpoint_every else None,
                    should_stop=lambda: guard.should_stop,
                    sdc_every=sdc_every,
                    progress=(
                        progress_line.update if progress_line else None
                    ),
                    diag_every=diag_every,
                    diag_strict=diag_strict,
                    snapshot_every=snapshots,
                    save_snapshot=save_snap,
                )
            finally:
                if progress_line is not None:
                    progress_line.close()
                if snap_streamer is not None:
                    snap_streamer.close()
            sync(out.u)
            io_s = io_acc[0] if (checkpoint_every or snapshots) else None
            best = time.perf_counter() - t0 - (io_s or 0.0)
        elif periodic:
            chunk = min(x for x in (snapshot_every, checkpoint_every) if x)
            io_s = 0.0  # shadows the outer None: periodic runs report it
            # the streamer wraps the async writer with atomic publishes,
            # optional striding and the --snapshot-max-bytes rotation cap
            with io_utils.SnapshotStreamer(
                save_dir, stride=snapshot_stride,
                max_bytes=snapshot_max_bytes,
            ) as writer:
                t0 = time.perf_counter()
                out, done = state, 0
                while done < iters:
                    n = min(chunk, iters - done)
                    out = solver.run(out, n)
                    done += n
                    # filenames carry the GLOBAL iteration so a resumed
                    # run continues the numbering instead of overwriting
                    # earlier artifacts in the same directory
                    glob_it = start_it + done
                    # host I/O is timed separately and excluded from the
                    # solve rate — the reference times only kernel work
                    # (main.c:184-307; output happens after the loop).
                    # Drain the async-dispatched chunk FIRST: otherwise
                    # the device compute blocks inside np.asarray in the
                    # writers and books as I/O, inflating the solve rate.
                    sync(out.u)
                    io_t0 = time.perf_counter()
                    snap_now = (
                        snapshot_every and done % snapshot_every == 0
                    )
                    ckpt_now = (
                        checkpoint_every and done % checkpoint_every == 0
                    )
                    # one gather serves both writers when they coincide
                    u_host = (
                        _fetch(out.u)
                        if snap_now or (ckpt_now and not checkpoint_sharded)
                        else None
                    )
                    if snap_now:
                        if is_coord:
                            writer.write(u_host, glob_it)
                    if ckpt_now:
                        if checkpoint_sharded:
                            # per-shard directory: no gather to one host
                            io_utils.save_checkpoint_sharded(
                                os.path.join(
                                    save_dir,
                                    f"checkpoint_{glob_it:06d}.ckptd",
                                ),
                                out,
                                grid=solver.grid,
                                physics=physics_meta(solver),
                            )
                        else:
                            # single-writer .ckpt publish — same audit
                            # as the supervised _write_checkpoint path
                            # tpucfd-check: allow[rank-divergent-effect]
                            if is_coord:
                                io_utils.save_checkpoint(
                                    os.path.join(
                                        save_dir,
                                        f"checkpoint_{glob_it:06d}.ckpt",
                                    ),
                                    type(out)(u=u_host, t=out.t, it=out.it),
                                    grid=solver.grid,
                                    physics=physics_meta(solver),
                                )
                        io_utils.rotate_checkpoints(save_dir, checkpoint_keep)
                    io_s += time.perf_counter() - io_t0
                    if guard.should_stop:
                        break  # preemption: finalize below with what ran
                sync(out.u)
                best = time.perf_counter() - t0 - io_s
        else:
            for _ in range(max(1, repeats)):
                t0 = time.perf_counter()
                if iters is not None:
                    out = solver.run(state, iters)
                else:
                    out = solver.advance_to(state, t_end)
                sync(out.u)
                best = min(best, time.perf_counter() - t0)
                if guard.should_stop:
                    break  # preemption between repeats

    if guard.should_stop:
        # preemption-safe exit: final atomic checkpoint + manifest, then
        # the documented exit code (resume with --resume auto). A
        # multi-process run must receive the signal on every process
        # (sharded checkpoint saves are collective).
        from multigpu_advectiondiffusion_tpu.resilience.preemption import (
            EXIT_PREEMPTED,
        )

        ckpt_path = None
        if save_dir:
            sync(out.u)
            ckpt_path = _write_checkpoint(out)
            # Safe rank divergence: every rank wrote (or gathered for)
            # the final checkpoint above; the preempt.json breadcrumb
            # is advisory single-writer metadata published atomically.
            # tpucfd-check: allow[rank-divergent-effect]
            if is_coord:
                manifest = {
                    "signal": int(guard.signum),
                    "iteration": int(out.it),
                    "t": float(out.t),
                    "checkpoint": ckpt_path,
                    "exit_code": EXIT_PREEMPTED,
                    "resume": "--resume auto",
                }
                tmp = os.path.join(save_dir, "preempt.json.tmp")
                with open(tmp, "w") as f:
                    json.dump(manifest, f, indent=2)
                os.replace(tmp, os.path.join(save_dir, "preempt.json"))
        if is_coord:
            where = f"; checkpoint: {ckpt_path}" if ckpt_path else ""
            print(
                f"preempted by signal {guard.signum} at iteration "
                f"{int(out.it)}{where}; exiting {EXIT_PREEMPTED}"
            )
        raise PreemptionExit(guard.signum, ckpt_path)

    # iterations executed THIS run — a resumed state's it starts at the
    # checkpoint's cumulative count, which must not inflate the summary
    n_iters = iters if iters is not None else max(1, int(out.it) - start_it)
    dt = getattr(solver, "dt", None)
    if dt is None:
        dt = (float(out.t) - float(state.t)) / max(n_iters, 1)

    summary = RunSummary(
        name=name,
        grid_xyz=solver.grid.shape_xyz,
        iters=n_iters,
        stages=STAGES[solver.cfg.integrator],
        seconds=best,
        dt=float(dt),
        t_final=float(out.t),
        devices=1 if solver.mesh is None else solver.mesh.devices.size,
        dtype=str(solver.cfg.dtype),
        compile_seconds=compile_s,
        io_seconds=io_s,
        engaged=solver.engaged_path(
            mode="iters" if iters is not None else "t_end"
        ),
        resilience=sup_report.to_dict() if sup_report is not None else None,
    )
    # static cost model for the ENGAGED rung: bytes/FLOPs per step and
    # the roofline efficiency of the measured rate (telemetry/costmodel)
    from multigpu_advectiondiffusion_tpu.telemetry import costmodel

    summary.cost_model = costmodel.summarize_run(
        solver, summary.engaged["stepper"], n_iters, best
    )
    # measured introspection: the final watermark sample plus the
    # per-executable XLA capture reconciled against the modeled cost
    sync(out.u)
    xprof.sample_watermark(step=int(out.it))
    summary.memory = xprof.watermark_summary()
    summary.xla = xprof.measured_summary(solver, n_iters, best)
    from multigpu_advectiondiffusion_tpu import telemetry

    t_sink = telemetry.get_sink()
    if t_sink.active:
        t_sink.event(
            "summary", name,
            seconds=round(best, 6),
            mlups=round(summary.mlups, 3),
            stepper=summary.engaged["stepper"],
            roofline_pct=(summary.cost_model or {}).get("roofline_pct"),
            mass_drift=(
                summary.resilience.get("mass_drift")
                if summary.resilience
                else None
            ),
        )
        if summary.xla is not None:
            # the per-run measured-vs-modeled record the trace report
            # renders: XLA bytes/flops per step, model ratio + band
            # flag, achieved vs peak bandwidth
            t_sink.event("xla", "measured", run=name, **summary.xla)
    if summary.xla is not None and summary.cost_model is not None:
        # feed the measured-peak calibration with the run's achieved
        # rate on its BINDING resource (the non-binding one never
        # approaches its roof — calibrating it down would be noise);
        # consumed by costmodel.peak_rates and the tuner's pruning
        from multigpu_advectiondiffusion_tpu.telemetry import calibration

        bound = summary.cost_model.get("bound")
        kwargs = {}
        if bound == "hbm" and summary.xla.get("achieved_gbs"):
            kwargs["bytes_per_s"] = (
                summary.xla["achieved_gbs"] * 1e9
                / max(1, summary.xla.get("devices", 1))
            )
        elif bound == "flops" and summary.xla.get("achieved_gflops"):
            kwargs["flops_per_s"] = (
                summary.xla["achieved_gflops"] * 1e9
                / max(1, summary.xla.get("devices", 1))
            )
        if kwargs and is_coord:
            try:
                kind = jax.local_devices()[0].device_kind
            except Exception:
                kind = None
            calibration.observe(
                jax.default_backend(), run=name, device_kind=kind,
                **kwargs,
            )

    if check_error and hasattr(solver, "error_norms"):
        # gathered first: eager norm arithmetic mixes the state with a
        # process-local analytic field, which non-fully-addressable
        # arrays cannot do (_fetch is collective — all processes call)
        norms = solver.error_norms(
            type(out)(u=_fetch(out.u), t=out.t, it=out.it)
        )
        summary.error_l1, summary.error_l2, summary.error_linf = tuple(norms)

    if save_dir:
        u_host = _fetch(out.u)
        # Safe rank divergence: the allgather above was collective
        # (every rank calls _fetch); result/summary publishing is
        # single-writer by design and nothing downstream of it holds
        # a rendezvous this rank could miss.
        # tpucfd-check: allow[rank-divergent-effect]
        if is_coord:
            io_utils.save_binary(u_host, os.path.join(save_dir, "result.bin"))
            summary.write_json(os.path.join(save_dir, "summary.json"))
            if plot:
                from multigpu_advectiondiffusion_tpu.utils.plot import (
                    plot_field,
                )

                plot_field(
                    u_host,
                    grid=solver.grid,
                    title=f"{name} t={float(out.t):.4f}",
                    path=os.path.join(save_dir, f"{name}.png"),
                )

    if is_coord:
        summary.print_block()
    return summary
