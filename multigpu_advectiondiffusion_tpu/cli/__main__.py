"""Command-line interface.

Replaces the reference's per-project positional-arg binaries + shell/MATLAB
harness (``./Diffusion3d.run K L W H Nx Ny Nz iters bX bY bZ``,
``run.sh``/``Run.m`` — SURVEY §3.1/§3.5) with one argparse CLI:

    python -m multigpu_advectiondiffusion_tpu.cli diffusion3d \
        --K 1.0 --lengths 2 2 2 --n 400 200 200 --iters 1000 --save out/
    python -m multigpu_advectiondiffusion_tpu.cli burgers3d \
        --t-end 0.06 --cfl 0.3 --n 400 400 400 --save out/ --plot
    python -m multigpu_advectiondiffusion_tpu.cli convergence --ndim 3
    python -m multigpu_advectiondiffusion_tpu.cli diffusion3d \
        --n 256 256 256 --iters 100 --mesh dz=4,dy=2
    python -m multigpu_advectiondiffusion_tpu.cli --model adr \
        --n 128 128 128 --velocity 0.5 --kappa-variation 0.2 \
        --reaction 0.3 --iters 200 --mesh dz=4

Model subcommands (``diffusion3d``, ``burgers2d``, ``adr3d``, ...) are
GENERATED from the solver-plugin registry (``models/registry.py``);
``--model NAME`` resolves through the same registry (dimensionality
from ``--ndim`` or the ``--n`` arity), so a newly registered family is
immediately runnable with no CLI edits.

Block sizes (bX/bY/bZ) have no TPU meaning and are not taken; XLA/Pallas
choose tiling.

Exit codes (full table in README "Failure modes & resilience"):
0 success; 1 failure; 75 preempted (SIGTERM/SIGINT landed; a final
CRC-valid checkpoint + ``preempt.json`` manifest were written to
``--save DIR`` — rerun the same command with ``--resume auto``);
76 rank failure (a peer process of a multi-process run died or stalled
past ``--watchdog-timeout``; restart — on the surviving topology if a
host is gone — with ``--resume auto``); 77 silent data corruption
detected (``--sdc-every``) and the rollback budget exhausted.
"""

from __future__ import annotations

import argparse
import sys

from multigpu_advectiondiffusion_tpu.cli.drivers import (
    decomposition_for,
    parse_mesh_spec,
    run_ensemble_solver,
    run_solver,
)


def _add_common(p: argparse.ArgumentParser, ndim: int):
    p.add_argument("--n", type=int, nargs=ndim, required=True,
                   metavar=tuple("N" + c for c in "xyz"[:ndim]),
                   help="grid nodes per physical axis (x [y [z]])")
    p.add_argument("--lengths", type=float, nargs=ndim, default=None,
                   help="physical extents (L [W [H]]); domain centered at 0")
    p.add_argument("--iters", type=int, default=None,
                   help="fixed iteration count (reference main.c mode)")
    p.add_argument("--t-end", type=float, default=None,
                   help="march to this simulated time instead of --iters")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64", "bfloat16"])
    p.add_argument("--precision", default="native",
                   choices=["native", "bf16"],
                   help="storage precision rung: bf16 = store the "
                        "run-resident state (HBM buffers, every halo/"
                        "remote-DMA wire byte) in bfloat16 while all "
                        "stencil taps and RK stages compute in float32, "
                        "with compensated (Kahan hi/lo) accumulation on "
                        "the generic path — half the memory traffic at "
                        "float32 arithmetic; requires --dtype float32 "
                        "and validates loudly per rung (single-run "
                        "only; per-stage Burgers needs --fixed-dt and "
                        "engages the slab rung)")
    p.add_argument("--ic", default=None, help="initial-condition name")
    p.add_argument("--bc", default=None, nargs="*",
                   help="boundary kind(s): one value or one per axis "
                        "(dirichlet|edge|periodic)")
    p.add_argument("--integrator", default="ssp_rk3",
                   choices=["euler", "ssp_rk2", "ssp_rk3"])
    p.add_argument("--mesh", default=None,
                   help="device-mesh spec, e.g. 'dz=4' or 'dz=4,dy=2'; a "
                        "'_suffix' groups members of a compound axis for "
                        "one grid axis, outermost first — the multi-host "
                        "layout 'dz_dcn=2,dz_ici=4' splits z over 2 "
                        "process granules x 4 chips")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="multi-process launch (the mpirun analog): run "
                        "one CLI process per host with the same "
                        "--coordinator and --num-processes and a unique "
                        "--process-id; jax.distributed joins them and "
                        "the mesh spans every process's devices")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--save", default=None, metavar="DIR",
                   help="write initial.bin/result.bin/summary.json here")
    p.add_argument("--plot", action="store_true",
                   help="also render a PNG into --save DIR")
    p.add_argument("--check-error", action="store_true",
                   help="report L1/L2/Linf vs the analytic solution")
    p.add_argument("--repeats", type=int, default=1,
                   help="timed repetitions; best time is reported")
    p.add_argument("--snapshot-every", type=int, default=0, metavar="N",
                   help="write snap_NNNNNN.bin every N iters (async)")
    p.add_argument("--checkpoint-every", type=int, default=0, metavar="N",
                   help="write restartable checkpoint_NNNNNN.ckpt every N "
                        "iters (atomic, CRC-verified)")
    p.add_argument("--checkpoint-keep", type=int, default=0, metavar="N",
                   help="keep only the newest N checkpoints (0 = keep all)")
    p.add_argument("--checkpoint-sharded", action="store_true",
                   help="write per-shard checkpoint directories (.ckptd: "
                        "each process saves only its addressable shards + "
                        "a layout manifest — no gather to one host; resume "
                        "reassembles onto any mesh)")
    p.add_argument("--resume", default=None, metavar="CKPT",
                   help="resume from a .ckpt/.npz/.ckptd checkpoint "
                        "instead of the initial condition; 'auto' scans "
                        "--save DIR for the newest CRC-valid checkpoint, "
                        "skipping corrupt/truncated ones")
    p.add_argument("--sentinel-every", type=int, default=0, metavar="N",
                   help="divergence-sentinel cadence: a mesh-aware "
                        "all-finite + norm-growth probe every N steps "
                        "between fused-run calls; on divergence the run "
                        "rolls back to the last good checkpoint and "
                        "retries with dt scaled by --dt-backoff "
                        "(0 = unsupervised)")
    p.add_argument("--sentinel-growth", type=float, default=1e3,
                   metavar="G",
                   help="sentinel norm bound: max|u| may not exceed G x "
                        "max(1, initial max|u|)")
    p.add_argument("--max-retries", type=int, default=3, metavar="N",
                   help="rollback-and-retry budget before the "
                        "divergence error propagates")
    p.add_argument("--dt-backoff", type=float, default=0.5, metavar="F",
                   help="dt (fixed-dt solvers) or CFL (adaptive) "
                        "multiplier applied per rollback retry")
    p.add_argument("--watchdog-timeout", type=float, default=0.0,
                   metavar="S",
                   help="rank-liveness watchdog for multi-process runs "
                        "(needs --save DIR): every process writes a "
                        "heartbeat record and monitors its peers'; a "
                        "peer dead or silent for S seconds aborts THIS "
                        "process with exit code 76 and a structured "
                        "rank_failure report instead of hanging in a "
                        "collective forever (0 = off, the MPI "
                        "abort-the-world model)")
    p.add_argument("--checkify", action="store_true",
                   help="runtime sanitizer: compile every dispatch "
                        "program with jax.experimental.checkify NaN/"
                        "div-by-zero/OOB checks discharged in; a trip "
                        "names the offending primitive and recovers "
                        "through the supervisor's rollback path (the "
                        "cuda-memcheck analog; single-device runs "
                        "only — see README 'Static analysis & "
                        "sanitizers')")
    p.add_argument("--sdc-every", type=int, default=0, metavar="M",
                   help="silent-data-corruption guard: every M-th "
                        "sentinel probe re-executes one step from the "
                        "probed state and compares bit-exact; a "
                        "mismatch emits an sdc:detect event and "
                        "recovers via rollback WITHOUT a dt backoff "
                        "(0 = off; needs --sentinel-every; costs two "
                        "extra steps per check)")
    p.add_argument("--diag-every", type=int, default=0, metavar="M",
                   help="in-situ physics diagnostics: every M-th "
                        "sentinel probe evaluates the fused observable "
                        "suite (conservation budgets, total variation, "
                        "spectral high-wavenumber tail, per-solver "
                        "extras — all inside the sentinel's ONE jitted "
                        "probe) and emits a phys:diag event; tolerance-"
                        "rule breaches (max-principle, TV growth) emit "
                        "phys:violation warnings; the trajectory lands "
                        "in summary.json's diagnostics block for the "
                        "science gate (0 = off; needs --sentinel-every)")
    p.add_argument("--diag-strict", action="store_true",
                   help="escalate a phys:violation into the rollback + "
                        "dt-backoff retry path instead of a warning "
                        "(needs --diag-every)")
    p.add_argument("--snapshots", type=int, default=0, metavar="N",
                   help="supervised field-snapshot streaming: write a "
                        "downsampled snap_NNNNNN.bin every N steps "
                        "through the double-buffered background writer "
                        "(atomic publish, io:snapshot_write events; "
                        "needs --sentinel-every — unsupervised runs use "
                        "--snapshot-every)")
    p.add_argument("--snapshot-stride", type=int, default=1, metavar="S",
                   help="downsample snapshots by striding every axis "
                        "(u[::S, ::S, ...]) before writing — 1/S^d of "
                        "the field's bytes per snapshot (default 1)")
    p.add_argument("--snapshot-max-bytes", type=int, default=0,
                   metavar="N",
                   help="rotation cap for snapshot files (both "
                        "--snapshots and --snapshot-every): delete the "
                        "oldest snapshots once their total exceeds N "
                        "bytes, keeping the newest — the --metrics-max-"
                        "bytes discipline for fields (0 = unbounded)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler device trace of the timed "
                        "solve into DIR (TensorBoard/Perfetto viewable) — "
                        "the nvprof wrapping of profile.sh, TPU-style")
    p.add_argument("--trace", dest="profile", metavar="DIR",
                   help="alias for --profile: the captured trace carries "
                        "the whole rung hierarchy as labeled spans "
                        "(tpucfd.run[<stepper>], tpucfd.halo_exchange_*, "
                        "tpucfd.<rung> step bodies) viewable in Perfetto")
    p.add_argument("--metrics", default=None, metavar="PATH",
                   help="stream structured telemetry to PATH as JSONL: "
                        "span/counter events from dispatch and halo "
                        "exchanges, per-executable XLA cost/memory "
                        "capture (xla:cost — compiler-reported flops/"
                        "bytes + compile seconds per compiled program), "
                        "chunk-cadence physics probes and device-memory "
                        "watermarks (mem:watermark, supervised runs), "
                        "resilience events (rollbacks, retries, "
                        "preemption), checkpoint writes and calibration "
                        "updates — see README 'Observability' for the "
                        "event schema; analyze or merge streams with "
                        "the 'trace' subcommand (incl. the measured-vs-"
                        "modeled report section)")
    p.add_argument("--metrics-max-bytes", type=int, default=0,
                   metavar="N",
                   help="size-capped rotation for the --metrics stream: "
                        "when the file exceeds N bytes it rotates to "
                        "PATH.1 (previous rotation dropped) and a "
                        "sink:rotate event opens the fresh tail — "
                        "long supervised runs keep the newest ~2N "
                        "bytes of evidence (0 = unbounded)")
    p.add_argument("--progress", action="store_true",
                   help="live terminal status line at the supervised "
                        "chunk cadence (step, rate, MLUPS, ETA, mass "
                        "drift, outliers) rendered from the "
                        "supervisor's progress events; needs "
                        "--sentinel-every > 0")
    p.add_argument("--impl", default="xla",
                   choices=["xla", "pallas", "pallas_axis", "pallas_step",
                            "pallas_slab", "pallas_stage", "auto"],
                   help="kernel strategy (pallas = best available: fused/"
                        "VMEM-slab TPU kernels where eligible, XLA "
                        "otherwise — incl. for WENO7 and non-f32 dtypes, "
                        "where XLA measures faster / Pallas has no "
                        "lowering; pallas_slab = pin the 3-D whole-run "
                        "slab stepper; pallas_stage = pin the 3-D "
                        "per-stage stepper; pallas_axis = pin the "
                        "per-axis slab kernels; pallas_step = whole-step "
                        "temporal blocking; auto = measured: resolve the "
                        "rung AND --steps-per-exchange from the tuning "
                        "cache, measuring candidates on a miss when "
                        "--tune is given; the summary's 'kernel path' "
                        "line reports what actually ran)")
    p.add_argument("--steps-per-exchange", type=int, default=1,
                   metavar="K",
                   help="communication-avoiding halo cadence: exchange a "
                        "K*G-deep ghost zone once per K steps (redundant "
                        "ghost recompute in between) instead of G-deep "
                        "every step — sharded z-slab slab-rung runs "
                        "only; 1 = the reference's per-step MPI cadence; "
                        "with --impl auto the tuner picks K")
    p.add_argument("--exchange", choices=["collective", "dma"],
                   default="collective",
                   help="halo-exchange transport for sharded slab-rung "
                        "runs: collective = XLA ppermute between "
                        "compiled calls (default, the reference's MPI "
                        "shape); dma = in-kernel remote DMA — the "
                        "sharded whole-run Pallas program pushes its "
                        "ghost rows to the ±z neighbors itself and "
                        "never returns to XLA between steps (z-slab "
                        "meshes, TPU backend or the CPU interpret "
                        "simulator; validated loudly like --impl pins; "
                        "with --impl auto the tuner picks it)")
    p.add_argument("--tune", action="store_true",
                   help="allow the --impl auto tuner to MEASURE on a "
                        "cache miss: time the (rung x K) candidate "
                        "space (cost-model pruned) and persist the "
                        "winner to the tuning cache; without this, auto "
                        "uses the cache or falls back to --impl pallas")
    p.add_argument("--tuning-cache", default=None, metavar="PATH",
                   help="tuning decision cache file (default: "
                        "$TPUCFD_TUNING_CACHE or ~/.cache/"
                        "multigpu_advectiondiffusion_tpu/tuning.json); "
                        "atomic JSON, one audited decision per (solver, "
                        "shape, dtype, mesh, backend) key")
    p.add_argument("--ensemble", type=int, default=0, metavar="B",
                   help="batched ensemble engine: advance B independent "
                        "members (varying ICs and/or swept scalars — see "
                        "--sweep) in ONE compiled batched dispatch "
                        "instead of B serialized runs; per-member "
                        "summaries (max|u|, mass drift) and member-"
                        "attributed divergence ride the batch. Composes "
                        "with --mesh through a 'members' axis (--mesh "
                        "members=8, or members=4,dz=2 for the members x "
                        "z-slab composition — one dispatch serves B x P "
                        "users); uniform-physics ensembles fold B into "
                        "the whole-run slab rung's Pallas grid where it "
                        "engages. A purely spatial --mesh still declines "
                        "loudly (README 'Ensemble engine'; 0 = off)")
    p.add_argument("--sweep", action="append", default=[],
                   metavar="NAME=a:b",
                   help="member-varying parameter for --ensemble B: "
                        "NAME=a:b sweeps linearly across the B members, "
                        "NAME=v1,v2,... lists one value per member. NAME "
                        "is a member-varying scalar (diffusion: K/"
                        "diffusivity; burgers: cfl) or an IC parameter "
                        "as ic.PARAM (e.g. ic.width, ic.left/ic.right "
                        "for Riemann-state sweeps); repeatable")
    p.add_argument("--aot-cache", default=None, metavar="DIR",
                   help="persistent AOT executable cache (also "
                        "$TPUCFD_AOT_CACHE): compiled dispatch programs "
                        "are serialized here keyed by (solver, shape, "
                        "dtype, mesh, impl, steps-per-exchange, ensemble "
                        "B, operand avals, backend, jax version); a "
                        "repeat request deserializes instead of "
                        "recompiling (aot_cache:hit events; xla:cost "
                        "records compile_seconds_saved). Corrupt/stale "
                        "entries are misses, writes are atomic")
    p.add_argument("--dt-scale", type=float, default=1.0, metavar="F",
                   help="scale the initial time step (fixed-dt "
                        "solvers) or CFL (adaptive) by F before the "
                        "run — the scheduler's dt-backoff INHERITANCE "
                        "knob: a retried job starts at the reduced dt "
                        "its failed attempt backed off to instead of "
                        "re-diverging at full dt (applied after resume "
                        "validation; 1.0 = off)")
    p.add_argument("--overlap", default="padded",
                   choices=["padded", "split"],
                   help="sharded halo schedule: 'padded' exchanges before "
                        "each stencil, 'split' overlaps interior compute "
                        "with the in-flight exchange (on z-slab meshes the "
                        "fused steppers run the three-call interior/edge "
                        "schedule — the reference's five-stream "
                        "choreography, main.c:203-260)")


def _grid(args, ndim):
    from multigpu_advectiondiffusion_tpu.core.grid import Grid

    lengths = args.lengths if args.lengths is not None else [2.0] * ndim
    if args.bc and all(b == "periodic" for b in args.bc):
        return Grid.make_periodic(*args.n, lengths=lengths)
    return Grid.make(*args.n, lengths=lengths)


def _mesh_decomp(args, grid):
    mesh, sizes = parse_mesh_spec(args.mesh)
    return mesh, decomposition_for(grid, sizes)


def _run_model(spec, args, ndim, name=None, **build_extra):
    """ONE runner for every registered solver family: build the config
    through the spec's ``cli_build`` hook, then drive the shared
    single-run / batched-ensemble machinery. Adding a model touches the
    registry, never this function (ISSUE 15)."""
    grid = _grid(args, ndim)
    cfg = spec.cli_build(args, grid, ndim, **build_extra)
    name = name or f"{spec.name}{ndim}d"
    from multigpu_advectiondiffusion_tpu import telemetry

    # registry-resolution provenance: which family/spec served this
    # run (lands in the --metrics stream for --model AND subcommand
    # invocations alike)
    telemetry.event("model", "resolve", model=spec.name, ndim=ndim,
                    command=name)
    if args.ensemble and args.ensemble > 1:
        # batched ensemble engine: one vmapped dispatch advances every
        # member; sweep aliases (e.g. K -> diffusivity) come from the
        # family's registration spec
        return run_ensemble_solver(
            spec.solver_cls, cfg, name, args,
            aliases=dict(spec.sweep_aliases),
        )
    mesh, decomp = _mesh_decomp(args, grid)
    solver = spec.solver_cls(cfg, mesh=mesh, decomp=decomp)
    iters = args.iters if args.t_end is None else None
    if iters is None and args.t_end is None:
        iters = 100
    return run_solver(solver, name, iters=iters, t_end=args.t_end,
                      save_dir=args.save, plot=args.plot,
                      check_error=spec.check_error and args.check_error,
                      repeats=args.repeats,
                      snapshot_every=args.snapshot_every,
                      checkpoint_every=args.checkpoint_every,
                      checkpoint_keep=args.checkpoint_keep,
                      checkpoint_sharded=args.checkpoint_sharded,
                      resume=args.resume, profile_dir=args.profile,
                      sentinel_every=args.sentinel_every,
                      sentinel_growth=args.sentinel_growth,
                      max_retries=args.max_retries,
                      dt_backoff=args.dt_backoff,
                      watchdog_timeout=args.watchdog_timeout,
                      sdc_every=args.sdc_every,
                      progress=args.progress,
                      diag_every=args.diag_every,
                      diag_strict=args.diag_strict,
                      snapshots=args.snapshots,
                      snapshot_stride=args.snapshot_stride,
                      snapshot_max_bytes=args.snapshot_max_bytes,
                      dt_scale=args.dt_scale,
                      metrics_path=getattr(args, "metrics", None),
                      metrics_max_bytes=args.metrics_max_bytes)


def _run_convergence(args):
    """The TestingAccuracy.m equivalent: grid-refinement OOA study.

    ``--save DIR`` archives the study the way TestingAccuracy.m does
    (``Matlab_Prototipes/DiffusionNd/TestingAccuracy.m:51-70`` saves
    ``TestAccuracy.fig`` + ``.log``): the printed table as
    ``convergence.log``, machine-readable rows as ``convergence.json``,
    and a loglog error-vs-h figure as ``convergence.png`` (when
    matplotlib is available).
    """
    import json as _json

    from multigpu_advectiondiffusion_tpu.core.grid import Grid
    from multigpu_advectiondiffusion_tpu.models.diffusion import (
        DiffusionConfig,
        DiffusionSolver,
    )
    from multigpu_advectiondiffusion_tpu.utils.metrics import observed_order

    ndim = args.ndim
    ns = args.cells or {1: [17, 33, 65, 129], 2: [17, 33, 65],
                        3: [9, 17, 33]}[ndim]
    lines = [
        f"-- diffusion{ndim}d grid-refinement study "
        f"(TestingAccuracy.m analog), dtype={args.dtype}",
        f"{'n':>6} {'L1':>12} {'Linf':>12} {'OOA(L1)':>8}",
    ]
    rows = []
    prev_l1 = None
    for n in ns:
        grid = Grid.make(*(n,) * ndim, lengths=10.0)
        solver = DiffusionSolver(
            DiffusionConfig(grid=grid, dtype=args.dtype, order=args.order)
        )
        out = solver.advance_to(solver.initial_state(), args.t_end)
        norms = solver.error_norms(out, t=args.t_end)
        ooa = observed_order(prev_l1, norms.l1) if prev_l1 else None
        lines.append(
            f"{n:>6} {norms.l1:>12.4e} {norms.linf:>12.4e} "
            + (f"{ooa:8.2f}" if ooa is not None else " " * 8)
        )
        rows.append({"n": n, "h": grid.spacing[0], "l1": norms.l1,
                     "linf": norms.linf, "ooa_l1": ooa})
        prev_l1 = norms.l1
    print("\n".join(lines))
    if args.save:
        import os

        os.makedirs(args.save, exist_ok=True)
        from multigpu_advectiondiffusion_tpu.utils.io import (
            atomic_write_text,
        )

        atomic_write_text(os.path.join(args.save, "convergence.log"),
                          "\n".join(lines) + "\n")
        atomic_write_text(
            os.path.join(args.save, "convergence.json"),
            _json.dumps({"ndim": ndim, "dtype": args.dtype,
                         "order": args.order, "t_end": args.t_end,
                         "rows": rows}, indent=1),
        )
        from multigpu_advectiondiffusion_tpu.utils.plot import (
            plot_convergence,
        )

        try:
            plot_convergence(
                rows, args.order,
                os.path.join(args.save, "convergence.png"),
                title=f"diffusion{ndim}d OOA study",
            )
        except ImportError:
            pass  # matplotlib not installed: log/json still archived
    return None


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="multigpu_advectiondiffusion_tpu")
    sub = ap.add_subparsers(dest="command", required=True)

    # model subcommands are GENERATED from the solver-plugin registry:
    # every registered family gets <name>{1,2,3}d commands with its
    # spec's flags — a new model registers itself (models/registry.py)
    # and appears here with zero CLI edits (ISSUE 15)
    from multigpu_advectiondiffusion_tpu.models import (
        registry as model_registry,
    )

    for spec in model_registry.specs():
        for ndim in spec.cli_dims:
            p = sub.add_parser(
                f"{spec.name}{ndim}d",
                help=f"{ndim}-D {spec.description}",
            )
            _add_common(p, ndim)
            spec.cli_configure(p, ndim)
            p.set_defaults(
                fn=lambda a, s=spec, d=ndim: _run_model(s, a, d)
            )

    # the axisymmetric r-y geometry stays a dedicated command (its
    # defaults differ), but runs through the SAME registry spec
    p = sub.add_parser("diffusion-axisym",
                       help="axisymmetric r-y diffusion "
                            "(heat2d_axisymmetric.m)")
    _add_common(p, 2)
    model_registry.get("diffusion").cli_configure(p, 2, axisym=True)
    p.set_defaults(fn=lambda a: _run_model(
        model_registry.get("diffusion"), a, 2,
        name="diffusion_axisym", geometry="axisymmetric",
    ))

    p = sub.add_parser("convergence",
                       help="grid-refinement accuracy study "
                            "(TestingAccuracy.m)")
    p.add_argument("--ndim", type=int, default=3, choices=[1, 2, 3])
    p.add_argument("--cells", type=int, nargs="*", default=None)
    p.add_argument("--t-end", type=float, default=0.2)
    p.add_argument("--dtype", default="float64")
    p.add_argument("--order", type=int, default=4, choices=[2, 4])
    p.add_argument("--save", default=None, metavar="DIR",
                   help="archive the study (convergence.log/.json + "
                        "loglog .png) like TestingAccuracy.m's "
                        "TestAccuracy.fig/.log")
    p.set_defaults(fn=_run_convergence)

    # tpucfd-trace: the consumable layer over --metrics streams (also
    # runnable standalone: python -m multigpu_advectiondiffusion_tpu.cli.trace)
    from multigpu_advectiondiffusion_tpu.cli import trace as trace_cli

    p = sub.add_parser("trace",
                       help="analyze/merge --metrics JSONL streams "
                            "(tpucfd-trace): cross-rank clock-aligned "
                            "merge, phase breakdown, measured-vs-"
                            "roofline per rung, critical path, "
                            "Chrome/Perfetto trace_event export")
    trace_cli.configure_parser(p)

    # tpucfd-check: project static analysis (also standalone:
    # python -m multigpu_advectiondiffusion_tpu.analysis)
    from multigpu_advectiondiffusion_tpu.analysis import cli as check_cli

    p = sub.add_parser("check",
                       help="static analysis (tpucfd-check): AST lint "
                            "rules (closure constants, host syncs in "
                            "traced code, non-atomic writes, "
                            "unregistered telemetry) + the stencil/"
                            "halo consistency verifier; --selftest "
                            "proves every rule trips on a seeded "
                            "violation")
    check_cli.configure_parser(p)

    # crash-safe multi-run scheduler (service/): a journaled queue of
    # run requests multiplexed onto the device budget
    from multigpu_advectiondiffusion_tpu.service import cli as service_cli

    p = sub.add_parser("serve",
                       help="run the crash-safe job scheduler daemon: "
                            "journaled queue, admission control "
                            "(memory watermarks + AOT-warm), priority "
                            "preemption via the checkpoint-and-exit-75 "
                            "path, bounded per-policy retries; "
                            "--verify replays and linearization-checks "
                            "the journal offline (README 'Service "
                            "mode')")
    service_cli.configure_serve(p)

    p = sub.add_parser("submit",
                       help="park one run request in the scheduler's "
                            "spool (atomic; works while no daemon "
                            "runs): submit --root DIR [--priority N "
                            "--devices P] -- diffusion3d --n ... "
                            "--iters ...")
    service_cli.configure_submit(p)

    # continuous-batching request server (service/server.py): scenario
    # requests coalesced onto the ensemble member axis and marched as
    # one batched dispatch, crash-safe by journal replay
    p = sub.add_parser("serve-requests",
                       help="run the crash-safe continuous-batching "
                            "request server: compatible requests "
                            "coalesce onto one batched ensemble "
                            "dispatch, march in bounded slices "
                            "(finished members return, joiners enter "
                            "at slice boundaries), shed-with-retry-"
                            "after under overload; --verify replays "
                            "and linearization-checks the request "
                            "journal offline (README 'Request "
                            "serving')")
    service_cli.configure_serve_requests(p)

    p = sub.add_parser("request",
                       help="park one scenario request in the "
                            "server's spool (atomic; works while no "
                            "server runs): request --root DIR --model "
                            "diffusion --n 64 64 --t-end 0.2 "
                            "[--operand diffusivity=0.5 --wait 60]")
    service_cli.configure_request(p)

    p = sub.add_parser("migrate",
                       help="upgrade a service root's journal to the "
                            "current schema version in place (atomic "
                            "tempfile + rename; idempotent; refuses "
                            "journals stamped with a future version)")
    service_cli.configure_migrate(p)

    # tpucfd-status: the fleet dashboard (also standalone:
    # python -m multigpu_advectiondiffusion_tpu.cli.status)
    from multigpu_advectiondiffusion_tpu.cli import status as status_cli

    p = sub.add_parser("status",
                       help="fleet status dashboard (tpucfd-status): "
                            "journal-replayed request/job states + "
                            "merged cross-process metrics snapshots "
                            "(latency quantiles, queue depth, SLO "
                            "verdict) — live tty redraw, --once for "
                            "scripts, --json for machines")
    status_cli.configure_parser(p)
    p.set_defaults(fn=status_cli.run)

    return ap


def _resolve_model_argv(argv):
    """``--model NAME [--ndim N] ...`` -> the registry-resolved
    ``<NAME><N>d`` subcommand (``tpucfd --model adr --n 64 64 64 ...``).
    ``N`` comes from an explicit ``--ndim`` or the arity of ``--n``;
    unknown model names fail listing the registered families. Leaves
    every other argv untouched."""
    if not argv or argv[0] != "--model":
        return argv
    if len(argv) < 2:
        raise SystemExit("--model needs a model name")
    from multigpu_advectiondiffusion_tpu.models import (
        registry as model_registry,
    )

    name = argv[1]
    rest = list(argv[2:])
    try:
        spec = model_registry.get(name)
    except KeyError as err:
        raise SystemExit(str(err))
    ndim = None
    if "--ndim" in rest:
        i = rest.index("--ndim")
        try:
            ndim = int(rest[i + 1])
        except (IndexError, ValueError):
            raise SystemExit("--ndim wants an integer")
        del rest[i:i + 2]
    elif "--n" in rest:
        j = rest.index("--n") + 1
        ndim = 0
        while j + ndim < len(rest):
            tok = rest[j + ndim]
            try:
                int(tok)
            except ValueError:
                break
            ndim += 1
    if not ndim:
        raise SystemExit(
            "--model needs --ndim N or --n (to infer dimensionality)"
        )
    if ndim not in spec.cli_dims:
        raise SystemExit(
            f"model {name!r} serves {spec.cli_dims}-D grids, not {ndim}-D"
        )
    return [f"{name}{ndim}d"] + rest


def main(argv=None):
    from multigpu_advectiondiffusion_tpu.utils.platform_env import (
        honor_platform_env,
    )

    honor_platform_env()
    if argv is None:
        argv = sys.argv[1:]
    argv = _resolve_model_argv(list(argv))
    args = build_parser().parse_args(argv)
    # telemetry sink BEFORE any distributed/backend work, so the
    # multihost join's retry loop and every later subsystem stream into
    # the same --metrics file
    owned_sink = None
    if getattr(args, "metrics", None):
        from multigpu_advectiondiffusion_tpu import telemetry

        owned_sink = telemetry.install(
            args.metrics,
            max_bytes=getattr(args, "metrics_max_bytes", 0),
        )
    if getattr(args, "aot_cache", None):
        # persistent AOT executable cache: every dispatch program this
        # process compiles is serialized under DIR, and every repeat
        # request (this process or a later one) deserializes instead
        from multigpu_advectiondiffusion_tpu.tuning import aot_cache

        aot_cache.configure(cache_dir=args.aot_cache, enabled=True)
    if getattr(args, "checkify", False):
        # runtime sanitizer: arm process-wide BEFORE any solver builds
        # its dispatch programs (analysis/sanitizer.py)
        from multigpu_advectiondiffusion_tpu.analysis import sanitizer

        sanitizer.configure(enabled=True)
    if getattr(args, "tune", False) or getattr(args, "tuning_cache", None):
        # tuner surface: --tune allows measurement on a cache miss,
        # --tuning-cache points both lookup and persistence at PATH
        from multigpu_advectiondiffusion_tpu import tuning

        tuning.configure(
            cache_path=getattr(args, "tuning_cache", None),
            enabled=True if getattr(args, "tune", False) else None,
        )
    if getattr(args, "num_processes", None) is not None or getattr(
        args, "process_id", None
    ) is not None:
        # symmetric validation: without it, forgetting --coordinator
        # would silently run N independent solves racing on --save
        if not getattr(args, "coordinator", None):
            raise SystemExit(
                "--num-processes/--process-id need --coordinator"
            )
    if getattr(args, "coordinator", None):
        # the mpirun analog (MultiGPU/*/run.sh `mpirun -np 2 ...`): join
        # this process into the jax.distributed runtime BEFORE any
        # backend/mesh work, so jax.devices() spans every process
        if args.num_processes is None or args.process_id is None:
            raise SystemExit(
                "--coordinator needs --num-processes and --process-id"
            )
        from multigpu_advectiondiffusion_tpu.parallel import multihost

        multihost.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    if getattr(args, "dtype", None) == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)
    from multigpu_advectiondiffusion_tpu.resilience.errors import (
        EXIT_RANK_FAILURE,
        EXIT_SDC,
        RankFailureError,
        SDCDetectedError,
    )

    try:
        return args.fn(args)
    except RankFailureError as err:
        # a peer is dead/wedged: exit with the documented code (the
        # watchdog's monitor thread takes the os._exit path instead
        # when the main thread is unreachable inside a collective)
        print(f"rank failure: {err}; exiting {EXIT_RANK_FAILURE}",
              file=sys.stderr, flush=True)
        import jax

        from multigpu_advectiondiffusion_tpu import telemetry

        telemetry.get_sink().close()
        if jax.process_count() > 1:
            # a normal SystemExit would run jax.distributed's atexit
            # shutdown, which blocks on the DEAD peer's disconnect —
            # the hang this exit path exists to rule out
            import os

            os._exit(EXIT_RANK_FAILURE)
        raise SystemExit(EXIT_RANK_FAILURE)
    except SDCDetectedError as err:
        # only reaches the CLI when the rollback budget ran out
        print(f"unrecovered silent data corruption: {err}; "
              f"exiting {EXIT_SDC}", file=sys.stderr)
        raise SystemExit(EXIT_SDC)
    finally:
        if owned_sink is not None:
            from multigpu_advectiondiffusion_tpu import telemetry

            telemetry.uninstall(owned_sink)


if __name__ == "__main__":
    sys.exit(0 if main() is not False else 1)
