"""Pallas-vs-XLA kernel equality.

The gate from SURVEY §7 step 2: the Pallas slab-pipelined kernels must
agree with the XLA shifted-slice reference implementation. On CPU the
kernels run in interpret mode; the same code path compiles via Mosaic on
TPU.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.core.bc import Boundary, pad_axis
from multigpu_advectiondiffusion_tpu.ops import flux as flux_lib
from multigpu_advectiondiffusion_tpu.ops.laplacian import laplacian
from multigpu_advectiondiffusion_tpu.ops.weno import flux_divergence


def _field(shape, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


@pytest.mark.parametrize("shape", [(16, 24), (8, 12, 32)])
def test_laplacian_pallas_matches_xla(shape):
    u = _field(shape)
    spacing = [0.1] * len(shape)
    bcs = [Boundary("dirichlet")] * len(shape)
    ref = laplacian(u, spacing, diffusivity=0.7, bcs=bcs, impl="xla")
    out = laplacian(u, spacing, diffusivity=0.7, bcs=bcs, impl="pallas")
    # f32 tolerance scaled to the field magnitude: the interpret-mode
    # kernel and the fused XLA loop associate/fuse differently.
    scale = float(np.max(np.abs(np.asarray(ref))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-6 * scale)


@pytest.mark.parametrize("ndim,axis", [(2, 0), (2, 1), (3, 0), (3, 1), (3, 2)])
@pytest.mark.parametrize("variant", ["js", "z"])
def test_weno_pallas_matches_xla(ndim, axis, variant):
    shape = {2: (16, 24), 3: (8, 12, 32)}[ndim]
    u = _field(shape, seed=axis)
    fx = flux_lib.burgers()
    bc = Boundary("edge")
    ref = flux_divergence(u, axis, 0.05, fx, variant=variant, bc=bc,
                          impl="xla")
    out = flux_divergence(u, axis, 0.05, fx, variant=variant, bc=bc,
                          impl="pallas")
    scale = float(np.max(np.abs(np.asarray(ref))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-6 * scale)


@pytest.mark.parametrize("ndim,axis", [(2, 0), (2, 1), (3, 0), (3, 1), (3, 2)])
def test_weno7_pallas_matches_xla(ndim, axis):
    """The per-axis Pallas rung now covers WENO7 (halo-4 sweeps — the
    deepest stress of the roll-based tiled-axis construction); every
    sweep axis must match the XLA WENO7 path."""
    shape = {2: (16, 24), 3: (10, 12, 32)}[ndim]
    u = _field(shape, seed=20 + axis)
    fx = flux_lib.burgers()
    bc = Boundary("edge")
    ref = flux_divergence(u, axis, 0.05, fx, order=7, bc=bc, impl="xla")
    out = flux_divergence(u, axis, 0.05, fx, order=7, bc=bc, impl="pallas")
    scale = float(np.max(np.abs(np.asarray(ref))))
    # WENO7 betas carry ~1e5-scale integer coefficients, so f32
    # cancellation noise between the roll- and slice-order evaluations
    # is a few ulp of the *field* scale at near-zero-divergence cells
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5 * scale)


def test_weno7_pallas_solver_end_to_end():
    """A WENO7 solver with impl='pallas_axis' pins the per-axis WENO7
    kernels (explicitly opting out of the fused stepper) and matches the
    XLA solver; impl='pallas' engages the fused WENO7 stepper in BOTH
    dimensions (3-D per-stage, 2-D whole-run — round 5); and a 2-D
    order-7 config too large for the whole-run VMEM budget declines to
    the per-op ladder with XLA winning (the per-axis WENO7 kernel
    measures ~2x slower at 512^3 — 'pallas' promises best-available)."""
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    outs = {}
    for impl in ("xla", "pallas_axis"):
        cfg = BurgersConfig(grid=grid, weno_order=7, cfl=0.3,
                            adaptive_dt=False, dtype="float32",
                            ic="gaussian", impl=impl)
        solver = BurgersSolver(cfg)
        assert solver._fused_stepper() is None
        st = solver.run(solver.initial_state(), 4)
        outs[impl] = np.asarray(st.u)
    scale = float(np.max(np.abs(outs["xla"])))
    np.testing.assert_allclose(outs["pallas_axis"], outs["xla"],
                               rtol=1e-4, atol=1e-6 * scale)

    auto = BurgersSolver(BurgersConfig(
        grid=grid, weno_order=7, dtype="float32", impl="pallas"))
    assert auto.engaged_path()["stepper"] == "fused-stage"

    flat = BurgersSolver(BurgersConfig(
        grid=Grid.make(32, 32, lengths=4.0), weno_order=7,
        dtype="float32", impl="pallas"))
    assert flat.engaged_path()["stepper"] == "fused-whole-run"

    big = BurgersSolver(BurgersConfig(
        grid=Grid.make(8192, 8192, lengths=4.0), weno_order=7,
        dtype="float32", impl="pallas"))
    path = big.engaged_path()
    assert path["stepper"] == "generic-xla"
    assert "pallas_axis" in path["fallback"]


def test_weno7_pallas_supported_gates():
    """WENO7 support: JS only (like the XLA path and the reference's
    MATLAB-only WENO7), 2-D/3-D, VMEM-gated with the larger live set."""
    from multigpu_advectiondiffusion_tpu.ops.pallas import weno as pw

    assert pw.supported(3, 7, "js", shape=(512, 512, 512))
    assert not pw.supported(3, 7, "z", shape=(64, 64, 64))
    assert pw.supported(2, 7, "js", shape=(400, 406))
    assert not pw.supported(1, 7, "js", shape=(1000,))


def test_pallas_impls_gate_non_f32_dtypes_to_xla():
    """Non-f32 dtypes under any pallas flavor dispatch the per-op path
    to XLA (the per-axis DMA/roll kernels are f32-calibrated and Mosaic
    has no f64 vector path — on TPU the kernel would fail in the
    compiler, not fall back), and the engaged path says so. The ONE
    exception since the slab-run round: 3-D diffusion f64 rides the
    fused f32 kernels through the f64-storage/f32-compute convention
    instead of losing the whole ladder."""
    grid = Grid.make(16, 12, 12, lengths=4.0)
    d = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float64", impl="pallas"))
    assert d._op_impl() == "xla"  # per-axis kernels stay f32-only
    p = d.engaged_path()
    assert p["stepper"] in ("fused-whole-run-slab", "fused-stage")
    # 2-D diffusion f64 has no storage rung: generic path, reason given
    d2 = DiffusionSolver(
        DiffusionConfig(grid=Grid.make(16, 12, lengths=4.0),
                        dtype="float64", impl="pallas"))
    p2 = d2.engaged_path()
    assert p2["stepper"] == "generic-xla" and "f64 storage" in p2["fallback"]
    b = BurgersSolver(
        BurgersConfig(grid=grid, dtype="float64", impl="pallas_axis"))
    assert b._op_impl() == "xla"
    assert "float32-only" in b.engaged_path()["fallback"]
    # f32 keeps the per-axis kernels
    b32 = BurgersSolver(
        BurgersConfig(grid=grid, dtype="float32", impl="pallas_axis"))
    assert b32._op_impl() == "pallas"


def test_laplacian_pallas_gates_vmem_exceeding_rows():
    """The 3-D block picker must size the z-block against VMEM, not a
    fixed 8: the reference's 1601x986x35 slab workload (6.6 MB rows)
    OOM'd the compiler at the old divisor-only default (bz=7) and is
    viable only at bz=1; rows too wide for even a 1-row block must be
    rejected to the XLA path."""
    from multigpu_advectiondiffusion_tpu.ops.pallas import laplacian as pl_lap

    row = pl_lap._aligned_row_bytes_3d((35, 986, 1601), 4)
    assert pl_lap.pick_vmem_block_3d(35, row) == 1
    assert pl_lap.supported((35, 986, 1601), 4, 4)
    # ~33 MB rows: no viable block at all -> XLA fallback
    assert not pl_lap.supported((35, 2000, 4000), 4, 4)
    # 512^2 trailing: bz=8 measured 105.1 MB (over the 100 MiB scope);
    # the picker must stop at 4
    row512 = pl_lap._aligned_row_bytes_3d((512, 512, 512), 4)
    assert pl_lap.pick_vmem_block_3d(512, row512) == 4
    assert pl_lap.supported((512, 512, 512), 4, 4)
    assert pl_lap.supported((160, 204, 508), 4, 4)


def test_weno_pallas_supported_at_flagship_grid():
    """The per-axis Pallas WENO kernel must accept the 512^3 benchmark
    grid (the one Burgers config with a published reference number,
    SingleGPU/Burgers3d_WENO5/Run.m:15-25) — the z-block shrinks against
    VMEM rather than rejecting large rows."""
    from multigpu_advectiondiffusion_tpu.ops.pallas import weno as pw

    for variant in ("js", "z"):
        assert pw.supported(3, 5, variant, shape=(512, 512, 512),
                            dtype=jnp.float32)
    # the flagship row size forces a small (but viable) z-block
    b = pw._pick_vmem_block(
        512, 6, pw._row_bytes((518, 512, 512), jnp.float32)
    )
    assert b is not None and 512 % b == 0


@pytest.mark.parametrize("axis", [0, 1, 2])
def test_weno_pallas_explicit_multi_block(axis):
    """Force multiple leading-axis blocks (the flagship-grid regime) and
    check the blocked DMA path against XLA for every sweep axis —
    including the blocked axis itself (in-block halo)."""
    from multigpu_advectiondiffusion_tpu.core.bc import pad_axis
    from multigpu_advectiondiffusion_tpu.ops.pallas.weno import (
        flux_divergence_pallas,
    )

    shape = (12, 16, 32)
    u = _field(shape, seed=10 + axis)
    fx = flux_lib.burgers()
    bc = Boundary("edge")
    ref = flux_divergence(u, axis, 0.05, fx, bc=bc, impl="xla")
    up = pad_axis(u, axis, 3, bc)
    out = flux_divergence_pallas(up, axis, 0.05, fx, block=2)
    scale = float(np.max(np.abs(np.asarray(ref))))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-6 * scale)


def test_impl_pallas_axis_pins_per_axis_kernels():
    """impl='pallas_axis' is the explicit per-axis-kernel rung: the fused
    steppers must NOT engage, and the physics must match XLA."""
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    outs = {}
    for impl in ("xla", "pallas_axis"):
        cfg = BurgersConfig(grid=grid, cfl=0.3, adaptive_dt=False,
                            dtype="float32", ic="gaussian", impl=impl)
        solver = BurgersSolver(cfg)
        assert solver._fused_stepper() is None
        st = solver.run(solver.initial_state(), 4)
        outs[impl] = np.asarray(st.u)
    scale = float(np.max(np.abs(outs["xla"])))
    np.testing.assert_allclose(outs["pallas_axis"], outs["xla"],
                               rtol=1e-4, atol=1e-6 * scale)

    dcfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas_axis")
    assert DiffusionSolver(dcfg)._fused_stepper() is None


def test_fused_diffusion_run_matches_xla():
    """The fused single-kernel-per-stage fast path (run() with
    impl='pallas' on an eligible config) must agree with the generic XLA
    path to f32 rounding across a multi-step run."""
    grid = Grid.make(24, 28, 36, lengths=10.0)
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = DiffusionConfig(grid=grid, dtype="float32", impl=impl)
        solver = DiffusionSolver(cfg)
        if impl == "pallas":
            assert solver._fused_stepper() is not None, "fast path not taken"
        st = solver.run(solver.initial_state(), 9)
        outs[impl] = (np.asarray(st.u), float(st.t))
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=1e-5, atol=1e-6)
    assert outs["pallas"][1] == outs["xla"][1]


@pytest.mark.parametrize("nz,block_z", [(23, None), (14, 4)])
def test_fused_diffusion_non_multiple_nz_pads_dead_rows(nz, block_z):
    """Unsharded fused diffusion pads z to a block multiple instead of
    shrinking the block to a divisor (a prime-ish nz like the literal
    reference grid's 206 would otherwise force a tiny block). The dead
    tail rows hold the Dirichlet value and stay frozen, so results match
    the XLA path exactly as for multiple sizes. nz=23 (prime, above no
    viable same-size block) and an explicit non-divisor block both force
    real dead rows — asserted, so the padding path cannot silently stop
    being exercised."""
    grid = Grid.make(24, 16, nz, lengths=2.0)
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = DiffusionConfig(grid=grid, dtype="float32", impl=impl)
        solver = DiffusionSolver(cfg)
        if impl == "pallas":
            fused = solver._fused_stepper()
            assert fused is not None
            # the iters-mode selection is the slab whole-run stepper; the
            # cache key follows the rung
            key = (
                "fused_slab"
                if fused.engaged_label == "fused-whole-run-slab"
                else "fused"
            )
            if block_z is not None:
                fused = type(fused)(
                    grid.shape, solver.dtype, grid.spacing, [1.0] * 3,
                    solver.dt, 2, 0.0, block_z=block_z,
                )
                solver._cache[key] = fused
            # dead tail rows beyond the interior (halo is the stepper's
            # own fused-step/stage ghost depth)
            dead = fused.padded_shape[0] - 2 * fused.halo - nz
            assert dead > 0, "test must exercise the dead-row path"
        st = solver.run(solver.initial_state(), 6)
        outs[impl] = np.asarray(st.u)
    assert outs["pallas"].shape == outs["xla"].shape
    scale = float(np.max(np.abs(outs["xla"])))
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               rtol=1e-5, atol=2e-6 * scale)


def test_fused_diffusion_advance_to_matches_xla():
    """Diffusion advance_to (the MATLAB heat drivers' native
    `while t < t_end` loop, heat3d.m:48-77) must engage the fused
    stepper's run_to — dt rides a runtime SMEM scalar so the same
    compiled stages serve the trimmed last step — and reproduce the
    generic path's trajectory, landing time, and step count."""
    grid = Grid.make(24, 28, 36, lengths=10.0)
    outs = {}
    t_end = None
    for impl in ("xla", "pallas"):
        cfg = DiffusionConfig(grid=grid, dtype="float32", impl=impl)
        solver = DiffusionSolver(cfg)
        st0 = solver.initial_state()
        if t_end is None:
            t_end = float(st0.t) + 4.5 * solver.dt  # trimmed 5th step
        st = solver.advance_to(st0, t_end)
        if impl == "pallas":
            assert "fused_adv" in solver._cache, "fused t_end path not taken"
        outs[impl] = (np.asarray(st.u), float(st.t), int(st.it))
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs["pallas"][1], t_end, rtol=1e-6)
    assert outs["pallas"][2] == outs["xla"][2] == 5


def test_fused_diffusion_split_overlap_matches_serialized(devices):
    """overlap='split' diffusion on a z-slab mesh runs the three-call
    overlapped schedule (interior blocks concurrent with the z-halo
    ppermute) — matching both the serialized-refresh fused path and the
    generic XLA path, in run() and the fused run_to. Match: the
    reference's five-stream choreography around its tuned kernel
    (MultiGPU/Diffusion3d_Baseline/main.c:203-260, Kernels.cu:207-261)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 16, 120, lengths=2.0)  # local lz=60 -> 3 blocks
    outs = {}
    for overlap in ("split", "padded"):
        cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas",
                              overlap=overlap)
        solver = DiffusionSolver(
            cfg, mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz")
        )
        fused = solver._fused_stepper()
        assert fused is not None and fused.sharded
        assert fused.overlap_split == (overlap == "split")
        st = solver.run(solver.initial_state(), 5)
        outs[overlap] = np.asarray(st.u)
    scale = float(np.max(np.abs(outs["padded"])))
    np.testing.assert_allclose(outs["split"], outs["padded"],
                               rtol=1e-6, atol=1e-7 * scale)

    # run_to on the split path: step count + trajectory vs unsharded
    scfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas",
                           overlap="split")
    ss = DiffusionSolver(
        scfg, mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz")
    )
    st0 = ss.initial_state()
    t_end = float(st0.t) + 3.4 * ss.dt
    out = ss.advance_to(st0, t_end)
    assert "fused_adv" in ss._cache
    ref_solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    )
    ref = ref_solver.advance_to(ref_solver.initial_state(), t_end)
    assert int(out.it) == int(ref.it) == 4
    np.testing.assert_allclose(
        np.asarray(out.u), np.asarray(ref.u), rtol=1e-6, atol=1e-7 * scale
    )


def test_fused_diffusion_advance_to_sharded_pencil(devices):
    """Diffusion run_to on a (dz, dy) pencil mesh exercises the
    serialized-refresh sharded path (pencils can't use split-overlap,
    which is z-slab-only) with the offsets operand — bit-identical to
    the unsharded fused advance_to with the same step count."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 16, 48, lengths=2.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas",
                          overlap="split")  # split requested, pencil denies
    ref_solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    )
    st0 = ref_solver.initial_state()
    t_end = float(st0.t) + 3.4 * ref_solver.dt
    ref = ref_solver.advance_to(st0, t_end)
    solver = DiffusionSolver(
        cfg, mesh=make_mesh({"dz": 2, "dy": 2}),
        decomp=Decomposition.of({0: "dz", 1: "dy"}),
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded and not fused.overlap_split
    out = solver.advance_to(solver.initial_state(), t_end)
    assert "fused_adv" in solver._cache
    assert int(out.it) == int(ref.it) == 4
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref.u))


def test_fused_diffusion_ineligible_configs_fall_back():
    """Configs outside the fused kernel's assumptions must quietly use
    the generic path (and still run)."""
    grid = Grid.make(16, 16, 16, lengths=10.0)
    for kw in (
        {"integrator": "ssp_rk2"},
        {"bc": "periodic", "ic": "gaussian"},
        {"reference_parity": False},
        {"order": 2},
        {"boundary_band": 0},
    ):
        cfg = DiffusionConfig(grid=grid, impl="pallas", **kw)
        solver = DiffusionSolver(cfg)
        assert solver._fused_stepper() is None, kw
        solver.run(solver.initial_state(), 2)


def test_diffusion_solver_pallas_impl():
    grid = Grid.make(32, 24, 16, lengths=10.0)
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = DiffusionConfig(grid=grid, dtype="float32", impl=impl)
        solver = DiffusionSolver(cfg)
        outs[impl] = np.asarray(solver.run(solver.initial_state(), 5).u)
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               rtol=1e-5, atol=1e-6)


def test_burgers_solver_pallas_impl():
    grid = Grid.make(32, 16, lengths=2.0)
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32", impl=impl)
        solver = BurgersSolver(cfg)
        outs[impl] = np.asarray(solver.run(solver.initial_state(), 5).u)
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize(
    "kw",
    [
        {},
        {"weno_variant": "z"},
        {"nu": 1e-3},
        {"flux": "linear"},
        {"flux": "buckley"},
        {"weno_order": 7},
        {"weno_order": 7, "nu": 1e-3},
    ],
    ids=["js", "z", "viscous", "linear", "buckley", "weno7",
         "weno7-viscous"],
)
def test_fused_burgers_run_matches_xla(kw):
    """The fused single-kernel-per-stage Burgers fast path (run() with
    impl='pallas' on an eligible 3-D fixed-dt config) must agree with the
    generic XLA path to f32 rounding across a multi-step run."""
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, cfl=0.3, adaptive_dt=False,
                            dtype="float32", ic="gaussian", impl=impl, **kw)
        solver = BurgersSolver(cfg)
        if impl == "pallas":
            assert solver._fused_stepper() is not None, "fast path not taken"
        st = solver.run(solver.initial_state(), 5)
        outs[impl] = (np.asarray(st.u), float(st.t))
    scale = float(np.max(np.abs(outs["xla"][0])))
    # WENO7's ~1e5-scale beta coefficients amplify f32 reassociation
    # noise between the e-form kernel and the q-form XLA path (same
    # reasoning as test_weno7_pallas_matches_xla), so order 7 carries a
    # wider — still rounding-level — band
    atol = (2e-6 if kw.get("weno_order", 5) == 5 else 3e-5) * scale
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=2e-5, atol=atol)
    assert outs["pallas"][1] == outs["xla"][1]


def test_fused_burgers_adaptive_dt_matches_xla():
    """Adaptive dt on the fused path: the runtime SMEM dt scalar (global
    max|f'(u)| reduction between fused steps) must reproduce the generic
    path's trajectory AND its time axis (restored correct CFL — the
    reference hard-codes max|u|=1, Burgers3d_Baseline/main.c:193)."""
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, cfl=0.3, adaptive_dt=True, nu=1e-5,
                            dtype="float32", ic="gaussian", impl=impl)
        solver = BurgersSolver(cfg)
        if impl == "pallas":
            assert solver._fused_stepper() is not None, "fast path not taken"
        st = solver.run(solver.initial_state(), 5)
        outs[impl] = (np.asarray(st.u), float(st.t))
    scale = float(np.max(np.abs(outs["xla"][0])))
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=2e-5, atol=2e-6 * scale)
    np.testing.assert_allclose(outs["pallas"][1], outs["xla"][1], rtol=1e-5)


# Sharded-vs-unsharded fused equality bound: the kernels are identical
# in both worlds, but in interpret mode (CPU tests) the kernel body is
# compiled by XLA as ordinary ops, and XLA's per-program FMA-contraction
# freedom perturbs the WENO nonlinear weights by a few ulp between the
# two programs (same phenomenon as test_sharded._WENO_ULPS; on real TPU
# the Mosaic-compiled kernel is one artifact with no such freedom).
# Relative bound, f32.
_FUSED_WENO_ULPS = 32 * np.finfo(np.float32).eps


def _assert_fused_close(actual, desired):
    a, d = np.asarray(actual), np.asarray(desired)
    scale = max(float(np.max(np.abs(d))), 1e-30)
    assert float(np.max(np.abs(a - d))) / scale <= _FUSED_WENO_ULPS


@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused_burgers_sharded_matches_unsharded_fused(
    devices, adaptive
):
    """The fused Burgers stepper shard-local under shard_map (ppermute
    ghost refresh between stages, pmax dt reduction) must reproduce the
    single-device fused run to the documented interpret-mode ulp bound —
    the tuned kernel under the mesh, as the reference runs its tuned
    kernels under MPI (MultiGPU/Burgers3d_Baseline/main.c:189-317)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 16, 16, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                        adaptive_dt=adaptive, impl="pallas")
    ref_solver = BurgersSolver(cfg)
    assert ref_solver._fused_stepper() is not None
    ref = ref_solver.run(ref_solver.initial_state(), 5)
    solver = BurgersSolver(
        cfg, mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz")
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded, "sharded fast path not taken"
    out = solver.run(solver.initial_state(), 5)
    _assert_fused_close(out.u, ref.u)
    np.testing.assert_allclose(float(out.t), float(ref.t), rtol=1e-6)


@pytest.mark.parametrize("order", [5, 7], ids=["weno5", "weno7"])
@pytest.mark.parametrize("flux", ["linear", "buckley"])
def test_fused_burgers3d_generic_flux_matches_xla(flux, order):
    """The 3-D fused kernel's generic Lax-Friedrichs split (any Flux,
    not just the Burgers-specialized identity) plus the emitted
    max|f'(u)| for a non-identity df must match the XLA path — only the
    2-D whole-run stepper covered non-Burgers fluxes before. Both
    orders: the split and the emission are shared across the radius-
    parameterized family, and this pins that for halo 4 too."""
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, flux=flux, weno_order=order,
                            cfl=0.3, dtype="float32",
                            ic="gaussian", impl=impl)
        solver = BurgersSolver(cfg)
        if impl == "pallas":
            fused = solver._fused_stepper()
            assert fused is not None and fused._emit_max
        st = solver.run(solver.initial_state(), 4)
        outs[impl] = (np.asarray(st.u), float(st.t))
    scale = float(np.max(np.abs(outs["xla"][0])))
    # order 7 carries the wider e-form/q-form rounding band of the
    # adaptive weno7-vs-XLA tests (dt feeds the gap back per step)
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=2e-5,
                               atol=(2e-6 if order == 5 else 6e-5) * scale)
    np.testing.assert_allclose(outs["pallas"][1], outs["xla"][1], rtol=1e-6)


def test_fused_burgers_adaptive_emits_wave_speed_in_kernel(devices):
    """Adaptive runs emit max|f'(u_next)| from the final stage kernel(s)
    — no between-step HBM re-read (measured: the adaptive row closes to
    ~0.4% of the fixed-dt rate); fixed-dt runs don't build the machinery
    at all. The trajectory equality vs XLA/sharded/split is covered by
    the adaptive tests above — dt comes from the same max, so the
    chains are identical."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(16, 16, 24, lengths=2.0)
    adaptive = BurgersSolver(BurgersConfig(
        grid=grid, nu=1e-5, dtype="float32", impl="pallas"))
    assert adaptive._fused_stepper()._emit_max
    fixed = BurgersSolver(BurgersConfig(
        grid=grid, nu=1e-5, dtype="float32", adaptive_dt=False,
        impl="pallas"))
    assert not fixed._fused_stepper()._emit_max
    # sharded serialized refresh: emission works (local max, pmax in
    # dt_from_max) — execution equality is in the sharded adaptive tests
    sh = BurgersSolver(
        BurgersConfig(grid=grid, nu=1e-5, dtype="float32", impl="pallas"),
        mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"))
    assert sh._fused_stepper()._emit_max
    # split overlap emits too: the three stage-3 calls each fold their
    # own blocks, combined by two scalar maxes in the step
    grid_s = Grid.make(16, 16, 48, lengths=2.0)
    sp = BurgersSolver(
        BurgersConfig(grid=grid_s, nu=1e-5, dtype="float32",
                      impl="pallas", overlap="split"),
        mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"))
    f = sp._fused_stepper()
    assert f.overlap_split and f._emit_max


@pytest.mark.parametrize("ny", [14, 19])
def test_fused_burgers_non_multiple_ny_rounds_with_dead_columns(ny):
    """Unsharded fused Burgers rounds y up to the sublane tile instead of
    rejecting unaligned extents (the reference's 1601x986x35 workload);
    the dead columns are re-filled as edge replicas every stage, so
    results match XLA. Dead columns must actually exist or the path is
    untested."""
    grid = Grid.make(24, ny, 16, lengths=2.0)
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                            adaptive_dt=True, impl=impl)
        solver = BurgersSolver(cfg)
        if impl == "pallas":
            fused = solver._fused_stepper()
            assert fused is not None
            assert fused.padded_shape[1] - 16 > ny, "need dead y columns"
        st = solver.run(solver.initial_state(), 5)
        outs[impl] = np.asarray(st.u)
    assert outs["pallas"].shape == outs["xla"].shape
    scale = float(np.max(np.abs(outs["xla"])))
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               rtol=2e-5, atol=2e-6 * scale)


def test_fused_burgers_y_rounding_composes_with_z_sharding(devices):
    """y-rounding is legal when the y axis is NOT sharded: a z-slab
    decomposition never ships y columns as ghosts, so an unaligned ny
    may still take the fused path — matching the unsharded fused run to
    the documented interpret-mode ulp bound. (A y-sharded unaligned ny
    falls back instead.)"""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 14, 16, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                        adaptive_dt=True, impl="pallas")
    solver = BurgersSolver(
        cfg, mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz")
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded
    out = solver.run(solver.initial_state(), 5)
    ref_solver = BurgersSolver(cfg)
    ref = ref_solver.run(ref_solver.initial_state(), 5)
    _assert_fused_close(out.u, ref.u)

    # y-sharded + unaligned ny must NOT take the fused path
    ysolver = BurgersSolver(
        cfg, mesh=make_mesh({"dy": 2}), decomp=Decomposition.of({1: "dy"})
    )
    assert ysolver._fused_stepper() is None


def test_fused_burgers_ineligible_configs_fall_back():
    """Configs outside the fused Burgers kernel's assumptions must
    quietly use the generic path (and still run)."""
    grid = Grid.make(16, 16, 16, lengths=4.0)
    for kw in (
        {"dtype": "float64"},
        # order 7 is fused-eligible since round 5; f64 still declines it
        {"weno_order": 7, "dtype": "float64"},
        {"integrator": "ssp_rk2"},
        {"bc": "periodic"},
        {"nu": 1e-3, "laplacian_order": 2},
    ):
        cfg = BurgersConfig(grid=grid, ic="gaussian", impl="pallas",
                            **{"adaptive_dt": False, **kw})
        solver = BurgersSolver(cfg)
        assert solver._fused_stepper() is None, kw
        solver.run(solver.initial_state(), 2)
    # adaptive dt is a fused-eligible config (runtime SMEM dt + global
    # max|f'(u)| reduction between steps) — no longer a fallback case
    cfg = BurgersConfig(grid=grid, ic="gaussian", impl="pallas",
                        adaptive_dt=True)
    assert BurgersSolver(cfg)._fused_stepper() is not None


@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused_burgers_advance_to_matches_xla(adaptive):
    """advance_to (the reference Burgers drivers' *native* `while
    (t < tEnd)` mode, MultiGPU/Burgers3d_Baseline/main.c:190-317) must
    engage the fused stepper's run_to and reproduce the generic path's
    trajectory, landing time, and step count."""
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    # ~4.5 generic steps at this CFL: exercises the trimmed last step
    t_end = 0.05
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, cfl=0.3, adaptive_dt=adaptive,
                            nu=1e-5, dtype="float32", ic="gaussian",
                            impl=impl)
        solver = BurgersSolver(cfg)
        st = solver.advance_to(solver.initial_state(), t_end)
        if impl == "pallas":
            assert "fused_adv" in solver._cache, "fused t_end path not taken"
        outs[impl] = (np.asarray(st.u), float(st.t), int(st.it))
    scale = float(np.max(np.abs(outs["xla"][0])))
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=2e-5, atol=2e-6 * scale)
    np.testing.assert_allclose(outs["pallas"][1], t_end, rtol=1e-6)
    assert outs["pallas"][2] == outs["xla"][2] > 0


@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused_burgers_advance_to_sharded_matches_unsharded(devices, adaptive):
    """Fused run_to shard-local under shard_map (ppermute ghost refresh,
    pmax dt) must reproduce the single-device fused advance_to
    bit-for-bit, with the same step count."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 16, 16, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                        adaptive_dt=adaptive, impl="pallas")
    t_end = 0.01
    ref_solver = BurgersSolver(cfg)
    ref = ref_solver.advance_to(ref_solver.initial_state(), t_end)
    assert "fused_adv" in ref_solver._cache
    solver = BurgersSolver(
        cfg, mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz")
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded
    out = solver.advance_to(solver.initial_state(), t_end)
    _assert_fused_close(out.u, ref.u)
    np.testing.assert_allclose(float(out.t), float(ref.t), rtol=1e-6)
    assert int(out.it) == int(ref.it) > 0


@pytest.mark.parametrize("order", [5, 7], ids=["weno5", "weno7"])
@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused_burgers_xsharded_matches_unsharded(devices, adaptive, order):
    """An x-sharded mesh engages the stored-x-ghost layout (interior at
    lane offset r, ppermute refresh rewriting real ghost lanes) instead
    of falling back to the generic path, and must reproduce the
    unsharded fused run — the lane-axis analog of the tuned-kernel-
    under-MPI property (SURVEY §2.1.5: decomposition on any axis)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(16, 16, 48, lengths=2.0)
    cfg = BurgersConfig(grid=grid, weno_order=order, nu=1e-5,
                        dtype="float32", adaptive_dt=adaptive,
                        impl="pallas")
    ref_solver = BurgersSolver(cfg)
    ref_fused = ref_solver._fused_stepper()
    assert ref_fused is not None and not ref_fused.x_sharded
    ref = ref_solver.run(ref_solver.initial_state(), 5)
    solver = BurgersSolver(
        cfg, mesh=make_mesh({"dx": 2}), decomp=Decomposition.of({2: "dx"})
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded and fused.x_sharded, (
        getattr(solver, "_fused_fallback", None)
    )
    assert fused.core_offsets[2] == fused.halo
    out = solver.run(solver.initial_state(), 5)
    _assert_fused_close(out.u, ref.u)
    np.testing.assert_allclose(float(out.t), float(ref.t), rtol=1e-6)


def test_fused_burgers_extent1_mesh_axis_still_engages_fused(devices):
    """An extent-1 mesh axis exchanges no ghosts, so it must not trip
    the y-rounding (or x-layout) eligibility gates: a {dz:4, dy:1} mesh
    with ly % 8 != 0 engages the fused stepper exactly like {dz:4}."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(16, 50, 16, lengths=2.0)  # ly = 50, not 8-aligned
    cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32", impl="pallas")
    s = BurgersSolver(cfg, mesh=make_mesh({"dz": 4, "dy": 1}),
                      decomp=Decomposition.of({0: "dz", 1: "dy"}))
    fused = s._fused_stepper()
    assert fused is not None and not fused.x_sharded, (
        getattr(s, "_fused_fallback", None)
    )
    ref = BurgersSolver(cfg)
    r = ref.run(ref.initial_state(), 3)
    o = s.run(s.initial_state(), 3)
    _assert_fused_close(o.u, r.u)


def test_fused_burgers_xsharded_block_mesh_split_overlap(devices):
    """A {dz, dx} block mesh with overlap='split': the z halo rides the
    overlapped exchanged-slab schedule while the x ghosts (stored-x-ghost
    layout) keep the serialized per-stage refresh — and the exchanged z
    slabs must carry fresh x ghost lanes. Matches the all-serialized
    fused path and the unsharded run."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 16, 48, lengths=2.0)
    unsharded = BurgersSolver(
        BurgersConfig(grid=grid, nu=1e-5, dtype="float32", impl="pallas")
    )
    ref = unsharded.run(unsharded.initial_state(), 5)
    outs = {}
    for overlap in ("split", "padded"):
        cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                            impl="pallas", overlap=overlap)
        solver = BurgersSolver(
            cfg,
            mesh=make_mesh({"dz": 2, "dx": 2}),
            decomp=Decomposition.of({0: "dz", 2: "dx"}),
        )
        fused = solver._fused_stepper()
        assert fused is not None and fused.x_sharded
        assert fused.overlap_split == (overlap == "split"), (
            overlap, getattr(solver, "_fused_fallback", None)
        )
        st = solver.run(solver.initial_state(), 5)
        outs[overlap] = np.asarray(st.u)
        np.testing.assert_allclose(float(st.t), float(ref.t), rtol=1e-6)
    _assert_fused_close(outs["split"], outs["padded"])
    _assert_fused_close(outs["split"], ref.u)


def test_fused_burgers_block_mesh_8dev_split_overlap(devices):
    """A full {dz:2, dy:2, dx:2} BLOCK mesh (all 8 virtual devices) with
    overlap='split': y_sharded AND x_sharded engage simultaneously under
    the split-overlap schedule — the z halo rides the exchanged-slab
    operands while BOTH the y ghosts and the stored-x-ghost lanes take
    the serialized per-stage refresh. This is the one decomposition the
    _split_overlap_requested gate accepts that had no coverage (ADVICE
    round 5). Must match the all-serialized fused path and the
    unsharded fused run."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    # local (24, 16, 24): z hosts a 3-block interior band (bz<=8), local
    # ly=16 is sublane-aligned (y_sharded), lx=24 >= halo
    grid = Grid.make(48, 32, 48, lengths=2.0)
    unsharded = BurgersSolver(
        BurgersConfig(grid=grid, nu=1e-5, dtype="float32", impl="pallas")
    )
    ref = unsharded.run(unsharded.initial_state(), 4)
    outs = {}
    for overlap in ("split", "padded"):
        cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                            impl="pallas", overlap=overlap)
        solver = BurgersSolver(
            cfg,
            mesh=make_mesh({"dz": 2, "dy": 2, "dx": 2}),
            decomp=Decomposition.of({0: "dz", 1: "dy", 2: "dx"}),
        )
        fused = solver._fused_stepper()
        assert fused is not None and fused.sharded, (
            overlap, getattr(solver, "_fused_fallback", None)
        )
        assert fused.x_sharded
        assert fused.overlap_split == (overlap == "split"), (
            overlap, getattr(solver, "_fused_fallback", None)
        )
        st = solver.run(solver.initial_state(), 4)
        outs[overlap] = np.asarray(st.u)
        np.testing.assert_allclose(float(st.t), float(ref.t), rtol=1e-6)
    _assert_fused_close(outs["split"], outs["padded"])
    _assert_fused_close(outs["split"], ref.u)


def test_fused_diffusion_block_mesh_8dev_split_overlap(devices):
    """A full {dz:2, dy:2, dx:2} BLOCK mesh (all 8 virtual devices) with
    overlap='split' for DIFFUSION: the z halo rides the exchanged-slab
    operands while the y and x ghosts (stored on every axis for
    diffusion) take the serialized per-stage refresh. Completes the
    ADVICE r5 coverage of the _split_overlap_requested gate: the Burgers
    8-device block-mesh test pins the WENO side; this pins the O4
    stencil family on the same decomposition. Must match the
    all-serialized fused path and the unsharded fused run."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    # local (48, 8, 16): z's largest block divisor (16) hosts a 3-slab
    # interior band, y/x locals clear the O4 halo (2)
    grid = Grid.make(32, 16, 96, lengths=2.0)
    unsharded = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas_stage")
    )
    ref = unsharded.run(unsharded.initial_state(), 5)
    outs = {}
    for overlap in ("split", "padded"):
        cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas",
                              overlap=overlap)
        solver = DiffusionSolver(
            cfg,
            mesh=make_mesh({"dz": 2, "dy": 2, "dx": 2}),
            decomp=Decomposition.of({0: "dz", 1: "dy", 2: "dx"}),
        )
        fused = solver._fused_stepper()
        assert fused is not None and fused.sharded, (
            overlap, getattr(solver, "_fused_fallback", None)
        )
        assert fused.overlap_split == (overlap == "split"), (
            overlap, getattr(solver, "_fused_fallback", None),
            fused.n_slabs,
        )
        st = solver.run(solver.initial_state(), 5)
        outs[overlap] = np.asarray(st.u)
        np.testing.assert_allclose(float(st.t), float(ref.t), rtol=1e-6)
    _assert_fused_close(outs["split"], outs["padded"])
    _assert_fused_close(outs["split"], ref.u)


def test_fused_diffusion_xsharded_split_overlap(devices):
    """The split-overlap broadening also exposes {dz, dx} DIFFUSION
    meshes: the z halo rides the exchanged-slab schedule while the x
    ghosts (stored layout — diffusion keeps ghosts on every axis) take
    the serialized refresh. Must match the serialized fused path and
    the unsharded fused run to the same ulp band the z-slab split test
    uses (interpret mode compiles each schedule separately, so FMA
    fusion may differ by an ulp)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    # local lz = 96 hosts a >= 3-block interior band for diffusion's
    # larger block sizes
    grid = Grid.make(32, 16, 192, lengths=2.0)
    unsharded = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    )
    ref = unsharded.run(unsharded.initial_state(), 5)
    outs = {}
    for overlap in ("split", "padded"):
        cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas",
                              overlap=overlap)
        solver = DiffusionSolver(
            cfg,
            mesh=make_mesh({"dz": 2, "dx": 2}),
            decomp=Decomposition.of({0: "dz", 2: "dx"}),
        )
        fused = solver._fused_stepper()
        assert fused is not None and fused.sharded
        assert fused.overlap_split == (overlap == "split"), (
            overlap, getattr(solver, "_fused_fallback", None)
        )
        st = solver.run(solver.initial_state(), 5)
        outs[overlap] = np.asarray(st.u)
    scale = float(np.max(np.abs(outs["padded"])))
    np.testing.assert_allclose(outs["split"], outs["padded"],
                               rtol=1e-6, atol=1e-7 * scale)
    np.testing.assert_allclose(outs["split"], np.asarray(ref.u),
                               rtol=1e-6, atol=1e-7 * scale)


def test_fused_burgers_xsharded_advance_to(devices):
    """run_to through the stored-x-ghost layout (adaptive dt, emitted
    wave speed, x refresh between stages) matches the unsharded fused
    trajectory and step count."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(16, 16, 48, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                        adaptive_dt=True, impl="pallas")
    ref_s = BurgersSolver(cfg)
    t_end = 0.04
    ref = ref_s.advance_to(ref_s.initial_state(), t_end)
    solver = BurgersSolver(
        cfg, mesh=make_mesh({"dx": 2}), decomp=Decomposition.of({2: "dx"})
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.x_sharded
    out = solver.advance_to(solver.initial_state(), t_end)
    _assert_fused_close(out.u, ref.u)
    np.testing.assert_allclose(float(out.t), float(ref.t), rtol=1e-6)
    assert int(out.it) == int(ref.it) > 0


@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused_burgers_split_overlap_matches_serialized(devices, adaptive):
    """overlap='split' on a z-slab mesh runs the three-call overlapped
    schedule (interior blocks concurrent with the z-halo ppermute; edge
    blocks consume the exchanged slabs as separate operands) and must
    match both the serialized-refresh fused path and the generic XLA
    path. Match: the reference's five-stream boundary/interior split
    (MultiGPU/Diffusion3d_Baseline/main.c:203-260) applied to the tuned
    kernel."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 16, 48, lengths=2.0)  # local lz=24 -> n_bz=3
    outs = {}
    for overlap in ("split", "padded"):
        cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                            adaptive_dt=adaptive, impl="pallas",
                            overlap=overlap)
        solver = BurgersSolver(
            cfg, mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz")
        )
        fused = solver._fused_stepper()
        assert fused is not None and fused.sharded
        assert fused.overlap_split == (overlap == "split")
        st = solver.run(solver.initial_state(), 5)
        outs[overlap] = np.asarray(st.u)
    _assert_fused_close(outs["split"], outs["padded"])

    xcfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                         adaptive_dt=adaptive, impl="xla")
    xs = BurgersSolver(xcfg)
    ref = np.asarray(xs.run(xs.initial_state(), 5).u)
    scale = float(np.max(np.abs(ref)))
    np.testing.assert_allclose(outs["split"], ref, rtol=2e-5,
                               atol=2e-6 * scale)


@pytest.mark.parametrize("model", ["burgers", "diffusion"])
@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused_split_overlap_pencil_matches_serialized(
    devices, model, adaptive
):
    """overlap='split' on a {dz, dy} PENCIL mesh: the z halo rides the
    three-call overlapped schedule while the y halo keeps the
    serialized per-stage refresh on each stage's composed output. Must
    match the all-serialized fused path and the unsharded fused run —
    the reference's boundary/interior stream split generalized past
    what its 1-D MPI slabs could decompose (SURVEY §2.1.5)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    if model == "diffusion" and adaptive:
        pytest.skip("diffusion has no adaptive dt")
    # local z must host a 3-block interior band for each model's block
    # picker: burgers bz<=8 -> lz=24; diffusion bz=20 -> lz=60
    grid = (
        Grid.make(24, 16, 48, lengths=2.0)
        if model == "burgers"
        else Grid.make(24, 16, 120, lengths=2.0)
    )
    mk = (
        (lambda **kw: BurgersSolver(
            BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                          adaptive_dt=adaptive, impl="pallas", **kw)))
        if model == "burgers"
        else (lambda **kw: DiffusionSolver(
            DiffusionConfig(grid=grid, dtype="float32", impl="pallas",
                            **kw)))
    )
    unsharded = mk()
    assert unsharded._fused_stepper() is not None
    ref = unsharded.run(unsharded.initial_state(), 5)

    outs = {}
    for overlap in ("split", "padded"):
        solver = mk(overlap=overlap).__class__(
            mk(overlap=overlap).cfg,
            mesh=make_mesh({"dz": 2, "dy": 2}),
            decomp=Decomposition.of({0: "dz", 1: "dy"}),
        )
        fused = solver._fused_stepper()
        assert fused is not None and fused.sharded
        assert fused.overlap_split == (overlap == "split"), (
            model, overlap, getattr(solver, "_fused_fallback", None)
        )
        st = solver.run(solver.initial_state(), 5)
        outs[overlap] = np.asarray(st.u)
        np.testing.assert_allclose(float(st.t), float(ref.t), rtol=1e-6)
    _assert_fused_close(outs["split"], outs["padded"])
    _assert_fused_close(outs["split"], ref.u)


def test_fused_burgers_split_overlap_pencil_run_to(devices):
    """advance_to through the pencil split-overlap schedule (run_to
    inside shard_map with both the exchanged-slab z path and the y
    refresh) matches the unsharded fused trajectory and step count."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 16, 48, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                        adaptive_dt=True, impl="pallas", overlap="split")
    ref_s = BurgersSolver(cfg)
    t_end = 0.04
    ref = ref_s.advance_to(ref_s.initial_state(), t_end)
    solver = BurgersSolver(
        cfg,
        mesh=make_mesh({"dz": 2, "dy": 2}),
        decomp=Decomposition.of({0: "dz", 1: "dy"}),
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.overlap_split
    out = solver.advance_to(solver.initial_state(), t_end)
    _assert_fused_close(out.u, ref.u)
    np.testing.assert_allclose(float(out.t), float(ref.t), rtol=1e-6)
    assert int(out.it) == int(ref.it) > 0


@pytest.mark.parametrize(
    "nz_global",
    [16, 44],
    ids=["thin-band", "thin-block"],
)
def test_fused_burgers_split_overlap_small_shard_falls_back(
    devices, nz_global
):
    """Shards that can't host a safe interior band silently use the
    serialized-refresh schedule instead of failing: local lz=8 gives
    n_bz=1 (< 3), and local lz=22 forces bz=2 < R — a thin block whose
    first interior-role box would reach into the never-refreshed ghost
    rows."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 16, nz_global, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                        impl="pallas", overlap="split")
    solver = BurgersSolver(
        cfg, mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz")
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded and not fused.overlap_split
    if nz_global == 44:
        assert fused.block[0] < 3, "expected a thin z-block"
    out = solver.run(solver.initial_state(), 2)  # executes on the fallback
    ref_s = BurgersSolver(
        BurgersConfig(grid=grid, nu=1e-5, dtype="float32", impl="pallas")
    )
    ref = ref_s.run(ref_s.initial_state(), 2)
    _assert_fused_close(out.u, ref.u)


@pytest.mark.parametrize("axis", [0, 1, 2], ids=["z", "y", "x"])
def test_fused_burgers_weno7_single_axis_sweeps(axis):
    """Each WENO7 sweep of the fused kernel in isolation: an IC varying
    along only one axis exercises exactly that direction's halo-4
    reconstruction (z row slices / y sublane rolls / x lane rolls with
    4-lane ghost synthesis); the other sweeps see constant data and
    contribute zero divergence. Must match the XLA WENO7 solver."""
    # Grid.make takes physical-order (nx, ny, nz); arrays are (z, y, x)
    grid = Grid.make(32, 16, 12, lengths=2.0)
    shape = grid.shape
    assert shape == (12, 16, 32)
    x = np.linspace(0.0, 2.0, shape[axis], endpoint=False)
    prof = np.exp(-18.0 * (x / 2.0 - 0.45) ** 2)
    u0 = np.broadcast_to(
        prof.reshape([-1 if d == axis else 1 for d in range(3)]), shape
    ).astype(np.float32)
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, weno_order=7, cfl=0.3,
                            adaptive_dt=False, dtype="float32", impl=impl)
        solver = BurgersSolver(cfg)
        if impl == "pallas":
            assert solver._fused_stepper() is not None, "fast path not taken"
        from multigpu_advectiondiffusion_tpu.models.state import SolverState

        st = solver.run(SolverState.create(jnp.asarray(u0)), 4)
        outs[impl] = np.asarray(st.u)
    scale = float(np.max(np.abs(outs["xla"])))
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               rtol=2e-5, atol=2e-5 * scale)


def test_fused_burgers_weno7_adaptive_dt_matches_xla():
    """Adaptive-dt WENO7 on the fused path: the stage-emitted
    max|f'(u)| and the halo-4 reconstruction together must reproduce the
    XLA trajectory and its time axis."""
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, weno_order=7, cfl=0.3,
                            adaptive_dt=True, dtype="float32",
                            ic="gaussian", impl=impl)
        solver = BurgersSolver(cfg)
        if impl == "pallas":
            assert solver._fused_stepper() is not None, "fast path not taken"
        st = solver.run(solver.initial_state(), 5)
        outs[impl] = (np.asarray(st.u), float(st.t))
    scale = float(np.max(np.abs(outs["xla"][0])))
    # wider than the fixed-dt band: the e-form/q-form rounding gap in
    # max|f'(u)| feeds back through dt, compounding across steps
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=2e-5, atol=6e-5 * scale)
    np.testing.assert_allclose(outs["pallas"][1], outs["xla"][1], rtol=1e-5)


@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused_burgers_weno7_sharded_matches_unsharded(devices, adaptive):
    """The fused WENO7 stepper under a z-slab mesh: the 4-row ppermute
    ghost refresh between stages must reproduce the single-device fused
    run to the interpret-mode ulp bound."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(24, 16, 16, lengths=2.0)
    cfg = BurgersConfig(grid=grid, weno_order=7, dtype="float32",
                        adaptive_dt=adaptive, impl="pallas")
    ref_solver = BurgersSolver(cfg)
    assert ref_solver._fused_stepper() is not None
    ref = ref_solver.run(ref_solver.initial_state(), 5)
    solver = BurgersSolver(
        cfg, mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz")
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded, "sharded fast path not taken"
    assert fused.halo == 4
    out = solver.run(solver.initial_state(), 5)
    _assert_fused_close(out.u, ref.u)
    np.testing.assert_allclose(float(out.t), float(ref.t), rtol=1e-6)


@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused_burgers_weno7_advance_to_matches_xla(adaptive):
    """run_to (t_end mode) through the fused WENO7 stepper: trajectory,
    final time, and step count must match the generic path."""
    grid = Grid.make(16, 16, 16, lengths=2.0)
    t_end = 0.05
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, weno_order=7, cfl=0.3,
                            adaptive_dt=adaptive, dtype="float32",
                            ic="gaussian", impl=impl)
        solver = BurgersSolver(cfg)
        st = solver.advance_to(solver.initial_state(), t_end)
        outs[impl] = (np.asarray(st.u), float(st.t), int(st.it))
    scale = float(np.max(np.abs(outs["xla"][0])))
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=2e-5, atol=2e-5 * scale)
    np.testing.assert_allclose(outs["pallas"][1], outs["xla"][1], rtol=1e-6)
    assert outs["pallas"][2] == outs["xla"][2]


def test_fused_burgers_ghost_maintenance_long_run():
    """Many fused steps: the persistent padded state's edge ghosts must
    track the evolving boundary cells (a stale-ghost bug shows up as
    drift against the per-step-padded XLA path — the failure mode the
    reference actually has, SURVEY §3.2)."""
    grid = Grid.make(16, 12, 20, lengths=[3.0, 2.0, 2.5])
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, cfl=0.25, adaptive_dt=False,
                            dtype="float32", ic="gaussian", impl=impl)
        solver = BurgersSolver(cfg)
        outs[impl] = np.asarray(solver.run(solver.initial_state(), 25).u)
    scale = float(np.max(np.abs(outs["xla"])))
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               rtol=5e-5, atol=5e-6 * scale)


def test_fused_diffusion2d_run_matches_xla():
    """The whole-run VMEM-resident 2-D stepper (run() with impl='pallas'
    on an eligible 2-D config) must agree with the generic XLA path to
    f32 rounding, including the accumulated t."""
    grid = Grid.make(40, 28, lengths=10.0)
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = DiffusionConfig(grid=grid, dtype="float32", impl=impl)
        solver = DiffusionSolver(cfg)
        if impl == "pallas":
            fused = solver._fused_stepper()
            assert fused is not None, "2-D fast path not taken"
            assert type(fused).__name__ == "FusedDiffusion2DStepper"
        st = solver.run(solver.initial_state(), 9)
        outs[impl] = (np.asarray(st.u), float(st.t))
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=1e-5, atol=1e-6)
    assert outs["pallas"][1] == outs["xla"][1]


def test_fused_diffusion2d_zero_iters_identity():
    grid = Grid.make(24, 16, lengths=4.0)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas"))
    st0 = solver.initial_state()
    st = solver.run(st0, 0)
    np.testing.assert_array_equal(np.asarray(st.u), np.asarray(st0.u))
    assert float(st.t) == float(st0.t)


def test_fused_diffusion2d_too_large_falls_back():
    """Grids whose padded state cannot fit the VMEM budget quietly use
    the generic path."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion2d import (
        FusedDiffusion2DStepper,
    )

    assert not FusedDiffusion2DStepper.supported((8192, 8192), jnp.float32)
    grid = Grid.make(8192, 8192, lengths=10.0)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas"))
    assert solver._fused_stepper() is None


@pytest.mark.parametrize(
    "kw",
    [{}, {"weno_variant": "z"}, {"nu": 1e-3}, {"flux": "buckley"},
     {"adaptive_dt": True}, {"adaptive_dt": True, "nu": 1e-3}],
    ids=["js", "z", "viscous", "buckley", "adaptive", "adaptive-viscous"],
)
def test_fused_burgers2d_run_matches_xla(kw):
    """The whole-run VMEM-resident 2-D Burgers stepper must agree with
    the generic XLA path to f32 rounding, including accumulated t —
    in both dt modes (adaptive recomputes the in-core max|f'(u)|
    reduction before every step, LFWENO5FDM2d.m:71)."""
    grid = Grid.make(40, 24, lengths=[4.0, 2.5])
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, cfl=0.3,
                            dtype="float32", ic="gaussian", impl=impl,
                            **{"adaptive_dt": False, **kw})
        solver = BurgersSolver(cfg)
        if impl == "pallas":
            fused = solver._fused_stepper()
            assert type(fused).__name__ == "FusedBurgers2DStepper", kw
        st = solver.run(solver.initial_state(), 8)
        outs[impl] = (np.asarray(st.u), float(st.t))
    scale = float(np.max(np.abs(outs["xla"][0])))
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=3e-5, atol=3e-6 * scale)
    if kw.get("adaptive_dt"):
        np.testing.assert_allclose(outs["pallas"][1], outs["xla"][1],
                                   rtol=1e-5)
    else:
        assert outs["pallas"][1] == outs["xla"][1]


# --------------------------------------------------------------------- #
# Sharded 2-D fused path (fused2d_sharded): the tuned 2-D kernel under a
# mesh — per-stage whole-shard kernels + ppermute ghost refresh, matching
# the reference's MPI deployment of its 2-D kernels
# (MultiGPU/Diffusion2d_Baseline/main.c:189-280, Burgers2d_Baseline/
# main.c:186+).
# --------------------------------------------------------------------- #

_DECOMPS_2D = [
    ({"dy": 4}, {0: "dy"}),  # reference-style slab (outer axis)
    ({"dx": 4}, {1: "dx"}),  # lane-axis slab
    ({"dy": 2, "dx": 2}, {0: "dy", 1: "dx"}),  # pencil
]


@pytest.mark.parametrize("mesh_axes,decomp_map", _DECOMPS_2D,
                         ids=["slab-y", "slab-x", "pencil"])
def test_fused2d_sharded_diffusion_bit_identical(devices, mesh_axes,
                                                 decomp_map):
    """The per-stage 2-D diffusion kernel shard-local under shard_map
    (global wall masks via the offsets operand, ppermute ghost refresh
    between stages) must reproduce the single-chip whole-run fused
    stepper bit-for-bit — identical per-cell op sequence over identical
    values."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 32, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    ref_solver = DiffusionSolver(cfg)
    assert type(ref_solver._fused_stepper()).__name__ == (
        "FusedDiffusion2DStepper"
    )
    ref = ref_solver.run(ref_solver.initial_state(), 8)
    solver = DiffusionSolver(
        cfg, mesh=make_mesh(mesh_axes), decomp=Decomposition.of(decomp_map)
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded, solver._fused_fallback
    assert type(fused).__name__ == "ShardedFusedDiffusion2DStepper"
    out = solver.run(solver.initial_state(), 8)
    assert float(jnp.max(jnp.abs(ref.u - out.u))) == 0.0


@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
@pytest.mark.parametrize("mesh_axes,decomp_map", _DECOMPS_2D,
                         ids=["slab-y", "slab-x", "pencil"])
def test_fused2d_sharded_burgers_matches_unsharded(devices, mesh_axes,
                                                   decomp_map, adaptive):
    """The per-stage 2-D Burgers kernel under the mesh (both dt modes;
    adaptive rides the pmax reduction between steps) must reproduce the
    single-chip whole-run fused stepper to the documented interpret-mode
    ulp bound, with identical accumulated t."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 32, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-4, dtype="float32",
                        adaptive_dt=adaptive, impl="pallas")
    ref_solver = BurgersSolver(cfg)
    assert type(ref_solver._fused_stepper()).__name__ == (
        "FusedBurgers2DStepper"
    )
    ref = ref_solver.run(ref_solver.initial_state(), 6)
    solver = BurgersSolver(
        cfg, mesh=make_mesh(mesh_axes), decomp=Decomposition.of(decomp_map)
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded, solver._fused_fallback
    assert type(fused).__name__ == "ShardedFusedBurgers2DStepper"
    out = solver.run(solver.initial_state(), 6)
    _assert_fused_close(out.u, ref.u)
    np.testing.assert_allclose(float(out.t), float(ref.t), rtol=1e-6)


@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused_burgers2d_weno7_matches_xla(adaptive):
    """The 2-D whole-run stepper at order 7 (halo 4, LFWENO7FDM2d.m)
    must agree with the generic XLA path in both dt modes — order
    parity for the 2-D fused family, matching what the 3-D family
    already serves."""
    grid = Grid.make(40, 24, lengths=[4.0, 2.5])
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, weno_order=7, cfl=0.3, nu=1e-4,
                            dtype="float32", ic="gaussian", impl=impl,
                            adaptive_dt=adaptive)
        solver = BurgersSolver(cfg)
        if impl == "pallas":
            fused = solver._fused_stepper()
            assert type(fused).__name__ == "FusedBurgers2DStepper", (
                getattr(solver, "_fused_fallback", None)
            )
            assert fused.halo == 4
        st = solver.run(solver.initial_state(), 8)
        outs[impl] = (np.asarray(st.u), float(st.t))
    scale = float(np.max(np.abs(outs["xla"][0])))
    # same band as the 3-D WENO7-vs-XLA tests: the fused e-form and the
    # XLA q-form round differently through the order-7 nonlinear
    # weights, compounding over the 8 steps (adaptive additionally
    # feeds the gap back through dt)
    np.testing.assert_allclose(
        outs["pallas"][0], outs["xla"][0], rtol=2e-5,
        atol=(6e-5 if adaptive else 3e-5) * scale,
    )
    np.testing.assert_allclose(outs["pallas"][1], outs["xla"][1], rtol=1e-5)


@pytest.mark.parametrize(
    "mesh_axes,decomp_map",
    [({"dy": 2, "dx": 2}, {0: "dy", 1: "dx"})],
    ids=["pencil"],
)
def test_fused2d_sharded_burgers_weno7(devices, mesh_axes, decomp_map):
    """Order 7 through the sharded per-stage 2-D kernels: the 4-deep
    ppermute refresh on both axes must reproduce the single-chip
    whole-run order-7 stepper (adaptive dt, pmax in the loop)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 32, lengths=2.0)
    cfg = BurgersConfig(grid=grid, weno_order=7, nu=1e-4, dtype="float32",
                        adaptive_dt=True, impl="pallas")
    ref_solver = BurgersSolver(cfg)
    assert type(ref_solver._fused_stepper()).__name__ == (
        "FusedBurgers2DStepper"
    )
    ref = ref_solver.run(ref_solver.initial_state(), 6)
    solver = BurgersSolver(
        cfg, mesh=make_mesh(mesh_axes), decomp=Decomposition.of(decomp_map)
    )
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded and fused.halo == 4, (
        getattr(solver, "_fused_fallback", None)
    )
    out = solver.run(solver.initial_state(), 6)
    _assert_fused_close(out.u, ref.u)
    np.testing.assert_allclose(float(out.t), float(ref.t), rtol=1e-6)


def test_fused2d_weno7_split_overlap(devices):
    """Order 7 through the 2-D split-overlap band schedule (halo-4 edge
    bands consuming the exchanged slabs) matches the serialized refresh
    and the unsharded whole-run stepper."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 48, lengths=2.0)  # ly local 12 >= 3*4
    ref_solver = BurgersSolver(
        BurgersConfig(grid=grid, weno_order=7, nu=1e-4, dtype="float32",
                      impl="pallas")
    )
    ref = ref_solver.run(ref_solver.initial_state(), 6)
    outs = {}
    for overlap in ("split", "padded"):
        cfg = BurgersConfig(grid=grid, weno_order=7, nu=1e-4,
                            dtype="float32", impl="pallas",
                            overlap=overlap)
        solver = BurgersSolver(
            cfg, mesh=make_mesh({"dy": 4}), decomp=Decomposition.of({0: "dy"})
        )
        fused = solver._fused_stepper()
        assert fused is not None and fused.halo == 4
        assert fused.overlap_split == (overlap == "split"), (
            overlap, getattr(solver, "_fused_fallback", None)
        )
        st = solver.run(solver.initial_state(), 6)
        outs[overlap] = np.asarray(st.u)
    _assert_fused_close(outs["split"], outs["padded"])
    _assert_fused_close(outs["split"], ref.u)


@pytest.mark.parametrize("adaptive", [False, True], ids=["fixed", "adaptive"])
def test_fused2d_sharded_burgers_advance_to(devices, adaptive):
    """Sharded 2-D t_end mode runs the fused run_to (trimmed last step
    through the runtime SMEM dt) and reproduces the generic path's
    trajectory, landing time, and step count — a capability the
    single-chip whole-run stepper doesn't have (no run_to)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 32, lengths=2.0)
    mesh_axes, decomp_map = {"dy": 4}, {0: "dy"}
    t_end = 0.05  # ~4.5 steps at this CFL: exercises the trimmed step
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = BurgersConfig(grid=grid, cfl=0.3, nu=1e-4, dtype="float32",
                            adaptive_dt=adaptive, impl=impl)
        solver = BurgersSolver(
            cfg, mesh=make_mesh(mesh_axes),
            decomp=Decomposition.of(decomp_map),
        )
        st = solver.advance_to(solver.initial_state(), t_end)
        if impl == "pallas":
            assert "fused_adv" in solver._cache, "fused t_end not engaged"
        outs[impl] = (np.asarray(st.u), float(st.t), int(st.it))
    scale = float(np.max(np.abs(outs["xla"][0])))
    np.testing.assert_allclose(outs["pallas"][0], outs["xla"][0],
                               rtol=2e-5, atol=2e-6 * scale)
    np.testing.assert_allclose(outs["pallas"][1], t_end, rtol=1e-6)
    assert outs["pallas"][2] == outs["xla"][2] > 0


def test_fused2d_sharded_diffusion_run_to_matches_run(devices):
    """Sharded 2-D diffusion run_to landing exactly on n*dt must agree
    with the fixed-count fused run of the same n."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 32, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    mesh = make_mesh({"dy": 4})
    a = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.of({0: "dy"}))
    run = a.run(a.initial_state(), 5)
    b = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.of({0: "dy"}))
    adv = b.advance_to(b.initial_state(), float(run.t))
    assert "fused_adv" in b._cache
    assert int(adv.it) == 5
    np.testing.assert_allclose(np.asarray(adv.u), np.asarray(run.u),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("model", ["burgers", "diffusion"])
def test_fused2d_split_overlap_matches_serialized(devices, model):
    """overlap='split' on a 2-D y-slab mesh runs the three-band schedule
    (interior band concurrent with the in-flight slab ppermute; only the
    two h-row edge bands consume the exchanged slabs) — matching the
    serialized-refresh path and the unsharded fused run at ulp level, in
    run() and run_to."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 40, lengths=10.0)  # ly=10/shard >= 3*halo
    mesh_kw = dict(mesh=make_mesh({"dy": 4}),
                   decomp=Decomposition.of({0: "dy"}))
    outs = {}
    for overlap in ("padded", "split"):
        if model == "burgers":
            cfg = BurgersConfig(grid=grid, nu=1e-4, dtype="float32",
                                impl="pallas", overlap=overlap)
            solver = BurgersSolver(cfg, **mesh_kw)
        else:
            cfg = DiffusionConfig(grid=grid, dtype="float32",
                                  impl="pallas", overlap=overlap)
            solver = DiffusionSolver(cfg, **mesh_kw)
        fused = solver._fused_stepper()
        assert fused is not None and fused.sharded
        assert fused.overlap_split == (overlap == "split")
        want = "split" if overlap == "split" else "serialized-refresh"
        assert solver.engaged_path()["overlap"] == want
        outs[overlap] = solver.run(solver.initial_state(), 6)
    a, b = np.asarray(outs["padded"].u), np.asarray(outs["split"].u)
    scale = float(np.abs(a).max())
    # band slicing/assembly compiles different FMA contractions than the
    # whole-shard call — same values, few-ulp freedom (as in 3-D split)
    assert float(np.abs(a - b).max()) <= 8 * np.finfo(np.float32).eps * scale
    # adaptive dt inherits the state's few-ulp freedom through the CFL
    # max, so the accumulated t may differ in the last ulp
    assert abs(float(outs["padded"].t) - float(outs["split"].t)) <= (
        8 * np.finfo(np.float32).eps * max(1.0, abs(float(outs["padded"].t)))
    )


def test_fused2d_split_overlap_run_to(devices):
    """The split schedule serves run_to (trimmed last step) with the
    generic path's step count and landing time."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 40, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas",
                          overlap="split")
    solver = DiffusionSolver(cfg, mesh=make_mesh({"dy": 4}),
                             decomp=Decomposition.of({0: "dy"}))
    assert solver._fused_stepper().overlap_split
    st0 = solver.initial_state()
    t_end = float(st0.t) + 4.4 * solver.dt
    out = solver.advance_to(st0, t_end)
    assert "fused_adv" in solver._cache
    assert int(out.it) == 5
    np.testing.assert_allclose(float(out.t), t_end, rtol=1e-6)


def test_fused2d_split_overlap_thin_band_falls_back(devices):
    """Shards without a non-degenerate interior band (ly < 3*halo) fall
    back to the serialized refresh — and still match."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 32, lengths=10.0)  # ly=8/shard < 3*3 for WENO5
    cfg = BurgersConfig(grid=grid, nu=1e-4, dtype="float32",
                        impl="pallas", overlap="split")
    solver = BurgersSolver(cfg, mesh=make_mesh({"dy": 4}),
                           decomp=Decomposition.of({0: "dy"}))
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded and not fused.overlap_split
    ref = BurgersSolver(BurgersConfig(grid=grid, nu=1e-4, dtype="float32",
                                      impl="pallas"))
    r = ref.run(ref.initial_state(), 4)
    o = solver.run(solver.initial_state(), 4)
    _assert_fused_close(o.u, r.u)


def test_fused2d_sharded_thin_shard_declines_loudly(devices):
    """A sharded axis thinner than the WENO5 halo declines the fused
    path with a specific reason — and the generic path then fails with
    a loud halo error too (no silent wrong answer at any rung)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(40, 8, lengths=2.0)  # ly = 2 < halo 3 over dy=4
    cfg = BurgersConfig(grid=grid, dtype="float32", impl="pallas")
    solver = BurgersSolver(
        cfg, mesh=make_mesh({"dy": 4}), decomp=Decomposition.of({0: "dy"})
    )
    assert solver._fused_stepper() is None
    assert "halo" in solver._fused_fallback
    with pytest.raises(ValueError, match="halo"):
        solver.run(solver.initial_state(), 2)


def test_fused_diffusion_bf16_storage_rung():
    """The bf16-storage/f32-compute rung (HBM bytes halved on the
    roof-bound ref grid): trajectories must stay within bf16 rounding of
    the f32 fused run — storage is the only thing quantized; the RK
    arithmetic runs f32."""
    grid = Grid.make(32, 24, 24, lengths=10.0)
    outs = {}
    for dtype in ("float32", "bfloat16"):
        s = DiffusionSolver(
            DiffusionConfig(grid=grid, dtype=dtype, impl="pallas")
        )
        fused = s._fused_stepper()
        assert fused is not None, (dtype, s._fused_fallback)
        # f32 may ride the slab whole-run rung; bf16 storage exists only
        # in the per-stage stepper
        want = (
            ("fused-stage",)
            if dtype == "bfloat16"
            else ("fused-stage", "fused-whole-run-slab")
        )
        assert fused.engaged_label in want
        st = s.run(s.initial_state(), 5)
        outs[dtype] = np.asarray(st.u, np.float32)
    scale = float(np.abs(outs["float32"]).max())
    diff = float(np.abs(outs["float32"] - outs["bfloat16"]).max())
    # the IC itself is bf16-quantized (~0.4% relative) and each stage
    # stores through bf16: a few percent of drift over 5 steps is the
    # storage price — but the f32 arithmetic must keep it at that level
    assert diff <= 0.05 * scale, (diff, scale)
    # ...and strictly better than computing IN bf16 (the XLA path with
    # the same dtype), which loses the stencil's cancellation digits
    s_xla = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="bfloat16", impl="xla")
    )
    xla_bf16 = np.asarray(s_xla.run(s_xla.initial_state(), 5).u, np.float32)
    diff_xla = float(np.abs(outs["float32"] - xla_bf16).max())
    assert diff <= diff_xla * 1.05, (diff, diff_xla)


def test_fused_diffusion_bf16_declines_off_design():
    """bf16 storage exists only where it pays: the 3-D per-stage
    stepper. 2-D and whole-step configs decline with a reason."""
    s2 = DiffusionSolver(DiffusionConfig(
        grid=Grid.make(24, 24, lengths=10.0), dtype="bfloat16",
        impl="pallas"))
    assert s2._fused_stepper() is None
    assert "bf16" in s2._fused_fallback
    s3 = DiffusionSolver(DiffusionConfig(
        grid=Grid.make(24, 24, 24, lengths=10.0), dtype="bfloat16",
        impl="pallas_step"))
    assert s3._fused_stepper() is None


def test_step_fused_diffusion_matches_xla():
    """The whole-step (3-stages-per-HBM-pass) ladder variant must match
    the generic path; it is not the default (measured slower than the
    per-stage pipeline on v5e — compute growth outweighs the HBM saving;
    kept as an explicit rung of the kernel-strategy ladder)."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion_step import (
        StepFusedDiffusionStepper,
    )

    grid = Grid.make(36, 28, 24, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32")
    ref = DiffusionSolver(cfg)
    st = ref.run(ref.initial_state(), 7)
    s = DiffusionSolver(cfg)
    f = StepFusedDiffusionStepper(grid.shape, s.dtype, grid.spacing,
                                  [1.0] * 3, s.dt, 2, 0.0, block_z=8)
    st0 = s.initial_state()
    u, t = f.run(st0.u, st0.t, 7)
    np.testing.assert_allclose(np.asarray(u), np.asarray(st.u),
                               rtol=1e-5, atol=1e-6)
    assert float(t) == float(st.t)


def test_fused_pencil_split_requires_refresh(devices):
    """A pencil split-overlap stepper driven directly with only `exch`
    (no serialized refresh for the non-leading sharded axes) must raise
    — silently-frozen y ghosts are the failure mode the guard exists
    for."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_burgers import (
        FusedBurgersStepper,
    )
    from multigpu_advectiondiffusion_tpu.ops import flux as flux_lib

    st = FusedBurgersStepper(
        (24, 8, 48), "float32", (0.1, 0.1, 0.1), flux_lib.burgers(),
        "js", 0.0, dt=0.01, global_shape=(48, 16, 48), y_sharded=True,
        overlap_split=True,
    )
    assert st.overlap_split
    u = jnp.zeros((24, 8, 48), jnp.float32)
    with pytest.raises(ValueError, match="non-leading"):
        st.run(u, jnp.zeros((), jnp.float32), 1,
               exch=lambda P: (P[:3], P[:3]))
