"""Verification of the halo-exchange / interior-compute overlap.

The reference hand-builds overlap with five CUDA streams — boundary RHS
on send streams while the interior RHS runs on the compute stream
(``MultiGPU/Diffusion3d_Baseline/main.c:203-260``). The rebuild's
``overlap="split"`` schedule claims the same property via dataflow: the
interior stencil must not depend on the in-flight ``ppermute`` ghosts,
so XLA's async collective scheduler can run both concurrently. Two
checks, strongest-available per environment:

1. Dataflow independence (any backend): poison the exchanged ghost
   slabs with NaN — the interior output cells must stay finite, proving
   the interior computation consumes no ghost data (the precondition
   for overlap; a dependency would serialize it).
2. TPU instruction schedule (AOT, no chips needed): compile the sharded
   split-overlap step against a multi-chip v5e topology
   (``jax.experimental.topologies``) and assert the compiled module
   issues ``collective-permute-start``, schedules compute fusions, and
   only then waits on ``collective-permute-done`` — the overlap as the
   TPU compiler actually scheduled it, the machine-checked analog of
   reading the five-stream choreography out of an nvprof trace
   (``profile.sh``).
"""

from __future__ import annotations

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu import (
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.ops.stencils import split_axis_apply
from multigpu_advectiondiffusion_tpu.parallel.mesh import Decomposition


def test_split_schedule_interior_is_ghost_independent():
    """NaN-poisoned ghosts must not reach interior output cells: the
    interior compute consumes only local data, so nothing forces it to
    wait for the exchange."""
    r = 2
    u = jnp.asarray(np.random.default_rng(0).normal(size=(16, 8, 8)),
                    jnp.float32)
    nan = jnp.full((r,) + u.shape[1:], jnp.nan, u.dtype)

    def f(lo, hi):
        return split_axis_apply(
            lambda up: up[2 * r :] - up[: -2 * r], u, 0, r, lo, hi
        )

    out = jax.jit(f)(nan, nan)
    core = np.asarray(out)[r:-r]
    edges = np.asarray(out)[:r], np.asarray(out)[-r:]
    assert np.isfinite(core).all(), "interior depends on ghost data"
    assert all(np.isnan(e).all() for e in edges), (
        "boundary bands should be exactly the ghost-dependent region"
    )


def test_split_overlap_tpu_schedule_hides_collectives():
    """AOT-compile the sharded ``overlap='split'`` diffusion step for a
    4-chip v5e topology and read the overlap out of the compiled
    module's schedule: compute fusions must sit between a
    ``collective-permute-start`` and its ``collective-permute-done``."""
    try:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc("v5e:2x2", "tpu")
    except Exception as e:  # no TPU compiler plugin in this environment
        pytest.skip(f"TPU AOT topology unavailable: {type(e).__name__}")

    from jax.sharding import Mesh

    devs = np.asarray(topo.devices[:4])
    mesh = Mesh(devs, ("dz",))
    grid = Grid.make(128, 128, 128, lengths=2.0)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", overlap="split"),
        mesh=mesh,
        decomp=Decomposition.slab("dz"),
    )
    f = solver._wrap(solver._local_step)
    u = jax.ShapeDtypeStruct(grid.shape, jnp.float32,
                             sharding=solver.sharding())
    t = jax.ShapeDtypeStruct((), jnp.float32)
    txt = f.lower(u, t).compile().as_text()

    # entry-computation schedule order == text order within the module
    events = []
    for i, line in enumerate(txt.splitlines()):
        ls = line.strip()
        if re.search(r"= .*collective-permute-start", ls):
            events.append((i, "start"))
        elif re.search(r"= .*collective-permute-done", ls):
            events.append((i, "done"))
        elif re.search(r"= .*fusion\(", ls):
            events.append((i, "fusion"))

    starts = [i for i, k in events if k == "start"]
    dones = [i for i, k in events if k == "done"]
    assert starts and dones, "expected async collective-permute pairs"

    # at least one start ... fusion ... done window must exist
    overlapped = 0
    for s in starts:
        d = min((d for d in dones if d > s), default=None)
        if d is None:
            continue
        overlapped += sum(1 for i, k in events if k == "fusion" and s < i < d)
    assert overlapped > 0, (
        "no compute scheduled inside a collective-permute window — "
        "the split overlap is not being hidden"
    )
