"""Verification of the halo-exchange / interior-compute overlap.

The reference hand-builds overlap with five CUDA streams — boundary RHS
on send streams while the interior RHS runs on the compute stream
(``MultiGPU/Diffusion3d_Baseline/main.c:203-260``). The rebuild's
``overlap="split"`` schedule claims the same property via dataflow: the
interior stencil must not depend on the in-flight ``ppermute`` ghosts,
so XLA's async collective scheduler can run both concurrently. Two
checks, strongest-available per environment:

1. Dataflow independence (any backend): poison the exchanged ghost
   slabs with NaN — the interior output cells must stay finite, proving
   the interior computation consumes no ghost data (the precondition
   for overlap; a dependency would serialize it).
2. TPU instruction schedule (AOT, no chips needed): compile the sharded
   split-overlap step against a multi-chip v5e topology
   (``jax.experimental.topologies``) and assert the compiled module
   issues ``collective-permute-start``, schedules compute fusions, and
   only then waits on ``collective-permute-done`` — the overlap as the
   TPU compiler actually scheduled it, the machine-checked analog of
   reading the five-stream choreography out of an nvprof trace
   (``profile.sh``).
"""

from __future__ import annotations

import re

import numpy as np
import pytest

import aot_utils

import jax
import jax.numpy as jnp
from jax.experimental import enable_x64

from multigpu_advectiondiffusion_tpu import (
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.ops.stencils import split_axis_apply
from multigpu_advectiondiffusion_tpu.parallel.mesh import Decomposition


def test_split_schedule_interior_is_ghost_independent():
    """NaN-poisoned ghosts must not reach interior output cells: the
    interior compute consumes only local data, so nothing forces it to
    wait for the exchange."""
    r = 2
    u = jnp.asarray(np.random.default_rng(0).normal(size=(16, 8, 8)),
                    jnp.float32)
    nan = jnp.full((r,) + u.shape[1:], jnp.nan, u.dtype)

    def f(lo, hi):
        return split_axis_apply(
            lambda up: up[2 * r :] - up[: -2 * r], u, 0, r, lo, hi
        )

    out = jax.jit(f)(nan, nan)
    core = np.asarray(out)[r:-r]
    edges = np.asarray(out)[:r], np.asarray(out)[-r:]
    assert np.isfinite(core).all(), "interior depends on ghost data"
    assert all(np.isnan(e).all() for e in edges), (
        "boundary bands should be exactly the ghost-dependent region"
    )


def _schedule_events(txt, extra=()):
    """(line, kind) events of a compiled module's entry schedule: async
    collective-permute starts/dones, compute fusions, and any extra
    (pattern, kind) pairs — text order == schedule order."""
    events = []
    pats = [
        (r"= .*collective-permute-start", "start"),
        (r"= .*collective-permute-done", "done"),
        (r"= .*fusion\(", "fusion"),
        *extra,
    ]
    for i, line in enumerate(txt.splitlines()):
        ls = line.strip()
        for pat, kind in pats:
            if re.search(pat, ls):
                events.append((i, kind))
                break
    return events


def _count_in_windows(events, kind):
    starts = [i for i, k in events if k == "start"]
    dones = [i for i, k in events if k == "done"]
    n = 0
    for s in starts:
        d = min((d for d in dones if d > s), default=None)
        if d is None:
            continue
        n += sum(1 for i, k in events if k == kind and s < i < d)
    return n, bool(starts and dones)


@pytest.mark.slow
def test_split_overlap_tpu_schedule_hides_collectives():
    """AOT-compile the sharded ``overlap='split'`` diffusion step for a
    4-chip v5e topology and read the overlap out of the compiled
    module's schedule: compute fusions must sit between a
    ``collective-permute-start`` and its ``collective-permute-done``."""
    topo = aot_utils.get_aot_topology("v5e:2x2")

    from jax.sharding import Mesh

    devs = np.asarray(topo.devices[:4])
    mesh = Mesh(devs, ("dz",))
    grid = Grid.make(128, 128, 128, lengths=2.0)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", overlap="split"),
        mesh=mesh,
        decomp=Decomposition.slab("dz"),
    )
    f = solver._wrap(solver._local_step)
    u = jax.ShapeDtypeStruct(grid.shape, jnp.float32,
                             sharding=solver.sharding())
    t = jax.ShapeDtypeStruct((), jnp.float32)
    txt = f.lower(u, t).compile().as_text()

    # entry-computation schedule order == text order within the module
    events = _schedule_events(txt)
    overlapped, have_pairs = _count_in_windows(events, "fusion")
    assert have_pairs, "expected async collective-permute pairs"
    assert overlapped > 0, (
        "no compute scheduled inside a collective-permute window — "
        "the split overlap is not being hidden"
    )


@pytest.mark.slow
@pytest.mark.parametrize("model", ["burgers", "diffusion",
                                   "burgers-pencil", "burgers-xghost"])
def test_fused_split_overlap_tpu_schedule_hides_collectives(
    monkeypatch, model
):
    """The fused split-overlap schedules, AOT-compiled for a 4-chip v5e
    topology with the real Mosaic kernels (interpret mode forced off):
    the interior stage kernel — a ``tpu_custom_call`` — must be
    scheduled between a ``collective-permute-start`` and its ``-done``,
    i.e. the tuned kernel runs while the z-halo rides the ICI, which is
    what the reference's five-stream choreography exists for
    (MultiGPU/Diffusion3d_Baseline/main.c:203-260, Kernels.cu:207-261).
    """
    topo = aot_utils.get_aot_topology("v5e:2x2")

    from jax.sharding import Mesh

    from multigpu_advectiondiffusion_tpu import BurgersConfig, BurgersSolver
    from multigpu_advectiondiffusion_tpu.ops.pallas import (
        fused_burgers as fb,
        fused_diffusion as fd,
        laplacian as lap,
    )

    # force real Mosaic lowering (the CPU-pinned test env defaults to
    # interpret mode, which would compile plain fusions instead)
    monkeypatch.setattr(fb, "interpret_mode", lambda: False)
    monkeypatch.setattr(fd, "interpret_mode", lambda: False)
    monkeypatch.setattr(lap, "interpret_mode", lambda: False)

    devs = np.asarray(topo.devices[:4])
    if model == "burgers-pencil":
        mesh = Mesh(devs.reshape(2, 2), ("dz", "dy"))
    elif model == "burgers-xghost":
        mesh = Mesh(devs.reshape(2, 2), ("dz", "dx"))
    else:
        mesh = Mesh(devs, ("dz",))
    # x64 (the suite default) poisons Mosaic verification with i64
    # constants — the kernels are f32/i32 by design
    with enable_x64(False):
        if model == "burgers":
            # local lz = 32 -> bz=8 -> n_bz=4: a real interior band
            grid = Grid.make(128, 16, 128, lengths=2.0)
            solver = BurgersSolver(
                BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                              adaptive_dt=False, impl="pallas",
                              overlap="split"),
                mesh=mesh,
                decomp=Decomposition.slab("dz"),
            )
        elif model == "burgers-pencil":
            # {dz, dy} pencil: local (64, 8, 128) — the z halo rides the
            # overlapped exchanged-slab schedule, y a serialized refresh
            grid = Grid.make(128, 16, 128, lengths=2.0)
            solver = BurgersSolver(
                BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                              adaptive_dt=False, impl="pallas",
                              overlap="split"),
                mesh=mesh,
                decomp=Decomposition.of({0: "dz", 1: "dy"}),
            )
        elif model == "burgers-xghost":
            # {dz, dx}: the stored-x-ghost layout (interior at lane
            # offset r) through REAL Mosaic lowering — the CPU interpret
            # tests can't validate this layout's Mosaic compile — with
            # the z exchange overlapped and the x refresh serialized
            grid = Grid.make(128, 16, 128, lengths=2.0)
            solver = BurgersSolver(
                BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                              adaptive_dt=False, impl="pallas",
                              overlap="split"),
                mesh=mesh,
                decomp=Decomposition.of({0: "dz", 2: "dx"}),
            )
            assert solver._fused_stepper().x_sharded
        else:
            # local lz = 60 -> bz=20 -> n_bz=3
            grid = Grid.make(128, 16, 240, lengths=2.0)
            solver = DiffusionSolver(
                DiffusionConfig(grid=grid, dtype="float32",
                                impl="pallas", overlap="split"),
                mesh=mesh,
                decomp=Decomposition.slab("dz"),
            )
        fused = solver._fused_stepper()
        assert fused is not None and fused.overlap_split
        refresh, offsets_fn, exch = solver._fused_sharded_ctx(fused)
        assert exch is not None
        # pencil/x-sharded meshes carry a serialized non-z refresh
        # alongside the overlapped z exchange; pure slabs have none
        assert (refresh is not None) == (
            model in ("burgers-pencil", "burgers-xghost")
        )

        def block(u, t):
            kw = {"exch": exch}
            if refresh is not None:
                kw["refresh"] = refresh
            if offsets_fn is not None and model == "diffusion":
                kw["offsets"] = offsets_fn()
            return fused.run(u, t, 2, **kw)

        f = solver._wrap(block)
        u = jax.ShapeDtypeStruct(grid.shape, jnp.float32,
                                 sharding=solver.sharding())
        t = jax.ShapeDtypeStruct((), jnp.float32)
        try:
            txt = f.lower(u, t).compile().as_text()
        except Exception as e:  # Mosaic AOT unavailable on this rig
            aot_utils.aot_unavailable(
                f"Mosaic AOT compile unavailable: {type(e).__name__}: {e}"
            )

    events = _schedule_events(
        txt, extra=[(r"= .*custom-call.*tpu_custom_call", "kernel")]
    )
    kernels_in, have_pairs = _count_in_windows(events, "kernel")
    fusions_in, _ = _count_in_windows(events, "fusion")
    assert have_pairs, "expected async collective-permute pairs"
    assert kernels_in + fusions_in > 0, (
        "no stage kernel or fusion scheduled inside a collective-permute "
        "window — the fused split overlap is not being hidden"
    )
    # the serialized path has zero kernels in windows by construction;
    # demand the actual Mosaic stage kernel in at least one window
    assert kernels_in > 0, (
        "fusions but no tpu_custom_call inside the permute windows — "
        "the interior stage kernel is still serialized with the exchange"
    )


@pytest.mark.slow
@pytest.mark.parametrize("overlap", ["padded", "split"])
@pytest.mark.parametrize("model", ["burgers", "diffusion",
                                   "burgers-weno7"])
def test_fused2d_sharded_mosaic_aot_compiles(monkeypatch, model, overlap):
    """The sharded 2-D per-stage steppers (whole-shard VMEM kernels +
    ppermute ghost refresh, or the three-band split-overlap schedule)
    must compile through the real Mosaic pipeline for a 4-chip v5e
    topology — the interpret-mode suite can't catch Mosaic-only lowering
    rejections (alignment, memory-space, aliasing constraints). For
    overlap='split' the compiled schedule must place a stage kernel
    inside a collective-permute window — the ghost-independent interior
    band actually hides the exchange."""
    topo = aot_utils.get_aot_topology("v5e:2x2")

    from jax.sharding import Mesh

    from multigpu_advectiondiffusion_tpu import BurgersConfig, BurgersSolver
    from multigpu_advectiondiffusion_tpu.ops.pallas import (
        fused2d_sharded as f2s,
        fused_burgers as fb,
        fused_diffusion as fd,
        laplacian as lap,
    )

    for mod in (f2s, fb, fd, lap):
        monkeypatch.setattr(mod, "interpret_mode", lambda: False)

    devs = np.asarray(topo.devices[:4])
    mesh = Mesh(devs, ("dy",))
    with enable_x64(False):
        grid = Grid.make(256, 256, lengths=2.0)
        if model == "burgers":
            solver = BurgersSolver(
                BurgersConfig(grid=grid, nu=1e-4, dtype="float32",
                              impl="pallas", overlap=overlap),
                mesh=mesh,
                decomp=Decomposition.of({0: "dy"}),
            )
        elif model == "burgers-weno7":
            # order 7 (halo-4 bands) through real Mosaic lowering
            solver = BurgersSolver(
                BurgersConfig(grid=grid, weno_order=7, nu=1e-4,
                              dtype="float32", impl="pallas",
                              overlap=overlap),
                mesh=mesh,
                decomp=Decomposition.of({0: "dy"}),
            )
        else:
            solver = DiffusionSolver(
                DiffusionConfig(grid=grid, dtype="float32", impl="pallas",
                                overlap=overlap),
                mesh=mesh,
                decomp=Decomposition.of({0: "dy"}),
            )
        fused = solver._fused_stepper()
        assert fused is not None and fused.sharded
        assert fused.overlap_split == (overlap == "split")
        if model == "burgers-weno7":
            assert fused.halo == 4
        refresh, offsets_fn, exch = solver._fused_sharded_ctx(fused)

        def block(u, t):
            return fused.run(u, t, 2, refresh=refresh,
                             offsets=offsets_fn(), exch=exch)

        f = solver._wrap(block)
        u = jax.ShapeDtypeStruct(grid.shape, jnp.float32,
                                 sharding=solver.sharding())
        t = jax.ShapeDtypeStruct((), jnp.float32)
        try:
            txt = f.lower(u, t).compile().as_text()
        except Exception as e:  # Mosaic AOT unavailable on this rig
            aot_utils.aot_unavailable(
                f"Mosaic AOT compile unavailable: {type(e).__name__}: {e}"
            )

    assert "tpu_custom_call" in txt, "stage kernels did not lower via Mosaic"
    assert "collective-permute" in txt, "ghost refresh lost its ppermute"
    if overlap == "split":
        events = _schedule_events(
            txt, extra=[(r"= .*custom-call.*tpu_custom_call", "kernel")]
        )
        kernels_in, have_pairs = _count_in_windows(events, "kernel")
        assert have_pairs, "expected async collective-permute pairs"
        assert kernels_in > 0, (
            "no stage kernel scheduled inside a collective-permute "
            "window — the 2-D split overlap is not being hidden"
        )


@pytest.mark.slow
@pytest.mark.parametrize("model", ["diffusion", "burgers"])
def test_fused_slab_run_mosaic_aot_compiles(monkeypatch, model):
    """The slab-pipelined whole-run stepper (single Pallas program over
    a (timestep, z-slab) grid with the stacked ping-pong state) must
    compile through the real Mosaic pipeline for a v5e target — the
    interpret-mode suite can't catch Mosaic-only rejections of the
    dynamically-indexed stacked-buffer DMAs."""
    topo = aot_utils.get_aot_topology("v5e:2x2")

    from multigpu_advectiondiffusion_tpu import BurgersConfig, BurgersSolver
    from multigpu_advectiondiffusion_tpu.ops.pallas import (
        fused_burgers as fb,
        fused_diffusion as fd,
        fused_slab_run as fsr,
        laplacian as lap,
    )

    for mod in (fsr, fb, fd, lap):
        monkeypatch.setattr(mod, "interpret_mode", lambda: False)

    with enable_x64(False):
        if model == "diffusion":
            grid = Grid.make(128, 128, 64, lengths=2.0)
            solver = DiffusionSolver(
                DiffusionConfig(grid=grid, dtype="float32",
                                impl="pallas_slab")
            )
        else:
            grid = Grid.make(128, 64, 64, lengths=2.0)
            solver = BurgersSolver(
                BurgersConfig(grid=grid, nu=1e-5, dtype="float32",
                              adaptive_dt=False, impl="pallas_slab")
            )
        fused = solver._fused_stepper()
        assert fused is not None, getattr(solver, "_fused_fallback", None)
        assert fused.engaged_label == "fused-whole-run-slab"
        assert fused.n_slabs >= 2, "want a multi-slab pipeline"

        def block(u, t):
            return fused.run(u, t, 3)

        # unsharded: pin the AOT lowering to one device of the TPU
        # topology via the operands' sharding
        sharding = jax.sharding.SingleDeviceSharding(topo.devices[0])
        u = jax.ShapeDtypeStruct(grid.shape, jnp.float32, sharding=sharding)
        t = jax.ShapeDtypeStruct((), jnp.float32, sharding=sharding)
        try:
            txt = jax.jit(block).lower(u, t).compile().as_text()
        except Exception as e:  # Mosaic AOT unavailable on this rig
            aot_utils.aot_unavailable(
                f"Mosaic AOT compile unavailable: {type(e).__name__}: {e}"
            )

    assert "tpu_custom_call" in txt, "slab kernel did not lower via Mosaic"
