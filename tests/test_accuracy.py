"""Analytic-solution accuracy tests — the port of the reference's real
test suite (SURVEY §4: ``Matlab_Prototipes/DiffusionNd/TestingAccuracy.m``,
``diffusion{1,2,3}dTest.m``), plus IC/exact-solution consistency checks.
"""


import jax.numpy as jnp
import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.utils.metrics import observed_order


# --------------------------------------------------------------------- #
# IC <-> exact-solution consistency (must hold for ANY config params)
# --------------------------------------------------------------------- #
def test_ic_matches_exact_at_t0_nondefault_params():
    grid = Grid.make(33, 33, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, diffusivity=0.27, t0=1.0, dtype="float64")
    solver = DiffusionSolver(cfg)
    state = solver.initial_state()
    norms = solver.error_norms(state, t=cfg.t0)
    assert norms.linf < 1e-12


def _axisym_config(n, diffusivity=0.27):
    """The reference's setup (heat2d_axisymmetric.m:20-43): r spans the full
    diameter through the axis, Dirichlet-0 at the far-field r faces,
    zero-gradient on y; IC/exact pair exp(-r^2/(4 D t)) scaled by t0/t."""
    grid = Grid.make(n, n, bounds=[(-5.0, 5.0), (-5.0, 5.0)])
    return DiffusionConfig(
        grid=grid,
        geometry="axisymmetric",
        diffusivity=diffusivity,
        t0=1.0,
        bc=("edge", "dirichlet"),  # (y, r) array order
        dtype="float64",
    )


def test_axisymmetric_ic_matches_exact_at_t0():
    cfg = _axisym_config(33)
    solver = DiffusionSolver(cfg)
    norms = solver.error_norms(solver.initial_state(), t=cfg.t0)
    assert norms.linf < 1e-12


# --------------------------------------------------------------------- #
# Grid-refinement convergence (TestingAccuracy.m:30-47)
# --------------------------------------------------------------------- #
def _diffusion_error(n, ndim, t_end=0.2):
    sizes = (n,) * ndim
    grid = Grid.make(*sizes, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float64")
    solver = DiffusionSolver(cfg)
    out = solver.advance_to(solver.initial_state(), t_end)
    return solver.error_norms(out, t=t_end).l1


@pytest.mark.parametrize("ndim", [1, 2, 3])
def test_diffusion_convergence_order(ndim):
    """Observed order of accuracy under 2x refinement. The scheme is
    formally 4th-order in space / 3rd-order in time; with the
    reference-parity boundary-band clamp the MATLAB study observes
    ~3.8-3.9 (TestingAccuracy.log). Require >= 2.5 as the gate."""
    ns = {1: (33, 65, 129), 2: (17, 33, 65), 3: (9, 17, 33)}[ndim]
    errs = [_diffusion_error(n, ndim) for n in ns]
    orders = [observed_order(errs[i], errs[i + 1]) for i in range(len(errs) - 1)]
    assert errs[0] > errs[-1], f"no error reduction: {errs}"
    assert max(orders) > 2.5, f"orders {orders} from errors {errs}"


def test_axisymmetric_convergence():
    errs = []
    for n in (33, 65):
        solver = DiffusionSolver(_axisym_config(n))
        out = solver.advance_to(solver.initial_state(), 1.5)
        errs.append(solver.error_norms(out, t=1.5).l1)
    assert errs[1] < errs[0] / 4, f"axisymmetric not converging: {errs}"


# --------------------------------------------------------------------- #
# Measured Gaussian decay rate vs the analytic (t0/t)^{d/2} amplitude
# (ISSUE 8: the in-situ diagnostics' decay fit as an accuracy gate —
# the machine-checked version of Run.m eyeballing the decaying plots)
# --------------------------------------------------------------------- #
def _decay_fit(solver, iters):
    from multigpu_advectiondiffusion_tpu.diagnostics import physics
    from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
        supervise_run,
    )

    _, report = supervise_run(
        solver, solver.initial_state(), iters=iters,
        sentinel_every=5, diag_every=1,
    )
    traj = report.diagnostics["trajectory"]
    assert report.diagnostics["violations"] == [], (
        report.diagnostics["violations"]
    )
    return physics.gaussian_decay_fit(
        [p["time"] for p in traj], [p["max"] for p in traj],
        analytic_rate=-solver.grid.ndim / 2.0,
    )


def test_gaussian_decay_rate_generic():
    """Fused-diagnostic amplitude trajectory on the generic XLA rung:
    the fitted log-log slope must match the analytic -d/2 (f64, a
    resolved Gaussian: the fit is tight)."""
    grid = Grid.make(33, 33, 33, lengths=10.0)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float64", t0=0.5)
    )
    assert solver.engaged_path()["stepper"] == "generic-xla"
    fit = _decay_fit(solver, 40)
    assert fit is not None and fit["points"] >= 6
    assert fit["rel_err"] < 1e-2, fit


def test_gaussian_decay_rate_fused_slab():
    """The same gate on the VMEM whole-run slab rung (f32, coarser
    grid): a slab-pipeline defect that perturbed amplitudes would move
    the measured rate off -3/2."""
    grid = Grid.make(24, 16, 16, lengths=10.0)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", t0=1.0,
                        impl="pallas_slab")
    )
    assert solver.engaged_path()["stepper"] == "fused-whole-run-slab"
    fit = _decay_fit(solver, 30)
    assert fit is not None and fit["points"] >= 5
    assert fit["rel_err"] < 0.06, fit


# --------------------------------------------------------------------- #
# WENO linear-advection exactness checks
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("order", [5, 7])
def test_weno_advects_periodic_gaussian(order):
    """Linear flux, periodic BC: after one period the profile returns.
    WENO5/7 on a smooth profile should give small L_inf error."""
    n = 128
    grid = Grid.make_periodic(n, lengths=1.0)
    # period: domain length 1, speed -1 -> t=1 is one full revolution
    cfg = BurgersConfig(
        grid=grid,
        flux="linear",
        weno_order=order,
        bc="periodic",
        cfl=0.4,
        ic="gaussian_advection",
        dtype="float64",
    )
    solver = BurgersSolver(cfg)
    state = solver.initial_state()
    u0 = np.asarray(state.u)
    out = solver.advance_to(state, 1.0)
    err = float(jnp.max(jnp.abs(out.u - state.u)))
    assert err < 2e-3, f"WENO{order} advection error {err}"
    # and the solution actually moved during the run (t advanced)
    assert abs(float(out.t) - 1.0) < 1e-9


def test_weno5_z_sharper_than_js_on_discontinuity():
    """WENO5-Z is designed to lose less resolution at discontinuities
    (SingleGPU _SharedMem variant's motivation). Sanity-check the two
    variants differ and both remain bounded on a square jump."""
    n = 129
    grid = Grid.make_periodic(n, lengths=1.0)
    outs = {}
    for variant in ("js", "z"):
        cfg = BurgersConfig(
            grid=grid, flux="linear", weno_variant=variant, bc="periodic",
            ic="square_jump_1d", dtype="float64",
        )
        solver = BurgersSolver(cfg)
        outs[variant] = np.asarray(solver.advance_to(solver.initial_state(), 0.2).u)
    assert not np.array_equal(outs["js"], outs["z"])
    for v, u in outs.items():
        assert np.isfinite(u).all()
        assert u.max() < 2.3 and u.min() > 0.7, f"{v} lost boundedness"


def test_burgers_shock_total_variation_bounded():
    """SSP-RK3 + WENO on Burgers with a smooth IC steepening to a shock:
    total variation must not blow up (TVB sanity, LFWENO5FDM1d.m setup)."""
    grid = Grid.make_periodic(201, lengths=2.0, origin=-1.0)
    cfg = BurgersConfig(grid=grid, flux="burgers", ic="sine", bc="periodic",
                        dtype="float64")
    solver = BurgersSolver(cfg)
    state = solver.initial_state()
    tv0 = float(jnp.sum(jnp.abs(jnp.diff(state.u))))
    out = solver.advance_to(state, 0.5)  # shock forms at t = 1/pi
    tv1 = float(jnp.sum(jnp.abs(jnp.diff(out.u))))
    assert tv1 < tv0 * 1.05, f"total variation grew: {tv0} -> {tv1}"


@pytest.mark.parametrize("order,expect", [(5, 5.0), (7, 5.0)])
def test_weno_residual_observed_order(order, expect):
    """Semi-discrete residual convergence on smooth periodic advection.

    WENO5-JS reaches its design order 5. WENO7-JS is limited to ~5 by
    the classical JS weights (w - d = O(dx^2), below the O(dx^3) needed
    for 7th order with fixed epsilon — Henrick et al. 2005); the MATLAB
    reference's WENO7 has the same property, so ~5 is the parity
    expectation, with the 7th-order linear part verified separately."""
    from multigpu_advectiondiffusion_tpu.core.bc import Boundary
    from multigpu_advectiondiffusion_tpu.ops import flux as flux_lib
    from multigpu_advectiondiffusion_tpu.ops.weno import flux_divergence

    fx = flux_lib.get("linear")
    bc = Boundary("periodic")
    errs = []
    for n in (64, 128, 256):
        x = (np.arange(n) + 0.5) / n
        u = jnp.asarray(np.sin(2 * np.pi * x), jnp.float64)
        div = np.asarray(flux_divergence(u, 0, 1.0 / n, fx, order=order,
                                         bc=bc))
        exact = np.asarray(fx.df(0.0)) * 2 * np.pi * np.cos(2 * np.pi * x)
        errs.append(np.max(np.abs(div - exact)))
    observed = np.log2(errs[0] / errs[1]), np.log2(errs[1] / errs[2])
    assert min(observed) > expect - 0.35, (order, errs, observed)


def test_weno7_linear_part_is_seventh_order():
    """With the optimal linear weights forced, the WENO7 combination must
    be the standard 7th-order upwind flux [-3,25,-101,319,214,-38,4]/420
    (WENO7resAdv_X.m candidate/weight tables)."""
    import multigpu_advectiondiffusion_tpu.ops.weno as W

    orig = W._weno7_weights
    W._weno7_weights = lambda betas, d: list(d)
    try:
        coeffs = []
        for j in range(7):
            q = [jnp.asarray(np.array([1.0 if k == j else 0.0]))
                 for k in range(7)]
            coeffs.append(float(np.asarray(W._weno7_minus(q))[0]))
    finally:
        W._weno7_weights = orig
    np.testing.assert_allclose(
        np.array(coeffs) * 420.0,
        [-3.0, 25.0, -101.0, 319.0, 214.0, -38.0, 4.0],
        rtol=1e-12, atol=1e-9,
    )


def test_weno7_difference_form_matches_q_form():
    """The fused kernels' forward-difference WENO7 reconstruction
    (``_weno7_side_nd_e`` — betas as _B7 quadratic forms in the window's
    first differences, division-free weights, deviation-from-center
    candidates) must equal the q-form oracle ``_weno7_minus``/``_plus``
    on arbitrary data. f64 pins the algebraic identity to round-off."""
    import multigpu_advectiondiffusion_tpu.ops.weno as W

    rng = np.random.default_rng(7)
    q = [jnp.asarray(rng.standard_normal(257), jnp.float64) * s
         for s in (1.0, 3.0, 0.1, 1.0, 2.0, 0.5, 1.0)]
    e = [q[j + 1] - q[j] for j in range(6)]
    for side, oracle in (("minus", W._weno7_minus), ("plus", W._weno7_plus)):
        num, den = W._weno7_side_nd_e(*e, side)
        got = np.asarray(q[3] + num / den)
        ref = np.asarray(oracle(q))
        np.testing.assert_allclose(got, ref, rtol=1e-11, atol=1e-13)


# --------------------------------------------------------------------- #
# Discrete conservation (the property the flux-difference form exists
# to guarantee: interface fluxes telescope, so sum(u) is invariant
# under periodic BCs — LFWENO5FDM3d.m's `res` is a flux difference)
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("order,variant",
                         [(5, "js"), (5, "z"), (7, "js")],
                         ids=["weno5-js", "weno5-z", "weno7"])
@pytest.mark.parametrize("ndim", [1, 2, 3])
def test_weno_discrete_conservation_periodic(ndim, order, variant):
    """sum(u) must be invariant to round-off over periodic steps for
    every order/variant: the divergence is a difference of interface
    fluxes, so the volume integral telescopes exactly. Catches any
    off-by-one between the two interface evaluations of a cell, wrong
    ghost wiring, and non-conservative RK assembly in one gate."""
    shape = {1: (64,), 2: (32, 24), 3: (24, 16, 12)}[ndim]
    grid = Grid.make_periodic(*reversed(shape), lengths=2.0)
    cfg = BurgersConfig(grid=grid, weno_order=order, weno_variant=variant,
                        bc="periodic", cfl=0.3, dtype="float64",
                        ic="sine" if ndim == 1 else "gaussian")
    solver = BurgersSolver(cfg)
    st0 = solver.initial_state()
    s0 = float(jnp.sum(st0.u))
    out = solver.run(st0, 8)
    s1 = float(jnp.sum(out.u))
    # telescoping is exact; the only residue is f64 summation round-off
    scale = float(jnp.sum(jnp.abs(st0.u))) + 1.0
    assert abs(s1 - s0) <= 1e-11 * scale, (s0, s1)


def test_weno_discrete_conservation_sharded(devices):
    """The same telescoping through the periodic ppermute exchange on a
    pencil mesh: the halo wiring must not create or destroy mass."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make_periodic(24, 16, 16, lengths=2.0)
    cfg = BurgersConfig(grid=grid, bc="periodic", cfl=0.3,
                        dtype="float64", ic="gaussian")
    solver = BurgersSolver(
        cfg, mesh=make_mesh({"dz": 2, "dy": 2}),
        decomp=Decomposition.of({0: "dz", 1: "dy"}),
    )
    st0 = solver.initial_state()
    s0 = float(jnp.sum(st0.u))
    out = solver.run(st0, 8)
    s1 = float(jnp.sum(out.u))
    scale = float(jnp.sum(jnp.abs(st0.u))) + 1.0
    assert abs(s1 - s0) <= 1e-11 * scale, (s0, s1)
