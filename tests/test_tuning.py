"""Measured autotuned dispatch (``impl="auto"``) + decision cache.

Pins the ISSUE 4 acceptance contract: cache-backed, reproducible
(persisted JSON, atomic writes), every decision visible as ``tune:*``
telemetry — and the CI satellite: same key -> same cached decision, a
cache hit skips re-measurement entirely.
"""

from __future__ import annotations

import dataclasses
import json
import os

import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
    telemetry,
    tuning,
)
from multigpu_advectiondiffusion_tpu.parallel.mesh import (
    Decomposition,
    make_mesh,
)
from multigpu_advectiondiffusion_tpu.tuning.cache import (
    CACHE_SCHEMA,
    TuningCache,
)


@pytest.fixture(autouse=True)
def _scoped_tuner_config(tmp_path):
    """Every test gets its own cache file and fast measurement knobs;
    the process-wide tuner state is restored afterwards."""
    saved = dict(tuning._state)
    tuning.configure(
        cache_path=str(tmp_path / "tuning.json"),
        enabled=True,
        measure_iters=2,
        measure_reps=1,
    )
    yield
    tuning._state.clear()
    tuning._state.update(saved)


def _sharded_burgers_cfg():
    # lz = 20: the candidate space is {stage, slab} x k ∈ {1, 2} — k=4
    # needs a 36-row shard and must be gated OUT (asserted below); the
    # 8x8 plane keeps interpret-mode measurement cheap in tier-1
    return BurgersConfig(
        grid=Grid.make(8, 8, 40, lengths=2.0), nu=1e-5,
        adaptive_dt=False, dtype="float32", impl="auto",
    )


def _mesh2(devices):
    return make_mesh({"dz": 2}, devices=devices[:2])


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_auto_measures_caches_and_replays(tmp_path, devices):
    """Miss -> candidates measured -> decision persisted atomically;
    second construction: cache hit, identical decision, zero new
    measurements (the determinism satellite)."""
    cfg = _sharded_burgers_cfg()
    mpath = str(tmp_path / "ev.jsonl")
    with telemetry.capture(mpath):
        s1 = BurgersSolver(cfg, mesh=_mesh2(devices),
                           decomp=Decomposition.slab("dz"))
        s2 = BurgersSolver(cfg, mesh=_mesh2(devices),
                           decomp=Decomposition.slab("dz"))
    assert s1._tuned["source"] == "measured"
    assert s2._tuned["source"] == "cache"
    assert s2._tuned["impl"] == s1._tuned["impl"]
    assert (
        s2._tuned["steps_per_exchange"] == s1._tuned["steps_per_exchange"]
    )
    # resolved configs are concrete — "auto" never reaches dispatch
    assert s1.cfg.impl != "auto" and s2.cfg.impl == s1.cfg.impl
    evs = _events(mpath)
    tune = [e for e in evs if e["kind"] == "tune"]
    lookups = [e for e in tune if e["name"] == "lookup"]
    assert [e["hit"] for e in lookups] == [False, True]
    measures = [e for e in tune if e["name"] == "measure"]
    assert measures, "miss must measure"
    # the measure events all precede the second lookup: a hit re-measures
    # nothing
    second_lookup_t = lookups[1]["t"]
    assert all(e["t"] < second_lookup_t for e in measures)
    decisions = [e for e in tune if e["name"] == "decision"]
    assert len(decisions) == 1
    assert decisions[0]["impl"] == s1._tuned["impl"]
    # k-candidates: local z=20 serves k=2 (18 rows) but NOT k=4 (36) —
    # the shard-thickness gate prunes the space before any device time
    cand_ev = [e for e in tune if e["name"] == "candidates"]
    ks = {c["steps_per_exchange"] for c in cand_ev[0]["considered"]}
    assert {1, 2} <= ks and 4 not in ks, ks
    # the engaged path carries the provenance bench rows publish
    eng = s1.engaged_path()
    assert eng["tuned"]["source"] == "measured"
    assert eng["steps_per_exchange"] == s1._tuned["steps_per_exchange"]


def _small_burgers_cfg():
    # lz = 16 < 2*G: only the {stage, slab} x k=1 space — cheap to
    # measure, enough to exercise the cache machinery
    return BurgersConfig(
        grid=Grid.make(8, 8, 32, lengths=2.0), nu=1e-5,
        adaptive_dt=False, dtype="float32", impl="auto",
    )


@pytest.fixture()
def _canned_measurement(monkeypatch):
    """Cache-mechanics tests don't need real device time: stub the
    measurement with deterministic canned rates (slab wins)."""
    from multigpu_advectiondiffusion_tpu.tuning import autotuner

    def fake(solver_cls, cfg, mesh, decomp, cand, iters, reps):
        rate = 100.0 if cand["impl"] == "pallas_slab" else 50.0
        return {"mlups": rate + cand["steps_per_exchange"],
                "seconds": 0.01, "spread": 0.0,
                "engaged": "stubbed"}

    monkeypatch.setattr(autotuner, "measure_candidate", fake)


def test_cache_file_is_atomic_and_schemad(tmp_path, devices,
                                          _canned_measurement):
    cfg = _small_burgers_cfg()
    BurgersSolver(cfg, mesh=_mesh2(devices),
                  decomp=Decomposition.slab("dz"))
    path = tuning.cache_path()
    data = json.load(open(path))
    assert data["schema"] == CACHE_SCHEMA
    (entry,) = data["entries"].values()
    assert entry["impl"] in ("pallas_slab", "pallas_stage")
    assert entry["source"] in ("measured", "static")
    assert entry["candidates"], "provenance must list the candidate space"
    # no tempfile leftovers from the atomic replace
    d = os.path.dirname(path)
    assert not [n for n in os.listdir(d) if n.endswith(".tmp")]


def test_corrupt_cache_is_a_miss_not_a_crash(tmp_path, devices,
                                             _canned_measurement):
    path = tuning.cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write('{"schema": 1, "entries": {tru')  # truncated write
    cfg = _small_burgers_cfg()
    s = BurgersSolver(cfg, mesh=_mesh2(devices),
                      decomp=Decomposition.slab("dz"))
    assert s._tuned["source"] == "measured"  # re-tuned, file rewritten
    assert json.load(open(path))["entries"]


def test_auto_without_tuning_falls_back_to_heuristic(tmp_path, devices):
    tuning.configure(enabled=False)
    cfg = _sharded_burgers_cfg()
    mpath = str(tmp_path / "ev.jsonl")
    with telemetry.capture(mpath):
        s = BurgersSolver(cfg, mesh=_mesh2(devices),
                          decomp=Decomposition.slab("dz"))
    assert s._tuned["source"] == "untuned-heuristic"
    assert s.cfg.impl == "pallas"
    assert s.cfg.steps_per_exchange == 1
    fallbacks = [
        e for e in _events(mpath)
        if e["kind"] == "tune" and e["name"] == "fallback"
    ]
    assert fallbacks and "tune" in fallbacks[0]["reason"]
    # nothing persisted: a heuristic is not a decision
    assert not os.path.exists(tuning.cache_path())


def test_key_separates_configs(devices):
    """Different (shape / mesh / dtype / physics) never share a cache
    entry; the same config always regenerates the same key string."""
    cfg = _sharded_burgers_cfg()
    mesh = _mesh2(devices)
    dec = Decomposition.slab("dz")
    k1 = tuning.make_key(BurgersSolver, cfg, mesh, dec, "cpu")
    assert k1 == tuning.make_key(BurgersSolver, cfg, mesh, dec, "cpu")
    other_shape = dataclasses.replace(
        cfg, grid=Grid.make(8, 8, 144, lengths=2.0)
    )
    assert tuning.make_key(BurgersSolver, other_shape, mesh, dec,
                           "cpu") != k1
    assert tuning.make_key(BurgersSolver, cfg, mesh, dec, "tpu") != k1
    mesh4 = make_mesh({"dz": 4}, devices=devices[:4])
    assert tuning.make_key(BurgersSolver, cfg, mesh4, dec, "cpu") != k1
    assert tuning.make_key(
        BurgersSolver, dataclasses.replace(cfg, weno_order=7), mesh,
        dec, "cpu",
    ) != k1


def test_key_separates_ensemble_dimension(devices):
    """ISSUE 9 satellite: the batched-engine member count is a key
    dimension — a B=64 decision can never be served to a B=1 run."""
    cfg = _sharded_burgers_cfg()
    mesh = _mesh2(devices)
    dec = Decomposition.slab("dz")
    k1 = tuning.make_key(BurgersSolver, cfg, mesh, dec, "cpu")
    assert k1 == tuning.make_key(BurgersSolver, cfg, mesh, dec, "cpu",
                                 ensemble=1)
    k64 = tuning.make_key(BurgersSolver, cfg, mesh, dec, "cpu",
                          ensemble=64)
    assert k64 != k1 and "ens=64" in k64 and "ens=1" in k1
    # a decision persisted under the B=64 key is invisible to a B=1
    # resolve: the lookup misses and (tuning disabled) falls back
    import jax

    backend = jax.default_backend()
    cache = TuningCache(tuning.cache_path())
    cache.put(
        tuning.make_key(BurgersSolver, cfg, None, None, backend,
                        ensemble=64),
        {"impl": "pallas_stage", "steps_per_exchange": 1,
         "source": "measured", "ensemble": 64},
    )
    tuning.configure(enabled=False)
    d1 = tuning.resolve(BurgersSolver, cfg, None, None, ensemble=1)
    assert d1["source"] == "untuned-heuristic"
    d64 = tuning.resolve(BurgersSolver, cfg, None, None, ensemble=64)
    assert d64["source"] == "cache" and d64["impl"] == "pallas_stage"


def test_candidate_space_scales_with_shard_depth(devices):
    """candidates() (no measurement — cheap) enumerates every k the
    shard can serve and nothing more: lz=36 admits {1,2,4}, lz=20 only
    {1,2}, adaptive dt collapses to the per-stage candidate."""
    dec = Decomposition.slab("dz")
    deep = dataclasses.replace(
        _sharded_burgers_cfg(), grid=Grid.make(8, 8, 72, lengths=2.0)
    )
    cands = tuning.candidates(BurgersSolver, deep, _mesh2(devices), dec)
    ks = {c["steps_per_exchange"] for c in cands
          if c["impl"] == "pallas_slab"}
    assert ks == {1, 2, 4}, cands
    shallow = _sharded_burgers_cfg()
    cands = tuning.candidates(BurgersSolver, shallow, _mesh2(devices),
                              dec)
    ks = {c["steps_per_exchange"] for c in cands
          if c["impl"] == "pallas_slab"}
    assert ks == {1, 2}, cands
    adaptive = dataclasses.replace(shallow, adaptive_dt=True)
    cands = tuning.candidates(BurgersSolver, adaptive, _mesh2(devices),
                              dec)
    assert cands == [{"impl": "pallas_stage", "steps_per_exchange": 1,
                      "exchange": "collective"}]


def test_dma_rung_is_a_measured_candidate(devices):
    """ISSUE 13 acceptance: the in-kernel remote-DMA rung enters the
    tuner's candidate space (per servable cadence, asked from the
    dispatch's own gates), is NEVER cost-model-pruned (no credible
    static model for in-kernel overlap — it engages only by winning
    measurements), and a persisted decision records ``exchange``."""
    dec = Decomposition.slab("dz")
    cfg = DiffusionConfig(
        grid=Grid.make(8, 8, 72, lengths=2.0), dtype="float32",
        impl="auto",
    )
    cands = tuning.candidates(DiffusionSolver, cfg, _mesh2(devices), dec)
    dma = [c for c in cands if c.get("exchange") == "dma"]
    assert dma, cands
    assert all(c["impl"] == "pallas_slab" for c in dma)
    # collective candidates keep their modeled pruning metric; the dma
    # rung has no static opinion and must always be measured
    assert tuning.modeled_step_seconds(
        cfg, (36, 8, 8), dma[0], 2, "cpu"
    ) is None
    s = DiffusionSolver(cfg, mesh=_mesh2(devices), decomp=dec)
    d = s._tuned
    assert d["source"] == "measured"
    assert "exchange" in d
    measured = {
        (c.get("impl"), c.get("steps_per_exchange"), c.get("exchange"))
        for c in d["candidates"] if c.get("mlups") is not None
    }
    assert any(ex == "dma" for _, _, ex in measured), measured


def test_auto_on_unsharded_3d_measures_slab_vs_stage():
    """Single chip: the tuner measures the PR 1 'deliberately
    conservative' choice instead of hand-modeling it — pallas_slab vs
    pallas_stage on the 3-D fixed-dt config."""
    cfg = BurgersConfig(
        grid=Grid.make(8, 8, 24, lengths=2.0), nu=1e-5,
        adaptive_dt=False, dtype="float32", impl="auto",
    )
    s = BurgersSolver(cfg)
    d = s._tuned
    assert d["source"] in ("measured", "static")
    impls = {c["impl"] for c in d.get("candidates", [])}
    assert {"pallas_stage", "pallas_slab"} <= impls
    # no k>1 off-mesh
    assert d["steps_per_exchange"] == 1


def test_auto_ineligible_config_resolves_statically(devices):
    """A config with no (rung x k) space — adaptive dt kills the slab
    rung — resolves without wasting measurement time on a single
    candidate, and still dispatches."""
    cfg = BurgersConfig(
        grid=Grid.make(8, 8, 48, lengths=2.0), nu=1e-5,
        adaptive_dt=True, dtype="float32", impl="auto",
    )
    s = BurgersSolver(cfg, mesh=_mesh2(devices),
                      decomp=Decomposition.slab("dz"))
    assert s._tuned["source"] == "static"
    assert s.engaged_path()["stepper"] == "fused-stage"
    out = s.run(s.initial_state(), 2)
    assert int(out.it) == 2
