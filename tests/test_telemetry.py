"""Telemetry subsystem suite (tier-1, CPU).

Covers the observability layer end to end: the JSONL event sink (span
nesting, counter accumulation, well-formedness), the profiling helpers
(``Stopwatch``, the fixed ``annotate``), the static cost model against
hand-computed bytes/FLOPs for one diffusion and one WENO5 rung, the
supervised CLI run's ``--metrics`` stream (span + counter + physics
events, schema'd summary with mass-drift and roofline fields), the
ordered rollback events of a fault-injected run, and the multihost
initialize retry events.
"""

from __future__ import annotations

import json
import math
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
    telemetry,
)
from multigpu_advectiondiffusion_tpu.cli.__main__ import main as cli_main
from multigpu_advectiondiffusion_tpu.parallel.mesh import Decomposition
from multigpu_advectiondiffusion_tpu.resilience import faults, supervise_run
from multigpu_advectiondiffusion_tpu.telemetry import costmodel
from multigpu_advectiondiffusion_tpu.utils.profiling import (
    Stopwatch,
    annotate,
)
from multigpu_advectiondiffusion_tpu.utils.summary import (
    SUMMARY_SCHEMA,
    RunSummary,
)


def _events(path) -> list:
    """Parse a JSONL stream; every line must be a JSON object."""
    out = []
    with open(path) as f:
        for line in f:
            assert line.endswith("\n"), "unterminated JSONL line"
            out.append(json.loads(line))
    return out


def _diffusion2d(**kw):
    cfg = DiffusionConfig(
        grid=Grid.make(16, 12, lengths=4.0), dtype="float32", **kw
    )
    return DiffusionSolver(cfg)


# --------------------------------------------------------------------- #
# Profiling helpers (satellite: annotate fix, Stopwatch coverage)
# --------------------------------------------------------------------- #
def test_stopwatch_accumulates_named_segments():
    sw = Stopwatch()
    with sw.segment("solve"):
        time.sleep(0.01)
    with sw.segment("solve"):  # same name accumulates
        time.sleep(0.01)
    with sw.segment("io"):
        pass
    assert set(sw.segments) == {"solve", "io"}
    assert sw.segments["solve"] >= 0.02
    rep = sw.report()
    assert "solve" in rep and "io" in rep and "total" in rep


def test_stopwatch_segment_syncs_operand():
    sw = Stopwatch()
    with sw.segment("compute", sync=jnp.ones((8, 8))):
        pass
    assert sw.segments["compute"] > 0.0


def test_annotate_preserves_wrapped_metadata():
    @annotate("labeled-span")
    def solve_step(x):
        """Docstring the profiler label must not eat."""
        return x + 1

    assert solve_step.__name__ == "solve_step"
    assert "profiler label" in solve_step.__doc__
    assert solve_step(1) == 2


def test_annotate_usable_as_context_manager():
    with annotate("ad-hoc-region"):
        x = jnp.sum(jnp.ones((4, 4)))
    assert float(x) == 16.0


# --------------------------------------------------------------------- #
# Event sink
# --------------------------------------------------------------------- #
def test_sink_jsonl_well_formed_and_ordered(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path) as sink:
        sink.event("physics", "probe", step=1, mass=2.5)
        sink.counter("halo.bytes_per_execution", 128)
        with sink.span("chunk", iters=3):
            sink.event("io", "checkpoint_write", path="x", bytes=64)
    evs = _events(path)
    assert evs[0]["kind"] == "meta" and evs[0]["name"] == "open"
    assert evs[0]["schema"] == telemetry.EVENT_SCHEMA
    for ev in evs:
        assert {"t", "proc", "kind", "name"} <= set(ev)
    ts = [ev["t"] for ev in evs]
    assert ts == sorted(ts), "timestamps must be monotonic"


def test_sink_span_nesting(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path) as sink:
        with sink.span("outer"):
            with sink.span("inner"):
                pass
        with sink.span("second"):
            pass
    spans = [e for e in _events(path) if e["kind"] == "span"]
    outer_b, inner_b, inner_e, outer_e, sec_b, sec_e = spans
    assert outer_b["phase"] == "begin" and outer_b["depth"] == 0
    assert inner_b["parent"] == outer_b["id"] and inner_b["depth"] == 1
    assert inner_e["phase"] == "end" and inner_e["id"] == inner_b["id"]
    assert outer_e["id"] == outer_b["id"] and "seconds" in outer_e
    assert sec_b["parent"] is None and sec_b["id"] != outer_b["id"]


def test_sink_counter_accumulation(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path) as sink:
        sink.counter("bytes", 100)
        sink.counter("bytes", 50)
        sink.counter("calls", 1)
        assert sink.counters() == {"bytes": 150, "calls": 1}
    evs = [e for e in _events(path) if e["kind"] == "counter"]
    assert [(e["name"], e["inc"], e["total"]) for e in evs] == [
        ("bytes", 100, 100), ("bytes", 50, 150), ("calls", 1, 1),
    ]


def test_sink_tail_and_null_sink(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path) as sink:
        for i in range(5):
            sink.event("dispatch", "build", key=str(i))
        tail = sink.tail(2)
        assert [e["key"] for e in tail] == ["3", "4"]
    # after capture ends the null sink is active: no-ops, no raise
    assert not telemetry.get_sink().active
    telemetry.event("physics", "probe")
    telemetry.counter("x", 1)
    with telemetry.span("noop"):
        pass
    assert telemetry.get_sink().tail() == []


# --------------------------------------------------------------------- #
# Cost model vs hand-computed bytes/FLOPs
# --------------------------------------------------------------------- #
def test_costmodel_diffusion_fused_stage_hand_computed():
    """3-D O4 diffusion on the per-stage fused rung, 8^3 f32 cells.

    Hand computation at the documented conventions:
      FLOPs/cell/stage = O4 axis term (7) x 3 axes + 2 cross-axis adds
                         + 5 RK combine = 28
      FLOPs/step       = 3 stages x 512 cells x 28 = 43008
      HBM passes/step  = 8 (S->T1: 2; T1,S->T2: 3; T2,S->S: 3)
      bytes/step       = 8 x 512 x 4 = 16384
    """
    c = costmodel.step_cost(
        "diffusion", (8, 8, 8), 4, "fused-stage", stages=3, order=4
    )
    assert c.flops_per_cell_stage == 28
    assert c.flops == 3 * 512 * 28 == 43008
    assert c.passes == 8
    assert c.hbm_bytes == 8 * 512 * 4 == 16384
    # the slab whole-run rung's selling point: one HBM round trip/step
    slab = costmodel.step_cost(
        "diffusion", (8, 8, 8), 4, "fused-whole-run-slab", stages=3, order=4
    )
    assert slab.hbm_bytes == 2 * 512 * 4 == 4096
    assert slab.flops == c.flops  # same math, less traffic


def test_costmodel_weno5_hand_computed():
    """3-D inviscid WENO5 Burgers on generic-xla, 16^3 f32 cells.

    Hand computation:
      WENO5 axis sweep = LF split 7 + 2 sides x (betas 33 + eps 3 +
        alphas 9 + normalize 6 + stencils 15 + combine 5 = 71) + flux
        divergence 2 = 151
      FLOPs/cell/stage = 151 x 3 axes + 2 cross-axis adds + 5 RK = 460
      HBM passes/step  = 3 stages x 6 (materialized-RHS bound) = 18
    """
    cells = 16 ** 3
    c = costmodel.step_cost(
        "burgers", (16, 16, 16), 4, "generic-xla", stages=3, weno_order=5
    )
    assert c.flops_per_cell_stage == 151 * 3 + 2 + 5 == 460
    assert c.flops == 3 * cells * 460
    assert c.hbm_bytes == 18 * cells * 4
    # viscous adds the O2 Laplacian (4x3 + 2) plus one axpy (2) = 16
    v = costmodel.step_cost(
        "burgers", (16, 16, 16), 4, "generic-xla", stages=3, weno_order=5,
        viscous=True,
    )
    assert v.flops_per_cell_stage == 460 + 16


def test_costmodel_f64_storage_pays_f64_bytes():
    f32 = costmodel.step_cost("diffusion", (8, 8, 8), 4, "fused-stage")
    f64 = costmodel.step_cost("diffusion", (8, 8, 8), 8, "fused-stage")
    assert f64.hbm_bytes == 2 * f32.hbm_bytes


def test_costmodel_roofline_pct(monkeypatch):
    monkeypatch.setenv("TPUCFD_PEAK_BYTES_PER_S", "1e9")
    monkeypatch.setenv("TPUCFD_PEAK_FLOPS_PER_S", "1e15")
    c = costmodel.step_cost("diffusion", (64, 64), 4, "fused-stage")
    iters = 10
    model_seconds = c.hbm_bytes * iters / 1e9  # memory-bound by forced peaks
    r = costmodel.roofline(c, iters, model_seconds)
    assert r["bound"] == "hbm"
    assert r["roofline_pct"] == pytest.approx(100.0)
    # twice as slow as the roof -> 50%
    r2 = costmodel.roofline(c, iters, 2 * model_seconds)
    assert r2["roofline_pct"] == pytest.approx(50.0)


def test_costmodel_solver_summary_matches_step_cost():
    solver = _diffusion2d()
    out = costmodel.summarize_run(solver, "generic-xla", 10, 0.5)
    by_hand = costmodel.step_cost("diffusion", (12, 16), 4, "generic-xla")
    assert out["hbm_bytes_per_step"] == by_hand.hbm_bytes
    assert out["flops_per_step"] == by_hand.flops
    assert out["stepper"] == "generic-xla"
    assert out["roofline_pct"] is not None
    # burgers duck-typing picks the WENO branch
    b = BurgersSolver(
        BurgersConfig(grid=Grid.make(32, lengths=2.0), dtype="float32")
    )
    bout = costmodel.summarize_run(b, "generic-xla", 10, 0.5)
    assert bout["flops_per_cell_stage"] == 151 + 0 + 5  # 1-D WENO5 + RK


def test_costmodel_vmem_resident_rung_is_compute_bound():
    c = costmodel.step_cost("diffusion", (64, 64), 4, "fused-whole-run")
    assert c.hbm_bytes == 0.0
    r = costmodel.roofline(c, 10, 1.0)
    assert r["bound"] == "flops"


def test_xla_memory_analysis_cross_check():
    """Where the backend exposes memory_analysis(), the argument bytes
    must match the static model's per-field size (the model's
    cells*itemsize unit is real, not invented)."""
    x = np.ones((32, 32), np.float32)
    res = costmodel.xla_memory_analysis(lambda a: a * 2.0, x)
    if res is None:
        pytest.skip("backend provides no memory_analysis()")
    assert res.get("argument_size_in_bytes", 0) >= x.nbytes


# --------------------------------------------------------------------- #
# Supervised CLI run: the acceptance stream
# --------------------------------------------------------------------- #
def test_cli_metrics_stream_and_summary(tmp_path, devices):
    """A supervised, sharded CLI run with --metrics produces a parseable
    JSONL stream containing span, counter, physics and io events, and
    the summary JSON carries schema/mass-drift/roofline fields."""
    run = tmp_path / "run"
    mpath = str(tmp_path / "events.jsonl")
    cli_main([
        "diffusion2d", "--n", "16", "12", "--iters", "6",
        "--mesh", "dy=2", "--sentinel-every", "2",
        "--checkpoint-every", "2", "--save", str(run),
        "--metrics", mpath,
    ])
    evs = _events(mpath)
    kinds = {e["kind"] for e in evs}
    assert {"meta", "span", "counter", "physics", "resilience", "io",
            "dispatch"} <= kinds
    armed = [e for e in evs if e["name"] == "sentinel_armed"]
    assert armed and armed[0]["cadence"] == 2
    # spans nest under the run_solver root
    roots = [
        e for e in evs
        if e["kind"] == "span" and e["name"] == "run_solver"
        and e["phase"] == "begin"
    ]
    assert len(roots) == 1
    runs = [
        e for e in evs
        if e["kind"] == "span" and e["name"] == "solver.run"
        and e["phase"] == "begin"
    ]
    assert runs and all(e["parent"] == roots[0]["id"] for e in runs)
    assert all("stepper" in e for e in runs)
    # halo counters: trace-time record of the sharded exchange; the
    # (12, 16) grid sharded dy=2 gives (6, 16) shards, and the O4 halo
    # (2) moves 2 slabs x (2 x 16) cells x 4 B = 256 B per exchange
    halo = [e for e in evs if e["name"] == "halo.bytes_per_execution"]
    assert halo and all(e["inc"] % 256 == 0 for e in halo)
    # physics probes stream min/max/l2/mass + drift
    phys = [e for e in evs if e["kind"] == "physics"]
    assert len(phys) >= 3
    assert {"min", "max", "l2", "mass", "mass_drift"} <= set(phys[-1])
    # checkpoint writes are attributable io events
    io_evs = [e for e in evs if e["kind"] == "io"]
    assert any(e["name"] == "checkpoint_write" for e in io_evs)
    # summary JSON: schema'd, with the acceptance fields
    summary = json.loads((run / "summary.json").read_text())
    assert summary["schema"] == SUMMARY_SCHEMA
    assert summary["mass_drift"] == pytest.approx(
        phys[-1]["mass_drift"], rel=1e-6
    )
    assert summary["roofline_pct"] is not None
    assert summary["cost_model"]["stepper"] == summary["engaged"]["stepper"]
    # no leftover tmp file from the atomic summary write
    assert not [n for n in os.listdir(run) if ".tmp" in n]


def test_rollback_shows_as_ordered_events(tmp_path):
    """A fault-injected rollback run shows the rollback as ORDERED
    events: probes before it, the rollback record, then the retried
    chunks and a final healthy probe (the acceptance stream)."""
    mpath = str(tmp_path / "events.jsonl")
    solver = _diffusion2d()
    state = solver.initial_state()
    t_end = 30 * solver.dt
    with telemetry.capture(mpath):
        with faults.nan_at_step(solver, 6):
            out, report = supervise_run(
                solver, state, t_end=t_end, sentinel_every=3,
                max_retries=2, dt_backoff=0.5,
            )
    assert report.retries == 1
    evs = _events(mpath)
    names = [(e["kind"], e["name"]) for e in evs]
    rb = names.index(("resilience", "rollback"))
    # at least one chunk dispatched and probed before the rollback...
    pre = names[:rb]
    assert ("span", "solver.advance_to") in pre or (
        "span", "solver.step") in pre
    assert ("physics", "probe") in pre
    # ...and the retry continues after it: more chunks, healthy probes
    post = names[rb + 1:]
    assert ("physics", "probe") in post
    assert any(k == "span" for k, _ in post)
    ev = evs[rb]
    assert ev["reason"] == "non-finite field"
    assert "dt" in ev["action"] and ev["retry"] == 1
    assert ev["rollback_to_it"] >= 0
    # the report's last probe stats mirror the stream's last physics event
    last_phys = [e for e in evs if e["kind"] == "physics"][-1]
    assert report.mass_drift == pytest.approx(
        last_phys["mass_drift"], rel=1e-6
    )


def test_ladder_degrade_emits_event(tmp_path):
    mpath = str(tmp_path / "events.jsonl")
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    )
    with telemetry.capture(mpath):
        with faults.mosaic_failure():
            solver.run(solver.initial_state(), 2)
    degrades = [
        e for e in _events(mpath) if (e["kind"], e["name"]) ==
        ("ladder", "degrade")
    ]
    assert degrades, "kernel-ladder downgrade must appear in the stream"
    assert degrades[-1]["to"] == "xla"
    assert all("Mosaic" in e["reason"] for e in degrades)


def test_multihost_initialize_emits_retry_events(monkeypatch, tmp_path):
    from multigpu_advectiondiffusion_tpu.parallel import multihost

    calls = {"n": 0}

    def flaky(**kwargs):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("coordinator not reachable yet")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    mpath = str(tmp_path / "events.jsonl")
    with telemetry.capture(mpath):
        multihost.initialize(
            coordinator_address="localhost:1234", num_processes=1,
            process_id=0, attempts=3, backoff_seconds=0.0,
        )
    evs = [e for e in _events(mpath) if e["kind"] == "dist_init"]
    assert [e["name"] for e in evs] == [
        "attempt", "retry", "attempt", "retry", "attempt", "ok",
    ]
    assert evs[0]["attempt"] == 1 and evs[0]["attempts"] == 3
    assert "coordinator not reachable" in evs[1]["error"]
    assert evs[-1]["attempt"] == 3

    def always_down(**kwargs):
        raise RuntimeError("connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", always_down)
    mpath2 = str(tmp_path / "events2.jsonl")
    with telemetry.capture(mpath2):
        with pytest.raises(RuntimeError, match="after 2 attempt"):
            multihost.initialize(
                coordinator_address="localhost:1234", num_processes=1,
                process_id=0, attempts=2, backoff_seconds=0.0,
            )
    evs2 = [e for e in _events(mpath2) if e["kind"] == "dist_init"]
    assert evs2[-1]["name"] == "failed" and evs2[-1]["attempts"] == 2


# --------------------------------------------------------------------- #
# Halo byte accounting: deep / k-step exchanges report true bytes
# --------------------------------------------------------------------- #
def _halo_counter_events(path):
    return [
        e for e in _events(path)
        if e.get("name") == "halo.bytes_per_execution"
    ]


def test_halo_bytes_deep_k_step_schedule(tmp_path, devices):
    """The k-step comm-avoiding schedule must report its true per-
    compiled-execution traffic: one k*G-deep exchange site, repeated
    once per block (loop trip count folded in), not a per-step h-deep
    estimate. 4 iters at k=2 -> 2 blocks of a 12-row-deep exchange."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import make_mesh

    grid = Grid.make(16, 16, 48, lengths=2.0)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas_slab",
                        steps_per_exchange=2),
        mesh=make_mesh({"dz": 2}, devices=devices[:2]),
        decomp=Decomposition.of({0: "dz"}),
    )
    fused = solver._fused_stepper()
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path):
        solver.run(solver.initial_state(), 4)
    evs = _halo_counter_events(path)
    assert len(evs) == 1, evs  # ONE deep site, traced once
    ev = evs[0]
    py, px = fused.padded_shape[1:]
    per_exchange = 2 * fused.exchange_depth * py * px * 4  # lo+hi slabs
    assert ev["halo"] == fused.exchange_depth == 12
    assert ev["repeats"] == 2  # 4 iters / k=2 -> 2 blocks
    assert ev["inc"] == 2 * per_exchange


def test_halo_bytes_per_step_slab_counts_loop_trips(tmp_path, devices):
    """The per-step (k=1) sharded slab schedule exchanges G-deep once
    per step inside a fori_loop: the counter must carry the trip count,
    not one trace-site's worth (the pre-ISSUE-4 under-report)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import make_mesh

    grid = Grid.make(16, 16, 48, lengths=2.0)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas_slab"),
        mesh=make_mesh({"dz": 2}, devices=devices[:2]),
        decomp=Decomposition.of({0: "dz"}),
    )
    fused = solver._fused_stepper()
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path):
        solver.run(solver.initial_state(), 5)
    evs = _halo_counter_events(path)
    assert len(evs) == 1
    ev = evs[0]
    py, px = fused.padded_shape[1:]
    assert ev["halo"] == fused.halo == 6
    assert ev["repeats"] == 5
    assert ev["inc"] == 5 * 2 * fused.halo * py * px * 4


def test_halo_bytes_fused_stage_counts_loop_trips(tmp_path, devices):
    """The per-stage fused stepper refreshes h-deep ghosts after every
    RK stage inside the run loop: 3 sites, each repeated num_iters
    times per compiled execution."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import make_mesh

    grid = Grid.make(16, 16, 48, lengths=2.0)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas_stage"),
        mesh=make_mesh({"dz": 2}, devices=devices[:2]),
        decomp=Decomposition.of({0: "dz"}),
    )
    fused = solver._fused_stepper()
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path):
        solver.run(solver.initial_state(), 4)
    evs = _halo_counter_events(path)
    # one embed-time refresh (repeats=1) + 3 per-stage loop sites
    loop = [e for e in evs if e["repeats"] == 4]
    assert len(loop) == 3, evs
    py, px = fused.padded_shape[1:]
    per = 2 * fused.halo * py * px * 4
    assert all(e["inc"] == 4 * per for e in loop)
    embed = [e for e in evs if e["repeats"] == 1]
    assert len(embed) == 1 and embed[0]["inc"] == per


# --------------------------------------------------------------------- #
# Summary schema + atomic write
# --------------------------------------------------------------------- #
def test_write_json_atomic_and_schema(tmp_path):
    s = RunSummary(
        name="t", grid_xyz=(8, 8), iters=4, stages=3, seconds=0.5,
        dt=1e-3, t_final=0.1,
    )
    path = str(tmp_path / "summary.json")
    s.write_json(path)
    d = json.loads(open(path).read())
    assert d["schema"] == SUMMARY_SCHEMA
    assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]


def test_sharded_probe_physics_stats_are_global(devices):
    """min/max/mass/L2 must span the whole mesh, not one shard."""
    from jax.sharding import Mesh

    from multigpu_advectiondiffusion_tpu.resilience.sentinel import (
        make_health_probe,
    )

    mesh = Mesh(np.asarray(devices[:2]), ("dy",))
    cfg = DiffusionConfig(grid=Grid.make(16, 12, lengths=4.0),
                          dtype="float32")
    sharded = DiffusionSolver(cfg, mesh=mesh,
                              decomp=Decomposition.of({0: "dy"}))
    local = DiffusionSolver(cfg)
    st = local.initial_state()
    st_sh = sharded.initial_state()
    a = make_health_probe(local)(st)
    b = make_health_probe(sharded)(st_sh)
    for key in ("max_abs", "min", "max", "l2", "mass"):
        assert b[key] == pytest.approx(a[key], rel=1e-5), key
    vol = math.prod(cfg.grid.spacing)
    assert a["mass"] == pytest.approx(
        vol * float(jnp.sum(st.u)), rel=1e-5
    )


# --------------------------------------------------------------------- #
# Cost-model cross-check vs XLA's own memory accounting (ISSUE 6
# satellite: the dormant memory_analysis() hook promoted to tier-1)
# --------------------------------------------------------------------- #
def _memory_cross_check_case(solver):
    state = solver.initial_state()
    res = costmodel.solver_memory_cross_check(solver, state)
    if res is None:
        pytest.skip("backend provides no memory_analysis()")
    field = res["field_bytes"]
    assert field == math.prod(solver.grid.shape) * 4  # f32 storage
    xla = res["xla"]
    # XLA's own accounting confirms the model's unit: one compiled step
    # reads at least the state field and writes at least the state field
    assert xla["argument_size_in_bytes"] >= field
    assert xla["output_size_in_bytes"] >= field
    model_bytes = res["model"]["hbm_bytes_per_step"]
    min_traffic = res["min_traffic_bytes"]
    # the static model must never claim LESS traffic than the compiled
    # program's own unavoidable in+out footprint ...
    assert model_bytes >= 0.9 * min_traffic, (model_bytes, min_traffic)
    # ... nor more than the documented generic-xla pass count allows
    # (18 passes vs the 2-pass in/out floor, plus scalar/padding slop)
    assert model_bytes <= 20 * min_traffic, (model_bytes, min_traffic)
    return res


def test_memory_cross_check_diffusion_rung():
    res = _memory_cross_check_case(_diffusion2d(impl="xla"))
    # generic-xla diffusion models 18 field passes per step
    assert res["model"]["hbm_passes_per_step"] == 18


def test_memory_cross_check_weno5_rung():
    solver = BurgersSolver(BurgersConfig(
        grid=Grid.make(24, 16, lengths=2.0), weno_order=5,
        adaptive_dt=False, dtype="float32", impl="xla",
    ))
    res = _memory_cross_check_case(solver)
    # WENO5's FLOP model rides the same traffic model (18 passes) but a
    # far heavier per-cell count — both halves are cross-checked
    assert res["model"]["hbm_passes_per_step"] == 18
    assert res["model"]["flops_per_cell_stage"] >= 2 * 151
