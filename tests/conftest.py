"""Test configuration: CPU backend with 8 virtual devices.

The reference cannot test multi-GPU without a physical cluster
(``MPIDeviceCheck`` exits with < 2 GPUs, ``Util.cu:43-61``). Here the
distributed runtime is validated on a simulated 8-device CPU mesh
(SURVEY §4 implication (c)). Env vars must be set before jax imports.
"""

import os

# Force-override: the ambient environment may pin jax to a real TPU (e.g.
# an axon tunnel whose sitecustomize calls
# jax.config.update('jax_platforms', 'axon,cpu') at interpreter startup,
# trumping the JAX_PLATFORMS env var). The test suite always runs on
# virtual CPU devices so sharding is exercised without hardware — so both
# the env var AND the config entry must be forced before backend init.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import tempfile

# the tuner must never read or write the user-level decision cache from
# tests (bench entry points under test enable measurement process-wide),
# and any in-test measurement runs at smoke-grade cost
os.environ.setdefault(
    "TPUCFD_TUNING_CACHE",
    os.path.join(tempfile.mkdtemp(prefix="tpucfd_test_tuning_"),
                 "tuning.json"),
)
os.environ.setdefault("TPUCFD_TUNE_ITERS", "2")
os.environ.setdefault("TPUCFD_TUNE_REPS", "1")

# measured-peak calibration must never read or write the user-level
# record from tests. The per-test fixture below gives each in-process
# test a fresh store; this session-level default covers SUBPROCESSES
# whose env is snapshotted at module-import time (test_examples._ENV),
# before any fixture runs.
os.environ.setdefault(
    "TPUCFD_CALIBRATION_PATH",
    os.path.join(tempfile.mkdtemp(prefix="tpucfd_test_calib_"),
                 "calibration.json"),
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _isolate_calibration(tmp_path, monkeypatch):
    """Measured-peak calibration (telemetry/calibration.py) takes
    precedence over the env-assumed peaks in costmodel.peak_rates; a
    record written by one test (any run_solver call observes one) must
    never leak into another test's rooflines or tuner pruning — each
    test gets a fresh, empty store. Also zero the watermark tracker so
    one test's device-memory peak cannot bleed into the next."""
    monkeypatch.setenv(
        "TPUCFD_CALIBRATION_PATH", str(tmp_path / "calibration.json")
    )
    from multigpu_advectiondiffusion_tpu.telemetry import xprof

    xprof.reset_watermarks()
    yield
    xprof.reset_watermarks()


@pytest.fixture(autouse=True)
def _isolate_tuner_state():
    """bench/matrix entry points call tuning.configure (process-global);
    restore the knobs after every test so one test's enablement cannot
    change another's dispatch."""
    from multigpu_advectiondiffusion_tpu import tuning

    saved = dict(tuning._state)
    yield
    tuning._state.clear()
    tuning._state.update(saved)
