"""Operational hardening of the serving layer (ISSUE 20).

Single-writer lease (flock + advisory metadata, stale takeover with a
pid+cmdline guard, structured exit 78 for the loser); graceful drain &
handover (SIGTERM parks the in-flight batch at a slice boundary,
journals ``shutdown clean=true``, releases the lease; the successor
starts with zero replay-recovery work and answers every request
exactly once, bit-exact); the hung-dispatch watchdog
(``faults.stall_dispatch`` → batch evacuated from slice checkpoints,
poison member bisected to quarantine, healthy members unperturbed);
deadline enforcement at slice boundaries with a ``--best-effort``
opt-out; journal schema versioning (sealed seq-0 header, loud refusal
of future versions, the ``migrate`` CLI verb upgrading v0 roots in
place); and the HTTP adapter's fuzz surface (structured 400/405/413/
503, bounded reads, ``/healthz``, never a traceback).
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu.cli.status import (
    collect_status,
    render_text,
)
from multigpu_advectiondiffusion_tpu.models.ensemble import EnsembleSolver
from multigpu_advectiondiffusion_tpu.resilience import faults
from multigpu_advectiondiffusion_tpu.service import journal as journal_mod
from multigpu_advectiondiffusion_tpu.service.daemon import Scheduler
from multigpu_advectiondiffusion_tpu.service.journal import (
    JOURNAL_SCHEMA,
    Journal,
    JournalSchemaError,
    journal_schema,
    migrate_journal,
    schema_stamps,
    verify_records,
)
from multigpu_advectiondiffusion_tpu.service.lease import (
    EXIT_LEASE_HELD,
    LeaseHeldError,
    ServiceLease,
    inspect_lease,
)
from multigpu_advectiondiffusion_tpu.service.requests import (
    ALLOWED_REQUEST_TRANSITIONS,
    REQUEST_TERMINAL_STATES,
    RequestSpec,
    submit_request_to_spool,
)
from multigpu_advectiondiffusion_tpu.service.server import RequestServer
from multigpu_advectiondiffusion_tpu.utils.io import load_binary

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N = [12, 12]
T0 = 0.1
T_END = 0.18  # ~12 steps on the 12x12 stability dt
LONG_T_END = 3 * T_END  # enough steps for several 2-step slices


def _spec(rid, **kw) -> RequestSpec:
    base = dict(model="diffusion", n=list(N), t_end=T_END,
                ic="gaussian")
    base.update(kw)
    return RequestSpec(request_id=rid, **base)


def _events(root):
    path = os.path.join(root, "serve_events.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    return out


def _verdict(root, rid):
    with open(os.path.join(root, "requests", rid, "verdict.json")) as f:
        return json.load(f)


def _crash(root, rid):
    with open(os.path.join(root, "requests", rid, "crash.json")) as f:
        return json.load(f)


def _journal_verifies(root, require_complete=True):
    path = os.path.join(root, "journal.jsonl")
    records, torn = Journal.replay(path)
    return verify_records(
        records, torn=torn,
        allowed_transitions=ALLOWED_REQUEST_TRANSITIONS,
        terminal_states=REQUEST_TERMINAL_STATES,
        initial_state="received",
        require_complete=require_complete,
        schema_versions=schema_stamps(path),
    )


def _done_counts(root):
    records, _ = Journal.replay(os.path.join(root, "journal.jsonl"))
    counts = {}
    for r in records:
        if r.get("type") == "state" and r.get("to") == "done":
            counts[r["job"]] = counts.get(r["job"], 0) + 1
    return counts


def _reference_field(srv, spec):
    """The request's answer computed OUTSIDE the serving machinery."""
    tpl = srv._template(spec)
    ens = EnsembleSolver(
        tpl["family"].solver_cls, tpl["cfg"],
        [RequestServer._member_overrides(spec)],
    )
    out = ens.advance_to(ens.initial_state(), [float(spec.t_end)])
    return np.asarray(out.u[0], dtype=np.float32)


def _assert_bits_match(root, srv, spec):
    got = load_binary(
        os.path.join(root, "requests", spec.request_id, "result.bin"),
        tuple(N),
    )
    np.testing.assert_array_equal(got, _reference_field(srv, spec))


def _dead_pid() -> int:
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    return proc.pid


def _stale_meta(root, pid) -> dict:
    now = time.time()
    meta = {
        "pid": pid, "role": "serve-requests", "root": root,
        "cmdline": "python -c pass", "acquired": now - 120.0,
        "heartbeat": now - 90.0, "draining": False,
    }
    with open(os.path.join(root, "lease.json"), "w") as f:
        json.dump(meta, f)
    return meta


# --------------------------------------------------------------------- #
# Single-writer lease
# --------------------------------------------------------------------- #

def test_lease_acquire_inspect_release(tmp_path):
    root = str(tmp_path / "root")
    lease = ServiceLease(root, role="serve-requests").acquire()
    try:
        assert lease.held
        assert lease.takeover is None
        info = inspect_lease(root)
        assert info["present"] and info["locked"] and info["alive"]
        assert not info["stale"]
        assert info["holder"]["pid"] == os.getpid()
        assert info["holder"]["role"] == "serve-requests"
        assert info["age_s"] >= 0.0
        # heartbeat flips the advisory draining flag immediately
        lease.heartbeat(draining=True, force=True)
        assert inspect_lease(root)["draining"] is True
    finally:
        lease.release()
    info = inspect_lease(root)
    assert not info["present"] and not info["locked"]
    assert not os.path.exists(os.path.join(root, "lease.json"))


def test_lease_excludes_second_holder(tmp_path):
    root = str(tmp_path / "root")
    lease = ServiceLease(root).acquire()
    try:
        with pytest.raises(LeaseHeldError, match="lease held by pid"):
            ServiceLease(root).acquire()
    finally:
        lease.release()
    # released: the next acquire wins without takeover forensics
    lease2 = ServiceLease(root).acquire()
    assert lease2.takeover is None
    lease2.release()


def test_stale_lease_reclaimed_with_takeover(tmp_path):
    root = str(tmp_path / "root")
    os.makedirs(root)
    dead = _dead_pid()
    _stale_meta(root, dead)
    info = inspect_lease(root)
    assert info["present"] and not info["locked"]
    assert info["stale"] and not info["alive"]
    # the crashed holder's root is reclaimable: acquire wins and
    # records who it took over from
    lease = ServiceLease(root).acquire()
    try:
        assert lease.takeover is not None
        assert lease.takeover["pid"] == dead
        assert lease.takeover["age_s"] > 0.0
        assert inspect_lease(root)["alive"]
    finally:
        lease.release()


def test_request_server_lease_wiring(tmp_path):
    root = str(tmp_path / "srv")
    srv = RequestServer(root, fsync=False, lease=True)
    try:
        kinds = [(e["kind"], e["name"]) for e in _events(root)]
        assert ("lease", "acquire") in kinds
        with pytest.raises(LeaseHeldError, match="lease held by pid"):
            RequestServer(root, fsync=False, lease=True)
    finally:
        srv.close()
    # close released the lease; a successor acquires immediately
    assert not inspect_lease(root)["present"]
    srv2 = RequestServer(root, fsync=False, lease=True)
    srv2.close()
    kinds = [(e["kind"], e["name"]) for e in _events(root)]
    assert kinds.count(("lease", "release")) >= 2


def test_scheduler_reuses_lease(tmp_path):
    root = str(tmp_path / "sched")
    sch = Scheduler(root, fsync=False, lease=True)
    try:
        with pytest.raises(LeaseHeldError, match="lease held by pid"):
            Scheduler(root, fsync=False, lease=True)
    finally:
        sch.close()
    # crashed-holder takeover: stale metadata, free flock
    dead = _dead_pid()
    _stale_meta(root, dead)
    sch2 = Scheduler(root, fsync=False, lease=True)
    try:
        assert sch2.lease.takeover["pid"] == dead
    finally:
        sch2.close()
    events = []
    with open(os.path.join(root, "sched_events.jsonl")) as f:
        for line in f:
            events.append(json.loads(line))
    takeovers = [e for e in events
                 if e["kind"] == "lease" and e["name"] == "takeover"]
    assert takeovers and takeovers[-1]["prev_pid"] == dead


# --------------------------------------------------------------------- #
# Chaos (a): two servers race one root → structured loser exit 78
# --------------------------------------------------------------------- #

_SERVER_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, sys.argv[1])
import jax
jax.config.update("jax_platforms", "cpu")
from multigpu_advectiondiffusion_tpu.cli.__main__ import main
main(["serve-requests", "--root", sys.argv[2], "--until-idle",
      "--max-batch", "4", "--slice-steps", "2", "--poll", "0.01"])
print("SERVE-WORKER-OK", flush=True)
'''


def _launch_server(tmp_path, tag, root):
    script = tmp_path / f"server_{tag}.py"
    script.write_text(_SERVER_WORKER)
    log = tmp_path / f"server_{tag}.log"
    handle = open(log, "w")
    proc = subprocess.Popen(
        [sys.executable, str(script), REPO, root],
        stdout=handle, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    return proc, log, handle


def _run_to_completion(tmp_path, tag, root, timeout=240):
    proc, log, handle = _launch_server(tmp_path, tag, root)
    try:
        rc = proc.wait(timeout=timeout)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        handle.close()
    assert rc == 0, f"server {tag} rc={rc}:\n{log.read_text()[-2000:]}"
    assert "SERVE-WORKER-OK" in log.read_text()


@pytest.mark.chaos
def test_second_server_exits_78_naming_holder(tmp_path):
    """Two servers race one root: exactly one serves; the loser exits
    with the structured lease code instead of interleaving journal
    appends with the winner."""
    root = str(tmp_path / "contested")
    holder = RequestServer(root, fsync=False, lease=True)
    try:
        proc, log, handle = _launch_server(tmp_path, "loser", root)
        try:
            rc = proc.wait(timeout=240)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
            handle.close()
        assert rc == EXIT_LEASE_HELD, log.read_text()[-2000:]
        text = log.read_text()
        assert "lease held by pid" in text
        assert str(os.getpid()) in text
        assert "SERVE-WORKER-OK" not in text
        # the loser never wrote a byte of the holder's journal
        assert _journal_verifies(root, require_complete=False) == []
    finally:
        holder.close()


# --------------------------------------------------------------------- #
# Chaos (b): graceful drain & handover, exactly once, bit-exact
# --------------------------------------------------------------------- #

def test_drain_parks_batch_and_successor_resumes_exactly_once(tmp_path):
    """In-process drain mid-batch: admission stops, the batch parks at
    a slice boundary, the journal ends with ``shutdown clean=true``,
    the lease is released — and the successor answers everything
    exactly once, bit-exact, with zero crash-recovery requeues."""
    root = str(tmp_path / "drained")
    specs = [
        _spec("d0", t_end=LONG_T_END),
        _spec("d1", t_end=LONG_T_END, ic_params={"width": 0.12}),
    ]
    for s in specs:
        submit_request_to_spool(root, s)
    srv1 = RequestServer(root, max_batch=4, slice_steps=2,
                         fsync=False, lease=True)
    try:
        srv1.recover()
        deadline = time.time() + 180
        while time.time() < deadline:
            srv1.tick()
            if srv1._batch is not None and srv1._batch.slices >= 1:
                break
        assert srv1._batch is not None and srv1._batch.slices >= 1
        srv1.request_drain("test")
        # a request arriving during the drain stays spooled — the
        # durable mailbox is the successor's, not ours
        late = _spec("late")
        submit_request_to_spool(root, late)
        out = srv1.serve(until_idle=True)
        assert out["reason"] == "drained"
    finally:
        srv1.close()

    records, _ = Journal.replay(os.path.join(root, "journal.jsonl"))
    last = records[-1]
    assert last["type"] == "note" and last["note"] == "shutdown"
    assert last["clean"] is True
    kinds = [(e["kind"], e["name"]) for e in _events(root)]
    assert ("drain", "start") in kinds
    assert ("drain", "parked") in kinds
    assert ("drain", "done") in kinds
    # lease released at drain completion, not at close
    assert not inspect_lease(root)["present"]
    # the late arrival was NOT admitted by the draining server
    assert all(r.get("job") != "late" for r in records)

    srv2 = RequestServer(root, max_batch=4, slice_steps=2,
                         fsync=False, lease=True)
    try:
        report = srv2.recover()
        assert report["clean_shutdown"] is True
        assert report["requeued"] == 0 and report["failed"] == 0
        out = srv2.serve(until_idle=True)
        assert out["reason"] == "idle"
        for s in specs + [late]:
            assert _verdict(root, s.request_id)["status"] == "done"
            _assert_bits_match(root, srv2, s)
    finally:
        srv2.close()
    assert _journal_verifies(root) == []
    assert _done_counts(root) == {"d0": 1, "d1": 1, "late": 1}


_CHAOS_T_END = 0.5


def _chaos_specs():
    return [
        _spec(f"c{i}", t_end=_CHAOS_T_END,
              ic_params={"width": 0.08 + 0.02 * i})
        for i in range(4)
    ]


def _wait_for_slice(proc, root, timeout=180.0):
    events = os.path.join(root, "serve_events.jsonl")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        slices = 0
        try:
            with open(events) as f:
                for line in f:
                    if '"serve"' in line and '"slice"' in line:
                        slices += 1
        except OSError:
            slices = 0
        if slices:
            return slices
        if proc.poll() is not None:
            raise TimeoutError(
                f"server exited before a slice (rc={proc.poll()})"
            )
        time.sleep(0.02)
    raise TimeoutError(f"no serve:slice event within {timeout}s")


@pytest.mark.chaos
def test_sigterm_mid_batch_drains_clean_and_hands_over(tmp_path):
    """The acceptance chaos case: SIGTERM the serving daemon mid-batch.
    It drains to ``shutdown clean=true`` and exits 0; a successor —
    with one more request submitted across the handover — answers
    every request exactly once, bit-exact vs an uninterrupted run."""
    root = str(tmp_path / "termed")
    ref_root = str(tmp_path / "uninterrupted")
    mid = _spec("mid", t_end=_CHAOS_T_END, ic_params={"width": 0.2})
    for s in _chaos_specs() + [mid]:
        submit_request_to_spool(ref_root, s)
    for s in _chaos_specs():
        submit_request_to_spool(root, s)
    _run_to_completion(tmp_path, "ref", ref_root)

    proc, log, handle = _launch_server(tmp_path, "victim", root)
    try:
        assert _wait_for_slice(proc, root) >= 1
        os.kill(proc.pid, signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        handle.close()
    # a drain is an ORDERLY exit: rc 0, worker epilogue reached
    assert rc == 0, log.read_text()[-2000:]
    assert "SERVE-WORKER-OK" in log.read_text()
    records, _ = Journal.replay(os.path.join(root, "journal.jsonl"))
    last = records[-1]
    assert last["type"] == "note" and last["note"] == "shutdown"
    assert last["clean"] is True

    # continuous submission across the handover
    submit_request_to_spool(root, mid)
    _run_to_completion(tmp_path, "successor", root)

    recovers = [e for e in _events(root)
                if e["kind"] == "serve" and e["name"] == "recover"]
    assert recovers[-1]["clean_shutdown"] is True
    assert recovers[-1]["requeued"] == 0
    assert _journal_verifies(root) == []
    expected = {s.request_id: 1 for s in _chaos_specs() + [mid]}
    assert _done_counts(root) == expected
    for s in _chaos_specs() + [mid]:
        drained_bits = open(
            os.path.join(root, "requests", s.request_id, "result.bin"),
            "rb").read()
        ref_bits = open(
            os.path.join(ref_root, "requests", s.request_id,
                         "result.bin"), "rb").read()
        assert drained_bits == ref_bits, (
            f"{s.request_id}: drain handover changed the answer"
        )


# --------------------------------------------------------------------- #
# Chaos (c): hung dispatch → evacuation, bisection, quarantine
# --------------------------------------------------------------------- #

@pytest.mark.chaos
def test_stall_dispatch_bisects_and_quarantines_poison(tmp_path):
    """An injected dispatch stall blows the slice budget: the batch is
    evacuated from its slice checkpoints, bisection isolates the
    poison member, which is quarantined+failed with forensics — and
    the healthy members finish bit-exact."""
    root = str(tmp_path / "stalled")
    healthy = [
        _spec(f"h{i}", t_end=LONG_T_END,
              operands={"diffusivity": 0.10 + 0.01 * i})
        for i in range(3)
    ]
    poison = _spec("poison", t_end=LONG_T_END,
                   operands={"diffusivity": 0.777})
    for s in healthy + [poison]:
        submit_request_to_spool(root, s)
    srv = RequestServer(root, max_batch=4, slice_steps=2, fsync=False,
                        hang_budget_s=0.5)
    try:
        with faults.stall_dispatch(1.5, operand="diffusivity",
                                   value=0.777):
            out = srv.serve(until_idle=True)
        assert out["reason"] == "idle"
        v = _verdict(root, "poison")
        assert v["status"] == "failed"
        assert v["reason"] == "dispatch_hung"
        crash = _crash(root, "poison")
        assert crash["type"] == "DispatchHung"
        assert crash["quarantined"] is True
        assert crash["elapsed_s"] > crash["budget_s"]
        hungs = [e for e in _events(root)
                 if e["kind"] == "dispatch" and e["name"] == "hung"]
        # at least the 4-wide blow and the poison cohort's repeat
        assert len(hungs) >= 2
        for s in healthy:
            assert _verdict(root, s.request_id)["status"] == "done"
            _assert_bits_match(root, srv, s)
    finally:
        srv.close()
    assert _journal_verifies(root) == []
    assert _done_counts(root) == {"h0": 1, "h1": 1, "h2": 1}


def test_transient_solo_stall_retries_not_quarantines(tmp_path):
    """A solo batch's FIRST budget blow (a loaded host, a GC pause) is
    a requeue-retry from its checkpoint, not a quarantine — only the
    repeat strike fails the request."""
    root = str(tmp_path / "transient")
    spec = _spec("t0", t_end=LONG_T_END,
                 operands={"diffusivity": 0.5})
    submit_request_to_spool(root, spec)
    srv = RequestServer(root, max_batch=2, slice_steps=2, fsync=False,
                        hang_budget_s=0.5)
    try:
        # the first slice of a batch is watchdog-exempt, so stall two
        # slices: the second trips the budget (strike 1, requeue); the
        # retry's slices are stall-free and march to completion
        with faults.stall_dispatch(1.5, operand="diffusivity",
                                   value=0.5, times=2):
            out = srv.serve(until_idle=True)
        assert out["reason"] == "idle"
        assert _verdict(root, "t0")["status"] == "done"
        _assert_bits_match(root, srv, spec)
        records, _ = Journal.replay(os.path.join(root, "journal.jsonl"))
        requeues = [r for r in records if r.get("type") == "state"
                    and r.get("to") == "requeued"
                    and r.get("reason") == "dispatch_hung"]
        assert len(requeues) == 1
    finally:
        srv.close()
    assert _journal_verifies(root) == []


def test_persistent_solo_stall_quarantined_on_repeat(tmp_path):
    root = str(tmp_path / "wedged")
    submit_request_to_spool(
        root, _spec("w0", t_end=LONG_T_END,
                    operands={"diffusivity": 0.5}))
    srv = RequestServer(root, max_batch=2, slice_steps=2, fsync=False,
                        hang_budget_s=0.5)
    try:
        with faults.stall_dispatch(1.5, operand="diffusivity",
                                   value=0.5):
            out = srv.serve(until_idle=True)
        assert out["reason"] == "idle"
        v = _verdict(root, "w0")
        assert v["status"] == "failed"
        assert v["reason"] == "dispatch_hung"
        crash = _crash(root, "w0")
        assert crash["quarantined"] is True
        assert crash["strikes"] >= 2
    finally:
        srv.close()
    assert _journal_verifies(root) == []


# --------------------------------------------------------------------- #
# Chaos (d): deadline enforcement at slice boundaries
# --------------------------------------------------------------------- #

@pytest.mark.chaos
def test_deadline_cancelled_at_boundary_rest_unperturbed(tmp_path):
    root = str(tmp_path / "deadline")
    keep = [
        _spec("k0", t_end=LONG_T_END),
        _spec("k1", t_end=LONG_T_END, ic_params={"width": 0.12}),
    ]
    doomed = _spec("doomed", t_end=LONG_T_END, deadline_s=0.05,
                   ic_params={"width": 0.15})
    for s in keep + [doomed]:
        submit_request_to_spool(root, s)
    srv = RequestServer(root, max_batch=4, slice_steps=2, fsync=False)
    try:
        out = srv.serve(until_idle=True)
        assert out["reason"] == "idle"
        v = _verdict(root, "doomed")
        assert v["status"] == "failed"
        assert v["reason"] == "deadline_exceeded"
        crash = _crash(root, "doomed")
        assert crash["type"] == "DeadlineExceeded"
        assert crash["elapsed_s"] > crash["deadline_s"]
        # partial progress recorded: frozen before its horizon
        assert crash["t"] < LONG_T_END
        cancels = [e for e in _events(root)
                   if e["kind"] == "req"
                   and e["name"] == "deadline_cancel"]
        assert cancels and cancels[0]["job"] == "doomed"
        for s in keep:
            assert _verdict(root, s.request_id)["status"] == "done"
            _assert_bits_match(root, srv, s)
    finally:
        srv.close()
    assert _journal_verifies(root) == []


def test_best_effort_ignores_deadlines(tmp_path):
    root = str(tmp_path / "besteffort")
    submit_request_to_spool(
        root, _spec("be", t_end=T_END, deadline_s=0.001))
    srv = RequestServer(root, max_batch=4, slice_steps=2, fsync=False,
                        best_effort=True)
    try:
        srv.serve(until_idle=True)
        assert _verdict(root, "be")["status"] == "done"
        assert not any(
            e["kind"] == "req" and e["name"] == "deadline_cancel"
            for e in _events(root)
        )
    finally:
        srv.close()


# --------------------------------------------------------------------- #
# Chaos (e): journal schema versioning & migration
# --------------------------------------------------------------------- #

def test_journal_stamps_schema_header(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    with Journal(path, fsync=False) as j:
        j.append("submit", job="a")
        j.append("state", job="a", **{"from": "received",
                                      "to": "admitted"})
    assert journal_schema(path) == JOURNAL_SCHEMA
    assert schema_stamps(path) == [JOURNAL_SCHEMA]
    # readers strip the header: record counts stay pure
    records, torn = Journal.replay(path)
    assert torn == 0
    assert [r["type"] for r in records] == ["submit", "state"]
    with_header, _ = Journal.replay(path, include_schema=True)
    assert with_header[0]["seq"] == 0
    assert with_header[0]["note"] == "schema"
    assert with_header[0]["schema"] == JOURNAL_SCHEMA


def test_future_schema_refused_loudly(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    rec = {"seq": 0, "wall": 0.0, "type": "note", "note": "schema",
           "schema": JOURNAL_SCHEMA + 41}
    with open(path, "w") as f:
        f.write(journal_mod._seal(rec) + "\n")
    with pytest.raises(JournalSchemaError, match="schema"):
        Journal.replay(path)
    with pytest.raises(JournalSchemaError):
        Journal(path, fsync=False)
    with pytest.raises(JournalSchemaError):
        migrate_journal(path)
    # the dashboard reports the refusal as a fact, not a crash
    root = str(tmp_path)
    status = collect_status(root)
    assert status["schema_error"]
    assert any("SCHEMA ERROR" in line for line in render_text(status))


def test_migrate_upgrades_v0_in_place(tmp_path, capsys):
    root = str(tmp_path / "v0root")
    os.makedirs(root)
    path = os.path.join(root, "journal.jsonl")
    with Journal(path, fsync=False) as j:
        j.append("submit", job="a")
        j.append("state", job="a", **{"from": "received",
                                      "to": "admitted"})
    before, _ = Journal.replay(path)
    # strip the header (a pre-versioning root) and leave a torn tail
    lines = open(path).read().splitlines()
    with open(path, "w") as f:
        f.write("\n".join(lines[1:]) + "\n")
        f.write('{"seq": 9, "ty')
    assert journal_schema(path) == 0

    from multigpu_advectiondiffusion_tpu.cli.__main__ import (
        main as cli_main,
    )
    cli_main(["migrate", "--root", root])
    out = capsys.readouterr().out
    assert "schema" in out
    assert journal_schema(path) == JOURNAL_SCHEMA
    after, torn = Journal.replay(path)
    # identical state machine, torn tail preserved byte-for-byte
    assert after == before
    assert torn == 1
    assert verify_records(
        after, torn=torn,
        allowed_transitions=ALLOWED_REQUEST_TRANSITIONS,
        terminal_states=REQUEST_TERMINAL_STATES,
        initial_state="received",
        schema_versions=schema_stamps(path)) == []
    # idempotent: a second migrate is a no-op
    result = migrate_journal(path)
    assert result["migrated"] is False
    assert result["schema"] == JOURNAL_SCHEMA
    cli_main(["migrate", "--root", root])
    assert "nothing to do" in capsys.readouterr().out


def test_migrate_missing_journal_fails_structured(tmp_path, capsys):
    from multigpu_advectiondiffusion_tpu.cli.__main__ import (
        main as cli_main,
    )
    with pytest.raises(SystemExit):
        cli_main(["migrate", "--root", str(tmp_path / "nothere")])


# --------------------------------------------------------------------- #
# HTTP adapter hardening + /healthz
# --------------------------------------------------------------------- #

def _http(port):
    return http.client.HTTPConnection("127.0.0.1", port, timeout=10)


def test_http_fuzz_surface_and_healthz(tmp_path):
    root = str(tmp_path / "http")
    srv = RequestServer(root, fsync=False, http_port=0)
    try:
        port = srv.http_port

        def roundtrip(method, path, body=None, headers=None):
            conn = _http(port)
            try:
                conn.request(method, path, body=body,
                             headers=headers or {})
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                conn.close()

        # malformed JSON → structured 400, never a traceback
        status, body = roundtrip("POST", "/requests", b"{not json")
        assert status == 400
        assert b"Traceback" not in body
        assert "error" in json.loads(body)

        # non-UTF-8 body → 400
        status, body = roundtrip("POST", "/requests", b"\xff\xfe{}")
        assert status == 400 and b"Traceback" not in body

        # structurally-valid JSON that is not a spec → 400, not 500
        status, body = roundtrip(
            "POST", "/requests",
            json.dumps({"model": "diffusion"}).encode())
        assert status == 400 and b"Traceback" not in body
        status, body = roundtrip("POST", "/requests", b"[1, 2, 3]")
        assert status == 400 and b"Traceback" not in body

        # oversize claim → 413 before a byte is read
        conn = _http(port)
        try:
            conn.putrequest("POST", "/requests")
            conn.putheader("Content-Length", str((1 << 20) + 1))
            conn.putheader("Content-Type", "application/json")
            conn.endheaders()
            resp = conn.getresponse()
            assert resp.status == 413
            payload = json.loads(resp.read())
            assert payload["max_body_bytes"] == 1 << 20
        finally:
            conn.close()

        # garbage Content-Length → 400
        conn = _http(port)
        try:
            conn.putrequest("POST", "/requests")
            conn.putheader("Content-Length", "banana")
            conn.endheaders()
            resp = conn.getresponse()
            assert resp.status == 400
        finally:
            conn.close()

        # wrong methods → 405
        for method in ("PUT", "DELETE"):
            status, body = roundtrip(method, "/requests")
            assert status == 405
            assert b"Traceback" not in body

        # healthz: live lease/drain state for load-balancer probes
        status, body = roundtrip("GET", "/healthz")
        assert status == 200
        health = json.loads(body)
        assert health["status"] == "ok"
        assert health["draining"] is False
        assert health["lease"] is None  # started without a lease
        assert health["open_requests"] == 0

        # a well-formed submission still lands in the spool
        spec = _spec("h1")
        status, body = roundtrip(
            "POST", "/requests",
            json.dumps({"request_id": "h1", "model": "diffusion",
                        "n": N, "t_end": T_END,
                        "ic": "gaussian"}).encode())
        assert status == 202
        assert json.loads(body)["request_id"] == "h1"
        del spec

        # draining: admission refused with a structured 503
        srv.draining = True
        status, body = roundtrip(
            "POST", "/requests",
            json.dumps({"request_id": "h2", "model": "diffusion",
                        "n": N, "t_end": T_END}).encode())
        assert status == 503
        refusal = json.loads(body)
        assert refusal["status"] == "draining"
        assert refusal["retry_after_s"] > 0
        status, body = roundtrip("GET", "/healthz")
        health = json.loads(body)
        assert health["status"] == "draining"
        assert health["draining"] is True
    finally:
        srv.close()


def test_healthz_reports_lease_holder(tmp_path):
    root = str(tmp_path / "leased")
    srv = RequestServer(root, fsync=False, http_port=0, lease=True)
    try:
        conn = _http(srv.http_port)
        try:
            conn.request("GET", "/healthz")
            health = json.loads(conn.getresponse().read())
        finally:
            conn.close()
        assert health["lease"] == {"pid": os.getpid(), "held": True}
    finally:
        srv.close()


# --------------------------------------------------------------------- #
# tpucfd-status: lease / drain / clean-shutdown surface
# --------------------------------------------------------------------- #

def test_status_shows_lease_holder_and_stale(tmp_path):
    root = str(tmp_path / "statroot")
    lease = ServiceLease(root, role="serve-requests").acquire()
    try:
        status = collect_status(root)
        assert status["lease"]["alive"]
        text = "\n".join(render_text(status))
        assert f"pid={os.getpid()}" in text
        assert "role=serve-requests" in text
        assert "STALE" not in text
        # a draining holder is rendered as such
        lease.heartbeat(draining=True, force=True)
        status = collect_status(root)
        assert status["draining"] is True
        assert "draining" in "\n".join(render_text(status))
    finally:
        lease.release()
    dead = _dead_pid()
    _stale_meta(root, dead)
    status = collect_status(root)
    assert status["lease"]["stale"]
    text = "\n".join(render_text(status))
    assert "STALE" in text and "takes over" in text


def test_status_shows_clean_shutdown_marker(tmp_path):
    root = str(tmp_path / "cleanroot")
    os.makedirs(os.path.join(root, "requests"))
    with Journal(os.path.join(root, "journal.jsonl"), fsync=False) as j:
        j.append("note", note="drain", reason="test")
        j.append("note", note="shutdown", clean=True, pid=os.getpid())
    status = collect_status(root)
    assert status["clean_shutdown"] is True
    assert status["draining"] is False
    assert "clean shutdown" in "\n".join(render_text(status))
