"""The bf16-storage / f32-compute bandwidth rung (``precision='bf16'``,
ISSUE 16).

The rung's contract, each clause proven here:

* state LIVES in bfloat16 (HBM buffers, every halo wire byte) while
  every stencil tap and RK stage computes in float32 — the facing state
  stays f32 and tracks the native run closely;
* the generic-XLA loop carries a Kahan-style hi/lo compensation term,
  and that term is what keeps long-horizon error bounded: with the
  carry disabled (``TPUCFD_BF16_NO_CARRY=1``, the precision-gate
  selftest's injection point) per-step increments round away at the
  bf16 ulp and the error grows with the horizon;
* sharded runs move HALF the halo bytes (the counters prove the exact
  0.5 ratio);
* every fused stepper declares its storage dtype + bytes-per-cell and
  ``analysis.halo_verify`` refuses a spec that doesn't;
* ineligible configs decline LOUDLY (wrong dtype, adaptive-dt Burgers,
  ensembles) instead of silently running native storage;
* the science gate (diagnostics/compare) judges bf16 rounds against
  per-storage-dtype tolerance bands, with explicit ``--band`` overrides
  still winning.
"""

import dataclasses

import jax.numpy as jnp
import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
    telemetry,
)
from multigpu_advectiondiffusion_tpu.core.dtypes import bf16_carry_enabled
from multigpu_advectiondiffusion_tpu.parallel.mesh import (
    Decomposition,
    make_mesh,
)


def _diff_cfg(impl="xla", precision="bf16", n=(16, 14, 12), **kw):
    grid = Grid.make(*n, lengths=10.0)
    return DiffusionConfig(
        grid=grid, dtype="float32", impl=impl, precision=precision, **kw
    )


def _rel_l2(a, b):
    a = jnp.asarray(a, jnp.float32).ravel()
    b = jnp.asarray(b, jnp.float32).ravel()
    return float(jnp.linalg.norm(a - b) / jnp.linalg.norm(b))


# --------------------------------------------------------------------- #
# Carry toggle + hi/lo split in isolation
# --------------------------------------------------------------------- #
def test_carry_toggle_env(monkeypatch):
    monkeypatch.delenv("TPUCFD_BF16_NO_CARRY", raising=False)
    assert bf16_carry_enabled()
    for val in ("1", "true", "YES"):
        monkeypatch.setenv("TPUCFD_BF16_NO_CARRY", val)
        assert not bf16_carry_enabled()
    monkeypatch.setenv("TPUCFD_BF16_NO_CARRY", "0")
    assert bf16_carry_enabled()


def test_pack_roundtrip_beats_plain_downcast(monkeypatch):
    """``hi`` is exactly the bf16 downcast (so a wire transfer of the
    packed state moves precisely the declared bf16 bytes) and the
    carry's reconstruction is strictly closer to the f32 state than the
    plain downcast."""
    monkeypatch.delenv("TPUCFD_BF16_NO_CARRY", raising=False)
    solver = DiffusionSolver(_diff_cfg())
    u = solver.initial_state().u + 1.2345e-3  # off bf16-representable values
    packed = solver._bf16_pack(u)
    assert len(packed) == 2  # (hi, lo) with the carry armed
    hi, lo = packed
    assert hi.dtype == jnp.bfloat16 and lo.dtype == jnp.bfloat16
    assert jnp.array_equal(hi, u.astype(jnp.bfloat16))
    err_comp = _rel_l2(solver._bf16_unpack(packed), u)
    err_plain = _rel_l2(hi.astype(jnp.float32), u)
    assert err_comp < 0.25 * err_plain

    monkeypatch.setenv("TPUCFD_BF16_NO_CARRY", "1")
    bare = DiffusionSolver(_diff_cfg())
    packed = bare._bf16_pack(u)
    assert len(packed) == 1  # carry-off: plain downcast only
    assert jnp.array_equal(
        bare._bf16_unpack(packed), u.astype(jnp.bfloat16).astype(jnp.float32)
    )


def test_compensated_accumulation_bounded(monkeypatch):
    """THE rung's numerical claim, in isolation on the generic loop:
    vs the native-f32 trajectory, the compensated bf16 run's error
    stays at a few bf16 round-offs and barely grows with the horizon,
    while the uncompensated run's error is orders of magnitude larger
    AND grows with the step count (small per-step increments round
    away at the bf16 ulp without the carry)."""
    monkeypatch.delenv("TPUCFD_BF16_NO_CARRY", raising=False)
    cfg32 = _diff_cfg(precision="native")
    cfg16 = dataclasses.replace(cfg32, precision="bf16")

    def run(cfg, iters):
        s = DiffusionSolver(cfg)
        return s.run(s.initial_state(), iters).u

    errs = {}
    for iters in (60, 120):
        ref = run(cfg32, iters)
        monkeypatch.delenv("TPUCFD_BF16_NO_CARRY", raising=False)
        carry = _rel_l2(run(cfg16, iters), ref)
        monkeypatch.setenv("TPUCFD_BF16_NO_CARRY", "1")
        nocarry = _rel_l2(run(cfg16, iters), ref)
        errs[iters] = (carry, nocarry)
        # compensated: bounded at a few bf16 ulps (measured ~6e-6)
        assert carry < 1e-4, (iters, carry)
        # uncompensated: dominated by accumulation stall (measured
        # ~7e-3 at 60 steps, ~1.7e-2 at 120)
        assert nocarry > 20 * carry, (iters, carry, nocarry)
    # ...and GROWING with the horizon, unlike the compensated error
    assert errs[120][1] > 1.5 * errs[60][1]


# --------------------------------------------------------------------- #
# Eligibility gates — loud declines, never silent native storage
# --------------------------------------------------------------------- #
def test_validation_rejects_ineligible_dtypes():
    with pytest.raises(ValueError, match="redundant"):
        DiffusionSolver(
            dataclasses.replace(_diff_cfg(), dtype="bfloat16")
        )
    with pytest.raises(ValueError, match="must be float32"):
        DiffusionSolver(dataclasses.replace(_diff_cfg(), dtype="float64"))
    with pytest.raises(ValueError, match="precision"):
        _diff_cfg(precision="fp8")


def test_burgers_bf16_needs_fixed_dt_and_engages_slab():
    grid = Grid.make(32, 24, 16, lengths=(2.0, 2.0, 2.0))
    # adaptive dt: the fused rungs decline LOUDLY (the per-stage WENO
    # kernel has no split-dtype machinery) and the storage split rides
    # the generic loop around the per-axis ops instead
    adaptive = BurgersSolver(
        BurgersConfig(grid=grid, dtype="float32", impl="pallas",
                      precision="bf16", adaptive_dt=True, nu=1e-5)
    )
    engaged = adaptive.engaged_path()
    assert "slab" not in engaged["stepper"]
    assert "--fixed-dt" in (engaged["fallback"] or "")
    # fixed dt: Burgers' only fused bf16 rung, the whole-run slab
    # program, engages
    solver = BurgersSolver(
        BurgersConfig(grid=grid, dtype="float32", impl="pallas",
                      precision="bf16", adaptive_dt=False, nu=1e-5)
    )
    engaged = solver.engaged_path()
    assert "slab" in engaged["stepper"]
    assert engaged["precision"] == "bf16"
    assert engaged["storage_dtype"] == "bfloat16"


def test_ensemble_declines_bf16():
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )

    with pytest.raises(ValueError, match="single-run rung"):
        es = EnsembleSolver(DiffusionSolver, _diff_cfg(), 2)
        es.run(es.initial_state(), 1)


# --------------------------------------------------------------------- #
# Engagement facts: engaged_path, telemetry, keys
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("impl", ["xla", "pallas", "pallas_slab"])
def test_engaged_path_reports_storage_split(impl):
    solver = DiffusionSolver(_diff_cfg(impl=impl))
    engaged = solver.engaged_path()
    assert engaged["precision"] == "bf16"
    assert engaged["storage_dtype"] == "bfloat16"
    # the FACING state stays f32 and tracks the native run closely
    out = solver.run(solver.initial_state(), 5)
    assert out.u.dtype == jnp.float32
    native = DiffusionSolver(_diff_cfg(impl=impl, precision="native"))
    ref = native.run(native.initial_state(), 5)
    assert _rel_l2(out.u, ref.u) < 2e-2


def test_precision_engage_event_emitted(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with telemetry.capture(path):
        DiffusionSolver(_diff_cfg())
    import json

    events = [json.loads(l) for l in open(path) if l.strip()]
    engage = [e for e in events
              if e.get("kind") == "precision" and e.get("name") == "engage"]
    assert engage, "precision:engage event missing from the stream"
    assert engage[0]["storage_dtype"] == "bfloat16"
    assert engage[0]["compute_dtype"] == "float32"
    assert engage[0]["carry"] is True


def test_keys_fingerprint_storage_and_carry(monkeypatch):
    """A bf16 tuner decision must never serve a native run; an AOT
    entry compiled carry-on must never serve a carry-off process."""
    import jax

    from multigpu_advectiondiffusion_tpu.tuning.aot_cache import (
        dispatch_key,
    )
    from multigpu_advectiondiffusion_tpu.tuning.autotuner import make_key

    cfg16, cfg32 = _diff_cfg(), _diff_cfg(precision="native")
    backend = jax.default_backend()
    k16 = make_key(DiffusionSolver, cfg16, None, None, backend)
    k32 = make_key(DiffusionSolver, cfg32, None, None, backend)
    assert "prec=bf16" in k16 and "prec=native" in k32
    assert k16 != k32

    monkeypatch.delenv("TPUCFD_BF16_NO_CARRY", raising=False)
    on = dispatch_key(DiffusionSolver(cfg16), "run")
    monkeypatch.setenv("TPUCFD_BF16_NO_CARRY", "1")
    off = dispatch_key(DiffusionSolver(cfg16), "run")
    assert "storage=bfloat16" in on
    assert on != off  # the carry toggle is a first-class key dimension


def test_cost_model_prices_storage_bytes():
    """HBM passes are priced at the STORAGE itemsize: the bf16 rung's
    modeled bytes/step are half the native model's."""
    from multigpu_advectiondiffusion_tpu.telemetry.costmodel import (
        solver_step_cost,
    )

    s16 = DiffusionSolver(_diff_cfg())
    s32 = DiffusionSolver(_diff_cfg(precision="native"))
    stepper = s16.engaged_path()["stepper"]
    b16 = solver_step_cost(s16, stepper).hbm_bytes
    b32 = solver_step_cost(s32, stepper).hbm_bytes
    assert b16 == 0.5 * b32, (b16, b32)


# --------------------------------------------------------------------- #
# Wire bytes: sharded halo traffic halves exactly
# --------------------------------------------------------------------- #
def _halo_bytes(cfg, devices):
    mesh = make_mesh({"dz": 4}, devices=devices[:4])
    solver = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.slab("dz"))
    import json
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        path = f"{tmp}/events.jsonl"
        with telemetry.capture(path):
            solver.run(solver.initial_state(), 2)
        events = [json.loads(l) for l in open(path) if l.strip()]
    return sum(
        e.get("inc", 0)
        for e in events
        if e.get("kind") == "counter"
        and e.get("name") == "halo.bytes_per_execution"
    ), solver


def test_sharded_halo_bytes_halved(devices):
    """Ghost slabs cross the wire at the storage dtype: the traced
    halo byte counters of the bf16 run are EXACTLY half the native
    run's, and the sharded bf16 result still tracks native f32."""
    b16, s16 = _halo_bytes(_diff_cfg(), devices)
    b32, s32 = _halo_bytes(_diff_cfg(precision="native"), devices)
    assert b32 > 0
    assert b16 == 0.5 * b32, (b16, b32)
    out16 = s16.run(s16.initial_state(), 10)
    out32 = s32.run(s32.initial_state(), 10)
    assert _rel_l2(out16.u, out32.u) < 2e-2


# --------------------------------------------------------------------- #
# Storage-declaration proofs (analysis.halo_verify)
# --------------------------------------------------------------------- #
def test_stencil_spec_declares_storage_and_verifies():
    from multigpu_advectiondiffusion_tpu.analysis.halo_verify import (
        verify_stepper,
    )
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_diffusion import (
        FusedDiffusionStepper,
    )

    def make(dtype, **kw):
        return FusedDiffusionStepper(
            (24, 10, 12), dtype, (0.1,) * 3, [1.0] * 3, 1e-4, 2, 0.0,
            **kw,
        )

    stepper = make(jnp.bfloat16, storage_dtype=jnp.float32)
    spec = stepper.stencil_spec()
    assert spec["storage_dtype"] == "bfloat16"
    assert spec["bytes_per_cell"] == 2
    assert verify_stepper(stepper) == []

    # a spec that hides its storage dtype is REFUSED, and a lying
    # bytes-per-cell is caught against the dtype's itemsize
    class Undeclared(FusedDiffusionStepper):
        def stencil_spec(self):
            spec = dict(super().stencil_spec())
            spec.pop("storage_dtype")
            spec.pop("bytes_per_cell")
            return spec

    class Lying(FusedDiffusionStepper):
        def stencil_spec(self):
            return dict(super().stencil_spec(), bytes_per_cell=2)

    bad = verify_stepper(
        Undeclared((24, 10, 12), jnp.float32, (0.1,) * 3, [1.0] * 3,
                   1e-4, 2, 0.0)
    )
    assert any("storage_dtype" in v.what for v in bad)
    bad = verify_stepper(
        Lying((24, 10, 12), jnp.float32, (0.1,) * 3, [1.0] * 3,
              1e-4, 2, 0.0)
    )
    assert any("bytes_per_cell" in v.what for v in bad)


def test_halo_verify_battery_covers_bf16():
    """The full battery registers the bf16 combos (per-stage diffusion
    and ADR, slab diffusion/Burgers incl. the dma rung) — count
    enforced by EXPECTED_FAMILY_COMBOS, presence by name here."""
    from multigpu_advectiondiffusion_tpu.analysis import halo_verify

    names = {c.name for c in halo_verify.default_combos()}
    for expected in (
        "diffusion3d-stage[bf16]",
        "slab-diffusion[bf16]",
        "slab-diffusion[bf16,dma]",
        "slab-burgers[o5,bf16]",
        "adr3d-stage[bf16]",
    ):
        assert expected in names, expected


# --------------------------------------------------------------------- #
# Science gate: per-storage-dtype tolerance bands
# --------------------------------------------------------------------- #
def _round(dev, storage=None):
    meta = {"solver": "DiffusionSolver"}
    if storage:
        meta["storage_dtype"] = storage
    return {
        "schema": 1,
        "runs": {
            "r": {
                "meta": meta,
                "observables": {
                    "l2": [[10, 1.0], [20, 1.0 + dev]],
                    "time": [[10, 0.5], [20, 0.5]],
                },
            }
        },
    }


def test_compare_gate_uses_per_dtype_bands():
    from multigpu_advectiondiffusion_tpu.diagnostics import compare as C

    # a 5e-3 l2 deviation: DRIFT at f32 bands, ok at bf16 bands
    assert not C.compare(_round(5e-3), _round(0.0)).ok
    res = C.compare(_round(5e-3, "bfloat16"), _round(0.0, "bfloat16"))
    assert res.ok
    assert any("bfloat16 storage" in n for n in res.notes)
    # beyond even the bf16 bands still trips
    assert not C.compare(
        _round(5e-2, "bfloat16"), _round(0.0, "bfloat16")
    ).ok
    # an explicit --band override outranks the per-dtype table
    assert not C.compare(
        _round(5e-3, "bfloat16"), _round(0.0, "bfloat16"),
        bands={"l2": 1e-4},
    ).ok
    # time keeps its tight band at bf16: dt arithmetic is storage-
    # independent, so a drifting schedule is a bug at any precision
    bad = _round(0.0, "bfloat16")
    bad["runs"]["r"]["observables"]["time"] = [[10, 0.5], [20, 0.50005]]
    assert not C.compare(bad, _round(0.0, "bfloat16")).ok


def test_diagnostics_meta_records_storage_dtype():
    """physics.meta_for stamps the storage dtype a run's state lived
    in — the hook the per-dtype bands resolve through."""
    from multigpu_advectiondiffusion_tpu.diagnostics.physics import meta_for

    assert meta_for(DiffusionSolver(_diff_cfg()))[
        "storage_dtype"
    ] == "bfloat16"
    # native runs record their (compute) storage truthfully too — the
    # gate simply finds no per-dtype table for float32
    assert meta_for(DiffusionSolver(_diff_cfg(precision="native")))[
        "storage_dtype"
    ] == "float32"
