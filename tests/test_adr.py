"""ISSUE 15: solver-plugin registry + the ADR title workload.

Holds the tentpole and its satellites together:

* the registry's names/contract enforcement and the derived exports;
* the analytic advecting–decaying Gaussian on BOTH rungs (generic f64
  WENO5, fused-stage f32 upwind) within tolerance;
* fused-vs-generic and sharded-vs-single rung equivalence;
* ensemble B>1 bit-equality of the batched dispatch vs looped singles;
* the max-principle/positivity diagnostics contract;
* the registry-resolved halo combo matrix (ADR rungs + expected
  per-family counts; a missing family is a coverage violation);
* CLI ``--model adr`` resolution;
* cost-model/tuner-key coverage for the new family.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    ADRConfig,
    ADRSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.models import registry
from multigpu_advectiondiffusion_tpu.models.adr import kappa_profile
from multigpu_advectiondiffusion_tpu.models.state import SolverState
from multigpu_advectiondiffusion_tpu.parallel.mesh import (
    Decomposition,
    make_mesh,
)


def _cfg(**kw):
    grid = kw.pop("grid", None) or Grid.make(
        *kw.pop("n", (12, 10, 8)), lengths=10.0
    )
    base = dict(velocity=(0.5, 0.25, 0.125)[: grid.ndim]
                if grid.ndim > 1 else 0.5,
                reaction_rate=0.3, dtype="float32")
    base.update(kw)
    return ADRConfig(grid=grid, **base)


# --------------------------------------------------------------------- #
# Registry (tentpole)
# --------------------------------------------------------------------- #
def test_registry_names_and_specs():
    names = registry.names()
    assert {"diffusion", "burgers", "adr"} <= set(names)
    spec = registry.get("adr")
    assert spec.solver_cls is ADRSolver
    assert spec.config_cls is ADRConfig
    cfg = _cfg()
    assert registry.spec_for_config(cfg).name == "adr"
    assert registry.family_of_run_name("adr3d_mlups") == "adr"
    assert registry.solver_for_run_name("diffusion3d") is registry.get(
        "diffusion"
    ).solver_cls
    with pytest.raises(KeyError):
        registry.get("lattice_boltzmann")


def test_register_model_rejects_half_wired_plugin():
    class ToyConfig:
        pass

    class ToySolver:
        def stencil_spec(self):
            return {}

        def diagnostics_spec(self):
            return {}

        # ensemble_operands and cfl_rule missing

    with pytest.raises(ValueError, match="cfl_rule"):
        registry.register_model(registry.ModelSpec(
            name="toy-halfwired", config_cls=ToyConfig,
            solver_cls=ToySolver, description="incomplete",
        ))
    assert "toy-halfwired" not in registry.names()


def test_registry_completeness_lint_rule_registered():
    from multigpu_advectiondiffusion_tpu.analysis import all_rules
    from multigpu_advectiondiffusion_tpu.analysis.fixtures import (
        RULE_FIXTURES,
    )

    assert "registry-completeness" in all_rules()
    assert "registry-completeness" in RULE_FIXTURES


def test_exports_derive_from_registry():
    import multigpu_advectiondiffusion_tpu as pkg
    from multigpu_advectiondiffusion_tpu import models

    for name in ("ADRConfig", "ADRSolver", "DiffusionSolver",
                 "BurgersSolver"):
        assert name in pkg.__all__
        assert name in models.__all__
        assert getattr(models, name) is getattr(pkg, name)


def test_contract_methods_answer_on_every_family():
    diff = registry.get("diffusion")
    burg = registry.get("burgers")
    g3 = Grid.make(10, 8, 6, lengths=2.0)
    solvers = [
        diff.solver_cls(diff.config_cls(grid=g3)),
        burg.solver_cls(burg.config_cls(grid=g3)),
        ADRSolver(_cfg(grid=g3)),
    ]
    for s in solvers:
        spec = s.stencil_spec()
        assert spec["stage_radius"] >= 1
        rule = s.cfl_rule()
        assert rule["kind"]
        assert isinstance(s.ensemble_operands(), dict)
        assert isinstance(s.diagnostics_spec(), dict)


# --------------------------------------------------------------------- #
# Physics: analytic accuracy on both rungs (satellite 3)
# --------------------------------------------------------------------- #
def test_adr_ic_matches_exact_at_t0():
    s = ADRSolver(_cfg(n=(16, 12, 12), reaction_rate=0.5))
    st = s.initial_state()
    exact = s.exact_solution(s.cfg.t0)
    np.testing.assert_allclose(
        np.asarray(st.u), np.asarray(exact), atol=1e-6
    )


def test_adr_analytic_gaussian_generic_weno5_f64():
    g = Grid.make(48, 32, 32, lengths=10.0)
    cfg = ADRConfig(grid=g, velocity=(0.6, 0.3, 0.15),
                    reaction_rate=0.5, advect="weno5", dtype="float64")
    s = ADRSolver(cfg)
    out = s.advance_to(s.initial_state(), 0.18)
    n = s.error_norms(out)
    # measured linf ~1.6e-3 on this grid (peak amplitude ~0.38)
    assert n.linf < 5e-3, n
    assert n.l2 < 4e-3, n


def test_adr_analytic_gaussian_fused_stage_f32():
    g = Grid.make(48, 32, 32, lengths=10.0)
    cfg = ADRConfig(grid=g, velocity=(0.6, 0.3, 0.15),
                    reaction_rate=0.5, advect="upwind",
                    dtype="float32", impl="pallas")
    s = ADRSolver(cfg)
    assert s.engaged_path()["stepper"] == "fused-stage"
    out = s.advance_to(s.initial_state(), 0.18)
    n = s.error_norms(out)
    # first-order upwind smears: measured linf ~9.3e-3 on this grid
    assert n.linf < 2.5e-2, n


def test_adr_fused_matches_generic_upwind():
    cfg = _cfg(n=(16, 12, 12), kappa_variation=0.2)
    sx = ADRSolver(dataclasses.replace(cfg, impl="xla"))
    sp = ADRSolver(dataclasses.replace(cfg, impl="pallas_stage"))
    assert sp.engaged_path()["stepper"] == "fused-stage"
    ox = sx.run(sx.initial_state(), 4)
    op = sp.run(sp.initial_state(), 4)
    np.testing.assert_allclose(
        np.asarray(ox.u), np.asarray(op.u), atol=5e-7
    )


def test_adr_weno5_declines_fusion_loudly():
    s = ADRSolver(_cfg(advect="weno5", impl="pallas"))
    eng = s.engaged_path()
    # fusion declined (the Laplacian still rides the per-axis rung);
    # the reason names the baked upwind flux
    assert eng["stepper"] in ("per-axis-pallas", "generic-xla")
    assert "upwind" in eng["fallback"]


def test_adr_kappa_profile_positive_and_matches_kernel_formula():
    import math

    shape = (8, 6, 6)
    prof = kappa_profile(shape, shape, (0, 0, 0), 0.3, jnp.float32)
    p = np.asarray(prof)
    assert p.shape == shape
    assert (p > 0).all()
    # center cell of an odd-n axis sits at x̂=0 -> cos=1 on that axis
    want = 1.0 + 0.3 * math.cos(
        math.pi * (0 / (shape[0] - 1) - 0.5)
    ) * math.cos(math.pi * (0 / (shape[1] - 1) - 0.5)) * math.cos(
        math.pi * (0 / (shape[2] - 1) - 0.5)
    )
    np.testing.assert_allclose(p[0, 0, 0], want, rtol=1e-6)


# --------------------------------------------------------------------- #
# Sharded on a dz mesh (acceptance)
# --------------------------------------------------------------------- #
def test_adr_sharded_generic_matches_single_device():
    cfg = _cfg(n=(16, 12, 12), kappa_variation=0.2)
    single = ADRSolver(cfg)
    o1 = single.run(single.initial_state(), 4)
    mesh = make_mesh({"dz": 2})
    shard = ADRSolver(cfg, mesh=mesh, decomp=Decomposition.slab("dz"))
    o2 = shard.run(shard.initial_state(), 4)
    # roundoff-level: the advective fusion re-associates across
    # program shapes (models/adr.py docstring)
    np.testing.assert_allclose(
        np.asarray(o1.u), np.asarray(o2.u), atol=1e-6, rtol=1e-5
    )


def test_adr_sharded_fused_stage_matches_single_device():
    cfg = _cfg(n=(16, 12, 12), kappa_variation=0.2, impl="pallas_stage")
    single = ADRSolver(cfg)
    o1 = single.run(single.initial_state(), 4)
    mesh = make_mesh({"dz": 2})
    shard = ADRSolver(cfg, mesh=mesh, decomp=Decomposition.slab("dz"))
    assert shard.engaged_path()["stepper"] == "fused-stage"
    o2 = shard.run(shard.initial_state(), 4)
    np.testing.assert_allclose(
        np.asarray(o1.u), np.asarray(o2.u), atol=1e-6
    )


# --------------------------------------------------------------------- #
# Ensemble (acceptance: B>1 equality grade)
# --------------------------------------------------------------------- #
def test_adr_ensemble_batched_matches_looped_bit_exact():
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )

    cfg = _cfg(n=(10, 8, 8), ic="gaussian")
    es = EnsembleSolver(
        ADRSolver, cfg,
        [{"ic_params": (("width", 0.1 + 0.02 * i),)} for i in range(3)],
    )
    est = es.initial_state()
    out = es.run(est, 3)
    for i in range(3):
        single = es.member_solver(i)
        o = single.run(
            SolverState(u=est.u[i], t=est.t[i], it=est.it[i]), 3
        )
        assert np.array_equal(np.asarray(out.u[i]), np.asarray(o.u)), (
            f"member {i} diverged from its looped single run"
        )


def test_adr_ensemble_fused_stage_vmap_bit_exact():
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )

    cfg = _cfg(n=(10, 8, 8), kappa_variation=0.2, ic="gaussian",
               impl="pallas_stage")
    es = EnsembleSolver(
        ADRSolver, cfg,
        [{"ic_params": (("width", 0.1 + 0.02 * i),)} for i in range(2)],
    )
    est = es.initial_state()
    out = es.run(est, 2)
    assert es.engaged_path()["stepper"] == "ensemble-vmap[fused-stage]"
    for i in range(2):
        single = es.member_solver(i)
        o = single.run(
            SolverState(u=est.u[i], t=est.t[i], it=est.it[i]), 2
        )
        assert np.array_equal(np.asarray(out.u[i]), np.asarray(o.u))


def test_adr_ensemble_member_varying_operands():
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )

    cfg = _cfg(n=(10, 8, 8))
    es = EnsembleSolver(
        ADRSolver, cfg,
        [{"diffusivity": 0.5}, {"diffusivity": 1.5},
         {"reaction_rate": 1.0}],
    )
    est = es.initial_state()
    out = es.run(est, 3)
    u = np.asarray(out.u)
    assert np.isfinite(u).all()
    # different K/lambda must produce different trajectories
    assert not np.array_equal(u[0], u[1])
    assert not np.array_equal(u[0], u[2])


# --------------------------------------------------------------------- #
# Diagnostics contract (satellite 3)
# --------------------------------------------------------------------- #
def test_adr_diagnostics_rules_reaction_free():
    s = ADRSolver(_cfg(reaction_rate=0.0))
    rules = {r.name for r in s.diagnostics_spec()["rules"]}
    assert {"max_principle", "positivity"} <= rules
    meta = ADRSolver(_cfg(reaction_rate=0.0, velocity=0.25)
                     ).diagnostics_spec()["meta"]
    assert meta["decay_rate_analytic"] == -1.5


def test_positivity_rule_trips_on_negative_dip():
    from multigpu_advectiondiffusion_tpu.diagnostics.physics import (
        positivity_rule,
    )

    rule = positivity_rule()
    baseline = {"min": 0.0, "max": 1.0}
    assert rule.check({"min": -0.1, "max": 1.0}, baseline,
                      rule.tolerance)
    assert rule.check({"min": -1e-6, "max": 1.0}, baseline,
                      rule.tolerance) is None
    # signed initial data: vacuous
    assert rule.check({"min": -5.0, "max": 1.0},
                      {"min": -1.0, "max": 1.0}, rule.tolerance) is None


def test_adr_max_principle_holds_over_run():
    s = ADRSolver(_cfg(n=(16, 12, 12), reaction_rate=0.0,
                       kappa_variation=0.2))
    out = s.run(s.initial_state(), 10)
    u = np.asarray(out.u)
    assert u.max() <= 1.0 + 1e-3
    assert u.min() >= -1e-3


# --------------------------------------------------------------------- #
# Static halo matrix (satellite 2)
# --------------------------------------------------------------------- #
def test_halo_matrix_covers_adr_and_expected_counts():
    from multigpu_advectiondiffusion_tpu.analysis import halo_verify

    by_family, missing = halo_verify.family_combos()
    assert not missing
    for fam, combos in by_family.items():
        assert len(combos) == halo_verify.EXPECTED_FAMILY_COMBOS[fam], fam
    report = halo_verify.verify_all()
    assert report.ok, "\n".join(str(v) for v in report.violations)
    names = {c.name for c in report.combos if c.admitted}
    assert {"adr3d-stage", "adr3d-stage[varK]",
            "adr3d-stage[sharded]"} <= names
    assert report.checked >= 52


def test_halo_matrix_flags_missing_family_and_count_drift(monkeypatch):
    from multigpu_advectiondiffusion_tpu.analysis import halo_verify

    # a registered family with no combo battery is a coverage failure
    trimmed = dict(halo_verify.FAMILY_COMBOS)
    del trimmed["adr"]
    monkeypatch.setattr(halo_verify, "FAMILY_COMBOS", trimmed)
    report = halo_verify.verify_all()
    assert any(
        "no halo-verifier combo battery" in v.what
        and "adr" in v.kernel
        for v in report.violations
    )
    # a shrunken battery (dropped combo) is a counted coverage failure
    monkeypatch.setattr(halo_verify, "FAMILY_COMBOS", {
        **halo_verify.FAMILY_COMBOS,
        "adr": lambda: halo_verify._adr_combos()[:-1],
    })
    report = halo_verify.verify_all()
    assert any(
        "combo-matrix size drifted" in v.what for v in report.violations
    )


def test_adr_fused_stepper_stencil_spec_is_consistent():
    from multigpu_advectiondiffusion_tpu.analysis import halo_verify
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_adr import (
        FusedADRStepper,
    )

    stepper = FusedADRStepper(
        (24, 10, 12), jnp.float32, (0.1, 0.1, 0.1), 1.0,
        (0.5, 0.25, 0.0), 0.3, 1e-4, 2, 0.0, kappa_variation=0.2,
        global_shape=(48, 10, 12),
    )
    assert halo_verify.verify_stepper(stepper) == []
    spec = stepper.stencil_spec()
    assert spec["stage_radius"] == 2  # max(upwind 1, O4 2)
    assert spec["steps_per_exchange"] == 1


# --------------------------------------------------------------------- #
# CLI --model resolution (tentpole) + config validation
# --------------------------------------------------------------------- #
def test_cli_model_flag_resolves_and_runs(tmp_path):
    from multigpu_advectiondiffusion_tpu.cli.__main__ import main

    summary = main([
        "--model", "adr", "--n", "10", "8", "6", "--iters", "2",
        "--velocity", "0.5", "--kappa-variation", "0.2",
        "--reaction", "0.3", "--save", str(tmp_path),
    ])
    assert summary.iters == 2
    assert (tmp_path / "summary.json").exists()


def test_cli_model_flag_unknown_model_fails_listing_registry():
    from multigpu_advectiondiffusion_tpu.cli.__main__ import (
        _resolve_model_argv,
    )

    with pytest.raises(SystemExit, match="registered models"):
        _resolve_model_argv(["--model", "nope", "--n", "8", "8"])
    argv = _resolve_model_argv(
        ["--model", "adr", "--ndim", "2", "--n", "8", "8"]
    )
    assert argv[0] == "adr2d"
    assert "--ndim" not in argv


def test_adr_config_rejects_slab_only_knobs():
    g = Grid.make(8, 8, 8, lengths=2.0)
    with pytest.raises(ValueError, match="per-step exchange"):
        ADRConfig(grid=g, steps_per_exchange=2)
    with pytest.raises(ValueError, match="collective"):
        ADRConfig(grid=g, exchange="dma")
    with pytest.raises(ValueError, match="eps"):
        ADRConfig(grid=g, kappa_variation=1.5)
    with pytest.raises(ValueError, match="DECAY"):
        ADRConfig(grid=g, reaction_rate=-1.0)


# --------------------------------------------------------------------- #
# Cost model + tuner keys + bench tables (satellites 4/6)
# --------------------------------------------------------------------- #
def test_costmodel_prices_adr():
    from multigpu_advectiondiffusion_tpu.telemetry import costmodel

    cfg = _cfg(kappa_variation=0.2)
    assert costmodel.solver_kind(cfg) == "adr"
    kw = costmodel.solver_cost_kwargs(cfg)
    assert kw["variable_k"] and kw["reaction"]
    cost = costmodel.step_cost("adr", (16, 12, 12), 4, "fused-stage",
                               **kw)
    assert cost.flops > 0 and cost.hbm_bytes > 0
    # WENO5 advection prices well above upwind
    up = costmodel.rhs_flops_per_cell("adr", 3, advect="upwind")
    we = costmodel.rhs_flops_per_cell("adr", 3, advect="weno5")
    assert we > up > 0
    s = ADRSolver(cfg)
    out = costmodel.summarize_run(s, "generic-xla", 4, 0.1)
    assert out is not None and out["flops_per_step"] > 0


def test_tuner_key_carries_adr_extras():
    from multigpu_advectiondiffusion_tpu.tuning.autotuner import make_key

    cfg = _cfg(advect="weno5")
    key = make_key(ADRSolver, cfg, None, None, "cpu")
    assert "adr" in key
    assert "advect=weno5" in key


def test_bench_matrix_builds_adr_cases():
    from multigpu_advectiondiffusion_tpu.bench import matrix

    cases = {c.name: c for c in matrix.CASES}
    assert "adr3d" in cases and "adr2d" in cases
    assert "adr3d" in matrix.BASELINES_MLUPS
    solver = matrix.build_solver(
        cases["adr3d"], "float32", (10, 8, 8), None
    )
    assert type(solver).__name__ == "ADRSolver"
    assert solver.cfg.kappa_variation


def test_bench_compare_family_coverage_notes():
    from multigpu_advectiondiffusion_tpu.bench import compare as cmp

    old = {
        "adr3d_mlups": {"metric": "adr3d_mlups", "value": 10.0},
        "diffusion3d_mlups": {"metric": "diffusion3d_mlups",
                              "value": 5.0},
    }
    new = {
        "diffusion3d_mlups": {"metric": "diffusion3d_mlups",
                              "value": 5.0},
    }
    res = cmp.compare(new, old)
    assert any("adr" in n and "NONE" in n for n in res.notes)
    assert not res.ok  # the dropped metric also gates as missing
    assert cmp.family_coverage(old) == {"adr": 1, "diffusion": 1}
