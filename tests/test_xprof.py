"""Measured-introspection suite (ISSUE 7 tentpole, tier-1, CPU).

Covers the xprof layer end to end: per-executable XLA cost/memory
capture at dispatch (one compile, reused for execution) on a diffusion
and a WENO5 rung, device-memory watermark sampling with the
live-arrays fallback, the calibration record's round-trip and its
precedence over env-assumed peaks (consulted by both the cost model
and the tuner's pruning), the dispatch-executable reuse of
``solver_memory_cross_check``, the exception-safe idempotent
``profiling.trace``, and a real supervised CLI run whose ``--metrics``
stream carries ``xla:cost`` / ``mem:watermark`` / ``calib:update``
events and whose summary gains the ``memory``/``xla`` blocks.
"""

from __future__ import annotations

import json

import pytest

import jax
import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
    telemetry,
)
from multigpu_advectiondiffusion_tpu.cli.__main__ import main as cli_main
from multigpu_advectiondiffusion_tpu.telemetry import (
    calibration,
    costmodel,
    schema,
    xprof,
)


def _events(path) -> list:
    with open(path) as f:
        return [json.loads(line) for line in f]


def _diffusion3d(**kw):
    cfg = DiffusionConfig(
        grid=Grid.make(12, 10, 8, lengths=3.0), dtype="float32", **kw
    )
    return DiffusionSolver(cfg)


def _burgers2d(**kw):
    cfg = BurgersConfig(
        grid=Grid.make(20, 16, lengths=2.0), weno_order=5,
        adaptive_dt=False, dtype="float32", **kw
    )
    return BurgersSolver(cfg)


# --------------------------------------------------------------------- #
# Executable capture at dispatch
# --------------------------------------------------------------------- #
def test_dispatch_captures_diffusion_executable(tmp_path):
    """One solver.run dispatch produces exactly one ExecRecord with
    nonzero XLA-reported flops/bytes, the modeled per-step prediction
    alongside, and a schema-valid xla:cost event."""
    path = str(tmp_path / "ev.jsonl")
    solver = _diffusion3d(impl="xla")
    with telemetry.capture(path):
        solver.run(solver.initial_state(), 3)
    recs = xprof.records(solver)
    assert len(recs) == 1
    rec = recs[0]
    assert rec.key == "('run', 3)" and rec.steps == 3
    assert rec.stepper == "generic-xla"
    assert rec.flops > 0 and rec.bytes_accessed > 0
    assert rec.compile_seconds > 0
    # XLA's argument accounting covers at least the state field
    field = 12 * 10 * 8 * 4
    assert rec.argument_bytes >= field
    assert rec.peak_bytes >= field
    # the static model's per-step numbers ride the record
    by_hand = costmodel.step_cost(
        "diffusion", (8, 10, 12), 4, "generic-xla"
    )
    assert rec.model_bytes_per_step == by_hand.hbm_bytes
    assert rec.model_flops_per_step == by_hand.flops
    evs = [e for e in _events(path) if e["kind"] == "xla"]
    assert len(evs) == 1 and evs[0]["name"] == "cost"
    assert schema.validate_event(evs[0]) == []
    assert evs[0]["flops"] == rec.flops


def test_dispatch_captures_weno5_executable():
    """The WENO5 rung's capture: the executable's flop count must
    reflect the far heavier per-cell sweep (>= the model's 151/axis
    convention at the same order of magnitude as the diffusion rung's
    discrepancy band allows nonzero, real numbers)."""
    solver = _burgers2d(impl="xla")
    solver.run(solver.initial_state(), 2)
    rec = xprof.primary_record(xprof.records(solver))
    assert rec is not None and rec.steps == 2
    assert rec.flops > 0 and rec.bytes_accessed > 0
    # WENO5 is flop-heavy: XLA's per-cell count must clearly exceed
    # the diffusion rung's (the margin is well under the modeled 11x —
    # boundary padding dominates these tiny grids — but the ordering
    # must hold for the captured numbers to be real)
    diff = _diffusion3d(impl="xla")
    diff.run(diff.initial_state(), 2)
    drec = xprof.primary_record(xprof.records(diff))
    cells_b = 20 * 16
    cells_d = 12 * 10 * 8
    assert rec.flops / cells_b > 1.5 * drec.flops / cells_d


def test_dispatch_capture_reuses_one_compile_per_program():
    """Repeat calls of the same program never re-capture (one record
    per dispatch-cache entry), and the compiled object is reused."""
    solver = _diffusion3d(impl="xla")
    st = solver.initial_state()
    st = solver.run(st, 2)
    st = solver.run(st, 2)
    assert len(xprof.records(solver)) == 1
    entry = solver._cache[("run", 2)]
    assert entry._compiled is not None and not entry._fallback


def test_xprof_disabled_falls_back_to_plain_jit(monkeypatch):
    monkeypatch.setenv("TPUCFD_XPROF", "0")
    solver = _diffusion3d(impl="xla")
    out = solver.run(solver.initial_state(), 2)
    assert int(out.it) == 2
    assert xprof.records(solver) == []


def test_measured_summary_reconciles_model():
    solver = _diffusion3d(impl="xla")
    solver.run(solver.initial_state(), 4)
    out = xprof.measured_summary(solver, iters=4, seconds=0.25)
    assert out["executables"] == 1
    assert out["xla_bytes_per_step"] > 0
    assert out["model_bytes_per_step"] == costmodel.step_cost(
        "diffusion", (8, 10, 12), 4, "generic-xla"
    ).hbm_bytes
    # ratio + band flag are present and consistent
    ratio = out["model_bytes_ratio"]
    tol = out["tolerance_factor"]
    assert out["bytes_within_tolerance"] == (1 / tol <= ratio <= tol)
    assert out["achieved_gbs"] == pytest.approx(
        out["xla_bytes_per_step"] * 4 / 0.25 / 1e9, rel=5e-2
    )  # loose: the summary rounds to 4 decimals
    assert out["peak_gbs"] > 0


# --------------------------------------------------------------------- #
# Device-memory watermarks (live-arrays fallback is the CPU path)
# --------------------------------------------------------------------- #
def test_watermark_live_arrays_fallback(tmp_path):
    """CPU devices report no memory_stats(): the sample must fall back
    to the live-arrays census, see a held array, and keep the running
    peak after it dies."""
    xprof.reset_watermarks()
    held = jnp.ones((64, 64), jnp.float32)  # 16 KiB live
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path):
        s1 = xprof.sample_watermark(step=1)
    assert s1["source"] == "live_arrays"
    assert s1["bytes_in_use"] >= held.nbytes
    del held
    s2 = xprof.sample_watermark(emit=False)
    summary = xprof.watermark_summary()
    assert summary["peak_bytes_in_use"] >= s1["bytes_in_use"]
    assert summary["peak_bytes_in_use"] >= s2["bytes_in_use"]
    assert summary["samples"] == 2
    assert summary["headroom_bytes"] is None  # census has no limit
    evs = [e for e in _events(path) if e["kind"] == "mem"]
    assert len(evs) == 1  # emit=False stayed out of the stream
    assert schema.validate_event(evs[0]) == []
    assert evs[0]["step"] == 1


def test_watermark_reset_zeroes_peak():
    xprof.sample_watermark(emit=False)
    assert xprof.watermark_summary() is not None
    xprof.reset_watermarks()
    assert xprof.watermark_summary() is None


# --------------------------------------------------------------------- #
# Calibration: round-trip + precedence over env peaks
# --------------------------------------------------------------------- #
def test_calibration_roundtrip_max_merge(tmp_path, monkeypatch):
    path = str(tmp_path / "cal.json")
    monkeypatch.setenv(calibration.ENV_PATH, path)
    mpath = str(tmp_path / "ev.jsonl")
    with telemetry.capture(mpath):
        calibration.observe("cpu", bytes_per_s=2.0e9, run="r1")
        calibration.observe("cpu", bytes_per_s=1.0e9, run="r2")  # slower
        calibration.observe("cpu", flops_per_s=3.0e9, run="r3")
    rec = calibration.lookup("cpu")
    assert rec["bytes_per_s"] == 2.0e9  # max-merge kept the faster run
    assert rec["flops_per_s"] == 3.0e9
    assert rec["samples"] == 3 and rec["run"] == "r3"
    # the file itself is the artifact: schema'd, reread equals lookup
    data = json.loads(open(path).read())
    assert data["schema"] == calibration.CALIBRATION_SCHEMA
    assert data["entries"]["cpu"]["bytes_per_s"] == 2.0e9
    evs = [e for e in _events(mpath) if e["kind"] == "calib"]
    assert [e["persisted"] for e in evs] == [True, False, True]
    assert all(schema.validate_event(e) == [] for e in evs)


def test_calibration_beats_env_peaks(tmp_path, monkeypatch):
    """Measured beats assumed: with a calibration record present,
    peak_rates returns it even when the env override is set; without
    one, the env override still wins over the static default."""
    monkeypatch.setenv("TPUCFD_PEAK_BYTES_PER_S", "1e9")
    monkeypatch.setenv("TPUCFD_PEAK_FLOPS_PER_S", "1e12")
    monkeypatch.setenv(
        calibration.ENV_PATH, str(tmp_path / "cal.json")
    )
    assert costmodel.peak_rates("cpu") == (1e9, 1e12)  # env over default
    calibration.observe("cpu", bytes_per_s=7.5e9)
    peak_b, peak_f = costmodel.peak_rates("cpu")
    assert peak_b == 7.5e9  # calibrated over env
    assert peak_f == 1e12   # uncalibrated component keeps the env value
    info = costmodel.peak_info("cpu")
    assert info["bytes_source"] == "calibrated"
    assert info["flops_source"] == "env"


def test_calibration_disabled_by_env(monkeypatch):
    monkeypatch.setenv(calibration.ENV_PATH, "off")
    assert calibration.default_path() is None
    assert calibration.observe("cpu", bytes_per_s=1e9) is None
    assert calibration.lookup("cpu") is None


def test_tuner_pruning_consults_calibrated_peaks(tmp_path, monkeypatch):
    """The autotuner's pruning metric (modeled_step_seconds) runs on
    peak_rates — a calibrated peak must change the modeled time, i.e.
    the tuner prunes with measured rather than assumed rates."""
    from multigpu_advectiondiffusion_tpu.tuning.autotuner import (
        modeled_step_seconds,
    )

    monkeypatch.setenv(
        calibration.ENV_PATH, str(tmp_path / "cal.json")
    )
    cfg = DiffusionConfig(
        grid=Grid.make(16, 16, 32, lengths=2.0), dtype="float32",
        impl="pallas_slab",
    )
    cand = {"impl": "pallas_slab", "steps_per_exchange": 1}
    before = modeled_step_seconds(cfg, (32, 16, 16), cand, 1, "cpu")
    assert before is not None and before > 0
    # this candidate is flops-bound on the assumed CPU peaks: a rig
    # that demonstrated 100x the assumed FLOP rate prices it cheaper
    _, peak_f = costmodel.peak_rates("cpu")
    calibration.observe("cpu", flops_per_s=100.0 * peak_f)
    after = modeled_step_seconds(cfg, (32, 16, 16), cand, 1, "cpu")
    assert after < before


# --------------------------------------------------------------------- #
# solver_memory_cross_check reuses the dispatched executable
# --------------------------------------------------------------------- #
def test_memory_cross_check_reuses_dispatch_executable(monkeypatch):
    """The cross-check must read XLA's accounting from the dispatch
    layer's own compiled step — never lower/compile a second copy
    (the legacy hook is monkeypatched to prove it is not consulted)."""
    solver = _diffusion3d(impl="xla")
    state = solver.initial_state()

    def forbidden(fn, *args):  # pragma: no cover - failing path
        raise AssertionError(
            "xla_memory_analysis recompiled a second copy of the step"
        )

    monkeypatch.setattr(costmodel, "xla_memory_analysis", forbidden)
    res = costmodel.solver_memory_cross_check(solver, state)
    assert res is not None
    field = 12 * 10 * 8 * 4
    assert res["field_bytes"] == field
    assert res["xla"]["argument_size_in_bytes"] >= field
    # the record the cross-check consumed is the dispatched step's
    rec = [r for r in xprof.records(solver) if r.key == "step"]
    assert rec and res["xla"]["argument_size_in_bytes"] == \
        rec[0].argument_bytes


# --------------------------------------------------------------------- #
# profiling.trace: exception-safe + idempotent (satellite)
# --------------------------------------------------------------------- #
def test_trace_closes_on_exception_and_recovers(tmp_path, monkeypatch):
    from multigpu_advectiondiffusion_tpu.utils import profiling

    calls = {"start": 0, "stop": 0, "open": False}

    def fake_start(log_dir):
        if calls["open"]:
            raise RuntimeError("profiler already running")
        calls["start"] += 1
        calls["open"] = True

    def fake_stop():
        if not calls["open"]:
            raise RuntimeError("no trace running")
        calls["stop"] += 1
        calls["open"] = False

    monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)
    # an exception inside the traced body must still stop the trace
    with pytest.raises(ValueError, match="boom"):
        with profiling.trace(str(tmp_path / "t1")):
            raise ValueError("boom")
    assert calls == {"start": 1, "stop": 1, "open": False}
    # a trace leaked by some OTHER owner poisons start_trace: trace()
    # must close it and retry instead of failing forever
    calls["open"] = True
    with profiling.trace(str(tmp_path / "t2")):
        pass
    assert calls["open"] is False and calls["start"] == 2


def test_trace_is_idempotent_under_nesting(tmp_path, monkeypatch):
    from multigpu_advectiondiffusion_tpu.utils import profiling

    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda d: calls.__setitem__("start", calls["start"] + 1),
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace",
        lambda: calls.__setitem__("stop", calls["stop"] + 1),
    )
    with profiling.trace(str(tmp_path / "outer")):
        with profiling.trace(str(tmp_path / "inner")):  # no-op
            pass
    assert calls == {"start": 1, "stop": 1}


# --------------------------------------------------------------------- #
# The acceptance run: supervised CLI solves with --metrics
# --------------------------------------------------------------------- #
def _assert_measured_stream(mpath, run_dir, name):
    evs = _events(mpath)
    # per-executable xla:cost with nonzero XLA-reported numbers
    costs = [e for e in evs if (e["kind"], e["name"]) == ("xla", "cost")]
    assert costs, "no xla:cost events in the stream"
    assert all(e["flops"] > 0 and e["bytes_accessed"] > 0 for e in costs)
    assert all(schema.validate_event(e) == [] for e in costs)
    # chunk-cadence mem:watermark events (live-arrays fallback on CPU)
    marks = [e for e in evs
             if (e["kind"], e["name"]) == ("mem", "watermark")]
    assert len(marks) >= 3
    assert all(e["source"] == "live_arrays" for e in marks)
    assert all(e["bytes_in_use"] > 0 for e in marks)
    # the measured-vs-modeled reconciliation + the calibration write
    assert any(
        (e["kind"], e["name"]) == ("xla", "measured") for e in evs
    )
    calib = [e for e in evs if e["kind"] == "calib"]
    assert calib and calib[-1]["persisted"]
    # summary carries the memory block with peak bytes and the xla block
    summary = json.loads((run_dir / "summary.json").read_text())
    assert summary["schema"] >= 3
    assert summary["memory"]["peak_bytes_in_use"] > 0
    assert summary["memory"]["source"] == "live_arrays"
    assert summary["xla"]["xla_bytes_per_step"] > 0
    assert summary["xla"]["model_bytes_ratio"] is not None
    assert summary["name"] == name
    return evs


def test_cli_supervised_diffusion3d_measured_stream(tmp_path):
    run = tmp_path / "run"
    mpath = str(tmp_path / "events.jsonl")
    cli_main([
        "diffusion3d", "--n", "12", "10", "8", "--iters", "6",
        "--sentinel-every", "2", "--save", str(run),
        "--metrics", mpath,
    ])
    evs = _assert_measured_stream(mpath, run, "diffusion3d")
    # the calibration record is on disk and consulted by peak_rates
    rec = calibration.lookup("cpu")
    assert rec is not None and rec.get("bytes_per_s", 0) > 0
    info = costmodel.peak_info("cpu")
    assert "calibrated" in (info["bytes_source"], info["flops_source"])
    # dispatch builds and xla:cost captures pair up
    builds = [e for e in evs if e["kind"] == "dispatch"]
    assert len(builds) == len(
        [e for e in evs if (e["kind"], e["name"]) == ("xla", "cost")]
    )


def test_cli_supervised_burgers3d_measured_stream(tmp_path):
    run = tmp_path / "run"
    mpath = str(tmp_path / "events.jsonl")
    cli_main([
        "burgers3d", "--n", "10", "8", "8", "--iters", "6",
        "--fixed-dt", "--sentinel-every", "2", "--save", str(run),
        "--metrics", mpath,
    ])
    _assert_measured_stream(mpath, run, "burgers3d")


def test_trace_report_measured_section(tmp_path):
    """tpucfd-trace renders the measured-vs-modeled section from a
    real supervised stream: per-executable rows with ratio + band flag
    (discrepancies reported, not hidden) and the per-rank memory peak."""
    from multigpu_advectiondiffusion_tpu.telemetry.analyze import analyze

    run = tmp_path / "run"
    mpath = str(tmp_path / "events.jsonl")
    cli_main([
        "diffusion2d", "--n", "16", "12", "--iters", "6",
        "--sentinel-every", "3", "--save", str(run),
        "--metrics", mpath,
    ])
    report = analyze([mpath])
    x = report.xla
    assert x["executables"], "no xla:cost rows in the report"
    row = x["executables"][-1]
    assert row["xla_bytes"] > 0
    assert row["model_bytes_ratio"] is not None
    assert row["within_tolerance"] in (True, False)
    assert x["runs"] and x["runs"][0]["run"] == "diffusion2d"
    assert x["memory"]["proc0"]["peak_bytes"] > 0
    text = report.format_text()
    assert "measured vs modeled" in text
    flag = "ok" if row["within_tolerance"] else "DISCREPANT"
    assert flag in text
