"""Gradient-based inverse problem through the batched dispatch
(ISSUE 11 satellite): ``jax.grad`` flows through
``advance_to_ensemble`` (bounded-loop mode) w.r.t. the member
diffusivity operands, and a short descent recovers a perturbed K.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from examples.inverse_diffusivity import recover_diffusivity


def test_grad_through_advance_to_ensemble_is_finite_and_signed():
    """The raw differentiability contract: a (B,) diffusivity operand
    vector yields a finite per-member gradient whose sign points at
    the truth (K too small => negative dL/dK past the optimum etc.)."""
    from multigpu_advectiondiffusion_tpu import (
        DiffusionConfig,
        DiffusionSolver,
        Grid,
    )
    from multigpu_advectiondiffusion_tpu.models.state import EnsembleState

    grid = Grid.make(32, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, diffusivity=1.0, dtype="float32",
                          impl="xla")
    solver = DiffusionSolver(cfg)
    s0 = solver.initial_state()
    t_end = float(s0.t) + 0.04
    obs = solver.advance_to(s0, t_end)
    est0 = EnsembleState(
        u=jnp.stack([s0.u] * 2), t=jnp.stack([s0.t] * 2),
        it=jnp.zeros((2,), jnp.int32),
    )

    def loss(ks):
        out = solver.advance_to_ensemble(
            est0, t_end, operands={"diffusivity": ks}, max_steps=48
        )
        return jnp.sum(jnp.mean((out.u - obs.u[None]) ** 2, axis=1))

    grads = jax.grad(loss)(jnp.asarray([0.6, 1.8], jnp.float32))
    g = np.asarray(grads)
    assert np.isfinite(g).all()
    # member 0 sits below the truth (K=1): the misfit decreases with
    # larger K => negative gradient; member 1 above => positive
    assert g[0] < 0 < g[1], g


def test_descent_recovers_perturbed_diffusivity():
    """Loose-tolerance convergence: every descent trajectory lands
    within 10% of the true K from guesses up to ~2.5x off."""
    k_true = 1.3
    recovered, history = recover_diffusivity(
        [0.5, 1.0, 2.6], n=32, k_true=k_true, t_window=0.04,
        iterations=35, lr=0.06, max_steps=48,
    )
    rec = np.asarray(recovered)
    assert np.all(np.abs(rec - k_true) / k_true < 0.10), rec
    # and the descent actually descended
    assert history[-1] < 0.2 * history[0], (history[0], history[-1])


def test_bounded_mode_matches_while_loop_semantics():
    """``max_steps`` large enough must reproduce the data-dependent
    while-loop dispatch exactly (field, time AND per-member step
    counts) — the differentiable mode is a semantics-preserving
    re-expression, not an approximation."""
    from multigpu_advectiondiffusion_tpu import (
        DiffusionConfig,
        DiffusionSolver,
        EnsembleSolver,
        Grid,
    )

    grid = Grid.make(12, 10, 8, lengths=(1.2, 1.0, 0.8))
    cfg = DiffusionConfig(grid=grid, diffusivity=1.0, dtype="float32",
                          impl="xla", ic="gaussian")
    members = [{"diffusivity": k} for k in (0.5, 1.0, 2.0)]
    es = EnsembleSolver(DiffusionSolver, cfg, members)
    est = es.initial_state()
    t_end = float(est.t[0]) + 0.002
    out_while = es.advance_to(est, t_end)
    out_bounded = es.advance_to(est, t_end, max_steps=64)
    np.testing.assert_array_equal(
        np.asarray(out_while.u), np.asarray(out_bounded.u)
    )
    np.testing.assert_array_equal(
        np.asarray(out_while.t), np.asarray(out_bounded.t)
    )
    np.testing.assert_array_equal(
        np.asarray(out_while.it), np.asarray(out_bounded.it)
    )


def test_bounded_mode_too_small_budget_is_visible():
    """An insufficient ``max_steps`` is not silent: members that did
    not reach t_end report t < t_end (the caller's convergence check
    sees it), never a wrong field at a lying time."""
    from multigpu_advectiondiffusion_tpu import (
        DiffusionConfig,
        DiffusionSolver,
        EnsembleSolver,
        Grid,
    )

    grid = Grid.make(12, 10, 8, lengths=(1.2, 1.0, 0.8))
    cfg = DiffusionConfig(grid=grid, diffusivity=1.0, dtype="float32",
                          impl="xla", ic="gaussian")
    es = EnsembleSolver(DiffusionSolver, cfg, 2)
    est = es.initial_state()
    t_end = float(est.t[0]) + 0.01
    out = es.advance_to(est, t_end, max_steps=2)
    assert np.all(np.asarray(out.it) == 2)
    assert np.all(np.asarray(out.t) < t_end - 1e-9)
