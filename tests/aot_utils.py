"""Shared TPU-AOT plumbing for the Mosaic schedule proofs.

The AOT tests (``test_overlap_schedule.py``) compile against a virtual
v5e topology — no chips needed, but the TPU compiler plugin must
initialize, and it serializes on ``/tmp/libtpu_lockfile``. A previous
process that died holding the lock leaves a *stale* lockfile behind;
libtpu then fails to initialize and the proofs used to silently skip —
the flake VERDICT weak #7 called out. Two fixes here:

* **repair**: before giving up, probe the lockfile with a non-blocking
  ``flock`` — if no live process holds it, the file is stale; remove it
  and retry the topology fetch once;
* **strict mode**: ``TPUCFD_STRICT_AOT=1`` turns every remaining skip
  into a hard failure — the env flag TPU sessions set to assert zero
  AOT skips (a skipped schedule proof on a rig that *should* compile is
  a regression, not an environment quirk).
"""

from __future__ import annotations

import os

import pytest

LIBTPU_LOCKFILE = "/tmp/libtpu_lockfile"
STRICT_ENV = "TPUCFD_STRICT_AOT"


def strict_aot() -> bool:
    return os.environ.get(STRICT_ENV, "") == "1"


def aot_unavailable(reason: str):
    """Skip the test — or, under ``TPUCFD_STRICT_AOT=1``, fail it."""
    if strict_aot():
        pytest.fail(
            f"{STRICT_ENV}=1 forbids AOT skips, but: {reason}"
        )
    pytest.skip(reason)


def _lockfile_is_stale(path: str = LIBTPU_LOCKFILE) -> bool:
    """True when the libtpu lockfile exists but no live process holds
    its flock (the holder died) — safe to remove and retry."""
    import fcntl

    try:
        fd = os.open(path, os.O_RDWR)
    except OSError:
        return False
    try:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            return False  # a live process holds the lock: not stale
        fcntl.flock(fd, fcntl.LOCK_UN)
        return True
    finally:
        os.close(fd)


def repair_stale_libtpu_lock(path: str = LIBTPU_LOCKFILE) -> bool:
    """Remove a stale libtpu lockfile; True when a repair happened."""
    if os.path.exists(path) and _lockfile_is_stale(path):
        try:
            os.remove(path)
            return True
        except OSError:
            pass
    return False


def get_aot_topology(name: str = "v5e:2x2"):
    """The AOT topology descriptor, with one stale-lockfile repair +
    retry. Skips (or fails, under strict mode) when the TPU compiler
    plugin is genuinely unavailable in this environment."""
    try:
        from jax.experimental import topologies
    except ImportError as e:
        aot_unavailable(f"TPU AOT topology unavailable: {type(e).__name__}")
    err = None
    for attempt in (0, 1):
        try:
            return topologies.get_topology_desc(name, "tpu")
        except Exception as e:  # no plugin, or a poisoned lockfile
            err = e
            if attempt == 0 and repair_stale_libtpu_lock():
                continue  # repaired: one retry
            break
    aot_unavailable(
        f"TPU AOT topology unavailable: {type(err).__name__}: {err}"
    )
