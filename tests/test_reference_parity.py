"""Algorithm parity with the reference's accuracy-test drivers.

``advance_reference`` must reproduce the MATLAB test loop
(``Matlab_Prototipes/DiffusionNd/diffusion{1,2,3}dTest.m``) exactly:
4th-order Laplacian zeroed on the 2-cell boundary band
(``Laplace3d.m:21``), per-*step* Dirichlet face clamp
(``diffusion3dTest.m:59-62``), and the untrimmed-final-dt quirk
(``:64-67``). The oracle here is a literal NumPy transcription of those
drivers; the framework must agree to f64 round-off.

(The shipped ``TestingAccuracy.log`` is NOT reproducible from the shipped
``.m`` files — its ``nE`` column shows nodes {11,21,41,81} while
``TestingAccuracy.m:16`` now sets {9,17,33,65}, and the recorded norms
differ from what the current code produces. Parity is therefore defined
against the code, not the stale log.)
"""

import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)

T_END = 0.5  # TestingAccuracy.m:11
FACTOR = 0.9  # TestingAccuracy.m:12
T0 = 0.1
L = 10.0
D = 1.0


def _oracle(nodes, ndim):
    """Literal transcription of diffusion{1,2,3}dTest.m."""
    n = nodes
    dx = L / (n - 1)
    axes = np.meshgrid(*([np.linspace(-L / 2, L / 2, n)] * ndim),
                       indexing="ij")
    r2 = sum(a * a for a in axes)
    u = np.exp(-r2 / (4 * D * T0))
    u_exact = (T0 / T_END) ** (ndim / 2.0) * np.exp(-r2 / (4 * D * T_END))
    Dx = D / dx**2
    dt0 = 1 / (2 * D * (ndim / dx**2)) * FACTOR

    core = (slice(2, n - 2),) * ndim

    def lap(u):
        out = np.zeros_like(u)
        acc = np.zeros_like(u[core])
        for ax in range(ndim):
            for shift, c in [(2, -1), (1, 16), (0, -30), (-1, 16), (-2, -1)]:
                idx = [slice(2, n - 2)] * ndim
                idx[ax] = slice(2 + shift, n - 2 + shift)
                acc = acc + (Dx / 12 * c) * u[tuple(idx)]
        out[core] = acc
        return out

    def clamp(u):
        for ax in range(ndim):
            lo = [slice(None)] * ndim
            hi = [slice(None)] * ndim
            lo[ax], hi[ax] = 0, n - 1
            u[tuple(lo)] = 0.0
            u[tuple(hi)] = 0.0
        return u

    t, dt = T0, dt0
    while t < T_END:
        uo = u.copy()
        u = uo + dt * lap(u)
        u = 0.75 * uo + 0.25 * (u + dt * lap(u))
        u = (uo + 2 * (u + dt * lap(u))) / 3
        u = clamp(u)
        if t + dt > T_END:
            dt = T_END - t
        t += dt
    err = np.abs(u_exact - u)
    return u, dx**ndim * err.sum(), err.max()


@pytest.mark.parametrize("ndim,nodes", [(1, 21), (1, 41), (1, 81),
                                        (2, 21), (2, 41), (3, 21)])
def test_advance_reference_matches_matlab_oracle(ndim, nodes):
    u_ref, l1_ref, linf_ref = _oracle(nodes, ndim)
    grid = Grid.make(*(nodes,) * ndim, lengths=L)
    cfg = DiffusionConfig(grid=grid, safety=FACTOR, dtype="float64")
    solver = DiffusionSolver(cfg)
    out = solver.advance_reference(solver.initial_state(), T_END)
    # field-level agreement to f64 round-off (op-order differences only)
    np.testing.assert_allclose(np.asarray(out.u), u_ref,
                               rtol=1e-9, atol=1e-12)
    norms = solver.error_norms(out, t=T_END)
    assert norms.l1 == pytest.approx(l1_ref, rel=1e-9)
    assert norms.linf == pytest.approx(linf_ref, rel=1e-9)


# --------------------------------------------------------------------- #
# WENO interface-flux golden vectors vs the MATLAB formulas
# (WENO5resAdv_X.m:57-125, WENO7resAdv_X.m:60-148)
# --------------------------------------------------------------------- #
def _matlab_weno5_fluxes(w, flux_f, dflux_f):
    """Transcription of WENO5resAdv_X.m for one row: returns hn+hp at the
    interfaces right of cells 0..N-1 (MATLAB hn(I)+hp(I), I=3..N+2)."""
    N = len(w)
    W = np.concatenate([[w[0], w[0]], w, [w[-1], w[-1], w[-1]]])
    a = np.abs(dflux_f(W))
    V = 0.5 * (flux_f(W) + a * W)
    U = 0.5 * (flux_f(W) - a * W)
    I = np.arange(2, N + 2)  # 0-based MATLAB I=3:N+2

    vmm, vm, v, vp, vpp = (V[I - 2], V[I - 1], V[I], V[I + 1], V[I + 2])
    B0 = 13 / 12 * (vmm - 2 * vm + v) ** 2 + 0.25 * (vmm - 4 * vm + 3 * v) ** 2
    B1 = 13 / 12 * (vm - 2 * v + vp) ** 2 + 0.25 * (vm - vp) ** 2
    B2 = 13 / 12 * (v - 2 * vp + vpp) ** 2 + 0.25 * (3 * v - 4 * vp + vpp) ** 2
    eps = 1e-6
    a0, a1, a2 = 0.1 / (eps + B0) ** 2, 0.6 / (eps + B1) ** 2, 0.3 / (eps + B2) ** 2
    s = a0 + a1 + a2
    hn = (a0 / s) * (2 * vmm - 7 * vm + 11 * v) / 6 \
        + (a1 / s) * (-vm + 5 * v + 2 * vp) / 6 \
        + (a2 / s) * (2 * v + 5 * vp - vpp) / 6

    umm, um, uc, up, upp = (U[I - 1], U[I], U[I + 1], U[I + 2], U[I + 3])
    B0 = 13 / 12 * (umm - 2 * um + uc) ** 2 + 0.25 * (umm - 4 * um + 3 * uc) ** 2
    B1 = 13 / 12 * (um - 2 * uc + up) ** 2 + 0.25 * (um - up) ** 2
    B2 = 13 / 12 * (uc - 2 * up + upp) ** 2 + 0.25 * (3 * uc - 4 * up + upp) ** 2
    a0, a1, a2 = 0.3 / (eps + B0) ** 2, 0.6 / (eps + B1) ** 2, 0.1 / (eps + B2) ** 2
    s = a0 + a1 + a2
    hp = (a0 / s) * (-umm + 5 * um + 2 * uc) / 6 \
        + (a1 / s) * (2 * um + 5 * uc - up) / 6 \
        + (a2 / s) * (11 * uc - 7 * up + 2 * upp) / 6
    return hn + hp


@pytest.mark.parametrize("flux_name", ["burgers", "linear"])
def test_weno5_interface_flux_matches_matlab(flux_name):
    from multigpu_advectiondiffusion_tpu.core.bc import Boundary, pad_axis
    from multigpu_advectiondiffusion_tpu.ops import flux as flux_lib
    from multigpu_advectiondiffusion_tpu.ops.weno import (
        interface_flux_from_padded,
    )

    rng = np.random.default_rng(7)
    w = rng.standard_normal(32)
    fx = flux_lib.get(flux_name)
    ref = _matlab_weno5_fluxes(w, lambda x: np.asarray(fx.f(x)),
                               lambda x: np.asarray(fx.df(x)))

    import jax.numpy as jnp

    up = pad_axis(jnp.asarray(w), 0, 3, Boundary("edge"))
    h = np.asarray(interface_flux_from_padded(up, 0, fx, order=5))
    # my interface i sits left of cell i; MATLAB's hn(I)+hp(I) sits right
    # of cell I-3 (0-based) -> my h[1:] == MATLAB[:, all N]
    np.testing.assert_allclose(h[1:], ref, rtol=1e-12, atol=1e-14)
