"""IO layer tests: reference-format binaries, ASCII, async writer,
checkpoint/resume (the subsystem the reference lacks, SURVEY §5)."""

import os

import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.utils import io as tio


def test_binary_roundtrip(tmp_path):
    u = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    p = str(tmp_path / "u.bin")
    tio.save_binary(u, p)
    # layout: x fastest (SaveBinary3D, Tools.c:110) == C-order ravel
    raw = np.fromfile(p, dtype=np.float32)
    np.testing.assert_array_equal(raw, u.ravel())
    back = tio.load_binary(p, u.shape)
    np.testing.assert_array_equal(back, u)


def test_ascii_matches_reference_format(tmp_path):
    u = np.array([1.0, 0.5, 1e-7, 3.14159])
    p = str(tmp_path / "u.txt")
    tio.save_ascii(u, p)
    lines = open(p).read().strip().split("\n")
    assert lines == ["1", "0.5", "1e-07", "3.14159"]


def test_async_writer(tmp_path):
    snaps = [np.full((8, 8), i, np.float32) for i in range(5)]
    with tio.AsyncBinaryWriter() as w:
        for i, s in enumerate(snaps):
            w.submit(s, str(tmp_path / f"s{i}.bin"))
    for i, s in enumerate(snaps):
        back = tio.load_binary(str(tmp_path / f"s{i}.bin"), s.shape)
        np.testing.assert_array_equal(back, s)


def test_checkpoint_resume(tmp_path):
    grid = Grid.make(17, 17, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float64")
    solver = DiffusionSolver(cfg)
    s = solver.run(solver.initial_state(), 3)
    p = str(tmp_path / "ck.npz")
    tio.save_checkpoint(p, s, grid=grid)
    restored = tio.load_checkpoint(p)
    np.testing.assert_array_equal(np.asarray(restored.u), np.asarray(s.u))
    assert float(restored.t) == float(s.t)
    # resuming and stepping produces the same trajectory as uninterrupted
    a = solver.run(restored, 2)
    b = solver.run(s, 2)
    np.testing.assert_array_equal(np.asarray(a.u), np.asarray(b.u))


def test_native_library_is_used_if_built():
    lib = tio._load_native()
    here = os.path.dirname(os.path.dirname(os.path.abspath(tio.__file__)))
    built = os.path.exists(os.path.join(here, "..", "native", "libtpucfd_io.so"))
    if built:
        assert lib, "native lib exists but ctypes binding failed"
    else:
        pytest.skip("native lib not built (numpy fallback in use)")
